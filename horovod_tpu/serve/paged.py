"""Paged KV memory: a pure, rank-deterministic page allocator and
per-slot block tables — the vLLM block-table idea (Kwon et al. 2023,
PAPERS.md) reduced to the serving plane's SPMD essentials.

The contiguous slot pool reserves ``slots x cache_len`` rows whether or
not a request ever writes them; PR 14 measured the cost
(``serve.kv.waste_ratio`` ~0.6+ on mixed-length traffic).  Here KV rows
live in fixed-size **pages** (``page_size`` token rows each) handed out
from a free list as positions actually advance, and each slot's cache
is the ordered list of pages in its **block table** — so allocated
bytes track tokens written, not worst-case length, and admission
capacity is judged in free pages rather than free slots.

Like the scheduler (serve/scheduler.py), this module is a **pure state
machine** — the serving HVD001 invariant: every rank of the serving
world feeds its own instance the SAME calls in the SAME order and must
derive the IDENTICAL page assignment, because the block table is an
input to the compiled decode step and a rank-divergent table would
desync the decode math the whole plane's bitwise-replay story rests
on.  Nothing here may read a clock, ``hvd.rank()``, ``random``, or an
unordered dict iteration; hvdtpu-lint HVD012 registers this file as a
determinism contract, and tests replay one trace through N instances.

Allocation policy (all deterministic):

* the free list is a min-heap — ``alloc`` always returns the
  LOWEST-numbered free page (heapq's ordering is a pure function of
  its contents);
* pages are **refcounted** so prefix caching can later map one
  physical page into several block tables (ROADMAP item 3c); a page
  returns to the free list when its count reaches zero;
* admission reserves nothing physically but **commits** the request's
  worst case (``ceil((len(prompt+resume) + max_new_tokens) /
  page_size)`` pages): a request is admitted only when the sum of all
  active commitments plus its own fits the pool, so a mid-decode page
  allocation can never fail and no preemption/swap path is needed
  (the honest trade vs vLLM's swapping, stated in docs/inference.md).
"""

from __future__ import annotations

import heapq
from typing import Dict, List, Optional, Sequence

__all__ = ["pages_for", "page_reject_reason", "PagedKV"]


def pages_for(tokens: int, page_size: int) -> int:
    """Pages needed to hold ``tokens`` rows (0 tokens -> 0 pages)."""
    if tokens <= 0:
        return 0
    return -(-int(tokens) // int(page_size))


def page_reject_reason(prompt_len: int, max_new_tokens: int,
                       page_size: int, num_pages: int) -> Optional[str]:
    """Permanent page-infeasibility verdict for one request, or None.

    Pure — every rank (and every group of a width-sharded fleet)
    reaches the same verdict for the same log entry, like
    ``frontend.validate_request``.  A request whose worst case exceeds
    the WHOLE pool can never be admitted no matter how long it queues;
    rejecting it loudly beats a permanently head-blocked FCFS queue.
    """
    need = pages_for(prompt_len + max_new_tokens, page_size)
    if need > num_pages:
        return (
            f"request needs {need} KV pages worst-case "
            f"(prompt {prompt_len} + max_new_tokens {max_new_tokens} at "
            f"{page_size} rows/page) but the pool holds {num_pages}"
        )
    return None


class PagedKV:
    """Block tables + free-list page allocator for one slot pool.

    Tracks, per slot: the ordered page list (the block table), the
    write position, and the worst-case page commitment made at
    admission.  The device-side pool (models/decode.py
    ``init_paged_pool``) is indexed by these page ids; ``null_page``
    (== ``num_pages``) pads table rows past the allocated prefix — out
    of bounds by construction, so scatter-``drop`` discards writes to
    it and gather-``fill`` reads zeros (masked by ``pos`` anyway).
    """

    def __init__(self, num_slots: int, num_pages: int, page_size: int,
                 max_len: int):
        if num_pages < 1 or page_size < 1:
            raise ValueError("num_pages and page_size must be >= 1")
        self.num_slots = int(num_slots)
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        # Per-slot virtual capacity: the block table's fixed width.  The
        # compiled decode gathers exactly this many pages per slot, so
        # it is the serving context rounded UP to whole pages.
        self.max_pages_per_slot = pages_for(max_len, page_size)
        self.null_page = self.num_pages
        self._free: List[int] = list(range(self.num_pages))
        heapq.heapify(self._free)
        self._ref: List[int] = [0] * self.num_pages
        self._tables: Dict[int, List[int]] = {}
        self._pos: Dict[int, int] = {}
        self._committed: Dict[int, int] = {}

    # ------------------------------------------------------------ queries

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.num_pages - len(self._free)

    @property
    def committed_pages(self) -> int:
        return sum(self._committed.values())

    def table(self, slot: int) -> List[int]:
        return list(self._tables.get(slot, ()))

    def position(self, slot: int) -> int:
        return self._pos.get(slot, 0)

    def can_admit(self, total_len: int) -> bool:
        """Admission judgement in pages: does the pool have room for
        this request's WORST CASE on top of every active commitment?
        Committed-not-yet-allocated pages count against the pool so a
        mid-decode ``ensure_capacity`` can never fail — the price is
        capacity bounded by budgets, not by live usage (documented)."""
        need = pages_for(total_len, self.page_size)
        if need > self.max_pages_per_slot:
            return False
        return self.committed_pages + need <= self.num_pages

    def admission_gate(self):
        """Batch form of :meth:`can_admit` for ONE scheduling round:
        the returned callable accumulates the round's accepted worst
        cases, so two requests admitted in the same round cannot both
        be judged against the same free pool (the engine-side admit of
        the second would then overcommit and raise — a rank-killing
        accounting bug, regression-tested).  Build a fresh gate every
        round; acceptance order is the FCFS order, so every rank's
        gate makes identical judgements."""
        pending = [0]

        def gate(total_len: int) -> bool:
            need = pages_for(total_len, self.page_size)
            if need > self.max_pages_per_slot:
                return False
            if self.committed_pages + pending[0] + need <= self.num_pages:
                pending[0] += need
                return True
            return False

        return gate

    # --------------------------------------------------------- allocation

    def _alloc_page(self) -> int:
        if not self._free:
            raise RuntimeError(
                "page pool exhausted — the commitment invariant was "
                "violated (admission must gate on can_admit)"
            )
        page = heapq.heappop(self._free)
        self._ref[page] = 1
        return page

    def admit(self, slot: int, prefill_len: int, total_len: int) -> List[int]:
        """Allocate the pages ``prefill_len`` written rows need, set the
        slot's position, and commit the request's worst case
        (``total_len`` rows).  Returns the slot's block table."""
        if slot in self._tables:
            raise ValueError(f"slot {slot} already holds a block table")
        if not 0 <= slot < self.num_slots:
            raise ValueError(f"slot {slot} outside the {self.num_slots}-"
                             f"slot pool")
        if not self.can_admit(total_len):
            raise RuntimeError(
                f"admitting {total_len} rows would overcommit the "
                f"{self.num_pages}-page pool (can_admit gate skipped?)"
            )
        if prefill_len > total_len:
            raise ValueError("prefill_len exceeds the committed total")
        table = [self._alloc_page()
                 for _ in range(pages_for(prefill_len, self.page_size))]
        self._tables[slot] = table
        self._pos[slot] = int(prefill_len)
        self._committed[slot] = pages_for(total_len, self.page_size)
        return list(table)

    def ensure_capacity(self, slot: int) -> bool:
        """Make sure the slot's NEXT write position has a page; returns
        True when a page was newly allocated (the device block table
        must be refreshed).  Called before every decode step for every
        active slot — under the commitment invariant this cannot fail.
        """
        table = self._tables.get(slot)
        if table is None:
            raise KeyError(f"slot {slot} has no block table")
        pos = self._pos[slot]
        page_idx = pos // self.page_size
        if page_idx < len(table):
            return False
        if page_idx >= self.max_pages_per_slot:
            # Writing past the virtual capacity is the decode overrun
            # the NaN-poison contract covers; no page to allocate.
            return False
        if len(table) >= self._committed[slot]:
            raise RuntimeError(
                f"slot {slot} grew past its {self._committed[slot]}-page "
                f"commitment — admission accounting is broken"
            )
        table.append(self._alloc_page())
        return True

    def advance(self, slot: int) -> None:
        """Host mirror of the device-side position advance (one token
        written by the decode step)."""
        if slot not in self._pos:
            raise KeyError(f"slot {slot} has no block table")
        self._pos[slot] += 1

    def release(self, slot: int) -> None:
        """Evict: drop the slot's table, decref its pages (freed at
        zero), release its commitment.  Free-list re-entry keeps the
        heap ordering, so page reuse is deterministic."""
        table = self._tables.pop(slot, None)
        if table is None:
            return
        self._pos.pop(slot, None)
        self._committed.pop(slot, None)
        for page in table:
            self._ref[page] -= 1
            if self._ref[page] == 0:
                heapq.heappush(self._free, page)

    def retain(self, pages: Sequence[int]) -> None:
        """Bump refcounts (prefix caching maps shared pages into a
        second block table; the page frees only when BOTH release)."""
        for page in pages:
            if self._ref[page] < 1:
                raise ValueError(f"page {page} is not allocated")
            self._ref[page] += 1

    def adopt(self, slot: int, pages: Sequence[int], prefill_len: int,
              total_len: int) -> None:
        """Install an externally assembled (e.g. prefix-shared) table.
        Caller must have ``retain``-ed shared pages first."""
        if slot in self._tables:
            raise ValueError(f"slot {slot} already holds a block table")
        self._tables[slot] = list(pages)
        self._pos[slot] = int(prefill_len)
        self._committed[slot] = pages_for(total_len, self.page_size)

    def reset(self) -> None:
        """Drop everything (elastic epoch rebuild): all pages free, no
        tables — the deterministic replay of admissions from the
        request log rebuilds identical tables on every rank."""
        self._free = list(range(self.num_pages))
        heapq.heapify(self._free)
        self._ref = [0] * self.num_pages
        self._tables.clear()
        self._pos.clear()
        self._committed.clear()

    # ------------------------------------------------------------- arrays

    def table_row(self, slot: int) -> List[int]:
        """The slot's block table padded to ``max_pages_per_slot`` with
        ``null_page`` — the row the compiled decode step consumes."""
        table = self._tables.get(slot, [])
        pad = self.max_pages_per_slot - len(table)
        return list(table) + [self.null_page] * pad

    # -------------------------------------------------------------- stats

    def stats(self, row_bytes: float) -> dict:
        """Page-granular occupancy: ``allocated`` is pages actually
        handed out (times their row capacity), ``live`` is positions
        written — the successor of memplane.kv_occupancy's fixed-row
        math, recomputed from the block table so the waste a partial
        last page carries is the ONLY waste left.  Pages belong to
        exactly the admitted-not-yet-evicted slots, so no active-set
        argument is needed: a released slot's pages left with it."""
        used = self.used_pages
        allocated = used * self.page_size * float(row_bytes)
        live = 0.0
        for s in sorted(self._tables):
            cap = len(self._tables[s]) * self.page_size
            live += min(self._pos.get(s, 0), cap) * float(row_bytes)
        return {
            "slots_in_use": len(self._tables),
            "allocated_bytes": int(allocated),
            "live_bytes": int(live),
            "waste_ratio": (1.0 - live / allocated) if allocated else 0.0,
            "page_size": self.page_size,
            "pages_free": self.free_pages,
            "pages_used": used,
            "pages_committed": self.committed_pages,
        }
