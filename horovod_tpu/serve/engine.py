"""Slot engine: the compiled-model half of the serving plane.

Wraps the slot-based decode primitives (models/decode.py) for the
continuous-batching loop: ONE jitted ``decode_step`` over the whole
slot pool (shape never changes, so it compiles once), plus one jitted
``assign_slot`` per prompt-length *bucket* (prompts are right-padded to
the next power of two, so admission compiles O(log max_len) variants,
not one per prompt length).

Three orthogonal modes (ISSUE 15):

* ``kv_mode="paged"`` — KV rows live in fixed-size pages handed out by
  the pure allocator (serve/paged.py); the compiled step gathers each
  slot's prefix through its block table, so resident KV bytes track
  tokens actually written and admission capacity is judged in free
  pages (``can_admit``), not free slots.  ``"contiguous"`` keeps the
  PR-10 worst-case-row pool (the PR-14 waste baseline).
* ``width > 1`` — Megatron tensor parallelism inside the serving
  fleet: params split by ``tensor_parallel.stack_tp_params`` and the
  paged decode step shard_mapped over the ``width`` axis of a
  ``(replica, width)`` device-mesh view (PR-8 conventions: replicas
  ride DCN across processes, width rides ICI).  Each width shard holds
  only ITS heads' KV pages; every block rejoins through two psums.
  Requires ``kv_mode="paged"``.
* per-request sampling — temperature/top-k picks keyed purely on
  ``(request id, emission index, serve seed)`` (serve/sampling.py), so
  every rank derives the identical token and elastic replay reproduces
  the stream.  ``temperature == 0`` (default) is the old greedy path.

Determinism contract (the serving HVD001 invariant): given the same
config, params, seed, and the same sequence of admit/step/release
calls, every rank's engine produces bit-identical tokens — the
scheduler feeds every rank the same calls, the page allocator is a
pure state machine, the sampler's keys are pure functions of request
identity, and XLA's decode math is deterministic per backend.
"""

from __future__ import annotations

import weakref
from typing import Dict, Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models.decode import (
    assign_slot, assign_slot_paged, decode_step, decode_step_paged,
    init_cache, init_paged_pool,
)
from ..obs import memplane
from . import sampling
from .paged import PagedKV, pages_for

__all__ = ["SlotEngine", "prompt_bucket", "WIDTH_AXIS", "REPLICA_AXIS"]

_MIN_BUCKET = 8

# Mesh axis names of the serving width shard — the (replica, width)
# view of the PR-8 mesh conventions (DCN outer, ICI inner).
REPLICA_AXIS = "replica"
WIDTH_AXIS = "width"


def prompt_bucket(n: int, cache_len: int) -> int:
    """Pad target for an ``n``-token prefill: the next power of two
    (floor ``_MIN_BUCKET``), clamped to the cache length."""
    if n > cache_len:
        raise ValueError(
            f"prompt of {n} tokens exceeds the {cache_len}-token cache"
        )
    b = _MIN_BUCKET
    while b < n:
        b *= 2
    return min(b, cache_len)


def _pick_tokens(logits, temps, topks, keys, sidx):
    """Vectorized per-slot token pick: each row samples with ITS
    request's key at ITS emission index (sampling.sample_token — the
    same math the oracle tests run)."""

    def one(lg, t, k, base, i):
        return sampling.sample_token(lg, t, k,
                                     sampling.token_key(base, i))

    return jax.vmap(one)(logits, temps, topks, keys, sidx)


class SlotEngine:
    """A fixed pool of decode slots over one model.

    ``admit`` prefills a request into one slot (other slots' caches are
    bitwise untouched — pinned by tests/test_decode.py); ``step`` runs
    one decode iteration for the ACTIVE slots only (frozen rows ride
    along masked).  In paged mode eviction MUST be reported via
    :meth:`release_slot` so the slot's pages return to the free list;
    in contiguous mode an evicted slot is simply excluded from the next
    step's mask and overwritten by the next admission.
    """

    def __init__(self, cfg, params, num_slots: int,
                 max_len: Optional[int] = None, *,
                 kv_mode: str = "contiguous",
                 page_size: int = 16,
                 num_pages: Optional[int] = None,
                 width: int = 1,
                 sample_seed: int = 0):
        if kv_mode not in ("contiguous", "paged"):
            raise ValueError(f"unknown kv_mode {kv_mode!r}")
        self.cfg = cfg
        self.params = params
        self.num_slots = num_slots
        self.kv_mode = kv_mode
        self.width = int(width or 1)
        self.sample_seed = int(sample_seed)
        if self.width > 1 and kv_mode != "paged":
            raise ValueError(
                "width sharding requires kv_mode='paged' (the width-"
                "sharded decode program is the paged one)"
            )
        # Serving context cap: never beyond the model's trained context
        # (a learned-positions model NaN-poisons past max_len, and the
        # prefill forward rejects prompts beyond it) — admission
        # buckets and request validation both bound against THIS.
        self.cache_len = int(max_len or cfg.max_len)
        self.serve_len = min(self.cache_len, int(cfg.max_len))

        self.paged: Optional[PagedKV] = None
        self._mesh = None
        self._sh = self._rep = None
        if kv_mode == "paged":
            self.page_size = int(page_size)
            mp = pages_for(self.cache_len, self.page_size)
            # Default pool: worst case (every slot full) — safe, no
            # memory win; callers size it down to get one (bench/CI
            # prove the waste target with a bounded pool).
            self.num_pages = int(num_pages or num_slots * mp)
            self.paged = PagedKV(num_slots, self.num_pages,
                                 self.page_size, self.cache_len)
            # The virtual slot length the compiled step sees (whole
            # pages); >= cache_len, masked by pos beyond it.
            self.cache_len = self.paged.max_pages_per_slot * self.page_size
            kv_heads = cfg.kv_heads
            self.cache = init_paged_pool(cfg, self.num_pages,
                                         self.page_size, num_slots,
                                         kv_heads=kv_heads)
        else:
            self.cache = init_cache(cfg, num_slots, max_len)
            self.cache_len = int(self.cache["k"].shape[2])
            self.serve_len = min(self.cache_len, int(cfg.max_len))

        if self.width > 1:
            from jax.sharding import Mesh  # noqa: PLC0415

            from ..parallel.tensor_parallel import (  # noqa: PLC0415
                stack_tp_params,
            )

            devs = jax.devices()
            if len(devs) < self.width:
                raise ValueError(
                    f"width={self.width} needs at least that many "
                    f"devices; this process sees {len(devs)}"
                )
            self._mesh = Mesh(
                np.array(devs[:self.width]).reshape(1, self.width),
                (REPLICA_AXIS, WIDTH_AXIS),
            )
            self._sh, self._rep = stack_tp_params(params, cfg, self.width)

        # Host-side per-slot state, identical on every rank by the
        # schedule invariant: current input token, sampling params,
        # request stream root, emission index.
        self._cur = np.zeros(num_slots, np.int32)
        self._temp = np.zeros(num_slots, np.float32)
        self._topk = np.zeros(num_slots, np.int32)
        self._bkey = np.zeros((num_slots,) + sampling.KEY_SHAPE,
                              np.uint32)
        self._sidx = np.zeros(num_slots, np.int32)

        self._tables_dev = None
        self._build_compiled()
        self._assign_exec: Dict[int, object] = {}
        self._step_exec = None
        self._step_flops: Optional[float] = None
        self._step_flops_known = False
        # Memory-plane owner tags: weakref so a dropped engine (tests
        # build many) is pruned, not pinned alive by its observability.
        ref = weakref.ref(self)
        memplane.register_owner(
            "kv_cache", lambda: (lambda e: e.cache if e else None)(ref())
        )
        memplane.register_owner(
            "params", lambda: (lambda e: e.params if e else None)(ref())
        )

    # ---------------------------------------------------------- compiled

    def _build_compiled(self):
        cfg = self.cfg

        if self.kv_mode == "contiguous":

            def _assign(params, cache, slot, tokens, length, temp,
                        topk, bkey):
                cache, last = assign_slot(cfg, params, cache, slot,
                                          tokens, length)
                tok = sampling.sample_token(
                    last, temp, topk, sampling.token_key(bkey, 0)
                )
                return cache, tok

            def _step(params, cache, tokens, mask, temps, topks, keys,
                      sidx):
                logits, cache = decode_step(cfg, params, cache, tokens,
                                            write_mask=mask)
                return _pick_tokens(logits, temps, topks, keys,
                                    sidx), cache

            # The cache is the big state; donate it so each call
            # updates in place instead of keeping input and output
            # pools both live.
            self._assign_compiled = jax.jit(_assign, donate_argnums=(1,))
            self._step_compiled = jax.jit(_step, donate_argnums=(1,))
            return

        if self.width == 1:

            def _assign(params, pool, tables, slot, tokens, length,
                        temp, topk, bkey):
                pool, last = assign_slot_paged(cfg, params, pool,
                                               tables, slot, tokens,
                                               length)
                tok = sampling.sample_token(
                    last, temp, topk, sampling.token_key(bkey, 0)
                )
                return pool, tok

            def _step(params, pool, tables, tokens, mask, temps, topks,
                      keys, sidx):
                logits, pool = decode_step_paged(cfg, params, pool,
                                                 tables, tokens,
                                                 write_mask=mask)
                return _pick_tokens(logits, temps, topks, keys,
                                    sidx), pool

            self._assign_compiled = jax.jit(_assign, donate_argnums=(1,))
            self._step_compiled = jax.jit(_step, donate_argnums=(1,))
            return

        # Width-sharded paged decode: ONE jitted program shard_mapped
        # over the width axis.  The pool's kv-head axis is split across
        # the mesh (each shard holds its heads' pages); params travel
        # as the (sharded, replicated) pair; tables/tokens/sampling
        # state are replicated.  check_rep is off via shard_map_compat
        # (version shim), so the replicated outputs rely on the psum
        # rejoin — deterministic per backend, pinned by tests.
        from jax.sharding import PartitionSpec as P  # noqa: PLC0415

        from ..ops.collectives import shard_map_compat  # noqa: PLC0415

        pool_spec = {
            "k": P(None, None, None, WIDTH_AXIS, None),
            "v": P(None, None, None, WIDTH_AXIS, None),
            "pos": P(),
        }

        def _assign_sm(sh, rep, pool, tables, slot, tokens, length,
                       temp, topk, bkey):
            p = jax.tree_util.tree_map(lambda a: a[0], sh)
            pool, last = assign_slot_paged(
                cfg, p, pool, tables, slot, tokens, length,
                tp_axis=WIDTH_AXIS, rep=rep,
            )
            tok = sampling.sample_token(
                last, temp, topk, sampling.token_key(bkey, 0)
            )
            return pool, tok

        def _step_sm(sh, rep, pool, tables, tokens, mask, temps,
                     topks, keys, sidx):
            p = jax.tree_util.tree_map(lambda a: a[0], sh)
            logits, pool = decode_step_paged(
                cfg, p, pool, tables, tokens, write_mask=mask,
                tp_axis=WIDTH_AXIS, rep=rep,
            )
            return _pick_tokens(logits, temps, topks, keys,
                                sidx), pool

        self._assign_compiled = jax.jit(
            shard_map_compat(
                _assign_sm, mesh=self._mesh,
                in_specs=(P(WIDTH_AXIS), P(), pool_spec, P(), P(), P(),
                          P(), P(), P(), P()),
                out_specs=(pool_spec, P()),
            ),
            donate_argnums=(2,),
        )
        self._step_compiled = jax.jit(
            shard_map_compat(
                _step_sm, mesh=self._mesh,
                in_specs=(P(WIDTH_AXIS), P(), pool_spec, P(), P(), P(),
                          P(), P(), P(), P()),
                out_specs=(P(), pool_spec),
            ),
            donate_argnums=(2,),
        )

    def _tables(self):
        """Device block-table array, cached until an admit/release/
        page-boundary allocation changes it — steady-state decode
        steps (no boundary crossing) reuse the uploaded array instead
        of paying a host rebuild + transfer per step."""
        if self._tables_dev is None:
            self._tables_dev = jnp.asarray(
                [self.paged.table_row(s) for s in range(self.num_slots)],
                jnp.int32,
            )
        return self._tables_dev

    def _params_args(self):
        if self.width > 1:
            return (self._sh, self._rep)
        return (self.params,)

    # --------------------------------------------------------- admission

    def can_admit(self, total_len: int) -> bool:
        """Admission capacity judgement: in paged mode, does the pool
        have free pages for this request's WORST CASE (prompt + full
        token budget) on top of every active commitment?  Contiguous
        mode has no page accounting — a free slot is always enough.
        Point-in-time view; a scheduling round admitting SEVERAL
        requests must use :meth:`admission_gate`."""
        if self.paged is None:
            return True
        return self.paged.can_admit(int(total_len))

    def admission_gate(self):
        """One scheduling round's capacity gate: ``gate(req, resume) ->
        bool``, accumulating the round's accepted worst cases so two
        same-round admissions are never judged against the same free
        pages (serve/paged.py admission_gate)."""
        if self.paged is None:
            return lambda req, resume: True
        page_gate = self.paged.admission_gate()

        def gate(req, resume) -> bool:
            return page_gate(len(req.prompt) + req.max_new_tokens)

        return gate

    def admit(self, slot: int, prompt: Sequence[int],
              resume: Sequence[int] = (), *,
              total_len: Optional[int] = None,
              temperature: float = 0.0, top_k: int = 0,
              rid: str = "") -> Optional[int]:
        """Prefill ``prompt`` (plus already-emitted ``resume`` tokens on
        elastic replay) into ``slot``.

        Fresh request: returns its FIRST generated token (sampled at
        emission index 0 with the request's key — greedy when
        ``temperature == 0``).  Replay: the resume tokens were already
        emitted to the client, so nothing new is generated here — the
        slot is rebuilt to the exact cache state the dead world held
        and returns None; the next ``step`` samples at emission index
        ``len(resume)``, continuing the stream bit-exactly.

        ``total_len`` (paged mode): the request's worst case, ``prompt
        + max_new_tokens`` rows — what the page allocator commits so a
        mid-decode page allocation can never fail.  Defaults to the
        full serving context.
        """
        if resume:
            seq = list(prompt) + list(resume[:-1])
            cur = int(resume[-1])
        else:
            seq = list(prompt)
            cur = None
        bucket = prompt_bucket(len(seq), self.serve_len)
        padded = np.zeros(bucket, np.int32)
        padded[:len(seq)] = seq
        bkey = np.asarray(sampling.request_key(self.sample_seed, rid),
                          np.uint32)
        if self.paged is not None:
            total = int(total_len or self.serve_len)
            self.paged.admit(slot, len(seq), max(total, len(seq)))
            self._tables_dev = None
            args = self._params_args() + (
                self.cache, self._tables(), jnp.asarray(slot, jnp.int32),
                jnp.asarray(padded), jnp.asarray(len(seq), jnp.int32),
                jnp.asarray(temperature, jnp.float32),
                jnp.asarray(top_k, jnp.int32), jnp.asarray(bkey),
            )
        else:
            args = (self.params, self.cache,
                    jnp.asarray(slot, jnp.int32), jnp.asarray(padded),
                    jnp.asarray(len(seq), jnp.int32),
                    jnp.asarray(temperature, jnp.float32),
                    jnp.asarray(top_k, jnp.int32), jnp.asarray(bkey))
        assign_fn = self._assign_exec.get(bucket)
        if assign_fn is None:
            # First admission at this bucket: AOT-compile once (the jit
            # dispatch cache never runs — ONE compile per bucket, same
            # handoff as _step_exec) and register the artifact's memory
            # breakdown while we hold it.
            assign_fn = self._assign_compiled.lower(*args).compile()
            memplane.register_program(f"serve.assign_b{bucket}", assign_fn)
            self._assign_exec[bucket] = assign_fn
        self.cache, first = assign_fn(*args)
        self._temp[slot] = temperature
        self._topk[slot] = top_k
        self._bkey[slot] = bkey
        if cur is not None:
            self._cur[slot] = cur
            self._sidx[slot] = len(resume)
            return None
        tok = int(first)
        self._cur[slot] = tok
        self._sidx[slot] = 1
        return tok

    def release_slot(self, slot: int) -> None:
        """Evict: return the slot's pages to the free list (no-op in
        contiguous mode — the next admission overwrites the rows)."""
        if self.paged is not None:
            self.paged.release(slot)
            self._tables_dev = None

    # ------------------------------------------------------------ decode

    def step(self, active: Iterable[int]) -> Dict[int, int]:
        """One decode iteration: every slot in ``active`` consumes its
        current token and emits the next; all other slots are frozen.
        Returns ``{slot: token}`` for the active slots."""
        slots: List[int] = sorted(active)
        if not slots:
            return {}
        mask = np.zeros(self.num_slots, bool)
        mask[slots] = True
        if self.paged is not None:
            # Page-boundary crossings: make sure each active slot's
            # next write position has a page (cannot fail under the
            # commitment invariant); the device table refreshes only
            # when an allocation actually changed it.
            for s in slots:
                if self.paged.ensure_capacity(s):
                    self._tables_dev = None
            extra = (self._tables(),)
        else:
            extra = ()
        step_fn = self._step_exec or self._step_compiled
        toks, self.cache = step_fn(
            *(self._params_args() + (self.cache,) + extra + (
                jnp.asarray(self._cur), jnp.asarray(mask),
                jnp.asarray(self._temp), jnp.asarray(self._topk),
                jnp.asarray(self._bkey), jnp.asarray(self._sidx),
            ))
        )
        toks = np.asarray(toks)
        out = {}
        for s in slots:
            self._cur[s] = toks[s]
            self._sidx[s] += 1
            if self.paged is not None:
                self.paged.advance(s)
            out[s] = int(toks[s])
        return out

    # --------------------------------------------------------- profiling

    def step_flops(self) -> Optional[float]:
        """Model FLOPs of one ``decode_step`` over the full slot pool,
        from XLA's cost analysis of the compiled artifact (the same
        accountant bench.py trusts — post-fusion, per-device; a width-
        sharded program reports its SHARD's flops, which is the point:
        width divides per-device work).  AOT lowered once and cached;
        None when the backend exposes no cost model."""
        if self._step_flops_known:
            return self._step_flops
        self._step_flops_known = True
        try:
            from ..obs.profile import flops_from_compiled  # noqa: PLC0415

            mask = np.ones(self.num_slots, bool)
            extra = (self._tables(),) if self.paged is not None else ()
            compiled = self._step_compiled.lower(
                *(self._params_args() + (self.cache,) + extra + (
                    jnp.asarray(self._cur), jnp.asarray(mask),
                    jnp.asarray(self._temp), jnp.asarray(self._topk),
                    jnp.asarray(self._bkey), jnp.asarray(self._sidx),
                ))
            ).compile()
            self._step_exec = compiled
            memplane.register_program("serve.decode_step", compiled)
            self._step_flops = flops_from_compiled(compiled)
        except Exception:
            self._step_flops = None
        return self._step_flops

    # ------------------------------------------------------ kv occupancy

    def kv_stats(self, active: Iterable[int] = ()) -> dict:
        """Allocated-vs-live KV bytes.

        Contiguous mode: the fixed-row math (memplane.kv_occupancy) —
        each busy slot charged its full worst-case ``cache_len`` row,
        the PR-14 waste baseline.  Paged mode: recomputed from the
        block table — allocated is pages actually handed out, so the
        only waste left is each slot's partial last page — plus the
        page-pool gauges (``page_size``/``pages_free``/``pages_used``)
        the /metrics surface exports."""
        pool = int(self.cache["k"].nbytes) + int(self.cache["v"].nbytes)
        if self.paged is not None:
            per_pos = pool / float(self.num_pages * self.page_size)
            out = self.paged.stats(per_pos)
            out["pool_bytes"] = pool
            # What the PR-10 contiguous design would have reserved for
            # the same busy slots (slots x worst-case rows): the PR-14
            # baseline recomputed on THIS traffic, so the paged win is
            # an apples-to-apples number in every record.
            out["contiguous_equiv_bytes"] = int(
                out["slots_in_use"] * self.cache_len * per_pos
            )
            return out
        per_pos = pool / float(self.num_slots * self.cache_len)
        positions = np.asarray(self.cache["pos"]).reshape(-1)
        if positions.shape[0] < self.num_slots:  # legacy scalar pos
            positions = np.full(self.num_slots, int(positions[0] if
                                                    positions.size else 0))
        return memplane.kv_occupancy(
            positions.tolist(), list(active), self.cache_len, per_pos,
            pool_bytes=pool,
        )

    # ---------------------------------------------------------- hot swap

    def set_params(self, params) -> None:
        """Swap the served weights in place (weight hot-swap,
        serve/hotswap.py).  The jitted step/assign executables key on
        shapes and dtypes, which a same-model checkpoint preserves — a
        flip costs zero recompiles and the KV cache is untouched (the
        flip happens between decode steps; in-flight requests continue
        over their existing cache).  A width-sharded engine restacks
        the checkpoint into its (sharded, replicated) pair — same
        shapes, so still zero recompiles."""
        old = jax.tree_util.tree_structure(self.params)
        new = jax.tree_util.tree_structure(params)
        if old != new:
            raise ValueError(
                f"hot-swap params tree mismatch: engine serves {old}, "
                f"got {new} — this checkpoint belongs to a different "
                f"model"
            )
        self.params = params
        if self.width > 1:
            from ..parallel.tensor_parallel import (  # noqa: PLC0415
                stack_tp_params,
            )

            self._sh, self._rep = stack_tp_params(params, self.cfg,
                                                  self.width)

    # ------------------------------------------------------------- reset

    def reset(self) -> None:
        """Drop every slot (elastic epoch rebuild): fresh zero cache,
        free page pool, zero cursors.  Compiled functions are retained
        — recovery pays re-prefill, never re-compile."""
        if self.paged is not None:
            self.paged.reset()
            self._tables_dev = None
            self.cache = init_paged_pool(self.cfg, self.num_pages,
                                         self.page_size, self.num_slots,
                                         kv_heads=self.cfg.kv_heads)
        else:
            self.cache = init_cache(self.cfg, self.num_slots,
                                    self.cache_len)
        self._cur[:] = 0
        self._temp[:] = 0.0
        self._topk[:] = 0
        self._bkey[:] = 0
        self._sidx[:] = 0
