"""Slot engine: the compiled-model half of the serving plane.

Wraps the slot-based decode primitives (models/decode.py) for the
continuous-batching loop: ONE jitted ``decode_step`` over the whole
slot pool (shape never changes, so it compiles once), plus one jitted
``assign_slot`` per prompt-length *bucket* (prompts are right-padded to
the next power of two, so admission compiles O(log max_len) variants,
not one per prompt length).

Determinism contract (the serving HVD001 invariant): given the same
config, params, and the same sequence of admit/step/evict calls, every
rank's engine produces bit-identical tokens — the scheduler feeds every
rank the same calls, and XLA's decode math is deterministic per
backend.  Greedy decoding only: sampling would need a per-request PRNG
stream replicated across ranks and replayed across elastic epochs,
which is future work (docs/inference.md, honest limits).
"""

from __future__ import annotations

import weakref
from typing import Dict, Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models.decode import assign_slot, decode_step, init_cache
from ..obs import memplane

__all__ = ["SlotEngine", "prompt_bucket"]

_MIN_BUCKET = 8


def prompt_bucket(n: int, cache_len: int) -> int:
    """Pad target for an ``n``-token prefill: the next power of two
    (floor ``_MIN_BUCKET``), clamped to the cache length."""
    if n > cache_len:
        raise ValueError(
            f"prompt of {n} tokens exceeds the {cache_len}-token cache"
        )
    b = _MIN_BUCKET
    while b < n:
        b *= 2
    return min(b, cache_len)


class SlotEngine:
    """A fixed pool of decode slots over one model.

    ``admit`` prefills a request into one slot (other slots' caches are
    bitwise untouched — pinned by tests/test_decode.py); ``step`` runs
    one decode iteration for the ACTIVE slots only (frozen rows ride
    along masked).  Eviction needs no engine call: an evicted slot is
    simply excluded from the next step's mask and overwritten by the
    next admission.
    """

    def __init__(self, cfg, params, num_slots: int,
                 max_len: Optional[int] = None):
        self.cfg = cfg
        self.params = params
        self.num_slots = num_slots
        self.cache = init_cache(cfg, num_slots, max_len)
        self.cache_len = int(self.cache["k"].shape[2])
        # Serving context cap: never beyond the model's trained context
        # (a learned-positions model NaN-poisons past max_len, and the
        # prefill forward rejects prompts beyond it), and never beyond
        # the slot — admission buckets and request validation both
        # bound against THIS, so an oversized cache can't admit a
        # request whose power-of-two bucket trips the forward's
        # max_len guard and crash-loops the fleet.
        self.serve_len = min(self.cache_len, int(cfg.max_len))
        # Current input token per slot (the last token emitted there).
        self._cur = np.zeros(num_slots, np.int32)

        def _assign(params, cache, slot, tokens, length):
            cache, last = assign_slot(cfg, params, cache, slot,
                                      tokens, length)
            return cache, jnp.argmax(last).astype(jnp.int32)

        # One jitted assign serves every bucket: jax.jit's own trace
        # cache keys on the padded shape, so power-of-two padding alone
        # bounds compiles at O(log max_len).  The per-bucket AOT
        # executables live in _assign_exec (same single-compile handoff
        # as _step_exec) so each bucket's memory breakdown is read off
        # the artifact the moment it compiles.
        self._assign_compiled = jax.jit(_assign, donate_argnums=(1,))
        self._assign_exec: Dict[int, object] = {}

        def _step(params, cache, tokens, mask):
            logits, cache = decode_step(cfg, params, cache, tokens,
                                        write_mask=mask)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

        # The cache is the big state (L·b·S·kv — the whole point of the
        # slot pool); donate it so each step updates in place instead of
        # keeping input and output pools both live.
        self._step_compiled = jax.jit(_step, donate_argnums=(1,))
        # AOT executable shared by step() and step_flops(): jit's
        # dispatch cache never sees lower().compile(), so without the
        # handoff every rank that asks for FLOPs would pay the
        # full-pool compile a second time on its first real step.
        self._step_exec = None
        self._step_flops: Optional[float] = None
        self._step_flops_known = False
        # Memory-plane owner tags: the census buckets live arrays by
        # who holds them.  Registered through a weakref so a dropped
        # engine (tests build many) is pruned, not pinned alive by its
        # own observability.
        ref = weakref.ref(self)
        memplane.register_owner(
            "kv_cache", lambda: (lambda e: e.cache if e else None)(ref())
        )
        memplane.register_owner(
            "params", lambda: (lambda e: e.params if e else None)(ref())
        )

    # --------------------------------------------------------- admission

    def admit(self, slot: int, prompt: Sequence[int],
              resume: Sequence[int] = ()) -> Optional[int]:
        """Prefill ``prompt`` (plus already-emitted ``resume`` tokens on
        elastic replay) into ``slot``.

        Fresh request: returns its FIRST generated token (greedy pick at
        the prompt's last position).  Replay: the resume tokens were
        already emitted to the client, so nothing new is generated here
        — the slot is rebuilt to the exact cache state the dead world
        held and returns None.
        """
        if resume:
            seq = list(prompt) + list(resume[:-1])
            cur = int(resume[-1])
        else:
            seq = list(prompt)
            cur = None
        bucket = prompt_bucket(len(seq), self.serve_len)
        padded = np.zeros(bucket, np.int32)
        padded[:len(seq)] = seq
        args = (self.params, self.cache, jnp.asarray(slot, jnp.int32),
                jnp.asarray(padded), jnp.asarray(len(seq), jnp.int32))
        assign_fn = self._assign_exec.get(bucket)
        if assign_fn is None:
            # First admission at this bucket: AOT-compile once (the jit
            # dispatch cache never runs — ONE compile per bucket, same
            # handoff as _step_exec) and register the artifact's memory
            # breakdown while we hold it.
            assign_fn = self._assign_compiled.lower(*args).compile()
            memplane.register_program(f"serve.assign_b{bucket}", assign_fn)
            self._assign_exec[bucket] = assign_fn
        self.cache, first = assign_fn(*args)
        if cur is not None:
            self._cur[slot] = cur
            return None
        tok = int(first)
        self._cur[slot] = tok
        return tok

    # ------------------------------------------------------------ decode

    def step(self, active: Iterable[int]) -> Dict[int, int]:
        """One decode iteration: every slot in ``active`` consumes its
        current token and emits the next; all other slots are frozen.
        Returns ``{slot: token}`` for the active slots."""
        slots: List[int] = sorted(active)
        if not slots:
            return {}
        mask = np.zeros(self.num_slots, bool)
        mask[slots] = True
        step_fn = self._step_exec or self._step_compiled
        toks, self.cache = step_fn(
            self.params, self.cache, jnp.asarray(self._cur),
            jnp.asarray(mask),
        )
        toks = np.asarray(toks)
        out = {}
        for s in slots:
            self._cur[s] = toks[s]
            out[s] = int(toks[s])
        return out

    # --------------------------------------------------------- profiling

    def step_flops(self) -> Optional[float]:
        """Model FLOPs of one ``decode_step`` over the full slot pool,
        from XLA's cost analysis of the compiled artifact (the same
        accountant bench.py trusts — post-fusion, per-device).  AOT
        lowered once and cached; None when the backend exposes no cost
        model.  The serving MFU gauge divides this by the measured
        decode-step time, so the number is honest about masked slots:
        the artifact computes every row whether or not it is live."""
        if self._step_flops_known:
            return self._step_flops
        self._step_flops_known = True
        try:
            from ..obs.profile import flops_from_compiled  # noqa: PLC0415

            mask = np.ones(self.num_slots, bool)
            compiled = self._step_compiled.lower(
                self.params, self.cache, jnp.asarray(self._cur),
                jnp.asarray(mask),
            ).compile()
            self._step_exec = compiled
            memplane.register_program("serve.decode_step", compiled)
            self._step_flops = flops_from_compiled(compiled)
        except Exception:
            self._step_flops = None
        return self._step_flops

    # ------------------------------------------------------ kv occupancy

    def kv_stats(self, active: Iterable[int] = ()) -> dict:
        """Allocated-vs-live KV bytes for the slots in ``active`` —
        the waste number ROADMAP item 1's paged attention will attack
        (obs/memplane.py kv_occupancy, measured before the fix lands so
        its win is provable).  ``allocated`` charges each busy slot its
        full worst-case ``cache_len`` row (that IS what the contiguous
        pool reserves); ``live`` counts only written positions.  Costs
        one tiny pos-vector device read — call it at gauge cadence, it
        rides the serving loop's existing per-step host sync."""
        pool = int(self.cache["k"].nbytes) + int(self.cache["v"].nbytes)
        per_pos = pool / float(self.num_slots * self.cache_len)
        positions = np.asarray(self.cache["pos"]).reshape(-1)
        if positions.shape[0] < self.num_slots:  # legacy scalar pos
            positions = np.full(self.num_slots, int(positions[0] if
                                                    positions.size else 0))
        return memplane.kv_occupancy(
            positions.tolist(), list(active), self.cache_len, per_pos,
            pool_bytes=pool,
        )

    # ---------------------------------------------------------- hot swap

    def set_params(self, params) -> None:
        """Swap the served weights in place (weight hot-swap,
        serve/hotswap.py).  The jitted step/assign executables key on
        shapes and dtypes, which a same-model checkpoint preserves — a
        flip costs zero recompiles and the KV cache is untouched (the
        flip happens between decode steps; in-flight requests continue
        over their existing cache).  Structure/shape mismatches were
        already rejected at prefetch time by the manifest validation,
        but a direct caller gets the same loud error here."""
        old = jax.tree_util.tree_structure(self.params)
        new = jax.tree_util.tree_structure(params)
        if old != new:
            raise ValueError(
                f"hot-swap params tree mismatch: engine serves {old}, "
                f"got {new} — this checkpoint belongs to a different "
                f"model"
            )
        self.params = params

    # ------------------------------------------------------------- reset

    def reset(self) -> None:
        """Drop every slot (elastic epoch rebuild): fresh zero cache,
        zero cursors.  Compiled functions are retained — recovery pays
        re-prefill, never re-compile."""
        self.cache = init_cache(self.cfg, self.num_slots, self.cache_len)
        self._cur[:] = 0
