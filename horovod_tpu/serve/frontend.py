"""Request front end: HTTP ingest on the launcher, streaming results.

The serving plane reuses the launcher's HMAC-signed KV store
(run/rendezvous.py) as its wire — the same plumbing that already
carries rendezvous, heartbeats, live telemetry and checkpoint replicas.
Three key families under the ``serve`` scope:

* ``serve/req/<rid>``  — client submissions (signed PUT).  The HTTP
  surface deliberately has no listing verb, so workers cannot drain
  this directly; the launcher-resident :class:`IngestPump` (which owns
  the store in-process, like the live aggregator) scans it and...
* ``serve/log/<n>``    — ...rewrites each submission into a totally
  ordered, immutable ingest log.  Rank 0 of the serving world drains
  the log by sequence number and broadcasts each step's schedule to
  its peers, so every rank admits identical requests in identical
  order (the HVD001 invariant).  The log also IS the durable request
  record elastic recovery replays from.
* ``serve/out/<rid>``  — per-request streaming state, written by the
  serving leader after every step: tokens emitted so far, done flag,
  admission/finish bookkeeping.  Clients poll it (signed GET) to
  stream tokens as they are generated.

``serve/stop`` is the drain sentinel: the leader folds it into the
step schedule, finishes everything in flight, and the world exits
cleanly.
"""

from __future__ import annotations

import pickle
import threading
import time
import uuid
from typing import List, Optional, Sequence

from ..obs import trace as obs_trace
from ..run.rendezvous import KVStoreClient
from ..utils.logging import get_logger

LOG = get_logger("serve.frontend")

SCOPE = "serve"
REQ_PREFIX = SCOPE + "/req/"

__all__ = ["ServeClient", "IngestPump", "validate_request", "SCOPE"]


def validate_request(doc: dict, serve_len: int,
                     vocab_size: Optional[int] = None) -> Optional[str]:
    """Reject reason for an ingest-log entry, or None when servable.
    Pure — every rank applies it to the same log entry and reaches the
    same verdict (a rank-divergent reject would desync the schedule).

    ``serve_len`` is the engine's serving context cap
    (``min(cache_len, cfg.max_len)``): bounding against the raw cache
    length alone would let an oversized cache admit a prompt whose
    prefill bucket trips the model's own max_len guard.  ``vocab_size``
    rejects out-of-vocab ids — the embedding gather would otherwise
    silently CLAMP them (JAX's default), returning deterministic
    garbage where this module's contract is a loud reject."""
    prompt = doc.get("prompt")
    if not isinstance(prompt, (list, tuple)) or not prompt:
        return "empty or malformed prompt"
    if not all(isinstance(t, int) and t >= 0 for t in prompt):
        return "prompt tokens must be non-negative ints"
    if vocab_size is not None and any(t >= vocab_size for t in prompt):
        return f"prompt token out of vocab (>= {vocab_size})"
    mnt = doc.get("max_new_tokens", 0)
    if not isinstance(mnt, int) or mnt < 1:
        return "max_new_tokens must be >= 1"
    if len(prompt) + mnt > serve_len:
        return (
            f"prompt ({len(prompt)}) + max_new_tokens ({mnt}) exceeds "
            f"the {serve_len}-token serving context"
        )
    temp = doc.get("temperature", 0.0)
    if not isinstance(temp, (int, float)) or temp < 0:
        return "temperature must be a number >= 0"
    top_k = doc.get("top_k", 0)
    if not isinstance(top_k, int) or top_k < 0:
        return "top_k must be an int >= 0"
    return None


class ServeClient:
    """Client half of the front end: submit prompts, stream tokens.

    Talks the signed KV protocol (the secret travels via
    ``HVDTPU_SECRET`` or the constructor), so any process holding the
    per-job secret can drive a serving job — the CI gates, bench.py's
    open-loop generator, and operator tooling all use this class.
    """

    def __init__(self, addr: str, secret: Optional[str] = None):
        self._kv = KVStoreClient(addr, secret)

    def submit(self, prompt: Sequence[int], *,
               max_new_tokens: int = 16,
               eos_id: Optional[int] = None,
               temperature: float = 0.0,
               top_k: int = 0,
               rid: Optional[str] = None) -> str:
        """Enqueue one generation request; returns its request id.

        ``temperature > 0`` samples instead of greedy argmax (``top_k``
        truncates the candidate set); the stream is still deterministic
        — tokens are keyed on (rid, emission index, serve seed), so a
        resubmission with the SAME rid reproduces the same text and
        elastic replay continues it bit-exactly (serve/sampling.py)."""
        rid = rid or uuid.uuid4().hex[:16]
        doc = {
            "rid": rid,
            "prompt": [int(t) for t in prompt],
            "max_new_tokens": int(max_new_tokens),
            "eos_id": None if eos_id is None else int(eos_id),
            "temperature": float(temperature),
            "top_k": int(top_k),
            # Client-clock submit stamp: the trace waterfall's first
            # span (submit -> ingest) is measured against this; the
            # rid doubles as the request's trace id.
            "submit_t": time.time(),
        }
        self._kv.put(SCOPE, f"req/{rid}", pickle.dumps(doc))
        return rid

    def poll(self, rid: str) -> Optional[dict]:
        """Streaming state ``{"tokens", "done", ...}`` or None before
        the first token lands."""
        raw = self._kv.get(SCOPE, f"out/{rid}")
        return None if raw is None else pickle.loads(raw)

    def result(self, rid: str, timeout: float = 120.0) -> dict:
        """Block until the request finishes; raises RuntimeError when
        the server rejected it (the reject reason is in the doc)."""
        deadline = time.monotonic() + timeout
        t_fetch0 = time.time()
        delay = 0.02
        while time.monotonic() < deadline:
            doc = self.poll(rid)
            if doc is not None and doc.get("done"):
                if doc.get("error"):
                    raise RuntimeError(
                        f"request {rid} rejected: {doc['error']}"
                    )
                # Result-fetch span on the caller's clock (the bench /
                # CI client runs in the launcher process, so this lands
                # in the launcher's span dump when tracing is armed).
                if obs_trace.enabled() and obs_trace.sampled(rid):
                    obs_trace.add_span(rid, "result_fetch", t_fetch0,
                                       time.time(),
                                       tokens=len(doc.get("tokens", [])))
                return doc
            time.sleep(delay)
            delay = min(delay * 2, 0.25)
        raise TimeoutError(f"request {rid} not finished within {timeout}s")

    def stop(self) -> None:
        """Raise the drain sentinel: in-flight and queued requests
        complete, then the serving world exits."""
        self._kv.put(SCOPE, "stop", b"1")


class IngestPump:
    """Launcher-resident ingest thread: scans ``serve/req/*`` on the
    in-process store (the listing the HTTP surface deliberately lacks)
    and appends each submission to the totally ordered ``serve/log/<n>``
    the serving leader drains.

    Ordering within one scan round is by request id — arrival order
    inside a round is not observable from a dict snapshot, and a
    deterministic tiebreak beats a racy one.  Arrival wall time is
    stamped here (the launcher's clock), which is what ttft is measured
    against.
    """

    def __init__(self, server, interval: float = 0.02,
                 out_ttl_secs: Optional[float] = None):
        from ..utils import env as envmod  # noqa: PLC0415

        self._server = server
        self._kv = KVStoreClient(f"127.0.0.1:{server.port}",
                                 server.secret)
        self.interval = max(float(interval), 0.005)
        # Finished-output retention: a result doc whose log index fell
        # below the leader's compaction watermark is kept this long for
        # late client polls, then GC'd (see _gc_finished_outputs).
        self.out_ttl_secs = (
            float(out_ttl_secs) if out_ttl_secs is not None
            else envmod.env_float(envmod.SERVE_OUT_TTL,
                                  envmod.DEFAULT_SERVE_OUT_TTL)
        )
        self._next = 0
        self._done_seen: dict = {}  # out key -> monotonic first-seen-done
        # The finished-output GC unpickles every live out doc, so it
        # runs on its own ~1s cadence, not the 20ms ingest tick (TTL
        # granularity is hundreds of seconds; millisecond precision
        # would buy 50x the deserialization cost and nothing else).
        self._gc_every = min(1.0, max(self.out_ttl_secs / 4, 0.01))
        self._next_gc = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def ingested(self) -> int:
        return self._next

    def round(self) -> int:
        """Move every pending submission into the log; returns how many.
        Also garbage-collects dead-epoch serving scopes (see
        :meth:`_gc_stale_epochs`) and compacted finished outputs (see
        :meth:`_gc_finished_outputs`) — the pump is the one serving
        component with in-process listing access to the store."""
        self._gc_stale_epochs()
        self._gc_finished_outputs()
        pending = self._server.scan(REQ_PREFIX)
        moved = 0
        for key in sorted(pending):
            try:
                doc = pickle.loads(pending[key])
                rid = doc["rid"]
            except Exception:
                LOG.warning("dropping malformed submission %s", key)
                self._server.discard([key])
                continue
            doc["arrival"] = time.time()
            doc["n"] = self._next
            self._kv.put(SCOPE, f"log/{self._next}", pickle.dumps(doc))
            self._next += 1
            moved += 1
            self._server.discard([key])
            # Launcher-side spans: submit -> ingest (client clock to
            # launcher clock — one host in practice) and the log
            # append itself.  The deterministic sampling verdict is the
            # SAME one every serving rank reaches for this rid.
            if obs_trace.enabled() and obs_trace.sampled(rid):
                submit_t = float(doc.get("submit_t") or doc["arrival"])
                obs_trace.add_span(rid, "ingest",
                                   min(submit_t, doc["arrival"]),
                                   doc["arrival"], n=doc["n"])
                obs_trace.add_span(rid, "log_append", doc["arrival"],
                                   time.time(), n=doc["n"])
            LOG.debug("ingested request %s as log/%d", rid, doc["n"])
        return moved

    def _gc_stale_epochs(self) -> None:
        """Drop schedule/recovery keys from epochs older than the
        current rendezvous epoch.  The leader's in-band GC only trims
        its OWN epoch's trailing window; every world break would
        otherwise permanently leak the dead epoch's remaining sched
        pickles and recovery doc — unbounded launcher memory on a
        long-lived fleet with periodic rank churn.  Old-epoch keys are
        immutable and unreadable by design (survivors and respawns
        alike rebuild from the NEW epoch's recovery doc), so deleting
        them can never race a reader."""
        raw = self._server.scan("elastic/epoch")
        try:
            current = int(raw["elastic/epoch"])
        except (KeyError, ValueError):
            return  # no elastic world yet (or a non-elastic store)
        doomed = []
        for key in self._server.scan("serve_e"):
            scope = key.split("/", 1)[0]
            try:
                epoch = int(scope[len("serve_e"):])
            except ValueError:
                continue
            if epoch < current:
                doomed.append(key)
        if doomed:
            self._server.discard(doomed)
            LOG.debug("GC'd %d stale-epoch serving keys", len(doomed))

    def _gc_finished_outputs(self) -> None:
        """Drop result docs of requests the leader's compaction
        watermark already retired (their log keys are gone — recovery
        replay will never need them) once they have been done for
        ``out_ttl_secs``.  This is the second half of request-log
        compaction: without it ``serve/out/*`` still grows with total
        requests ever served even though ``serve/log/*`` no longer
        does.  The TTL exists for late pollers; a client that sleeps
        past it sees a result timeout, which docs/inference.md states
        as the honest trade."""
        if time.monotonic() < self._next_gc:
            return
        self._next_gc = time.monotonic() + self._gc_every
        raw = self._server.scan(SCOPE + "/log_watermark")
        try:
            watermark = int(
                raw[SCOPE + "/log_watermark"].decode())
        except (KeyError, ValueError):
            return  # no compaction yet
        # Orphan sweep: the leader publishes the watermark BEFORE
        # deleting the retired log keys, so a crash between the two
        # leaves below-watermark entries nobody will ever read (the
        # recovery scan starts at the watermark).  The pump is the one
        # component that can list them.
        orphans = []
        for key in self._server.scan(SCOPE + "/log/"):
            try:
                if int(key.rsplit("/", 1)[1]) < watermark:
                    orphans.append(key)
            except ValueError:
                continue
        if orphans:
            self._server.discard(orphans)
            LOG.debug("GC'd %d below-watermark log orphans",
                      len(orphans))
        now = time.monotonic()
        doomed = []
        live = self._server.scan(SCOPE + "/out/")
        for key, blob in live.items():
            try:
                doc = pickle.loads(blob)
            except Exception:
                continue
            n = doc.get("n")
            if not doc.get("done") or n is None or int(n) >= watermark:
                continue
            first = self._done_seen.setdefault(key, now)
            if now - first >= self.out_ttl_secs:
                doomed.append(key)
        if doomed:
            self._server.discard(doomed)
            for key in doomed:
                self._done_seen.pop(key, None)
            LOG.debug("GC'd %d compacted result docs", len(doomed))
        # Tracking entries for keys something else already removed.
        for key in list(self._done_seen):
            if key not in live:
                self._done_seen.pop(key, None)

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="hvdtpu_serve_ingest", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.round()
            except Exception as exc:  # pragma: no cover - defensive
                LOG.warning("ingest round failed: %s", exc)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        try:
            self.round()  # drain what arrived before the stop
        except Exception:  # pragma: no cover - defensive
            pass
