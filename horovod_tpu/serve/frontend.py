"""Request front door: sharded HTTP ingest on the launcher, streaming
results.

The serving plane reuses the launcher's HMAC-signed KV store
(run/rendezvous.py) as its wire — the same plumbing that already
carries rendezvous, heartbeats, live telemetry and checkpoint replicas.
Since ISSUE 16 the request plane is **sharded**: ``F`` frontend pumps
(:class:`FrontDoor`) each own a rid-hash partition of the request log,
so one frontend death strands nothing.  Key families under the
``serve`` scope:

* ``serve/req/<shard>/<rid>`` — client submissions (signed PUT).
  Clients route by the pure hash ``crc32(rid) % F`` — the same
  PYTHONHASHSEED-proof digest the sampling plane keys streams on — so
  producer-side routing needs no coordination.  The HTTP surface
  deliberately has no listing verb, so workers cannot drain this
  directly; the launcher-resident shard pumps (which own the store
  in-process, like the live aggregator) scan their partitions and...
* ``serve/log/<shard>/<n>`` — ...rewrite each submission into a
  per-shard, immutable ingest log with per-shard sequence numbers.
  The interleave ``gkey = n * F + shard`` is the total order every
  consumer derives identically; each serving group's leader drains the
  partition ``gkey % groups == group`` (service.py).  The log also IS
  the durable request record elastic recovery replays from.
* ``serve/out/<rid>`` — per-request streaming state, written by the
  serving leader after every step: tokens emitted so far, done flag,
  admission/finish bookkeeping.  Clients poll it (signed GET) to
  stream tokens as they are generated.
* ``serve/frontdoor`` — the shard-ownership doc (`{frontends, owners,
  fd_epoch}`): clients read ``frontends`` once to route, workers read
  it at epoch start to derive the interleave.
* ``serve/fd/hb/<fid>`` — per-frontend heartbeat counters.  The
  :class:`FrontDoor` supervisor declares a frontend dead when its beat
  goes stale (or its thread dies), hands its shards to the lowest
  surviving frontend, and surfaces a takeover event the elastic
  monitor turns into a re-minted epoch (the PR-13 resize machinery) —
  in-flight requests replay from the log with zero drops.

``serve/stop`` is the drain sentinel: the leader folds it into the
step schedule, finishes everything in flight, and the world exits
cleanly.
"""

from __future__ import annotations

import pickle
import threading
import time
import uuid
import zlib
from typing import Dict, List, Optional, Sequence, Set

from ..obs import trace as obs_trace
from ..run.rendezvous import KVStoreClient
from ..utils.logging import get_logger
from .scheduler import SLO_CLASSES

LOG = get_logger("serve.frontend")

SCOPE = "serve"
REQ_PREFIX = SCOPE + "/req/"
LOG_PREFIX = SCOPE + "/log/"
WATERMARK_PREFIX = SCOPE + "/log_watermark/"
FRONTDOOR_KEY = "frontdoor"
HEARTBEAT_PREFIX = "fd/hb/"

__all__ = ["ServeClient", "IngestPump", "FrontDoor", "validate_request",
           "Rejection", "RequestRejected", "shard_of", "SCOPE"]


def shard_of(rid: str, frontends: int) -> int:
    """The rid's front-door shard: ``crc32(rid) % F``.  Pure and
    PYTHONHASHSEED-proof (never builtin ``hash()``), so the client, the
    pumps, and every serving rank derive the same route."""
    if frontends <= 1:
        return 0
    return zlib.crc32(rid.encode("utf-8")) % frontends


class Rejection(str):
    """A machine-readable reject verdict: a plain ``str`` (the human
    message — drop-in for every call site that formatted the old bare
    string) carrying a stable ``code`` for programmatic handling."""

    code: str

    def __new__(cls, code: str, message: str) -> "Rejection":
        obj = super().__new__(cls, message)
        obj.code = code
        return obj

    def __getnewargs__(self):
        return (self.code, str(self))

    @property
    def message(self) -> str:
        return str(self)


class RequestRejected(RuntimeError):
    """Raised by :meth:`ServeClient.result` when the server refused the
    request; ``code`` is the machine-readable reason
    (:func:`validate_request`), ``message`` the human one."""

    def __init__(self, rid: str, code: str, message: str):
        super().__init__(f"request {rid} rejected [{code}]: {message}")
        self.rid = rid
        self.code = code
        self.message = message


def validate_request(doc: dict, serve_len: int,
                     vocab_size: Optional[int] = None,
                     budget_tokens: Optional[int] = None
                     ) -> Optional[Rejection]:
    """Reject verdict for an ingest-log entry, or None when servable.
    Pure — every rank applies it to the same log entry and reaches the
    same verdict (a rank-divergent reject would desync the schedule).
    Returns a :class:`Rejection` (a str subclass), so existing
    formatting keeps working while clients get a stable ``code``.

    ``serve_len`` is the engine's serving context cap
    (``min(cache_len, cfg.max_len)``): bounding against the raw cache
    length alone would let an oversized cache admit a prompt whose
    prefill bucket trips the model's own max_len guard.  ``vocab_size``
    rejects out-of-vocab ids — the embedding gather would otherwise
    silently CLAMP them (JAX's default), returning deterministic
    garbage where this module's contract is a loud reject.
    ``budget_tokens`` is the TenantQoS per-window token budget when a
    QoS policy is armed: a request whose cost (prompt +
    max_new_tokens) exceeds the whole budget would be throttled in
    EVERY window forever — with per-tenant-FIFO heads that bricks the
    tenant behind it, and its never-done log slot stalls the shard's
    compaction watermark permanently.  Rejecting it loudly at
    validation time publishes a done doc, so the client learns
    immediately and compaction advances."""
    prompt = doc.get("prompt")
    if not isinstance(prompt, (list, tuple)) or not prompt:
        return Rejection("bad_prompt", "empty or malformed prompt")
    if not all(isinstance(t, int) and t >= 0 for t in prompt):
        return Rejection("bad_token",
                         "prompt tokens must be non-negative ints")
    if vocab_size is not None and any(t >= vocab_size for t in prompt):
        return Rejection(
            "oob_token", f"prompt token out of vocab (>= {vocab_size})"
        )
    mnt = doc.get("max_new_tokens", 0)
    if not isinstance(mnt, int) or mnt < 1:
        return Rejection("bad_budget", "max_new_tokens must be >= 1")
    if len(prompt) + mnt > serve_len:
        return Rejection(
            "ctx_exceeded",
            f"prompt ({len(prompt)}) + max_new_tokens ({mnt}) exceeds "
            f"the {serve_len}-token serving context",
        )
    if budget_tokens is not None and len(prompt) + mnt > budget_tokens:
        return Rejection(
            "budget_exceeded",
            f"prompt ({len(prompt)}) + max_new_tokens ({mnt}) exceeds "
            f"the {budget_tokens}-token per-window tenant budget; the "
            f"request could never be admitted",
        )
    temp = doc.get("temperature", 0.0)
    if not isinstance(temp, (int, float)) or temp < 0:
        return Rejection("bad_temperature",
                         "temperature must be a number >= 0")
    top_k = doc.get("top_k", 0)
    if not isinstance(top_k, int) or top_k < 0:
        return Rejection("bad_top_k", "top_k must be an int >= 0")
    tenant = doc.get("tenant", "default")
    if not isinstance(tenant, str) or not tenant or len(tenant) > 64 \
            or "/" in tenant:
        return Rejection(
            "bad_tenant",
            "tenant must be a non-empty str (<= 64 chars, no '/')",
        )
    slo = doc.get("slo", "standard")
    if slo not in SLO_CLASSES:
        return Rejection(
            "bad_slo", f"slo must be one of {'/'.join(SLO_CLASSES)}"
        )
    return None


class ServeClient:
    """Client half of the front door: submit prompts, stream tokens.

    Talks the signed KV protocol (the secret travels via
    ``HVDTPU_SECRET`` or the constructor), so any process holding the
    per-job secret can drive a serving job — the CI gates, bench.py's
    open-loop generator, and operator tooling all use this class.
    Routing is client-side and coordination-free: one read of the
    ``serve/frontdoor`` doc pins ``F``, then every submission routes by
    ``crc32(rid) % F``.
    """

    def __init__(self, addr: str, secret: Optional[str] = None):
        self._kv = KVStoreClient(addr, secret)
        self._frontends: Optional[int] = None

    def frontends(self) -> int:
        """Shard count ``F`` from the front-door doc (cached once
        READ — the count is fixed for the job's lifetime; only shard
        OWNERSHIP moves on takeover, which routing is blind to by
        design).  An absent or unreadable doc falls back to 1 WITHOUT
        caching: a client constructed before the FrontDoor publishes
        (or during a transient KV error) must not pin every later
        submission to shard 0 for its lifetime — the next call
        re-reads."""
        if self._frontends is None:
            raw = self._kv.get(SCOPE, FRONTDOOR_KEY)
            if raw is None:
                return 1
            try:
                self._frontends = max(
                    int(pickle.loads(raw).get("frontends", 1)), 1
                )
            except Exception:
                return 1
        return self._frontends

    def submit(self, prompt: Sequence[int], *,
               max_new_tokens: int = 16,
               eos_id: Optional[int] = None,
               temperature: float = 0.0,
               top_k: int = 0,
               tenant: str = "default",
               slo: str = "standard",
               rid: Optional[str] = None) -> str:
        """Enqueue one generation request; returns its request id.

        ``temperature > 0`` samples instead of greedy argmax (``top_k``
        truncates the candidate set); the stream is still deterministic
        — tokens are keyed on (rid, emission index, serve seed), so a
        resubmission with the SAME rid reproduces the same text and
        elastic replay continues it bit-exactly (serve/sampling.py).

        ``tenant``/``slo`` feed the tenant-aware admission policy
        (serve/scheduler.py TenantQoS): the tenant names the token
        budget bucket, the slo class ("interactive" | "standard" |
        "batch") the admission weight.  Both are validated server-side
        (machine-readable reject on a bad value) and ignored when the
        fleet runs without a QoS policy."""
        rid = rid or uuid.uuid4().hex[:16]
        doc = {
            "rid": rid,
            "prompt": [int(t) for t in prompt],
            "max_new_tokens": int(max_new_tokens),
            "eos_id": None if eos_id is None else int(eos_id),
            "temperature": float(temperature),
            "top_k": int(top_k),
            "tenant": str(tenant),
            "slo": str(slo),
            # Client-clock submit stamp: the trace waterfall's first
            # span (submit -> ingest) is measured against this; the
            # rid doubles as the request's trace id.
            "submit_t": time.time(),
        }
        shard = shard_of(rid, self.frontends())
        self._kv.put(SCOPE, f"req/{shard}/{rid}", pickle.dumps(doc))
        return rid

    def poll(self, rid: str) -> Optional[dict]:
        """Streaming state ``{"tokens", "done", ...}`` or None before
        the first token lands."""
        raw = self._kv.get(SCOPE, f"out/{rid}")
        return None if raw is None else pickle.loads(raw)

    def result(self, rid: str, timeout: float = 120.0, *,
               poll_floor: float = 0.02,
               poll_cap: float = 0.5) -> dict:
        """Block until the request finishes; raises
        :class:`RequestRejected` when the server refused it (the
        machine-readable code rides the exception) and TimeoutError on
        the deadline.

        Polling backs off exponentially from ``poll_floor`` to
        ``poll_cap`` — the same fix ``KVStoreClient.wait`` got in PR 3,
        so thousands of blocked clients cannot saturate a frontend
        shard — and RESETS to the floor whenever the stream makes
        progress (first doc, more tokens): an actively streaming
        request is tracked closely, a queued one is polled gently."""
        deadline = time.monotonic() + timeout
        t_fetch0 = time.time()
        delay = poll_floor
        progress = -1
        while time.monotonic() < deadline:
            doc = self.poll(rid)
            if doc is not None and doc.get("done"):
                if doc.get("error"):
                    raise RequestRejected(
                        rid, doc.get("error_code") or "rejected",
                        doc["error"],
                    )
                # Result-fetch span on the caller's clock (the bench /
                # CI client runs in the launcher process, so this lands
                # in the launcher's span dump when tracing is armed).
                if obs_trace.enabled() and obs_trace.sampled(rid):
                    obs_trace.add_span(rid, "result_fetch", t_fetch0,
                                       time.time(),
                                       tokens=len(doc.get("tokens", [])))
                return doc
            seen = -1 if doc is None else len(doc.get("tokens", ()))
            if seen > progress:
                progress = seen
                delay = poll_floor
            time.sleep(delay)
            delay = min(delay * 2, poll_cap)
        raise TimeoutError(f"request {rid} not finished within {timeout}s")

    def stop(self) -> None:
        """Raise the drain sentinel: in-flight and queued requests
        complete, then the serving world exits."""
        self._kv.put(SCOPE, "stop", b"1")


class _FrontendKilled(Exception):
    """Internal: an injected frontend death (FrontDoor.kill or the
    ``frontend_beat:action=frontend_exit`` chaos point) — the pump
    thread dies abruptly, mid-traffic, without draining."""


class _ShardFence:
    """In-process fencing for front-door shard ownership.

    The stale-heartbeat supervisor can declare a pump dead that is
    merely SLOW — stalled mid-round on the GIL or a store scan, which
    is exactly what made its beat stale.  Without a fence that zombie
    finishes its in-flight round concurrently with the adopter: both
    scan the same ``serve/req/<shard>/`` keys and can append the same
    rid twice, or write the same ``log/<shard>/<n>`` key with
    different rids.  Two guarantees close that race:

    * **per-shard locks** — at most one pump is ever inside a shard's
      scan-and-append round, so the adopter can never interleave
      appends with the pump it replaced; the adopter recovers the
      shard's cursor and dedup set AFTER first acquiring the lock, so
      it sees every append the previous owner got in;
    * **an owner map** — a pump re-checks ownership under the lock at
      round start and again before every append, so a zombie that lost
      its shard to a takeover aborts instead of writing.

    All pumps are launcher-resident threads of ONE FrontDoor, which is
    what makes an in-process fence sufficient: there is no
    cross-process writer to fence against."""

    def __init__(self, owners: Dict[int, int]):
        self._meta = threading.Lock()
        self._owners: Dict[int, int] = {int(s): int(f)
                                        for s, f in owners.items()}
        self._locks: Dict[int, threading.Lock] = {}

    def lock_of(self, shard: int) -> threading.Lock:
        with self._meta:
            return self._locks.setdefault(int(shard), threading.Lock())

    def owner_of(self, shard: int) -> Optional[int]:
        with self._meta:
            return self._owners.get(int(shard))

    def transfer(self, shard: int, fid: int,
                 timeout: float = 1.0) -> None:
        """Move a shard to ``fid``.  Acquiring the shard lock first
        puts the flip BETWEEN rounds of the previous owner (the common
        case: the stall just ended); when the owner stays wedged past
        ``timeout`` the flip happens anyway and the per-append owner
        check fences its leftover writes instead."""
        lock = self.lock_of(shard)
        got = lock.acquire(timeout=timeout)
        try:
            with self._meta:
                self._owners[int(shard)] = int(fid)
        finally:
            if got:
                lock.release()


class IngestPump:
    """One launcher-resident frontend pump: scans its owned request
    shards (``serve/req/<s>/*`` — the listing the HTTP surface
    deliberately lacks) and appends each submission to the per-shard
    ingest log ``serve/log/<s>/<n>`` the serving leaders drain.

    Ordering within one scan round is by request id — arrival order
    inside a round is not observable from a dict snapshot, and a
    deterministic tiebreak beats a racy one.  Arrival wall time is
    stamped here (the launcher's clock), which is what ttft is measured
    against.

    Standalone construction (``IngestPump(server)``) is the F=1 front
    door minus supervision: one pump owning shard 0 and the GC duties —
    the shape every pre-16 call site expects.  Under a
    :class:`FrontDoor` each pump owns its own shard set (``gc=False``;
    the door's GC pump sweeps), heartbeats every round, and can ADOPT a
    dead sibling's shards mid-stream: adoption recovers the shard's
    next sequence number from the surviving log keys and dedupes
    against already-logged rids, so the crash window between a dead
    pump's log-append and req-discard can never double-ingest."""

    def __init__(self, server, interval: float = 0.02,
                 out_ttl_secs: Optional[float] = None, *,
                 fid: int = 0, frontends: int = 1,
                 shards: Optional[Sequence[int]] = None,
                 gc: bool = True,
                 fence: Optional[_ShardFence] = None):
        from ..utils import env as envmod  # noqa: PLC0415

        self._server = server
        # Shard-ownership fence (FrontDoor-managed pumps only): a
        # standalone pump has no sibling to race, so None skips the
        # locking entirely.
        self._fence = fence
        self._kv = KVStoreClient(f"127.0.0.1:{server.port}",
                                 server.secret)
        self.fid = int(fid)
        self.frontends = max(int(frontends), 1)
        self.interval = max(float(interval), 0.005)
        # Finished-output retention: a result doc whose log index fell
        # below its shard's compaction watermark is kept this long for
        # late client polls, then GC'd (see _gc_finished_outputs).
        self.out_ttl_secs = (
            float(out_ttl_secs) if out_ttl_secs is not None
            else envmod.env_float(envmod.SERVE_OUT_TTL,
                                  envmod.DEFAULT_SERVE_OUT_TTL)
        )
        self._lock = threading.Lock()
        self._shards: List[int] = (
            sorted(int(s) for s in shards) if shards is not None
            else [self.fid]
        )
        self._next: Dict[int, int] = {}        # shard -> next log index
        self._known: Dict[int, Set[str]] = {}  # shard -> logged rids
        self.ingested_by_shard: Dict[int, int] = {}
        self.beats = 0
        self._gc_enabled = bool(gc)
        self._done_seen: dict = {}  # out key -> monotonic first-seen-done
        # The finished-output GC unpickles every live out doc, so it
        # runs on its own ~1s cadence, not the 20ms ingest tick (TTL
        # granularity is hundreds of seconds; millisecond precision
        # would buy 50x the deserialization cost and nothing else).
        self._gc_every = min(1.0, max(self.out_ttl_secs / 4, 0.01))
        self._next_gc = 0.0
        self._stop = threading.Event()
        self._stopped = False   # deliberate stop() vs abrupt death
        self._killed = False
        self._thread: Optional[threading.Thread] = None

    @property
    def ingested(self) -> int:
        return sum(self.ingested_by_shard.values())

    @property
    def shards(self) -> List[int]:
        with self._lock:
            return list(self._shards)

    def adopt(self, shards: Sequence[int]) -> None:
        """Take ownership of a dead sibling's shards (thread-safe; the
        pump picks them up at its next round)."""
        with self._lock:
            for s in shards:
                if int(s) not in self._shards:
                    self._shards.append(int(s))
            self._shards.sort()

    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # ------------------------------------------------------------ ingest

    def _adopt_state(self, shard: int) -> None:
        """Recover a shard's append cursor + dedup set from the store:
        next index = max surviving log key + 1 (floored at the shard's
        watermark), known rids = the uncompacted entries'.  Run on
        first ownership AND on takeover — a fresh shard trivially
        yields (watermark, empty)."""
        nxt = 0
        raw = self._server.scan(WATERMARK_PREFIX + str(shard))
        try:
            nxt = int(raw[WATERMARK_PREFIX + str(shard)].decode())
        except (KeyError, ValueError):
            pass
        known: Set[str] = set()
        for key, blob in self._server.scan(
                f"{LOG_PREFIX}{shard}/").items():
            try:
                m = int(key.rsplit("/", 1)[1])
            except ValueError:
                continue
            nxt = max(nxt, m + 1)
            try:
                known.add(pickle.loads(blob)["rid"])
            except Exception:
                continue
        self._next[shard] = nxt
        self._known[shard] = known

    def round(self) -> int:
        """Move every pending submission on the owned shards into their
        logs; returns how many.  Also publishes this frontend's
        heartbeat and (when this pump owns the GC duty) collects
        dead-epoch serving scopes and compacted finished outputs."""
        from ..testing.faults import maybe_fail  # noqa: PLC0415

        # Deterministic chaos: the frontend analog of worker_exit —
        # an advisory action the supervisor must notice via the stale
        # heartbeat, not a cooperative shutdown.  step = THIS pump's
        # 1-based beat counter (the shared per-point counter would
        # interleave nondeterministically across F pumps).  The GC
        # pump (fid < 0) is exempt: it publishes no heartbeat, so an
        # unfiltered frontend_exit spec would kill it silently and GC
        # would stop for the rest of the job — chaos targets the
        # FRONTEND pumps, whose death the supervisor can detect.
        if self.fid >= 0 and maybe_fail(
                "frontend_beat", step=self.beats + 1,
                rank=self.fid) == "frontend_exit":
            raise _FrontendKilled(f"frontend {self.fid}")
        if self._gc_enabled:
            self._gc_stale_epochs()
            self._gc_finished_outputs()
        moved = 0
        for shard in self.shards:
            if self._fence is None:
                if shard not in self._next:
                    self._adopt_state(shard)
                moved += self._pump_shard(shard)
                continue
            # Fenced path (FrontDoor pumps): the shard lock serializes
            # this round against a live-but-slow previous owner, and
            # the ownership check under it aborts a pump that lost the
            # shard to a takeover — the zero-drop/zero-dup claim must
            # hold even when the stale heartbeat was a false positive.
            lock = self._fence.lock_of(shard)
            if not lock.acquire(blocking=False):
                # The previous owner is still mid-round (stalled): skip
                # this tick rather than wedge behind it; the shard is
                # retried next round.
                continue
            try:
                if self._fence.owner_of(shard) != self.fid:
                    continue  # lost the shard; never append
                if shard not in self._next:
                    self._adopt_state(shard)
                moved += self._pump_shard(shard)
            finally:
                lock.release()
        self.beats += 1
        if self.fid >= 0:
            self._kv.put(SCOPE, f"{HEARTBEAT_PREFIX}{self.fid}",
                         str(self.beats).encode())
        return moved

    def _pump_shard(self, shard: int) -> int:
        pending = self._server.scan(f"{REQ_PREFIX}{shard}/")
        moved = 0
        known = self._known.setdefault(shard, set())
        for key in sorted(pending):
            if self._fence is not None \
                    and self._fence.owner_of(shard) != self.fid:
                # Fenced off mid-round: the takeover declared this pump
                # dead while it was wedged past the transfer timeout.
                # Stop appending immediately — the adopter re-derives
                # the cursor and dedup set under the shard lock after
                # this round releases it, so everything appended so far
                # is seen and nothing is appended twice.
                break
            try:
                doc = pickle.loads(pending[key])
                rid = doc["rid"]
            except Exception:
                LOG.warning("dropping malformed submission %s", key)
                self._server.discard([key])
                continue
            if rid in known:
                # Already logged by the dead previous owner (it crashed
                # between log-append and req-discard): finish its
                # discard, never double-append.
                self._server.discard([key])
                continue
            n = self._next.setdefault(shard, 0)
            doc["arrival"] = time.time()
            doc["shard"] = shard
            doc["n"] = n
            # The total order every consumer derives: per-shard
            # sequence interleaved over the shard count.
            doc["gkey"] = n * self.frontends + shard
            self._kv.put(SCOPE, f"log/{shard}/{n}", pickle.dumps(doc))
            self._next[shard] = n + 1
            known.add(rid)
            if len(known) > 4096:
                # Bound the dedup set: re-derive it from the store (the
                # compacted prefix left the replay set, so its rids can
                # leave the dedup set too).
                self._adopt_state(shard)
            moved += 1
            self.ingested_by_shard[shard] = (
                self.ingested_by_shard.get(shard, 0) + 1
            )
            self._server.discard([key])
            # Launcher-side spans: submit -> ingest (client clock to
            # launcher clock — one host in practice) and the log
            # append itself.  The deterministic sampling verdict is the
            # SAME one every serving rank reaches for this rid.
            if obs_trace.enabled() and obs_trace.sampled(rid):
                submit_t = float(doc.get("submit_t") or doc["arrival"])
                obs_trace.add_span(rid, "ingest",
                                   min(submit_t, doc["arrival"]),
                                   doc["arrival"], n=doc["gkey"])
                obs_trace.add_span(rid, "log_append", doc["arrival"],
                                   time.time(), n=doc["gkey"])
            LOG.debug("ingested request %s as log/%d/%d", rid, shard, n)
        return moved

    # ---------------------------------------------------------------- gc

    def _gc_stale_epochs(self) -> None:
        """Drop schedule/recovery keys from epochs older than the
        current rendezvous epoch.  The leader's in-band GC only trims
        its OWN epoch's trailing window; every world break would
        otherwise permanently leak the dead epoch's remaining sched
        pickles and recovery doc — unbounded launcher memory on a
        long-lived fleet with periodic rank churn.  Old-epoch keys are
        immutable and unreadable by design (survivors and respawns
        alike rebuild from the NEW epoch's recovery doc), so deleting
        them can never race a reader."""
        raw = self._server.scan("elastic/epoch")
        try:
            current = int(raw["elastic/epoch"])
        except (KeyError, ValueError):
            return  # no elastic world yet (or a non-elastic store)
        doomed = []
        for key in self._server.scan("serve_e"):
            scope = key.split("/", 1)[0]
            try:
                epoch = int(scope[len("serve_e"):])
            except ValueError:
                continue
            if epoch < current:
                doomed.append(key)
        if doomed:
            self._server.discard(doomed)
            LOG.debug("GC'd %d stale-epoch serving keys", len(doomed))

    def _watermarks(self) -> Dict[int, int]:
        marks: Dict[int, int] = {}
        for key, blob in self._server.scan(WATERMARK_PREFIX).items():
            try:
                marks[int(key.rsplit("/", 1)[1])] = int(blob.decode())
            except ValueError:
                continue
        return marks

    def _gc_finished_outputs(self) -> None:
        """Drop result docs of requests the leader's compaction
        watermarks already retired (their log keys are gone — recovery
        replay will never need them) once they have been done for
        ``out_ttl_secs``.  This is the second half of request-log
        compaction: without it ``serve/out/*`` still grows with total
        requests ever served even though ``serve/log/*`` no longer
        does.  The TTL exists for late pollers; a client that sleeps
        past it sees a result timeout, which docs/inference.md states
        as the honest trade."""
        if time.monotonic() < self._next_gc:
            return
        self._next_gc = time.monotonic() + self._gc_every
        marks = self._watermarks()
        if not marks:
            return  # no compaction yet
        # Orphan sweep: the leader publishes each shard's watermark
        # BEFORE deleting the retired log keys, so a crash between the
        # two leaves below-watermark entries nobody will ever read (the
        # recovery scan starts at the watermark).  The pump is the one
        # component that can list them.
        orphans = []
        for key in self._server.scan(LOG_PREFIX):
            try:
                _, shard_s, n_s = key.rsplit("/", 2)
                if int(n_s) < marks.get(int(shard_s), 0):
                    orphans.append(key)
            except ValueError:
                continue
        if orphans:
            self._server.discard(orphans)
            LOG.debug("GC'd %d below-watermark log orphans",
                      len(orphans))
        now = time.monotonic()
        doomed = []
        live = self._server.scan(SCOPE + "/out/")
        for key, blob in live.items():
            try:
                doc = pickle.loads(blob)
            except Exception:
                continue
            n = doc.get("n")
            shard = int(doc.get("shard") or 0)
            if not doc.get("done") or n is None \
                    or int(n) >= marks.get(shard, 0):
                continue
            first = self._done_seen.setdefault(key, now)
            if now - first >= self.out_ttl_secs:
                doomed.append(key)
        if doomed:
            self._server.discard(doomed)
            for key in doomed:
                self._done_seen.pop(key, None)
            LOG.debug("GC'd %d compacted result docs", len(doomed))
        # Tracking entries for keys something else already removed.
        for key in list(self._done_seen):
            if key not in live:
                self._done_seen.pop(key, None)

    # --------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop,
            name=f"hvdtpu_serve_ingest_{self.fid}", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.round()
            except _FrontendKilled as exc:
                LOG.warning("frontend pump died abruptly: %s", exc)
                return  # no drain — the supervisor must take over
            except Exception as exc:  # pragma: no cover - defensive
                LOG.warning("ingest round failed: %s", exc)

    def kill(self) -> None:
        """Abrupt, mid-stream death (chaos hook): the thread exits
        without the final drain and WITHOUT marking a deliberate stop,
        so the FrontDoor supervisor sees exactly what a crashed
        frontend looks like."""
        self._killed = True
        self._stop.set()

    def stop(self) -> None:
        self._stopped = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if self._killed:
            return
        try:
            self.round()  # drain what arrived before the stop
        except Exception:  # pragma: no cover - defensive
            pass


class FrontDoor:
    """The sharded, supervised front door: ``F`` frontend pumps (one
    per rid-hash shard), a GC pump, and a heartbeat supervisor that
    survives any one frontend's death.

    Lifecycle of a frontend death (:meth:`kill`, a crash, or the
    ``frontend_beat:action=frontend_exit`` chaos point):

    1. the supervisor notices the dead pump (thread down or heartbeat
       counter stale past ``heartbeat_timeout``; on the stale path it
       also joins the thread briefly — a stale beat may mean SLOW, not
       dead);
    2. its shards are ADOPTED by the lowest surviving frontend
       (deterministic) — ownership flips through the
       :class:`_ShardFence` first, so even a live-but-slow "corpse"
       cannot append concurrently with its adopter — which recovers
       each shard's append cursor from the surviving log keys and
       dedupes already-logged rids — no drop, no double-ingest; with
       no survivor (F=1) a replacement pump is spawned in place;
    3. the ownership doc (``serve/frontdoor``) is re-published under a
       bumped ``fd_epoch`` and a takeover event is queued;
    4. the elastic monitor polls :meth:`poll_takeover` and re-mints the
       serving world's rendezvous epoch — exactly the PR-13 resize
       machinery — so every in-flight request replays from the durable
       log, bitwise on course.

    Clients never re-route: the rid hash names the SHARD, and shards
    are immortal — only their owning pump changes."""

    def __init__(self, server, frontends: int = 1,
                 interval: float = 0.02,
                 out_ttl_secs: Optional[float] = None,
                 heartbeat_timeout: float = 2.0):
        self._server = server
        self._kv = KVStoreClient(f"127.0.0.1:{server.port}",
                                 server.secret)
        self.frontends = max(int(frontends), 1)
        self.interval = max(float(interval), 0.005)
        self.heartbeat_timeout = float(heartbeat_timeout)
        self.owners: Dict[int, int] = {s: s
                                       for s in range(self.frontends)}
        # The ownership fence every pump writes through: a takeover
        # flips it BEFORE the adopter picks the shards up, so a
        # false-positive death (live-but-slow pump) can never append
        # concurrently with its adopter (_ShardFence).
        self._fence = _ShardFence(self.owners)
        self._pumps: Dict[int, IngestPump] = {
            fid: IngestPump(server, interval, out_ttl_secs, fid=fid,
                            frontends=self.frontends, gc=False,
                            fence=self._fence)
            for fid in range(self.frontends)
        }
        # GC rides its own pump (no shards, no heartbeat): the duty
        # must survive any frontend's death, so it cannot live on one.
        # It is exempt from the frontend_exit chaos point (round()) and
        # supervised by thread liveness instead (_check_pumps respawns
        # it) — "GC must survive any frontend's death" includes its own.
        self._gc_pump = IngestPump(server, max(interval * 5, 0.05),
                                   out_ttl_secs, fid=-1,
                                   frontends=self.frontends,
                                   shards=(), gc=True)
        self.fd_epoch = 0
        self.takeovers = 0
        self._events: List[dict] = []
        self._lock = threading.Lock()
        self._beat_seen: Dict[int, tuple] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._publish_doc()
        self._publish_gauges()

    # ------------------------------------------------------------- state

    def _publish_doc(self) -> None:
        # Snapshot under the lock, put outside it: the supervisor
        # mutates owners/fd_epoch under self._lock, and the KV put is
        # network I/O that must not ride inside the critical section.
        with self._lock:
            doc = {
                "frontends": self.frontends,
                "owners": dict(self.owners),
                "fd_epoch": self.fd_epoch,
            }
        self._kv.put(SCOPE, FRONTDOOR_KEY, pickle.dumps(doc))

    def _publish_gauges(self) -> None:
        from ..obs import get_registry  # noqa: PLC0415

        reg = get_registry()
        reg.gauge("serve.frontend.count").set(self.frontends)
        reg.gauge("serve.frontend.alive").set(
            sum(1 for p in self._pumps.values()
                if p.alive() or p._thread is None and not p._killed)
        )

    @property
    def ingested(self) -> int:
        return (sum(p.ingested for p in self._pumps.values())
                + self._gc_pump.ingested)

    def stats(self) -> dict:
        """Front-door provenance for bench records and tests:
        per-shard ingest counters, ownership, takeover history."""
        by_shard: Dict[int, int] = {}
        for p in self._pumps.values():
            for s, c in p.ingested_by_shard.items():
                by_shard[s] = by_shard.get(s, 0) + c
        # stats() runs on bench/test/metrics threads while the
        # supervisor mutates this state under self._lock mid-takeover:
        # iterating self.owners bare can observe a dict resize, and a
        # bare fd_epoch/takeovers pair can be torn across a takeover.
        with self._lock:
            owners = {int(k): int(v) for k, v in self.owners.items()}
            fd_epoch = self.fd_epoch
            takeovers = self.takeovers
        return {
            "frontends": self.frontends,
            "owners": owners,
            "fd_epoch": fd_epoch,
            "takeovers": takeovers,
            "ingested_by_shard": {int(s): by_shard[s]
                                  for s in sorted(by_shard)},
        }

    def prometheus(self) -> str:
        """Launcher-local ``serve.frontend.*`` series for the live
        plane's /metrics exposition (the same add_render lane the
        autoscale controller uses — these series exist only on the
        launcher, so worker snapshots never carry them)."""
        s = self.stats()
        lines = [
            f"hvdtpu_serve_frontend_count {s['frontends']}",
            f"hvdtpu_serve_frontend_takeovers {s['takeovers']}",
            f"hvdtpu_serve_frontend_fd_epoch {s['fd_epoch']}",
        ]
        for fid in sorted(self._pumps):
            up = 1 if self._pumps[fid].alive() else 0
            lines.append(
                f'hvdtpu_serve_frontend_up{{fid="{fid}"}} {up}')
        for shard, count in s["ingested_by_shard"].items():
            owner = s["owners"].get(shard, -1)
            lines.append(
                f'hvdtpu_serve_frontend_ingested'
                f'{{shard="{shard}",owner="{owner}"}} {count}')
        return "\n".join(lines) + "\n"

    def poll_takeover(self) -> List[dict]:
        """Drain queued takeover events (``{"fid", "owner", "shards"}``)
        — the elastic monitor consumes these and re-mints the serving
        epoch, one mint per event."""
        with self._lock:
            events, self._events = self._events, []
            return events

    # --------------------------------------------------------- lifecycle

    def start(self) -> None:
        for pump in self._pumps.values():
            pump.start()
        self._gc_pump.start()
        self._thread = threading.Thread(
            target=self._supervise, name="hvdtpu_serve_frontdoor",
            daemon=True,
        )
        self._thread.start()

    def kill(self, fid: int) -> None:
        """Chaos hook: abruptly kill frontend ``fid`` mid-stream (no
        drain, no handoff) — the supervisor must detect it and the
        surviving frontends must strand nothing."""
        self._pumps[int(fid)].kill()

    def _supervise(self) -> None:
        tick = max(self.interval, 0.02)
        while not self._stop.wait(tick):
            try:
                self._check_pumps()
            except Exception as exc:  # pragma: no cover - defensive
                LOG.warning("frontdoor supervisor tick failed: %s", exc)

    def _check_pumps(self) -> None:
        now = time.monotonic()
        dead: List[int] = []
        for fid, pump in sorted(self._pumps.items()):
            if pump._stopped:
                continue
            if not pump.alive():
                dead.append(fid)
                continue
            seen = self._beat_seen.get(fid)
            if seen is None or seen[0] != pump.beats:
                self._beat_seen[fid] = (pump.beats, now)
            elif now - seen[1] > self.heartbeat_timeout:
                LOG.warning(
                    "frontend %d heartbeat stale > %.1fs; declaring "
                    "it dead", fid, self.heartbeat_timeout,
                )
                pump.kill()
                # Bounded join: kill() only raises the stop flag, so a
                # LIVE-but-slow pump may still be mid-round.  Most
                # stalls end quickly once noticed — joining here makes
                # the takeover race-free in the common case; a pump
                # still wedged past the bound is fenced off by
                # _ShardFence instead (ownership flips before the
                # adopter appends, and the zombie's leftover writes
                # abort on the owner check).
                if pump._thread is not None:
                    pump._thread.join(timeout=0.5)
                dead.append(fid)
        for fid in dead:
            self._takeover(fid)
        if dead:
            self._publish_gauges()
        # The GC pump has no heartbeat (fid=-1 publishes none), so it
        # is supervised by thread liveness: if it dies — it is exempt
        # from the chaos point, but defense-in-depth against a real
        # crash — respawn it, or stale-epoch and finished-output GC
        # silently stops for the rest of the job.
        gc = self._gc_pump
        if gc._thread is not None and not gc._stopped and not gc.alive():
            LOG.warning("GC pump died; respawning it")
            fresh = IngestPump(self._server, gc.interval,
                               gc.out_ttl_secs, fid=-1,
                               frontends=self.frontends, shards=(),
                               gc=True)
            # Carry the done-TTL tracking over so already-finished
            # outputs keep their original GC deadline.
            fresh._done_seen = dict(gc._done_seen)
            self._gc_pump = fresh
            fresh.start()

    def _takeover(self, fid: int) -> None:
        from ..obs import get_registry  # noqa: PLC0415

        pump = self._pumps[fid]
        shards = pump.shards
        self._beat_seen.pop(fid, None)
        survivors = [f for f, p in sorted(self._pumps.items())
                     if f != fid and p.alive() and not p._stopped]
        if survivors:
            owner = survivors[0]
            # Fence FIRST, adopt second: each shard's ownership flips
            # under its lock (waiting out an in-flight round, bounded)
            # before the survivor can append to it, so a
            # false-positive death — the pump was alive but slow —
            # cannot double-ingest against its adopter.
            for s in shards:
                self._fence.transfer(s, owner)
            self._pumps[owner].adopt(shards)
            # Retire the dead pump: its shards are re-owned, so the
            # supervisor must not re-fire this takeover every tick.
            pump._stopped = True
        else:
            # No survivor (F=1, or everyone died at once): spawn a
            # replacement pump in place — the supervisor is the actor
            # of last resort.  Ownership stays with this fid; the
            # per-shard fence locks still serialize the replacement
            # against the corpse's possible in-flight last round.
            owner = fid
            fresh = IngestPump(
                self._server, self.interval, pump.out_ttl_secs,
                fid=fid, frontends=self.frontends, shards=shards,
                gc=False, fence=self._fence,
            )
            # The replacement inherits the corpse's ingest accounting:
            # counters survive a respawn the way a rank's completed
            # work survives an epoch — stats()/bench records must not
            # read a death as traffic vanishing.
            fresh.ingested_by_shard = dict(pump.ingested_by_shard)
            self._pumps[fid] = fresh
            fresh.start()
        with self._lock:
            for s in shards:
                self.owners[s] = owner
            self.fd_epoch += 1
            self.takeovers += 1
            fd_epoch = self.fd_epoch
            self._events.append({"fid": fid, "owner": owner,
                                 "shards": list(shards)})
        self._publish_doc()
        reg = get_registry()
        reg.counter("serve.frontend.takeovers").inc()
        LOG.warning("frontend %d dead; shards %s taken over by "
                    "frontend %d (fd_epoch %d)", fid, shards, owner,
                    fd_epoch)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        for pump in self._pumps.values():
            try:
                pump.stop()
            except Exception:  # pragma: no cover - defensive
                pass
        try:
            self._gc_pump.stop()
        except Exception:  # pragma: no cover - defensive
            pass
