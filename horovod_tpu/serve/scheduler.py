"""Continuous-batching scheduler core: iteration-level admit/evict over
a fixed pool of batch slots.

Orca-style scheduling (Yu et al., OSDI '22) reduced to its SPMD
essentials: between decode steps, queued requests are admitted into
free slots (FCFS, lowest-numbered slot first) and finished sequences
(EOS or token budget) are evicted immediately, their slots recycled —
so ONE compiled ``decode_step`` shape serves a churning request mix
without recompilation.

This module is deliberately a **pure state machine**: no jax, no
networking, no clocks, no rank awareness.  Every rank of the serving
world runs its own instance and feeds it the SAME inputs in the SAME
order (new requests from the rank-0 schedule broadcast, token
observations from the deterministic decode math) — so every rank
derives an identical admit/evict schedule.  That is the serving plane's
HVD001 invariant: a rank-divergent schedule here is exactly the
divergent-collective deadlock class hvdtpu-lint checks for on the
training side, which is why nothing in this file may consult
``hvd.rank()``, a wall clock, or an unordered dict iteration.  Unit
tests drive the decision table directly (tests/test_serve.py), and the
multi-rank determinism test replays one trace through N instances.

Since PR 12 the contract is also *statically checked*: hvdtpu-lint's
HVD012 registers this module (and anything marked ``# hvdtpu:
deterministic``) as a determinism contract and rejects any clock /
``random`` / hash-order / rank read in its call tree at lint time —
the invariant holds on every diff, not just when the replay test runs.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Sequence, Tuple

__all__ = ["Request", "ActiveSlot", "Admission", "Eviction",
           "SlotScheduler"]


@dataclass(frozen=True)
class Request:
    """One generation request.  ``arrival`` is informational (latency
    accounting) — scheduling NEVER reads it; order of arrival is fixed
    by the ingest log's sequence numbers, not by clocks.

    ``temperature``/``top_k`` select per-request sampling
    (serve/sampling.py): pure DATA here — the scheduler never reads
    them either; the engine keys the PRNG stream on (rid, emission
    index, serve seed), so they stay rank-deterministic."""

    rid: str
    prompt: Tuple[int, ...]
    max_new_tokens: int = 16
    eos_id: Optional[int] = None
    arrival: float = 0.0
    temperature: float = 0.0
    top_k: int = 0

    def __post_init__(self):
        if not self.prompt:
            raise ValueError(f"request {self.rid!r} has an empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(
                f"request {self.rid!r}: max_new_tokens must be >= 1"
            )
        if self.temperature < 0:
            raise ValueError(
                f"request {self.rid!r}: temperature must be >= 0"
            )


@dataclass
class ActiveSlot:
    """One slot's live request plus its emission progress."""

    req: Request
    slot: int
    emitted: List[int] = field(default_factory=list)
    # Serving-step index the admission happened at (scheduling never
    # reads it; the frontend publishes it so tests and operators can
    # SEE continuous admission — requests entering mid-stream).
    admitted_step: int = 0
    # How many of `emitted` were replayed from a dead world's streams
    # rather than generated here (scheduling never reads it; the trace
    # plane uses it to mark the replayed prefix on a request's
    # waterfall lane, and snapshot() exposes it for introspection).
    resumed: int = 0

    @property
    def done(self) -> bool:
        if len(self.emitted) >= self.req.max_new_tokens:
            return True
        return bool(
            self.emitted
            and self.req.eos_id is not None
            and self.emitted[-1] == self.req.eos_id
        )


@dataclass(frozen=True)
class Admission:
    slot: int
    req: Request
    resume: Tuple[int, ...]  # already-emitted tokens (elastic replay)


@dataclass(frozen=True)
class Eviction:
    slot: int
    rid: str
    reason: str  # "eos" | "budget"
    tokens: Tuple[int, ...]
    admitted_step: int = 0
    resumed: int = 0  # replayed-prefix length (see ActiveSlot.resumed)


class SlotScheduler:
    """The per-rank scheduling state machine.

    Lifecycle per decode step::

        sched.enqueue(req)            # rank-0-broadcast new arrivals
        admits = sched.admit()        # queued -> free slots, FCFS
        ... engine prefills each admission, decodes active slots ...
        sched.record(slot, token)     # one emitted token per live slot
        evicts = sched.evict_finished()

    Deterministic by construction: the queue is FCFS, free slots are
    handed out in ascending slot order, and eviction order is ascending
    slot order.
    """

    def __init__(self, num_slots: int):
        if num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        self.num_slots = num_slots
        self.queue: Deque[Tuple[Request, Tuple[int, ...]]] = deque()
        self.active: Dict[int, ActiveSlot] = {}

    # ------------------------------------------------------------ intake

    def enqueue(self, req: Request,
                resume: Sequence[int] = ()) -> None:
        """Append to the FCFS queue.  ``resume``: tokens the request
        already emitted before a world break — the admission carries
        them so the engine re-prefills ``prompt + resume`` instead of
        restarting the generation (zero dropped requests on respawn).
        A request whose resume already satisfies its stop condition
        must not be re-admitted; the caller detects that via
        :meth:`ActiveSlot.done` semantics replicated here."""
        self.queue.append((req, tuple(resume)))

    # --------------------------------------------------------- admission

    def free_slots(self) -> List[int]:
        return [s for s in range(self.num_slots) if s not in self.active]

    # hvdtpu: deterministic
    def admit(self, step: int = 0, can_admit=None) -> List[Admission]:
        """Admit queued requests into free slots: FCFS, lowest slot
        first.  Mutates the schedule and returns the admissions in
        order.  ``step`` is recorded on the slot for observability
        only — it never influences the decision.

        ``can_admit(req, resume) -> bool`` is the CAPACITY gate (paged
        KV: are there free pages for this request's worst case?).  FCFS
        is strict: when the HEAD of the queue does not fit, admission
        stops — skipping ahead would let a stream of small requests
        starve a big one, and (worse) make the admit order depend on
        capacity timing in a way that is harder to reason about across
        elastic replays.  The gate MUST be a deterministic function of
        the schedule so far (the engine's page accounting is), or ranks
        diverge — the HVD001 invariant extends through this callback.
        """
        out: List[Admission] = []
        for slot in self.free_slots():
            if not self.queue:
                break
            req, resume = self.queue[0]
            if can_admit is not None and not can_admit(req, resume):
                break
            self.queue.popleft()
            self.active[slot] = ActiveSlot(req=req, slot=slot,
                                           emitted=list(resume),
                                           admitted_step=step,
                                           resumed=len(resume))
            out.append(Admission(slot=slot, req=req, resume=resume))
        return out

    # ---------------------------------------------------------- progress

    def record(self, slot: int, token: int) -> None:
        """Record one emitted token for a live slot."""
        act = self.active.get(slot)
        if act is None:
            raise KeyError(f"slot {slot} has no active request")
        if act.done:
            raise ValueError(
                f"slot {slot} ({act.req.rid}) is finished; the engine "
                f"must not emit past the stop condition"
            )
        act.emitted.append(int(token))

    # hvdtpu: deterministic
    def evict_finished(self) -> List[Eviction]:
        """Evict every finished slot (ascending order), freeing it for
        the next step's admissions."""
        out: List[Eviction] = []
        for slot in sorted(self.active):
            act = self.active[slot]
            if not act.done:
                continue
            reason = (
                "eos"
                if act.req.eos_id is not None
                and act.emitted
                and act.emitted[-1] == act.req.eos_id
                else "budget"
            )
            out.append(Eviction(slot=slot, rid=act.req.rid,
                                reason=reason,
                                tokens=tuple(act.emitted),
                                admitted_step=act.admitted_step,
                                resumed=act.resumed))
            del self.active[slot]
        return out

    # ------------------------------------------------------------- views

    @property
    def queue_depth(self) -> int:
        return len(self.queue)

    @property
    def active_slots(self) -> int:
        return len(self.active)

    def idle(self) -> bool:
        return not self.queue and not self.active

    def snapshot(self) -> List[dict]:
        """In-flight then queued requests as plain dicts (ascending
        slot order, then queue order) — introspection/debugging view.
        NOTE: elastic recovery does NOT flow through this method; the
        authoritative replay is service._build_recovery(), which joins
        the durable KV ingest log with the published token streams (a
        respawned leader has no in-memory scheduler to snapshot)."""
        return [
            {
                "rid": act.req.rid,
                "prompt": list(act.req.prompt),
                "max_new_tokens": act.req.max_new_tokens,
                "eos_id": act.req.eos_id,
                "arrival": act.req.arrival,
                "emitted": list(act.emitted),
                "resumed": act.resumed,
            }
            for _, act in sorted(self.active.items())
        ] + [
            {
                "rid": req.rid,
                "prompt": list(req.prompt),
                "max_new_tokens": req.max_new_tokens,
                "eos_id": req.eos_id,
                "arrival": req.arrival,
                "emitted": list(resume),
            }
            for req, resume in self.queue
        ]
