"""Continuous-batching scheduler core: iteration-level admit/evict over
a fixed pool of batch slots.

Orca-style scheduling (Yu et al., OSDI '22) reduced to its SPMD
essentials: between decode steps, queued requests are admitted into
free slots (FCFS, lowest-numbered slot first) and finished sequences
(EOS or token budget) are evicted immediately, their slots recycled —
so ONE compiled ``decode_step`` shape serves a churning request mix
without recompilation.

This module is deliberately a **pure state machine**: no jax, no
networking, no clocks, no rank awareness.  Every rank of the serving
world runs its own instance and feeds it the SAME inputs in the SAME
order (new requests from the rank-0 schedule broadcast, token
observations from the deterministic decode math) — so every rank
derives an identical admit/evict schedule.  That is the serving plane's
HVD001 invariant: a rank-divergent schedule here is exactly the
divergent-collective deadlock class hvdtpu-lint checks for on the
training side, which is why nothing in this file may consult
``hvd.rank()``, a wall clock, or an unordered dict iteration.  Unit
tests drive the decision table directly (tests/test_serve.py), and the
multi-rank determinism test replays one trace through N instances.

Since PR 12 the contract is also *statically checked*: hvdtpu-lint's
HVD012 registers this module (and anything marked ``# hvdtpu:
deterministic``) as a determinism contract and rejects any clock /
``random`` / hash-order / rank read in its call tree at lint time —
the invariant holds on every diff, not just when the replay test runs.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Sequence, Set, Tuple

__all__ = ["Request", "ActiveSlot", "Admission", "Eviction",
           "SlotScheduler", "TenantQoS", "SLO_CLASSES"]

# The SLO vocabulary and its default admission weights: an
# ``interactive`` head outranks a ``standard`` head outranks a
# ``batch`` head, 8:4:1.  Pure data — the frontend validates the class
# names (validate_request), the scheduler only weighs them.
SLO_CLASSES: Tuple[str, ...] = ("interactive", "standard", "batch")
_DEFAULT_WEIGHTS: Dict[str, int] = {
    "interactive": 8, "standard": 4, "batch": 1,
}


@dataclass(frozen=True)
class Request:
    """One generation request.  ``arrival`` is informational (latency
    accounting) — scheduling NEVER reads it; order of arrival is fixed
    by the ingest log's sequence numbers, not by clocks.

    ``temperature``/``top_k`` select per-request sampling
    (serve/sampling.py): pure DATA here — the scheduler never reads
    them either; the engine keys the PRNG stream on (rid, emission
    index, serve seed), so they stay rank-deterministic."""

    rid: str
    prompt: Tuple[int, ...]
    max_new_tokens: int = 16
    eos_id: Optional[int] = None
    arrival: float = 0.0
    temperature: float = 0.0
    top_k: int = 0
    # Multi-tenant QoS (pure data like temperature/top_k): ``tenant``
    # names the budget bucket, ``slo`` the admission weight class.
    # With qos=None the scheduler never reads either — the
    # single-tenant path stays byte-identical FCFS.
    tenant: str = "default"
    slo: str = "standard"

    def __post_init__(self):
        if not self.prompt:
            raise ValueError(f"request {self.rid!r} has an empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(
                f"request {self.rid!r}: max_new_tokens must be >= 1"
            )
        if self.temperature < 0:
            raise ValueError(
                f"request {self.rid!r}: temperature must be >= 0"
            )
        if not self.tenant or not isinstance(self.tenant, str):
            raise ValueError(
                f"request {self.rid!r}: tenant must be a non-empty str"
            )

    @property
    def cost(self) -> int:
        """Admission cost in tokens — the same worst case the paged
        pool commits (prompt + full budget), so one number drives both
        capacity and tenant budgets."""
        return len(self.prompt) + self.max_new_tokens


@dataclass
class ActiveSlot:
    """One slot's live request plus its emission progress."""

    req: Request
    slot: int
    emitted: List[int] = field(default_factory=list)
    # Serving-step index the admission happened at (scheduling never
    # reads it; the frontend publishes it so tests and operators can
    # SEE continuous admission — requests entering mid-stream).
    admitted_step: int = 0
    # How many of `emitted` were replayed from a dead world's streams
    # rather than generated here (scheduling never reads it; the trace
    # plane uses it to mark the replayed prefix on a request's
    # waterfall lane, and snapshot() exposes it for introspection).
    resumed: int = 0

    @property
    def done(self) -> bool:
        if len(self.emitted) >= self.req.max_new_tokens:
            return True
        return bool(
            self.emitted
            and self.req.eos_id is not None
            and self.emitted[-1] == self.req.eos_id
        )


@dataclass(frozen=True)
class Admission:
    slot: int
    req: Request
    resume: Tuple[int, ...]  # already-emitted tokens (elastic replay)


@dataclass(frozen=True)
class Eviction:
    slot: int
    rid: str
    reason: str  # "eos" | "budget"
    tokens: Tuple[int, ...]
    admitted_step: int = 0
    resumed: int = 0  # replayed-prefix length (see ActiveSlot.resumed)


class TenantQoS:
    """Deterministic weighted-fair admission policy (ISSUE 16).

    Pure configuration + arithmetic — every rank constructs an
    identical instance from the job spec and the scheduler derives the
    identical pick from it, so the HVD001/HVD012 determinism contract
    extends through multi-tenant admission unchanged.  Three rules,
    applied to the per-tenant FIFO heads of the queue:

    1. **Budgets** — with ``budget_tokens`` set, a tenant whose spend
       this window (admitted ``prompt + max_new_tokens``) would exceed
       the budget is *throttled*: skipped, counted, resumed at the
       next window.  Windows are serving-step-indexed
       (``step // window_steps``), never wall clock — every rank
       refills at the same broadcast step.
    2. **SLO preemption** — among un-throttled heads, the highest
       ``weights[slo]`` wins: an interactive head admits before a
       batch head that arrived earlier.
    3. **Weighted fairness** — within one weight class, the tenant
       with the lowest *virtual time* wins; each admission advances
       the winner's clock by ``cost / weight``, so long-run admitted
       tokens converge to the weight ratio.  Ties break on arrival
       (queue) order.

    Honest limit: a tenant arriving late starts at virtual time 0 and
    briefly wins its weight class until its clock catches up — the
    window is bounded by one backlog's worth of cost, and the trade
    (no global clock to maintain) keeps the policy a pure fold over
    the admission sequence.
    """

    def __init__(self, weights: Optional[Dict[str, int]] = None,
                 budget_tokens: Optional[int] = None,
                 window_steps: int = 64):
        self.weights = dict(_DEFAULT_WEIGHTS)
        if weights:
            self.weights.update({str(k): int(v)
                                 for k, v in sorted(weights.items())})
        if any(w < 1 for w in self.weights.values()):
            raise ValueError("slo weights must be >= 1")
        self.budget_tokens = (None if budget_tokens is None
                              else int(budget_tokens))
        if self.budget_tokens is not None and self.budget_tokens < 1:
            raise ValueError("budget_tokens must be >= 1")
        self.window_steps = max(int(window_steps), 1)

    @classmethod
    def from_spec(cls, cfg: Optional[dict]) -> Optional["TenantQoS"]:
        """Build from the job spec's ``tenants`` dict (None/{} = off).
        The spec travels to every rank identically (pickled func /
        forwarded env), which is what makes the policy rank-identical
        by construction."""
        if not cfg:
            return None
        return cls(weights=cfg.get("weights"),
                   budget_tokens=cfg.get("budget_tokens"),
                   window_steps=int(cfg.get("window_steps") or 64))

    def weight_of(self, slo: str) -> int:
        return self.weights.get(slo, 1)


class SlotScheduler:
    """The per-rank scheduling state machine.

    Lifecycle per decode step::

        sched.enqueue(req)            # rank-0-broadcast new arrivals
        admits = sched.admit()        # queued -> free slots, FCFS
        ... engine prefills each admission, decodes active slots ...
        sched.record(slot, token)     # one emitted token per live slot
        evicts = sched.evict_finished()

    Deterministic by construction: the queue is FCFS, free slots are
    handed out in ascending slot order, and eviction order is ascending
    slot order.
    """

    def __init__(self, num_slots: int,
                 qos: Optional[TenantQoS] = None):
        if num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        self.num_slots = num_slots
        self.queue: Deque[Tuple[Request, Tuple[int, ...]]] = deque()
        self.active: Dict[int, ActiveSlot] = {}
        # Tenant-aware admission (TenantQoS); None keeps the original
        # FCFS path byte-identical.  All the per-tenant state below is
        # a pure fold over (enqueue order, admit(step) calls) — no
        # clocks, no ranks, no unordered iteration (HVD012).
        self.qos = qos
        self.vtime: Dict[str, float] = {}     # weighted-fair clocks
        self.spent: Dict[str, int] = {}       # window token spend
        self.throttled: Dict[str, int] = {}   # cumulative throttles
        self.admitted_tokens: Dict[str, int] = {}  # cumulative cost
        self._window = -1

    # ------------------------------------------------------------ intake

    def enqueue(self, req: Request,
                resume: Sequence[int] = ()) -> None:
        """Append to the FCFS queue.  ``resume``: tokens the request
        already emitted before a world break — the admission carries
        them so the engine re-prefills ``prompt + resume`` instead of
        restarting the generation (zero dropped requests on respawn).
        A request whose resume already satisfies its stop condition
        must not be re-admitted; the caller detects that via
        :meth:`ActiveSlot.done` semantics replicated here."""
        self.queue.append((req, tuple(resume)))

    # --------------------------------------------------------- admission

    def free_slots(self) -> List[int]:
        return [s for s in range(self.num_slots) if s not in self.active]

    # hvdtpu: deterministic
    def admit(self, step: int = 0, can_admit=None) -> List[Admission]:
        """Admit queued requests into free slots: FCFS, lowest slot
        first.  Mutates the schedule and returns the admissions in
        order.  ``step`` is recorded on the slot for observability
        only — it never influences the decision.

        ``can_admit(req, resume) -> bool`` is the CAPACITY gate (paged
        KV: are there free pages for this request's worst case?).  FCFS
        is strict: when the HEAD of the queue does not fit, admission
        stops — skipping ahead would let a stream of small requests
        starve a big one, and (worse) make the admit order depend on
        capacity timing in a way that is harder to reason about across
        elastic replays.  The gate MUST be a deterministic function of
        the schedule so far (the engine's page accounting is), or ranks
        diverge — the HVD001 invariant extends through this callback.

        With a :class:`TenantQoS` policy the pick is the qos-chosen
        head (budget -> slo weight -> virtual time -> arrival) and
        admission is head-strict on THAT head: when the chosen head
        does not fit, admission stops — skipping past it would
        re-introduce exactly the capacity-timing dependence and
        big-request starvation strict FCFS exists to prevent.
        """
        out: List[Admission] = []
        if self.qos is None:
            for slot in self.free_slots():
                if not self.queue:
                    break
                req, resume = self.queue[0]
                if can_admit is not None and not can_admit(req, resume):
                    break
                self.queue.popleft()
                self.active[slot] = ActiveSlot(req=req, slot=slot,
                                               emitted=list(resume),
                                               admitted_step=step,
                                               resumed=len(resume))
                out.append(Admission(slot=slot, req=req, resume=resume))
            return out
        self._maybe_refill(step)
        throttled_this_call: Set[str] = set()
        for slot in self.free_slots():
            if not self.queue:
                break
            pick = self._pick(throttled_this_call)
            if pick is None:
                break  # every queued tenant is over budget this window
            req, resume = self.queue[pick]
            if can_admit is not None and not can_admit(req, resume):
                break
            del self.queue[pick]
            w = self.qos.weight_of(req.slo)
            self.vtime[req.tenant] = (
                self.vtime.get(req.tenant, 0.0) + req.cost / w
            )
            self.spent[req.tenant] = (
                self.spent.get(req.tenant, 0) + req.cost
            )
            self.admitted_tokens[req.tenant] = (
                self.admitted_tokens.get(req.tenant, 0) + req.cost
            )
            self.active[slot] = ActiveSlot(req=req, slot=slot,
                                           emitted=list(resume),
                                           admitted_step=step,
                                           resumed=len(resume))
            out.append(Admission(slot=slot, req=req, resume=resume))
        return out

    def _maybe_refill(self, step: int) -> None:
        """Step-indexed budget window: every rank calls admit() with
        the same broadcast step, so every rank refills at the same
        instant — the no-clocks budget refill."""
        if self.qos is None or self.qos.budget_tokens is None:
            return
        win = step // self.qos.window_steps
        if win != self._window:
            self._window = win
            self.spent = {}

    def _pick(self, throttled_this_call: Set[str]) -> Optional[int]:
        """Queue index of the next admission under the QoS rules, or
        None when every queued tenant is throttled.  One forward scan:
        each tenant's FIRST queued request is its head (per-tenant
        FIFO), heads compete on (budget, slo weight, virtual time,
        arrival order) — every input a pure function of the schedule
        so far."""
        assert self.qos is not None
        budget = self.qos.budget_tokens
        heads: Dict[str, int] = {}
        for idx, (req, _) in enumerate(self.queue):
            if req.tenant not in heads:
                heads[req.tenant] = idx
        best: Optional[Tuple[int, float, int]] = None
        best_idx: Optional[int] = None
        for tenant in sorted(heads):
            idx = heads[tenant]
            req = self.queue[idx][0]
            if budget is not None and \
                    self.spent.get(tenant, 0) + req.cost > budget:
                if tenant not in throttled_this_call:
                    throttled_this_call.add(tenant)
                    self.throttled[tenant] = (
                        self.throttled.get(tenant, 0) + 1
                    )
                continue
            key = (-self.qos.weight_of(req.slo),
                   self.vtime.get(tenant, 0.0), idx)
            if best is None or key < best:
                best, best_idx = key, idx
        return best_idx

    # ---------------------------------------------------------- progress

    def record(self, slot: int, token: int) -> None:
        """Record one emitted token for a live slot."""
        act = self.active.get(slot)
        if act is None:
            raise KeyError(f"slot {slot} has no active request")
        if act.done:
            raise ValueError(
                f"slot {slot} ({act.req.rid}) is finished; the engine "
                f"must not emit past the stop condition"
            )
        act.emitted.append(int(token))

    # hvdtpu: deterministic
    def evict_finished(self) -> List[Eviction]:
        """Evict every finished slot (ascending order), freeing it for
        the next step's admissions."""
        out: List[Eviction] = []
        for slot in sorted(self.active):
            act = self.active[slot]
            if not act.done:
                continue
            reason = (
                "eos"
                if act.req.eos_id is not None
                and act.emitted
                and act.emitted[-1] == act.req.eos_id
                else "budget"
            )
            out.append(Eviction(slot=slot, rid=act.req.rid,
                                reason=reason,
                                tokens=tuple(act.emitted),
                                admitted_step=act.admitted_step,
                                resumed=act.resumed))
            del self.active[slot]
        return out

    # ------------------------------------------------------------- views

    @property
    def queue_depth(self) -> int:
        return len(self.queue)

    @property
    def active_slots(self) -> int:
        return len(self.active)

    def idle(self) -> bool:
        return not self.queue and not self.active

    def tenant_depths(self) -> Dict[str, int]:
        """Queued requests per tenant (sorted tenant order) — the
        ``serve.tenant.queued`` gauges.  Observability only; admission
        never calls it."""
        depths: Dict[str, int] = {}
        for req, _ in self.queue:
            depths[req.tenant] = depths.get(req.tenant, 0) + 1
        return {t: depths[t] for t in sorted(depths)}

    def snapshot(self) -> List[dict]:
        """In-flight then queued requests as plain dicts (ascending
        slot order, then queue order) — introspection/debugging view.
        NOTE: elastic recovery does NOT flow through this method; the
        authoritative replay is service._build_recovery(), which joins
        the durable KV ingest log with the published token streams (a
        respawned leader has no in-memory scheduler to snapshot)."""
        return [
            {
                "rid": act.req.rid,
                "prompt": list(act.req.prompt),
                "max_new_tokens": act.req.max_new_tokens,
                "eos_id": act.req.eos_id,
                "arrival": act.req.arrival,
                "tenant": act.req.tenant,
                "slo": act.req.slo,
                "emitted": list(act.emitted),
                "resumed": act.resumed,
            }
            for _, act in sorted(self.active.items())
        ] + [
            {
                "rid": req.rid,
                "prompt": list(req.prompt),
                "max_new_tokens": req.max_new_tokens,
                "eos_id": req.eos_id,
                "arrival": req.arrival,
                "tenant": req.tenant,
                "slo": req.slo,
                "emitted": list(resume),
            }
            for req, resume in self.queue
        ]
