"""Replicated per-request PRNG sampling (ROADMAP item 1c): temperature
/ top-k token picks that are a PURE function of ``(request id,
emission index, serve seed)`` — so every rank of the serving world
derives the identical token, and an elastic replay that re-prefills
``prompt + resume`` continues the stream bit-exactly where the dead
world stopped.  This ends the slot engine's greedy-only loop.

Key discipline (the HVD001 invariant applied to randomness):

* ``request_key(seed, rid)`` folds a stable CRC-32 of the request id
  into ``PRNGKey(seed)`` — NOT Python's ``hash`` (PYTHONHASHSEED-
  dependent, the exact poison hvdtpu-lint HVD012 rejects) — giving
  each request its own stream root, identical on every rank.
* token ``i`` of a request is sampled with ``fold_in(root, i)`` where
  ``i`` is the request's EMISSION index (tokens emitted so far), not
  the serving step: two fleets that admit the same request at
  different steps — or a replay that resumes mid-stream — still draw
  the same keys.
* :func:`sample_token` is the ONE sampling math, used inside the slot
  engine's jitted step AND by the single-stream oracle tests, so
  "bitwise-equal to the oracle" is a property of shared code, not of
  two implementations agreeing.

``temperature == 0`` is greedy argmax (the key is ignored), so the
default path is byte-identical to the pre-sampling engine.
"""

from __future__ import annotations

import zlib

import jax
import jax.numpy as jnp

__all__ = ["request_key", "token_key", "sample_token", "KEY_SHAPE"]

# Raw key width: old-style jax PRNG keys are uint32[2]; the engine
# carries them as plain arrays so they cross the host/jit boundary as
# data, not as typed-key objects (version-tolerant).
KEY_SHAPE = (2,)


def request_key(seed: int, rid: str):
    """The request's PRNG stream root: ``fold_in(PRNGKey(seed),
    crc32(rid))``.  crc32 is stable across processes, platforms and
    PYTHONHASHSEED — the determinism contract's replacement for
    ``hash``."""
    rid_tag = zlib.crc32(rid.encode("utf-8")) & 0x7FFFFFFF
    return jax.random.fold_in(jax.random.PRNGKey(int(seed)), rid_tag)


def token_key(base, emission_index):
    """Key for the request's ``emission_index``-th generated token."""
    return jax.random.fold_in(base, emission_index)


def sample_token(logits, temperature, top_k, key):
    """One token from one row of logits — greedy when ``temperature <=
    0``, else top-k-truncated temperature sampling via the Gumbel-max
    trick (an argmax, like the greedy path, so the whole pick stays
    inside the compiled step).

    ``logits [vocab]`` fp32; ``temperature`` scalar f32; ``top_k``
    scalar i32 (0 = no truncation); ``key`` uint32[2].  Jit/vmap-safe:
    both branches are computed and selected with ``where`` (per-slot
    mixed greedy/sampled pools share one compiled step).
    """
    greedy = jnp.argmax(logits).astype(jnp.int32)
    safe_t = jnp.where(temperature > 0, temperature, 1.0)
    lt = logits.astype(jnp.float32) / safe_t
    # top-k truncation without dynamic shapes: positions below the
    # k-th largest logit are -inf.  top_k == 0 (or >= vocab) keeps all.
    vocab = logits.shape[-1]
    k_eff = jnp.clip(jnp.where(top_k > 0, top_k, vocab), 1, vocab)
    sorted_lt = jnp.sort(lt)[::-1]
    kth = sorted_lt[jnp.minimum(k_eff - 1, vocab - 1)]
    lt = jnp.where(lt < kth, -jnp.inf, lt)
    g = jax.random.gumbel(key, (vocab,), dtype=jnp.float32)
    sampled = jnp.argmax(lt + g).astype(jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy)
