"""Load-driven autoscaling for serving fleets: grow/shrink the elastic
world deliberately, not just on failure.

The serving plane (service.py) already aggregates the pressure signals
— ``serve.queue_depth`` and ``serve.ttft_ms`` stream to the launcher's
live plane every round — and the elastic launcher already knows how to
re-form a world around a membership change (re-minted rendezvous epoch
+ replay recovery).  This module closes the loop: a launcher-resident
controller watches those gauges and drives the SAME epoch machinery on
purpose, so a scale event is indistinguishable from a survived failure
— in-flight requests replay, zero are dropped (Ray's actor-pool
elasticity, specialized to SPMD serving).

Split on the same line as the scheduler: :class:`AutoscalePolicy` is a
**pure decision table** — no clocks read, no I/O, every input passed in
— so hysteresis, per-direction cooldowns, and the grow-failure backoff
are unit-testable as a function of (time, pressure) sequences.
:class:`AutoscaleController` is the launcher-side glue: it reads the
live plane's merged views, feeds the policy, and publishes the
``autoscale.*`` metrics; the launcher's monitor loop *executes*
decisions, because only it owns epoch minting and process spawn.

Decision rules (docs/inference.md has the operator's view):

* **grow** when ``queue_depth`` has stayed at/above ``scale_up_queue``
  (or ttft p50 above ``scale_up_ttft_ms``, when set) continuously for
  ``up_window_secs`` — a one-round spike never scales — and the up
  cooldown and any grow-failure backoff have expired and the world is
  below ``max_workers``.
* **shrink** when the fleet is fully drained (queue empty AND no active
  slot) continuously for ``scale_down_idle_secs`` and the down cooldown
  has expired and the world is above ``min_workers``.
* both directions measure their cooldown from the LAST resize in
  EITHER direction, so an up immediately chased by a down (flapping)
  is structurally impossible within one cooldown window.
* a failed grow (standby host refuses admission — chaos point
  ``scale_admit``/``action=scale_fail``) backs off exponentially:
  ``backoff_base_secs * 2^(failures-1)`` capped at
  ``backoff_max_secs``; one successful grow resets the ladder.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..utils.logging import get_logger

LOG = get_logger("serve.autoscale")

__all__ = [
    "AutoscaleConfig",
    "AutoscalePolicy",
    "AutoscaleController",
    "Decision",
    "gauges_from_views",
    "world_token",
]

DEFAULT_SCALE_UP_QUEUE = 4
DEFAULT_UP_WINDOW_SECS = 1.0
DEFAULT_SCALE_DOWN_IDLE_SECS = 10.0
DEFAULT_COOLDOWN_SECS = 15.0
DEFAULT_BACKOFF_BASE_SECS = 5.0
DEFAULT_BACKOFF_MAX_SECS = 300.0


@dataclass(frozen=True)
class AutoscaleConfig:
    """The envelope and the knobs (CLI: ``--serve-autoscale``,
    ``--scale-up-queue``, ``--scale-down-idle-secs``,
    ``--scale-cooldown-secs``, plus ``--min-workers``/``--max-workers``
    for the envelope)."""

    min_workers: int
    max_workers: int
    scale_up_queue: int = DEFAULT_SCALE_UP_QUEUE
    scale_up_ttft_ms: Optional[float] = None
    up_window_secs: float = DEFAULT_UP_WINDOW_SECS
    scale_down_idle_secs: float = DEFAULT_SCALE_DOWN_IDLE_SECS
    up_cooldown_secs: float = DEFAULT_COOLDOWN_SECS
    down_cooldown_secs: float = DEFAULT_COOLDOWN_SECS
    grow_step: int = 1
    shrink_step: int = 1
    backoff_base_secs: float = DEFAULT_BACKOFF_BASE_SECS
    backoff_max_secs: float = DEFAULT_BACKOFF_MAX_SECS

    def __post_init__(self):
        if not 1 <= self.min_workers <= self.max_workers:
            raise ValueError(
                f"autoscale envelope must satisfy 1 <= min_workers "
                f"({self.min_workers}) <= max_workers "
                f"({self.max_workers})"
            )
        if self.scale_up_queue < 1:
            raise ValueError("scale_up_queue must be >= 1")
        if self.grow_step < 1 or self.shrink_step < 1:
            raise ValueError("grow_step/shrink_step must be >= 1")


@dataclass(frozen=True)
class Decision:
    direction: str  # "up" | "down"
    target: int     # desired world size
    reason: str


class AutoscalePolicy:
    """Pure hysteresis/cooldown/backoff state machine.

    ``observe(now, ...)`` is the only input channel and ``now`` is a
    caller-supplied monotonic timestamp — this class never reads a
    clock, so the decision table is a deterministic function of its
    observation sequence (tests drive it with a fake clock, exactly
    like the scheduler's decision-table tests)."""

    def __init__(self, cfg: AutoscaleConfig):
        self.cfg = cfg
        self._pressure_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._last_resize: Optional[float] = None
        self._backoff_until: Optional[float] = None
        self._grow_failures = 0
        # (now, direction, target, reason) — the decision trace the
        # no-flapping acceptance asserts cooldowns against.
        self.trace: List[Tuple[float, str, int, str]] = []

    # ----------------------------------------------------------- inputs

    def observe(self, now: float, *, queue_depth: int, active_slots: int,
                world_size: int,
                ttft_p50_ms: Optional[float] = None
                ) -> Optional[Decision]:
        """One pressure observation; returns the resize decision the
        caller should execute, or None."""
        cfg = self.cfg
        pressured = queue_depth >= cfg.scale_up_queue or (
            cfg.scale_up_ttft_ms is not None
            and ttft_p50_ms is not None
            and ttft_p50_ms >= cfg.scale_up_ttft_ms
        )
        idle = queue_depth == 0 and active_slots == 0

        # Hysteresis windows: pressure/idle must be CONTINUOUS — any
        # contrary observation restarts the window.
        self._pressure_since = (
            self._pressure_since if pressured and
            self._pressure_since is not None
            else (now if pressured else None)
        )
        self._idle_since = (
            self._idle_since if idle and self._idle_since is not None
            else (now if idle else None)
        )

        if (
            self._pressure_since is not None
            and now - self._pressure_since >= cfg.up_window_secs
            and world_size < cfg.max_workers
            and self._cooldown_ok(now, cfg.up_cooldown_secs)
            and (self._backoff_until is None or now >= self._backoff_until)
        ):
            target = min(world_size + cfg.grow_step, cfg.max_workers)
            return self._decide(now, "up", target, (
                f"queue {queue_depth} >= {cfg.scale_up_queue} for "
                f"{now - self._pressure_since:.1f}s"
            ))
        if (
            self._idle_since is not None
            and now - self._idle_since >= cfg.scale_down_idle_secs
            and world_size > cfg.min_workers
            and self._cooldown_ok(now, cfg.down_cooldown_secs)
        ):
            target = max(world_size - cfg.shrink_step, cfg.min_workers)
            return self._decide(now, "down", target, (
                f"idle for {now - self._idle_since:.1f}s"
            ))
        return None

    def _cooldown_ok(self, now: float, cooldown: float) -> bool:
        return (self._last_resize is None
                or now - self._last_resize >= cooldown)

    def _decide(self, now: float, direction: str, target: int,
                reason: str) -> Decision:
        # The cooldown clock starts at the DECISION (the launcher
        # executes it synchronously), and both hysteresis windows
        # restart so the next decision needs fresh evidence.
        self._last_resize = now
        self._pressure_since = None
        self._idle_since = None
        self.trace.append((now, direction, target, reason))
        return Decision(direction=direction, target=target, reason=reason)

    # ------------------------------------------------- grow-failure path

    def record_grow_ok(self) -> None:
        self._grow_failures = 0
        self._backoff_until = None

    def record_grow_failed(self, now: float) -> float:
        """Exponential backoff on a refused admission; returns the
        backoff window in seconds."""
        self._grow_failures += 1
        backoff = min(
            self.cfg.backoff_base_secs * (2 ** (self._grow_failures - 1)),
            self.cfg.backoff_max_secs,
        )
        self._backoff_until = now + backoff
        self.trace.append((now, "grow_failed", self._grow_failures,
                           f"backoff {backoff:.1f}s"))
        return backoff


def gauges_from_views(views, world=None) -> Optional[Dict[str, float]]:
    """The autoscale pressure signals from the live plane's merged
    per-rank views (obs/live.py ``LiveAggregator.merged()``): worst
    (max) queue depth and active slots across ranks — the gauges are
    near-identical by the identical-schedule invariant, and max never
    hides pressure — plus the worst ttft p50.  None until some rank has
    streamed a serve gauge (the policy must not decide on silence).

    ``world`` restricts the read to CURRENT members: the aggregator
    keeps a dead or released rank's final view forever, and a rank
    that died busy would otherwise pin frozen queue/active values into
    every future decision (perpetual pressure, or an idle-shrink that
    can never fire)."""
    if world is not None:
        members = set(world)
        views = {r: v for r, v in views.items() if r in members}
    queue = active = ttft = None
    for view in views.values():
        for m in view.metrics.values():
            name = m.get("name")
            if name == "serve.queue_depth":
                v = float(m["value"])
                queue = v if queue is None else max(queue, v)
            elif name == "serve.active_slots":
                v = float(m["value"])
                active = v if active is None else max(active, v)
            elif name == "serve.ttft_ms" and m.get("count"):
                p50 = m.get("p50")
                if p50 is not None:
                    ttft = p50 if ttft is None else max(ttft, p50)
    if queue is None:
        return None
    out: Dict[str, float] = {
        "queue_depth": queue,
        "active_slots": active or 0.0,
    }
    if ttft is not None:
        out["ttft_p50_ms"] = ttft
    return out


def world_token(prev_world: Optional[int], world: int,
                version: Optional[int] = None) -> str:
    """The live-digest / summary autoscale token (``world 4→6 v=12``)
    — ONE formatter so the console digest and ``--stats-summary`` can
    never disagree about what a resize or a swap looked like (the PR-3
    single-source rule)."""
    if prev_world is not None and prev_world != world:
        token = f"world {prev_world}→{world}"
    else:
        token = f"world {world}"
    if version is not None:
        token += f" v={int(version)}"
    return token


class AutoscaleController:
    """Launcher-side glue around the pure policy.

    Owns nothing it does not need: the launcher's monitor loop calls
    :meth:`tick` on its own cadence and executes any returned decision
    itself (epoch mint + spawn/drop), then reports the outcome through
    :meth:`executed` / :meth:`grow_failed`.  Metrics land in the
    launcher process's own registry (dumped with the ``launcher`` tag,
    so ``--stats-summary`` picks them up) and are appended to the
    ``/metrics`` exposition via :meth:`prometheus`."""

    def __init__(self, cfg: AutoscaleConfig, registry=None):
        from ..obs import get_registry  # noqa: PLC0415

        self.cfg = cfg
        self.policy = AutoscalePolicy(cfg)
        self._reg = registry if registry is not None else get_registry()

    def tick(self, now: float, views, world) -> Optional[Decision]:
        """``world``: the CURRENT membership list — views from ranks
        outside it (dead, released) are ignored, not averaged in."""
        world = list(world)
        self._reg.gauge("autoscale.world").set(len(world))
        gauges = gauges_from_views(views, world)
        if gauges is None:
            return None
        return self.policy.observe(
            now,
            queue_depth=int(gauges["queue_depth"]),
            active_slots=int(gauges["active_slots"]),
            world_size=len(world),
            ttft_p50_ms=gauges.get("ttft_p50_ms"),
        )

    def executed(self, decision: Decision, epoch: int,
                 world_size: int) -> None:
        self._reg.counter("autoscale.decisions",
                          direction=decision.direction).inc()
        self._reg.gauge("autoscale.world").set(world_size)
        if decision.direction == "up":
            self.policy.record_grow_ok()
        LOG.info("autoscale %s -> world %d at epoch %d (%s)",
                 decision.direction, world_size, epoch, decision.reason)

    def grow_failed(self, now: float, rank: int) -> None:
        backoff = self.policy.record_grow_failed(now)
        self._reg.counter("autoscale.backoffs").inc()
        LOG.warning(
            "autoscale grow refused admission for rank %d; backing off "
            "%.1fs", rank, backoff,
        )

    # ------------------------------------------------------- exposition

    def prometheus(self) -> str:
        """Launcher-local autoscale series appended to the live plane's
        ``/metrics`` render (worker snapshots never carry these — the
        controller lives in the launcher)."""
        lines = [
            "# HELP hvdtpu_autoscale_world Current serving world size "
            "as the autoscale controller last saw it",
            "# TYPE hvdtpu_autoscale_world gauge",
            f"hvdtpu_autoscale_world "
            f"{self._reg.gauge('autoscale.world').value}",
            "# HELP hvdtpu_autoscale_decisions Resize decisions "
            "executed, by direction",
            "# TYPE hvdtpu_autoscale_decisions counter",
        ]
        for direction in ("up", "down"):
            lines.append(
                f'hvdtpu_autoscale_decisions{{direction="{direction}"}} '
                f"{int(self._reg.counter('autoscale.decisions', direction=direction).value)}"
            )
        lines += [
            "# HELP hvdtpu_autoscale_backoffs Grow attempts refused "
            "admission (exponential backoff armed)",
            "# TYPE hvdtpu_autoscale_backoffs counter",
            f"hvdtpu_autoscale_backoffs "
            f"{int(self._reg.counter('autoscale.backoffs').value)}",
        ]
        return "\n".join(lines) + "\n"
