"""Serving worker CLI: one rank of a continuous-batching fleet.

Spawned by ``hvdrun --elastic --serve`` (which also arms the ingest
pump on its rendezvous store)::

    hvdrun --elastic --serve -np 2 -- \\
        python -m horovod_tpu.serve --size nano --slots 4

Mirrors ``elastic/worker.py``'s lifecycle (death hooks first, heartbeat
immediately, epoch-qualified error publishing) with the function baked
in instead of fetched: the serving loop :func:`~.service.serve_worker`.
Model geometry comes from flags, each overridable by the HVDTPU_SERVE_*
env the launcher forwards — so one ``--serve`` invocation configures
the whole fleet.
"""

from __future__ import annotations

import argparse
import json
import sys
import traceback

from ..utils import env as envmod


def parse_spec(argv=None) -> dict:
    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.serve",
        description="One serving rank (run under hvdrun --elastic --serve).",
    )
    p.add_argument("--size", default=None,
                   help="gpt() model family entry (default nano)")
    p.add_argument("--slots", type=int, default=None,
                   help="decode slot pool size (default 4)")
    p.add_argument("--max-len", type=int, default=None,
                   help="slot cache length (default: the model's max_len)")
    p.add_argument("--seed", type=int, default=None,
                   help="params init seed + sampling root, identical "
                        "on every rank")
    p.add_argument("--kv-mode", default=None,
                   choices=["paged", "contiguous"],
                   help="KV cache layout (default paged: block-table "
                        "pages, admission judged in free pages)")
    p.add_argument("--page-size", type=int, default=None,
                   help="KV page size in token rows (default 16)")
    p.add_argument("--kv-pages", type=int, default=None,
                   help="KV page-pool size (default: worst case)")
    p.add_argument("--width", type=int, default=None,
                   help="width-sharded fleet: np//width serving groups, "
                        "each rank's paged decode shard_mapped over "
                        "width devices (default 0 = replicated)")
    p.add_argument("--attention", default="reference",
                   choices=["reference", "flash"],
                   help="attention implementation for the served model "
                        "(default reference: runs on every backend; "
                        "flash is the TPU fast path)")
    p.add_argument("--weights-dir", default=None,
                   help="weight hot-swap source: sharded-checkpoint "
                        "directory a training job publishes versions "
                        "into (default: HVDTPU_SERVE_WEIGHTS_DIR, "
                        "unset = hot-swap off)")
    p.add_argument("--swap-poll-steps", type=int, default=None,
                   help="serving steps between hot-swap manifest "
                        "polls (default 16)")
    p.add_argument("--slo-ttft-ms", type=float, default=None,
                   help="time-to-first-token objective ceiling in ms "
                        "for --slo-class requests (unset = no ttft "
                        "objective)")
    p.add_argument("--slo-tpot-ms", type=float, default=None,
                   help="per-output-token objective ceiling in ms for "
                        "--slo-class requests (unset = no tpot "
                        "objective)")
    p.add_argument("--slo-objective", type=float, default=None,
                   help="fraction of requests that must meet the "
                        "ceilings (default 0.99: a 1%% error budget "
                        "the burn-rate alerts spend against)")
    p.add_argument("--slo-class", default=None,
                   help="which SLO class the ceilings apply to "
                        "(default interactive)")
    args = p.parse_args(argv)

    import os  # noqa: PLC0415

    def pick(flag, env_name, cast, default):
        if flag is not None:
            return flag
        raw = os.environ.get(env_name)
        return cast(raw) if raw not in (None, "") else default

    spec = {
        "size": pick(args.size, envmod.SERVE_MODEL, str, "nano"),
        "num_slots": pick(args.slots, envmod.SERVE_SLOTS, int, 4),
        "seed": pick(args.seed, envmod.SERVE_SEED, int, 0),
        "kv_mode": pick(args.kv_mode, envmod.SERVE_KV_MODE, str,
                        "paged"),
        "page_size": pick(args.page_size, envmod.SERVE_PAGE_SIZE, int,
                          16),
        "width": pick(args.width, envmod.SERVE_WIDTH, int, 0),
        "overrides": {"attention_impl": args.attention},
    }
    kv_pages = pick(args.kv_pages, envmod.SERVE_KV_PAGES, int, 0)
    if kv_pages:
        spec["kv_pages"] = kv_pages
    max_len = pick(args.max_len, envmod.SERVE_MAX_LEN, int, 0)
    if max_len:
        spec["max_len"] = max_len
    weights_dir = pick(args.weights_dir, envmod.SERVE_WEIGHTS_DIR,
                       str, None)
    if weights_dir:
        spec["weights_dir"] = weights_dir
        spec["swap_poll_steps"] = pick(
            args.swap_poll_steps, envmod.SERVE_SWAP_POLL_STEPS, int, 16
        )
    # Tenant-aware admission: fleet-wide (every rank must build the
    # identical TenantQoS), so it travels the launcher-forwarded env
    # like the model geometry does.
    tenant_budget = pick(None, envmod.SERVE_TENANT_BUDGET, int, 0)
    if tenant_budget:
        spec["tenants"] = {"budget_tokens": tenant_budget}
    # SLO objectives (obs/slo.py): fleet-wide like the QoS policy —
    # every rank must judge the identical targets, so they ride the
    # launcher-forwarded env with flag overrides.  Classes without a
    # target never alert (untagged traffic trips nothing).
    slo_ttft = pick(args.slo_ttft_ms, envmod.SERVE_SLO_TTFT_MS,
                    float, 0.0)
    slo_tpot = pick(args.slo_tpot_ms, envmod.SERVE_SLO_TPOT_MS,
                    float, 0.0)
    if slo_ttft or slo_tpot:
        target = {
            "objective": pick(args.slo_objective,
                              envmod.SERVE_SLO_OBJECTIVE, float, 0.99),
        }
        if slo_ttft:
            target["ttft_ms"] = slo_ttft
        if slo_tpot:
            target["tpot_ms"] = slo_tpot
        cls = pick(args.slo_class, envmod.SERVE_SLO_CLASS, str,
                   "interactive")
        spec["slo"] = {cls: target}
    return spec


def main(argv=None) -> int:
    # Same death-path arming as elastic/worker.py: everything after
    # this point leaves a black box if it dies.
    from ..obs import flightrec  # noqa: PLC0415

    flightrec.install_death_hooks()
    spec = parse_spec(argv)

    from ..elastic.context import ElasticContext, context  # noqa: PLC0415
    from ..elastic.exceptions import HorovodShutdownError  # noqa: PLC0415
    from .service import serve_worker  # noqa: PLC0415

    ctx = context()
    if not isinstance(ctx, ElasticContext):
        print(
            "python -m horovod_tpu.serve must be spawned by the elastic "
            "launcher (hvdrun --elastic --serve); HVDTPU_ELASTIC_KV is "
            "unset", file=sys.stderr,
        )
        return 2
    ctx.start_heartbeat()
    flush_trigger = "explicit"
    try:
        summary = serve_worker(spec)
        print(json.dumps({"serve_summary": summary}), flush=True)
        return 0
    except HorovodShutdownError as exc:
        # Outlived the retry budget / dropped from the world: exit like
        # a crash so the launcher's monitor decides, not this rank.
        flightrec.record_exception(exc, where="serve.worker")
        flush_trigger = "exception"
        return 1
    except BaseException as exc:
        flightrec.record_exception(exc, where="serve.worker")
        flush_trigger = "exception"
        import cloudpickle  # noqa: PLC0415

        try:
            ctx.kv.put(
                "elastic", f"error_{ctx.rank}_{ctx.epoch}",
                cloudpickle.dumps(traceback.format_exc()),
            )
        except Exception:
            pass
        return 1
    finally:
        ctx.stop_heartbeat()
        try:
            flightrec.flush(flush_trigger)
        except Exception:
            pass


if __name__ == "__main__":
    sys.exit(main())
