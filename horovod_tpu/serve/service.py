"""The serving loop and job driver: continuous batching on the elastic
launcher.

Topology: every rank of the serving world runs the SAME model
replicated over the SAME slot pool and derives an IDENTICAL admit/evict
schedule — rank 0 of the current world (the *leader*, lowest live rank)
is the only rank that reads the ingest log and the only rank that
writes result streams, and it broadcasts each step's schedule through
an epoch-scoped KV key its peers block on.  Identical schedule + the
deterministic decode math = identical tokens on every rank, which is
what makes a dead rank REPLACEABLE: the respawned incarnation rebuilds
the same state from the durable request log and token streams, and no
in-flight request is dropped.

Elastic recovery rides the PR-1 machinery unchanged: the launcher
detects the dead rank, mints a fresh rendezvous epoch, respawns the
rank via the same ``elastic.worker`` entry; survivors notice the epoch
bump (every KV wait is epoch-watched) and re-rendezvous.  At each epoch
start the leader republishes a *recovery doc* — the ingest-log replay
of every not-yet-finished request, with the tokens already streamed to
clients — and every rank rebuilds its scheduler and re-prefills its
slots from it.  Tokens already delivered are never re-emitted;
generation resumes mid-stream, bitwise on course.

Observability rides the PR-2/3 planes: ``serve.*`` instruments land in
the per-rank metrics registry, stream to the launcher's ``/metrics``
endpoint when live stats are armed, show in the live digest, and
aggregate into ``--stats-summary``.

Two riders close the train→serve loop without a restart (ISSUE 13):
the launcher's autoscale controller (serve/autoscale.py) drives the
same epoch machinery deliberately from the streamed queue/ttft gauges
— a resize is indistinguishable from a survived failure, and a rank
dropped by a shrink exits as a clean *release* — and the weight
hot-swap manager (serve/hotswap.py) flips the fleet to newly published
checkpoints on a version-stamped step over the schedule-broadcast
lane, with the durable ``serve/weight_version`` record making
epoch recovery converge on exactly one version.  The leader also
advances a finished watermark that compacts ``serve/log/*`` (and,
via the ingest pump, ``serve/out/*``) so the store and the recovery
replay stop growing with total requests ever served.
"""

from __future__ import annotations

import pickle
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..elastic.exceptions import HorovodShutdownError
from ..obs import get_registry
from ..obs import flightrec as obs_flightrec
from ..obs import goodput as obs_goodput
from ..obs import memplane
from ..obs import progress as obs_progress
from ..obs import slo as obs_slo
from ..obs import trace as obs_trace
from ..testing.faults import maybe_fail
from ..utils.logging import get_logger
from .frontend import (SCOPE, FrontDoor, Rejection, ServeClient,
                       validate_request)
from .hotswap import VERSION_KEY, SwapManager
from .paged import page_reject_reason
from .scheduler import Request, SlotScheduler, TenantQoS

LOG = get_logger("serve")

__all__ = ["serve_worker", "ServeJob", "DEFAULT_SPEC", "RateWindow"]

# A request's decode progress is flushed to its trace lane every this
# many tokens (plus a final remainder span at eviction): per-token
# spans would drown the bounded ring, one-span-per-request would hide
# mid-stream stalls.
_DECODE_SPAN_TOKENS = 8


class RateWindow:
    """Sliding wall-clock token-rate window.

    ``serve.tokens_per_sec`` used to be epoch-cumulative tokens over
    epoch-elapsed time — a number only the leader's whole-epoch cadence
    could explain, and one a trace report (built from per-step decode
    spans) could legitimately disagree with.  This window is fed the
    SAME timestamps the decode-compute spans record, so the digest
    gauge and the trace report are two views of one clock: recent
    tokens over a trailing ``window`` seconds (epoch-elapsed until the
    window first fills, matching the old early-epoch semantics)."""

    def __init__(self, window_secs: float = 5.0):
        self.window = float(window_secs)
        self._events: deque = deque()  # (t, ntokens)
        self._total = 0
        self._first_t: Optional[float] = None

    def observe(self, t: float, n: int) -> None:
        if n <= 0:
            return
        if self._first_t is None:
            self._first_t = t
        self._events.append((t, n))
        self._total += n
        cut = t - self.window
        while self._events and self._events[0][0] < cut:
            _, m = self._events.popleft()
            self._total -= m

    def rate(self, now: float) -> float:
        if self._first_t is None:
            return 0.0
        cut = now - self.window
        while self._events and self._events[0][0] < cut:
            _, m = self._events.popleft()
            self._total -= m
        span = min(now - self._first_t, self.window)
        return self._total / max(span, 1e-3)

# How many trailing step-schedule keys the leader keeps before deleting
# (authenticated DELETE): an unbounded schedule history would grow the
# launcher's store forever on a long-lived serving job.  The window
# must comfortably exceed the worst leader-vs-peer step lag (peers
# have no back-pressure on the leader): a peer whose next schedule key
# was already GC'd can only time out and force a world re-formation.
_SCHED_KEEP = 256

DEFAULT_SPEC: Dict[str, Any] = {
    "size": "nano",          # gpt(<size>) model family entry
    "overrides": {},         # TransformerConfig overrides
    "seed": 0,               # params init seed AND the sampling root
                             # (identical on every rank; serve/sampling.py)
    "num_slots": 4,
    "max_len": None,         # slot cache length (default cfg.max_len)
    "kv_mode": "paged",      # paged KV (block tables) | "contiguous"
    "page_size": 16,         # KV page size in token rows (paged mode)
    "kv_pages": None,        # page-pool size (default: worst case)
    "width": 0,              # 0 = replicated fleet (peers are hot
                             # standbys, PR-10); >= 1 = width-sharded
                             # fleet: the world splits into
                             # size // width serving GROUPS, each
                             # independently serving the log partition
                             # n % groups == g — np multiplies
                             # tokens/sec instead of adding standbys
    "idle_secs": 0.01,       # leader pacing when nothing is in flight
    "stream_every": 4,       # publish token streams every N tokens
    "weights_dir": None,     # weight hot-swap source (None = off)
    "swap_poll_steps": 16,   # leader manifest-poll cadence (steps)
    "frontends": 1,          # front-door shard count F: F ingest pumps
                             # each owning the rid-hash partition
                             # crc32(rid) % F (ServeJob / the launcher
                             # publish the authoritative count in the
                             # serve/frontdoor doc — workers read THAT)
    "tenants": None,         # tenant-aware admission (TenantQoS.from_
                             # spec): {"weights": {slo: w},
                             # "budget_tokens": B, "window_steps": W};
                             # None = plain FCFS, byte-identical to
                             # the pre-QoS scheduler
}


def _epoch_scope(epoch: int) -> str:
    return f"serve_e{epoch}"


def _fleet_shape(world, rank, width: int):
    """The width-sharded fleet layout, a pure function of the sorted
    world and the spec: ``width == 0`` is the legacy replicated fleet
    (one group, every rank a hot standby of the leader); ``width >= 1``
    carves the world into ``size // width`` serving GROUPS of ``width``
    ranks each (contiguous by world position — DCN carries the group
    axis, ICI the width axis inside each rank's device mesh).  Each
    group independently serves the ingest-log partition ``n % groups ==
    group``; leftover ranks (world not divisible) idle as standbys and
    become capacity at the next resize.  Returns ``(groups, group,
    group_world, standby)`` with ``group=None`` for standbys."""
    size = len(world)
    idx = world.index(rank)
    if width < 1:
        return 1, 0, list(world), False
    groups = max(size // width, 1)
    if idx >= groups * width:
        return groups, None, [], True
    group = idx // width
    return groups, group, list(world[group * width:(group + 1) * width]), False


def _fetch(ctx, scope: str, key: str, what: str) -> bytes:
    """Poll one serving key; a rendezvous-epoch bump mid-wait means the
    world broke — surface it as the shutdown signal the outer loop
    turns into re-rendezvous + replay."""
    deadline = time.monotonic() + ctx.timeout
    while True:
        raw = ctx.kv.get(scope, key)
        if raw is not None:
            return raw
        if ctx.current_epoch() > ctx.epoch:
            raise HorovodShutdownError(
                f"world re-formed while waiting for {what}"
            )
        if time.monotonic() > deadline:
            raise HorovodShutdownError(
                f"timed out waiting for {what} — a peer likely died "
                f"without the launcher re-forming the world yet"
            )
        time.sleep(0.005)


def _serve_health_check(ctx, scope: str, group: int, group_world,
                        step: int, sdoc_raw: bytes, paged,
                        action: str) -> None:
    """The divergence sentinel's serving twin: every rank of a width
    group digests the broadcast schedule doc it is about to obey plus
    its KV page-table state, publishes the tiny digest under
    ``healthd/``, fetches its peers' and compares.  Replicated decode
    is the serving form of HVD001 — followers that drift from the
    leader's schedule or page tables produce silent token corruption
    the output checksums can't localize.  Every rank runs the identical
    comparison on the identical matrix, so every rank reaches the
    identical verdict (and ``halt`` stops the whole group, not one
    rank)."""
    import numpy as np  # noqa: PLC0415

    from ..obs import divergence as obs_divergence  # noqa: PLC0415

    digest = obs_divergence.serve_state_digest(sdoc_raw, paged)
    members = sorted(group_world)
    ctx.kv.put(scope, f"healthd/{group}/{step}/{ctx.rank}",
               digest.astype(np.uint32).tobytes())
    rows = []
    for r in members:
        if r == ctx.rank:
            rows.append(digest)
        else:
            raw = _fetch(ctx, scope, f"healthd/{group}/{step}/{r}",
                         f"serve health digest from rank {r}")
            rows.append(np.frombuffer(raw, dtype=np.uint32))
    mat = np.stack(rows)
    reg = get_registry()
    reg.counter("health.divergence.checks").inc()
    reg.gauge("health.divergence.last_check_step").set(step)
    # GC our own stale key (leader GC'd sched keys the same way).
    prev = step - _SCHED_KEEP
    if prev > 0:
        ctx.kv.delete(scope, f"healthd/{group}/{prev}/{ctx.rank}")
    if bool((mat == mat[0]).all()):
        reg.gauge("health.divergence.alert").set(0)
        return
    minority_idx, _ = obs_divergence._partition(mat)
    minority = [members[i] for i in minority_idx]
    component = ("page_table"
                 if bool((mat[:, :obs_divergence.DIGEST_WIDTH]
                          == mat[0, :obs_divergence.DIGEST_WIDTH]).all())
                 else "sched_doc")
    detail = (f"step={step} "
              f"minority={','.join(str(r) for r in minority)} "
              f"component={component} group={group}")
    reg.counter("health.divergence.detected", component=component).inc()
    reg.gauge("health.divergence.alert").set(1)
    obs_flightrec.record("health.divergence", name=component,
                         cycle=step, detail=detail)
    LOG.error("serving-state divergence: %s", detail)
    if action == "halt":
        raise obs_divergence.DivergenceHalt(
            f"serving divergence sentinel: rank(s) {minority} diverged "
            f"from the group at step {step} in {component} "
            f"(--divergence-action halt)"
        )
    if action == "dump":
        try:
            obs_flightrec.dump_flight_recorder(
                trigger="health.divergence")
        except Exception:  # pragma: no cover - defensive
            pass


def _frontdoor_shape(kv) -> int:
    """The front-door shard count ``F`` from the ownership doc the
    launcher published (``serve/frontdoor``): the interleave constant
    every consumer derives the total order from.  Fixed for the job's
    lifetime (only shard OWNERSHIP moves on frontend takeover), so one
    read at epoch start is safe.  Absent doc = the pre-16 single pump
    = 1."""
    raw = kv.get(SCOPE, "frontdoor")
    if raw is None:
        return 1
    try:
        return max(int(pickle.loads(raw).get("frontends", 1)), 1)
    except Exception:
        return 1


def _build_recovery(kv, group: int = 0, groups: int = 1,
                    frontends: int = 1) -> dict:
    """Replay the durable request record: every front-door shard's
    ingest log from that shard's finished watermark up, joined with
    each request's streamed tokens and merged in ``gkey`` order
    (``gkey = n * F + shard`` — the same interleave every rank
    derives).  Only the (group) leader runs this — peers adopt its
    published doc, so a log entry racing in mid-scan can never split
    the world's view.  In a width-sharded fleet each group's doc
    carries only ITS log partition (``gkey % groups == group``);
    ``others`` maps the remaining in-flight ``(shard, n)`` slots to
    their rids so group 0's leader (the global leader) can advance the
    compaction watermarks across groups.

    The per-shard watermark (``serve/log_watermark/<s>``) is the
    compaction floor the leader advances as requests finish: every
    entry below it is done and its log key deleted, so neither this
    replay nor the ingest store grows with total requests ever served —
    only with what is actually in flight (ROADMAP 1d).
    ``weight_version`` is the durable flip record the whole fleet
    converges on (hotswap.py's single-version argument rests on every
    rank adopting THIS value at epoch start)."""
    frontends = max(int(frontends), 1)
    watermark: Dict[int, int] = {}
    log_next: Dict[int, int] = {}
    docs = []
    for shard in range(frontends):
        raw = kv.get(SCOPE, f"log_watermark/{shard}")
        wm = int(raw.decode()) if raw is not None else 0
        watermark[shard] = wm
        n = wm
        while True:
            raw = kv.get(SCOPE, f"log/{shard}/{n}")
            if raw is None:
                break
            doc = pickle.loads(raw)
            doc.setdefault("shard", shard)
            doc.setdefault("n", n)
            doc.setdefault("gkey", n * frontends + shard)
            docs.append(doc)
            n += 1
        log_next[shard] = n
    # Replay order is the gkey interleave — per-shard sequence fanned
    # over F — NOT necessarily the live arrival order: live enqueue
    # interleaves arrivals across probe steps, so a quiet shard's
    # low-n entry can sort ahead of busy-shard entries that were
    # enqueued before it live.  What recovery requires is only that
    # every rank derives the SAME order (all ranks adopt the leader's
    # doc, and per-rid token streams are order-independent); the
    # fairness skew is bounded by one in-flight backlog.
    docs.sort(key=lambda d: d["gkey"])
    inflight = []
    done_slots: List[Tuple[int, int]] = []
    others: Dict[Tuple[int, int], str] = {}
    for doc in docs:
        slot = (int(doc["shard"]), int(doc["n"]))
        out_raw = kv.get(SCOPE, f"out/{doc['rid']}")
        emitted: List[int] = []
        if out_raw is not None:
            out = pickle.loads(out_raw)
            if out.get("done"):
                # Finished (or rejected) before the break: only its
                # compaction bookkeeping survives into the new epoch.
                done_slots.append(slot)
                continue
            emitted = list(out.get("tokens", []))
        if int(doc["gkey"]) % groups != group:
            # Another group's request: irrelevant to this group's
            # schedule, but the global leader tracks it for compaction.
            others[slot] = doc["rid"]
            continue
        entry = dict(doc)
        entry["emitted"] = emitted
        inflight.append(entry)
    raw = kv.get(SCOPE, VERSION_KEY)
    version = int(raw.decode()) if raw is not None else 0
    return {"log_next": log_next, "inflight": inflight,
            "watermark": watermark, "done_slots": done_slots,
            "others": others, "weight_version": version,
            "frontends": frontends}


def _publish_out(kv, rid: str, *, tokens, done: bool, epoch: int,
                 admitted_step: int, error: Optional[str] = None,
                 finished_step: Optional[int] = None,
                 reason: Optional[str] = None,
                 n: Optional[int] = None,
                 shard: Optional[int] = None,
                 t_done: Optional[float] = None) -> None:
    doc = {
        "rid": rid,
        "tokens": list(tokens),
        "done": done,
        "epoch": epoch,
        "admitted_step": admitted_step,
    }
    if isinstance(error, Rejection):
        # Machine-readable reject code rides the doc next to the human
        # message; ServeClient.result re-raises it as RequestRejected.
        doc["error_code"] = error.code
    if t_done is not None:
        # Leader-clock completion stamp: lets a measuring client
        # compute throughput from server-side stamps instead of its
        # own polling cadence (bench.py --serve; poll-granularity
        # error was larger than the effects being measured).
        doc["t_done"] = float(t_done)
    if error is not None:
        doc["error"] = error
    if finished_step is not None:
        doc["finished_step"] = finished_step
    if reason is not None:
        doc["reason"] = reason
    if n is not None:
        # Log slot (shard, per-shard index): the ingest pump's
        # finished-output GC keys its per-shard watermark comparison on
        # these (frontend._gc_finished_outputs).
        doc["n"] = int(n)
    if shard is not None:
        doc["shard"] = int(shard)
    kv.put(SCOPE, f"out/{rid}", pickle.dumps(doc))


def _serve_epoch(ctx, engine, spec: dict, totals: Dict[str, Any],
                 profiler=None, swap: Optional[SwapManager] = None,
                 slo_plane: Optional[obs_slo.SLOPlane] = None,
                 tok_goodput: Optional[obs_goodput.TokenGoodput] = None):
    """One rendezvous epoch of the serving loop.  Returns the per-rank
    summary dict on a clean drain (``serve/stop``), raises
    HorovodShutdownError on a world break (the caller re-enters).

    Tracing (obs/trace.py, armed by ``HVDTPU_TRACE``): every sampled
    request's life through this loop lands as spans on its rid lane —
    the ttft components tile the [arrival, first-token] interval
    exactly (queue_wait + schedule_broadcast + admit_wait + prefill =
    the histogram's sample, same timestamps), and busy steps land
    step-lane spans (schedule_broadcast / prefill / decode_compute /
    stream_publish / whole-step — prefill twinned on the step lane
    UNsampled, so the residual subtraction never depends on the
    sample rate) the tpot decomposition derives from.
    Spans carry THIS epoch, not the env's spawn epoch: a survivor's
    single dump holds every epoch it lived through, which is how a
    replayed request's waterfall shows both incarnations."""
    reg = get_registry()
    epoch = ctx.rendezvous()
    width = int(spec.get("width") or 0)
    groups, group, group_world, standby = _fleet_shape(
        ctx.world, ctx.rank, width
    )
    reg.gauge("serve.world_size").set(ctx.size)
    reg.gauge("serve.groups").set(groups)
    if group is not None:
        # This rank's serving group: the digest sums tokens/sec ACROSS
        # groups (independent capacity) but takes the max WITHIN one
        # (replicated peers report the same stream).
        reg.gauge("serve.group").set(group)
    if standby:
        # World not divisible by the width: this rank is a hot standby
        # until the next resize makes it part of a group.  It still
        # heartbeats, ticks progress, and drains cleanly on stop.
        LOG.info("epoch %d: rank %d standing by (world %d, width %d)",
                 epoch, ctx.rank, ctx.size, width)
        while True:
            if ctx.world_changed():
                raise HorovodShutdownError(
                    f"epoch advanced past {epoch}; re-forming"
                )
            if ctx.kv.get(SCOPE, "stop") is not None:
                return {"rank": ctx.rank, "epoch": epoch, "steps": 0,
                        "standby": True,
                        "completed": totals["completed"],
                        "tokens": totals["tokens"]}
            obs_progress.tick()
            # A standby has nothing latency-sensitive to wake for:
            # pace its stop/world probes gently so a parked rank does
            # not tax the store the serving groups are using.
            time.sleep(max(float(spec.get("idle_secs", 0.01)), 0.05))
    leader = group_world[0]
    is_leader = ctx.rank == leader
    # The GLOBAL leader (lowest live rank) owns the compaction
    # watermark — the one piece of bookkeeping that must see every
    # group's completions.
    is_global = ctx.rank == ctx.world[0]
    scope = _epoch_scope(epoch)
    tracing = obs_trace.enabled()
    t_rate = obs_trace.sample_rate()

    # Epoch-start recovery broadcast: the group leader's replay of the
    # durable request record IS the schedule seed — every rank of the
    # group (survivor or fresh respawn) rebuilds the identical
    # scheduler state from it.  Groups recover independently; the log
    # partition (n % groups) makes their replays disjoint.
    t_rec0 = time.time()
    if is_leader:
        rec = _build_recovery(ctx.kv, group, groups,
                              _frontdoor_shape(ctx.kv))
        ctx.kv.put(scope, f"recovery/{group}", pickle.dumps(rec))
    else:
        rec = pickle.loads(_fetch(ctx, scope, f"recovery/{group}",
                                  f"recovery doc for epoch {epoch}"))
    # The interleave constant travels in the recovery doc: every rank
    # of the group derives the shard merge from the LEADER's read of
    # the front-door doc, not its own racy one.
    frontends = max(int(rec.get("frontends", 1)), 1)
    reg.gauge("serve.frontends").set(frontends)
    # Every rank converges on the durable weight version BEFORE any
    # replay prefill — a replayed request's rebuilt cache must be
    # computed under the version the new epoch serves.
    if swap is not None:
        swap.reset_epoch()
        swap.ensure_version(engine, rec.get("weight_version", 0))
    # Tenant-aware admission (spec["tenants"], TenantQoS.from_spec):
    # the policy object is a pure function of the spec, so every rank
    # of every group builds the identical one — the HVD012 determinism
    # contract extends from the scheduler through its policy.
    sched = SlotScheduler(spec["num_slots"],
                          qos=TenantQoS.from_spec(spec.get("tenants")))
    engine.reset()
    log_next: Dict[int, int] = {int(s): int(n) for s, n in
                                rec["log_next"].items()}
    # Request-log compaction (global-leader-only writes, like every
    # other durable-record write): the (shard, n) log slot of every
    # in-flight request, the done set above the per-shard watermarks,
    # and the watermarks themselves.  ``other_rids`` maps the OTHER
    # groups' in-flight slots to rids — the global leader cannot see
    # their evictions directly, so it advances past them by polling
    # their published done docs (one O(1) KV get per head-of-watermark
    # candidate per shard per step).
    n_of: Dict[str, Tuple[int, int]] = {}
    done_slots = {(int(s), int(n))
                  for s, n in rec.get("done_slots", [])}
    other_rids: Dict[Tuple[int, int], str] = {
        (int(k[0]), int(k[1])): v
        for k, v in rec.get("others", {}).items()
    }
    watermark: Dict[int, int] = {int(s): int(w) for s, w in
                                 rec.get("watermark", {}).items()}

    def _advance_watermark() -> None:
        """Global-leader bookkeeping, now per front-door shard: fold
        finished log slots into each shard's watermark, push the new
        floor durably, THEN delete the compacted log keys (a crash
        between the two leaves orphan entries below the floor —
        harmless, the pump's GC sweeps them — never a floor above
        surviving entries).  Slots owned by other groups advance when
        their done doc is visible."""
        for shard in sorted(watermark):
            old = watermark[shard]
            mark = old
            while True:
                slot = (shard, mark)
                if slot in done_slots:
                    done_slots.discard(slot)
                    other_rids.pop(slot, None)
                    mark += 1
                    continue
                rid = other_rids.get(slot)
                if rid is not None:
                    raw = ctx.kv.get(SCOPE, f"out/{rid}")
                    if raw is not None and \
                            pickle.loads(raw).get("done"):
                        other_rids.pop(slot)
                        mark += 1
                        continue
                break
            if mark > old:
                watermark[shard] = mark
                ctx.kv.put(SCOPE, f"log_watermark/{shard}",
                           str(mark).encode())
                for i in range(old, mark):
                    ctx.kv.delete(SCOPE, f"log/{shard}/{i}")
        # One compaction gauge across shards: total retired entries.
        reg.gauge("serve.log_watermark").set(sum(watermark.values()))

    def _mark_done(rid: str) -> None:
        slot = n_of.pop(rid, None)
        if slot is not None:
            done_slots.add(slot)
        if is_global:
            _advance_watermark()

    def _reject_reason(entry) -> Optional[str]:
        """Full per-entry verdict: the frontend validation (including
        the tenant-budget feasibility check — a cost that exceeds the
        whole per-window budget would be throttled forever, bricking
        its tenant and freezing the shard's compaction watermark) plus
        the page-feasibility check (a request whose worst case exceeds
        the WHOLE page pool can never be admitted — rejecting it
        loudly beats a permanently head-blocked FCFS queue).  Pure —
        the qos policy is built from the spec every rank shares — so
        every rank and every group reaches the same verdict."""
        reason = validate_request(
            entry, engine.serve_len, engine.cfg.vocab_size,
            budget_tokens=(None if sched.qos is None
                           else sched.qos.budget_tokens),
        )
        if reason is None and engine.paged is not None:
            reason = page_reject_reason(
                len(entry["prompt"]), entry["max_new_tokens"],
                engine.page_size, engine.num_pages,
            )
        return reason

    def _entry_request(entry) -> Request:
        return Request(
            rid=entry["rid"], prompt=tuple(entry["prompt"]),
            max_new_tokens=entry["max_new_tokens"],
            eos_id=entry.get("eos_id"),
            arrival=entry.get("arrival", 0.0),
            temperature=float(entry.get("temperature") or 0.0),
            top_k=int(entry.get("top_k") or 0),
            tenant=str(entry.get("tenant") or "default"),
            slo=str(entry.get("slo") or "standard"),
        )

    def _entry_slot(entry) -> Optional[Tuple[int, int]]:
        """The entry's durable log slot ``(shard, n)`` — the compaction
        bookkeeping key (legacy docs without a shard stamp are shard
        0's, the only shard a pre-16 store ever had)."""
        if entry.get("n") is None:
            return None
        return (int(entry.get("shard") or 0), int(entry["n"]))

    # Admission capacity in FREE PAGES (paged mode): each round's gate
    # accumulates its own acceptances, so two same-round admissions are
    # never judged against the same free pool.  A deterministic
    # function of the schedule so far — the HVD001 invariant extends
    # through this gate.

    replayed = 0
    for entry in rec["inflight"]:
        reason = _reject_reason(entry)
        if reason is not None:
            # Same accounting as the live path: a reject during replay
            # must show in serve.rejected too, or the runbook's
            # "rejected climbing" check misses exactly the rejects that
            # coincide with world breaks.
            reg.counter("serve.rejected").inc()
            if is_leader:
                _publish_out(ctx.kv, entry["rid"], tokens=(), done=True,
                             epoch=epoch, admitted_step=0, error=reason,
                             n=entry.get("n"),
                             shard=entry.get("shard"))
                if _entry_slot(entry) is not None:
                    n_of[entry["rid"]] = _entry_slot(entry)
                    _mark_done(entry["rid"])
            continue
        if is_leader and _entry_slot(entry) is not None:
            n_of[entry["rid"]] = _entry_slot(entry)
        sched.enqueue(_entry_request(entry),
                      resume=entry.get("emitted", ()))
        if entry.get("emitted"):
            replayed += 1
    if replayed:
        reg.counter("serve.replayed").inc(replayed)
        obs_flightrec.record(
            "init", name="serve_replay", cycle=epoch,
            detail=f"{replayed} in-flight requests replayed",
        )
        LOG.info("epoch %d: replaying %d in-flight requests", epoch,
                 replayed)
    if tracing:
        # The recovery span is the left edge of every replayed
        # request's second incarnation: the waterfall's gap between a
        # request's epoch-N spans and this span IS the recovery cost.
        obs_trace.add_span("serve.steps", "recovery", t_rec0,
                           time.time(), epoch=epoch, replayed=replayed)

    step = 0
    rate_win = RateWindow()
    # Registry counters persist across epochs while sched state does
    # not: these epoch-local cursors turn the scheduler's cumulative
    # per-tenant numbers into counter increments exactly once.
    tenant_prev_throttled: Dict[str, int] = {}
    tenant_prev_admitted: Dict[str, int] = {}
    # rid-keyed decode-window starts for the per-N-token decode spans:
    # (wall t, tokens emitted at window start).
    dspan: Dict[int, Tuple[float, int]] = {}
    idle_secs = float(spec.get("idle_secs", 0.01))
    stream_every = max(int(spec.get("stream_every", 4)), 1)
    # A single-rank group has no peers to broadcast to: publishing the
    # step schedule would cost a signed KV roundtrip per step that
    # nobody reads (recovery never replays sched keys — it rebuilds
    # from log + out).  At ~2ms per roundtrip that is a large slice of
    # a CPU decode step, and it is exactly the fleet shape the width-1
    # scaling bench runs, so skip it.
    solo = len(group_world) == 1
    # Serving twin of the divergence sentinel (obs/divergence.py):
    # armed by --health, cadence --health-check-steps.  Solo groups
    # have no replica to diverge from, so they skip it entirely.
    from ..obs.health import HealthConfig  # noqa: PLC0415

    health_cfg = HealthConfig.from_env()
    health_every = (health_cfg.check_steps
                    if health_cfg.enabled and not solo else 0)
    # The drain sentinel is write-once; probing it every busy step is
    # another roundtrip per step.  Probe on idle steps and every 8th
    # busy step (drain latency <= 8 steps), and latch the first hit.
    stop_latched = False
    was_busy = False
    idle_streak = 0
    while True:
        step += 1
        t_step0 = time.time()
        # Deterministic chaos: the serving analog of the elastic
        # collective's step-boundary injection point — same spec
        # grammar, same epoch-0 default that keeps respawns convergent.
        maybe_fail("worker_exit", step=step, rank=ctx.rank)
        # Epoch-bump probe: one KV get.  Busy steps only probe every
        # 4th (detection lag <= 3 steps; peers blocked in _fetch watch
        # the epoch continuously, and heartbeat/progress monitoring is
        # out-of-band) — at CPU decode speeds an every-step probe was
        # a measurable slice of the serving loop.
        if (not was_busy or step % 4 == 0) and ctx.world_changed():
            raise HorovodShutdownError(
                f"epoch advanced past {epoch} (a peer died); "
                f"re-forming the serving world"
            )

        # -- schedule broadcast (the group leader reads the log and
        # keeps its partition n % groups == group; its peers follow) --
        if is_leader:
            new_entries = []
            # Log probe: one KV get per shard per step minimum.  When
            # the local queue already holds waiting work, new arrivals
            # cannot change THIS step's admissions (they join behind
            # the queue), so probe every 4th step; total order is the
            # gkey interleave's either way.  An empty queue probes
            # every step: that is the latency-sensitive case.
            probe = sched.queue_depth == 0 or step % 4 == 0
            for shard in (sorted(log_next) if probe else ()):
                while True:
                    cursor = log_next[shard]
                    raw = ctx.kv.get(SCOPE, f"log/{shard}/{cursor}")
                    if raw is None:
                        if groups > 1 and not is_global:
                            # The GLOBAL leader compacts log keys the
                            # moment a shard's contiguous prefix is
                            # done — keys THIS group's lagging cursor
                            # may not have scanned yet.  A gap at the
                            # cursor therefore means either "end of
                            # shard log" or "compacted under me":
                            # re-read the shard's watermark and jump
                            # over the deleted range, or this group's
                            # cursor polls a deleted key forever and
                            # its partition starves.
                            raw_wm = ctx.kv.get(
                                SCOPE, f"log_watermark/{shard}")
                            wm = (int(raw_wm.decode())
                                  if raw_wm is not None else 0)
                            if wm > cursor:
                                log_next[shard] = wm
                                continue
                        break
                    doc = pickle.loads(raw)
                    doc.setdefault("shard", shard)
                    doc.setdefault("n", cursor)
                    doc.setdefault("gkey",
                                   cursor * frontends + shard)
                    if int(doc["gkey"]) % groups == group:
                        new_entries.append(doc)
                    elif is_global:
                        # Another group's request: remember its rid so
                        # the compaction watermark can advance past it
                        # once its done doc lands.
                        other_rids[(shard, cursor)] = doc["rid"]
                    log_next[shard] = cursor + 1
            # Shard scans are sequential; the schedule's enqueue order
            # is the gkey interleave, identical on every rank and
            # every replay.
            new_entries.sort(key=lambda d: d["gkey"])
            if not stop_latched and (not was_busy or step % 8 == 0):
                stop_latched = ctx.kv.get(SCOPE, "stop") is not None
            sdoc = {"new": new_entries, "stop": stop_latched}
            if swap is not None:
                # The poll-and-flip decision travels the SAME broadcast
                # lane as admissions: derived from shared data (the
                # committed manifest + the ranks' prefetch votes) by
                # the group leader alone, obeyed by its group — the
                # serving form of "all ranks agree to deviate".
                sw = swap.leader_step(ctx.kv, scope, group_world, step)
                if sw is not None:
                    sdoc["swap"] = sw
            sdoc_raw = pickle.dumps(sdoc) if not solo else b""
            if not solo:
                ctx.kv.put(scope, f"sched/{group}/{step}", sdoc_raw)
                if step > _SCHED_KEEP:
                    ctx.kv.delete(scope,
                                  f"sched/{group}/{step - _SCHED_KEEP}")
        else:
            sdoc_raw = _fetch(
                ctx, scope, f"sched/{group}/{step}",
                f"schedule for group {group} step {step}")
            sdoc = pickle.loads(sdoc_raw)
        t_sched = time.time()

        # -- serving divergence sentinel: digest the schedule doc this
        # rank is about to obey + its page-table state, compare across
        # the width group (every rank, identical verdict) ----------------
        if health_every and step % health_every == 0:
            _serve_health_check(ctx, scope, group, group_world, step,
                                sdoc_raw, getattr(engine, "paged", None),
                                health_cfg.divergence_action)

        # -- weight hot-swap transitions (between decode steps, before
        # this step's admissions: a flip is version-stamped to exactly
        # this step on every rank) --------------------------------------
        if swap is not None and sdoc.get("swap"):
            swap.apply(sdoc["swap"], engine, ctx.kv, scope, ctx.rank,
                       epoch, step)

        for entry in sdoc["new"]:
            reason = _reject_reason(entry)
            if reason is not None:
                reg.counter("serve.rejected").inc()
                if is_leader:
                    _publish_out(ctx.kv, entry["rid"], tokens=(),
                                 done=True, epoch=epoch,
                                 admitted_step=0, error=reason,
                                 n=entry.get("n"),
                                 shard=entry.get("shard"))
                    if _entry_slot(entry) is not None:
                        n_of[entry["rid"]] = _entry_slot(entry)
                        _mark_done(entry["rid"])
                continue
            if is_leader and _entry_slot(entry) is not None:
                n_of[entry["rid"]] = _entry_slot(entry)
            sched.enqueue(_entry_request(entry))

        # -- admissions: queued -> free slots (and, in paged mode,
        # free PAGES for the head request's worst case), prefill each
        busy_before = sched.active_slots
        admissions = sched.admit(step, can_admit=engine.admission_gate())
        for adm in admissions:
            t_a0 = time.time()
            # Deterministic OOM chaos on the prefill-allocation path:
            # admission is where a real fleet usually dies (a long
            # prompt's prefill is the allocation spike).
            memplane.alloc_guard("assign_slot", rank=ctx.rank)
            tok = engine.admit(
                adm.slot, adm.req.prompt, adm.resume,
                total_len=len(adm.req.prompt) + adm.req.max_new_tokens,
                temperature=adm.req.temperature, top_k=adm.req.top_k,
                rid=adm.req.rid,
            )
            t_a1 = time.time()
            # A recycled slot must never inherit the previous tenant's
            # decode-window mark.
            dspan.pop(adm.slot, None)
            req_traced = tracing and obs_trace.sampled(adm.req.rid,
                                                       t_rate)
            if tracing:
                # Step-lane twin of the request-lane prefill span,
                # UNgated on per-request sampling: the tpot report
                # subtracts named phases from the whole-step span, and
                # an unsampled request's prefill would otherwise
                # masquerade as scheduler residual.
                obs_trace.add_span("serve.steps", "prefill", t_a0, t_a1,
                                   epoch=epoch, step=step,
                                   slot=adm.slot)
            if tok is None:
                # Replay rebuild; its tokens already streamed.  The
                # replay_prefill span marks the second incarnation's
                # restart point on the request's lane.
                if req_traced:
                    obs_trace.add_span(
                        adm.req.rid, "replay_prefill", t_a0, t_a1,
                        epoch=epoch, step=step, slot=adm.slot,
                        resumed=len(adm.resume),
                    )
                    dspan[adm.slot] = (t_a1, len(adm.resume))
                continue
            sched.record(adm.slot, tok)
            rate_win.observe(t_a1, 1)
            if req_traced:
                dspan[adm.slot] = (t_a1, 1)
            # Dedup by rid, like evictions: a request admitted just
            # before a world break whose first out doc never landed is
            # re-admitted as fresh on replay, and survivors' counters
            # persist across epochs — without the set, admitted/ttft
            # would over-count exactly the break-coincident requests.
            if adm.req.rid in totals["admitted_rids"]:
                continue
            totals["admitted_rids"].add(adm.req.rid)
            reg.counter("serve.admitted").inc()
            if busy_before > 0:
                # The continuous-batching moment: this request entered
                # while other slots were mid-decode.
                reg.counter("serve.admitted_while_busy").inc()
            ttft_ms = None
            if adm.req.arrival:
                # Measured at t_a1 — the same instant that closes the
                # prefill span, so the trace report's component sum and
                # this histogram's sample agree by construction.
                ttft_ms = max(t_a1 - adm.req.arrival, 0.0) * 1000.0
                reg.histogram("serve.ttft_ms").observe(ttft_ms)
                if slo_plane is not None:
                    # The SLO accountant sees the SAME sample with its
                    # tenant tag: objectives are judged per
                    # (tenant, class), never on the fleet aggregate.
                    slo_plane.observe_ttft(adm.req.tenant, adm.req.slo,
                                           ttft_ms, t_a1)
            if req_traced:
                # The four spans tile [arrival, first token] exactly:
                # queue_wait ends where this step began, the broadcast
                # span covers the schedule fetch, admit_wait absorbs
                # validation plus same-step earlier prefills, and
                # prefill is the engine.admit call whose argmax IS the
                # first token (first-decode is folded into prefill on
                # the greedy slot engine).
                # The ingest pump appends concurrently with this loop,
                # so an arrival can land INSIDE (t_step0, t_sched]:
                # schedule_broadcast must then start at the arrival,
                # not reach back to t_step0, or the components would
                # over-tile [arrival, first token] and break the
                # exact-sum contract the CI trace gate enforces.
                t_q1 = t_step0
                if adm.req.arrival:
                    t_q1 = min(max(adm.req.arrival, t_step0), t_sched)
                    obs_trace.add_span(
                        adm.req.rid, "queue_wait",
                        min(adm.req.arrival, t_q1), t_q1,
                        epoch=epoch, step=step,
                    )
                obs_trace.add_span(adm.req.rid, "schedule_broadcast",
                                   t_q1, t_sched, epoch=epoch,
                                   step=step)
                obs_trace.add_span(adm.req.rid, "admit_wait", t_sched,
                                   t_a0, epoch=epoch, step=step)
                obs_trace.add_span(
                    adm.req.rid, "prefill", t_a0, t_a1, epoch=epoch,
                    step=step, slot=adm.slot,
                    prompt_len=len(adm.req.prompt),
                    ttft_ms=(round(ttft_ms, 3)
                             if ttft_ms is not None else None),
                )
        evictions = sched.evict_finished()
        for ev in evictions:
            # Paged mode: an eviction returns the slot's pages to the
            # free list immediately — the very next admissions (this
            # step's were already decided) can reuse them.
            engine.release_slot(ev.slot)

        # -- one decode iteration over the live slots ----------------
        active = sorted(sched.active)
        if active:
            t_d0 = time.time()
            memplane.alloc_guard("decode_step", rank=ctx.rank)
            toks = engine.step(active)
            t_d1 = time.time()
            step_ms = (t_d1 - t_d0) * 1000.0
            for slot in active:
                sched.record(slot, toks[slot])
                reg.histogram("serve.tpot_ms").observe(step_ms)
                if slo_plane is not None:
                    req = sched.active[slot].req
                    slo_plane.observe_tpot(req.tenant, req.slo,
                                           step_ms, t_d1)
            rate_win.observe(t_d1, len(active))
            if profiler is not None:
                profiler.observe(t_d1 - t_d0)
            if tracing:
                obs_trace.add_span("serve.steps", "decode_compute",
                                   t_d0, t_d1, epoch=epoch, step=step,
                                   slots=len(active))
                # Per-request decode windows: flush a span to the rid
                # lane every _DECODE_SPAN_TOKENS tokens.
                for slot in active:
                    mark = dspan.get(slot)
                    if mark is None:
                        continue
                    n = len(sched.active[slot].emitted)
                    if n - mark[1] >= _DECODE_SPAN_TOKENS:
                        obs_trace.add_span(
                            sched.active[slot].req.rid, "decode",
                            mark[0], t_d1, epoch=epoch, step=step,
                            tokens=n - mark[1],
                        )
                        dspan[slot] = (t_d1, n)
            post = sched.evict_finished()
            for ev in post:
                engine.release_slot(ev.slot)
            evictions += post

        # -- stream results (leader only writes; peers computed the
        # identical tokens and discard them) -------------------------
        t_p0 = time.time()
        if is_leader:
            for slot in sorted(sched.active):
                act = sched.active[slot]
                n = len(act.emitted)
                # Batched streaming: republishing the full token list
                # every step is O(T^2) signed bytes per request.  The
                # first token goes out immediately (ttft is real), then
                # every stream_every-th; eviction publishes the rest.
                # A world break between publishes costs at most
                # stream_every tokens of deterministic recompute.
                if n <= 1 or n % stream_every == 0:
                    _publish_out(ctx.kv, act.req.rid,
                                 tokens=act.emitted, done=False,
                                 epoch=epoch,
                                 admitted_step=act.admitted_step)
        for ev in evictions:
            if is_leader:
                slot_ref = n_of.get(ev.rid)
                _publish_out(ctx.kv, ev.rid, tokens=ev.tokens,
                             done=True, epoch=epoch,
                             admitted_step=ev.admitted_step,
                             finished_step=step, reason=ev.reason,
                             n=None if slot_ref is None else slot_ref[1],
                             shard=(None if slot_ref is None
                                    else slot_ref[0]),
                             t_done=time.time())
                # Done doc durably published -> this log index can
                # leave the replay set; the watermark advances and the
                # compacted log keys are deleted.
                _mark_done(ev.rid)
            # Dedup by rid: a request a peer finished just before a
            # world break (its done doc never published) is replayed
            # and finished AGAIN on that peer — without the set, its
            # completed/evicted accounting would diverge from the
            # other ranks'.
            if ev.rid not in totals["done_rids"]:
                totals["done_rids"].add(ev.rid)
                reg.counter("serve.evicted").inc()
                totals["completed"] += 1
            mark = dspan.pop(ev.slot, None)
            if tracing and obs_trace.sampled(ev.rid, t_rate):
                t_fin = time.time()
                if mark is not None and len(ev.tokens) > mark[1]:
                    obs_trace.add_span(ev.rid, "decode", mark[0], t_fin,
                                       epoch=epoch, step=step,
                                       tokens=len(ev.tokens) - mark[1])
                obs_trace.add_span(ev.rid, "finish", t_fin, t_fin,
                                   epoch=epoch, step=step,
                                   reason=ev.reason,
                                   tokens=len(ev.tokens),
                                   resumed=ev.resumed)

        # -- gauges + progress beat ----------------------------------
        t_step1 = time.time()
        busy = bool(active or admissions or sdoc["new"] or evictions)
        was_busy = busy
        if tracing and busy:
            if is_leader:
                obs_trace.add_span("serve.steps", "stream_publish",
                                   t_p0, t_step1, epoch=epoch,
                                   step=step)
            obs_trace.add_span("serve.steps", "schedule_broadcast",
                               t_step0, t_sched, epoch=epoch, step=step)
            obs_trace.add_span("serve.steps", "step", t_step0, t_step1,
                               epoch=epoch, step=step,
                               active=len(active))
        reg.gauge("serve.queue_depth").set(sched.queue_depth)
        reg.gauge("serve.active_slots").set(sched.active_slots)
        if sched.qos is not None and busy:
            # Per-tenant plane (tagged series): queue depth now, plus
            # throttle/admission counters advanced by the scheduler's
            # cumulative state (epoch-local) — deltas land in both the
            # registry (for /metrics + --stats-summary) and totals
            # (for the drain summary, which must span epochs).
            for tenant, depth in sched.tenant_depths().items():
                reg.gauge("serve.tenant.queued",
                          tenant=tenant).set(depth)
            for tenant in sorted(sched.throttled):
                delta = sched.throttled[tenant] \
                    - tenant_prev_throttled.get(tenant, 0)
                if delta:
                    tenant_prev_throttled[tenant] = \
                        sched.throttled[tenant]
                    reg.counter("serve.tenant.throttled",
                                tenant=tenant).inc(delta)
                    totals["tenant_throttled"][tenant] = \
                        totals["tenant_throttled"].get(tenant, 0) \
                        + delta
            for tenant in sorted(sched.admitted_tokens):
                delta = sched.admitted_tokens[tenant] \
                    - tenant_prev_admitted.get(tenant, 0)
                if delta:
                    tenant_prev_admitted[tenant] = \
                        sched.admitted_tokens[tenant]
                    reg.counter("serve.tenant.admitted_tokens",
                                tenant=tenant).inc(delta)
                    totals["tenant_admitted_tokens"][tenant] = \
                        totals["tenant_admitted_tokens"].get(
                            tenant, 0) + delta
        # KV occupancy: what the fixed-row pool reserves for the busy
        # slots vs the positions they actually wrote — the waste paged
        # attention (ROADMAP 1) will reclaim.  Rides the loop's
        # existing per-step host sync (one tiny pos read).
        kv = engine.kv_stats(sched.active)
        reg.gauge("serve.kv.allocated_bytes").set(kv["allocated_bytes"])
        reg.gauge("serve.kv.live_bytes").set(kv["live_bytes"])
        reg.gauge("serve.kv.waste_ratio").set(kv["waste_ratio"])
        if "page_size" in kv:
            # Page-granular pool gauges (paged mode): what admission
            # capacity is actually judged in.
            reg.gauge("serve.kv.page_size").set(kv["page_size"])
            reg.gauge("serve.kv.page_free").set(kv["pages_free"])
            reg.gauge("serve.kv.page_used").set(kv["pages_used"])
        if kv["allocated_bytes"] > 0:
            # Busy-step waste aggregate for the drain summary (the
            # gauges only show the LAST step, which at drain is an
            # idle pool): what bench records and the CI waste gate
            # judge the paged fix by.
            totals["kv_busy_steps"] += 1
            totals["kv_waste_sum"] += kv["waste_ratio"]
            totals["kv_alloc_peak"] = max(totals["kv_alloc_peak"],
                                          kv["allocated_bytes"])
            contig = kv.get("contiguous_equiv_bytes", 0)
            if contig > 0:
                # The same step judged by the contiguous design's
                # worst-case reservation — the PR-14 baseline on this
                # very traffic.
                totals["kv_contig_waste_sum"] += (
                    1.0 - kv["live_bytes"] / contig
                )
        if is_global:
            # Pick up OTHER groups' completions (their done docs) so
            # the compaction floor keeps moving even when this group
            # is idle.
            _advance_watermark()
        # Sliding wall-clock window, fed the SAME timestamps the
        # decode-compute spans carry: the digest and the trace report
        # cannot disagree about throughput.
        reg.gauge("serve.tokens_per_sec").set(rate_win.rate(t_step1))
        reg.counter("serve.steps").inc()
        step_tokens = len(active) + sum(
            1 for a in admissions if not a.resume
        )
        totals["tokens"] += step_tokens
        if tok_goodput is not None:
            # Token goodput: tokens actually decoded over slot-step
            # capacity — idle steps count zero tokens on a full pool,
            # which is exactly the wasted capacity the fraction must
            # show.  Published beside the KV-occupancy gauges above.
            tok_goodput.observe_step(step_tokens)
            tok_goodput.publish(reg, t_step1)
        if slo_plane is not None:
            # Burn-rate accounting every step: the two-window alerts
            # land in serve.slo.* (live stream + digest + summary) the
            # same step they start firing.
            slo_plane.publish(reg, t_step1)
        obs_progress.tick()

        if sdoc["stop"] and sched.idle():
            LOG.info("serving drained at epoch %d step %d", epoch, step)
            out = {
                "rank": ctx.rank,
                "epoch": epoch,
                "steps": step,
                "completed": totals["completed"],
                "tokens": totals["tokens"],
                "admitted_while_busy": int(
                    reg.counter("serve.admitted_while_busy").value
                ),
                "frontends": frontends,
            }
            if sched.qos is not None:
                # Per-tenant accounting across every epoch this rank
                # lived through: what the noisy-tenant gate asserts
                # the flooder was throttled by.
                tenants = sorted(
                    set(totals["tenant_throttled"])
                    | set(totals["tenant_admitted_tokens"])
                )
                out["tenants"] = {
                    t: {
                        "throttled":
                            totals["tenant_throttled"].get(t, 0),
                        "admitted_tokens":
                            totals["tenant_admitted_tokens"].get(t, 0),
                    }
                    for t in tenants
                }
            if slo_plane is not None and slo_plane.observed:
                # The SLO verdict travels with the drain summary: what
                # bench records and --stats-summary judge the latency
                # objectives by.
                out["slo"] = slo_plane.summary(time.time())
            if tok_goodput is not None:
                t_now = time.time()
                out["goodput"] = {
                    "token_fraction": round(tok_goodput.fraction(), 6),
                    "tokens_per_slot_sec": round(
                        tok_goodput.per_slot_second(t_now), 4),
                }
                ledger = obs_goodput.get_ledger()
                if ledger is not None:
                    # The wall-clock ledger's story for this rank:
                    # fractions per class + the per-epoch lost-time
                    # attribution.
                    out["goodput"]["wall"] = ledger.summary(t_now)
            if swap is not None:
                # Every rank reports the version it drained on — the
                # single-version chaos gate asserts these agree.
                out["weight_version"] = swap.version
            if profiler is not None:
                out["perf"] = profiler.summary()
            # The rank's memory story rides the drain summary so a
            # `bench.py --serve` record embeds a WORKER-side breakdown
            # (census + per-program compiled bytes + the pool the KV
            # slots pin), not just the launcher's empty view.
            mem = memplane.memory_record()
            mem["kv_pool_bytes"] = engine.kv_stats(())["pool_bytes"]
            out["memory"] = mem
            # KV-occupancy verdict over the whole run (busy steps
            # only — the drained pool is trivially empty): the number
            # the bench record and the CI waste gate judge the paged
            # pool by, against the PR-14 contiguous baseline.
            out["kv"] = {
                "mode": engine.kv_mode,
                "waste_ratio_mean": (
                    totals["kv_waste_sum"]
                    / max(totals["kv_busy_steps"], 1)
                ),
                "contiguous_equiv_waste_mean": (
                    totals["kv_contig_waste_sum"]
                    / max(totals["kv_busy_steps"], 1)
                ),
                "allocated_peak_bytes": totals["kv_alloc_peak"],
                "pool_bytes": mem["kv_pool_bytes"],
            }
            if engine.paged is not None:
                out["kv"]["page_size"] = engine.page_size
                out["kv"]["num_pages"] = engine.num_pages
            if width:
                out["kv"]["width"] = width
                out["group"] = group
            return out
        if not active and not admissions and not sdoc["new"] and is_leader:
            # Idle pacing: peers are paced by the schedule fetch; the
            # leader throttles itself so an empty queue costs a few KV
            # gets per idle_secs, not a busy loop.  The pace BACKS OFF
            # exponentially (cap 16x) — a drained group polling at
            # full rate measurably slows the groups still serving
            # through the shared store; the cost is bounded extra
            # admission latency on an idle fleet.
            idle_streak += 1
            time.sleep(min(idle_secs * (1 << min(idle_streak, 4)),
                           idle_secs * 16))
        else:
            idle_streak = 0


def serve_worker(spec: Optional[dict] = None):
    """The per-rank serving entry: run continuous-batching inference
    until the drain sentinel, surviving world re-formations.

    Launch with :class:`ServeJob` (python API), ``hvdrun --elastic
    --serve`` (CLI), or any elastic launcher wiring that serves this
    function.  Requires the elastic context (the request plane IS the
    launcher's KV store)."""
    import jax.numpy as jnp  # noqa: PLC0415

    from .. import elastic  # noqa: PLC0415
    from ..models.transformer import gpt  # noqa: PLC0415
    from .engine import SlotEngine  # noqa: PLC0415

    merged = dict(DEFAULT_SPEC)
    merged.update(spec or {})
    spec = merged
    ctx = elastic.context()
    if not hasattr(ctx, "kv"):
        raise RuntimeError(
            "serve_worker needs the elastic launcher (the request log "
            "and result streams live in its KV store); run it via "
            "ServeJob or `hvdrun --elastic --serve`"
        )

    obs_progress.set_phase("compile")
    import jax  # noqa: PLC0415

    model = gpt(spec["size"], **spec.get("overrides", {}))
    dummy = jnp.zeros((1, min(8, model.cfg.max_len)), jnp.int32)
    params = model.init(jax.random.PRNGKey(spec["seed"]), dummy)
    width = int(spec.get("width") or 0)
    engine = SlotEngine(
        model.cfg, params, spec["num_slots"], spec.get("max_len"),
        kv_mode=spec.get("kv_mode") or "paged",
        page_size=int(spec.get("page_size") or 16),
        num_pages=spec.get("kv_pages"),
        # spec width 0/1 both mean an unsharded engine; > 1 shard_maps
        # the paged decode over the local device mesh's width axis.
        width=max(width, 1),
        sample_seed=int(spec.get("seed") or 0),
    )
    # The serving MFU accountant: decode-step FLOPs from the compiled
    # artifact's own cost analysis over the measured step time,
    # published live as perf.* gauges (estimate-flagged off-TPU) —
    # the measurement layer ROADMAP item 5 was missing.
    from ..obs.profile import MFUProfiler  # noqa: PLC0415

    flops = engine.step_flops()
    profiler = MFUProfiler(
        flops, jax.devices()[0].device_kind,
        source="cost_analysis" if flops else "unavailable",
    )
    # Memory plane: the engine registered its owner tags (kv_cache,
    # params) at construction; arming the census collector here makes
    # every live-stream snapshot carry mem.* gauges — the serving
    # fleet's HBM story streams to /metrics alongside its latencies.
    memplane.install_census()
    # Weight hot-swap rider (spec["weights_dir"]): versions survive
    # epoch re-formation on this object; version 0 is the seed-derived
    # init params every rank built identically above.
    swap = None
    if spec.get("weights_dir"):
        swap = SwapManager(
            spec["weights_dir"], params,
            poll_steps=int(spec.get("swap_poll_steps") or 16),
        )
        get_registry().gauge("serve.weight_version").set(0)
    totals = {"completed": 0, "tokens": 0,
              "kv_busy_steps": 0, "kv_waste_sum": 0.0,
              "kv_contig_waste_sum": 0.0,
              "kv_alloc_peak": 0, "done_rids": set(),
              "admitted_rids": set(),
              "tenant_throttled": {}, "tenant_admitted_tokens": {}}
    # Goodput + SLO planes (ISSUE 17), built ONCE per process so their
    # sliding windows and lost-time books span world re-formations:
    # the wall-clock ledger (fed by the flight-recorder tap — the
    # rendezvous/phase events this loop already records become
    # transitions), the token-goodput accountant over the slot pool,
    # and the per-tenant burn-rate plane from the spec's objectives.
    obs_goodput.install()
    tok_goodput = obs_goodput.TokenGoodput(spec["num_slots"],
                                           time.time())
    slo_plane = obs_slo.SLOPlane(obs_slo.targets_from_spec(spec))
    from ..exceptions import RankDroppedError  # noqa: PLC0415

    while True:
        try:
            return _serve_epoch(ctx, engine, spec, totals, profiler,
                                swap, slo_plane, tok_goodput)
        except RankDroppedError:
            # Deliberate scale-down (or a shrink past this rank): the
            # launcher re-minted a world without us.  That is a clean
            # release, not a failure — exit 0 with a summary so the
            # monitor banks the result and can re-admit this rank on a
            # later grow.  (RankDroppedError subclasses
            # HorovodShutdownError, so this arm must come first.)
            LOG.info("rank %d released from the serving world "
                     "(scale-down); exiting cleanly", ctx.rank)
            get_registry().counter("serve.released").inc()
            return {
                "rank": ctx.rank,
                "released": True,
                "completed": totals["completed"],
                "tokens": totals["tokens"],
            }
        except HorovodShutdownError as exc:
            LOG.warning("serving world broke (%s); re-forming", exc)
            ctx.notify_world_broken()
            reg = get_registry()
            reg.counter("serve.world_breaks").inc()
            continue


class ServeJob:
    """Python-API driver: one object that owns the launcher side of a
    serving job — KV store, ingest pump, elastic worker fleet — and
    hands back a :class:`ServeClient` for submitting and streaming.

    ::

        job = ServeJob({"size": "nano", "num_slots": 4}, np=2,
                       env={"JAX_PLATFORMS": "cpu"})
        job.start()
        rid = job.client.submit([5, 17, 3], max_new_tokens=8)
        tokens = job.client.result(rid)["tokens"]
        job.stop()

    The elastic fleet runs ``serve_worker`` through the standard
    ``elastic.worker`` entry, so rank death -> blacklist -> respawn ->
    replay all behave exactly as a training job's would.
    """

    def __init__(self, spec: Optional[dict] = None, np: int = 1, *,
                 env: Optional[Dict[str, str]] = None,
                 max_retries: int = 3,
                 min_workers: Optional[int] = None,
                 max_workers: Optional[int] = None,
                 autoscale: Optional[dict] = None,
                 heartbeat_timeout: float = 60.0,
                 progress_timeout: float = 300.0,
                 blacklist_cooldown: float = 0.5,
                 live_stats_secs: Optional[float] = None,
                 live_history: Optional[str] = None,
                 timeout: Optional[float] = None):
        """``autoscale``: a dict of :class:`~.autoscale.AutoscaleConfig`
        overrides (``scale_up_queue``, ``scale_down_idle_secs``, ...)
        turning on load-driven grow/shrink between ``min_workers`` and
        ``max_workers`` (default np); requires live stats, so a missing
        ``live_stats_secs`` defaults to 0.5 when autoscale is on.
        ``spec["weights_dir"]`` arms weight hot-swap on every rank."""
        from ..run.rendezvous import KVStoreServer  # noqa: PLC0415

        self.spec = dict(DEFAULT_SPEC)
        self.spec.update(spec or {})
        self.np = np
        self._env = dict(env or {})
        if autoscale is not None and live_stats_secs is None:
            live_stats_secs = 0.5
        self._launch_kw = dict(
            max_retries=max_retries, min_workers=min_workers,
            max_workers=max_workers, autoscale=autoscale,
            heartbeat_timeout=heartbeat_timeout,
            progress_timeout=progress_timeout,
            blacklist_cooldown=blacklist_cooldown,
            live_stats_secs=live_stats_secs, live_history=live_history,
            job_timeout=timeout,
        )
        self._server = KVStoreServer()
        self._server.start()
        # The sharded front door: F ingest pumps (spec["frontends"])
        # plus the heartbeat supervisor that survives any one pump's
        # death by handing its shards to the lowest survivor.
        self._pump = FrontDoor(
            self._server,
            frontends=int(self.spec.get("frontends") or 1),
        )
        self.addr = f"127.0.0.1:{self._server.port}"
        self.client = ServeClient(self.addr, self._server.secret)
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._results: Optional[Dict[int, Any]] = None
        self._job = None

    @property
    def front_door(self) -> FrontDoor:
        """The sharded ingest plane (chaos hooks ``kill(fid)`` /
        ``poll_takeover()`` and the per-shard ``stats()`` live here)."""
        return self._pump

    @property
    def port(self) -> int:
        return self._server.port

    @property
    def secret(self) -> str:
        return self._server.secret

    def start(self) -> "ServeJob":
        import cloudpickle  # noqa: PLC0415

        from ..run.api import _pickle_func  # noqa: PLC0415
        from ..run.rendezvous import KVStoreClient  # noqa: PLC0415
        from ..run.runner import launch_elastic_job  # noqa: PLC0415

        kv = KVStoreClient(self.addr, self._server.secret)
        kv.put("elastic", "func",
               _pickle_func(serve_worker, (self.spec,), {}))
        self._pump.start()

        def _run():
            try:
                job = launch_elastic_job(
                    [sys.executable, "-m", "horovod_tpu.elastic.worker"],
                    self.np, kv_server=self._server, env=self._env,
                    front_door=self._pump,
                    **self._launch_kw,
                )
                results: Dict[int, Any] = {}
                for rank in job.world:
                    blob = kv.wait("elastic", f"result_{rank}",
                                   timeout=30)
                    ok, value = cloudpickle.loads(blob)
                    if not ok:  # pragma: no cover - monitor aborts first
                        raise RuntimeError(f"rank {rank} raised:\n{value}")
                    results[rank] = value
                self._results = results
                self._job = job
            except BaseException as exc:  # surfaced by stop()/wait()
                self._error = exc

        self._thread = threading.Thread(
            target=_run, name="hvdtpu_serve_job", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 180.0) -> Tuple[Dict[int, Any], Any]:
        """Drain and tear down: raise the stop sentinel, wait for the
        fleet to finish, return ``(per_rank_results, ElasticJobResult)``.
        """
        self.client.stop()
        return self.wait(timeout)

    def wait(self, timeout: float = 180.0) -> Tuple[Dict[int, Any], Any]:
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                raise TimeoutError(
                    f"serving fleet did not drain within {timeout}s"
                )
            self._thread = None
        try:
            if self._error is not None:
                raise self._error
            return self._results or {}, self._job
        finally:
            self.shutdown()

    def shutdown(self) -> None:
        """Release launcher-side resources (idempotent).  When tracing
        is armed, flush this process's spans (the ingest pump's and the
        client's) and merge every rank's span file into the waterfall +
        decomposition report — the python-API twin of the ``hvdrun
        --trace`` end-of-job merge."""
        try:
            self._pump.stop()
        except Exception:  # pragma: no cover - defensive
            pass
        try:
            self._server.stop()
        except Exception:  # pragma: no cover - defensive
            pass
        try:
            import os  # noqa: PLC0415

            from ..utils import env as envmod  # noqa: PLC0415

            raw = self._env.get(envmod.TRACE) \
                or os.environ.get(envmod.TRACE)
            if raw:
                # Explicit path: the dump target may have been armed
                # only in the WORKERS' env dict, not this process's
                # os.environ — the launcher's spans must land either
                # way (its file is tagged ``launcher``, which the
                # aggregators read from the doc, not the filename).
                obs_trace.flush(obs_trace.resolve_dump_path(raw))
                from ..obs import trace_merge  # noqa: PLC0415

                out = trace_merge.merge_glob(raw,
                                             expected_ranks=self.np)
                if out is not None:
                    LOG.info("merged trace -> %s (report %s)",
                             out["waterfall"], out["report"])
        except Exception:  # pragma: no cover - defensive
            pass

    def __enter__(self) -> "ServeJob":
        return self.start()

    def __exit__(self, *exc) -> None:
        try:
            if exc[0] is None:
                self.stop()
        finally:
            self.shutdown()
