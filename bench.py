#!/usr/bin/env python
"""Synthetic ResNet benchmark — the TPU equivalent of the reference's
examples/pytorch_synthetic_benchmark.py (ResNet-50, synthetic images,
img/sec reporting; docs/benchmarks.rst:66-79).

Prints ONE JSON line:
    {"metric": "resnet50_bf16_images_per_sec_per_chip", "value": N,
     "unit": "images/sec/chip", "vs_baseline": N / 103.55,
     "mfu": M, "flops_per_image": F, "device": "..."}

vs_baseline denominator: the only absolute per-accelerator throughput the
reference publishes in-tree — tf_cnn_benchmarks ResNet-101, batch 64,
1656.82 img/sec over 16 Pascal GPUs = 103.55 img/sec/GPU
(docs/benchmarks.rst:29-43).  The ratio therefore mixes model generation
and hardware generation; the scaling-efficiency story lives in the
multi-chip tests.  ``mfu`` is the honest absolute figure: achieved
training FLOP/s (from XLA's compiled cost analysis of the actual step
function) over the chip's peak matmul FLOP/s.

Usage: python bench.py [--model resnet50] [--dtype bf16] [--batch-size 256]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time


# Which phase the bench is in, for watchdog messages and the failure
# sidecar ("import" until the guarded jax import completes;
# _touch_progress advances it at every phase boundary).
_phase_name = "import"


def _budget_left(args) -> float:
    """Seconds until the TOTAL wall-clock budget expires.  The deadline is
    an epoch timestamp minted by the first process and carried through
    every re-exec, so retries and backoff sleeps all draw from one budget
    sized to the driver's window (r04 lesson: per-attempt accounting let
    cumulative attempts overrun the window and land rc=124)."""
    return args.deadline_epoch - time.time()


def _reexec_next_attempt(args) -> None:
    argv = [a for a in sys.argv[1:]
            if not (a.startswith("--retry-attempt")
                    or a.startswith("--deadline-epoch"))]
    argv.append(f"--retry-attempt={args.retry_attempt + 1}")
    argv.append(f"--deadline-epoch={args.deadline_epoch}")
    os.execv(sys.executable,
             [sys.executable, os.path.abspath(__file__)] + argv)


def _write_failure_sidecar(args, why: str, outcome: str) -> None:
    """Persist the failure diagnosis (most importantly WHICH phase was
    stuck) to a sidecar JSON next to the bench.  Three rc=86 rounds
    (BENCH_r03–r05) and the GQA compile hang were never diagnosed
    because the only evidence was an exit code; the next one names its
    phase.  Best-effort: a sidecar write must never mask the exit."""
    try:
        path = os.environ.get("HVDTPU_BENCH_SIDECAR") or os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "bench_last_failure.json",
        )
        doc = {
            "why": why,
            "phase": _phase_name,
            "outcome": outcome,
            "attempt": args.retry_attempt + 1,
            "attempts_allowed": args.attempts + 1,
            "budget_left_secs": round(_budget_left(args), 1),
            "argv": sys.argv[1:],
            "time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        }
        with open(path, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
    except Exception:
        pass


def _next_record_n(record_dir: str) -> int:
    """1 + the highest round number among existing BENCH_*.json records
    (by their ``n`` payload first, filename as fallback)."""
    import glob
    import re

    best = 0
    for path in glob.glob(os.path.join(record_dir, "BENCH_*.json")):
        n = None
        try:
            with open(path) as f:
                n = json.load(f).get("n")
        except (OSError, ValueError):
            pass
        if not isinstance(n, int):
            m = re.search(r"BENCH_r?0*(\d+)", os.path.basename(path))
            n = int(m.group(1)) if m else 0
        best = max(best, n)
    return best + 1


# Auto-written degraded records (give-up path, fatal main() exception,
# CPU fallback) fire only when bench.py runs as THE SCRIPT: importers
# (pytest drives _give_up_or_retry directly, scripts/profile_bench.py)
# must never leave BENCH_*.json droppings in the checkout.
_SCRIPT_MODE = __name__ == "__main__"


def _auto_record(why: str, *, rc: int, phase: str, parsed: dict = None):
    if not _SCRIPT_MODE:
        return None
    try:
        return write_degraded_record(
            why, rc=rc, phase=phase, parsed=parsed,
            record_dir=os.environ.get("HVDTPU_BENCH_RECORD_DIR") or None,
        )
    except Exception:
        return None  # a record write must never mask the real exit


def backend_provenance(probe: bool = False) -> dict:
    """The backend-provenance stamp every record carries: platform,
    device kind, and the JAX_PLATFORMS env — so scripts/perf_gate.py
    can tell "ran on CPU" from "tunnel flaked" without parsing ``why``
    strings.  ``probe=False`` (the degraded/death paths) never IMPORTS
    jax: in the r05 outage mode ``import jax`` itself hangs, and a
    record writer that hangs is worse than a record without a device
    kind — it reads jax state only when the module is already
    resident."""
    prov = {
        "platform": None,
        "device_kind": None,
        "jax_platforms": os.environ.get("JAX_PLATFORMS", ""),
    }
    jax_mod = sys.modules.get("jax")
    if jax_mod is None and probe:
        try:
            import jax as jax_mod  # noqa: PLC0415
        except Exception:
            jax_mod = None
    if jax_mod is not None:
        try:
            dev = jax_mod.devices()[0]
            prov["platform"] = dev.platform
            prov["device_kind"] = dev.device_kind
        except Exception:
            pass
    return prov


def write_degraded_record(why: str, *, rc: int, phase: str,
                          record_dir: str = None, parsed: dict = None):
    """ALWAYS land a BENCH record: when the bench cannot produce a real
    measurement (backend-unavailable exhaustion, watchdog give-up, CPU
    fallback), write a schema-valid ``BENCH_rNN.json`` marked
    ``"degraded": true`` with the failure phase.  r03–r05 produced no
    record at all, so the perf trajectory went dark for three rounds and
    nobody could see it from the records themselves; a degraded record
    keeps the trajectory explicit and is skipped as a regression
    baseline (see attach_regression).  Returns the written path."""
    d = record_dir or os.path.dirname(os.path.abspath(__file__))
    n = _next_record_n(d)
    doc = {
        "n": n,
        "cmd": "python bench.py " + " ".join(sys.argv[1:]),
        "rc": rc,
        "tail": why,
        "parsed": parsed,
        "degraded": True,
        "failure_phase": phase,
        "time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        # Who actually ran (or failed to): the sentinel's basis for
        # separating CPU fallback from real-hardware failure.  Never
        # probes — a degraded record may be written while jax is the
        # very thing that is hanging.
        "provenance": backend_provenance(probe=False),
    }
    # Degraded records carry the memory breakdown too (census says
    # "source: unavailable" when the failure predates jax init): the
    # item-5 sweep reads headroom off EVERY record on the trajectory,
    # and a record that died in warmup still knows what was resident.
    if parsed is None or "memory" not in parsed:
        try:
            from horovod_tpu.obs import memplane  # noqa: PLC0415

            doc["memory"] = memplane.memory_record()
        except Exception:
            pass
    path = os.path.join(d, f"BENCH_r{n:02d}.json")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)
    return path


def _give_up_or_retry(args, why: str) -> None:
    """Common tail for watchdog fires and UNAVAILABLE exceptions: re-exec
    if both a retry and enough budget for a cache-warmed attempt (~3 min)
    remain, else exit 86 immediately so the driver gets a clean rc instead
    of an outer-timeout rc=124."""
    left = _budget_left(args)
    if args.retry_attempt < args.attempts and left > 180:
        _write_failure_sidecar(args, why, outcome="retry")
        print(f"# {why} (attempt {args.retry_attempt + 1} of "
              f"{args.attempts + 1}, {left:.0f}s budget left); re-execing",
              file=sys.stderr, flush=True)
        _reexec_next_attempt(args)  # never returns
    _write_failure_sidecar(args, why, outcome="gave_up")
    _auto_record(why, rc=86, phase=_phase_name)
    print(f"# {why} [phase: {_phase_name}]; no retries or budget left "
          f"— giving up", file=sys.stderr, flush=True)
    os._exit(86)


def _import_guard_args():
    """The budget/retry knobs, parsed WITHOUT the full parser: the
    import guard below must run before anything heavyweight.

    Script-mode only.  Importers (pytest, scripts/profile_bench.py) get
    the static default namespace instead: parse_known_args over a
    FOREIGN argv can still SystemExit (a prefix-ambiguous ``--c...``
    flag, or a type error on an unrelated ``--attempts``), and minting
    ``deadline_epoch`` at import time would start the bench budget
    clock on processes that never bench.
    """
    if __name__ != "__main__":
        return argparse.Namespace(
            attempts=4, total_budget_secs=1440, retry_attempt=0,
            deadline_epoch=float("inf"), cpu=True,
        )
    p = argparse.ArgumentParser(add_help=False)
    p.add_argument("--attempts", type=int, default=4)
    p.add_argument("--total-budget-secs", type=int, default=1440)
    p.add_argument("--retry-attempt", type=int, default=0)
    p.add_argument("--deadline-epoch", type=float, default=0.0)
    p.add_argument("--cpu", action="store_true")
    a, _ = p.parse_known_args()
    if not a.deadline_epoch:
        a.deadline_epoch = time.time() + a.total_budget_secs
    return a


# --- import guard -----------------------------------------------------
# In the r05 outage mode a dead tunnel hangs ``import jax`` ITSELF (the
# axon plugin handshakes at import) — before main(), before the phase
# watchdog arms — so an unguarded bench would silently eat the driver's
# whole window and land rc=124.  A pre-import daemon gives that mode the
# same re-exec/give-up treatment as an in-flight hang: each attempt gets
# a 300s import window, the shared total budget caps the retries, and
# the give-up is a clean exit 86.
_IMPORT_GUARD = _import_guard_args()
_import_ok = threading.Event()


def _import_watchdog() -> None:
    start = time.monotonic()
    while not _import_ok.wait(15):
        if _budget_left(_IMPORT_GUARD) <= 0:
            _give_up_or_retry(
                _IMPORT_GUARD,
                "watchdog: total budget exhausted during jax import")
        if time.monotonic() - start > 300:
            _give_up_or_retry(
                _IMPORT_GUARD,
                "jax import made no progress in 300s (tunnel down?)")


# Script-mode only: importers (pytest, scripts/profile_bench.py) must
# not have a daemon parsing THEIR argv and execv-ing/exiting them.
if __name__ == "__main__" and not _IMPORT_GUARD.cpu:
    threading.Thread(target=_import_watchdog, daemon=True).start()

import jax  # noqa: E402  (guarded: may hang on a dead tunnel)

_import_ok.set()
_phase_name = "init"

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

BASELINE_IMG_PER_SEC_PER_ACCEL = 103.55  # docs/benchmarks.rst:43 (1656.82/16)

# Persistent compilation cache: re-exec retries (and future driver runs on
# this checkout) reuse the serialized executable instead of repaying the
# multi-minute XLA:TPU compile that cost r03/r04 their benchmark windows.
# Must be configured before the first compile; each knob is best-effort so
# a JAX version that lacks one still benches (just cold).
_CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          ".jax_cache")


def _enable_compile_cache() -> None:
    for opt, val in (
        ("jax_compilation_cache_dir", _CACHE_DIR),
        ("jax_persistent_cache_min_compile_time_secs", 0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(opt, val)
        except Exception:
            pass


_enable_compile_cache()

# Peak dense-matmul FLOP/s per chip: ONE table, shared with the live
# MFU profiler (obs/profile.py) so the bench headline and the perf.mfu
# gauge can never disagree about a chip's peak.
from horovod_tpu.obs.profile import PEAK_FLOPS  # noqa: E402


def peak_flops_per_chip(device, dtype: str) -> float:
    peak = PEAK_FLOPS.get(device.device_kind)
    if peak is None:  # CPU dev mode or unknown chip: MFU not meaningful
        return float("nan")
    if dtype == "fp32":
        peak = peak / 4.0  # fp32 matmul ≈ 1/4 MXU rate (bf16x3 + extra)
    return peak


def build_gpt_step(size: str, dtype: str, batch_size: int, seq_len: int,
                   attention: str = "flash", remat: bool = False,
                   flash_block_q: int = 512, flash_block_k: int = 256,
                   kv_heads: int = 0, pos_embedding: str = "learned",
                   moe_experts: int = 0, attention_window: int = 0,
                   overlap_mode: str = "off",
                   grad_bucket_mb: float = None):
    """GPT causal-LM training step (flash attention) — the long-context
    counterpart of the ResNet bench.  Returns ``(step, state, static)``
    like ``build_step``; throughput is reported in tokens/sec/chip."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.models.transformer import gpt
    from horovod_tpu.optim import DistributedOptimizer

    hvd.init()
    n_chips = hvd.num_devices()

    compute_dtype = jnp.float32 if dtype == "fp32" else jnp.bfloat16
    act_store = jnp.float8_e4m3fn if dtype == "fp8" else None
    model = gpt(size, dtype=compute_dtype, max_len=seq_len,
                attention_impl=attention, remat=remat,
                flash_block_q=flash_block_q, flash_block_k=flash_block_k,
                num_kv_heads=kv_heads or None,
                pos_embedding=pos_embedding, moe_experts=moe_experts,
                act_store_dtype=act_store,
                attention_window=attention_window or None)
    vocab = model.cfg.vocab_size

    global_batch = batch_size * n_chips
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(
            0, vocab, size=(global_batch, seq_len + 1)
        ),
        jnp.int32,
    )
    params = model.init(jax.random.PRNGKey(0), tokens[:2, :-1])
    params = hvd.broadcast_parameters(params, root_rank=0)

    def make_loss_fn(toks):
        def loss_fn(p):
            if moe_experts:
                logits, state = model.apply(
                    p, toks[:, :-1], mutable=["losses"]
                )
                aux = 0.01 * sum(jax.tree_util.tree_leaves(state["losses"]))
            else:
                logits = model.apply(p, toks[:, :-1])
                aux = 0.0
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, toks[:, 1:]
            ).mean() + aux

        return loss_fn

    from jax.sharding import PartitionSpec as P

    from horovod_tpu.ops.collectives import shard_map_compat

    mesh = hvd.mesh("flat")
    if overlap_mode != "off":
        # Backward-overlap plane: per-bucket collectives in the
        # cotangent path (+ optional ZeRO-1 sharded update) instead of
        # the end-of-step fused psum DistributedOptimizer runs.
        from horovod_tpu.optim.overlap import OverlapPlan

        plan = OverlapPlan(params, optax.adamw(1e-4), mode=overlap_mode,
                           bucket_mb=grad_bucket_mb, mesh=mesh)
        spec = plan.state_spec()

        def local_step(ostate, toks):
            body = plan.local_step(make_loss_fn(toks))
            ostate, loss = body(ostate)
            # Mean over the DP axis: out_specs P() presents the loss as
            # replicated, so it must actually BE global (see below).
            return ostate, jax.lax.pmean(loss, hvd.DP_AXIS)

        step = jax.jit(
            shard_map_compat(
                local_step,
                mesh=mesh,
                in_specs=(spec, P(hvd.DP_AXIS)),
                out_specs=(spec, P()),
            ),
            donate_argnums=(0,),
        )
        state = (plan.init(params), tokens)
        return step, state, {"n_chips": n_chips,
                             "global_batch": global_batch,
                             "carry_len": 1}

    tx = DistributedOptimizer(optax.adamw(1e-4))
    opt_state = tx.init(params)

    def local_step(params, opt_state, toks):
        loss, grads = jax.value_and_grad(make_loss_fn(toks))(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        # Mean over the DP axis: out_specs P() presents the return value as
        # replicated, so the loss must actually BE global — otherwise the
        # printed final_loss is one shard's and the finite-check could miss
        # a NaN confined to another shard's data.
        loss = jax.lax.pmean(loss, hvd.DP_AXIS)
        return optax.apply_updates(params, updates), opt_state, loss

    step = jax.jit(
        shard_map_compat(
            local_step,
            mesh=mesh,
            in_specs=(P(), P(), P(hvd.DP_AXIS)),
            out_specs=(P(), P(), P()),
        ),
        donate_argnums=(0, 1),
    )
    state = (params, opt_state, tokens)
    return step, state, {"n_chips": n_chips, "global_batch": global_batch,
                         "carry_len": 2}


def build_step(model_name: str, dtype: str, batch_size: int, image_size: int = 224,
               s2d_stem: bool = False, overlap_mode: str = "off",
               grad_bucket_mb: float = None):
    """Build the benchmark's jitted training step and its initial state.

    Shared by bench.py (timing) and scripts/profile_bench.py (tracing) so the
    profiled step is exactly the benchmarked step. Returns
    ``(step, state, static)`` where ``state = (params, batch_stats,
    opt_state, images, labels)`` and ``step`` is the un-lowered jit callable.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvd
    from horovod_tpu import models
    from horovod_tpu.optim import DistributedOptimizer

    hvd.init()
    n_chips = hvd.num_devices()

    compute_dtype = jnp.float32 if dtype == "fp32" else jnp.bfloat16
    act_store = jnp.float8_e4m3fn if dtype == "fp8" else None
    model_cls = {
        "resnet50": models.ResNet50,
        "resnet101": models.ResNet101,
        "resnet18": models.ResNet18,
        "vgg16": models.VGG16,
        "vgg19": models.VGG19,
        "inception3": models.InceptionV3,
    }[model_name]
    extra = {}
    if model_name.startswith("resnet"):
        extra = {"s2d_stem": s2d_stem, "act_store_dtype": act_store}
    elif dtype == "fp8":
        raise SystemExit("--dtype fp8 is resnet-only (e4m3 act storage)")
    model = model_cls(num_classes=1000, compute_dtype=compute_dtype, **extra)

    rng = jax.random.PRNGKey(0)
    global_batch = batch_size * n_chips
    # Inputs in the compute dtype: halves the first conv's HBM read under
    # bf16 and matches what a real bf16 input pipeline would feed.
    images = jnp.asarray(
        np.random.RandomState(0)
        .randn(global_batch, image_size, image_size, 3),
        dtype=compute_dtype,
    )
    labels = jnp.asarray(
        np.random.RandomState(1).randint(0, 1000, size=(global_batch,))
    )

    variables = model.init(rng, images[:2], train=True)
    # VGG has no BN; {} keeps the step signature uniform across models
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    params = hvd.broadcast_parameters(params, root_rank=0)

    def make_loss_fn(batch_stats, images, labels):
        def loss_fn(p):
            logits, mutated = model.apply(
                {"params": p, "batch_stats": batch_stats},
                images,
                train=True,
                mutable=["batch_stats"],
            )
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels
            ).mean()
            return loss, dict(mutated).get("batch_stats", {})

        return loss_fn

    from jax.sharding import PartitionSpec as P

    from horovod_tpu.ops.collectives import shard_map_compat

    mesh = hvd.mesh("flat")
    if overlap_mode != "off":
        # Backward-overlap plane (--overlap {bucket,bucket+zero1}): one
        # fused collective per gradient bucket, emitted inside the
        # backward; zero1 additionally shards the optimizer update.
        from horovod_tpu.optim.overlap import OverlapPlan

        plan = OverlapPlan(params, optax.sgd(0.01, momentum=0.9),
                           mode=overlap_mode, bucket_mb=grad_bucket_mb,
                           mesh=mesh)
        spec = plan.state_spec()

        def local_step(ostate, batch_stats, images, labels):
            body = plan.local_step(
                make_loss_fn(batch_stats, images, labels), has_aux=True
            )
            ostate, loss, new_stats = body(ostate)
            return ostate, new_stats, loss

        step = jax.jit(
            shard_map_compat(
                local_step,
                mesh=mesh,
                in_specs=(spec, P(), P(hvd.DP_AXIS), P(hvd.DP_AXIS)),
                out_specs=(spec, P(), P()),
            ),
            donate_argnums=(0, 1),
        )
        state = (plan.init(params), batch_stats, images, labels)
        return step, state, {"n_chips": n_chips,
                             "global_batch": global_batch,
                             "carry_len": 2}

    tx = DistributedOptimizer(
        optax.sgd(0.01, momentum=0.9), compression=hvd.Compression.none
    )
    opt_state = tx.init(params)

    def local_step(params, batch_stats, opt_state, images, labels):
        loss_fn = make_loss_fn(batch_stats, images, labels)
        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params
        )
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_stats, opt_state, loss

    step = jax.jit(
        shard_map_compat(
            local_step,
            mesh=mesh,
            in_specs=(P(), P(), P(), P(hvd.DP_AXIS), P(hvd.DP_AXIS)),
            out_specs=(P(), P(), P(), P()),
        ),
        donate_argnums=(0, 1, 2),
    )
    state = (params, batch_stats, opt_state, images, labels)
    return step, state, {"n_chips": n_chips, "global_batch": global_batch,
                         "carry_len": 3}


def _is_unavailable(exc: BaseException) -> bool:
    """True for the axon tunnel's transient failure signatures: backend
    init UNAVAILABLE (ate BENCH_r03) or an UNAVAILABLE surfacing from the
    first compile/execute RPC."""
    msg = f"{type(exc).__name__}: {exc}"
    return "UNAVAILABLE" in msg or "Unable to initialize backend" in msg


_watchdog_disarm = threading.Event()
_last_progress = time.monotonic()
_phase_window = 300.0  # init phase default; _touch_progress re-sets it


def _touch_progress(next_window: float = 300.0,
                    phase: str = None) -> None:
    """Mark a phase boundary (build / compile / warmup done) and set the
    NEXT phase's hang window.  The watchdog only fires when the current
    phase exceeds its own window, so a long but progressing run is never
    killed; the compile phase gets a wider window than init/warmup
    because legitimately slow XLA:TPU compiles exist (>10 min observed)
    while a healthy backend init never takes more than ~2 min.

    ``phase`` names the phase being ENTERED; the watchdog's fire message
    and the failure sidecar carry it, so an rc=86 names the phase that
    hung instead of leaving the next GQA-style compile hang a mystery.
    """
    global _last_progress, _phase_window, _phase_name
    _last_progress = time.monotonic()
    _phase_window = next_window
    if phase is not None:
        _phase_name = phase


def _retry_exec(args, exc: BaseException) -> None:
    """Re-exec this script with a clean process (JAX caches a failed
    backend for the life of the process, so in-process retry is useless).
    Backoff doubles from 15s but is capped at 60s and never sleeps past
    the total deadline."""
    _watchdog_disarm.set()  # the backoff sleep is not a hang
    delay = min(15 * (2 ** args.retry_attempt), 60)
    if _budget_left(args) - delay <= 180:
        # Backing off would eat the budget the retry itself needs:
        # skip the sleep and go straight to the retry/give-up decision.
        delay = 0
    print(
        f"# axon UNAVAILABLE (attempt {args.retry_attempt + 1} of "
        f"{args.attempts + 1}): {str(exc)[:200]}; retrying in {delay:.0f}s",
        file=sys.stderr, flush=True,
    )
    time.sleep(delay)
    _give_up_or_retry(args, "axon UNAVAILABLE")


def _arm_watchdog(args) -> None:
    """A half-down tunnel HANGS inside backend init / the first compile
    rather than raising (observed: jax.devices() blocked >15 min), so the
    except-based retry never fires.  A daemon thread re-execs the whole
    process when the current phase has made no progress for its window —
    execv replaces the process even while the main thread is stuck in a C
    call.  Per-phase windows (init 300s / compile args.watchdog_secs /
    warmup 300s) keep legitimately slow compiles alive while catching a
    dead-tunnel init fast; every window is additionally clamped to the
    remaining total budget."""
    if args.cpu or args.watchdog_secs <= 0:
        return

    def _fire():
        while True:
            time.sleep(15)
            if _watchdog_disarm.is_set():
                return
            if _budget_left(args) <= 0:
                _give_up_or_retry(args, "watchdog: total budget exhausted")
            # Phase-elapsed vs the phase's OWN window only — clamping the
            # window to remaining budget would kill a still-progressing
            # compile that fits both its window and the budget.
            if time.monotonic() - _last_progress <= _phase_window:
                continue
            _give_up_or_retry(
                args,
                f"watchdog: no progress in phase '{_phase_name}' for "
                f"{_phase_window:.0f}s")

    threading.Thread(target=_fire, daemon=True).start()


def _run_serve_load(args, np_: int, width: int, on_cpu: bool,
                    frontends: int = 1) -> dict:
    """One fleet under one open-loop workload: launch ``np_`` serving
    ranks (``width`` >= 1 turns on the width-sharded fleet — np_//width
    independent serving groups, each rank's paged decode shard_mapped
    over ``width`` local devices; ``frontends`` > 1 shards the front
    door into that many rid-hash-partitioned ingest pumps), submit the
    deterministic mixed-length request schedule, and measure ttft/tpot/
    tokens-per-sec on the client clock.  Returns the raw measurement
    dict the record (or the scaling comparison) embeds."""
    import threading

    from horovod_tpu.serve import ServeJob

    overrides = dict(
        num_layers=2, num_heads=4, emb_dim=64, max_len=256,
        vocab_size=512, attention_impl="reference", dtype=jnp.float32,
    )
    spec = {"size": "nano", "overrides": overrides, "seed": 0,
            "num_slots": args.serve_slots, "idle_secs": 0.005,
            # Stream batching at 8: the first token still publishes
            # immediately (ttft is real), but steady-state streaming
            # costs half the signed puts — on a CPU fleet the store
            # roundtrips are a measurable slice of the step.
            "stream_every": 8,
            "kv_mode": args.serve_kv_mode,
            "page_size": args.serve_page_size,
            "width": width,
            "frontends": max(int(frontends), 1)}
    if args.serve_kv_pages:
        spec["kv_pages"] = args.serve_kv_pages
    env = {"JAX_PLATFORMS": "cpu"} if on_cpu else {}
    if on_cpu:
        # Single-threaded eigen per worker: the serving model is tiny,
        # so the default all-cores threadpool buys nothing per process
        # and makes concurrent fleet members thrash each other —
        # exactly what a scaling comparison must not measure.  Width
        # shards additionally need `width` local devices (faked the
        # same way the test harness does).
        flags = ["--xla_cpu_multi_thread_eigen=false"]
        if width > 1:
            flags.append(
                f"--xla_force_host_platform_device_count={width}"
            )
        env["XLA_FLAGS"] = " ".join(flags)
    n_req = args.serve_requests
    # Mixed-length workload, identical across fleets (and across the
    # two legs of a --serve-scaling comparison): prompt lengths span
    # 2-6 KV pages at the default page size, so the paged pool's
    # partial-last-page waste is measured on realistic traffic, not on
    # single-page stubs.
    rng = np.random.RandomState(42)
    gaps = rng.exponential(1.0 / args.serve_rate, n_req)
    prompts = [rng.randint(0, 512, rng.randint(16, 49)).tolist()
               for _ in range(n_req)]
    budgets = [int(rng.randint(16, 33)) for _ in range(n_req)]

    job = ServeJob(
        spec, np=np_, env=env or None,
        timeout=max(_budget_left(args) - 60, 120),
    ).start()
    # Warmup OUTSIDE the measured window: one request per prompt-length
    # bucket the workload will hit (16/32/64) drives every rank through
    # its decode-step + per-bucket assign compiles.  Without this the
    # measurement is compile-dominated and a fleet comparison measures
    # XLA, not serving.  A width-sharded fleet partitions the log
    # round-robin across its groups, so each bucket is submitted
    # ``groups`` consecutive times — consecutive log indices land one
    # on every group, whatever the group count — or a group would pay
    # its first bucket-b compile mid-measurement (~500ms observed, a
    # third of the whole window).
    groups = max(np_ // width, 1) if width else 1
    warm = []
    for warm_len in (10, 20, 40):
        for _ in range(groups):
            warm.append(job.client.submit([7] * warm_len,
                                          max_new_tokens=9))
    for rid in warm:
        job.client.result(rid, timeout=max(_budget_left(args) - 60, 120))
    submit_t: dict = {}
    rids: list = []
    fd_stats: dict = {}

    def _submitter():
        t = time.perf_counter()
        for i in range(n_req):
            t += gaps[i]
            now = time.perf_counter()
            if t > now:
                time.sleep(t - now)
            rid = job.client.submit(prompts[i],
                                    max_new_tokens=budgets[i])
            submit_t[rid] = time.perf_counter()
            rids.append(rid)

    try:
        sub = threading.Thread(target=_submitter, daemon=True)
        t_start = time.perf_counter()
        t_start_wall = time.time()
        sub.start()
        first_t: dict = {}
        done: dict = {}
        deadline = time.monotonic() + max(_budget_left(args) - 90, 90)
        while len(done) < n_req:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"serve bench: {len(done)}/{n_req} requests "
                    f"finished before the budget ran out"
                )
            for rid in list(rids):
                if rid in done:
                    continue
                doc = job.client.poll(rid)
                if doc is None:
                    continue
                if doc.get("tokens") and rid not in first_t:
                    first_t[rid] = time.perf_counter()
                if doc.get("done"):
                    done[rid] = (time.perf_counter(),
                                 len(doc.get("tokens", [])),
                                 doc.get("t_done"))
            # A full sweep already costs ~0.5ms of server time per
            # pending rid; sweeping again immediately would make the
            # measuring client the store's biggest tenant and depress
            # exactly the number being measured.
            time.sleep(0.01)
        sub.join(timeout=10)
        total_tokens = sum(n for _, n, _ in done.values())
        # Throughput from SERVER-side completion stamps (the leaders'
        # eviction wall clocks) against the client's submit wall clock
        # — one host in this harness, so the clocks agree.  The
        # client's own polling cadence would otherwise be the largest
        # term in a fleet comparison (poll-granularity error per
        # request exceeded the per-step decode time).
        server_ends = [t for _, _, t in done.values() if t]
        if server_ends:
            elapsed = max(server_ends) - t_start_wall
        else:  # pre-t_done servers: fall back to the client clock
            elapsed = max(t for t, _, _ in done.values()) - t_start
        # SUSTAINED rate: tokens completed in the p20->p80 completion
        # window over that window's duration — the steady-state number
        # with the ramp (first admissions/prefills) and the drain tail
        # (last <slots requests trickling out) excluded.  Makespan
        # throughput stays the headline `value`; the scaling ratio is
        # judged on sustained (both fleets fully busy), which is what
        # "sustains N tokens/sec" means.
        sustained = None
        if len(server_ends) >= 10:
            ends = sorted(
                (t, n) for t, n in
                ((t, n) for _, n, t in done.values() if t)
            )
            lo = ends[int(len(ends) * 0.2)][0]
            hi = ends[int(len(ends) * 0.8)][0]
            mid_tokens = sum(n for t, n in ends if lo < t <= hi)
            if hi > lo:
                sustained = mid_tokens / (hi - lo)
        ttft = [
            (first_t[r] - submit_t[r]) * 1000.0
            for r in rids if r in first_t
        ]
        tpot = [
            (done[r][0] - first_t[r]) / max(done[r][1] - 1, 1) * 1000.0
            for r in rids if r in first_t and done[r][1] > 1
        ]
        results, _ejob = job.stop()
        # Per-shard ingest accounting from the front door itself —
        # counters survive stop(); a lopsided split here means the rid
        # hash is mixing badly, not that a pump is slow.
        fd_stats = job.front_door.stats()
    finally:
        job.shutdown()

    def pct(xs, q):
        return round(float(np.percentile(xs, q)), 2) if xs else None

    throughput = total_tokens / max(elapsed, 1e-9)
    meas = {
        "np": np_,
        "width": width,
        "frontends": max(int(frontends), 1),
        "groups": max(np_ // width, 1) if width else 1,
        "slots": args.serve_slots,
        "requests": n_req,
        "arrival_rate_per_sec": args.serve_rate,
        "total_tokens": total_tokens,
        "tokens_per_sec": round(throughput, 2),
        "sustained_tokens_per_sec": (round(sustained, 2)
                                     if sustained else None),
        "ttft_ms": {"p50": pct(ttft, 50), "p90": pct(ttft, 90),
                    "p99": pct(ttft, 99)},
        "tpot_ms": {"p50": pct(tpot, 50), "p90": pct(tpot, 90),
                    "p99": pct(tpot, 99)},
    }
    if fd_stats:
        meas["frontdoor"] = {
            "frontends": fd_stats.get("frontends"),
            "fd_epoch": fd_stats.get("fd_epoch"),
            "takeovers": fd_stats.get("takeovers"),
            "ingested_by_shard": {
                str(s): n for s, n in sorted(
                    (fd_stats.get("ingested_by_shard") or {}).items())
            },
        }
    ranks = sorted(results or {})
    meas["_results"] = results or {}
    if ranks:
        meas["completed_per_rank"] = {
            str(r): results[r]["completed"] for r in ranks
        }
        # Continuous batching actually happened: admissions that entered
        # while other slots were mid-decode (max across ranks — the
        # counts are identical by the schedule invariant).
        meas["admitted_while_busy"] = max(
            results[r].get("admitted_while_busy", 0) for r in ranks
        )
        # KV-occupancy verdict (worst rank): the paged pool's measured
        # waste and, recomputed on the SAME traffic, what the PR-10
        # contiguous reservation would have wasted (the PR-14 baseline).
        kvs = [results[r]["kv"] for r in ranks if results[r].get("kv")]
        if kvs:
            meas["kv"] = {
                "mode": kvs[0].get("mode"),
                "waste_ratio_mean": round(max(
                    k.get("waste_ratio_mean", 0.0) for k in kvs), 4),
                "contiguous_equiv_waste_mean": round(max(
                    k.get("contiguous_equiv_waste_mean", 0.0)
                    for k in kvs), 4),
                "page_size": kvs[0].get("page_size"),
                "num_pages": kvs[0].get("num_pages"),
                "pool_bytes": kvs[0].get("pool_bytes"),
            }
    return meas


def _serve_bench(args) -> int:
    """``--serve``: open-loop serving benchmark through the
    continuous-batching plane (horovod_tpu/serve/).

    A deterministic Poisson arrival process (seeded exponential gaps at
    ``--serve-rate`` req/s) submits ``--serve-requests`` mixed-length
    prompts AT SCHEDULE — open-loop, so queueing under load is measured
    instead of hidden by back-pressure — while a fine-grained poller
    stamps each request's first token and completion on the client
    clock.  The record lands ttft/tpot percentiles, end-to-end
    tokens/sec, and the paged pool's KV-waste verdict against the
    contiguous-equivalent baseline; ``--serve-scaling`` additionally
    runs the SAME workload at np=w and np=2w (w = --serve-width or 1)
    and embeds the fleet-scaling ratio — the width-sharded fleet's "np
    multiplies tokens/sec" claim measured, not asserted.  On CPU it is
    a degraded trajectory placeholder like every other CPU bench
    number (write_degraded_record via _auto_record)."""
    _touch_progress(next_window=max(args.watchdog_secs, 300),
                    phase="serve")
    on_cpu = args.cpu or jax.devices()[0].platform == "cpu"
    width = int(args.serve_width or 0)
    fd = max(int(getattr(args, "serve_frontends", 0) or 0), 0)
    frontdoor_scaling = None
    if fd > 1 and not args.serve_scaling:
        # Front-door comparison (PR-16): the SAME saturating trace
        # through a single-pump door and through an F-way sharded one.
        # On one host this measures ingest-path structure (per-shard
        # cursors, no cross-shard serialization), not network fan-in —
        # labeled as such below, same honesty rule as --serve-scaling.
        single = _run_serve_load(args, args.serve_np, width, on_cpu,
                                 frontends=1)
        single.pop("_results", None)
        main = _run_serve_load(args, args.serve_np, width, on_cpu,
                               frontends=fd)
        results = main.pop("_results")
        scaling = None
        ratio = (main["tokens_per_sec"]
                 / max(single["tokens_per_sec"], 1e-9))
        frontdoor_scaling = {
            "f1": {k: v for k, v in single.items()
                   if k != "completed_per_rank"},
            f"f{fd}": {k: v for k, v in main.items()
                       if k != "completed_per_rank"},
            "tokens_per_sec_ratio": round(ratio, 3),
            "provenance": ("cpu-mesh structural evidence"
                           if on_cpu else "device measurement"),
        }
    elif args.serve_scaling:
        w = max(width, 1)
        attempts = max(int(args.serve_scaling_attempts), 1)
        # Best-of-N per leg: this host's scheduler sometimes lands two
        # hot worker threads on SMT siblings and the whole run (both
        # groups alike) decodes at half speed — a bimodal environment
        # artifact, observed on single-fleet runs too.  Best-of is the
        # standard mitigation and is labeled in the record.
        def _rate(m):
            return m["sustained_tokens_per_sec"] or m["tokens_per_sec"]

        base = max((_run_serve_load(args, w, w, on_cpu)
                    for _ in range(attempts)), key=_rate)
        doubled = max((_run_serve_load(args, 2 * w, w, on_cpu)
                       for _ in range(attempts)), key=_rate)
        ratio = _rate(doubled) / max(_rate(base), 1e-9)
        # The basis must describe what was ACTUALLY divided: a leg with
        # too few server-side completion stamps falls back to makespan
        # throughput, and a mislabeled record would judge the >=1.7x
        # claim on a basis it misdescribes.
        both_sustained = (base["sustained_tokens_per_sec"] is not None
                          and doubled["sustained_tokens_per_sec"]
                          is not None)
        basis = ("sustained (p20-p80 completion window)"
                 if both_sustained else "makespan tokens_per_sec")
        main, results = doubled, doubled.pop("_results")
        base.pop("_results", None)
        scaling = {
            "np_w": {k: v for k, v in base.items()
                     if k != "completed_per_rank"},
            "np_2w": {k: v for k, v in doubled.items()
                      if k != "completed_per_rank"},
            "tokens_per_sec_ratio": round(ratio, 3),
            "ratio_basis": basis,
            "best_of": attempts,
            # Honest provenance: on the CPU mesh each rank simulates
            # its whole device set, so the ratio is structural evidence
            # of the fleet partition (independent groups over the log),
            # not a hardware throughput claim.
            "provenance": ("cpu-mesh structural evidence"
                           if on_cpu else "device measurement"),
        }
    else:
        main = _run_serve_load(args, args.serve_np, width, on_cpu,
                               frontends=max(fd, 1))
        results = main.pop("_results")
        scaling = None

    out = {
        "metric": "serve_nano_tokens_per_sec",
        "value": main["tokens_per_sec"],
        "unit": "tokens/sec",
        "device": jax.devices()[0].device_kind,
        "provenance": backend_provenance(probe=True),
        "serve": {k: v for k, v in main.items()},
    }
    if scaling is not None:
        out["serve"]["scaling"] = scaling
    if frontdoor_scaling is not None:
        out["serve"]["frontdoor_scaling"] = frontdoor_scaling
    ranks = sorted(results or {})
    if ranks:
        # Decode-step MFU from the serving ranks' own cost_analysis()
        # accounting (estimate-flagged on CPU) — the leader's view; the
        # numbers are near-identical across ranks by the identical-
        # schedule invariant.
        perf = results[ranks[0]].get("perf")
        if perf:
            out["perf"] = perf
        # Worker-side memory breakdown (obs/memplane.py): census +
        # per-program compiled bytes + the KV pool's resident
        # footprint — rank 0's view stands in for all.
        mem = results[ranks[0]].get("memory")
        if mem:
            out["memory"] = mem
    # Decode-step anatomy from the leader's perf summary (no training
    # collectives on the serve path, so the split is compute vs host
    # gap) — attached before the degraded-record path, same rule as the
    # training bench.
    try:
        from horovod_tpu.obs.anatomy import attach_anatomy  # noqa: PLC0415

        perf = out.get("perf") or {}
        attach_anatomy(
            out, step_ms=perf.get("step_ms"), mfu=perf.get("mfu"),
            flops_per_step=perf.get("flops_per_step"),
            device_kind=jax.devices()[0].device_kind,
        )
    except Exception:
        pass
    if on_cpu:
        out["degraded"] = True
    # Sentinel BEFORE the record write, same rule as the training path.
    attach_regression(out)
    if on_cpu:
        _auto_record("cpu fallback: numbers not comparable to TPU "
                     "records", rc=0, phase="serve-cpu-fallback",
                     parsed=out)
    _watchdog_disarm.set()
    print(json.dumps(out), flush=True)
    return 0


def attach_regression(out: dict, record_dir: str = None,
                      threshold_pct: float = 5.0) -> dict:
    """Trend-aware regression sentinel over the ``BENCH_*.json``
    trajectory (obs/trend.py owns the record reading/classification).

    The baseline is the EWMA over the last K non-degraded records
    matching this run's metric AND device (a CPU dev run must never be
    judged against a TPU record) — one lucky round no longer owns the
    bar.  The embedded delta carries ``baseline_records`` provenance
    (which records the EWMA folded), ``stale_records_skipped`` counts
    the newer records with no comparable measurement (the VERDICT r5
    situation, self-announcing), ``degraded_records_skipped`` counts
    the fallback records the baseline refused, and ``regression`` flags
    a value drop > ``threshold_pct``% vs the EWMA.  Every record also
    gets the ``trend`` stamp — the degraded-streak verdict ("N
    consecutive records without a real measurement, last real is rX")
    rides in the measurement itself.

    Best-effort by construction: any failure here must never sink the
    measurement that just survived the watchdog gauntlet.
    """
    try:
        from horovod_tpu.obs import trend as _trend  # noqa: PLC0415

        d = record_dir or os.path.dirname(os.path.abspath(__file__))
        records = _trend.load_bench_records(d)
        stamp = _trend.trend_stamp(d)
        if stamp is not None:
            out["trend"] = stamp
        key = (out.get("metric"), out.get("device"))
        newest = None  # newest real matching record: (fname, parsed)
        skipped = 0
        degraded_skipped = 0
        for _, fname, doc in reversed(records):
            parsed = _trend.parsed_payload(doc)
            # Degraded records (write_degraded_record) keep the
            # trajectory visible but are never a regression baseline: a
            # failed round must not reset the bar a real measurement is
            # judged against.
            if _trend.classify(doc) == "degraded":
                degraded_skipped += 1
                continue
            if (isinstance(parsed, dict)
                    and _trend.scenario_key(parsed) == key):
                newest = (fname, parsed)
                break
            skipped += 1
        ewma = _trend.ewma_baseline(records, *key)
        if newest is None or ewma is None:
            out["baseline_record"] = {
                "file": None,
                "stale_records_skipped": skipped,
                "degraded_records_skipped": degraded_skipped,
            }
            out["regression"] = None  # nothing comparable to regress from
            return out
        fname, parsed = newest
        deltas = {}
        for key_name in ("value", "mfu"):
            old, new = ewma.get(key_name), out.get(key_name)
            if (isinstance(old, (int, float)) and isinstance(new, (int, float))
                    and old):
                deltas[key_name] = {
                    "baseline": old,
                    "pct": round((new - old) / old * 100.0, 2),
                }
        # Peak device-memory delta, INFORMATIONAL only: memory growth
        # is worth seeing next to the perf number (a +20% throughput
        # that costs 2x HBM changes the item-5 bucket-size choice), but
        # it never flips the regression flag — the flag means "the
        # measurement got worse", and more bytes is not that.
        def _peak(doc):
            dev = ((doc.get("memory") or {}).get("census") or {}
                   ).get("device") or {}
            return dev.get("peak_bytes") or (
                (doc.get("memory") or {}).get("census") or {}
            ).get("total_bytes")

        old_peak, new_peak = _peak(parsed), _peak(out)
        if (isinstance(old_peak, (int, float)) and old_peak
                and isinstance(new_peak, (int, float))):
            deltas["peak_bytes"] = {
                "baseline": old_peak,
                "pct": round((new_peak - old_peak) / old_peak * 100.0, 2),
                "informational": True,
            }
        out["baseline_record"] = {
            "file": fname,
            "baseline_records": ewma["records"],
            "ewma": {"k": ewma["k"], "alpha": ewma["alpha"],
                     "count": ewma["count"]},
            "stale_records_skipped": skipped,
            "degraded_records_skipped": degraded_skipped,
            "stale": skipped > 0,
        }
        out["deltas"] = deltas
        out["regression"] = bool(
            deltas.get("value", {}).get("pct", 0.0) < -threshold_pct
        )
    except Exception:
        out.setdefault("regression", None)
    return out


def collect_engine_gauges() -> dict:
    """Snapshot the autotuner + negotiation-skip gauges out of the
    metrics registry (empty on the world==1 jit path, which never starts
    the engine) — every BENCH record carries what the tuner and the
    replay fast path were doing when the number was taken."""
    try:
        from horovod_tpu.obs import get_registry

        wanted_prefixes = ("autotune.", "overlap.", "perf.", "mem.",
                           "serve.kv.", "health.")
        wanted_names = {
            "engine.negotiation_skip_rate",
            "engine.cache_hit_rate",
            "engine.stats.cycles",
            "engine.stats.negotiated_cycles",
            "engine.stats.replay_cycles",
            "engine.stats.replay_epochs",
            "engine.stats.replay_breaks",
            # Two-fabric counters (multislice): what the DCN actually
            # carried vs ICI, and the DCN wire compression factor.
            "engine.dcn_bytes",
            "engine.ici_bytes",
            "engine.dcn_compression_ratio",
        }
        out = {}
        bucket_bytes = []
        health_alerts = 0.0
        for m in get_registry().snapshot():
            name = m.get("name", "")
            if m.get("tags"):
                # Per-bucket byte gauges are the one tagged family a
                # BENCH record wants whole: the next TPU round needs to
                # attribute an MFU delta to the bucket shape, not just
                # the bucket count.
                if name == "overlap.bucket_bytes":
                    tag = m["tags"].get("bucket")
                    if tag is not None and str(tag).isdigit():
                        bucket_bytes.append((int(tag), m.get("value")))
                elif name == "health.alerts":
                    # Rising-edge alert counters are per-class; the
                    # BENCH record wants the one number "did the
                    # numerics plane object during this measurement".
                    health_alerts += float(m.get("value") or 0)
                continue
            if name == "health.grad_norm_hist":
                # Histogram: the record carries its p50 (the satellite
                # the hardware campaign attaches numerics evidence by).
                if m.get("p50") is not None:
                    out["health.grad_norm_p50"] = m["p50"]
                continue
            if name in wanted_names or name.startswith(wanted_prefixes):
                out[name] = m.get("value")
        if health_alerts:
            out["health.alerts_total"] = health_alerts
        if bucket_bytes:
            out["overlap_bucket_bytes"] = [
                v for _, v in sorted(bucket_bytes)
            ]
        if "overlap.mode" in out:
            try:
                from horovod_tpu.optim.overlap import MODES

                out["overlap_mode"] = MODES[int(out["overlap.mode"])]
            except Exception:
                pass
        return out
    except Exception:
        return {}


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="resnet50",
                        choices=["resnet50", "resnet101", "resnet18",
                                 "vgg16", "vgg19", "inception3",
                                 "gpt-small", "gpt-medium", "gpt-large"])
    parser.add_argument("--dtype", default="bf16",
                        choices=["bf16", "fp32", "fp8"],
                        help="compute dtype (params/accumulators stay fp32; "
                        "fp8 = bf16 compute with e4m3 activation storage)")
    parser.add_argument("--batch-size", type=int, default=None,
                        help="per-chip batch (default: 128 resnet, 8 gpt)")
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--seq-len", type=int, default=1024,
                        help="sequence length for the gpt models")
    parser.add_argument("--attention", default="flash",
                        choices=["flash", "reference"],
                        help="gpt attention schedule (flash = Pallas kernel)")
    parser.add_argument("--remat", action="store_true",
                        help="remat transformer blocks (dots-saveable "
                        "policy): trades recompute for HBM -> larger batch")
    parser.add_argument("--flash-block-q", type=int, default=512,
                        help="flash attention q tile (measured winner on "
                        "v5e: 512; docs/performance.md round-5 sweep)")
    parser.add_argument("--flash-block-k", type=int, default=256)
    parser.add_argument("--kv-heads", type=int, default=0,
                        help="GQA/MQA kv heads for the gpt models "
                        "(0 = MHA)")
    parser.add_argument("--pos-embedding", default="learned",
                        choices=["learned", "rope"])
    parser.add_argument("--moe-experts", type=int, default=0,
                        help="replace gpt MLPs with this many experts "
                        "(0 = dense); aux loss folded into the objective")
    parser.add_argument("--attention-window", type=int, default=0,
                        help="sliding-window attention (last W keys; "
                        "0 = full causal); flash-only, banded tiles "
                        "skipped in fwd+bwd")
    parser.add_argument("--iters", type=int, default=10,
                        help="timed steps (the medium is +-3% run-to-run; "
                        "more iters buys nothing but window risk)")
    parser.add_argument("--warmup", type=int, default=5)
    parser.add_argument("--s2d-stem", action="store_true",
                        help="space-to-depth stem (MLPerf TPU recipe)")
    parser.add_argument("--cpu", action="store_true",
                        help="force CPU (dev mode; numbers not comparable)")
    parser.add_argument("--overlap", default=None,
                        choices=["off", "bucket", "bucket+zero1"],
                        help="backward-overlap gradient plane: bucket = "
                        "in-backward bucketed allreduce, bucket+zero1 "
                        "additionally reduce-scatter-shards the "
                        "optimizer update (default: HVDTPU_OVERLAP or "
                        "off)")
    parser.add_argument("--grad-bucket-mb", type=float, default=None,
                        help="gradient bucket size cap for --overlap "
                        "(default: HVDTPU_GRAD_BUCKET_MB or 16; sweep "
                        "candidates: autotune.grad_bucket_candidates)")
    parser.add_argument("--num-slices", type=int, default=0,
                        help="force a multislice partition "
                        "(HVDTPU_NUM_SLICES) so the record embeds the "
                        "per-fabric byte counters; 0 = discovered "
                        "topology")
    parser.add_argument("--serve", action="store_true",
                        help="serving-plane benchmark: open-loop "
                             "arrivals through the continuous-batching "
                             "scheduler; lands ttft/tpot percentiles "
                             "and tokens/sec instead of a training "
                             "step time")
    parser.add_argument("--serve-np", type=int, default=1,
                        help="serving ranks (elastic fleet size)")
    parser.add_argument("--serve-slots", type=int, default=4,
                        help="decode slot pool size per rank")
    parser.add_argument("--serve-requests", type=int, default=16,
                        help="requests in the open-loop arrival trace")
    parser.add_argument("--serve-rate", type=float, default=4.0,
                        help="mean arrival rate, requests/sec "
                             "(seeded exponential gaps)")
    parser.add_argument("--serve-width", type=int, default=0,
                        help="width-sharded fleet (0 = replicated): "
                             "np//width serving groups, each rank's "
                             "paged decode shard_mapped over width "
                             "devices")
    parser.add_argument("--serve-kv-mode", default="paged",
                        choices=["paged", "contiguous"],
                        help="KV layout (paged = block tables; "
                             "contiguous = PR-10 worst-case rows)")
    parser.add_argument("--serve-page-size", type=int, default=8,
                        help="KV page size in token rows (paged mode)")
    parser.add_argument("--serve-kv-pages", type=int, default=0,
                        help="KV page-pool size (0 = worst case)")
    parser.add_argument("--serve-scaling", action="store_true",
                        help="run the same workload at np=w and np=2w "
                             "(w = --serve-width or 1) and embed the "
                             "fleet-scaling tokens/sec ratio")
    parser.add_argument("--serve-scaling-attempts", type=int, default=2,
                        help="best-of-N runs per scaling leg (host-"
                             "scheduler noise mitigation; labeled in "
                             "the record)")
    parser.add_argument("--frontends", type=int, default=0,
                        dest="serve_frontends",
                        help="sharded front door: run the workload with "
                             "F frontend ingest shards; F>1 also runs "
                             "an F=1 leg on the same trace and embeds "
                             "the ingest comparison + per-shard "
                             "counters in the record")
    parser.add_argument("--campaign", default=None, metavar="SPEC",
                        help="run a resumable benchmark campaign from "
                        "this sweep-spec JSON instead of one "
                        "measurement (delegates to python -m "
                        "horovod_tpu.bench.campaign; see "
                        "docs/performance.md 'Running a campaign')")
    parser.add_argument("--attempts", type=int, default=4,
                        help="retries (fresh process) on tunnel UNAVAILABLE")
    parser.add_argument("--watchdog-secs", type=int, default=780,
                        help="compile-phase hang deadline (0 disables "
                        "the watchdog); init/warmup phases use 300s")
    parser.add_argument("--total-budget-secs", type=int, default=1440,
                        help="hard wall-clock budget across ALL attempts "
                        "incl. backoff; sized inside the driver's window")
    parser.add_argument("--retry-attempt", type=int, default=0,
                        help=argparse.SUPPRESS)
    parser.add_argument("--deadline-epoch", type=float, default=0.0,
                        help=argparse.SUPPRESS)
    args = parser.parse_args()
    if args.campaign:
        # Campaign mode: this process becomes the sweep driver — each
        # point runs as its own bench.py subprocess (crash isolation),
        # so none of the watchdog/retry machinery below applies here.
        from horovod_tpu.bench.campaign import main as campaign_main

        return campaign_main(["--spec", args.campaign])
    if not args.deadline_epoch:
        args.deadline_epoch = time.time() + args.total_budget_secs

    if args.cpu:
        # Env var too: hvd.init() re-asserts JAX_PLATFORMS from the
        # environment (to undo site-hook overrides), so config alone would
        # be flipped back.
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
    if args.num_slices > 0:
        # Before hvd.init(): the slice partition is resolved there.
        os.environ["HVDTPU_NUM_SLICES"] = str(args.num_slices)

    if args.serve:
        _arm_watchdog(args)
        try:
            return _serve_bench(args)
        except Exception as exc:
            # The serve round still lands a record — same dark-
            # trajectory rule as the training path.
            _auto_record(f"{type(exc).__name__}: {exc}"[:2000], rc=1,
                         phase="serve")
            raise

    if args.overlap is None:
        args.overlap = os.environ.get("HVDTPU_OVERLAP", "off")
        if args.overlap not in ("off", "bucket", "bucket+zero1"):
            raise SystemExit(
                f"HVDTPU_OVERLAP={args.overlap!r}: choices are off, "
                f"bucket, bucket+zero1"
            )
    is_gpt = args.model.startswith("gpt-")
    if args.batch_size is None:
        args.batch_size = 8 if is_gpt else 128
    _arm_watchdog(args)
    # Compiled cost analysis of the ACTUAL step: fwd+bwd+optimizer FLOPs as
    # XLA counts them post-fusion — no hand-derived 3x-forward estimates.
    # The AOT executable is also what we run (one compilation, not two);
    # cost_analysis is the post-SPMD-partitioning PER-DEVICE module, so
    # everything downstream is per-chip accounting.
    # One try spans backend init + build + compile + warmup: all the places
    # a tunnel UNAVAILABLE can surface before timing starts.
    try:
        if is_gpt:
            step, state, static = build_gpt_step(
                args.model[len("gpt-"):], args.dtype, args.batch_size,
                args.seq_len, attention=args.attention, remat=args.remat,
                flash_block_q=args.flash_block_q,
                flash_block_k=args.flash_block_k,
                kv_heads=args.kv_heads, pos_embedding=args.pos_embedding,
                moe_experts=args.moe_experts,
                attention_window=args.attention_window,
                overlap_mode=args.overlap,
                grad_bucket_mb=args.grad_bucket_mb,
            )
        else:
            step, state, static = build_step(
                args.model, args.dtype, args.batch_size, args.image_size,
                s2d_stem=args.s2d_stem, overlap_mode=args.overlap,
                grad_bucket_mb=args.grad_bucket_mb,
            )
        ncarry = static["carry_len"]
        carry, const = state[:ncarry], state[ncarry:]
        n_chips = static["n_chips"]
        global_batch = static["global_batch"]
        # init+build done; compile gets its own (wide) window
        _touch_progress(next_window=args.watchdog_secs, phase="compile")

        compiled = step.lower(*carry, *const).compile()
        # compile done; warmup window
        _touch_progress(next_window=300, phase="warmup")
        # Memory plane (obs/memplane.py): the train step's artifact-
        # derived breakdown, owner tags over the live state (the
        # closures read the CURRENT carry — it is rebound every
        # iteration), and the census collector so every registry
        # snapshot below carries mem.* gauges.  Best-effort: memory
        # accounting must never sink a measurement.
        try:
            from horovod_tpu.obs import memplane  # noqa: PLC0415

            memplane.register_program(
                f"train_step.{args.overlap}", compiled
            )
            _overlap_on = args.overlap != "off"

            def _params_now():
                c = carry[0]
                return c[0] if _overlap_on else c

            def _opt_now():
                if _overlap_on:
                    return carry[0][1]
                return carry[1] if len(carry) > 1 else None

            memplane.register_owner("params", _params_now)
            memplane.register_owner("optimizer_state", _opt_now)
            memplane.install_census()
        except Exception:
            pass
        # Donation audit: params/opt_state must stay aliased end-to-end
        # through whichever step wrapper built the program (donation
        # silently degrades to a copy on mismatch, so check the
        # artifact).  Best-effort: never sinks the measurement.
        try:
            from horovod_tpu.optim.overlap import audit_donation

            donation_audit = audit_donation(
                compiled, len(jax.tree_util.tree_leaves(carry))
            )
        except Exception:
            donation_audit = None
        from horovod_tpu.obs.profile import (  # noqa: PLC0415
            flops_from_compiled,
        )

        # flops_from_compiled, not cost_analysis()["flops"]: newer jax
        # returns a list-of-dicts and the bare subscript would silently
        # demote every record to the analytic fallback.
        _ca_flops = flops_from_compiled(compiled)
        flops_per_step_per_chip = (
            float(_ca_flops) if _ca_flops is not None else float("nan")
        )
        step = compiled

        loss = None
        for _ in range(args.warmup):
            *carry, loss = step(*carry, *const)
            _touch_progress()  # dispatch-time only; the sync is below
        # device_get forces a real host round-trip: on experimental
        # platforms block_until_ready has been observed to return before
        # execution completes, which would make the timing fictitious.
        if loss is not None:
            float(loss)
        # Warmup EXECUTED on device: the backend is alive and the step
        # runs.  Disarm the watchdog here — step calls are async
        # dispatches, so the timed loop's real execution all happens
        # inside the final float(loss) and a long measurement (big model,
        # many --iters) would otherwise be indistinguishable from a hang.
        _watchdog_disarm.set()
    except Exception as exc:
        if not args.cpu and _is_unavailable(exc) \
                and args.retry_attempt < args.attempts:
            _retry_exec(args, exc)  # never returns
        # Out of retries (or a non-transient failure): the round still
        # lands a record — r03–r05 left nothing, and three dark rounds
        # later nobody could see the trajectory had died.
        _auto_record(f"{type(exc).__name__}: {exc}"[:2000], rc=1,
                     phase=_phase_name)
        raise

    t0 = time.perf_counter()
    for _ in range(args.iters):
        *carry, loss = step(*carry, *const)
    final_loss = float(loss)
    elapsed = time.perf_counter() - t0
    assert np.isfinite(final_loss), f"non-finite loss {final_loss}"

    items_per_batch = (
        global_batch * args.seq_len if is_gpt else global_batch
    )
    per_chip = items_per_batch * args.iters / elapsed / n_chips
    peak = peak_flops_per_chip(jax.devices()[0], args.dtype)
    achieved_flops_per_chip = flops_per_step_per_chip * args.iters / elapsed
    mfu = achieved_flops_per_chip / peak

    # The live MFU accountant (obs/profile.py): same division, but
    # published as perf.* gauges and embedded estimate-flagged in the
    # record — cost_analysis() FLOPs when the backend exposes them,
    # the analytic per-model formula otherwise, so even a CPU run
    # exercises the full MFU pipeline end-to-end.
    from horovod_tpu.obs.profile import (  # noqa: PLC0415
        MFUProfiler, analytic_step_flops,
    )

    prof_flops = (flops_per_step_per_chip
                  if np.isfinite(flops_per_step_per_chip) else None)
    prof_source = "cost_analysis"
    if prof_flops is None:
        prof_flops = analytic_step_flops(
            args.model, args.batch_size,
            args.seq_len if is_gpt else None, args.image_size,
        )
        prof_source = "analytic"
    profiler = MFUProfiler(prof_flops, jax.devices()[0].device_kind,
                           args.dtype, source=prof_source)
    profiler.observe(elapsed / args.iters)
    unit = "tokens/sec/chip" if is_gpt else "images/sec/chip"
    out = {
        "metric": f"{args.model}_{args.dtype}_{unit.replace('/', '_per_')}",
        "value": round(per_chip, 2),
        "unit": unit,
        # the reference publishes no absolute LM throughput; the ratio is
        # only meaningful for the conv-net headline (docs/benchmarks.rst:43)
        "vs_baseline": (
            None if is_gpt
            else round(per_chip / BASELINE_IMG_PER_SEC_PER_ACCEL, 3)
        ),
        "mfu": round(mfu, 4) if np.isfinite(mfu) else None,
        "device": jax.devices()[0].device_kind,
        "provenance": backend_provenance(probe=True),
        # Always present, estimate-flagged off-TPU: the record-embedded
        # view of the live perf.* gauges (obs/profile.py).
        "perf": profiler.summary(),
    }
    if not is_gpt and np.isfinite(flops_per_step_per_chip):
        out["flops_per_image"] = round(
            flops_per_step_per_chip / args.batch_size / 1e9, 3
        )
    if args.overlap != "off":
        out["overlap_mode"] = args.overlap
    if donation_audit is not None:
        out["donation"] = donation_audit
    try:
        from horovod_tpu.obs import memplane  # noqa: PLC0415

        out["memory"] = memplane.memory_record()
    except Exception:
        pass
    try:
        # Numerics evidence in every BENCH record (obs/health.py):
        # materialize the headline health gauges from what the timed
        # loop actually measured (its final loss), so every record
        # carries health.loss / health.nonfinite / divergence-check
        # counts even when --health never armed.  Grad-norm series
        # appear only when the measured step itself carried the health
        # bundle — the record does not re-run the step to invent them.
        from horovod_tpu.obs import get_registry  # noqa: PLC0415

        _reg = get_registry()
        _reg.gauge("health.loss").set(final_loss)
        _reg.gauge("health.nonfinite").set(
            0 if np.isfinite(final_loss) else 1)
        # inc(0) materializes the counter at its current value (0 on
        # un-armed runs) without claiming a check happened.
        _reg.counter("health.divergence.checks").inc(0)
        _reg.counter("health.nonfinite_total").inc(0)
    except Exception:
        pass
    gauges = collect_engine_gauges()
    if gauges:
        out["engine_gauges"] = gauges
    try:
        import horovod_tpu as hvd  # noqa: PLC0415

        if hvd.num_slices() > 1:
            out["num_slices"] = hvd.num_slices()
    except Exception:
        pass
    # Step-time anatomy (obs/anatomy.py): compute / collective-wait /
    # host-gap components that tile the measured step time, the top-K
    # HLO op table, and the roofline verdict — attached BEFORE the
    # degraded-record path below so even a CPU fallback record ships
    # its number with the explanation.
    try:
        from horovod_tpu.obs.anatomy import attach_anatomy  # noqa: PLC0415

        attach_anatomy(
            out, step_ms=elapsed / args.iters * 1e3, mfu=out.get("mfu"),
            flops_per_step=prof_flops,
            device_kind=jax.devices()[0].device_kind, dtype=args.dtype,
            compiled=compiled, steps_observed=args.warmup + args.iters,
            gauges=gauges,
        )
    except Exception:
        pass
    on_cpu = jax.devices()[0].platform == "cpu"
    if on_cpu:
        # A CPU measurement is a trajectory placeholder, not a perf
        # claim: mark it degraded in the printed line AND land a record
        # saying so (the dark-trajectory fix — the driver may not write
        # one for an off-nominal run).
        out["degraded"] = True
    # Sentinel BEFORE the record write: the landed record must carry
    # its own trend/regression provenance, not just the stdout line.
    attach_regression(out)
    if on_cpu:
        _auto_record("cpu fallback: numbers not comparable to TPU records",
                     rc=0, phase="cpu-fallback", parsed=out)
    _watchdog_disarm.set()
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
