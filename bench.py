#!/usr/bin/env python
"""Synthetic ResNet benchmark — the TPU equivalent of the reference's
examples/pytorch_synthetic_benchmark.py (ResNet-50, synthetic images,
img/sec reporting; docs/benchmarks.rst:66-79).

Prints ONE JSON line:
    {"metric": "resnet50_images_per_sec_per_chip", "value": N,
     "unit": "images/sec/chip", "vs_baseline": N / 103.55}

vs_baseline denominator: the only absolute per-accelerator throughput the
reference publishes in-tree — tf_cnn_benchmarks ResNet-101, batch 64,
1656.82 img/sec over 16 Pascal GPUs = 103.55 img/sec/GPU
(docs/benchmarks.rst:29-43).  The ratio therefore mixes model generation
and hardware generation; the scaling-efficiency story lives in the
multi-chip tests, this number tracks single-chip training throughput.

Usage: python bench.py [--model resnet50] [--batch-size 64] [--iters 30]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

BASELINE_IMG_PER_SEC_PER_ACCEL = 103.55  # docs/benchmarks.rst:43 (1656.82/16)


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="resnet50",
                        choices=["resnet50", "resnet101", "resnet18"])
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--iters", type=int, default=30)
    parser.add_argument("--warmup", type=int, default=5)
    parser.add_argument("--cpu", action="store_true",
                        help="force CPU (dev mode; numbers not comparable)")
    args = parser.parse_args()

    if args.cpu:
        # Env var too: hvd.init() re-asserts JAX_PLATFORMS from the
        # environment (to undo site-hook overrides), so config alone would
        # be flipped back.
        import os

        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")

    import horovod_tpu as hvd
    from horovod_tpu import models
    from horovod_tpu.optim import DistributedOptimizer

    hvd.init()
    n_chips = hvd.num_devices()

    model_cls = {
        "resnet50": models.ResNet50,
        "resnet101": models.ResNet101,
        "resnet18": models.ResNet18,
    }[args.model]
    model = model_cls(num_classes=1000)

    rng = jax.random.PRNGKey(0)
    global_batch = args.batch_size * n_chips
    images = jnp.asarray(
        np.random.RandomState(0)
        .randn(global_batch, args.image_size, args.image_size, 3)
        .astype(np.float32)
    )
    labels = jnp.asarray(
        np.random.RandomState(1).randint(0, 1000, size=(global_batch,))
    )

    variables = model.init(rng, images[:2], train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]
    params = hvd.broadcast_parameters(params, root_rank=0)

    tx = DistributedOptimizer(
        optax.sgd(0.01, momentum=0.9), compression=hvd.Compression.none
    )
    opt_state = tx.init(params)

    def local_step(params, batch_stats, opt_state, images, labels):
        def loss_fn(p):
            logits, mutated = model.apply(
                {"params": p, "batch_stats": batch_stats},
                images,
                train=True,
                mutable=["batch_stats"],
            )
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels
            ).mean()
            return loss, mutated["batch_stats"]

        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params
        )
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_stats, opt_state, loss

    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = hvd.mesh("flat")
    step = jax.jit(
        shard_map(
            local_step,
            mesh=mesh,
            in_specs=(P(), P(), P(), P(hvd.DP_AXIS), P(hvd.DP_AXIS)),
            out_specs=(P(), P(), P(), P()),
            check_vma=False,
        ),
        donate_argnums=(0, 1, 2),
    )

    for _ in range(args.warmup):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, images, labels
        )
    # device_get forces a real host round-trip: on experimental platforms
    # block_until_ready has been observed to return before execution
    # completes, which would make the timing fictitious.
    float(loss)

    t0 = time.perf_counter()
    for _ in range(args.iters):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, images, labels
        )
    final_loss = float(loss)
    elapsed = time.perf_counter() - t0
    assert np.isfinite(final_loss), f"non-finite loss {final_loss}"

    img_per_sec = global_batch * args.iters / elapsed
    per_chip = img_per_sec / n_chips
    print(
        json.dumps(
            {
                "metric": f"{args.model}_images_per_sec_per_chip",
                "value": round(per_chip, 2),
                "unit": "images/sec/chip",
                "vs_baseline": round(
                    per_chip / BASELINE_IMG_PER_SEC_PER_ACCEL, 3
                ),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
