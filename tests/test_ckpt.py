"""Sharded checkpoints + peer-replica recovery (horovod_tpu/ckpt/,
ISSUE 7): shard/manifest format with checksum validation and N->M
reshard, the replica tier's push/fetch over the signed KV path, the
elastic State tier routing (peer -> disk -> none provenance), the new
fault actions, and the 2-proc chaos acceptance — kill a rank mid-epoch,
the respawned incarnation restores from its peer's in-memory replica."""

import glob
import json
import os
import pickle
import threading
import time

import numpy as np
import pytest

import horovod_tpu.elastic as elastic
from horovod_tpu import ckpt
from horovod_tpu.ckpt.replica import SCOPE as REP_SCOPE, ReplicaTier
from horovod_tpu.ckpt.sharded import (
    ShardCorruptError,
    shard_assignment,
    step_dir,
    write_shard,
)
from horovod_tpu.elastic.context import LocalContext
from horovod_tpu.elastic.state import State
from horovod_tpu.run.rendezvous import KVStoreClient, KVStoreServer
from horovod_tpu.testing import faults


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv(faults.SPEC_ENV, raising=False)
    monkeypatch.delenv("HVDTPU_CKPT_REPLICA", raising=False)
    monkeypatch.delenv("HVDTPU_CKPT_DIR", raising=False)
    faults.reset()
    elastic.reset_context()
    yield
    faults.reset()
    elastic.reset_context()


def _state(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "params": {
            "w": rng.randn(4, 3).astype(np.float32),
            "b": rng.randn(3).astype(np.float32),
        },
        "opt": [rng.randn(2).astype(np.float64), np.int32(seed)],
        "step": np.int64(7 + seed),
    }


def _assert_tree_equal(a, b):
    import jax

    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y)
        ),
        a, b,
    )


def _save_world(directory, state, step, world):
    """Simulate ``world`` writers: start every rank's async save first
    (rank 0 blocks on the others' sidecars), then commit them all."""
    handles = [
        ckpt.save_sharded_async(directory, state, step, rank=r,
                                world_size=world)
        for r in range(world)
    ]
    for h in handles:
        h.wait()
    return handles


# ---------------------------------------------------------------------------
# Sharded format
# ---------------------------------------------------------------------------


def test_shard_assignment_round_robin():
    assert shard_assignment(5, 2) == [[0, 2, 4], [1, 3]]
    assert shard_assignment(3, 4) == [[0], [1], [2], []]
    assert shard_assignment(0, 1) == [[]]
    with pytest.raises(ValueError):
        shard_assignment(3, 0)


def test_save_restore_roundtrip_world1(tmp_path):
    d = str(tmp_path)
    state = _state()
    ckpt.save_sharded(d, state, 3, rank=0, world_size=1)
    assert ckpt.list_steps(d) == [3]
    _assert_tree_equal(ckpt.restore_sharded(d, target=_state(9)), state)


def test_multi_writer_save_and_manifest(tmp_path):
    d = str(tmp_path)
    state = _state()
    _save_world(d, state, 5, world=4)
    manifest = ckpt.load_manifest(d, 5)
    assert manifest["schema"] == ckpt.SCHEMA
    assert manifest["world_size"] == 4
    assert len(manifest["shards"]) == 4
    assert manifest["num_leaves"] == len(manifest["leaves"])
    # every shard checksummed, every leaf assigned exactly once
    for s in manifest["shards"]:
        assert len(s["checksum"]) == 64
    owned = sorted(i for s in manifest["shards"] for i in s["leaves"])
    assert owned == list(range(manifest["num_leaves"]))
    _assert_tree_equal(ckpt.restore_sharded(d, target=_state(1)), state)


def test_restore_without_target_uses_manifest_treedef(tmp_path):
    d = str(tmp_path)
    state = _state()
    _save_world(d, state, 1, world=2)
    if ckpt.load_manifest(d, 1).get("treedef") is None:
        pytest.skip("this jax cannot pickle treedefs")
    _assert_tree_equal(ckpt.restore_sharded(d), state)


def test_reshard_n_to_m_roundtrips_bitwise(tmp_path):
    """A checkpoint written by 4 ranks restores under a 2-rank world
    (and vice versa) to the identical pytree — the elastic shrink/grow
    contract."""
    d = str(tmp_path)
    state = _state()
    _save_world(d, state, 1, world=4)
    restored = ckpt.restore_sharded(d, target=_state(3))
    _assert_tree_equal(restored, state)
    _save_world(d, restored, 2, world=2)
    again = ckpt.restore_sharded(d, target=_state(3))
    assert ckpt.load_manifest(d, 2)["world_size"] == 2
    _assert_tree_equal(again, state)


def test_corrupt_shard_rejected_and_falls_back(tmp_path):
    """A checksum-rejected shard invalidates its whole step; restore
    falls back to the previous committed step instead of dying (an
    explicitly requested step raises)."""
    d = str(tmp_path)
    good, bad = _state(0), _state(1)
    ckpt.save_sharded(d, good, 1, rank=0, world_size=1)
    os.environ[faults.SPEC_ENV] = "shard_write:action=corrupt_write"
    faults.reset()
    try:
        ckpt.save_sharded(d, bad, 2, rank=0, world_size=1)
    finally:
        del os.environ[faults.SPEC_ENV]
        faults.reset()
    # the manifest committed (checksum was computed pre-corruption),
    # but the bytes on disk are damaged — exactly a torn write
    assert ckpt.list_steps(d) == [1, 2]
    with pytest.raises(ShardCorruptError, match="checksum"):
        ckpt.restore_sharded(d, target=_state(5), step=2)
    out = ckpt.restore_sharded(d, target=_state(5))  # silent fallback
    _assert_tree_equal(out, good)


def test_uncommitted_step_is_invisible(tmp_path):
    """A step directory without a manifest (writer died pre-commit) is
    not a checkpoint: latest_step never selects it."""
    d = str(tmp_path)
    ckpt.save_sharded(d, _state(), 1, rank=0, world_size=1)
    leaves = {0: np.ones(3, np.float32)}
    write_shard(d, 2, 0, 1, leaves)  # shard + sidecar, no manifest
    assert os.path.isdir(step_dir(d, 2))
    assert ckpt.latest_step(d) == 1


def test_missing_peer_shard_fails_commit_on_every_rank(tmp_path):
    """Rank 0 never shows up: the manifest never commits, and the
    waiting rank's wait() raises instead of blessing the step."""
    d = str(tmp_path)
    h = ckpt.save_sharded_async(d, _state(), 1, rank=1, world_size=2,
                                commit_timeout=0.3)
    with pytest.raises(TimeoutError, match="manifest never committed"):
        h.wait()
    with pytest.raises(TimeoutError):  # repeat wait never blesses it
        h.wait()
    assert ckpt.latest_step(d) is None


def test_async_save_snapshots_before_mutation(tmp_path):
    """The handle's contract: leaves are snapshotted before return, so
    an in-place ``w -= lr*g`` between start and wait() must not tear
    the shard (np.asarray would alias the caller's numpy buffer)."""
    d = str(tmp_path)
    w = np.arange(8, dtype=np.float64)
    h = ckpt.save_sharded_async(d, {"w": w}, 1, rank=0, world_size=1)
    w -= 100.0  # mutate immediately, racing the writer thread
    h.wait()
    out = ckpt.restore_sharded(d, target={"w": np.zeros(8)})
    np.testing.assert_array_equal(out["w"], np.arange(8, dtype=np.float64))


def test_restore_rejects_same_arity_different_structure(tmp_path):
    """Leaf count alone must not admit a checkpoint from a different
    model: per-leaf shape/dtype from the manifest gate the restore."""
    d = str(tmp_path)
    ckpt.save_sharded(d, {"a": np.zeros((4, 3), np.float32),
                          "b": np.zeros(3, np.int64)}, 1,
                      rank=0, world_size=1)
    with pytest.raises(ValueError, match="shape"):
        ckpt.restore_sharded(d, target={"a": np.zeros((2, 2), np.float32),
                                        "b": np.zeros(3, np.int64)})
    with pytest.raises(ValueError, match="dtype"):
        ckpt.restore_sharded(d, target={"a": np.zeros((4, 3), np.float64),
                                        "b": np.zeros(3, np.int64)})


def test_clean_save_leaves_no_tmp_files(tmp_path):
    d = str(tmp_path)
    _save_world(d, _state(), 1, world=2)
    assert not glob.glob(os.path.join(d, "**", "*.tmp.*"), recursive=True)


def test_resave_same_step_commits_fresh_attempt(tmp_path):
    """A retried save at the same step must not be poisoned by the
    earlier attempt's manifest: the new commit carries the new data."""
    d = str(tmp_path)
    ckpt.save_sharded(d, {"w": np.zeros(4)}, 1, rank=0, world_size=1)
    ckpt.save_sharded(d, {"w": np.full(4, 7.0)}, 1, rank=0, world_size=1)
    out = ckpt.restore_sharded(d, target={"w": np.zeros(4)}, step=1)
    np.testing.assert_array_equal(out["w"], np.full(4, 7.0))


def test_failed_resave_never_destroys_durable_step(tmp_path):
    """A re-save attempt that never completes must leave the step's
    previously committed manifest fully restorable — durability is
    never traded for the retry handshake."""
    d = str(tmp_path)
    ckpt.save_sharded(d, {"w": np.zeros(4)}, 1, rank=0, world_size=1)
    # a doomed 2-writer re-save of the same step: rank 0 never shows up
    h = ckpt.save_sharded_async(d, {"w": np.full(4, 9.0)}, 1, rank=1,
                                world_size=2, commit_timeout=0.4)
    with pytest.raises((TimeoutError, RuntimeError)):
        h.wait()
    out = ckpt.restore_sharded(d, target={"w": np.zeros(4)}, step=1)
    np.testing.assert_array_equal(out["w"], np.zeros(4))


def test_target_structure_mismatch_raises(tmp_path):
    d = str(tmp_path)
    ckpt.save_sharded(d, _state(), 1, rank=0, world_size=1)
    with pytest.raises(ValueError, match="leaves"):
        ckpt.restore_sharded(d, target={"only": np.ones(2)})


def test_restore_empty_dir_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        ckpt.restore_sharded(str(tmp_path))


# ---------------------------------------------------------------------------
# Replica tier
# ---------------------------------------------------------------------------


@pytest.fixture()
def kv_server():
    server = KVStoreServer()
    server.start()
    try:
        yield server, KVStoreClient(f"127.0.0.1:{server.port}",
                                    server.secret)
    finally:
        server.stop()


def test_replica_push_fetch_roundtrip(kv_server):
    server, kv = kv_server
    tier = ReplicaTier(kv, 0, [0, 1, 2], chunk_bytes=8)
    payload = b"0123456789" * 5
    assert tier.push(payload, step=4, commits=4)
    got, meta = tier.fetch(0)
    assert got == payload
    assert meta["step"] == 4 and meta["commits"] == 4
    assert meta["chunks"] == 7  # 50 bytes / 8
    assert meta["holder"] == 1  # ring neighbor in [0, 1, 2]


def test_replica_ring_holder_wraps():
    tier = ReplicaTier(object(), 2, [0, 1, 2], chunk_bytes=8)
    assert tier.holder() == 0
    assert tier.holder(1) == 2


def test_replica_mid_push_death_keeps_previous_version(kv_server):
    """Chunks land before the meta record: a rank dying mid-push (here:
    new-step chunks present, meta never written) leaves the previous
    replica fully fetchable — never a torn one."""
    server, kv = kv_server
    tier = ReplicaTier(kv, 0, [0, 1], chunk_bytes=8)
    v1 = b"version-one-payload"
    assert tier.push(v1, step=1, commits=1)
    kv.put(REP_SCOPE, "o0.s2.c0", b"half-a-v2")  # died before meta
    got, meta = tier.fetch(0)
    assert got == v1 and meta["step"] == 1


def test_replica_corrupt_chunk_rejected(kv_server):
    server, kv = kv_server
    tier = ReplicaTier(kv, 0, [0, 1], chunk_bytes=1024)
    assert tier.push(b"payload", step=1)
    kv.put(REP_SCOPE, "o0.s1.c0", b"garbage")
    assert tier.fetch(0) is None  # checksum mismatch -> fall back


def test_replica_gc_removes_superseded_chunks(kv_server):
    server, kv = kv_server
    tier = ReplicaTier(kv, 0, [0, 1], chunk_bytes=4)
    tier.push(b"old-payload!", step=1)
    tier.push(b"new-payload!", step=2)
    assert not server.scan(f"{REP_SCOPE}/o0.s1.")
    got, meta = tier.fetch(0)
    assert got == b"new-payload!" and meta["step"] == 2


def test_replica_from_another_job_rejected(kv_server):
    """A reused KV endpoint must never serve one job's replica to the
    next job's respawn: the meta's job fingerprint gates adoption."""
    server, kv = kv_server
    tier = ReplicaTier(kv, 0, [0, 1], chunk_bytes=64)
    assert tier.push(b"previous-job-state", step=3)
    other_job = ReplicaTier(kv, 0, [0, 1], chunk_bytes=64)
    other_job.job_id = "0123456789abcdef"  # a different job generation
    assert other_job.fetch(0) is None
    assert tier.fetch(0) is not None  # the owning job still sees it


def test_replica_failed_push_sweeps_its_chunks(kv_server):
    """A push that dies before its meta lands must not leak its chunks
    in the launcher-resident store forever."""
    server, kv = kv_server

    class _MetaFailsKV:
        def __init__(self, inner):
            self._inner = inner

        def put(self, scope, key, value):
            if key.startswith("owner_"):
                raise ConnectionError("kv went away")
            self._inner.put(scope, key, value)

        def get(self, scope, key):
            return self._inner.get(scope, key)

        def delete(self, scope, key):
            self._inner.delete(scope, key)

    tier = ReplicaTier(_MetaFailsKV(kv), 0, [0], chunk_bytes=4)
    assert tier.push(b"twelve bytes", step=1) is False
    assert not server.scan(f"{REP_SCOPE}/o0.s1."), (
        "failed push leaked its chunks"
    )


def test_drop_replica_fault_suppresses_one_push(kv_server, monkeypatch):
    server, kv = kv_server
    monkeypatch.setenv(faults.SPEC_ENV,
                       "replica_push:action=drop_replica")
    faults.reset()
    tier = ReplicaTier(kv, 0, [0, 1], chunk_bytes=64)
    assert tier.push(b"dropped", step=1) is False
    assert tier.fetch(0) is None  # nothing landed
    assert tier.push(b"kept", step=2) is True  # count=1: only the first
    assert tier.fetch(0)[0] == b"kept"


def test_kv_delete_requires_signature(kv_server):
    server, kv = kv_server
    kv.put("s", "k", b"v")
    bad = KVStoreClient(f"127.0.0.1:{server.port}", "wrong-secret")
    with pytest.raises(PermissionError):
        bad.delete("s", "k")
    assert kv.get("s", "k") == b"v"
    kv.delete("s", "k")
    assert kv.get("s", "k") is None


def test_fault_grammar_new_actions():
    specs = faults.parse_spec(
        "shard_write:rank=1:action=corrupt_write,"
        "replica_push:step=3:action=drop_replica"
    )
    assert specs[0].action == "corrupt_write" and specs[0].rank == 1
    assert specs[1].action == "drop_replica" and specs[1].step == 3
    with pytest.raises(ValueError, match="unknown fault action"):
        faults.parse_spec("shard_write:action=corrupt")
    # advisory actions are rejected at points that don't consume them —
    # the spec would otherwise "fire" as a silent no-op
    with pytest.raises(ValueError, match="silent no-op"):
        faults.parse_spec("ckpt_write:action=corrupt_write")
    with pytest.raises(ValueError, match="silent no-op"):
        faults.parse_spec("worker_exit:action=drop_replica")
    data = b"abcdef"
    flipped = faults.corrupt_bytes(data)
    assert flipped != data and len(flipped) == len(data)
    assert faults.corrupt_bytes(data) == flipped  # deterministic


# ---------------------------------------------------------------------------
# Elastic State tier routing + provenance
# ---------------------------------------------------------------------------


def test_state_commit_pushes_replica_and_fresh_sync_adopts(kv_server):
    server, kv = kv_server
    st = State(w=np.zeros(3), step=0)
    st._replica_tier = ReplicaTier(kv, 0, [0], chunk_bytes=64)
    st.w = st.w + 5.0
    st.step = 3
    st.commit()
    # a "respawned incarnation": fresh State, same rank, no history
    st2 = State(w=np.zeros(3), step=0)
    st2._replica_tier = ReplicaTier(kv, 0, [0], chunk_bytes=64)
    st2.sync(LocalContext())
    assert st2.step == 3 and st2.w.tolist() == [5.0] * 3
    assert st2.last_restore["source"] == "peer"
    assert st2.last_restore["replica_adopted"] is True
    assert st2.last_restore["commits"] == 1


def test_state_disk_fallback_when_no_replica(tmp_path, kv_server):
    server, kv = kv_server
    d = str(tmp_path)
    st = State(w=np.zeros(3), step=0)
    st.w = st.w + 2.0
    st.step = 9
    st.commit()
    st._ckpt_dir = d
    st.save_sharded(ctx=LocalContext()).wait()
    st2 = State(w=np.zeros(3), step=0)
    st2._replica_tier = ReplicaTier(kv, 0, [0])  # KV empty: no replica
    st2._ckpt_dir = d
    st2.sync(LocalContext())
    assert st2.step == 9
    assert st2.last_restore["source"] == "disk"
    assert st2.last_restore["replica_adopted"] is False


def test_interrupted_first_sync_still_records_provenance(kv_server):
    """A cascading failure DURING the respawn's first sync (the
    election raises after the replica was already adopted) must not
    lose the provenance record: the retried sync still reports the
    peer restore, even though adoption already bumped the commit
    count."""
    from horovod_tpu.elastic.exceptions import HorovodShutdownError

    server, kv = kv_server
    st = State(w=np.zeros(3), step=0)
    st._replica_tier = ReplicaTier(kv, 0, [0], chunk_bytes=64)
    st.step = 4
    st.commit()

    class _DiesMidSync(LocalContext):
        def sync_state(self, blob, commit_count):
            raise HorovodShutdownError("peer died mid-election")

    st2 = State(w=np.zeros(3), step=0)
    st2._replica_tier = ReplicaTier(kv, 0, [0], chunk_bytes=64)
    with pytest.raises(HorovodShutdownError):
        st2.sync(_DiesMidSync())
    assert st2.commits == 1  # the replica WAS adopted before the raise
    assert st2.last_restore is None  # ...but nothing recorded yet
    st2.sync(LocalContext())  # the elastic.run retry
    assert st2.last_restore is not None
    assert st2.last_restore["source"] == "peer"
    assert st2.last_restore["replica_adopted"] is True
    assert st2.step == 4


def test_state_provenance_none_on_fresh_start():
    st = State(w=np.zeros(2))
    st._replica_tier = False
    st.sync(LocalContext())
    assert st.last_restore["source"] == "none"


def test_state_corrupt_replica_falls_back_to_disk(tmp_path, kv_server):
    """A checksum-rejected replica must not poison recovery: sync falls
    through to the disk manifest."""
    server, kv = kv_server
    d = str(tmp_path)
    st = State(w=np.zeros(3), step=0)
    st._replica_tier = ReplicaTier(kv, 0, [0], chunk_bytes=64)
    st.step = 4
    st.commit()
    st._ckpt_dir = d
    st.save_sharded(ctx=LocalContext()).wait()
    kv.put(REP_SCOPE, "o0.s1.c0", b"garbage")  # corrupt the replica
    st2 = State(w=np.zeros(3), step=0)
    st2._replica_tier = ReplicaTier(kv, 0, [0], chunk_bytes=64)
    st2._ckpt_dir = d
    st2.sync(LocalContext())
    assert st2.step == 4
    assert st2.last_restore["source"] == "disk"


def test_state_stale_replica_never_shadows_newer_disk(tmp_path,
                                                      kv_server):
    """The replica holds commit 1 (later pushes were dropped) while the
    disk manifest holds commit 3: sync must adopt the newer disk state,
    and must not claim the replica restored anything."""
    server, kv = kv_server
    d = str(tmp_path)
    st = State(w=np.zeros(3), step=0)
    st._replica_tier = ReplicaTier(kv, 0, [0], chunk_bytes=64)
    st._ckpt_dir = d
    st.step = 1
    st.commit()  # replica at commit 1
    st._replica_tier = False  # subsequent pushes "dropped"
    st.step = 2
    st.commit()
    st.step = 3
    st.commit()  # commits=3, replica still at 1
    st.save_sharded(ctx=LocalContext()).wait()
    st2 = State(w=np.zeros(3), step=0)
    st2._replica_tier = ReplicaTier(kv, 0, [0], chunk_bytes=64)
    st2._ckpt_dir = d
    st2.sync(LocalContext())
    assert st2.step == 3, "stale replica shadowed the newer manifest"
    assert st2.last_restore["source"] == "disk"
    assert st2.last_restore["replica_adopted"] is False


def test_state_peer_restore_never_reads_disk_shards(tmp_path, kv_server,
                                                    monkeypatch):
    """'Never touch cold storage': when the replica is at least as
    fresh as the disk manifest, sync must not reassemble the disk
    checkpoint (metadata peek only)."""
    from horovod_tpu.ckpt import sharded as _sharded

    server, kv = kv_server
    d = str(tmp_path)
    st = State(w=np.zeros(3), step=0)
    st._replica_tier = ReplicaTier(kv, 0, [0], chunk_bytes=64)
    st._ckpt_dir = d
    st.step = 2
    st.commit()  # replica at commit 1
    st.save_sharded(ctx=LocalContext()).wait()  # disk also at commit 1
    calls = []
    real = _sharded.restore_sharded
    monkeypatch.setattr(_sharded, "restore_sharded",
                        lambda *a, **k: calls.append(1) or real(*a, **k))
    st2 = State(w=np.zeros(3), step=0)
    st2._replica_tier = ReplicaTier(kv, 0, [0], chunk_bytes=64)
    st2._ckpt_dir = d
    st2.sync(LocalContext())
    assert st2.last_restore["source"] == "peer"
    assert not calls, "peer restore read the disk checkpoint anyway"


def test_state_replica_adopted_not_claimed_when_election_overrides():
    """A stale replica the owner election overrides with a fresher
    survivor broadcast must not be reported as a replica restore."""

    class _SurvivorCtx(LocalContext):
        """Election winner is a peer holding a NEWER snapshot."""

        def sync_state(self, blob, commit_count):
            return pickle.dumps(({"w": np.full(3, 9.0), "step": 7}, 7))

    st = State(w=np.zeros(3), step=0)
    st._replica_tier = _FakeTier(  # replica stale at commit 2
        pickle.dumps(({"w": np.full(3, 2.0), "step": 2}, 2)))
    st.sync(_SurvivorCtx())
    assert st.step == 7
    assert st.last_restore["source"] == "peer"
    assert st.last_restore["replica_adopted"] is False, (
        "a stale, overridden replica was claimed as the restore source"
    )


class _FakeTier:
    def __init__(self, payload):
        self._payload = payload
        self.rank, self.world = 0, [0]

    def fetch(self, owner=None):
        return self._payload, {"step": 0}

    def push(self, payload, *, step, commits=None):
        return True


def test_state_save_sharded_survives_sparse_world(tmp_path):
    """After an elastic shrink the world can have rank gaps ({0, 2});
    shards are indexed by world POSITION, so the save still commits
    with dense writer indices and restores bitwise."""

    class _Ctx(LocalContext):
        def __init__(self, rank, world):
            super().__init__()
            self.rank, self.world, self.size = rank, world, len(world)

    d = str(tmp_path)
    st0 = State(w=np.arange(4.0), step=0)
    st2 = State(w=np.arange(4.0), step=0)
    for st in (st0, st2):
        st.step = 5
        st.commit()
    h0 = st0.save_sharded(d, ctx=_Ctx(0, [0, 2]))
    h2 = st2.save_sharded(d, ctx=_Ctx(2, [0, 2]))
    h0.wait()
    h2.wait()
    manifest = ckpt.load_manifest(d, ckpt.latest_step(d))
    assert manifest["world_size"] == 2
    out = ckpt.restore_sharded(d, target={"w": np.zeros(4), "step": 0})
    np.testing.assert_array_equal(out["w"], np.arange(4.0))
    # a rank outside the world is told to re-rendezvous, not to corrupt
    with pytest.raises(RuntimeError, match="not in the current world"):
        st0.save_sharded(d, ctx=_Ctx(1, [0, 2]))


def test_kv_delete_mac_binds_key_no_replay(kv_server):
    """A captured DELETE MAC for one key must not replay against
    another: the signature binds method + key."""
    from urllib.error import HTTPError
    from urllib.request import Request, urlopen

    from horovod_tpu.run.rendezvous import _MAC_HEADER, _delete_mac

    server, kv = kv_server
    kv.put("s", "a", b"1")
    kv.put("s", "b", b"2")
    mac_for_a = _delete_mac(server.secret, "s/a")
    req = Request(f"http://127.0.0.1:{server.port}/s/b", method="DELETE")
    req.add_header(_MAC_HEADER, mac_for_a)  # the replay
    with pytest.raises(HTTPError) as err:
        urlopen(req, timeout=5)
    assert err.value.code == 403
    assert kv.get("s", "b") == b"2"
    kv.delete("s", "a")
    assert kv.get("s", "a") is None


def test_restore_provenance_lands_in_flightrec_ring(tmp_path):
    from horovod_tpu.obs import flightrec

    st = State(w=np.zeros(2))
    st._replica_tier = False
    st._ckpt_dir = str(tmp_path)  # tier armed (empty dir): recorded
    st.sync(LocalContext())
    events = [e for e in flightrec.get_recorder().snapshot()
              if e["kind"] == "ckpt.restore"]
    assert events, "sync recorded no ckpt.restore event"
    assert "source=none" in events[-1]["detail"]


def test_unarmed_fresh_start_stays_quiet():
    """A job with NO ckpt tier configured must not emit provenance
    metrics or flight-recorder events — quiet jobs stay quiet — while
    the API answer (last_restore) is still available."""
    from horovod_tpu.obs import flightrec, get_registry

    before = get_registry().counter("ckpt.restore_source",
                                    source="none").value
    n_events = len([e for e in flightrec.get_recorder().snapshot()
                    if e["kind"] == "ckpt.restore"])
    st = State(w=np.zeros(2))
    st._replica_tier = False  # no tier, no ckpt dir
    st.sync(LocalContext())
    assert st.last_restore["source"] == "none"
    assert get_registry().counter("ckpt.restore_source",
                                  source="none").value == before
    assert len([e for e in flightrec.get_recorder().snapshot()
                if e["kind"] == "ckpt.restore"]) == n_events


def test_restore_provenance_metrics_counters(kv_server):
    from horovod_tpu.obs import get_registry

    server, kv = kv_server
    st = State(w=np.zeros(2), step=0)
    st._replica_tier = ReplicaTier(kv, 0, [0], chunk_bytes=64)
    st.step = 1
    st.commit()
    st2 = State(w=np.zeros(2), step=0)
    st2._replica_tier = ReplicaTier(kv, 0, [0], chunk_bytes=64)
    before = get_registry().counter("ckpt.restore_source",
                                    source="peer").value
    st2.sync(LocalContext())
    reg = get_registry()
    assert reg.counter("ckpt.restore_source",
                       source="peer").value == before + 1
    assert reg.histogram("ckpt.restore_ms").count >= 1
    assert reg.counter("ckpt.replica_pushes").value >= 1
    assert reg.histogram("ckpt.replica_push_ms").count >= 1


# ---------------------------------------------------------------------------
# Surfacing: post-mortem, summary, CLI
# ---------------------------------------------------------------------------


def _fake_dump(rank, events, trigger="signal:SIGTERM", epoch=0):
    from horovod_tpu.obs import flightrec

    return {
        "schema": flightrec.SCHEMA,
        "rank": rank,
        "epoch": epoch,
        "trigger": trigger,
        "wall_time": 1000.0 + rank,
        "recorded": len(events),
        "overwritten": 0,
        "events": events,
        "last_exception": None,
    }


def test_postmortem_surfaces_restore_provenance():
    from horovod_tpu.obs import postmortem

    dumps = [
        _fake_dump(0, [
            {"seq": 0, "t": 1.0, "kind": "rendezvous", "name": "epoch1",
             "cycle": 1, "detail": "world=[0, 1]"},
            {"seq": 1, "t": 1.1, "kind": "ckpt.restore", "name": "commit4",
             "cycle": 4, "detail": "source=none replica=False ms=1"},
        ]),
        _fake_dump(1, [
            {"seq": 0, "t": 1.0, "kind": "rendezvous", "name": "epoch1",
             "cycle": 1, "detail": "world=[0, 1]"},
            {"seq": 1, "t": 1.2, "kind": "ckpt.restore", "name": "commit4",
             "cycle": 4, "detail": "source=peer replica=True ms=42"},
        ], trigger="signal:SIGABRT"),
    ]
    report = postmortem.analyze(dumps, expected_ranks=2)
    prov = report["restore_provenance"]
    assert prov["1"]["source"] == "peer"
    assert prov["1"]["replica_adopted"] is True
    assert prov["1"]["ms"] == 42.0
    assert prov["0"]["source"] == "none"
    text = postmortem.verdict(report)
    assert "rank 1 restored from a live peer at commit 4" in text


def test_summary_ckpt_section_renders():
    from horovod_tpu.obs.summary import ckpt_section

    dumps = {
        "0": {"metrics": [
            {"name": "ckpt.restore_source", "type": "counter",
             "tags": {"source": "peer"}, "value": 1},
            {"name": "ckpt.replica_pushes", "type": "counter",
             "tags": {}, "value": 5},
            {"name": "ckpt.restore_ms", "type": "histogram", "tags": {},
             "count": 1, "sum": 40.0, "min": 40.0, "max": 40.0,
             "mean": 40.0, "p50": 40.0, "p90": 40.0, "p99": 40.0},
        ]},
        "1": {"metrics": []},
    }
    text = ckpt_section(dumps)
    assert "rank 0: restores peer=1, replica pushes 5" in text
    assert "restore time" in text
    assert "rank 1" not in text  # quiet ranks stay quiet
    assert ckpt_section({"0": {"metrics": []}}) is None


def test_live_digest_gains_ckpt_token():
    from horovod_tpu.obs.live import LiveAggregator

    agg = LiveAggregator()
    agg.ingest({"rank": 0, "epoch": 0, "seq": 1, "metrics": [
        {"n": "ckpt.restore_source", "k": "c",
         "g": {"source": "peer"}, "v": 1},
        {"n": "ckpt.replica_pushes", "k": "c", "v": 8},
        {"n": "ckpt.replica_push_ms", "k": "h", "c": 8, "s": 24.0,
         "mn": 1, "mx": 9, "q50": 3.0, "q90": 8.0, "q99": 9.0},
    ]})
    # a second, slower rank: the digest must surface the WORST p50,
    # not whichever view iterates last
    agg.ingest({"rank": 1, "epoch": 0, "seq": 1, "metrics": [
        {"n": "ckpt.replica_pushes", "k": "c", "v": 8},
        {"n": "ckpt.replica_push_ms", "k": "h", "c": 8, "s": 7200.0,
         "mn": 800, "mx": 990, "q50": 900.0, "q90": 980.0,
         "q99": 990.0},
    ]})
    assert "ckpt restores peer=1 pushes 16 (worst p50 900ms)" \
        in agg.digest(2)
    # quiet jobs stay quiet: no ckpt token without tier activity
    agg2 = LiveAggregator()
    agg2.ingest({"rank": 0, "epoch": 0, "seq": 1, "metrics": [
        {"n": "engine.collectives_completed", "k": "c", "v": 4},
    ]})
    assert "ckpt" not in agg2.digest(1)


def test_cli_ckpt_knobs_map_to_env():
    from horovod_tpu.run.config_parser import set_env_from_args
    from horovod_tpu.run.runner import parse_args

    args = parse_args([
        "-np", "2", "--ckpt-replica", "--ckpt-dir", "/ckpts",
        "--ckpt-replica-chunk-kb", "256",
        "--ckpt-commit-timeout-secs", "30", "python", "x",
    ])
    env = {}
    set_env_from_args(env, args)
    assert env["HVDTPU_CKPT_REPLICA"] == "1"
    assert env["HVDTPU_CKPT_DIR"] == "/ckpts"
    assert env["HVDTPU_CKPT_REPLICA_CHUNK_KB"] == "256"
    assert env["HVDTPU_CKPT_COMMIT_TIMEOUT_SECS"] == "30.0"


# ---------------------------------------------------------------------------
# End-to-end chaos (real processes through the elastic launcher)
# ---------------------------------------------------------------------------


def _ckpt_chaos_train(total_steps=8):
    import numpy as np  # noqa: PLC0415

    import horovod_tpu.elastic as elastic  # noqa: PLC0415

    ctx = elastic.context()
    state = elastic.State(w=np.zeros(4, dtype=np.float64), step=0)

    @elastic.run
    def loop(state):
        while state.step < total_steps:
            grad = np.full(4, float(state.step + 1) * (ctx.rank + 1))
            state.w = state.w - 0.1 * ctx.allreduce(
                grad, name=f"g{state.step}")
            state.step += 1
            state.commit()
        return state.w.tolist(), state.step, state.last_restore

    return loop(state)


@pytest.mark.multiprocess
def test_ckpt_chaos_respawn_restores_from_peer_replica(tmp_path):
    """ISSUE 7 acceptance: 2-proc elastic job with the replica tier on;
    rank 1 is killed mid-epoch; its respawned incarnation restores from
    its predecessor's in-memory replica (provenance says peer, the
    replica specifically), training resumes from the last commit, and
    the job finishes with the no-fault run's state — in seconds, never
    touching disk."""
    bb = str(tmp_path / "bb")
    os.makedirs(bb)  # a non-existent spec would resolve as a plain path
    clean_env = {"JAX_PLATFORMS": "cpu", "HVDTPU_CKPT_REPLICA": "1"}
    fault_env = dict(clean_env,
                     HVDTPU_FAULT_SPEC="worker_exit:step=5:rank=1",
                     HVDTPU_FLIGHTREC_DUMP=bb)

    clean, _ = elastic.launch(_ckpt_chaos_train, np=2, env=clean_env,
                              timeout=120)
    faulted, job = elastic.launch(_ckpt_chaos_train, np=2, env=fault_env,
                                  max_retries=2, timeout=120)

    assert sorted(faulted) == [0, 1]
    for rank in (0, 1):
        assert faulted[rank][0] == clean[rank][0]
        assert faulted[rank][1] == 8
    events = [e[0] for e in job.trace]
    assert events.count("respawn") == 1

    # The respawned rank 1 restored from its peer replica, fast.
    prov = faulted[1][2]
    assert prov is not None and prov["source"] == "peer", prov
    assert prov["replica_adopted"] is True, (
        "rank 1 adopted a live survivor broadcast, not its "
        f"predecessor's replica: {prov}"
    )
    assert prov["commits"] >= 1
    assert prov["ms"] < 30_000, prov  # seconds, not minutes
    # Rank 0 (the survivor) recovered nothing: it rolled back to its
    # own commit.
    assert faulted[0][2] is not None and faulted[0][2]["source"] == "none"

    # Provenance reached the respawned incarnation's black box.
    dumps = glob.glob(os.path.join(bb, "flightrec.e*.rank.1.json"))
    restored = []
    for p in dumps:
        with open(p) as f:
            doc = json.load(f)
        restored += [e for e in doc.get("events", [])
                     if e.get("kind") == "ckpt.restore"
                     and "source=peer" in e.get("detail", "")]
    assert restored, f"no peer-sourced ckpt.restore event in {dumps}"


@pytest.mark.multiprocess
def test_ckpt_chaos_shrink_keeps_state_after_world_change(tmp_path):
    """Elastic world change (N->M): the respawn budget is 0, so losing
    rank 1 shrinks 3 -> 2; the survivors' state is unaffected and the
    job completes — committed state survives a world-size change."""
    env = {"JAX_PLATFORMS": "cpu", "HVDTPU_CKPT_REPLICA": "1",
           "HVDTPU_FAULT_SPEC": "worker_exit:step=3:rank=1"}
    results, job = elastic.launch(
        _ckpt_chaos_train, np=3, env=env, min_workers=2, max_retries=0,
        timeout=120)
    assert job.world == [0, 2]
    assert all(results[r][1] == 8 for r in results)
    assert "shrink" in [e[0] for e in job.trace]
