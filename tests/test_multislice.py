"""Multislice simulation suite (ISSUE 8): slice topology resolution,
the two-fabric hierarchical schedule vs the flat path (bitwise where the
math is exact, bounded where the DCN wire is compressed), the
topology-derived autotune categories, slice-tagged straggler blame, and
the slice blacklist — all on the virtual CPU mesh with forced
partitions, plus real 4-process forced-2x2 acceptance through the
launcher (reference strategy: NCCLHierarchicalAllreduce's fabric split,
nccl_operations.cc:162-300, simulated the way the reference CI simulates
multi-node with multi-process-on-localhost)."""

import numpy as np
import pytest

import horovod_tpu as hvd
import horovod_tpu.run as hvdrun
from horovod_tpu.basics import resolve_slice_partition, slice_grid
from horovod_tpu.ops.compression import (
    BFloat16Compressor,
    Compression,
    ErrorFeedbackCompressor,
    FP16Compressor,
)
from horovod_tpu.parallel.hierarchical import hierarchical_allreduce
from horovod_tpu.run.allocate import slice_assignment
from horovod_tpu.run.blacklist import HostBlacklist
from horovod_tpu.runtime.autotune import build_categories
from horovod_tpu.obs import straggler as obs_straggler

N = 8  # 2 slices x 4 "ranks" on the virtual mesh


@pytest.fixture
def hvd_caplog(caplog):
    """caplog that sees horovod_tpu records: the package logger sets
    propagate=False (it owns its stderr handler), so caplog's root
    handler needs propagation re-enabled for the test's duration."""
    import logging

    root = logging.getLogger("horovod_tpu")
    root.propagate = True
    try:
        with caplog.at_level("WARNING", logger="horovod_tpu"):
            yield caplog
    finally:
        root.propagate = False


# ---------------------------------------------------------------------------
# slice topology resolution
# ---------------------------------------------------------------------------


def test_forced_num_slices_partitions_processes():
    assert resolve_slice_partition(8, 0, [], {"HVDTPU_NUM_SLICES": "2"}) \
        == (2, 0)
    assert resolve_slice_partition(8, 3, [], {"HVDTPU_NUM_SLICES": "2"}) \
        == (2, 0)
    assert resolve_slice_partition(8, 4, [], {"HVDTPU_NUM_SLICES": "2"}) \
        == (2, 1)
    assert resolve_slice_partition(8, 7, [], {"HVDTPU_NUM_SLICES": "4"}) \
        == (4, 3)


def test_forced_slice_size_is_procs_per_slice():
    assert resolve_slice_partition(4, 2, [], {"HVDTPU_SLICE_SIZE": "2"}) \
        == (2, 1)
    # NUM_SLICES wins when both are set
    assert resolve_slice_partition(
        4, 3, [], {"HVDTPU_SLICE_SIZE": "2", "HVDTPU_NUM_SLICES": "4"}
    ) == (4, 3)


def test_uneven_forced_partition_downgrades_with_warning(hvd_caplog):
    assert resolve_slice_partition(
        4, 0, [], {"HVDTPU_NUM_SLICES": "3"}
    ) == (1, 0)
    assert "does not divide" in hvd_caplog.text


def test_explicit_single_slice_is_silent(hvd_caplog):
    assert resolve_slice_partition(
        4, 0, [], {"HVDTPU_NUM_SLICES": "1"}
    ) == (1, 0)
    assert hvd_caplog.text == ""


def test_single_process_world_partitions_devices():
    # the in-process 8-device test world: SLICE_SIZE counts chips
    devs = list(range(8))
    assert resolve_slice_partition(
        1, 0, devs, {"HVDTPU_SLICE_SIZE": "4"}
    ) == (2, 0)
    assert resolve_slice_partition(
        1, 0, devs, {"HVDTPU_NUM_SLICES": "2"}
    ) == (2, 0)


class _FakeDev:
    def __init__(self, slice_index, process_index):
        self.slice_index = slice_index
        self.process_index = process_index


def test_platform_discovery_via_slice_index():
    devs = [_FakeDev(s, p) for s in (0, 1) for p in (2 * s, 2 * s + 1)]
    assert resolve_slice_partition(4, 0, devs, {}) == (2, 0)
    assert resolve_slice_partition(4, 3, devs, {}) == (2, 1)


def test_discovery_rejects_process_spanning_slices(hvd_caplog):
    devs = [_FakeDev(0, 0), _FakeDev(1, 0), _FakeDev(1, 1), _FakeDev(0, 1)]
    assert resolve_slice_partition(2, 0, devs, {}) == (1, 0)
    assert "spans multiple slices" in hvd_caplog.text


def test_slice_grid_three_level_view():
    assert slice_grid(list(range(8)), 2, 1).shape == (2, 1, 4)
    assert slice_grid(list(range(8)), 2, 2).shape == (2, 2, 2)
    g = slice_grid(list(range(8)), 2, 2)
    assert g[1, 0, 0] == 4  # contiguous blocks per slice
    with pytest.raises(ValueError):
        slice_grid(list(range(8)), 3, 1)
    with pytest.raises(ValueError):
        slice_grid(list(range(8)), 2, 3)


def test_session_topology_is_single_slice():
    # the in-process suite initializes without forced slices
    assert hvd.num_slices() == 1
    assert hvd.slice_id() == 0
    assert hvd.slice_of_rank(0) == 0
    with pytest.raises(ValueError):
        hvd.mesh("slice")


def test_slice_assignment_contract():
    assert slice_assignment(4, 2) == [0, 0, 1, 1]
    assert slice_assignment(6, 3) == [0, 0, 1, 1, 2, 2]
    assert slice_assignment(4, 1) == [0, 0, 0, 0]
    with pytest.raises(ValueError):
        slice_assignment(4, 3)
    with pytest.raises(ValueError):
        slice_assignment(4, 0)


# ---------------------------------------------------------------------------
# hierarchical vs flat: bitwise equivalence + compressed-wire bounds
# ---------------------------------------------------------------------------


def _mesh2d():
    import jax
    from jax.sharding import Mesh

    devices = np.asarray(jax.devices()[:N], dtype=object).reshape(2, 4)
    return Mesh(devices, (hvd.CROSS_AXIS, hvd.LOCAL_AXIS))


def _run(fn, x):
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.runtime.device_plane import _shard_map

    return _shard_map(
        fn,
        mesh=_mesh2d(),
        in_specs=(P((hvd.CROSS_AXIS, hvd.LOCAL_AXIS)),),
        out_specs=P((hvd.CROSS_AXIS, hvd.LOCAL_AXIS)),
    )(x)


@pytest.mark.parametrize("op", [hvd.Sum, hvd.Average])
@pytest.mark.parametrize("dtype", [np.float32, np.int32])
@pytest.mark.parametrize("shape", [(5,), (8,), (3, 7), (1,)])
def test_hierarchical_bitwise_equals_flat(op, dtype, shape):
    """Integer-valued payloads sum exactly in any association order, so
    the 3-phase schedule must be BITWISE-equal to the flat reduction —
    across dtypes and pad/unpad shapes."""
    if dtype == np.int32 and op == hvd.Average:
        pytest.skip("int average: engine-exact floor semantics, not a "
                    "shard_map op contract")
    rng = np.random.RandomState(7)
    x = rng.randint(-50, 50, size=(N,) + shape).astype(dtype)

    def step(v):
        return hierarchical_allreduce(v[0], op)[None]

    out = np.asarray(_run(step, x))
    expect = x.astype(np.float64).sum(axis=0)
    if op == hvd.Average:
        expect = expect / N
    for r in range(N):
        np.testing.assert_array_equal(
            np.asarray(out[r], np.float64), expect
        )


@pytest.mark.parametrize("wire,rel", [("bf16", 2 ** -7), ("fp16", 2 ** -10)])
def test_hierarchical_compressed_wire_tolerance(wire, rel):
    """The DCN leg on a compressed wire: error bounded by one cast
    round-trip on slice-partial sums (documented tolerance in
    docs/performance.md)."""
    rng = np.random.RandomState(3)
    x = rng.randn(N, 33).astype(np.float32)

    def exact(v):
        return hierarchical_allreduce(v[0], hvd.Average)[None]

    def compressed(v):
        return hierarchical_allreduce(
            v[0], hvd.Average, compression=wire
        )[None]

    ref = np.asarray(_run(exact, x))
    got = np.asarray(_run(compressed, x))
    scale = np.abs(ref).max()
    assert np.abs(got - ref).max() <= rel * scale * 2
    # and it is genuinely lossy-or-equal, never wildly off
    assert not np.allclose(got, 0)


def test_hierarchical_rejects_unknown_compression():
    with pytest.raises(ValueError, match="unknown dcn compression"):
        hierarchical_allreduce(np.ones(4, np.float32), compression="zstd")


# ---------------------------------------------------------------------------
# compressors: contracts + error feedback
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "comp,rel", [(BFloat16Compressor, 2 ** -8), (FP16Compressor, 2 ** -11)]
)
def test_cast_compressor_roundtrip_bounds(comp, rel):
    rng = np.random.RandomState(11)
    x = rng.randn(257).astype(np.float32)
    wire, ctx = comp.compress(x)
    back = np.asarray(comp.decompress(wire, ctx))
    assert back.dtype == np.float32 and back.shape == x.shape
    assert np.abs(back - x).max() <= rel * np.abs(x).max() * 2


def test_error_feedback_carries_residual():
    """A constant stream that the wire rounds: naive casting accumulates
    K*eps of bias; error feedback keeps the ACCUMULATED error within a
    couple of single-step quanta because every dropped bit is re-fed."""
    ef = ErrorFeedbackCompressor(BFloat16Compressor)
    x = np.float32(1.0 + 2.0 ** -9)  # not representable in bf16
    steps = 64
    ef_sum = 0.0
    naive_sum = 0.0
    for i in range(steps):
        w, ctx = ef.compress(np.full(4, x, np.float32), key="g")
        ef_sum += float(np.asarray(ef.decompress(w, ctx))[0])
        nw, nctx = BFloat16Compressor.compress(np.full(4, x, np.float32))
        naive_sum += float(np.asarray(
            BFloat16Compressor.decompress(nw, nctx))[0])
    true_sum = steps * float(x)
    assert abs(ef_sum - true_sum) <= 3 * 2.0 ** -8
    assert abs(naive_sum - true_sum) >= steps * 2.0 ** -9 * 0.9
    assert abs(ef_sum - true_sum) < abs(naive_sum - true_sum) / 8


def test_error_feedback_reset_and_shape_change():
    ef = ErrorFeedbackCompressor(BFloat16Compressor)
    ef.compress(np.ones(4, np.float32), key="g")
    ef.compress(np.ones(8, np.float32), key="g")  # shape change: no crash
    ef.reset()
    assert ef._residuals == {}


def test_error_feedback_not_in_cast_namespace():
    # stateful: must be instantiated explicitly, never passed as a
    # namespace member where a stateless cast class is expected
    assert not hasattr(Compression, "ef_bf16")
    assert ErrorFeedbackCompressor is not None


# ---------------------------------------------------------------------------
# autotune categories are topology-derived
# ---------------------------------------------------------------------------


def test_categories_single_slice_excludes_hierarchical():
    cats = build_categories(multislice=False, replay_enabled=True)
    assert cats == [
        {"cache_enabled": True, "hierarchical_allreduce": False}
    ]


def test_categories_multislice_includes_hierarchical():
    cats = build_categories(multislice=True, replay_enabled=True)
    assert {"cache_enabled": True, "hierarchical_allreduce": True} in cats


def test_categories_incapable_plane_excludes_hierarchical():
    cats = build_categories(
        multislice=True, replay_enabled=False, hierarchical_capable=False
    )
    assert all(not c["hierarchical_allreduce"] for c in cats)
    # cache-off explored when replay is off (the native engine's chain)
    assert {"cache_enabled": False, "hierarchical_allreduce": False} in cats


def test_categories_replay_excludes_cache_off():
    cats = build_categories(multislice=True, replay_enabled=True)
    assert all(c["cache_enabled"] for c in cats)


# ---------------------------------------------------------------------------
# slice-tagged straggler blame
# ---------------------------------------------------------------------------


def _blame(count, rank, slice_id=None):
    tags = {"rank": str(rank)}
    if slice_id is not None:
        tags["slice"] = str(slice_id)
    return {
        "name": obs_straggler.PREFIX + "last_arrivals",
        "type": "counter",
        "value": count,
        "tags": tags,
    }


def test_merge_blames_slice_verdict():
    verdict = obs_straggler.merge_blames([
        [_blame(3, 2, 1), _blame(2, 3, 1), _blame(1, 0, 0)],
        [_blame(3, 2, 1)],
    ])
    assert verdict["rank"] == 2
    assert verdict["slice"] == 1
    assert verdict["slice_blames"] == {0: 1, 1: 5}
    assert verdict["slice_share"] == pytest.approx(5 / 6)


def test_merge_blames_without_slice_tags_has_no_slice_key():
    verdict = obs_straggler.merge_blames([[_blame(3, 1)]])
    assert verdict["rank"] == 1
    assert "slice" not in verdict


def test_slice_tag_empty_on_single_slice_topology():
    assert obs_straggler._slice_tag(0) == {}


def _live_payload(metrics):
    """Compact delta payload (obs/stream.py wire schema) for the
    aggregator tests."""
    return {
        "v": 1, "rank": 0, "epoch": 0, "seq": 0, "t": 1000.0,
        "phase": "steady", "progress": 5, "full": True,
        "metrics": list(metrics),
    }


def _compact(name, value, kind="c", **tags):
    out = {"n": name, "k": kind, "v": value}
    if tags:
        out["g"] = {k: str(v) for k, v in tags.items()}
    return out


def test_digest_names_straggling_slice():
    from horovod_tpu.obs import live as obs_live

    agg = obs_live.LiveAggregator()
    agg.ingest(_live_payload([
        _compact(obs_straggler.PREFIX + "last_arrivals", 4,
                 rank=2, slice=1),
    ]))
    d = agg.digest(1)
    assert "straggler rank 2" in d
    assert "slice 1 is the straggler" in d


def test_fabric_digest_token_and_summary_section():
    from horovod_tpu.obs import live as obs_live
    from horovod_tpu.obs import summary as obs_summary

    agg = obs_live.LiveAggregator()
    agg.ingest(_live_payload([
        _compact("engine.dcn_bytes", 48.0),
        _compact("engine.ici_bytes", 96.0),
        _compact("engine.dcn_compression_ratio", 2.0, kind="g"),
    ]))
    d = agg.digest(1)
    assert "fabric dcn" in d and "dcn/ici 0.50" in d and "wire x2.0" in d
    fabric = [
        {"name": "engine.dcn_bytes", "type": "counter", "value": 48.0},
        {"name": "engine.ici_bytes", "type": "counter", "value": 96.0},
        {"name": "engine.dcn_compression_ratio", "type": "gauge",
         "value": 2.0},
    ]
    section = obs_summary.fabric_section({"0": {"metrics": fabric}})
    assert section is not None
    assert "dcn 48" in section and "ici 96" in section
    # single-slice job (no fabric counters): no section
    assert obs_summary.fabric_section({"0": {"metrics": []}}) is None


# ---------------------------------------------------------------------------
# slice blacklist
# ---------------------------------------------------------------------------


def test_blacklist_slice_quorum_blacklists_whole_slice():
    clock = [0.0]
    bl = HostBlacklist(cooldown_base=10.0, clock=lambda: clock[0])
    s1 = ["c", "d", "e"]
    bl.record_failure("c", slice_id=1, slice_hosts=s1)
    # 1/3 failed: no quorum yet — healthy members stay admissible
    assert bl.is_admissible("d") and bl.is_admissible("e")
    assert bl.blacklisted_slices() == []
    bl.record_failure("d", slice_id=1, slice_hosts=s1)
    # 2/3 failed: strict majority — the whole slice is out
    assert not bl.is_admissible("e")
    assert bl.blacklisted_slices() == [1]
    # slice 0 hosts untouched
    assert bl.is_admissible("a")
    # cooldown elapses: implicit re-admission, slice drops off the list
    clock[0] = 1000.0
    assert bl.is_admissible("e")
    assert bl.blacklisted_slices() == []


def test_blacklist_two_host_slice_needs_both():
    bl = HostBlacklist(cooldown_base=10.0, clock=lambda: 0.0)
    bl.record_failure("a", slice_id=0, slice_hosts=["a", "b"])
    assert bl.is_admissible("b")  # 1/2 is not a strict majority
    bl.record_failure("b", slice_id=0, slice_hosts=["a", "b"])
    assert bl.blacklisted_slices() == [0]


def test_blacklist_slice_quorum_can_retrigger_after_readmission():
    """A persistently bad slice must be holdable-out AGAIN after its
    first wholesale hold expires — and only post-readmission failures
    count toward the fresh quorum."""
    clock = [0.0]
    bl = HostBlacklist(cooldown_base=10.0, clock=lambda: clock[0])
    hosts = ["a", "b"]
    bl.record_failure("a", slice_id=0, slice_hosts=hosts)
    bl.record_failure("b", slice_id=0, slice_hosts=hosts)
    assert bl.blacklisted_slices() == [0]
    clock[0] = 1000.0  # hold expired: clean window
    assert bl.blacklisted_slices() == []
    bl.record_failure("a", slice_id=0, slice_hosts=hosts)
    # one fresh failure is not a majority — stale failures don't count
    assert bl.blacklisted_slices() == []
    bl.record_failure("b", slice_id=0, slice_hosts=hosts)
    assert bl.blacklisted_slices() == [0]


def test_blacklist_without_slice_info_unchanged():
    bl = HostBlacklist(cooldown_base=10.0, clock=lambda: 0.0)
    assert bl.record_failure("h") == 1
    assert bl.blacklisted_slices() == []


# ---------------------------------------------------------------------------
# downgrade warnings (the silent no-op knob, fixed)
# ---------------------------------------------------------------------------


def test_engine_warns_on_unsupported_hierarchical_request(
    monkeypatch, hvd_caplog
):
    from horovod_tpu.runtime.engine import EagerEngine

    monkeypatch.setenv("HVDTPU_HIERARCHICAL_ALLREDUCE", "1")
    eng = EagerEngine()  # world=1: no plane, not capable
    assert eng.hierarchical is False
    assert eng._hier_capable is False
    assert "downgrading to flat" in hvd_caplog.text


def test_engine_rejects_unknown_dcn_compression(monkeypatch, hvd_caplog):
    from horovod_tpu.runtime.engine import EagerEngine

    monkeypatch.setenv("HVDTPU_DCN_COMPRESSION", "zstd")
    eng = EagerEngine()
    assert eng._dcn_wire is None
    assert "HVDTPU_DCN_COMPRESSION" in hvd_caplog.text


def test_slice_size_on_single_process_dev_topology(monkeypatch):
    """process_count=1 with chip-level slices (the 8-device dev world
    forced into 2): slice_size reports chips per slice, never 0."""
    from horovod_tpu import basics

    topo = basics.Topology(
        process_rank=0, process_count=1, local_rank=0, local_size=1,
        cross_rank=0, cross_size=1,
        devices=tuple(range(8)), num_slices=2, slice_id=0,
    )
    monkeypatch.setattr(basics, "_topology", topo)
    assert basics.slice_size() == 4


def test_apply_params_cannot_unpin_hierarchical(monkeypatch):
    """--hierarchical-allreduce pins the schedule: a tuned-params move
    carrying hierarchical=False must not flip a pinned engine flat."""
    from horovod_tpu.runtime.engine import EagerEngine
    from horovod_tpu.runtime.autotune import TunedParams

    eng = EagerEngine()
    eng._hier_capable = True
    eng._hier_pinned = True
    eng.hierarchical = True
    eng._apply_params(TunedParams(
        fusion_bytes=1 << 20, cycle_s=0.005,
        hierarchical_allreduce=False,
    ))
    assert eng.hierarchical is True
    # unpinned engines follow the tuner
    eng._hier_pinned = False
    eng._apply_params(TunedParams(
        fusion_bytes=1 << 20, cycle_s=0.005,
        hierarchical_allreduce=False,
    ))
    assert eng.hierarchical is False


def test_error_feedback_refuses_traced_input():
    import jax

    ef = ErrorFeedbackCompressor(BFloat16Compressor)

    def f(x):
        w, ctx = ef.compress(x, key="g")
        return ef.decompress(w, ctx)

    with pytest.raises(TypeError, match="cannot run inside jit"):
        jax.jit(f)(np.ones(4, np.float32))


def test_hierarchical_rejects_stateful_compressor_name():
    with pytest.raises(ValueError, match="unknown dcn compression"):
        hierarchical_allreduce(np.ones(4, np.float32),
                               compression="ef_bf16")


def test_cli_maps_num_slices_and_dcn_compression():
    from horovod_tpu.run import config_parser
    from horovod_tpu.run.runner import parse_args

    args = parse_args([
        "-np", "4", "--num-slices", "2", "--dcn-compression", "bf16",
        "--hierarchical-allreduce", "python", "x.py",
    ])
    env = {}
    config_parser.set_env_from_args(env, args)
    assert env["HVDTPU_NUM_SLICES"] == "2"
    assert env["HVDTPU_DCN_COMPRESSION"] == "bf16"
    assert env["HVDTPU_HIERARCHICAL_ALLREDUCE"] == "1"


# ---------------------------------------------------------------------------
# 4-process forced-2x2 acceptance through the launcher
# ---------------------------------------------------------------------------


def _hier_fn():
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu._engine_registry import peek_engine
    from horovod_tpu.obs import get_registry

    hvd.init()
    r = hvd.rank()
    outs = []
    for i in range(6):
        out = hvd.allreduce(
            np.arange(16, dtype=np.float32) * (i + 1) + r,
            op=hvd.Sum, name=f"g{i}",
        )
        outs.append(np.asarray(out).tolist())
    eng = peek_engine()
    counters = {
        m["name"]: m.get("value")
        for m in get_registry().snapshot()
        if not m.get("tags")
    }
    return {
        "rank": r,
        "slice": hvd.slice_id(),
        "num_slices": hvd.num_slices(),
        "hier": eng.hierarchical,
        "capable": eng._hier_capable,
        "outs": outs,
        "dcn": counters.get("engine.dcn_bytes", 0),
        "ici": counters.get("engine.ici_bytes", 0),
        "ratio": counters.get("engine.dcn_compression_ratio", 0),
        "stats": dict(eng.stats),
    }


_MS_ENV = {
    "HVDTPU_EAGER_ENGINE": "python",
    "HVDTPU_SLICE_SIZE": "2",
    # one CPU device per worker keeps the 4-proc spawn light
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
}


@pytest.mark.multiprocess
def test_hierarchical_engine_bitwise_equals_flat_4proc():
    """Forced 2x2 world: the engine's hierarchical path produces
    BITWISE-identical results to the flat path (integer-valued floats
    sum exactly), DCN moved exactly 1/slice_procs of the ICI bytes, and
    slice ids follow the contiguous-block rule."""
    hier = hvdrun.run(_hier_fn, np=4, use_cpu=True, timeout=300,
                      env={**_MS_ENV, "HVDTPU_HIERARCHICAL_ALLREDUCE": "1"})
    flat = hvdrun.run(_hier_fn, np=4, use_cpu=True, timeout=300,
                      env=dict(_MS_ENV))
    for r, h in enumerate(hier):
        assert h["num_slices"] == 2
        assert h["slice"] == r // 2
        assert h["capable"] and h["hier"]
        assert h["outs"] == flat[r]["outs"], "hier != flat result"
        assert h["dcn"] > 0 and h["ici"] > 0
        assert h["dcn"] * 2 == h["ici"], (h["dcn"], h["ici"])
    for f in flat:
        # without the pin the engine stays flat (tuner off) and charges
        # the full payload to DCN — the cost the schedule avoids
        assert not f["hier"]
        assert f["dcn"] > 0 and f["ici"] == 0
    # single-slice world: NEITHER fabric counter moves, so the fabric
    # digest token and summary section stay absent (documented contract)
    single = hvdrun.run(
        _hier_fn, np=2, use_cpu=True, timeout=300,
        env={k: v for k, v in _MS_ENV.items()
             if k != "HVDTPU_SLICE_SIZE"},
    )
    for s in single:
        assert s["num_slices"] == 1
        assert s["dcn"] == 0 and s["ici"] == 0


@pytest.mark.multiprocess
def test_hierarchical_compressed_dcn_wire_4proc():
    hier = hvdrun.run(
        _hier_fn, np=4, use_cpu=True, timeout=300,
        env={
            **_MS_ENV,
            "HVDTPU_HIERARCHICAL_ALLREDUCE": "1",
            "HVDTPU_DCN_COMPRESSION": "bf16",
        },
    )
    flat = hvdrun.run(_hier_fn, np=4, use_cpu=True, timeout=300,
                      env=dict(_MS_ENV))
    for r, h in enumerate(hier):
        assert h["ratio"] == 2.0  # f32 wire / bf16 DCN leg
        # dcn bytes halve again: shard elements x 2B instead of x 4B
        assert h["dcn"] * 4 == h["ici"], (h["dcn"], h["ici"])
        ref = np.asarray(flat[r]["outs"], np.float64)
        got = np.asarray(h["outs"], np.float64)
        # slice-partial sums cross DCN in bf16: one cast round-trip
        assert np.abs(got - ref).max() <= 2 ** -7 * np.abs(ref).max() * 2


def _hier_replay_fn():
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu._engine_registry import peek_engine
    from horovod_tpu.obs import get_registry

    hvd.init()
    ok = True
    for i in range(60):
        out = hvd.allreduce(np.ones(8, np.float32), op=hvd.Sum, name="grad")
        ok = ok and float(np.asarray(out)[0]) == 4.0
    eng = peek_engine()
    counters = {m["name"]: m.get("value") for m in get_registry().snapshot()
                if not m.get("tags")}
    return {"ok": ok, "stats": dict(eng.stats), "hier": eng.hierarchical,
            "dcn": counters.get("engine.dcn_bytes", 0),
            "ici": counters.get("engine.ici_bytes", 0)}


@pytest.mark.multiprocess
def test_hierarchical_replay_epoch_4proc():
    """Schedule replay composes with the hierarchical plane: the epoch
    check lane rides the hierarchical first buffer (psum_scatter + DCN
    psum + all_gather preserve a nonzero flag), negotiation is skipped
    in steady state, and every result stays correct."""
    results = hvdrun.run(
        _hier_replay_fn, np=4, use_cpu=True, timeout=300,
        env={
            **_MS_ENV,
            "HVDTPU_HIERARCHICAL_ALLREDUCE": "1",
            "HVDTPU_SCHEDULE_REPLAY_CYCLES": "5",
            "HVDTPU_CYCLE_TIME": "2",
        },
    )
    for r in results:
        assert r["ok"]
        assert r["hier"]
        assert r["stats"]["replay_epochs"] >= 1
        assert r["stats"]["replay_cycles"] > 0
        # replay appends the 1-elem flag lane (odd 9-elem buffers): the
        # dcn == ici / slice_procs identity must hold EXACTLY through
        # padded accounting
        assert r["dcn"] > 0 and r["dcn"] * 2 == r["ici"], (
            r["dcn"], r["ici"])


def _slice_blame_fn():
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.obs import get_registry

    hvd.init()
    for i in range(12):
        hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum, name=f"t{i}")
    return {
        "rank": hvd.rank(),
        "metrics": get_registry().snapshot(),
    }


@pytest.mark.multiprocess
def test_slice_tagged_straggler_blame_4proc():
    """A seeded delay on rank 2 (slice 1): the controller's attribution
    carries the slice tag, and the shared merger names slice 1 — the
    verdict the live digest and --stats-summary print."""
    results = hvdrun.run(
        _slice_blame_fn, np=4, use_cpu=True, timeout=300,
        env={
            **_MS_ENV,
            "HVDTPU_CYCLE_TIME": "2",
            # repeated delays so the seeded straggler dominates ordinary
            # startup skew (which can blame any slow-to-form rank once)
            "HVDTPU_FAULT_SPEC":
                "enqueue:rank=2:count=8:action=delay:400",
        },
    )
    verdict = obs_straggler.merge_blames(
        [r["metrics"] for r in results]
    )
    assert verdict is not None
    assert verdict["rank"] == 2
    assert verdict["slice"] == 1
    assert verdict["slice_blames"].get(1, 0) >= 4
