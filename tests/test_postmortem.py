"""Flight recorder + cross-rank post-mortem (ISSUE 4).

Covers the ring buffer itself (wrap-around, overwrite accounting,
thread-safety), every death-path flush (excepthook, SIGTERM, SIGABRT
via ``action=abort``, dump-only SIGUSR1), the new fault actions, the
analyzer (first failure, waiting states, schedule divergence, missing
black boxes), the ``/healthz`` probe, the CLI plumbing, and the 2-proc
acceptance: an elastic job crashed with ``action=abort`` on rank 1
yields a launcher-written ``postmortem.json`` that names rank 1, its
last collective, and rank 0's waiting state.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import urllib.request

import pytest

import horovod_tpu.elastic as elastic
from horovod_tpu.obs import flightrec, postmortem
from horovod_tpu.testing import faults
from horovod_tpu.utils import env as envmod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --------------------------------------------------------------------- ring


def test_ring_records_in_order_below_capacity():
    r = flightrec.FlightRecorder(capacity=16)
    for i in range(5):
        r.record("enqueue", name=f"t{i}", cycle=i, detail="ALLREDUCE")
    assert r.recorded == 5
    assert r.overwritten == 0
    snap = r.snapshot()
    assert [e["name"] for e in snap] == [f"t{i}" for i in range(5)]
    assert [e["seq"] for e in snap] == list(range(5))
    assert snap[0]["kind"] == "enqueue"
    assert snap[0]["detail"] == "ALLREDUCE"


def test_ring_wraparound_keeps_newest_and_counts_overwrites():
    r = flightrec.FlightRecorder(capacity=16)
    for i in range(40):
        r.record("e", name=f"t{i}")
    assert r.recorded == 40
    assert r.overwritten == 24
    snap = r.snapshot()
    assert len(snap) == 16
    assert snap[0]["name"] == "t24"  # oldest survivor
    assert snap[-1]["name"] == "t39"  # newest
    assert [e["seq"] for e in snap] == list(range(24, 40))


def test_ring_capacity_floor_and_env(monkeypatch):
    assert flightrec.FlightRecorder(capacity=1).capacity == \
        flightrec.MIN_CAPACITY
    monkeypatch.setenv(envmod.FLIGHTREC_CAPACITY, "99")
    assert flightrec.FlightRecorder().capacity == 99


def test_ring_thread_safety_under_concurrent_record():
    r = flightrec.FlightRecorder(capacity=64)
    n_threads, per_thread = 8, 500

    def pound(tid):
        for i in range(per_thread):
            r.record("e", name=f"{tid}.{i}", cycle=i)

    threads = [threading.Thread(target=pound, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert r.recorded == n_threads * per_thread
    assert r.overwritten == n_threads * per_thread - 64
    snap = r.snapshot()
    assert len(snap) == 64
    # every surviving slot is coherent (no torn writes): seqs strictly
    # ascending, and each event's fields belong to one record call
    seqs = [e["seq"] for e in snap]
    assert seqs == sorted(seqs) and len(set(seqs)) == 64
    for e in snap:
        tid, i = e["name"].split(".")
        assert e["cycle"] == int(i), e


def test_ring_dump_schema_and_exception(tmp_path):
    r = flightrec.FlightRecorder(capacity=16)
    r.record("enqueue", name="t0")
    try:
        raise ValueError("boom")
    except ValueError as exc:
        r.record_exception(exc, where="test")
    path = str(tmp_path / "flightrec.rank.0.json")
    doc = r.dump(path, rank=0, trigger="explicit")
    on_disk = json.load(open(path))
    assert on_disk == doc
    assert doc["schema"] == flightrec.SCHEMA
    assert doc["trigger"] == "explicit"
    assert doc["last_exception"]["type"] == "ValueError"
    assert "boom" in doc["last_exception"]["traceback"]
    assert doc["events"][-1]["kind"] == "exception"


def test_dump_flight_recorder_env_gating(tmp_path, monkeypatch):
    flightrec.reset_recorder()
    monkeypatch.delenv(envmod.FLIGHTREC_DUMP, raising=False)
    assert flightrec.dump_flight_recorder() is None
    monkeypatch.setenv(envmod.FLIGHTREC_DUMP, str(tmp_path))
    flightrec.record("enqueue", name="x")
    path = flightrec.dump_flight_recorder()
    assert path is not None and os.path.exists(path)
    assert "flightrec" in os.path.basename(path)
    flightrec.reset_recorder()


# ---------------------------------------------------- death-path subprocesses


def _run_victim(body: str, env: dict, tmp_path):
    """Run ``body`` in a fresh interpreter with the dump env armed."""
    script = (
        "import os, signal, sys\n"
        "from horovod_tpu.obs import flightrec\n"
        "flightrec.install_death_hooks()\n"
        + body
    )
    full_env = {
        **os.environ,
        "PYTHONPATH": REPO,
        "JAX_PLATFORMS": "cpu",
        envmod.FLIGHTREC_DUMP: str(tmp_path),
        "HVDTPU_RANK": "0",
        **env,
    }
    return subprocess.run(
        [sys.executable, "-c", script], env=full_env,
        capture_output=True, text=True, timeout=60,
    )


def _read_dump(tmp_path, rank=0):
    path = tmp_path / f"flightrec.rank.{rank}.json"
    assert path.exists(), list(tmp_path.iterdir())
    return json.loads(path.read_text())


def test_excepthook_flushes_ring_and_metrics(tmp_path):
    proc = _run_victim(
        # touching the registry arms its dump hook, like hvd.init does
        "from horovod_tpu.obs import get_registry\n"
        "get_registry().counter('test.events').inc()\n"
        "flightrec.record('enqueue', name='t0')\n"
        "raise ValueError('chaos')\n",
        {envmod.METRICS_DUMP: str(tmp_path)}, tmp_path,
    )
    assert proc.returncode == 1
    assert "ValueError" in proc.stderr  # previous hook still chained
    doc = _read_dump(tmp_path)
    assert doc["trigger"] == "excepthook"
    assert doc["last_exception"]["type"] == "ValueError"
    kinds = [e["kind"] for e in doc["events"]]
    assert "enqueue" in kinds and "exception" in kinds
    # satellite: the metrics dump rode the same death path (atexit
    # would also have fired here, but the trigger proves the hook ran)
    metrics = list(tmp_path.glob("metrics.*rank*.json"))
    assert metrics, "metrics dump did not ride the death-path flush"


def test_sigterm_flushes_then_dies_by_signal(tmp_path):
    proc = _run_victim(
        "flightrec.record('enqueue', name='t0')\n"
        "print('READY', flush=True)\n"
        "signal.raise_signal(signal.SIGTERM)\n"
        "print('UNREACHABLE', flush=True)\n",
        {}, tmp_path,
    )
    assert proc.returncode == -signal.SIGTERM  # honest exit status
    assert "UNREACHABLE" not in proc.stdout
    doc = _read_dump(tmp_path)
    assert doc["trigger"] == "signal:SIGTERM"
    assert doc["events"][-1]["kind"] == "signal"
    assert doc["events"][-1]["name"] == "SIGTERM"


def test_action_abort_dumps_then_aborts(tmp_path):
    proc = _run_victim(
        "from horovod_tpu.testing.faults import maybe_fail\n"
        "flightrec.record('complete', name='t1')\n"
        "maybe_fail('boom')\n"
        "print('UNREACHABLE', flush=True)\n",
        {"HVDTPU_FAULT_SPEC": "boom:action=abort"}, tmp_path,
    )
    assert proc.returncode == -signal.SIGABRT
    assert "UNREACHABLE" not in proc.stdout
    doc = _read_dump(tmp_path)
    assert doc["trigger"] == "signal:SIGABRT"
    kinds = [e["kind"] for e in doc["events"]]
    assert "fault" in kinds  # the injection black-boxed itself


def test_sigusr1_dumps_without_killing(tmp_path):
    proc = _run_victim(
        "flightrec.record('enqueue', name='t0')\n"
        "signal.raise_signal(signal.SIGUSR1)\n"
        "import json\n"
        "doc = json.load(open(os.path.join("
        f"{str(tmp_path)!r}, 'flightrec.rank.0.json')))\n"
        "print('TRIGGER=' + doc['trigger'], flush=True)\n"
        "os._exit(0)\n",  # skip atexit so the mid-run dump survives
        {}, tmp_path,
    )
    assert proc.returncode == 0, proc.stderr
    assert "TRIGGER=signal:SIGUSR1" in proc.stdout
    doc = _read_dump(tmp_path)
    assert doc["trigger"] == "signal:SIGUSR1"


def test_install_hooks_then_on_death_flushes_once(tmp_path):
    # worker entry points call install_death_hooks() BEFORE the first
    # get_registry() registers its on_death flusher; the atexit leg must
    # still run exactly once (a double flush would publish the final
    # live delta twice)
    proc = _run_victim(
        "flightrec.on_death(lambda: print('FLUSH', flush=True))\n",
        {}, tmp_path,
    )
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.count("FLUSH") == 1, proc.stdout


def test_death_trigger_is_sticky_over_atexit(tmp_path):
    # a caught-then-returned failure flushes "exception"; the atexit leg
    # that still runs must not relabel the dump as a routine exit
    proc = _run_victim(
        "flightrec.record('enqueue', name='t0')\n"
        "flightrec.flush('exception')\n"
        "sys.exit(1)\n",
        {}, tmp_path,
    )
    assert proc.returncode == 1
    assert _read_dump(tmp_path)["trigger"] == "exception"


# ------------------------------------------------------------- fault actions


def test_fault_action_abort_parses():
    (spec,) = faults.parse_spec("enqueue:rank=1:action=abort")
    assert spec.action == "abort" and spec.rank == 1


def test_fault_action_raise_named_exception(monkeypatch):
    (spec,) = faults.parse_spec("p:action=raise:FloatingPointError")
    assert spec.exc_name == "FloatingPointError"
    monkeypatch.setenv(faults.SPEC_ENV, "p:action=raise:ValueError")
    faults.reset()
    with pytest.raises(ValueError, match="injected fault at 'p'"):
        faults.maybe_fail("p")
    faults.reset()


def test_fault_action_raise_rejects_non_exception():
    with pytest.raises(ValueError, match="not a builtin exception"):
        faults.parse_spec("p:action=raise:print")
    with pytest.raises(ValueError, match="not a builtin exception"):
        faults.parse_spec("p:action=raise:NoSuchExc")


# ---------------------------------------------------------------- analyzer


def _mk_dump(rank, trigger, events, epoch=0, t=100.0, last_exception=None,
             overwritten=0):
    return {
        "schema": flightrec.SCHEMA, "rank": rank, "pid": 1,
        "wall_time": t, "trigger": trigger, "epoch": epoch,
        "capacity": 512, "recorded": len(events),
        "overwritten": overwritten, "last_exception": last_exception,
        "events": [
            dict(seq=i, t=t - 1 + i * 1e-3, kind=k, name=n, cycle=i,
                 detail="")
            for i, (k, n) in enumerate(events)
        ],
    }


def test_analyze_names_first_failure_and_waiters():
    d0 = _mk_dump(0, "signal:SIGTERM",
                  [("enqueue", "a"), ("complete", "a"), ("enqueue", "b")],
                  t=105.0)
    d1 = _mk_dump(1, "signal:SIGABRT",
                  [("enqueue", "a"), ("complete", "a"),
                   ("fault", "enqueue")], t=101.0)
    rep = postmortem.analyze([d0, d1], expected_ranks=2)
    assert rep["first_failure"]["rank"] == 1
    assert rep["first_failure"]["trigger"] == "signal:SIGABRT"
    assert rep["first_failure"]["last_collective"] == "a"
    assert rep["last_common_collective"] == {"op": "a", "occurrence": 1}
    by_rank = {r["rank"]: r for r in rep["ranks"]}
    assert by_rank[0]["position"] == "waiting"
    assert by_rank[0]["waiting_on"] == "b"
    assert by_rank[1]["position"] == "running"
    v = postmortem.verdict(rep)
    assert "ank 1" in v and "'a'" in v and "'b'" in v


def test_analyze_clean_exit_positions():
    d0 = _mk_dump(0, "atexit", [("enqueue", "a"), ("complete", "a")])
    rep = postmortem.analyze([d0])
    assert rep["first_failure"] is None
    assert rep["ranks"][0]["position"] == "exited"
    assert "routine exit" in postmortem.verdict(rep)


def test_analyze_flags_missing_black_box():
    d0 = _mk_dump(0, "signal:SIGTERM", [("enqueue", "a")])
    rep = postmortem.analyze([d0], expected_ranks=3)
    assert rep["ranks_missing_dumps"] == [1, 2]
    v = postmortem.verdict(rep)
    assert "no black box" in v


def test_analyze_missing_rank_is_first_suspect_when_nobody_died():
    d0 = _mk_dump(0, "atexit", [("complete", "a")])
    rep = postmortem.analyze([d0], expected_ranks=2)
    assert rep["first_failure"]["rank"] == 1
    assert rep["first_failure"]["trigger"] == "no_black_box"


def test_schedule_divergence_detection():
    d0 = _mk_dump(0, "atexit", [("enqueue", "x"), ("enqueue", "y")])
    d1 = _mk_dump(1, "atexit", [("enqueue", "x"), ("enqueue", "z")])
    rep = postmortem.analyze([d0, d1])
    div = rep["schedule_divergence"]
    assert div == {"index": 1, "ops": {0: "y", 1: "z"}}
    assert "DIVERGENCE" in postmortem.verdict(rep)
    # a rank that merely died earlier is NOT divergent
    d2 = _mk_dump(1, "atexit", [("enqueue", "x")])
    assert postmortem.analyze([d0, d2])["schedule_divergence"] is None


def test_last_common_collective_counts_repeated_names():
    # real loops reuse names every step: the common instance must be
    # the 2nd 'g', not "some g from 100 steps ago"
    d0 = _mk_dump(0, "signal:SIGTERM",
                  [("complete", "g")] * 4, t=105.0)
    d1 = _mk_dump(1, "signal:SIGABRT",
                  [("complete", "g")] * 2, t=101.0)
    rep = postmortem.analyze([d0, d1])
    assert rep["last_common_collective"] == {"op": "g", "occurrence": 2}
    assert "instance #2" in postmortem.verdict(rep)


def test_streams_align_at_last_rendezvous_not_ring_start():
    # a survivor's ring spans epochs a respawned peer never lived
    # through; comparing from ring start would convict every recovered
    # elastic job of schedule divergence
    survivor = _mk_dump(0, "signal:SIGTERM",
                        [("enqueue", "g0"), ("complete", "g0"),
                         ("enqueue", "g1"), ("complete", "g1"),
                         ("rendezvous", "epoch1"),
                         ("enqueue", "g1"), ("complete", "g1"),
                         ("enqueue", "g2")], t=105.0)
    respawn = _mk_dump(1, "signal:SIGABRT",
                       [("rendezvous", "epoch1"),
                        ("enqueue", "g1"), ("complete", "g1"),
                        ("enqueue", "g2")], epoch=1, t=101.0)
    rep = postmortem.analyze([survivor, respawn])
    assert rep["schedule_divergence"] is None
    assert rep["last_common_collective"] == {"op": "g1", "occurrence": 1}


def test_first_failure_prefers_self_inflicted_over_sigterm_cascade():
    # host clocks skew: the SIGTERMed survivor's wall time reads
    # EARLIER than the real (SIGABRT) failure — trigger class must
    # outrank raw cross-host wall-clock comparison
    survivor = _mk_dump(0, "signal:SIGTERM", [("enqueue", "a")], t=99.0)
    culprit = _mk_dump(1, "signal:SIGABRT", [("fault", "enqueue")],
                       t=101.0)
    rep = postmortem.analyze([survivor, culprit])
    assert rep["first_failure"]["rank"] == 1


def test_last_common_collective_refuses_wrapped_rings():
    # a wrapped ring's window starts at an unknown true instance;
    # occurrence alignment would be confidently wrong, so decline
    d0 = _mk_dump(0, "signal:SIGTERM", [("complete", "g")] * 4,
                  overwritten=100)
    d1 = _mk_dump(1, "signal:SIGABRT", [("complete", "g")] * 2)
    assert postmortem.analyze([d0, d1])["last_common_collective"] is None


def test_latest_incarnation_wins(tmp_path):
    old = _mk_dump(1, "signal:SIGTERM", [("enqueue", "a")], epoch=0,
                   t=100.0)
    new = _mk_dump(1, "atexit", [("complete", "a")], epoch=2, t=90.0)
    rep = postmortem.analyze([old, new])
    # epoch beats wall time: the respawned incarnation is the last word
    assert rep["ranks"][0]["trigger"] == "atexit"


def test_load_dumps_skips_garbage(tmp_path):
    good = tmp_path / "flightrec.rank.0.json"
    good.write_text(json.dumps(_mk_dump(0, "atexit", [("enqueue", "a")])))
    (tmp_path / "flightrec.rank.1.json").write_text("{half a json")
    (tmp_path / "flightrec.rank.2.json").write_text(
        json.dumps({"schema": "something-else"})
    )
    dumps = postmortem.load_dumps(str(tmp_path))
    assert len(dumps) == 1 and dumps[0]["rank"] == 0


def test_generate_writes_report_and_cli(tmp_path, capsys):
    p = tmp_path / "flightrec.rank.0.json"
    p.write_text(json.dumps(
        _mk_dump(0, "excepthook", [("enqueue", "a")],
                 last_exception={"type": "ValueError", "message": "x",
                                 "where": "", "traceback": ""})
    ))
    hist = tmp_path / "live_history.jsonl"
    hist.write_text('{"round": 1, "ranks_reporting": 1}\n')
    rc = postmortem.main([str(tmp_path), "--expected-ranks", "1",
                          "--live-history", str(hist)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "ank 0" in out and "postmortem report:" in out
    report = json.load(open(tmp_path / "postmortem.json"))
    assert report["schema"] == postmortem.REPORT_SCHEMA
    assert report["first_failure"]["rank"] == 0
    assert report["live_last_round"]["round"] == 1


def test_cli_returns_2_without_dumps(tmp_path):
    assert postmortem.main([str(tmp_path)]) == 2


def test_launcher_tag_never_claims_rank0(monkeypatch):
    # a launcher process inherits the job's dump env but has no rank:
    # its own artifact dumps must not clobber worker rank 0's files
    monkeypatch.delenv("HVDTPU_RANK", raising=False)
    monkeypatch.delenv("HVDTPU_ELASTIC_RANK", raising=False)
    monkeypatch.setattr(envmod, "_is_launcher", True)
    assert envmod.artifact_rank() == "launcher"
    assert "rank.launcher" in flightrec.resolve_dump_path("/x/")
    # an explicit worker rank wins over the mark (in-process API users)
    monkeypatch.setenv("HVDTPU_RANK", "3")
    assert envmod.artifact_rank() == "3"


def test_analyzer_ignores_launcher_dump():
    worker = _mk_dump(0, "signal:SIGTERM", [("enqueue", "a")])
    launcher = dict(_mk_dump(0, "atexit", []), rank="launcher")
    rep = postmortem.analyze([worker, launcher], expected_ranks=1)
    assert rep["ranks_with_dumps"] == [0]
    assert rep["ranks"][0]["trigger"] == "signal:SIGTERM"


# ------------------------------------------------------------------ healthz


def test_kvstore_healthz_is_unauthenticated_and_readonly():
    from horovod_tpu.run.rendezvous import KVStoreClient, KVStoreServer

    server = KVStoreServer()
    server.start()
    try:
        kv = KVStoreClient(f"127.0.0.1:{server.port}", server.secret)
        kv.put("s", "k", b"v")
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/healthz", timeout=5
        ).read()
        doc = json.loads(body)
        assert doc["status"] == "ok" and doc["keys"] == 1
    finally:
        server.stop()


# ------------------------------------------------------------- CLI plumbing


def test_cli_flightrec_dump_maps_to_env():
    from horovod_tpu.run import config_parser, runner

    args = runner.parse_args(
        ["-np", "1", "--flightrec-dump", "/tmp/bb", "true"]
    )
    env: dict = {}
    config_parser.set_env_from_args(env, args)
    assert env[envmod.FLIGHTREC_DUMP] == "/tmp/bb"


def test_cli_dump_grace_passes_through(monkeypatch):
    from horovod_tpu.run import runner

    seen = {}

    def fake_launch(command, np, **kwargs):
        seen.update(kwargs)
        return runner.ElasticJobResult()

    monkeypatch.setattr(runner, "launch_elastic_job", fake_launch)
    runner.main(["-np", "2", "--elastic", "--dump-grace-secs", "0",
                 "true"])
    assert seen["dump_grace_secs"] == 0.0
    runner.main(["-np", "2", "--elastic", "true"])
    assert seen["dump_grace_secs"] == 5.0


# -------------------------------------------------------- 2-proc acceptance


def _pm_train():
    import numpy as np  # noqa: PLC0415

    import horovod_tpu.elastic as elastic  # noqa: PLC0415

    ctx = elastic.context()
    state = elastic.State(w=np.zeros(2, dtype=np.float64), step=0)

    @elastic.run
    def loop(state):
        while state.step < 6:
            state.w = state.w + ctx.allreduce(
                np.ones(2), name=f"g{state.step}")
            state.step += 1
            state.commit()
        return state.step

    return loop(state)


@pytest.mark.multiprocess
def test_abort_on_rank1_yields_blaming_postmortem(tmp_path):
    """ISSUE 4 acceptance: ``action=abort`` on rank 1 leaves per-rank
    black boxes and a postmortem.json naming rank 1, its last
    collective, and rank 0's waiting state."""
    env = {
        "JAX_PLATFORMS": "cpu",
        "HVDTPU_FAULT_SPEC": "worker_exit:step=3:rank=1:action=abort",
        envmod.FLIGHTREC_DUMP: str(tmp_path),
    }
    with pytest.raises(RuntimeError):
        elastic.launch(_pm_train, np=2, env=env, max_retries=0,
                       timeout=120)
    dumps = sorted(p.name for p in tmp_path.glob("flightrec.*rank*"))
    assert len(dumps) == 2, dumps
    report = json.load(open(tmp_path / "postmortem.json"))
    assert report["schema"] == postmortem.REPORT_SCHEMA
    assert report["first_failure"]["rank"] == 1
    assert report["first_failure"]["trigger"] == "signal:SIGABRT"
    # rank 1 completed g0, g1 before aborting at its third submission
    assert report["first_failure"]["last_collective"] == "g1"
    by_rank = {r["rank"]: r for r in report["ranks"]}
    assert by_rank[0]["position"] == "waiting"
    assert by_rank[0]["waiting_on"] == "g2"
    v = report["verdict"]
    assert "ank 1" in v and "'g1'" in v and "'g2'" in v


@pytest.mark.multiprocess
def test_clean_elastic_run_writes_no_postmortem(tmp_path):
    env = {"JAX_PLATFORMS": "cpu", envmod.FLIGHTREC_DUMP: str(tmp_path)}
    results, _job = elastic.launch(_pm_train, np=2, env=env, timeout=120)
    assert sorted(results) == [0, 1]
    assert not (tmp_path / "postmortem.json").exists()
    # dumps still exist (user-provided target is kept) and read clean
    docs = [json.loads(p.read_text())
            for p in tmp_path.glob("flightrec.*rank*")]
    assert len(docs) == 2
    assert all(d["trigger"] == "atexit" for d in docs)
