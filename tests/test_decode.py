"""KV-cache decode path (models/decode.py) — the incremental dataflow
must match the full training forward exactly: per-position prefill
logits, and greedy continuations token-for-token.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.models.decode import (
    assign_slot, decode_step, generate, init_cache, prefill,
    prefill_scan, reset_slot,
)
from horovod_tpu.models.transformer import gpt


def _model(**overrides):
    common = dict(num_layers=2, num_heads=4, emb_dim=64, max_len=32,
                  vocab_size=256, dtype=jnp.float32,
                  attention_impl="reference")
    common.update(overrides)
    return gpt("nano", **common)


def _prompt(model, b=2, s=12, seed=0):
    return jnp.asarray(
        np.random.RandomState(seed).randint(
            0, model.cfg.vocab_size, (b, s)
        ),
        jnp.int32,
    )


@pytest.mark.parametrize("overrides", [
    {},                                        # MHA, learned positions
    {"pos_embedding": "rope"},                 # rotary
    {"num_kv_heads": 2},                       # GQA
    {"num_kv_heads": 1, "pos_embedding": "rope"},  # MQA + rope
])
def test_prefill_matches_full_forward(overrides):
    model = _model(**overrides)
    prompt = _prompt(model)
    params = model.init(jax.random.PRNGKey(0), prompt)
    want = model.apply(params, prompt)
    got, cache = jax.jit(
        lambda p, t: prefill(model.cfg, p, t)
    )(params, prompt)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-4, rtol=2e-4
    )
    np.testing.assert_array_equal(
        np.asarray(cache["pos"]), prompt.shape[1]
    )


@pytest.mark.parametrize("overrides", [
    {},                                        # MHA, learned positions
    {"pos_embedding": "rope"},                 # rotary
    {"num_kv_heads": 2},                       # GQA
    {"num_kv_heads": 1, "pos_embedding": "rope"},  # MQA + rope
])
def test_prefill_single_forward_bitwise_matches_scanned(overrides):
    """The satellite contract: the one-shot causal prefill and the
    token-by-token scanned path are the SAME computation — logits and
    the filled cache pinned bitwise, not just close."""
    model = _model(**overrides)
    prompt = _prompt(model, s=12, seed=9)
    params = model.init(jax.random.PRNGKey(9), prompt)
    single, c1 = jax.jit(
        lambda p, t: prefill(model.cfg, p, t)
    )(params, prompt)
    scanned, c2 = jax.jit(
        lambda p, t: prefill_scan(model.cfg, p, t)
    )(params, prompt)
    np.testing.assert_array_equal(np.asarray(single), np.asarray(scanned))
    np.testing.assert_array_equal(np.asarray(c1["k"]), np.asarray(c2["k"]))
    np.testing.assert_array_equal(np.asarray(c1["v"]), np.asarray(c2["v"]))
    np.testing.assert_array_equal(np.asarray(c1["pos"]),
                                  np.asarray(c2["pos"]))


def test_prefill_supports_zigzag_models():
    """A zigzag-layout model's forward demands explicit positions, but
    decode prompts are always contiguous — the single-forward prefill
    must supply them itself (review finding: it used to delegate
    positions=None into the zigzag guard) and stay bitwise equal to the
    scanned path, whose attend override never ran the zigzag schedule
    either."""
    from dataclasses import replace

    model = _model(pos_embedding="rope")
    prompt = _prompt(model, s=10, seed=17)
    params = model.init(jax.random.PRNGKey(17), prompt)
    zig = replace(model.cfg, attention_impl="zigzag")
    single, c1 = jax.jit(
        lambda p, t: prefill(zig, p, t)
    )(params, prompt)
    scanned, c2 = jax.jit(
        lambda p, t: prefill_scan(zig, p, t)
    )(params, prompt)
    np.testing.assert_array_equal(np.asarray(single), np.asarray(scanned))
    np.testing.assert_array_equal(np.asarray(c1["k"]), np.asarray(c2["k"]))
    # and identical to the reference-impl decode: the cache path never
    # runs the attention schedule the impl names
    ref, _ = jax.jit(
        lambda p, t: prefill(model.cfg, p, t)
    )(params, prompt)
    np.testing.assert_array_equal(np.asarray(single), np.asarray(ref))


def test_decode_step_extends_prefill():
    """One decode_step after prefill equals the full forward over the
    extended sequence's last position."""
    model = _model()
    prompt = _prompt(model, s=10, seed=1)
    nxt = _prompt(model, s=1, seed=2)[:, 0]
    params = model.init(jax.random.PRNGKey(1), prompt)
    _, cache = prefill(model.cfg, params, prompt)
    got, cache = decode_step(model.cfg, params, cache, nxt)
    full = model.apply(
        params, jnp.concatenate([prompt, nxt[:, None]], axis=1)
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(full[:, -1]), atol=2e-4, rtol=2e-4
    )
    np.testing.assert_array_equal(
        np.asarray(cache["pos"]), prompt.shape[1] + 1
    )


def test_generate_matches_full_forward_greedy():
    """Greedy cache decoding produces the same tokens as re-running the
    full forward at every step (the O(S^2)-per-token oracle)."""
    model = _model()
    prompt = _prompt(model, s=8, seed=3)
    params = model.init(jax.random.PRNGKey(2), prompt)
    steps = 6
    got = jax.jit(
        lambda p, t: generate(model.cfg, p, t, steps)
    )(params, prompt)

    seq = prompt
    want = []
    for _ in range(steps):
        logits = model.apply(params, seq)
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        want.append(nxt)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(
        np.asarray(got), np.asarray(jnp.stack(want, axis=1))
    )


def test_cache_validation():
    model = _model(moe_experts=4)
    with pytest.raises(ValueError, match="dense blocks only"):
        init_cache(model.cfg, 2)


def test_prefill_matches_windowed_forward():
    """Sliding-window models decode with the same band: cached-attention
    masking must match the flash kernel's window (review finding: a
    silently-full-context decode would drift from the trained model)."""
    model = _model(attention_impl="flash", attention_window=4,
                   flash_block_q=8, flash_block_k=8)
    prompt = _prompt(model, s=16, seed=4)
    params = model.init(jax.random.PRNGKey(3), prompt)
    want = model.apply(params, prompt)
    got, _ = jax.jit(
        lambda p, t: prefill(model.cfg, p, t)
    )(params, prompt)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-4, rtol=2e-4
    )


def test_decode_past_cache_end_poisons():
    """Writing past the cache clamps in XLA — the step must poison its
    logits instead of silently overwriting the last slot."""
    model = _model()
    prompt = _prompt(model, s=4, seed=5)
    params = model.init(jax.random.PRNGKey(4), prompt)
    _, cache = prefill(model.cfg, params, prompt, max_len=4)  # full
    logits, _ = decode_step(model.cfg, params, cache,
                            prompt[:, 0])  # pos == cache size
    assert not np.isfinite(np.asarray(logits)).any()


def test_sampled_generation():
    """Sampling: reproducible under a fixed key, top_k=1 degenerates to
    greedy, temperature>0 without a key raises."""
    model = _model()
    prompt = _prompt(model, s=6, seed=6)
    params = model.init(jax.random.PRNGKey(5), prompt)

    key = jax.random.PRNGKey(7)
    a = generate(model.cfg, params, prompt, 5, temperature=1.0, key=key)
    b = generate(model.cfg, params, prompt, 5, temperature=1.0, key=key)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert (np.asarray(a) < model.cfg.vocab_size).all()

    greedy = generate(model.cfg, params, prompt, 5)
    topk1 = generate(model.cfg, params, prompt, 5, temperature=0.5,
                     top_k=1, key=key)
    np.testing.assert_array_equal(np.asarray(topk1), np.asarray(greedy))

    with pytest.raises(ValueError, match="requires a PRNG key"):
        generate(model.cfg, params, prompt, 5, temperature=1.0)


def test_generate_eos_freezes_finished_rows():
    """``eos_id=``: rows that emit it repeat it as pad while unfinished
    rows keep producing exactly the tokens the eos-free run produces —
    a frozen row must never perturb its batch peers."""
    model = _model(pos_embedding="rope")
    prompt = _prompt(model, b=3, s=6, seed=8)
    params = model.init(jax.random.PRNGKey(8), prompt)
    steps = 6
    full = np.asarray(generate(model.cfg, params, prompt, steps))
    # Pick a token some row actually emits mid-stream so the freeze has
    # something real to freeze; fall back to an unused id (pure pad).
    eos = int(full[0, steps // 2])
    got = np.asarray(
        generate(model.cfg, params, prompt, steps, eos_id=eos)
    )
    for r in range(full.shape[0]):
        hits = np.flatnonzero(full[r] == eos)
        stop = hits[0] if hits.size else steps
        np.testing.assert_array_equal(got[r, :stop + 1],
                                      full[r, :stop + 1])
        assert (got[r, stop + 1:] == eos).all()


def test_generate_eos_unused_matches_plain():
    """An eos id the model never emits must leave generation untouched
    (the early-exit path is the same math, only gated)."""
    model = _model()
    prompt = _prompt(model, s=6, seed=10)
    params = model.init(jax.random.PRNGKey(10), prompt)
    plain = np.asarray(generate(model.cfg, params, prompt, 5))
    eos = int(model.cfg.vocab_size - 1)
    if eos in plain:  # pragma: no cover - vanishingly unlikely
        pytest.skip("sentinel token emitted by chance")
    got = np.asarray(
        generate(model.cfg, params, prompt, 5, eos_id=eos)
    )
    np.testing.assert_array_equal(got, plain)


def test_assign_slot_isolated_and_matches_single_stream():
    """The serving primitives: admitting a request into one slot of a
    busy pool (prompt right-padded to a bucket) leaves every other
    slot's K/V bitwise untouched, and the slot's greedy continuation
    equals single-stream ``generate`` token-for-token."""
    model = _model(pos_embedding="rope", num_kv_heads=2)
    cfg = model.cfg
    prompt = _prompt(model, b=1, s=7, seed=11)
    params = model.init(jax.random.PRNGKey(11), prompt)
    steps = 5
    want = np.asarray(generate(cfg, params, prompt, steps))[0]

    cache = init_cache(cfg, 4)
    other = _prompt(model, b=1, s=5, seed=12)[0]
    cache, _ = assign_slot(cfg, params, cache, 1, other)
    peer_k = np.asarray(cache["k"])[:, 1].copy()

    padded = jnp.zeros((16,), jnp.int32).at[:7].set(prompt[0])
    cache, last = assign_slot(cfg, params, cache, 2, padded, length=7)
    toks = [int(jnp.argmax(last))]
    cur = jnp.zeros((4,), jnp.int32).at[2].set(toks[0])
    active = jnp.zeros((4,), bool).at[2].set(True)
    for _ in range(steps - 1):
        logits, cache = decode_step(cfg, params, cache, cur,
                                    write_mask=active)
        toks.append(int(jnp.argmax(logits[2])))
        cur = cur.at[2].set(toks[-1])
    np.testing.assert_array_equal(np.asarray(toks), want)
    # peer slot bitwise untouched; frozen slots never advanced
    np.testing.assert_array_equal(np.asarray(cache["k"])[:, 1], peer_k)
    np.testing.assert_array_equal(
        np.asarray(cache["pos"]), [0, 5, 7 + steps - 1, 0]
    )


def test_reset_slot_clears_one_slot_only():
    model = _model()
    cfg = model.cfg
    params = model.init(jax.random.PRNGKey(13), _prompt(model))
    cache = init_cache(cfg, 3)
    cache, _ = assign_slot(cfg, params, cache, 0,
                           _prompt(model, b=1, s=4, seed=14)[0])
    cache, _ = assign_slot(cfg, params, cache, 2,
                           _prompt(model, b=1, s=6, seed=15)[0])
    keep = np.asarray(cache["k"])[:, 2].copy()
    cache = reset_slot(cache, 0)
    assert not np.asarray(cache["k"])[:, 0].any()
    np.testing.assert_array_equal(np.asarray(cache["pos"]), [0, 0, 6])
    np.testing.assert_array_equal(np.asarray(cache["k"])[:, 2], keep)


def test_legacy_scalar_pos_cache_still_decodes():
    """Pre-slot caches (scalar ``pos``, e.g. a pytree restored from an
    old checkpoint) broadcast into the per-slot layout on first use."""
    model = _model()
    prompt = _prompt(model, s=4, seed=16)
    params = model.init(jax.random.PRNGKey(16), prompt)
    _, cache = prefill(model.cfg, params, prompt)
    legacy = {"k": cache["k"], "v": cache["v"],
              "pos": jnp.asarray(4, jnp.int32)}
    want, _ = decode_step(model.cfg, params, cache, prompt[:, 0])
    got, out = decode_step(model.cfg, params, legacy, prompt[:, 0])
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert out["pos"].shape == (prompt.shape[0],)
