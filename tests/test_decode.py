"""KV-cache decode path (models/decode.py) — the incremental dataflow
must match the full training forward exactly: per-position prefill
logits, and greedy continuations token-for-token.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.models.decode import (
    decode_step, generate, init_cache, prefill,
)
from horovod_tpu.models.transformer import gpt


def _model(**overrides):
    common = dict(num_layers=2, num_heads=4, emb_dim=64, max_len=32,
                  vocab_size=256, dtype=jnp.float32,
                  attention_impl="reference")
    common.update(overrides)
    return gpt("nano", **common)


def _prompt(model, b=2, s=12, seed=0):
    return jnp.asarray(
        np.random.RandomState(seed).randint(
            0, model.cfg.vocab_size, (b, s)
        ),
        jnp.int32,
    )


@pytest.mark.parametrize("overrides", [
    {},                                        # MHA, learned positions
    {"pos_embedding": "rope"},                 # rotary
    {"num_kv_heads": 2},                       # GQA
    {"num_kv_heads": 1, "pos_embedding": "rope"},  # MQA + rope
])
def test_prefill_matches_full_forward(overrides):
    model = _model(**overrides)
    prompt = _prompt(model)
    params = model.init(jax.random.PRNGKey(0), prompt)
    want = model.apply(params, prompt)
    got, cache = jax.jit(
        lambda p, t: prefill(model.cfg, p, t)
    )(params, prompt)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-4, rtol=2e-4
    )
    assert int(cache["pos"]) == prompt.shape[1]


def test_decode_step_extends_prefill():
    """One decode_step after prefill equals the full forward over the
    extended sequence's last position."""
    model = _model()
    prompt = _prompt(model, s=10, seed=1)
    nxt = _prompt(model, s=1, seed=2)[:, 0]
    params = model.init(jax.random.PRNGKey(1), prompt)
    _, cache = prefill(model.cfg, params, prompt)
    got, cache = decode_step(model.cfg, params, cache, nxt)
    full = model.apply(
        params, jnp.concatenate([prompt, nxt[:, None]], axis=1)
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(full[:, -1]), atol=2e-4, rtol=2e-4
    )
    assert int(cache["pos"]) == prompt.shape[1] + 1


def test_generate_matches_full_forward_greedy():
    """Greedy cache decoding produces the same tokens as re-running the
    full forward at every step (the O(S^2)-per-token oracle)."""
    model = _model()
    prompt = _prompt(model, s=8, seed=3)
    params = model.init(jax.random.PRNGKey(2), prompt)
    steps = 6
    got = jax.jit(
        lambda p, t: generate(model.cfg, p, t, steps)
    )(params, prompt)

    seq = prompt
    want = []
    for _ in range(steps):
        logits = model.apply(params, seq)
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        want.append(nxt)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(
        np.asarray(got), np.asarray(jnp.stack(want, axis=1))
    )


def test_cache_validation():
    model = _model(moe_experts=4)
    with pytest.raises(ValueError, match="dense blocks only"):
        init_cache(model.cfg, 2)


def test_prefill_matches_windowed_forward():
    """Sliding-window models decode with the same band: cached-attention
    masking must match the flash kernel's window (review finding: a
    silently-full-context decode would drift from the trained model)."""
    model = _model(attention_impl="flash", attention_window=4,
                   flash_block_q=8, flash_block_k=8)
    prompt = _prompt(model, s=16, seed=4)
    params = model.init(jax.random.PRNGKey(3), prompt)
    want = model.apply(params, prompt)
    got, _ = jax.jit(
        lambda p, t: prefill(model.cfg, p, t)
    )(params, prompt)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-4, rtol=2e-4
    )


def test_decode_past_cache_end_poisons():
    """Writing past the cache clamps in XLA — the step must poison its
    logits instead of silently overwriting the last slot."""
    model = _model()
    prompt = _prompt(model, s=4, seed=5)
    params = model.init(jax.random.PRNGKey(4), prompt)
    _, cache = prefill(model.cfg, params, prompt, max_len=4)  # full
    logits, _ = decode_step(model.cfg, params, cache,
                            prompt[:, 0])  # pos == cache size
    assert not np.isfinite(np.asarray(logits)).any()


def test_sampled_generation():
    """Sampling: reproducible under a fixed key, top_k=1 degenerates to
    greedy, temperature>0 without a key raises."""
    model = _model()
    prompt = _prompt(model, s=6, seed=6)
    params = model.init(jax.random.PRNGKey(5), prompt)

    key = jax.random.PRNGKey(7)
    a = generate(model.cfg, params, prompt, 5, temperature=1.0, key=key)
    b = generate(model.cfg, params, prompt, 5, temperature=1.0, key=key)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert (np.asarray(a) < model.cfg.vocab_size).all()

    greedy = generate(model.cfg, params, prompt, 5)
    topk1 = generate(model.cfg, params, prompt, 5, temperature=0.5,
                     top_k=1, key=key)
    np.testing.assert_array_equal(np.asarray(topk1), np.asarray(greedy))

    with pytest.raises(ValueError, match="requires a PRNG key"):
        generate(model.cfg, params, prompt, 5, temperature=1.0)
