"""Minimum end-to-end slice (SURVEY.md §7): init -> mesh -> train step with
grads reduced through DistributedOptimizer under jit/shard_map -> loss
decreases and params stay identical across shards.

This is the TPU analog of the reference's examples/tensorflow2_mnist.py CI
smoke run (gen-pipeline.sh:134-232)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import shard_map
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.optim import DistributedOptimizer

N = 8


def test_linear_regression_converges():
    rng = np.random.RandomState(0)
    w_true = rng.randn(5, 1).astype(np.float32)
    X = rng.randn(64, 5).astype(np.float32)
    y = X @ w_true

    params = {"w": jnp.zeros((5, 1), jnp.float32)}
    tx = DistributedOptimizer(optax.sgd(0.2))
    opt_state = tx.init(params)

    def local_step(params, opt_state, xb, yb):
        def loss_fn(p):
            pred = xb @ p["w"]
            return jnp.mean((pred - yb) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        # loss averaged for reporting, like MetricAverageCallback
        return params, opt_state, hvd.allreduce(loss, op=hvd.Average)

    mesh = hvd.mesh("flat")
    step = jax.jit(
        shard_map(
            local_step,
            mesh=mesh,
            in_specs=(P(), P(), P(hvd.DP_AXIS), P(hvd.DP_AXIS)),
            out_specs=(P(), P(), P()),
            check_vma=False,
        )
    )

    losses = []
    for i in range(60):
        params, opt_state, loss = step(params, opt_state, X, y)
        losses.append(float(loss))
    assert losses[-1] < 1e-3, f"did not converge: {losses[-5:]}"
    assert losses[-1] < losses[0] * 1e-2
    np.testing.assert_allclose(np.asarray(params["w"]), w_true, atol=0.05)


def test_distribute_helper():
    """hvd.distribute: replicated state, batch sharded on dim 0."""
    from horovod_tpu.optim import distribute

    tx = DistributedOptimizer(optax.sgd(0.5))
    params = jnp.zeros((3,), jnp.float32)
    opt_state = tx.init(params)
    target = jnp.asarray([1.0, 2.0, 3.0])

    def local_step(p, s, batch):
        def loss_fn(p):
            return jnp.mean((batch @ p[None].T - batch @ target[None].T) ** 2)

        g = jax.grad(loss_fn)(p)
        u, s = tx.update(g, s, p)
        return optax.apply_updates(p, u), s

    step = distribute(local_step)
    batch = jnp.asarray(np.random.RandomState(1).randn(16, 3), np.float32)
    p, s = params, opt_state
    for _ in range(100):
        p, s = step(p, s, batch)
    np.testing.assert_allclose(np.asarray(p), np.asarray(target), atol=0.05)
