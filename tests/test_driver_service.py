"""Driver/task NIC discovery (reference: horovod/run/driver tests inside
test_run.py — task-server registration + interface intersection logic,
driven in-process against localhost servers)."""

import socket

import pytest

from horovod_tpu.run import driver_service as ds


def test_local_addresses_excludes_loopback():
    addrs = ds.local_addresses()
    for iface, lst in addrs.items():
        assert iface != "lo"
        for a in lst:
            assert not a.startswith("127.")


def test_signed_roundtrip_and_bad_signature():
    key = ds.make_secret()
    msg = ds._pack(key, {"op": "addresses"})
    assert ds._unpack(key, msg) == {"op": "addresses"}
    with pytest.raises(ValueError, match="signature"):
        ds._unpack("wrong-key", msg)


def test_task_server_addresses_and_probe():
    key = ds.make_secret()
    srv = ds.TaskServer(key)
    try:
        out = ds.probe("127.0.0.1", srv.port, key, {"op": "addresses"})
        assert out["addresses"] == ds.local_addresses()

        # probe: the server's own port is reachable; a dead port is not
        dead = socket.socket()
        dead.bind(("127.0.0.1", 0))
        dead_port = dead.getsockname()[1]
        dead.close()
        out = ds.probe(
            "127.0.0.1", srv.port, key,
            {"op": "probe", "candidates": [
                ["ifup", "127.0.0.1", srv.port],
                ["ifdown", "127.0.0.1", dead_port],
            ]},
        )
        assert out["reachable"] == ["ifup"]
    finally:
        srv.close()


def test_task_server_rejects_unsigned_request():
    key = ds.make_secret()
    srv = ds.TaskServer(key)
    try:
        with pytest.raises((ValueError, OSError)):
            ds.probe("127.0.0.1", srv.port, "attacker-key",
                     {"op": "addresses"}, timeout=3)
    finally:
        srv.close()


def test_discover_common_interfaces_two_hosts_localhost():
    """Two task servers standing in for two hosts: every iface that can
    reach the neighbor's task port survives the intersection."""
    key = ds.make_secret()
    a, b = ds.TaskServer(key), ds.TaskServer(key)
    try:
        ifaces = ds.discover_common_interfaces(
            [("127.0.0.1", a.port), ("127.0.0.1", b.port)], key
        )
        # Every non-loopback NIC of this machine is reachable from itself.
        assert ifaces == sorted(ds.local_addresses())
    finally:
        a.close()
        b.close()


def test_discover_single_host_queries_the_task_server():
    """One host: the answer must come from that host's task server (a
    remote single host is not the driver machine)."""
    key = ds.make_secret()
    srv = ds.TaskServer(key)
    try:
        out = ds.discover_common_interfaces([("127.0.0.1", srv.port)], key)
        assert out == sorted(ds.local_addresses())
    finally:
        srv.close()


def test_discover_no_tasks_answers_locally():
    assert ds.discover_common_interfaces([], ds.make_secret()) == sorted(
        ds.local_addresses()
    )


def test_task_server_survives_malformed_request():
    """A bad request must not kill the accept loop (the server would
    accept but never answer again)."""
    import socket as _s

    key = ds.make_secret()
    srv = ds.TaskServer(key)
    try:
        with _s.create_connection(("127.0.0.1", srv.port), timeout=5) as c:
            c.sendall(b"garbage\nnot-json\n")
        # malformed probe op payload (missing candidates) also survives
        with pytest.raises(Exception):
            ds.probe("127.0.0.1", srv.port, key, {"op": "probe"}, timeout=3)
        out = ds.probe("127.0.0.1", srv.port, key, {"op": "addresses"})
        assert out["addresses"] == ds.local_addresses()
    finally:
        srv.close()


def test_discover_nics_end_to_end_two_local_hosts():
    """Full driver flow: spawn task-server subprocesses for a 2-host job
    spec (both localhost), intersect, tear down (reference _run NIC
    discovery; CLI: hvdrun --discover-nics)."""
    from horovod_tpu.run.runner import discover_nics

    ifaces = discover_nics(hosts="localhost:1,localhost:1")
    assert ifaces == sorted(ds.local_addresses())


def test_discover_nics_cli_flag():
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.run", "--discover-nics"],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0
    assert out.stdout.strip()
