"""Observability plane (horovod_tpu/obs/): metrics registry types/tags/
dump schema, per-rank timeline merge (lanes, truncated-file tolerance),
the progress beat + workload-aware staleness policy, the end-of-job
summary table, and the engine/controller instrumentation seams."""

import json
import os
import threading

import numpy as np
import pytest

import horovod_tpu.obs as obs
from horovod_tpu.obs import progress as obs_progress
from horovod_tpu.obs import summary as obs_summary
from horovod_tpu.obs import timeline_merge
from horovod_tpu.obs.progress import ProgressPolicy
from horovod_tpu.obs.registry import resolve_dump_path
from horovod_tpu.runtime.timeline import Timeline, resolve_path


@pytest.fixture(autouse=True)
def _fresh_obs():
    obs.reset_registry()
    obs_progress.reset()
    yield
    obs.reset_registry()
    obs_progress.reset()


# ---------------------------------------------------------------------------
# registry: instrument types, tags, dump schema
# ---------------------------------------------------------------------------


def test_counter_gauge_histogram_basics():
    reg = obs.get_registry()
    c = reg.counter("ops.total")
    c.inc()
    c.inc(4)
    assert c.value == 5
    g = reg.gauge("queue.depth")
    g.set(7)
    g.set(3)
    assert g.value == 3.0
    h = reg.histogram("lat.ms")
    for v in (1.0, 2.0, 3.0, 100.0):
        h.observe(v)
    assert h.count == 4
    assert h.min == 1.0 and h.max == 100.0
    assert h.sum == pytest.approx(106.0)
    # bucketed quantiles are approximate but ordered and bounded
    assert h.quantile(0.5) <= h.quantile(0.99) <= 100.0


def test_registry_same_name_same_instrument_and_tags_split():
    reg = obs.get_registry()
    assert reg.counter("x") is reg.counter("x")
    a = reg.counter("x", rank="0")
    b = reg.counter("x", rank="1")
    assert a is not b
    a.inc()
    assert b.value == 0


def test_registry_kind_conflict_raises():
    reg = obs.get_registry()
    reg.counter("same.name")
    with pytest.raises(TypeError, match="already registered as counter"):
        reg.gauge("same.name")


def test_dump_schema(tmp_path):
    reg = obs.get_registry()
    reg.counter("a", k="v").inc(2)
    reg.gauge("b").set(1.5)
    reg.histogram("c").observe(10)
    path = str(tmp_path / "m.json")
    doc = reg.dump(path, rank="3")
    on_disk = json.loads(open(path).read())
    assert on_disk == json.loads(json.dumps(doc))
    assert on_disk["schema"] == "hvdtpu-metrics-v1"
    assert on_disk["rank"] == "3"
    by_name = {m["name"]: m for m in on_disk["metrics"]}
    assert by_name["a"]["type"] == "counter"
    assert by_name["a"]["tags"] == {"k": "v"}
    assert by_name["a"]["value"] == 2
    assert by_name["b"]["type"] == "gauge"
    assert by_name["c"]["type"] == "histogram"
    for field in ("count", "sum", "min", "max", "mean", "p50", "p90", "p99"):
        assert field in by_name["c"]


def test_collector_runs_at_snapshot_only():
    reg = obs.get_registry()
    calls = []

    def collect(r):
        calls.append(1)
        r.gauge("engine.stats.cycles").set(42)

    reg.register_collector(collect)
    assert calls == []
    snap = {m["name"]: m for m in reg.snapshot()}
    assert calls == [1]
    assert snap["engine.stats.cycles"]["value"] == 42.0


def test_broken_collector_does_not_lose_metrics():
    reg = obs.get_registry()
    reg.counter("survives").inc()
    reg.register_collector(lambda r: 1 / 0)
    names = [m["name"] for m in reg.snapshot()]
    assert "survives" in names


def test_resolve_dump_path_forms(tmp_path, monkeypatch):
    monkeypatch.delenv("HVDTPU_ELASTIC_EPOCH", raising=False)
    d = str(tmp_path)
    assert resolve_dump_path(d, rank="2") == os.path.join(
        d, "metrics.rank.2.json"
    )
    assert resolve_dump_path("/x/m-{rank}.json", rank="2") == "/x/m-2.json"
    assert resolve_dump_path("/x/m.json", rank="2") == "/x/m.rank.2.json"
    monkeypatch.setenv("HVDTPU_ELASTIC_EPOCH", "1")
    assert resolve_dump_path("/x/m.json", rank="2") == "/x/m.e1.rank.2.json"


def test_dump_metrics_env_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setenv(obs.METRICS_DUMP_ENV, str(tmp_path))
    monkeypatch.setenv("HVDTPU_RANK", "5")
    monkeypatch.delenv("HVDTPU_ELASTIC_EPOCH", raising=False)
    obs.get_registry().counter("x").inc()
    written = obs.dump_metrics()
    assert written == os.path.join(str(tmp_path), "metrics.rank.5.json")
    assert json.loads(open(written).read())["rank"] == "5"


def test_dump_metrics_unconfigured_is_noop(monkeypatch):
    monkeypatch.delenv(obs.METRICS_DUMP_ENV, raising=False)
    assert obs.dump_metrics() is None


# ---------------------------------------------------------------------------
# progress beat: counter, phases, payload
# ---------------------------------------------------------------------------


def test_framework_tick_does_not_end_init_grace():
    """The epoch-start state sync is a completed collective (liveness)
    but NOT steady state: the user's first step — and its possibly very
    long jit compile — has not run yet, and the init/compile grace must
    survive until a USER-level collective completes."""
    obs_progress.tick(to_steady=False)
    assert obs_progress.value() == 1
    assert obs_progress.phase() == "init"  # grace window still open
    obs_progress.tick()
    assert obs_progress.phase() == "steady"


def test_progress_phases_and_ticks():
    assert obs_progress.phase() == "init"
    assert obs_progress.value() == 0
    obs_progress.tick()
    assert obs_progress.phase() == "steady"
    assert obs_progress.value() == 1
    obs.set_phase("compile")
    assert obs_progress.phase() == "compile"
    obs_progress.tick(3)  # any completed collective ends the phase
    assert obs_progress.phase() == "steady"
    assert obs_progress.value() == 4
    with pytest.raises(ValueError, match="unknown phase"):
        obs.set_phase("siesta")


def test_beat_payload_roundtrip_and_legacy():
    obs_progress.tick(7)
    p, ph, w = obs_progress.parse_beat(obs_progress.beat_payload())
    assert p == 7 and ph == "steady" and w is False
    with obs_progress.waiting():
        assert obs_progress.in_wait()
        _, _, w = obs_progress.parse_beat(obs_progress.beat_payload())
        assert w is True
    assert not obs_progress.in_wait()
    # legacy beats (plain repr(time.time())) parse to no-data
    assert obs_progress.parse_beat(b"1714.23") == (None, None, False)
    assert obs_progress.parse_beat(b"\xff\xfegarbage") == (None, None, False)


def _beat(p, ph, w=False):
    return json.dumps({"t": 0.0, "p": p, "ph": ph, "w": w}).encode()


def test_policy_steady_deadlock_declared_dead():
    pol = ProgressPolicy(steady_timeout=10.0)
    assert pol.observe(0, _beat(5, "steady"), now=100.0) is None
    assert pol.observe(0, _beat(5, "steady"), now=105.0) is None
    reason = pol.observe(0, _beat(5, "steady"), now=111.0)
    assert reason is not None and "steady" in reason


def test_policy_advancing_counter_never_dies():
    pol = ProgressPolicy(steady_timeout=10.0)
    for i, t in enumerate((100.0, 150.0, 200.0)):
        assert pol.observe(0, _beat(i, "steady"), now=t) is None


def test_policy_compile_phase_exempt_by_default():
    """grace_timeout=0: a long compile phase is never killed — that is
    the workload-aware half of the policy (acceptance: long compile
    under the grace window survives)."""
    pol = ProgressPolicy(steady_timeout=5.0, grace_timeout=0.0)
    assert pol.observe(0, _beat(3, "compile"), now=0.0) is None
    assert pol.observe(0, _beat(3, "compile"), now=10_000.0) is None
    # ... and init is covered by the same exemption
    assert pol.observe(1, _beat(0, "init"), now=0.0) is None
    assert pol.observe(1, _beat(0, "init"), now=10_000.0) is None


def test_policy_grace_budget_applies_when_set():
    pol = ProgressPolicy(steady_timeout=5.0, grace_timeout=60.0)
    assert pol.observe(0, _beat(3, "compile"), now=0.0) is None
    assert pol.observe(0, _beat(3, "compile"), now=30.0) is None  # under
    reason = pol.observe(0, _beat(3, "compile"), now=61.0)
    assert reason is not None and "compile" in reason


def test_policy_waiting_rank_is_exempt():
    """A rank blocked inside a collective wait froze because of someone
    else — the policy must kill the hung peer, never the waiters (the
    original all-peers-shot failure mode of a naive counter rule)."""
    pol = ProgressPolicy(steady_timeout=5.0)
    assert pol.observe(0, _beat(5, "steady", w=True), now=0.0) is None
    assert pol.observe(0, _beat(5, "steady", w=True), now=1e6) is None
    # the same counter freeze while NOT waiting is culpable
    assert pol.observe(1, _beat(5, "steady", w=False), now=0.0) is None
    assert pol.observe(1, _beat(5, "steady", w=False), now=10.0) is not None


def test_policy_wait_transition_restarts_window():
    pol = ProgressPolicy(steady_timeout=5.0)
    assert pol.observe(0, _beat(5, "steady", w=True), now=0.0) is None
    # unblocking (w flips) restarts the window even with a frozen counter
    assert pol.observe(0, _beat(5, "steady", w=False), now=100.0) is None
    assert pol.observe(0, _beat(5, "steady", w=False), now=104.0) is None
    assert pol.observe(0, _beat(5, "steady", w=False), now=106.0) is not None


def test_policy_phase_change_restarts_window():
    pol = ProgressPolicy(steady_timeout=5.0, grace_timeout=100.0)
    assert pol.observe(0, _beat(3, "steady"), now=0.0) is None
    # dropping into compile re-arms the (grace) window even though the
    # counter did not move
    assert pol.observe(0, _beat(3, "compile"), now=4.0) is None
    assert pol.observe(0, _beat(3, "compile"), now=50.0) is None


def test_policy_disabled_and_legacy_beats_ignored():
    assert ProgressPolicy(0.0, 0.0).observe(0, _beat(1, "steady"), 1e9) is None
    pol = ProgressPolicy(steady_timeout=5.0)
    assert pol.observe(0, b"1714.0", now=0.0) is None
    assert pol.observe(0, b"1714.0", now=1e9) is None


def test_policy_forget_gives_successor_fresh_window():
    pol = ProgressPolicy(steady_timeout=10.0)
    pol.observe(0, _beat(5, "steady"), now=0.0)
    pol.forget(0)
    assert pol.observe(0, _beat(5, "steady"), now=100.0) is None


# ---------------------------------------------------------------------------
# timeline: per-rank paths, streaming format, merge
# ---------------------------------------------------------------------------


def test_timeline_resolve_path_forms(tmp_path, monkeypatch):
    monkeypatch.delenv("HVDTPU_ELASTIC_EPOCH", raising=False)
    assert resolve_path("/x/t.json", 1) == "/x/t.rank.1.json"
    assert resolve_path("/x/t-{rank}.json", 1) == "/x/t-1.json"
    d = str(tmp_path)
    assert resolve_path(d, 1) == os.path.join(d, "trace.rank.1.json")
    monkeypatch.setenv("HVDTPU_ELASTIC_EPOCH", "2")
    assert resolve_path("/x/t.json", 1) == "/x/t.e2.rank.1.json"


def test_timeline_clean_shutdown_is_valid_json_with_rank_pid(tmp_path):
    path = str(tmp_path / "t.json")
    tl = Timeline(path, rank=3)
    tl.start("g0", "ALLREDUCE")
    tl.end("g0", "ALLREDUCE")
    tl.shutdown()
    events = json.loads(open(path).read())
    real = [e for e in events if e.get("name") != "trace_complete"]
    assert {e["pid"] for e in real} == {3}
    assert events[-1]["name"] == "trace_complete"


def test_timeline_truncated_file_still_loads(tmp_path):
    """Crash-safety: a rank killed mid-job leaves a trace with no
    terminator (and possibly a half-written last line); load_events
    recovers every complete event."""
    path = str(tmp_path / "t.rank.0.json")
    tl = Timeline(path, rank=0)
    for i in range(5):
        tl.start(f"g{i}", "ALLREDUCE")
        tl.end(f"g{i}", "ALLREDUCE")
    tl.shutdown()
    text = open(path).read()
    # simulate the kill: drop the terminator and cut the last event line
    body = text[: text.rindex("{")]  # strip terminator event + "]"
    cut = body.rstrip().rstrip(",")
    cut = cut[: cut.rindex(",") + 1] + '{"ph": "B", "name": "half'
    open(path, "w").write(cut)
    with pytest.raises(ValueError):
        json.loads(open(path).read())
    events = timeline_merge.load_events(path)
    assert len(events) >= 8  # 10 complete events minus the mangled tail
    assert all(e.get("name") for e in events)


def test_timeline_mid_write_repair_unterminated_trailing_line(tmp_path):
    """A rank killed exactly mid-write leaves an UNTERMINATED trailing
    line (no newline, not even a closed JSON string); load_events must
    drop only that line and keep every complete event before it."""
    path = str(tmp_path / "t.rank.0.json")
    open(path, "w").write(
        "[\n"
        '{"ph": "B", "name": "a", "ts": 1, "pid": 0, "tid": 0},\n'
        '{"ph": "E", "name": "a", "ts": 2, "pid": 0, "tid": 0},\n'
        '{"ph": "B", "name": "b", "ts": 3, "pi'  # cut mid-key, no \n
    )
    events = timeline_merge.load_events(path)
    assert [e["name"] for e in events] == ["a", "a"]
    # the repaired events still merge into a valid Chrome trace
    out = str(tmp_path / "merged.json")
    n = timeline_merge.merge([path], out)
    assert n >= 2
    json.load(open(out))


def test_timeline_repair_single_partial_line_yields_empty(tmp_path):
    """Degenerate mid-write: the whole file is one unterminated line —
    repair converges to an empty event list, never an exception."""
    path = str(tmp_path / "t.rank.0.json")
    open(path, "w").write('{"ph": "B", "na')
    assert timeline_merge.load_events(path) == []


def test_timeline_merge_lanes_and_validity(tmp_path):
    for rank in (0, 1):
        tl = Timeline(str(tmp_path / f"t.rank.{rank}.json"), rank=rank)
        tl.start("g0", "ALLREDUCE")
        tl.end("g0", "ALLREDUCE")
        if rank == 0:
            tl.shutdown()  # rank 1 "dies": no terminator flushes late
        else:
            tl._queue.put(None)
            tl._writer.join(timeout=5)
    out = str(tmp_path / "merged.json")
    n = timeline_merge.merge(
        [str(tmp_path / "t.rank.0.json"), str(tmp_path / "t.rank.1.json")],
        out,
    )
    events = json.loads(open(out).read())  # MUST be valid JSON
    assert n == len([e for e in events if e.get("ph") != "M"])
    pids = {e["pid"] for e in events if e.get("ph") != "M"}
    assert pids == {0, 1}
    lane_names = {
        e["args"]["name"]
        for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    assert lane_names == {"rank 0", "rank 1"}


def test_timeline_merge_glob_plain_path(tmp_path, monkeypatch):
    monkeypatch.delenv("HVDTPU_ELASTIC_EPOCH", raising=False)
    raw = str(tmp_path / "trace.json")
    for rank in (0, 1):
        tl = Timeline(resolve_path(raw, rank), rank=rank)
        tl.mark_cycle()
        tl.start("g", "ALLREDUCE")
        tl.end("g", "ALLREDUCE")
        tl.shutdown()
    merged = timeline_merge.merge_glob(raw)
    assert merged == raw  # plain-path form merges back onto the raw path
    events = json.loads(open(raw).read())
    assert {e["pid"] for e in events} == {0, 1}
    # re-running the merge must not ingest its own output
    assert timeline_merge.merge_glob(raw) == raw
    assert {e["pid"] for e in json.loads(open(raw).read())} == {0, 1}


def test_timeline_merge_glob_nothing_to_merge(tmp_path):
    assert timeline_merge.merge_glob(str(tmp_path / "none.json")) is None


def test_rank_of_path_variants():
    assert timeline_merge.rank_of_path("/a/t.rank.3.json") == 3
    assert timeline_merge.rank_of_path("/a/t.e2.rank.11.json") == 11
    assert timeline_merge.rank_of_path("/a/trace-7.json") is None


def test_timeline_merge_epoch_incarnations_get_distinct_lanes(tmp_path):
    """A dead incarnation and its respawned successor both have
    perf_counter timestamps starting near zero — sharing a pid lane
    would overlay their lifetimes, so each (rank, epoch) gets its own
    lane, labelled with the epoch."""
    for tag in ("e0.rank.1", "e1.rank.1"):
        tl = Timeline(str(tmp_path / f"t.{tag}.json"), rank=1)
        tl.start("g", "ALLREDUCE")
        tl.end("g", "ALLREDUCE")
        tl.shutdown()
    out = str(tmp_path / "merged.json")
    timeline_merge.merge(
        [str(tmp_path / "t.e0.rank.1.json"),
         str(tmp_path / "t.e1.rank.1.json")], out)
    events = json.loads(open(out).read())
    pids = {e["pid"] for e in events if e.get("ph") != "M"}
    assert len(pids) == 2
    labels = {
        e["args"]["name"] for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    assert labels == {"rank 1", "rank 1 (epoch 1)"}


def test_launcher_cleans_stale_per_rank_files(tmp_path, monkeypatch):
    """A 2-rank run pointed at the same paths as an earlier 4-rank run
    must not inherit phantom lanes/columns from the leftovers."""
    from horovod_tpu.run.runner import _clean_stale_obs_files

    raw = str(tmp_path / "trace.json")
    for rank in range(4):
        (tmp_path / f"trace.rank.{rank}.json").write_text("[\n")
    (tmp_path / "trace.json").write_text("[]")  # merged output: kept
    (tmp_path / "metrics.rank.0.json").write_text("{}")
    _clean_stale_obs_files({
        "HVDTPU_TIMELINE": raw,
        "HVDTPU_METRICS_DUMP": str(tmp_path) + os.sep,
    })
    left = sorted(p.name for p in tmp_path.iterdir())
    assert left == ["trace.json"]


def test_pathspec_template_form_is_epoch_qualified(monkeypatch):
    """The {rank} template must not let a respawned incarnation
    overwrite its dead predecessor's file (the invariant holds for
    every value form)."""
    from horovod_tpu.obs import pathspec

    monkeypatch.setenv("HVDTPU_ELASTIC_EPOCH", "2")
    p = pathspec.resolve("/x/t-{rank}.json", "trace", 1)
    assert p == "/x/t-1.e2.json"
    assert pathspec.epoch_of_path(p) == 2
    monkeypatch.delenv("HVDTPU_ELASTIC_EPOCH")
    assert pathspec.resolve("/x/t-{rank}.json", "trace", 1) == "/x/t-1.json"


def test_cleanup_never_touches_untagged_files(tmp_path):
    """Deletion safety: cleanup only removes files carrying our rank
    tag, and skips template-form values entirely (their glob has no
    anchor and could match arbitrary user files)."""
    from horovod_tpu.run.runner import _clean_stale_obs_files

    (tmp_path / "m.notes.json").write_text("{}")  # user file
    (tmp_path / "m.0.json").write_text("{}")  # template-form leftover
    (tmp_path / "m.rank.1.json").write_text("{}")  # ours
    _clean_stale_obs_files(
        {"HVDTPU_METRICS_DUMP": str(tmp_path / "m.{rank}.json")}
    )
    assert (tmp_path / "m.notes.json").exists()  # template: no cleanup
    assert (tmp_path / "m.0.json").exists()
    _clean_stale_obs_files({"HVDTPU_METRICS_DUMP": str(tmp_path / "m.json")})
    assert (tmp_path / "m.notes.json").exists()  # no rank tag: kept
    assert not (tmp_path / "m.rank.1.json").exists()  # ours: removed


def test_beat_epoch_stamp_roundtrip():
    assert obs_progress.beat_epoch(obs_progress.beat_payload(epoch=3)) == 3
    assert obs_progress.beat_epoch(obs_progress.beat_payload()) is None
    assert obs_progress.beat_epoch(b"1714.0") is None


# ---------------------------------------------------------------------------
# summary table
# ---------------------------------------------------------------------------


def _write_dump(tmp_path, rank, metrics, epoch=None):
    obs.reset_registry()
    reg = obs.get_registry()
    for name, v in metrics.items():
        reg.counter(name).inc(v)
    tag = f"e{epoch}.rank.{rank}" if epoch else f"rank.{rank}"
    path = str(tmp_path / f"metrics.{tag}.json")
    reg.dump(path, rank=str(rank))
    return path


def test_summary_collect_and_format(tmp_path):
    _write_dump(tmp_path, 0, {"engine.collectives_completed": 10})
    _write_dump(tmp_path, 1, {"engine.collectives_completed": 9,
                              "elastic.recoveries": 1})
    table = obs_summary.summarize(str(tmp_path))
    assert table is not None
    lines = table.splitlines()
    assert "rank 0" in lines[0] and "rank 1" in lines[0]
    row = next(l for l in lines if l.startswith("engine.collectives"))
    assert "10" in row and "9" in row
    # a metric only one rank reported renders "-" for the others
    row = next(l for l in lines if l.startswith("elastic.recoveries"))
    assert "-" in row


def test_summary_tolerates_garbage_and_epoch_tags(tmp_path):
    _write_dump(tmp_path, 0, {"x": 1})
    _write_dump(tmp_path, 2, {"x": 3}, epoch=1)
    (tmp_path / "metrics.rank.9.json").write_text("{not json")
    dumps = obs_summary.collect_dumps(str(tmp_path))
    assert set(dumps) == {"0", "2@e1"}
    assert obs_summary.summarize(str(tmp_path / "missing")) is None


def test_summary_corrupt_dump_named_in_table_header(tmp_path):
    """A truncated per-rank dump is skipped but NAMED: the table header
    says which file was dropped and why, so a missing column reads as
    'dump was corrupt', never as 'rank never dumped'."""
    good = _write_dump(tmp_path, 0, {"x": 1})
    # simulate the mid-write kill: cut the good dump's twin in half
    text = open(good).read()
    (tmp_path / "metrics.rank.7.json").write_text(text[: len(text) // 2])
    # and a schema-invalid (valid-JSON) file alongside
    (tmp_path / "metrics.rank.8.json").write_text('{"rank": "8"}')
    dumps = obs_summary.collect_dumps(str(tmp_path))
    assert set(dumps) == {"0"}
    assert len(dumps.warnings) == 2
    assert any("metrics.rank.7.json" in w for w in dumps.warnings)
    assert any("metrics.rank.8.json" in w for w in dumps.warnings)
    table = obs_summary.format_summary_table(dumps)
    header = table.splitlines()[:3]
    assert any("WARNING" in line and "metrics.rank.7.json" in line
               for line in header)


def test_summary_goodput_section(tmp_path):
    from horovod_tpu.obs import goodput as obs_goodput

    obs.reset_registry()
    reg = obs.get_registry()
    led = obs_goodput.GoodputLedger(start=0.0)
    led.enter("productive_step", 3.0)
    led.epoch_start(1, 8.0)
    led.enter("productive_step", 9.0)
    led.publish(reg, 10.0)
    tg = obs_goodput.TokenGoodput(slots=4, start=0.0)
    tg.observe_step(3)
    tg.publish(reg, 1.0)
    path = str(tmp_path / "metrics.rank.0.json")
    reg.dump(path, rank="0")
    dumps = obs_summary.collect_dumps(str(tmp_path))
    section = obs_summary.goodput_section(dumps)
    assert section is not None
    # productive: (8-3) closed + (10-9) open = 6 of 10 total
    assert "goodput 60.0%" in section
    assert "recovery" in section and "lost rendezvous" in section
    assert "token goodput 75.0%" in section
    # training-only dumps produce no section
    assert obs_summary.goodput_section({"0": {"metrics": []}}) is None


def test_summary_slo_section(tmp_path):
    from horovod_tpu.obs import slo as obs_slo

    obs.reset_registry()
    reg = obs.get_registry()
    plane = obs_slo.SLOPlane(
        {"interactive": obs_slo.SLOTarget(ttft_ms=500.0)})
    for i in range(5):
        plane.observe_ttft("acme", "interactive", 900.0, float(i))
    plane.publish(reg, 5.0)
    path = str(tmp_path / "metrics.rank.0.json")
    reg.dump(path, rank="0")
    dumps = obs_summary.collect_dumps(str(tmp_path))
    section = obs_summary.slo_section(dumps)
    assert section is not None
    assert "acme/interactive ttft" in section
    assert "breaches 5" in section
    assert "ALERTS FIRED" in section
    assert obs_summary.slo_section({"0": {"metrics": []}}) is None


# ---------------------------------------------------------------------------
# instrumentation seams
# ---------------------------------------------------------------------------


def test_engine_single_process_registers_instruments():
    from horovod_tpu.runtime.engine import EagerEngine

    eng = EagerEngine()
    snap = {m["name"] for m in obs.get_registry().snapshot()}
    assert "engine.cycle_time_ms" in snap
    assert "engine.collectives_completed" in snap
    assert "engine.stats.cycles" in snap  # via the stats collector
    eng.shutdown()


def test_controller_stall_counter_increments(monkeypatch):
    import horovod_tpu.runtime.controller as ctl

    state = ctl.ControllerState(world_size=2)
    req = ctl.Request(
        request_rank=0,
        request_type=ctl.RequestType.ALLREDUCE,
        tensor_name="w",
        dtype="float32",
        shape=(2,),
    )
    state.message_table[req.key()] = ctl._TableEntry(requests={0: req})
    state.message_table[req.key()].first_seen -= 100.0
    state.last_stall_check -= 100.0
    ctl._check_stalls(state, warn_secs=1.0, shutdown_secs=0.0)
    snap = {
        (m["name"], m["tags"].get("tensor")): m
        for m in obs.get_registry().snapshot()
    }
    c = snap[("controller.stall_warnings", "w")]
    assert c["value"] == 1
    g = snap[("controller.stall_lagging_ranks", "w")]
    assert g["value"] == 1.0  # rank 1 is lagging


def test_checkpoint_metrics_single_process(tmp_path):
    import horovod_tpu as hvd

    hvd.init()
    from horovod_tpu.checkpoint import restore_checkpoint, save_checkpoint

    state = {"w": np.arange(3.0)}
    save_checkpoint(str(tmp_path / "ck"), state, step=1)
    restore_checkpoint(str(tmp_path / "ck"), state)
    snap = {m["name"]: m for m in obs.get_registry().snapshot()}
    assert snap["checkpoint.saves_started"]["value"] == 1
    assert snap["checkpoint.saves_committed"]["value"] == 1
    assert snap["checkpoint.restores"]["value"] == 1
    assert snap["checkpoint.commit_wait_ms"]["count"] == 1


def test_hang_fault_action_parses_and_blocks_thread(monkeypatch):
    """action=hang wedges only the calling thread — the signature the
    progress policy exists to catch (the full 4-proc version lives in
    test_elastic.py)."""
    from horovod_tpu.testing import faults

    monkeypatch.setenv(faults.SPEC_ENV, "spin:action=hang")
    faults.reset()
    started = threading.Event()

    def victim():
        started.set()
        faults.maybe_fail("spin")

    t = threading.Thread(target=victim, daemon=True)
    t.start()
    assert started.wait(5)
    t.join(timeout=0.5)
    assert t.is_alive()  # wedged, not raised/exited
    faults.reset()


def test_hang_fault_bad_action_rejected():
    from horovod_tpu.testing import faults

    with pytest.raises(ValueError, match="unknown fault action"):
        faults.parse_spec("x:action=explode")
