"""Goodput ledger (obs/goodput.py): decision-table transitions on a
fake clock, the tiling invariant (fractions sum to 1.0 with no gap and
no overlap), per-epoch lost-time attribution across elastic respawn,
the flightrec event-vocabulary mapping, the post-hoc event-fold
reconstruction, the serving token-goodput variant, and the live wiring
(flight-recorder tap + registry collector surviving reset_registry)."""

from __future__ import annotations

import pytest

import horovod_tpu.obs as obs
from horovod_tpu.obs import flightrec
from horovod_tpu.obs import goodput


@pytest.fixture(autouse=True)
def _fresh():
    obs.reset_registry()
    goodput.uninstall()
    yield
    goodput.uninstall()
    obs.reset_registry()


# ---------------------------------------------------------------------------
# ledger decision table (fake clock throughout — no call reads a clock)
# ---------------------------------------------------------------------------


def test_ledger_decision_table_tiles_the_wall_clock():
    led = goodput.GoodputLedger(start=0.0)
    led.enter("compile", 10.0)
    led.enter("productive_step", 30.0)
    led.enter("collective_wait", 40.0)
    led.resume(42.0)
    led.enter("checkpoint", 50.0)
    led.resume(53.0)
    led.epoch_start(1, 60.0, cause="rendezvous")
    led.enter("productive_step", 65.0)

    secs = led.secs(100.0)
    assert secs["init"] == pytest.approx(10.0)
    assert secs["compile"] == pytest.approx(20.0)
    assert secs["collective_wait"] == pytest.approx(2.0)
    assert secs["checkpoint"] == pytest.approx(3.0)
    assert secs["recovery"] == pytest.approx(5.0)
    # productive: (40-30) + (50-42) + (60-53) + (100-65)
    assert secs["productive_step"] == pytest.approx(60.0)
    assert sum(secs.values()) == pytest.approx(100.0)

    fr = led.fractions(100.0)
    assert sum(fr.values()) == pytest.approx(1.0, abs=1e-6)
    assert fr["productive_step"] == pytest.approx(0.6)


def test_resume_returns_to_the_interrupted_class():
    # a checkpoint taken during COMPILE must resume compile, not
    # productive time
    led = goodput.GoodputLedger(start=0.0, cls="compile")
    led.enter("checkpoint", 5.0)
    led.resume(7.0)
    assert led.current == "compile"
    # nested excursion: ckpt during a collective wait resumes the wait's
    # own resume target (the pre-excursion class), never the excursion
    led.enter("productive_step", 10.0)
    led.enter("collective_wait", 12.0)
    led.enter("checkpoint", 13.0)
    led.resume(14.0)
    assert led.current == "productive_step"


def test_lost_time_charged_to_the_epoch_that_paid_for_it():
    """Acceptance decision table: an elastic respawn's recovery seconds
    land under the NEW epoch, keyed by cause."""
    led = goodput.GoodputLedger(start=0.0)
    led.enter("productive_step", 2.0)
    led.epoch_start(1, 10.0, cause="rendezvous")   # epoch 1 begins
    led.enter("productive_step", 16.0)             # 6s rendezvous
    led.epoch_start(2, 20.0, cause="respawn")      # epoch 2 begins
    led.enter("productive_step", 29.0)             # 9s respawn

    lost = led.lost(40.0)
    assert lost == {1: {"rendezvous": pytest.approx(6.0)},
                    2: {"respawn": pytest.approx(9.0)}}
    by_epoch = led.by_epoch(40.0)
    assert by_epoch[1]["recovery"] == pytest.approx(6.0)
    assert by_epoch[2]["recovery"] == pytest.approx(9.0)
    # epoch 0 never saw recovery
    assert "recovery" not in by_epoch[0]
    # and the tiling invariant still holds across all three epochs
    assert sum(led.secs(40.0).values()) == pytest.approx(40.0)
    assert sum(led.fractions(40.0).values()) == pytest.approx(1.0,
                                                              abs=1e-6)


def test_backwards_clock_clamps_to_zero_length():
    led = goodput.GoodputLedger(start=100.0)
    led.enter("productive_step", 90.0)  # wall clock stepped back
    secs = led.secs(110.0)
    assert secs["init"] == 0.0
    assert secs["productive_step"] == pytest.approx(10.0)
    assert all(s >= 0.0 for s in secs.values())


def test_empty_ledger_fractions_are_zero_not_nan():
    led = goodput.GoodputLedger(start=5.0)
    assert led.fractions(5.0) == {c: 0.0 for c in goodput.CLASSES}


def test_unknown_class_rejected():
    led = goodput.GoodputLedger(start=0.0)
    with pytest.raises(ValueError):
        led.enter("napping", 1.0)
    with pytest.raises(ValueError):
        goodput.GoodputLedger(start=0.0, cls="napping")


# ---------------------------------------------------------------------------
# event vocabulary -> transitions
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kind,name,expect", [
    ("phase", "init", ("init", None)),
    ("phase", "compile", ("compile", None)),
    ("phase", "steady", ("productive_step", None)),
    ("phase", "mystery", None),
    ("rendezvous", "epoch2", ("recovery", "rendezvous")),
    ("ckpt.begin", "", ("checkpoint", None)),
    ("ckpt.commit", "", ("resume", None)),
    ("ckpt.error", "", ("resume", None)),
    ("ckpt.restore_disk", "", ("recovery", "respawn")),
    ("init", "serve_replay", ("recovery", "respawn")),
    ("init", "basics", None),
    ("stall", "", ("recovery", "stall")),
    ("signal", "SIGTERM", ("degraded", None)),
    ("exception", "ValueError", ("degraded", None)),
    ("enqueue", "ALLREDUCE", None),
    ("complete", "ALLREDUCE", None),
])
def test_classify_event_table(kind, name, expect):
    assert goodput.classify_event(kind, name) == expect


def test_ledger_from_events_chaos_run_with_respawn():
    """The acceptance chaos shape, post-hoc: init -> compile -> steady,
    a checkpoint excursion, a rendezvous into epoch 1 (elastic
    respawn), steady again — fractions must sum to 1.0 (±1e-6) and the
    lost time must land under epoch 1."""
    events = [
        {"t": 0.0, "kind": "phase", "name": "init"},
        {"t": 4.0, "kind": "phase", "name": "compile"},
        {"t": 14.0, "kind": "phase", "name": "steady"},
        {"t": 20.0, "kind": "ckpt.begin", "name": "v1"},
        {"t": 22.0, "kind": "ckpt.commit", "name": "v1"},
        {"t": 30.0, "kind": "rendezvous", "name": "epoch1", "cycle": 1},
        {"t": 36.0, "kind": "phase", "name": "steady"},
        {"t": 40.0, "kind": "enqueue", "name": "ALLREDUCE"},  # ignored
    ]
    led = goodput.ledger_from_events(events, start=0.0, end=50.0)
    secs = led.secs()
    assert secs["init"] == pytest.approx(4.0)
    assert secs["compile"] == pytest.approx(10.0)
    assert secs["checkpoint"] == pytest.approx(2.0)
    assert secs["recovery"] == pytest.approx(6.0)
    assert secs["productive_step"] == pytest.approx(28.0)
    assert sum(led.fractions().values()) == pytest.approx(1.0, abs=1e-6)
    assert led.lost() == {1: {"rendezvous": pytest.approx(6.0)}}
    assert led.epoch == 1


def test_ledger_from_events_unstamped_rendezvous_increments_epoch():
    events = [
        {"t": 0.0, "kind": "phase", "name": "steady"},
        {"t": 5.0, "kind": "rendezvous", "name": "epochX"},  # no cycle
        {"t": 8.0, "kind": "phase", "name": "steady"},
    ]
    led = goodput.ledger_from_events(events, start=0.0, end=10.0)
    assert led.epoch == 1
    assert led.lost() == {1: {"rendezvous": pytest.approx(3.0)}}


def test_summary_document_shape():
    led = goodput.GoodputLedger(start=0.0)
    led.enter("productive_step", 5.0)
    led.epoch_start(1, 8.0, cause="stall")
    led.enter("productive_step", 9.0)
    doc = led.summary(10.0)
    # productive: (8-5) closed + (10-9) open = 4 of 10 total
    assert doc["fraction"] == pytest.approx(0.4)
    assert doc["secs"]["init"] == pytest.approx(5.0)
    assert "idle" not in doc["secs"]  # zero classes are elided
    assert doc["lost"] == {"1": {"stall": 1.0}}


# ---------------------------------------------------------------------------
# publishing
# ---------------------------------------------------------------------------


def test_publish_gauges_land_in_registry():
    led = goodput.GoodputLedger(start=0.0)
    led.enter("productive_step", 4.0)
    led.epoch_start(1, 8.0)
    led.enter("productive_step", 9.0)
    reg = obs.get_registry()
    led.publish(reg, 10.0)
    snap = {(m["name"], tuple(sorted((m.get("tags") or {}).items()))): m
            for m in reg.snapshot()}
    # productive: (8-4) closed + (10-9) open = 5 of 10 total
    assert snap[("goodput.fraction", ())]["value"] == pytest.approx(0.5)
    assert snap[("goodput.secs", (("class", "init"),))]["value"] \
        == pytest.approx(4.0)
    assert snap[("goodput.lost_secs", (("cause", "rendezvous"),))][
        "value"] == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# serving token goodput
# ---------------------------------------------------------------------------


def test_token_goodput_fraction_and_rate():
    tg = goodput.TokenGoodput(slots=4, start=100.0)
    assert tg.fraction() == 0.0
    for _ in range(3):
        tg.observe_step(3)
    tg.observe_step(0)  # idle step still burns capacity
    assert tg.fraction() == pytest.approx(9 / 16)
    assert tg.per_slot_second(102.0) == pytest.approx(9 / (2.0 * 4))
    reg = obs.get_registry()
    tg.publish(reg, 102.0)
    snap = {m["name"]: m for m in reg.snapshot()}
    assert snap["serve.goodput.token_fraction"]["value"] \
        == pytest.approx(9 / 16, abs=1e-6)
    assert snap["serve.goodput.tokens_per_slot_sec"]["value"] \
        == pytest.approx(1.125, abs=1e-3)


# ---------------------------------------------------------------------------
# live wiring: flightrec tap + collector
# ---------------------------------------------------------------------------


def test_install_tap_feeds_ledger_from_flightrec_events():
    led = goodput.install(now=0.0)
    assert goodput.get_ledger() is led
    flightrec.record("phase", name="steady")
    assert led.current == "productive_step"
    flightrec.record("rendezvous", name="epoch3", cycle=3)
    assert led.current == "recovery"
    assert led.epoch == 3
    flightrec.record("enqueue", name="ALLREDUCE")  # no transition
    assert led.current == "recovery"


def test_collector_publishes_into_dump_snapshot():
    goodput.install(now=0.0)
    flightrec.record("phase", name="steady")
    names = {m["name"] for m in obs.get_registry().snapshot()}
    assert "goodput.fraction" in names
    assert "goodput.secs" in names


def test_collector_survives_registry_reset_and_reinstall():
    goodput.install(now=0.0)
    obs.reset_registry()  # fresh registry: the old hook is gone
    goodput.install(now=1.0)  # re-arm registers on the NEW instance
    names = {m["name"] for m in obs.get_registry().snapshot()}
    assert "goodput.fraction" in names


def test_uninstalled_tap_is_a_noop():
    goodput.install(now=0.0)
    goodput.uninstall()
    flightrec.record("phase", name="steady")  # must not raise
    assert goodput.get_ledger() is None
    names = {m["name"] for m in obs.get_registry().snapshot()}
    assert "goodput.fraction" not in names
