"""Cluster-integration tests (reference: horovod.spark.run semantics,
test_spark.py's driver/task registration coverage — SURVEY.md §2.7).

The generic protocol (register -> host-hash rank assignment -> function
shipping -> result collection) is exercised end-to-end with the local
subprocess executor; the rank-assignment math is unit-tested against the
reference's barrel-shift behavior (spark/runner.py:186-205)."""

import numpy as np
import pytest

from horovod_tpu.cluster import assign_ranks, local_executor, run_on_cluster

pytestmark = pytest.mark.multiprocess


def test_assign_ranks_single_host():
    slots = assign_ranks({0: "hostA", 1: "hostA", 2: "hostA"})
    assert [s["rank"] for s in slots] == [0, 1, 2]
    assert [s["local_rank"] for s in slots] == [0, 1, 2]
    assert all(s["local_size"] == 3 and s["cross_size"] == 1 for s in slots)


def test_assign_ranks_multi_host_barrel_shift():
    # task 0 lives on hostB: the barrel shift must make hostB the first
    # host so rank 0 is task 0's host (reference spark/runner.py:186-190)
    slots = assign_ranks({0: "hostB", 1: "hostA", 2: "hostB", 3: "hostA"})
    assert slots[0]["rank"] == 0 and slots[0]["cross_rank"] == 0
    assert slots[2]["rank"] == 1 and slots[2]["local_rank"] == 1
    assert slots[1]["rank"] == 2 and slots[1]["cross_rank"] == 1
    assert slots[3]["rank"] == 3
    assert all(s["local_size"] == 2 and s["cross_size"] == 2 for s in slots)
    # ranks are a permutation of 0..n-1
    assert sorted(s["rank"] for s in slots) == [0, 1, 2, 3]


def _cluster_fn(scale):
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    r = hvd.rank()
    out = hvd.allreduce(np.full(3, float(r + 1) * scale, np.float32),
                        op=hvd.Sum)
    res = {
        "rank": r,
        "size": hvd.size(),
        "local_rank": hvd.local_rank(),
        "sum": np.asarray(out).tolist(),
    }
    hvd.shutdown()
    return res


def test_run_on_cluster_local_executor():
    """Full protocol end-to-end: 2 task slots register with the driver,
    get host-hash ranks, bootstrap jax.distributed, run a collective, and
    the driver returns results in rank order."""
    results = run_on_cluster(
        _cluster_fn, (10.0,), num_proc=2,
        executor=local_executor(),
        start_timeout=180,
        env={"JAX_PLATFORMS": "cpu", "HVDTPU_EAGER_ENGINE": "python"},
    )
    assert [r["rank"] for r in results] == [0, 1]
    assert all(r["size"] == 2 for r in results)
    # same host -> contiguous local ranks
    assert sorted(r["local_rank"] for r in results) == [0, 1]
    for r in results:
        assert r["sum"] == [30.0, 30.0, 30.0]  # 10 + 20


def test_run_on_cluster_task_failure_propagates():
    def boom():
        raise ValueError("cluster task exploded")

    with pytest.raises(RuntimeError, match="cluster task exploded"):
        run_on_cluster(
            boom, num_proc=2, executor=local_executor(),
            start_timeout=120,
            env={"JAX_PLATFORMS": "cpu"},
        )


def test_estimator_cluster_backend(tmp_path):
    """Estimator trains through a cluster executor — the reference's
    Spark-estimator topology (KerasEstimator over horovod.spark.run)."""
    from horovod_tpu.checkpoint import LocalStore
    from horovod_tpu.estimator import Estimator
    from horovod_tpu.models import ConvNet

    import optax

    rng = np.random.RandomState(0)
    x = rng.rand(64, 28, 28, 1).astype(np.float32)
    y = rng.randint(0, 10, size=(64,)).astype(np.int32)

    est = Estimator(
        ConvNet(),
        optax.adam(1e-3),
        store=LocalStore(str(tmp_path)),
        epochs=1,
        batch_size=16,
        np_workers=2,
        backend=local_executor(),
        use_cpu=True,
        timeout=180,
        verbose=0,
    )
    model = est.fit({"features": x, "label": y})
    preds = model.transform({"features": x})
    assert preds["prediction"].shape[0] == 64
