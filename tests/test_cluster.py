"""Cluster-integration tests (reference: horovod.spark.run semantics,
test_spark.py's driver/task registration coverage — SURVEY.md §2.7).

The generic protocol (register -> host-hash rank assignment -> function
shipping -> result collection) is exercised end-to-end with the local
subprocess executor; the rank-assignment math is unit-tested against the
reference's barrel-shift behavior (spark/runner.py:186-205)."""

import numpy as np
import pytest

from horovod_tpu.cluster import assign_ranks, local_executor, run_on_cluster

pytestmark = pytest.mark.multiprocess


def test_assign_ranks_single_host():
    slots = assign_ranks({0: "hostA", 1: "hostA", 2: "hostA"})
    assert [s["rank"] for s in slots] == [0, 1, 2]
    assert [s["local_rank"] for s in slots] == [0, 1, 2]
    assert all(s["local_size"] == 3 and s["cross_size"] == 1 for s in slots)


def test_assign_ranks_multi_host_barrel_shift():
    # task 0 lives on hostB: the barrel shift must make hostB the first
    # host so rank 0 is task 0's host (reference spark/runner.py:186-190)
    slots = assign_ranks({0: "hostB", 1: "hostA", 2: "hostB", 3: "hostA"})
    assert slots[0]["rank"] == 0 and slots[0]["cross_rank"] == 0
    assert slots[2]["rank"] == 1 and slots[2]["local_rank"] == 1
    assert slots[1]["rank"] == 2 and slots[1]["cross_rank"] == 1
    assert slots[3]["rank"] == 3
    assert all(s["local_size"] == 2 and s["cross_size"] == 2 for s in slots)
    # ranks are a permutation of 0..n-1
    assert sorted(s["rank"] for s in slots) == [0, 1, 2, 3]


def _cluster_fn(scale):
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    r = hvd.rank()
    out = hvd.allreduce(np.full(3, float(r + 1) * scale, np.float32),
                        op=hvd.Sum)
    res = {
        "rank": r,
        "size": hvd.size(),
        "local_rank": hvd.local_rank(),
        "sum": np.asarray(out).tolist(),
    }
    hvd.shutdown()
    return res


def test_run_on_cluster_local_executor():
    """Full protocol end-to-end: 2 task slots register with the driver,
    get host-hash ranks, bootstrap jax.distributed, run a collective, and
    the driver returns results in rank order."""
    results = run_on_cluster(
        _cluster_fn, (10.0,), num_proc=2,
        executor=local_executor(),
        start_timeout=180,
        env={"JAX_PLATFORMS": "cpu", "HVDTPU_EAGER_ENGINE": "python"},
    )
    assert [r["rank"] for r in results] == [0, 1]
    assert all(r["size"] == 2 for r in results)
    # same host -> contiguous local ranks
    assert sorted(r["local_rank"] for r in results) == [0, 1]
    for r in results:
        assert r["sum"] == [30.0, 30.0, 30.0]  # 10 + 20


def test_run_on_cluster_task_failure_propagates():
    def boom():
        raise ValueError("cluster task exploded")

    with pytest.raises(RuntimeError, match="cluster task exploded"):
        run_on_cluster(
            boom, num_proc=2, executor=local_executor(),
            start_timeout=120,
            env={"JAX_PLATFORMS": "cpu"},
        )


def _spark_train_fn():
    """Tiny synchronous-SGD linear regression: grads averaged through the
    engine each step, so convergence proves the collectives worked inside
    the Spark task slots."""
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    r, size, local_rank = hvd.rank(), hvd.size(), hvd.local_rank()
    rng = np.random.RandomState(42 + r)  # different shards per rank
    w_true = np.asarray([2.0, -1.0], np.float32)
    X = rng.randn(32, 2).astype(np.float32)
    y = X @ w_true
    w = np.zeros(2, np.float32)  # identical init on every rank
    losses = []
    for _ in range(12):
        pred = X @ w
        losses.append(float(((pred - y) ** 2).mean()))
        grad = (2 * X.T @ (pred - y) / len(X)).astype(np.float32)
        g = np.asarray(hvd.allreduce(grad, op=hvd.Average))
        w -= 0.1 * g
    hvd.shutdown()
    return {"rank": r, "size": size, "local_rank": local_rank,
            "losses": losses, "w": w.tolist()}


def test_run_on_cluster_spark_executor():
    """VERDICT r3 item 4: the Spark adapter EXECUTES.  A faithful local
    pyspark stand-in (tests/pyspark_standin.py: real worker process per
    partition, RDD API) runs ``run_on_cluster(fn, num_proc=2,
    executor=spark_executor(sc))`` end to end, training a tiny model with
    engine-averaged gradients; rank assignment is verified against
    ``assign_ranks`` (same host -> identity ranks, contiguous local
    ranks)."""
    from pyspark_standin import install_fake_pyspark

    from horovod_tpu.cluster import spark_executor

    pyspark = install_fake_pyspark()
    sc = pyspark.SparkContext(master="local[2]")
    try:
        results = run_on_cluster(
            _spark_train_fn, num_proc=2,
            executor=spark_executor(sc),
            start_timeout=240,
            env={"JAX_PLATFORMS": "cpu", "HVDTPU_EAGER_ENGINE": "python"},
        )
    finally:
        sc.stop()
    # rank order and topology match assign_ranks for two same-host tasks
    expected = assign_ranks({0: "h", 1: "h"})
    assert [r["rank"] for r in results] == [s["rank"] for s in expected]
    assert sorted(r["local_rank"] for r in results) == [0, 1]
    assert all(r["size"] == 2 for r in results)
    for r in results:
        # trained: averaged-gradient SGD converges toward w_true
        assert r["losses"][-1] < 0.1 * r["losses"][0]
        np.testing.assert_allclose(r["w"], [2.0, -1.0], atol=0.35)
    # both ranks computed identical weights (same averaged gradients)
    np.testing.assert_allclose(results[0]["w"], results[1]["w"], atol=1e-5)


def test_run_on_cluster_spark_task_failure_propagates():
    """A task raising inside a Spark slot aborts the job with its
    traceback (stage-failure detection through the _SparkHandle)."""
    from pyspark_standin import install_fake_pyspark

    from horovod_tpu.cluster import spark_executor

    def boom():
        raise ValueError("spark task exploded")

    pyspark = install_fake_pyspark()
    sc = pyspark.SparkContext(master="local[2]")
    try:
        with pytest.raises(RuntimeError, match="spark task exploded"):
            run_on_cluster(
                boom, num_proc=2, executor=spark_executor(sc),
                start_timeout=120,
                env={"JAX_PLATFORMS": "cpu"},
            )
    finally:
        sc.stop()


def test_spark_executor_error_branches(monkeypatch):
    import sys

    from horovod_tpu.cluster import spark_executor

    # pyspark absent -> clear RuntimeError
    monkeypatch.setitem(sys.modules, "pyspark", None)
    with pytest.raises(RuntimeError, match="requires pyspark"):
        spark_executor()(2, "127.0.0.1:1", "s")

    # pyspark present but no active context
    from pyspark_standin import install_fake_pyspark

    mod = install_fake_pyspark()
    monkeypatch.setitem(sys.modules, "pyspark", mod)
    mod.SparkContext._active_spark_context = None
    with pytest.raises(RuntimeError, match="no active SparkContext"):
        spark_executor()(2, "127.0.0.1:1", "s")


def test_estimator_cluster_backend(tmp_path):
    """Estimator trains through a cluster executor — the reference's
    Spark-estimator topology (KerasEstimator over horovod.spark.run)."""
    from horovod_tpu.checkpoint import LocalStore
    from horovod_tpu.estimator import Estimator
    from horovod_tpu.models import ConvNet

    import optax

    rng = np.random.RandomState(0)
    x = rng.rand(64, 28, 28, 1).astype(np.float32)
    y = rng.randint(0, 10, size=(64,)).astype(np.int32)

    est = Estimator(
        ConvNet(),
        optax.adam(1e-3),
        store=LocalStore(str(tmp_path)),
        epochs=1,
        batch_size=16,
        np_workers=2,
        backend=local_executor(),
        use_cpu=True,
        timeout=180,
        verbose=0,
    )
    model = est.fit({"features": x, "label": y})
    preds = model.transform({"features": x})
    assert preds["prediction"].shape[0] == 64
