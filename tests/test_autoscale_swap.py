"""Autoscaling + live weight hot-swap (ISSUE 13): the pure
hysteresis/cooldown/backoff decision table, version-flip determinism
across simulated ranks, rollback on seeded checksum corruption,
request-log compaction, and the end-to-end chaos stories — N→M resize
with in-flight requests bitwise-equal to an uninterrupted run, and a
rank killed mid-swap converging on exactly one weight version with
zero dropped requests.
"""

from __future__ import annotations

import os
import pickle
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.models.decode import generate
from horovod_tpu.models.transformer import gpt
from horovod_tpu.serve import ServeJob, SlotEngine, publish_weights
from horovod_tpu.serve.autoscale import (
    AutoscaleConfig,
    AutoscalePolicy,
    gauges_from_views,
    world_token,
)
from horovod_tpu.serve.frontend import SCOPE, IngestPump
from horovod_tpu.serve.hotswap import VERSION_KEY, SwapManager
from horovod_tpu.testing import faults

_OVERRIDES = dict(num_layers=1, num_heads=2, emb_dim=32, max_len=64,
                  vocab_size=64, dtype=jnp.float32,
                  attention_impl="reference")


def _model():
    return gpt("nano", **_OVERRIDES)


def _params(seed):
    model = _model()
    return model, model.init(jax.random.PRNGKey(seed),
                             jnp.zeros((1, 8), jnp.int32))


def _cfg(**kw):
    base = dict(min_workers=1, max_workers=4, scale_up_queue=4,
                up_window_secs=1.0, scale_down_idle_secs=5.0,
                up_cooldown_secs=10.0, down_cooldown_secs=10.0,
                backoff_base_secs=2.0, backoff_max_secs=60.0)
    base.update(kw)
    return AutoscaleConfig(**base)


# ---------------------------------------------------------------------------
# Autoscale policy: the pure decision table
# ---------------------------------------------------------------------------


def test_policy_grow_needs_sustained_pressure():
    p = AutoscalePolicy(_cfg())
    # one pressured round is a spike, not a trend
    assert p.observe(0.0, queue_depth=9, active_slots=4,
                     world_size=2) is None
    # pressure interrupted -> window restarts
    assert p.observe(0.5, queue_depth=0, active_slots=0,
                     world_size=2) is None
    assert p.observe(0.6, queue_depth=9, active_slots=4,
                     world_size=2) is None
    assert p.observe(1.5, queue_depth=9, active_slots=4,
                     world_size=2) is None  # only 0.9s sustained
    d = p.observe(1.7, queue_depth=9, active_slots=4, world_size=2)
    assert d is not None and d.direction == "up" and d.target == 3


def test_policy_up_cooldown_blocks_consecutive_grows():
    p = AutoscalePolicy(_cfg())
    p.observe(0.0, queue_depth=9, active_slots=4, world_size=2)
    assert p.observe(1.0, queue_depth=9, active_slots=4,
                     world_size=2) is not None
    # still pressured, but inside the cooldown — and the hysteresis
    # window restarted at the decision
    assert p.observe(2.5, queue_depth=9, active_slots=4,
                     world_size=3) is None
    assert p.observe(10.5, queue_depth=9, active_slots=4,
                     world_size=3) is None
    d = p.observe(12.1, queue_depth=9, active_slots=4, world_size=3)
    assert d is not None and d.direction == "up" and d.target == 4


def test_policy_envelope_caps_both_directions():
    p = AutoscalePolicy(_cfg(max_workers=2))
    p.observe(0.0, queue_depth=9, active_slots=4, world_size=2)
    assert p.observe(2.0, queue_depth=9, active_slots=4,
                     world_size=2) is None  # already at max
    p2 = AutoscalePolicy(_cfg(min_workers=2))
    p2.observe(0.0, queue_depth=0, active_slots=0, world_size=2)
    assert p2.observe(20.0, queue_depth=0, active_slots=0,
                      world_size=2) is None  # already at min


def test_policy_shrink_needs_sustained_idle_and_cooldown():
    p = AutoscalePolicy(_cfg())
    assert p.observe(0.0, queue_depth=0, active_slots=0,
                     world_size=3) is None
    # a busy blip restarts the idle window
    assert p.observe(3.0, queue_depth=0, active_slots=1,
                     world_size=3) is None
    assert p.observe(3.1, queue_depth=0, active_slots=0,
                     world_size=3) is None
    assert p.observe(7.0, queue_depth=0, active_slots=0,
                     world_size=3) is None
    d = p.observe(8.2, queue_depth=0, active_slots=0, world_size=3)
    assert d is not None and d.direction == "down" and d.target == 2


def test_policy_no_flapping_across_directions():
    """An up decision starts the cooldown for DOWN too — the decision
    trace can never show up,down within one cooldown window."""
    p = AutoscalePolicy(_cfg(up_window_secs=0.1,
                             scale_down_idle_secs=0.1))
    d = p.observe(1.0, queue_depth=9, active_slots=4, world_size=2)
    assert d is None
    d = p.observe(1.2, queue_depth=9, active_slots=4, world_size=2)
    assert d is not None and d.direction == "up"
    # instantly idle afterwards: the down cooldown (from the up) holds
    for t in (2.0, 5.0, 9.0, 11.0):
        assert p.observe(t, queue_depth=0, active_slots=0,
                         world_size=3) is None
    d = p.observe(11.4, queue_depth=0, active_slots=0, world_size=3)
    assert d is not None and d.direction == "down"
    directions = [e[1] for e in p.trace]
    assert directions == ["up", "down"]
    # cooldown respected in the trace: >= 10s apart
    assert p.trace[1][0] - p.trace[0][0] >= 10.0


def test_policy_grow_failure_backs_off_exponentially():
    p = AutoscalePolicy(_cfg(up_window_secs=0.1, up_cooldown_secs=0.1))
    d = p.observe(1.0, queue_depth=9, active_slots=4, world_size=1)
    assert d is None
    assert p.observe(1.2, queue_depth=9, active_slots=4,
                     world_size=1) is not None
    assert p.record_grow_failed(1.2) == 2.0
    # pressured throughout, but backed off
    assert p.observe(2.0, queue_depth=9, active_slots=4,
                     world_size=1) is None
    d = p.observe(3.5, queue_depth=9, active_slots=4, world_size=1)
    assert d is not None and d.direction == "up"
    assert p.record_grow_failed(3.5) == 4.0   # doubled
    assert p.record_grow_failed(8.0) == 8.0   # doubled again
    p.record_grow_ok()                         # success resets the ladder
    assert p.record_grow_failed(20.0) == 2.0


def test_policy_ttft_pressure_when_configured():
    p = AutoscalePolicy(_cfg(scale_up_ttft_ms=500.0,
                             up_window_secs=0.1))
    assert p.observe(0.0, queue_depth=0, active_slots=2, world_size=1,
                     ttft_p50_ms=900.0) is None
    d = p.observe(0.2, queue_depth=0, active_slots=2, world_size=1,
                  ttft_p50_ms=900.0)
    assert d is not None and d.direction == "up"


def test_config_envelope_validated():
    with pytest.raises(ValueError, match="envelope"):
        AutoscaleConfig(min_workers=3, max_workers=2)


def test_world_token_formats():
    assert world_token(None, 4) == "world 4"
    assert world_token(4, 4) == "world 4"
    assert world_token(4, 6, 12) == "world 4→6 v=12"


def test_controller_prometheus_exposition():
    """The launcher-local autoscale series render as parseable
    exposition lines (HELP/TYPE once per family, counters by
    direction) — appended to the live plane's /metrics by
    LivePlane.add_render."""
    from horovod_tpu.obs.registry import MetricsRegistry
    from horovod_tpu.serve.autoscale import AutoscaleController, Decision

    reg = MetricsRegistry()
    c = AutoscaleController(_cfg(), registry=reg)
    c.executed(Decision("up", 3, "test"), epoch=1, world_size=3)
    c.executed(Decision("down", 2, "test"), epoch=2, world_size=2)
    c.grow_failed(0.0, rank=3)
    body = c.prometheus()
    assert "hvdtpu_autoscale_world 2" in body.replace(".0", "")
    assert 'hvdtpu_autoscale_decisions{direction="up"} 1' in body
    assert 'hvdtpu_autoscale_decisions{direction="down"} 1' in body
    assert "hvdtpu_autoscale_backoffs 1" in body
    for family in ("hvdtpu_autoscale_world",
                   "hvdtpu_autoscale_decisions",
                   "hvdtpu_autoscale_backoffs"):
        assert body.count(f"# TYPE {family} ") == 1
    assert body.endswith("\n")


def test_gauges_from_views_silence_and_worst_rank():
    class _V:
        def __init__(self, metrics):
            self.metrics = metrics

    assert gauges_from_views({}) is None
    views = {
        0: _V({"a": {"name": "serve.queue_depth", "value": 2},
               "b": {"name": "serve.active_slots", "value": 1}}),
        1: _V({"a": {"name": "serve.queue_depth", "value": 7},
               "c": {"name": "serve.ttft_ms", "count": 3,
                     "p50": 40.0}}),
    }
    g = gauges_from_views(views)
    assert g["queue_depth"] == 7 and g["active_slots"] == 1
    assert g["ttft_p50_ms"] == 40.0


# ---------------------------------------------------------------------------
# Fault grammar: the new point-restricted actions
# ---------------------------------------------------------------------------


def test_swap_and_scale_actions_point_restricted():
    faults.parse_spec("swap_commit:action=swap_abort:rank=1")
    faults.parse_spec("scale_admit:action=scale_fail")
    with pytest.raises(ValueError, match="only implemented at"):
        faults.parse_spec("ckpt_write:action=swap_abort")
    with pytest.raises(ValueError, match="only implemented at"):
        faults.parse_spec("swap_commit:action=scale_fail")


# ---------------------------------------------------------------------------
# Hot swap: version-flip determinism + rollback (simulated ranks)
# ---------------------------------------------------------------------------


@pytest.fixture
def kv_pair():
    from horovod_tpu.run.rendezvous import KVStoreClient, KVStoreServer

    server = KVStoreServer()
    server.start()
    kv = KVStoreClient(f"127.0.0.1:{server.port}", server.secret)
    try:
        yield server, kv
    finally:
        server.stop()


def test_version_flip_deterministic_across_simulated_ranks(
        tmp_path, kv_pair):
    """Two simulated ranks driven by the leader's broadcast transitions
    flip to bitwise-identical params on the same step, and the durable
    record lands BEFORE the flip broadcast."""
    _, kv = kv_pair
    model, params0 = _params(3)
    _, params1 = _params(9)
    wdir = str(tmp_path / "w")
    publish_weights(wdir, params1, 1)

    engines = [SlotEngine(model.cfg, params0, 1) for _ in range(2)]
    swaps = [SwapManager(wdir, params0, poll_steps=1) for _ in range(2)]
    leader = swaps[0]
    scope = "serve_e0"

    doc = leader.leader_step(kv, scope, [0, 1], step=1)
    assert doc == {"phase": "prefetch", "version": 1}
    for rank, (sw, eng) in enumerate(zip(swaps, engines)):
        sw.apply(doc, eng, kv, scope, rank, epoch=0, step=1)
    # votes in, but nothing flipped yet: exactly one version served
    assert all(sw.version == 0 for sw in swaps)
    assert kv.get(SCOPE, VERSION_KEY) is None

    doc = leader.leader_step(kv, scope, [0, 1], step=2)
    assert doc == {"phase": "flip", "version": 1}
    # durable record written before anyone applies the flip
    assert kv.get(SCOPE, VERSION_KEY) == b"1"
    for rank, (sw, eng) in enumerate(zip(swaps, engines)):
        sw.apply(doc, eng, kv, scope, rank, epoch=0, step=2)
    assert all(sw.version == 1 for sw in swaps)
    for a, b in zip(jax.tree_util.tree_leaves(engines[0].params),
                    jax.tree_util.tree_leaves(engines[1].params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree_util.tree_leaves(engines[0].params),
                    jax.tree_util.tree_leaves(params1)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_swap_rollback_on_seeded_checksum_corruption(
        tmp_path, kv_pair, monkeypatch):
    """A version published through a corrupt_write fault fails every
    rank's prefetch checksum; the leader broadcasts abort, the fleet
    keeps the incumbent, and the bad version is not re-offered."""
    _, kv = kv_pair
    model, params0 = _params(3)
    _, params1 = _params(9)
    wdir = str(tmp_path / "w")
    monkeypatch.setenv("HVDTPU_FAULT_SPEC",
                       "shard_write:action=corrupt_write")
    faults.reset()
    try:
        publish_weights(wdir, params1, 1)
    finally:
        monkeypatch.delenv("HVDTPU_FAULT_SPEC")
        faults.reset()

    eng = SlotEngine(model.cfg, params0, 1)
    sw = SwapManager(wdir, params0, poll_steps=1)
    scope = "serve_e0"
    doc = sw.leader_step(kv, scope, [0], step=1)
    assert doc == {"phase": "prefetch", "version": 1}
    sw.apply(doc, eng, kv, scope, 0, epoch=0, step=1)
    assert kv.get(scope, "swapok_1_0") == b"fail"
    doc = sw.leader_step(kv, scope, [0], step=2)
    assert doc == {"phase": "abort", "version": 1}
    sw.apply(doc, eng, kv, scope, 0, epoch=0, step=2)
    assert sw.version == 0
    assert kv.get(SCOPE, VERSION_KEY) is None
    for a, b in zip(jax.tree_util.tree_leaves(eng.params),
                    jax.tree_util.tree_leaves(params0)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # the rejected version is not re-offered...
    assert sw.leader_step(kv, scope, [0], step=3) is None
    # ...but a NEWER committed version is
    publish_weights(wdir, params1, 2)
    doc = sw.leader_step(kv, scope, [0], step=4)
    assert doc == {"phase": "prefetch", "version": 2}


def test_announce_rejects_foreign_job_fingerprint(tmp_path, kv_pair):
    _, kv = kv_pair
    _, params0 = _params(3)
    sw = SwapManager(str(tmp_path / "w"), params0, poll_steps=1)
    kv.put(SCOPE, "weights",
           pickle.dumps({"version": 5, "fp": "not-this-job"}))
    assert sw.poll_candidate(kv) is None


def test_publish_weights_rejects_version_zero(tmp_path):
    _, params0 = _params(3)
    with pytest.raises(ValueError, match=">= 1"):
        publish_weights(str(tmp_path / "w"), params0, 0)


# ---------------------------------------------------------------------------
# Request-log compaction
# ---------------------------------------------------------------------------


def test_pump_gcs_compacted_finished_outputs(kv_pair):
    server, kv = kv_pair
    pump = IngestPump(server, out_ttl_secs=0.05)
    kv.put(SCOPE, "log_watermark/0", b"2")
    # below-watermark log orphans (leader crashed between publishing
    # the shard's watermark and deleting) are swept by the pump
    kv.put(SCOPE, "log/0/0", pickle.dumps({"rid": "a", "n": 0}))
    kv.put(SCOPE, "log/0/2", pickle.dumps({"rid": "c", "n": 2}))
    kv.put(SCOPE, "out/a", pickle.dumps(
        {"rid": "a", "done": True, "n": 0, "tokens": [1]}))
    kv.put(SCOPE, "out/b", pickle.dumps(
        {"rid": "b", "done": True, "n": 2, "tokens": [2]}))   # >= mark
    kv.put(SCOPE, "out/c", pickle.dumps(
        {"rid": "c", "done": False, "n": 1, "tokens": []}))   # inflight
    pump._gc_finished_outputs()                # first sight: starts ttl
    assert kv.get(SCOPE, "log/0/0") is None    # orphan swept
    assert kv.get(SCOPE, "log/0/2") is not None  # at/above the watermark
    assert kv.get(SCOPE, "out/a") is not None
    time.sleep(0.1)
    pump._gc_finished_outputs()
    assert kv.get(SCOPE, "out/a") is None      # compacted + ttl expired
    assert kv.get(SCOPE, "out/b") is not None  # above the watermark
    assert kv.get(SCOPE, "out/c") is not None  # not done


# ---------------------------------------------------------------------------
# End-to-end: resize + swap chaos through a live fleet
# ---------------------------------------------------------------------------


def _spec(slots=2, **extra):
    o = dict(_OVERRIDES)
    spec = {"size": "nano", "overrides": o, "seed": 3,
            "num_slots": slots, "idle_secs": 0.005}
    spec.update(extra)
    return spec


def _oracle(prompts, steps, seed=3, params=None):
    model = gpt("nano", **_OVERRIDES)
    if params is None:
        params = model.init(jax.random.PRNGKey(seed),
                            jnp.zeros((1, 8), jnp.int32))
    return [
        np.asarray(generate(model.cfg, params,
                            jnp.asarray([p], jnp.int32), s))[0].tolist()
        for p, s in zip(prompts, steps)
    ]


@pytest.mark.multiprocess
@pytest.mark.slow
def test_autoscale_grow_under_load_then_drain_release():
    """ISSUE 13 acceptance (2): load-driven grow through a re-minted
    epoch with requests in flight (tokens bitwise-equal to an
    uninterrupted run — the resize is a survived failure as far as
    clients can tell), then drain-driven shrink releasing the standby
    cleanly, cooldown respected in the decision trace."""
    rs = np.random.RandomState(11)
    prompts = [rs.randint(0, 64, rs.randint(3, 9)).tolist()
               for _ in range(12)]
    # Long generations through ONE slot keep the queue above the
    # high-water mark for seconds — the hysteresis window must see
    # SUSTAINED pressure across several controller ticks, not a spike.
    steps = [48] * 12
    oracle = _oracle(prompts, steps)
    job = ServeJob(
        _spec(slots=1), np=1, min_workers=1, max_workers=2,
        autoscale={"scale_up_queue": 2, "up_window_secs": 0.2,
                   "scale_down_idle_secs": 1.0,
                   "up_cooldown_secs": 1.0, "down_cooldown_secs": 1.0},
        live_stats_secs=0.2,
        env={"JAX_PLATFORMS": "cpu"}, timeout=300,
    ).start()
    try:
        rids = [job.client.submit(p, max_new_tokens=s)
                for p, s in zip(prompts, steps)]
        docs = [job.client.result(r, timeout=240) for r in rids]
        # wait for the drain-driven release before stopping
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            ev = [e[0] for e in (job._job.trace if job._job else [])]
            if "scale_down" in ev:
                break
            time.sleep(0.25)
        results, ejob = job.stop()
    finally:
        job.shutdown()
    # zero dropped, bitwise-equal through the resize replays
    assert [d["tokens"] for d in docs] == oracle
    events = [e[0] for e in ejob.trace]
    assert "scale_up" in events and "scale_down" in events, ejob.trace
    assert events.count("failure") == 0    # resizes are not failures
    # requests finished inside a re-minted (post-resize) epoch
    assert max(d["epoch"] for d in docs) >= 1
    # the released standby exited cleanly with a release summary
    assert results[1].get("released") is True
    assert results[0]["completed"] == 12


@pytest.mark.multiprocess
@pytest.mark.slow
def test_chaos_kill_mid_swap_converges_on_one_version():
    """ISSUE 13 acceptance (1): a rank killed between shard prefetch
    and version flip (swap_commit/action=swap_abort).  The fleet
    re-forms, converges on exactly one weight version (the durable
    record), drops zero requests, and every token stream is
    bitwise-equal to single-stream generate under that version (the
    published version carries the same params, so the oracle covers
    both sides of the flip)."""
    import tempfile

    model, params0 = _params(3)
    wdir = tempfile.mkdtemp(prefix="hvdtpu_swapw_")
    # Same weights, new version stamp: the mechanics (prefetch, votes,
    # durable record, flip, mid-swap death, convergence) are fully
    # exercised while every request stays oracle-comparable.
    publish_weights(wdir, params0, 1)

    rs = np.random.RandomState(7)
    prompts = [rs.randint(0, 64, rs.randint(3, 9)).tolist()
               for _ in range(8)]
    steps = [3, 4, 5, 6, 3, 4, 5, 6]
    oracle = _oracle(prompts, steps)
    job = ServeJob(
        _spec(slots=2, weights_dir=wdir, swap_poll_steps=4), np=2,
        env={"JAX_PLATFORMS": "cpu",
             "HVDTPU_FAULT_SPEC": "swap_commit:action=swap_abort:rank=1"},
        max_retries=2, timeout=300,
    ).start()
    try:
        rids = []
        for p, s in zip(prompts, steps):
            rids.append(job.client.submit(p, max_new_tokens=s))
            time.sleep(0.05)
        docs = [job.client.result(r, timeout=240) for r in rids]
        results, ejob = job.stop()
    finally:
        job.shutdown()
    # zero dropped, bitwise-equal
    assert [d["tokens"] for d in docs] == oracle
    # the mid-swap death was a real failure+respawn
    events = [e[0] for e in ejob.trace]
    assert events.count("failure") == 1 and events.count("respawn") == 1
    # single-version convergence: every rank drained on the SAME
    # version — the durable record's (the flip record landed before the
    # death, so it must be 1)
    versions = {r: v.get("weight_version") for r, v in results.items()}
    assert versions == {0: 1, 1: 1}, versions


@pytest.mark.multiprocess
@pytest.mark.slow
def test_log_compaction_bounds_store_and_replay(tmp_path):
    """The ingest log does not grow with total requests ever served:
    after everything finishes, the watermark has retired every entry
    and the log keys below it are deleted."""
    job = ServeJob(_spec(slots=2), np=1,
                   env={"JAX_PLATFORMS": "cpu"}, timeout=240).start()
    try:
        rids = [job.client.submit([1 + i, 2, 3], max_new_tokens=3)
                for i in range(6)]
        for r in rids:
            job.client.result(r, timeout=180)
        # leader publishes the watermark + deletes synchronously with
        # the done docs, so results back means compaction happened
        raw = job._server.scan(SCOPE + "/log_watermark/")
        mark = int(raw[SCOPE + "/log_watermark/0"].decode())
        assert mark == 6
        assert job._server.scan(SCOPE + "/log/") == {}
        job.stop()
    finally:
        job.shutdown()
