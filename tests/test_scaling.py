"""Collective goodput / cycle-scaling regression gates (VERDICT r3 weak #5).

The reference enforces its negotiation-transport scaling property by
construction — rank 0's gather is ONE MPI_Gatherv
(mpi/mpi_controller.cc:107-150).  Here the native engine's equivalent is
the poll-multiplexed RecvMsgMulti (cpp/hvdtpu/tcp.cc:178-217) and the host
data plane's equivalent is the staged XLA reduce (O(bytes) on the wire,
engine.py).  scripts/collective_bench.py measures these; THIS file gates
them so a reintroduced serial-recv loop or gather-everything reduce fails
the matrix instead of quietly regressing.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

import horovod_tpu.run as hvdrun
from horovod_tpu.runtime.native import native_available

pytestmark = [pytest.mark.multiprocess, pytest.mark.full,
              pytest.mark.serial]

_BASELINE_PATH = os.path.join(os.path.dirname(__file__),
                              "scaling_baseline.json")


def _scaling_threshold() -> tuple[float, dict]:
    """Gate = max(hard floor 0.25, band * recorded idle-machine ratio).

    The recorded ratio (scaling_baseline.json, refreshed by
    scripts/record_scaling_baseline.py) turns the floor-only gate into a
    trend gate: a change that halves np=8 goodput fails against the
    banded baseline long before it reaches the 4x-cliff floor (VERDICT
    r4 weak #3)."""
    with open(_BASELINE_PATH) as f:
        base = json.load(f)
    return max(0.25, base["band"] * base["np8_over_np2"]), base


def _rate_worker(nbytes: int, iters: int):
    """ops/sec for cycle-dominated (tiny payload) eager allreduces."""
    import time

    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    x = np.ones(max(nbytes // 4, 1), np.float32)
    for _ in range(3):
        hvd.allreduce(x, op=hvd.Sum, name="warm")
    t0 = time.perf_counter()
    for _ in range(iters):
        hvd.allreduce(x, op=hvd.Sum, name="bench")
    dt = time.perf_counter() - t0
    hvd.shutdown()
    return iters / dt


@pytest.mark.skipif(not native_available(), reason="native engine not built")
def test_native_cycle_cost_sublinear_np8():
    """Per-op negotiation cost must scale sublinearly 2 -> 8 workers.

    With the poll-multiplexed gather, growing the world 4x costs well
    under 4x per cycle (measured sublinear, docs/performance.md goodput
    table).  Trend gate (VERDICT r4 weak #3): the measured np8/np2 ratio
    must stay within a band of the committed idle-machine baseline, not
    just above the catastrophic-cliff floor.  Best-of-2 live trials vs a
    banded median baseline: machine load only DEPRESSES the ratio (np=8
    contends harder than np=2), so retrying once and taking the max is
    one-sided-safe flake headroom, never a way to pass a real
    regression."""
    threshold, base = _scaling_threshold()
    env = {"HVDTPU_EAGER_ENGINE": "native", "HVDTPU_CYCLE_TIME": "1"}
    best = 0.0
    for _ in range(2):
        rate2 = hvdrun.run(_rate_worker, (256, 40), np=2, use_cpu=True,
                           timeout=300, env=env)[0]
        rate8 = hvdrun.run(_rate_worker, (256, 40), np=8, use_cpu=True,
                           timeout=300, env=env)[0]
        best = max(best, rate8 / rate2)
        if best >= threshold:
            break
    assert best >= threshold, (
        f"np=8/np=2 eager throughput ratio {best:.3f} fell below "
        f"{threshold:.3f} (= band {base['band']} x recorded baseline "
        f"{base['np8_over_np2']}, floor 0.25): negotiation cost regressed "
        "vs the recorded trend (serial recvs reintroduced?)"
    )


def _staged_bytes_worker(nbytes: int):
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu._engine_registry import peek_engine

    hvd.init()
    x = np.ones(nbytes // 4, np.float32)
    out = hvd.allreduce(x, op=hvd.Sum, name="staged")
    eng = peek_engine()
    stats = dict(eng.stats)
    hvd.shutdown()
    return {"sum0": float(np.asarray(out).ravel()[0]), "stats": stats}


def test_staged_host_reduce_is_o_bytes_np4():
    """A host (numpy) float32 allreduce must take the staged XLA plane —
    one H2D + device reduce + one D2H, wire cost O(bytes) — never the
    gather-everything fallback whose recv cost is O(world x bytes)
    (reference ring allreduce property, gloo_operations.cc:107-142)."""
    nbytes = 1 << 20  # 1 MB
    results = hvdrun.run(
        _staged_bytes_worker, (nbytes,), np=4, use_cpu=True, timeout=300,
        env={"HVDTPU_EAGER_ENGINE": "python"},
    )
    for r in results:
        assert r["sum0"] == 4.0
        s = r["stats"]
        assert s["host_staged_ops"] >= 1, "staged plane was not used"
        assert s["host_data_ops"] == 0, (
            "1 MB f32 payload fell back to the gather-everything host path"
        )
        # O(bytes): wire accounting grows by the payload, NOT world x payload
        assert s["host_recv_bytes"] <= 1.5 * nbytes, (
            f"recv bytes {s['host_recv_bytes']} ~ O(world x bytes): "
            "gather-everything reintroduced"
        )
