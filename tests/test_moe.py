"""Mixture-of-experts + expert parallelism (parallel/moe.py) — the
optional-stretch EP axis beyond the reference's DP (SURVEY.md §2.9).

Contracts:
* the one-hot dispatch/combine formulation equals a per-token reference
  loop (when capacity is ample);
* capacity overflow drops tokens (zero contribution), never corrupts;
* the EP (all_to_all) layout is numerically identical to the dense
  formulation with the full expert set;
* the Switch aux loss is 1 at uniform routing;
* gradients flow to router and experts.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.parallel.moe import (
    MoEParams,
    init_moe_params,
    moe_mlp,
    moe_mlp_ep,
)

EP = 4
AXIS = "ep"
D, FF, E = 16, 32, 8


def _x(seed=0, b=2, s=12):
    return jnp.asarray(
        np.random.RandomState(seed).randn(b, s, D), jnp.float32
    ) * 0.5


def _params(seed=0):
    return init_moe_params(jax.random.PRNGKey(seed), D, FF, E)


def _reference_loop(x, p: MoEParams, top_k: int):
    """Per-token routing loop (no capacity limits): the semantics the
    one-hot formulation must reproduce when capacity is ample."""
    b, s, d = x.shape
    x2 = np.asarray(x.reshape(-1, d), np.float64)
    router = np.asarray(p.router, np.float64)
    out = np.zeros_like(x2)
    for t in range(x2.shape[0]):
        logits = x2[t] @ router
        gates = np.exp(logits - logits.max())
        gates = gates / gates.sum()
        picks = np.argsort(-gates)[:top_k]
        weights = gates[picks] / gates[picks].sum()
        for w, e in zip(weights, picks):
            h = x2[t] @ np.asarray(p.w1[e], np.float64) \
                + np.asarray(p.b1[e], np.float64)
            h = np.asarray(jax.nn.gelu(jnp.asarray(h)))
            y = h @ np.asarray(p.w2[e], np.float64) \
                + np.asarray(p.b2[e], np.float64)
            out[t] += w * y
    return out.reshape(b, s, d)


@pytest.mark.parametrize("top_k", [1, 2])
def test_dense_matches_reference_loop(top_k):
    x, p = _x(), _params()
    y, aux = moe_mlp(x, p, top_k=top_k, capacity_factor=100.0)
    ref = _reference_loop(x, p, top_k)
    np.testing.assert_allclose(np.asarray(y), ref, atol=1e-4, rtol=1e-4)
    assert np.isfinite(float(aux)) and float(aux) > 0


def test_capacity_overflow_drops_not_corrupts():
    """capacity_factor tiny -> most tokens dropped; the kept ones still
    match the reference loop's value, dropped ones are exactly zero."""
    x, p = _x(1), _params(1)
    y, _ = moe_mlp(x, p, top_k=1, capacity_factor=0.01)  # capacity=1
    ref = _reference_loop(x, p, 1)
    y2 = np.asarray(y).reshape(-1, D)
    r2 = ref.reshape(-1, D)
    kept = ~np.all(y2 == 0.0, axis=1)
    assert kept.sum() >= 1  # at least one slot per expert exists
    assert (~kept).sum() >= 1  # and the tiny capacity dropped some
    np.testing.assert_allclose(y2[kept], r2[kept], atol=1e-4, rtol=1e-4)


def test_uniform_router_aux_is_one():
    x = _x(2)
    p = _params(2)._replace(router=jnp.zeros((D, E)))  # uniform gates
    _, aux = moe_mlp(x, p, top_k=2)
    # ce is exactly 1/E; me depends on argmax ties -> me sums to 1,
    # aux = E * sum(me * 1/E) = 1 regardless of tie-breaking
    np.testing.assert_allclose(float(aux), 1.0, rtol=1e-5)


def test_ep_matches_dense_per_shard():
    """moe_mlp_ep over a 4-way mesh == dense moe_mlp applied to each
    rank's token shard with the full expert set."""
    mesh = Mesh(np.asarray(jax.devices()[:EP]), (AXIS,))
    x = _x(3, b=EP * 2, s=8)
    p = _params(3)

    def local(x_l, router, w1, b1, w2, b2):
        lp = MoEParams(router, w1, b1, w2, b2)
        y, aux = moe_mlp_ep(x_l, lp, AXIS, top_k=2)
        return y, aux

    fwd = jax.jit(
        shard_map(
            local, mesh=mesh,
            in_specs=(P(AXIS), P(), P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
            out_specs=(P(AXIS), P()),
            check_vma=False,
        )
    )
    y_ep, aux_ep = fwd(x, p.router, p.w1, p.b1, p.w2, p.b2)

    ys, auxs = [], []
    per = x.shape[0] // EP
    for r in range(EP):
        y_r, aux_r = moe_mlp(x[r * per:(r + 1) * per], p, top_k=2)
        ys.append(np.asarray(y_r))
        auxs.append(float(aux_r))
    np.testing.assert_allclose(
        np.asarray(y_ep), np.concatenate(ys), atol=2e-5, rtol=2e-5
    )
    np.testing.assert_allclose(float(aux_ep), np.mean(auxs), rtol=1e-5)


def test_gradients_flow():
    x, p = _x(4), _params(4)

    def loss(p):
        y, aux = moe_mlp(x, p, top_k=2)
        return (y ** 2).mean() + 0.01 * aux

    grads = jax.grad(loss)(p)
    for name, g in grads._asdict().items():
        arr = np.asarray(g)
        assert np.all(np.isfinite(arr)), name
        assert np.abs(arr).max() > 0, f"no gradient signal in {name}"


def test_gpt_moe_trains_and_sows_aux():
    """TransformerConfig.moe_experts wires MoE MLPs into every block:
    the model trains, and the per-block aux losses are retrievable via
    the 'losses' collection."""
    import optax

    from horovod_tpu.models.transformer import gpt

    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 1024, size=(2, 32)), jnp.int32
    )
    model = gpt("nano", moe_experts=4, dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    # every block carries expert weights instead of fc1/fc2
    assert "w1" in params["params"]["block0"]
    assert "fc1" not in params["params"]["block0"]

    def loss_fn(p):
        logits, state = model.apply(p, tokens, mutable=["losses"])
        nll = optax.softmax_cross_entropy_with_integer_labels(
            logits, tokens
        ).mean()
        aux = sum(jax.tree_util.tree_leaves(state["losses"]))
        return nll + 0.01 * aux, (nll, aux)

    tx = optax.adam(1e-3)
    opt_state = tx.init(params)
    losses = []
    for _ in range(5):
        (loss, (nll, aux)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        losses.append(float(nll))
        assert np.isfinite(float(aux))
    assert losses[-1] < losses[0], f"MoE model did not train: {losses}"


def test_ep_gradient_recipe_matches_dense():
    """The documented EP training recipe (pmean router grad, expert grads
    scaled 1/P) yields exactly the gradients of the global objective
    'mean of per-rank losses' — no mesh-size-dependent scale on experts
    (docs/moe.md training contract)."""
    mesh = Mesh(np.asarray(jax.devices()[:EP]), (AXIS,))
    x = _x(5, b=EP * 2, s=8)
    p = _params(5)
    per = x.shape[0] // EP

    def loss_shard(p, xr):
        y, aux = moe_mlp(xr, p, top_k=2)
        return (y ** 2).mean() + 0.01 * aux

    def loss_dense(p):
        return sum(
            loss_shard(p, x[r * per:(r + 1) * per]) for r in range(EP)
        ) / EP

    g_dense = jax.grad(loss_dense)(p)

    def local_grads(router, w1, b1, w2, b2, x_l):
        lp = MoEParams(router, w1, b1, w2, b2)

        def loss_fn(lp):
            y, aux = moe_mlp_ep(x_l, lp, AXIS, top_k=2)
            return (y ** 2).mean() + 0.01 * aux

        g = jax.grad(loss_fn)(lp)
        return MoEParams(
            router=jax.lax.pmean(g.router, AXIS),
            w1=g.w1 / EP, b1=g.b1 / EP, w2=g.w2 / EP, b2=g.b2 / EP,
        )

    g_ep = jax.jit(
        shard_map(
            local_grads, mesh=mesh,
            in_specs=(P(), P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
            out_specs=MoEParams(P(), P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
            check_vma=False,
        )
    )(p.router, p.w1, p.b1, p.w2, p.b2, x)

    np.testing.assert_allclose(np.asarray(g_ep.router),
                               np.asarray(g_dense.router),
                               atol=2e-6, rtol=2e-5)
    for name in ("w1", "b1", "w2", "b2"):
        np.testing.assert_allclose(
            np.asarray(getattr(g_ep, name)),
            np.asarray(getattr(g_dense, name)),
            atol=2e-6, rtol=2e-5, err_msg=name,
        )


def test_grouped_routing_matches_reference_loop():
    """Routing within groups (the linear-memory GShard grouping) still
    matches the per-token loop when capacity is ample, across group
    boundaries (n=24, group_size=8 -> 3 groups)."""
    x, p = _x(6), _params(6)  # n = 24 tokens
    y, aux = moe_mlp(x, p, top_k=2, capacity_factor=100.0, group_size=8)
    ref = _reference_loop(x, p, 2)
    np.testing.assert_allclose(np.asarray(y), ref, atol=1e-4, rtol=1e-4)
    assert np.isfinite(float(aux))


def test_ep_grouped_matches_dense_grouped():
    """EP with multi-group routing == dense per shard with the same
    group size."""
    mesh = Mesh(np.asarray(jax.devices()[:EP]), (AXIS,))
    x = _x(7, b=EP * 2, s=8)  # 16 local tokens per rank
    p = _params(7)

    def local(x_l, router, w1, b1, w2, b2):
        lp = MoEParams(router, w1, b1, w2, b2)
        return moe_mlp_ep(x_l, lp, AXIS, top_k=2, group_size=8)[0]

    y_ep = jax.jit(
        shard_map(
            local, mesh=mesh,
            in_specs=(P(AXIS), P(), P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
            out_specs=P(AXIS), check_vma=False,
        )
    )(x, p.router, p.w1, p.b1, p.w2, p.b2)
    per = x.shape[0] // EP
    ys = [np.asarray(moe_mlp(x[r * per:(r + 1) * per], p, top_k=2,
                             group_size=8)[0]) for r in range(EP)]
    np.testing.assert_allclose(
        np.asarray(y_ep), np.concatenate(ys), atol=2e-5, rtol=2e-5
    )


def test_padded_group_routing_matches_reference_loop():
    """Token counts that don't divide the group pad with invalid rows
    (never shrink to a tiny-divisor group): n=22, group_size=8 -> groups
    of 8 with 2 padding rows, which claim no capacity; output still
    matches the per-token loop and padding contributes nothing."""
    x = jnp.asarray(
        np.random.RandomState(8).randn(2, 11, D), jnp.float32
    ) * 0.5  # n = 22
    p = _params(8)
    y, aux = moe_mlp(x, p, top_k=2, capacity_factor=100.0, group_size=8)
    ref = _reference_loop(x, p, 2)
    np.testing.assert_allclose(np.asarray(y), ref, atol=1e-4, rtol=1e-4)
    assert np.isfinite(float(aux))
