"""Live telemetry plane (obs/stream.py, obs/live.py, obs/straggler.py):
delta encoding round-trips, aggregator merge across elastic
incarnations, Prometheus exposition validity on the KV server's
/metrics branch, deterministic straggler attribution on both collective
paths (controller cycles, elastic KV waits) under the ``action=delay``
fault, the KV wait backoff, the bench regression gate, and the 2-proc
chaos acceptance: an injected delay straggler is named live and at job
end, and attribution resets across incarnations."""

import json
import re
import threading
import time
import urllib.request

import numpy as np
import pytest

import horovod_tpu.obs as obs
from horovod_tpu.obs import live as obs_live
from horovod_tpu.obs import progress as obs_progress
from horovod_tpu.obs import straggler as obs_straggler
from horovod_tpu.obs import stream as obs_stream
from horovod_tpu.obs import summary as obs_summary
from horovod_tpu.run import rendezvous as rdv
from horovod_tpu.run.rendezvous import KVStoreClient, KVStoreServer
from horovod_tpu.testing import faults


@pytest.fixture(autouse=True)
def _fresh_obs(monkeypatch):
    monkeypatch.delenv(faults.SPEC_ENV, raising=False)
    faults.reset()
    obs.reset_registry()
    obs_progress.reset()
    obs_stream.stop_stream()
    yield
    faults.reset()
    obs.reset_registry()
    obs_progress.reset()
    obs_stream.stop_stream()


@pytest.fixture()
def kv_server():
    server = KVStoreServer()
    server.start()
    try:
        yield server
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# stream: compact delta encoding
# ---------------------------------------------------------------------------


def _populate(reg):
    reg.counter("ops.total", kind="x").inc(3)
    reg.gauge("queue.depth").set(7)
    h = reg.histogram("lat.ms")
    for v in (1.0, 2.0, 40.0):
        h.observe(v)


def test_delta_roundtrip_changed_only():
    reg = obs.get_registry()
    _populate(reg)
    snap1 = obs_stream.snapshot_map(reg.snapshot())
    reg.counter("ops.total", kind="x").inc(2)
    reg.histogram("lat.ms").observe(99.0)
    snap2 = obs_stream.snapshot_map(reg.snapshot())

    delta = obs_stream.encode_delta(snap1, snap2)
    # only the two touched instruments travel
    assert sorted(d["n"] for d in delta) == ["lat.ms", "ops.total"]
    view = dict(snap1)
    obs_stream.apply_delta(view, delta)
    assert view == snap2


def test_delta_full_snapshot_and_expand_schema():
    reg = obs.get_registry()
    _populate(reg)
    snap = obs_stream.snapshot_map(reg.snapshot())
    delta = obs_stream.encode_delta({}, snap)
    assert len(delta) == 3
    view = {}
    obs_stream.apply_delta(view, delta)
    # expand_metric reconstructs the dump schema exactly (mean included)
    assert view == snap
    hist = view[obs_stream.metric_key(
        {"name": "lat.ms", "tags": {}})]
    for field in ("count", "sum", "min", "max", "mean", "p50", "p90", "p99"):
        assert field in hist


def test_delta_empty_when_nothing_changed():
    reg = obs.get_registry()
    _populate(reg)
    snap = obs_stream.snapshot_map(reg.snapshot())
    assert obs_stream.encode_delta(snap, snap) == []


def test_delta_tombstones_removed_instruments():
    """Instrument removal (the elastic-rendezvous straggler reset) must
    propagate to the aggregator view, or stale blame would survive a
    re-formed world forever."""
    reg = obs.get_registry()
    obs_straggler.record(1, 100.0)
    snap1 = obs_stream.snapshot_map(reg.snapshot())
    obs_straggler.reset()
    snap2 = obs_stream.snapshot_map(reg.snapshot())
    delta = obs_stream.encode_delta(snap1, snap2)
    assert all("rm" in d for d in delta)
    view = dict(snap1)
    obs_stream.apply_delta(view, delta)
    assert view == snap2
    assert not any(k.startswith(obs_straggler.PREFIX) for k in view)


# ---------------------------------------------------------------------------
# publisher -> KV server -> aggregator
# ---------------------------------------------------------------------------


def test_stream_compact_quantile_roundtrip():
    """The wire compaction renames histogram percentiles p50/p90/p99 to
    q50/q90/q99 and back; one dropped or mis-mapped quantile here would
    silently skew every live digest and /metrics summary."""
    reg = obs.get_registry()
    h = reg.histogram("lat.ms")
    for v in (1.0, 5.0, 9.0, 40.0, 400.0):
        h.observe(v)
    (metric,) = [m for m in reg.snapshot() if m["name"] == "lat.ms"]
    compact = obs_stream._compact(metric)
    assert {"q50", "q90", "q99"} <= set(compact)
    assert not {"p50", "p90", "p99"} & set(compact)
    assert compact["q50"] == metric["p50"]
    assert compact["q90"] == metric["p90"]
    assert compact["q99"] == metric["p99"]
    back = obs_stream.expand_metric(json.loads(json.dumps(compact)))
    for field in ("p50", "p90", "p99", "count", "sum", "min", "max"):
        assert back[field] == metric[field], field
    assert not {"q50", "q90", "q99"} & set(back)
    assert back["mean"] == pytest.approx(metric["mean"])


def test_publisher_to_aggregator_end_to_end(kv_server, tmp_path):
    reg = obs.get_registry()
    _populate(reg)
    kv = KVStoreClient(f"127.0.0.1:{kv_server.port}", kv_server.secret)
    pub = obs_stream.StreamPublisher(kv, rank=0, epoch=0, interval=60)
    assert pub.publish_once() is not None
    reg.counter("ops.total", kind="x").inc()
    assert pub.publish_once() is not None

    hist = str(tmp_path / "live_history.jsonl")
    plane = obs_live.LivePlane(
        kv_server, interval=60, history_path=hist, expected_ranks=1,
        print_digest=False,
    )
    assert plane.round() == 2
    # consumed keys are pruned from the store (bounded launcher memory)
    assert kv_server.scan(obs_stream.LIVE_SCOPE + "/") == {}
    merged = plane.agg.merged()
    assert list(merged) == [0]
    key = obs_stream.metric_key({"name": "ops.total", "tags": {"kind": "x"}})
    assert merged[0].metrics[key]["value"] == 4
    rows = [json.loads(l) for l in open(hist)]
    assert rows and rows[-1]["ranks_reporting"] == 1


def test_publisher_failure_is_swallowed():
    kv = KVStoreClient("127.0.0.1:1")  # nothing listens there
    pub = obs_stream.StreamPublisher(kv, rank=0, epoch=0, interval=60)
    assert pub.publish_once() is None
    assert pub._seq == 0  # unpublished delta is retried next beat
    pub.stop()  # exit flush against a dead launcher is swallowed too


def test_publisher_stop_flushes_final_partial_interval(kv_server):
    """stop() publishes once more so the last partial interval's
    metrics (the job's concluding attributions) reach the launcher's
    end-of-job drain round."""
    reg = obs.get_registry()
    kv = KVStoreClient(f"127.0.0.1:{kv_server.port}", kv_server.secret)
    pub = obs_stream.StreamPublisher(kv, rank=0, epoch=0, interval=3600)
    pub.start()
    pub.publish_once()
    reg.counter("final.events").inc(7)  # lands after the last beat
    pub.stop()
    plane = obs_live.LivePlane(kv_server, interval=3600,
                               history_path=None, print_digest=False)
    plane.round()
    key = obs_stream.metric_key({"name": "final.events", "tags": {}})
    assert plane.agg.merged()[0].metrics[key]["value"] == 7


def test_poison_doc_is_discarded_not_wedging(kv_server):
    """A JSON-valid but schema-invalid snapshot (a version-skewed
    worker) must cost one warning and be pruned — never wedge every
    subsequent round on the same key."""
    kv = KVStoreClient(f"127.0.0.1:{kv_server.port}", kv_server.secret)
    kv.put("obs/live/0", "0/0", b'{"epoch": 0}')  # no "rank": ingest raises
    kv.put("obs/live/0", "1/0", json.dumps(
        _payload(1, 0, 0, [_counter("a", 3)])).encode())
    plane = obs_live.LivePlane(kv_server, interval=60, history_path=None,
                               print_digest=False)
    plane.round()
    # the poison key is gone and the good doc was ingested
    assert kv_server.scan(obs_stream.LIVE_SCOPE + "/") == {}
    assert list(plane.agg.merged()) == [1]


def test_live_plane_armed_from_worker_env_dict(kv_server, capsys):
    """The launcher half must arm from base_env — the SAME source the
    spawned workers read — so an env-dict override cannot start workers
    streaming into a store nobody drains."""
    from horovod_tpu.run.runner import (
        _maybe_start_live_plane, _stop_live_plane,
    )

    base_env = {"HVDTPU_LIVE_STATS_SECS": "30"}
    plane, owned = _maybe_start_live_plane(
        base_env, 2, kv_server=kv_server,
        kv_addr=f"10.1.2.3:{kv_server.port}",
    )
    try:
        assert plane is not None and owned is None
        # workers and scrapers are told the same routable endpoint
        assert base_env["HVDTPU_LIVE_KV"] == f"10.1.2.3:{kv_server.port}"
        assert plane.announce_host == "10.1.2.3"
        assert f"http://10.1.2.3:{kv_server.port}/metrics" in (
            capsys.readouterr().out
        )
    finally:
        _stop_live_plane(plane, owned)
    # unarmed env -> no plane, no server
    assert _maybe_start_live_plane({}, 2, kv_server=kv_server) == (None, None)


def test_maybe_start_from_env(kv_server, monkeypatch):
    monkeypatch.setenv("HVDTPU_LIVE_STATS_SECS", "30")
    monkeypatch.setenv("HVDTPU_LIVE_KV", f"127.0.0.1:{kv_server.port}")
    monkeypatch.setenv(rdv.SECRET_ENV, kv_server.secret)
    monkeypatch.setenv("HVDTPU_RANK", "3")
    pub = obs_stream.maybe_start_from_env()
    assert pub is not None and pub.rank == "3"
    assert obs_stream.maybe_start_from_env() is pub  # singleton
    obs_stream.stop_stream()
    monkeypatch.setenv("HVDTPU_LIVE_STATS_SECS", "0")
    assert obs_stream.maybe_start_from_env() is None


# ---------------------------------------------------------------------------
# aggregator: incarnation merge, digest, history
# ---------------------------------------------------------------------------


def _payload(rank, epoch, seq, metrics=(), progress=0, phase="steady",
             full=None):
    return {
        "v": 1, "rank": rank, "epoch": epoch, "seq": seq,
        "t": 1000.0 + seq, "phase": phase, "progress": progress,
        "full": (seq == 0) if full is None else full,
        "metrics": list(metrics),
    }


def _counter(name, value, **tags):
    out = {"n": name, "k": "c", "v": value}
    if tags:
        out["g"] = {k: str(v) for k, v in tags.items()}
    return out


def test_aggregator_merges_across_incarnations():
    agg = obs_live.LiveAggregator()
    agg.ingest(_payload(1, 0, 0, [_counter("a", 10)], progress=10))
    agg.ingest(_payload(0, 0, 0, [_counter("a", 11)], progress=11))
    # rank 1 respawned into epoch 2: fresh counters, smaller values
    agg.ingest(_payload(1, 2, 0, [_counter("a", 1)], progress=1))
    merged = agg.merged()
    assert merged[1].epoch == 2
    assert merged[1].metrics[obs_stream.metric_key(
        {"name": "a", "tags": {}})]["value"] == 1
    assert merged[0].epoch == 0
    # the dead incarnation stays queryable
    assert [(v.rank, v.epoch) for v in agg.incarnations()] == [
        (0, 0), (1, 0), (1, 2)]


def test_aggregator_full_snapshot_resets_view():
    agg = obs_live.LiveAggregator()
    agg.ingest(_payload(0, 0, 0, [_counter("a", 1), _counter("b", 2)]))
    # publisher restarted in-process: full snapshot without "b"
    agg.ingest(_payload(0, 0, 0, [_counter("a", 5)], full=True))
    metrics = agg.merged()[0].metrics
    assert [m["name"] for m in metrics.values()] == ["a"]


def test_digest_names_straggler_and_lagging_rank():
    agg = obs_live.LiveAggregator()
    agg.ingest(_payload(0, 0, 0, [
        _counter(obs_straggler.PREFIX + "last_arrivals", 9, rank=1),
    ], progress=40))
    agg.ingest(_payload(1, 0, 0, [], progress=31))
    d = agg.digest(2)
    assert "ranks 2/2" in d
    assert "min 31 (rank 1)" in d
    assert "straggler rank 1" in d and "9 last-arrivals" in d
    row = agg.history_row(2)
    assert row["straggler"]["rank"] == 1
    assert row["progress"] == {"0": 40, "1": 31}


def test_digest_no_ranks_and_no_straggler():
    agg = obs_live.LiveAggregator()
    assert "no rank" in agg.digest()
    agg.ingest(_payload(0, 0, 0, []))
    assert "straggler none" in agg.digest(1)
    assert agg.straggler() is None


# ---------------------------------------------------------------------------
# Prometheus exposition + /metrics endpoint
# ---------------------------------------------------------------------------

_PROM_SAMPLE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"\\]*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"\\]*")*\})?'
    r' (NaN|[-+]?[0-9]+(\.[0-9]+)?([eE][-+]?[0-9]+)?)$'
)
_PROM_TYPE = re.compile(
    r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* (counter|gauge|summary)$"
)
_PROM_HELP = re.compile(
    r"^# HELP [a-zA-Z_:][a-zA-Z0-9_:]* \S.*$"
)


def _assert_valid_exposition(text):
    """Exposition-format conformance (the rules real scrapers enforce):
    every comment is a well-formed HELP or TYPE line, at most one of
    each per family (a second is a hard parse error), HELP precedes
    TYPE, and every sample belongs to a family whose TYPE already
    appeared (bare samples make scrapers warn)."""
    assert text.endswith("\n")
    seen_types = set()
    seen_helps = set()
    for line in text.rstrip("\n").splitlines():
        if line.startswith("#"):
            if line.startswith("# HELP"):
                assert _PROM_HELP.match(line), f"bad HELP line: {line!r}"
                name = line.split()[2]
                assert name not in seen_helps, f"duplicate HELP for {name}"
                assert name not in seen_types, \
                    f"HELP after TYPE for {name}"
                seen_helps.add(name)
            else:
                m = _PROM_TYPE.match(line)
                assert m, f"bad comment line: {line!r}"
                name = line.split()[2]
                assert name not in seen_types, f"duplicate TYPE for {name}"
                seen_types.add(name)
        else:
            assert _PROM_SAMPLE.match(line), f"bad sample line: {line!r}"
            base = re.match(r"[a-zA-Z_:][a-zA-Z0-9_:]*", line).group(0)
            # summaries sample under <name>, <name>_sum, <name>_count
            fam = re.sub(r"_(sum|count)$", "", base)
            assert base in seen_types or fam in seen_types, \
                f"sample with no TYPE family: {line!r}"
            # duplicate label names are a hard parse error for scrapers
            keys = re.findall(r'([a-zA-Z_][a-zA-Z0-9_]*)="', line)
            assert len(keys) == len(set(keys)), \
                f"duplicate label in: {line!r}"
    # every family carries help text, not just a type
    assert seen_types <= seen_helps, \
        f"TYPE without HELP: {sorted(seen_types - seen_helps)}"


def test_prometheus_exposition_is_valid_and_labelled():
    reg = obs.get_registry()
    _populate(reg)
    obs_straggler.record(1, 500.0)
    agg = obs_live.LiveAggregator()
    agg.ingest(_payload(
        0, 1, 0,
        obs_stream.encode_delta({}, obs_stream.snapshot_map(reg.snapshot())),
    ))
    text = agg.prometheus()
    _assert_valid_exposition(text)
    assert '# HELP hvdtpu_ops_total ' in text
    assert '# TYPE hvdtpu_ops_total counter' in text
    assert 'hvdtpu_ops_total{rank="0",epoch="1",kind="x"} 3.0' in text
    # histograms render as summaries with quantile labels + sum/count
    assert 'hvdtpu_lat_ms{rank="0",epoch="1",quantile="0.5"}' in text
    assert 'hvdtpu_lat_ms_count{rank="0",epoch="1"} 3' in text
    assert "hvdtpu_live_ranks_reporting 1" in text
    assert "hvdtpu_live_straggler_rank 1" in text
    # the blamed-rank instrument tag collides with the reserved rank
    # label and must be renamed, not duplicated (scrapers reject dups)
    assert ('hvdtpu_engine_straggler_last_arrivals'
            '{rank="0",epoch="1",tag_rank="1"} 1.0') in text


def _strict_parse_labels(line):
    """Char-level strict parse of one sample line's label block (the
    grammar real scrapers implement): label values may contain ONLY the
    escapes ``\\\\``, ``\\"`` and ``\\n``; a raw quote or backslash is
    a hard parse error.  Returns {label: unescaped value}."""
    if "{" not in line:
        return {}
    block = line[line.index("{") + 1: line.rindex("}")]
    labels = {}
    i = 0
    while i < len(block):
        eq = block.index("=", i)
        key = block[i:eq]
        assert re.fullmatch(r"[a-zA-Z_][a-zA-Z0-9_]*", key), \
            f"bad label name {key!r} in {line!r}"
        assert block[eq + 1] == '"', f"unquoted value in {line!r}"
        j = eq + 2
        out = []
        while True:
            assert j < len(block), f"unterminated value in {line!r}"
            c = block[j]
            if c == "\\":
                esc = block[j + 1] if j + 1 < len(block) else ""
                assert esc in ('\\', '"', 'n'), \
                    f"illegal escape \\{esc} in {line!r}"
                out.append({"\\": "\\", '"': '"', "n": "\n"}[esc])
                j += 2
            elif c == '"':
                j += 1
                break
            else:
                assert c != "\n", f"raw newline in value in {line!r}"
                out.append(c)
                j += 1
        assert key not in labels, f"duplicate label {key} in {line!r}"
        labels[key] = "".join(out)
        i = j + 1 if j < len(block) and block[j] == "," else j
    return labels


def test_prometheus_hostile_label_values_roundtrip():
    """Satellite acceptance: program names (and any instrument tag) can
    carry quotes, backslashes and newlines — the exposition must escape
    them so a strict parser recovers the ORIGINAL value, and the rest
    of the line must stay well-formed."""
    hostile = 'jit_train"step\\fused\nphase2'
    reg = obs.get_registry()
    reg.gauge("mem.compiled.total_bytes", program=hostile).set(123.0)
    reg.gauge("perf.step_ms").set(5.0)
    agg = obs_live.LiveAggregator()
    agg.ingest(_payload(
        0, 0, 0,
        obs_stream.encode_delta({}, obs_stream.snapshot_map(reg.snapshot())),
    ))
    text = agg.prometheus()
    assert text.endswith("\n")
    # no raw newline may survive inside any sample line: the hostile
    # value must occupy ONE line
    sample_lines = [l for l in text.splitlines()
                    if l.startswith("hvdtpu_mem_compiled_total_bytes")]
    assert len(sample_lines) == 1
    labels = _strict_parse_labels(sample_lines[0])
    assert labels["program"] == hostile
    assert labels["rank"] == "0"
    # and every line in the whole exposition strict-parses
    for line in text.rstrip("\n").splitlines():
        if not line.startswith("#"):
            _strict_parse_labels(line)
            assert re.search(r" (NaN|[-+]?[0-9.eE+-]+)$", line), line


def test_prometheus_escape_function_table():
    esc = obs_live.prometheus_escape
    assert esc('plain') == 'plain'
    assert esc('a"b') == 'a\\"b'
    assert esc('a\\b') == 'a\\\\b'
    assert esc('a\nb') == 'a\\nb'
    # backslash-first ordering: escaping must not double-process
    assert esc('\\n') == '\\\\n'


def test_digest_and_history_surface_slo_alert():
    """A firing burn-rate alert must be visible in the live digest line
    and counted in live_history.jsonl rows; a healthy plane shows the
    quiet token; jobs with no SLO traffic show nothing."""
    fast = {"g": {"tenant": "acme", "slo": "interactive",
                  "metric": "ttft", "window": "fast"}}
    agg = obs_live.LiveAggregator()
    agg.ingest(_payload(0, 0, 0, [
        dict({"n": "serve.slo.burn", "k": "g", "v": 12.3}, **fast),
        dict({"n": "serve.slo.alert", "k": "g", "v": 1.0}, **fast),
        {"n": "serve.slo.alerts", "k": "c", "v": 1,
         "g": {"tenant": "acme", "slo": "interactive", "metric": "ttft"}},
    ]))
    d = agg.digest(1)
    assert "slo ALERT acme/interactive ttft fast" in d
    assert "12.3x" in d
    row = agg.history_row(1)
    assert row["slo"] == {"firing": 1, "alerts": 1}
    # healthy: burn present, alert gauge 0
    agg2 = obs_live.LiveAggregator()
    agg2.ingest(_payload(0, 0, 0, [
        dict({"n": "serve.slo.burn", "k": "g", "v": 0.4}, **fast),
        dict({"n": "serve.slo.alert", "k": "g", "v": 0.0}, **fast),
    ]))
    assert "slo OK burn 0.4x" in agg2.digest(1)
    assert agg2.history_row(1)["slo"] == {"firing": 0, "alerts": 0}
    # no SLO series at all: no token, no history key
    agg3 = obs_live.LiveAggregator()
    agg3.ingest(_payload(0, 0, 0, []))
    assert "slo" not in agg3.digest(1)
    assert "slo" not in agg3.history_row(1)


def test_digest_goodput_token_names_worst_rank_sink():
    agg = obs_live.LiveAggregator()
    agg.ingest(_payload(0, 0, 0, [
        {"n": "goodput.fraction", "k": "g", "v": 0.9},
    ]))
    agg.ingest(_payload(1, 0, 0, [
        {"n": "goodput.fraction", "k": "g", "v": 0.6},
        {"n": "goodput.secs", "k": "g", "v": 30.0,
         "g": {"class": "recovery"}},
        {"n": "goodput.secs", "k": "g", "v": 5.0,
         "g": {"class": "compile"}},
        {"n": "goodput.secs", "k": "g", "v": 60.0,
         "g": {"class": "productive_step"}},
    ]))
    d = agg.digest(2)
    assert "goodput 60%" in d  # the worst rank, not the average
    assert "top sink recovery 30s" in d


def test_metrics_endpoint_render_failure_is_5xx(kv_server):
    kv_server.set_metrics_render(lambda: 1 / 0)
    with pytest.raises(urllib.error.HTTPError) as exc:
        urllib.request.urlopen(
            f"http://127.0.0.1:{kv_server.port}/metrics")
    # visible to scrapers (target unhealthy), but the server survives
    assert exc.value.code == 500
    kv_server.set_metrics_render(lambda: "ok 1\n")
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{kv_server.port}/metrics").read()
    assert body == b"ok 1\n"


def test_metrics_endpoint_read_only_unauthenticated(kv_server):
    url = f"http://127.0.0.1:{kv_server.port}/metrics"
    # no renderer installed -> 404 (plain KV deployments are unchanged)
    with pytest.raises(urllib.error.HTTPError) as exc:
        urllib.request.urlopen(url)
    assert exc.value.code == 404

    agg = obs_live.LiveAggregator()
    agg.ingest(_payload(0, 0, 0, [_counter("a", 1)]))
    kv_server.set_metrics_render(agg.prometheus)
    body = urllib.request.urlopen(url).read().decode()
    _assert_valid_exposition(body)
    assert "hvdtpu_a" in body
    # the KV surface stays HMAC-gated: an unsigned PUT is still refused
    req = urllib.request.Request(
        f"http://127.0.0.1:{kv_server.port}/x/y", data=b"evil",
        method="PUT",
    )
    with pytest.raises(urllib.error.HTTPError) as exc:
        urllib.request.urlopen(req)
    assert exc.value.code == 403


# ---------------------------------------------------------------------------
# straggler attribution: controller cycles + elastic waits + reset
# ---------------------------------------------------------------------------


def _request(rank, name="w"):
    from horovod_tpu.runtime.messages import Request, RequestType

    return Request(request_rank=rank, request_type=RequestType.ALLREDUCE,
                   tensor_name=name, dtype="float32", shape=(2,))


def _lists(world, *reqs):
    from horovod_tpu.runtime.messages import RequestList

    out = [RequestList() for _ in range(world)]
    for r in reqs:
        out[r.request_rank].requests.append(r)
    return out


def test_controller_blames_cross_cycle_last_arrival():
    import horovod_tpu.runtime.controller as ctl

    state = ctl.ControllerState(world_size=3)
    ctl.compute_responses(state, _lists(3, _request(0), _request(2)),
                          fusion_threshold_bytes=1 << 20)
    time.sleep(0.005)
    resp, _ = ctl.compute_responses(state, _lists(3, _request(1)),
                                    fusion_threshold_bytes=1 << 20)
    assert len(resp) == 1
    snap = {(m["name"], (m.get("tags") or {}).get("rank")): m
            for m in obs.get_registry().snapshot()}
    assert snap[("engine.straggler.last_arrivals", "1")]["value"] == 1
    hist = snap[("engine.straggler.skew_ms", None)]
    assert hist["count"] == 1 and hist["max"] > 0
    assert snap[("engine.straggler.last_rank", None)]["value"] == 1.0


def test_controller_same_cycle_blames_nobody():
    import horovod_tpu.runtime.controller as ctl

    state = ctl.ControllerState(world_size=2)
    resp, _ = ctl.compute_responses(
        state, _lists(2, _request(0), _request(1)),
        fusion_threshold_bytes=1 << 20,
    )
    assert len(resp) == 1
    names = {m["name"] for m in obs.get_registry().snapshot()}
    assert not any(n.startswith(obs_straggler.PREFIX) for n in names)


def test_controller_alert_threshold_counts_alerts():
    import horovod_tpu.runtime.controller as ctl

    state = ctl.ControllerState(world_size=2)
    ctl.compute_responses(state, _lists(2, _request(0)),
                          fusion_threshold_bytes=1 << 20, alert_skew_ms=0.001)
    time.sleep(0.01)
    ctl.compute_responses(state, _lists(2, _request(1)),
                          fusion_threshold_bytes=1 << 20,
                          alert_skew_ms=0.001)
    snap = {m["name"]: m for m in obs.get_registry().snapshot()}
    assert snap["engine.straggler.alerts"]["value"] == 1
    # below threshold: records but never alerts
    obs.reset_registry()
    obs_straggler.record(1, 10.0, alert_ms=1000.0)
    snap = {m["name"]: m for m in obs.get_registry().snapshot()}
    assert "engine.straggler.alerts" not in snap
    assert snap["engine.straggler.last_arrivals"]["value"] == 1


def test_record_waits_blames_waited_on_peer_only():
    # rank 0 waited 0.5s on rank 2, noise on the others
    blamed = obs_straggler.record_waits(
        {0: 0.0, 1: 0.01, 2: 0.5}, self_rank=0)
    assert blamed == 2
    # a wait under the polling-noise floor blames nobody
    assert obs_straggler.record_waits(
        {0: 0.0, 1: 0.05}, self_rank=0) is None
    # the delayed rank itself (everyone ready when it arrives) is silent
    assert obs_straggler.record_waits(
        {0: 0.01, 1: 0.01}, self_rank=1) is None
    snap = {(m["name"], (m.get("tags") or {}).get("rank")): m
            for m in obs.get_registry().snapshot()}
    assert snap[("engine.straggler.last_arrivals", "2")]["value"] == 1


def test_straggler_reset_clears_instruments():
    obs_straggler.record(1, 100.0)
    obs_straggler.reset()
    names = {m["name"] for m in obs.get_registry().snapshot()}
    assert not any(n.startswith(obs_straggler.PREFIX) for n in names)


def test_elastic_rendezvous_resets_attribution(kv_server):
    import pickle

    from horovod_tpu.elastic.context import ElasticContext

    kv = KVStoreClient(f"127.0.0.1:{kv_server.port}", kv_server.secret)
    kv.put("elastic", "world_0", pickle.dumps([0]))
    kv.put("elastic", "epoch", b"0")
    obs_straggler.record(1, 100.0)
    ctx = ElasticContext(0, kv, timeout=10.0)
    ctx.rendezvous()
    names = {m["name"] for m in obs.get_registry().snapshot()}
    assert not any(n.startswith(obs_straggler.PREFIX) for n in names)


def test_elastic_allreduce_attributes_delayed_peer(kv_server):
    """Two in-process 'ranks' over a real KV store; rank 1 carries an
    action=delay fault, so rank 0's wait attribution must name rank 1 —
    deterministic, no wall-clock races (the delay IS the signal)."""
    import pickle

    from horovod_tpu.elastic.context import ElasticContext

    kv = KVStoreClient(f"127.0.0.1:{kv_server.port}", kv_server.secret)
    kv.put("elastic", "world_0", pickle.dumps([0, 1]))
    kv.put("elastic", "epoch", b"0")

    c0 = ElasticContext(
        0, KVStoreClient(f"127.0.0.1:{kv_server.port}", kv_server.secret),
        timeout=20.0)
    c1 = ElasticContext(
        1, KVStoreClient(f"127.0.0.1:{kv_server.port}", kv_server.secret),
        timeout=20.0)

    def member(ctx, delay):
        ctx.rendezvous()
        if delay:
            time.sleep(delay)  # the straggler (same shape as the fault)
        return ctx.allreduce(np.ones(2), name="g0", average=False)

    out = [None, None]

    def call(i, ctx, delay):
        out[i] = member(ctx, delay)

    threads = [threading.Thread(target=call, args=(0, c0, 0.0)),
               threading.Thread(target=call, args=(1, c1, 0.4))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    np.testing.assert_array_equal(out[0], np.full(2, 2.0))
    snap = {(m["name"], (m.get("tags") or {}).get("rank")): m
            for m in obs.get_registry().snapshot()}
    assert snap[("engine.straggler.last_arrivals", "1")]["value"] == 1
    assert ("engine.straggler.last_arrivals", "0") not in snap


# ---------------------------------------------------------------------------
# summary straggler section
# ---------------------------------------------------------------------------


def _dump_doc(metrics):
    return {"schema": "hvdtpu-metrics-v1", "rank": "0", "metrics": metrics}


def test_summary_straggler_section_names_top_rank():
    obs_straggler.record(1, 480.0)
    obs_straggler.record(1, 520.0)
    obs_straggler.record(0, 30.0)
    doc = _dump_doc(obs.get_registry().snapshot())
    section = obs_summary.straggler_section({"0": doc, "1": doc})
    assert section is not None
    lines = section.splitlines()
    assert lines[0].startswith("rank 1: last to arrive in 2 collectives")
    assert "<- likely straggler" in lines[0]
    assert "rank 0: last to arrive in 1" in lines[1]
    assert "arrival skew: n=3" in section


def test_summary_straggler_section_absent_when_clean():
    assert obs_summary.straggler_section(
        {"0": _dump_doc(obs.get_registry().snapshot())}) is None


# ---------------------------------------------------------------------------
# satellites: delay fault grammar, wait backoff, bench gate, CLI
# ---------------------------------------------------------------------------


def test_delay_fault_grammar_and_sleep(monkeypatch):
    specs = faults.parse_spec("worker_exit:rank=1:action=delay:250:count=3")
    assert specs[0].action == "delay"
    assert specs[0].delay_ms == 250 and specs[0].count == 3
    assert faults.parse_spec("p:action=delay")[0].delay_ms == 1000
    assert faults.parse_spec("p:action=delay:delay_ms=75")[0].delay_ms == 75
    with pytest.raises(ValueError, match="not key=value"):
        faults.parse_spec("p:action=raise:250")  # bare ms needs delay

    monkeypatch.setenv(faults.SPEC_ENV, "pt:action=delay:200")
    faults.reset()
    t0 = time.monotonic()
    faults.maybe_fail("pt")  # sleeps, then CONTINUES (no raise)
    assert 0.15 < time.monotonic() - t0 < 2.0
    t0 = time.monotonic()
    faults.maybe_fail("pt")  # count exhausted: instant
    assert time.monotonic() - t0 < 0.05


class _FakeTime:
    def __init__(self):
        self.now = 0.0
        self.sleeps = []

    def time(self):
        return self.now

    def sleep(self, secs):
        self.sleeps.append(round(secs, 4))
        self.now += secs


def test_kv_wait_exponential_backoff(monkeypatch):
    clock = _FakeTime()
    monkeypatch.setattr(rdv, "time", clock)
    client = KVStoreClient("127.0.0.1:1", "s")
    monkeypatch.setattr(client, "get", lambda scope, key: None)
    with pytest.raises(TimeoutError):
        client.wait("s", "k", timeout=10.0)
    # doubles from 50 ms, capped at 1 s — not the old fixed 100 ms hammer
    assert clock.sleeps[:6] == [0.05, 0.1, 0.2, 0.4, 0.8, 1.0]
    assert max(clock.sleeps) <= 1.0
    assert len(clock.sleeps) < 20  # fixed 0.1s polling would need 100


def test_bench_regression_gate(tmp_path):
    import bench

    def rec(n, parsed, rc=0):
        (tmp_path / f"BENCH_r0{n}.json").write_text(
            json.dumps({"n": n, "rc": rc, "parsed": parsed}))

    dev = "TPU v5 lite"
    rec(1, {"metric": "m", "value": 100.0, "mfu": 0.2, "device": dev})
    rec(2, {"metric": "m", "value": 110.0, "mfu": 0.25, "device": dev})
    rec(3, None, rc=86)

    out = bench.attach_regression(
        {"metric": "m", "value": 99.0, "mfu": 0.22, "device": dev},
        record_dir=str(tmp_path))
    # r19: the baseline is the EWMA over the real trajectory
    # (0.5*110 + 0.5*100 = 105), not the single newest record, and the
    # provenance names every record the fold consumed.
    assert out["baseline_record"] == {
        "file": "BENCH_r02.json",
        "baseline_records": ["BENCH_r01.json", "BENCH_r02.json"],
        "ewma": {"k": 5, "alpha": 0.5, "count": 2},
        "stale_records_skipped": 1,
        "degraded_records_skipped": 0, "stale": True}
    assert out["deltas"]["value"]["pct"] == -5.71
    assert out["regression"] is True

    ok = bench.attach_regression(
        {"metric": "m", "value": 112.0, "device": dev},
        record_dir=str(tmp_path))
    assert ok["regression"] is False and "mfu" not in ok["deltas"]
    # device mismatch (CPU dev run vs TPU record) is never compared
    cpu = bench.attach_regression(
        {"metric": "m", "value": 5.0, "device": "cpu"},
        record_dir=str(tmp_path))
    assert cpu["regression"] is None
    assert cpu["baseline_record"]["file"] is None
    # an unreadable record dir must never sink the measurement
    assert "regression" in bench.attach_regression(
        {"metric": "m", "value": 1.0}, record_dir=None)


def test_cli_live_knobs_map_to_env():
    from horovod_tpu.run.config_parser import set_env_from_args
    from horovod_tpu.run.runner import parse_args

    args = parse_args([
        "-np", "2",
        "--live-stats-secs", "2.5",
        "--live-port", "9999",
        "--live-history-file", "/tmp/h.jsonl",
        "--alert-skew-ms", "250",
        "python", "train.py",
    ])
    env = {}
    set_env_from_args(env, args)
    assert env["HVDTPU_LIVE_STATS_SECS"] == "2.5"
    assert env["HVDTPU_ALERT_SKEW_MS"] == "250.0"
    # launcher-local knobs stay out of the worker env
    assert args.live_port == 9999
    assert args.live_history_file == "/tmp/h.jsonl"
    assert "HVDTPU_LIVE_KV" not in env


# ---------------------------------------------------------------------------
# 2-proc chaos acceptance: delay straggler named live and at job end
# ---------------------------------------------------------------------------


def _delay_chaos_train():
    import numpy as np  # noqa: PLC0415

    import horovod_tpu.elastic as elastic  # noqa: PLC0415

    ctx = elastic.context()
    state = elastic.State(w=np.zeros(2, dtype=np.float64), step=0)

    @elastic.run
    def loop(state):
        while state.step < 6:
            state.w = state.w + ctx.allreduce(
                np.ones(2), name=f"g{state.step}", average=False)
            state.step += 1
            state.commit()
        return state.step

    return loop(state)


@pytest.mark.multiprocess
def test_live_plane_names_delay_straggler_e2e(tmp_path):
    """ISSUE 3 acceptance: a 2-proc elastic job with an injected
    ``action=delay`` straggler on rank 1.  The live history (one row per
    aggregation round, i.e. one reporting interval) must name rank 1
    while the job runs, and the end-of-job dumps must attribute it in
    the straggler section."""
    import horovod_tpu.elastic as elastic

    hist = str(tmp_path / "live_history.jsonl")
    dumps = str(tmp_path / "metrics") + "/"
    env = {
        "JAX_PLATFORMS": "cpu",
        # every allreduce on rank 1 stalls 400 ms before contributing
        "HVDTPU_FAULT_SPEC": "worker_exit:rank=1:action=delay:400:count=6",
        "HVDTPU_METRICS_DUMP": dumps,
    }
    (tmp_path / "metrics").mkdir()
    results, job = elastic.launch(
        _delay_chaos_train, np=2, env=env, timeout=120,
        live_stats_secs=0.2, live_history=hist,
    )
    assert results == {0: 6, 1: 6}
    assert [e[0] for e in job.trace] == ["spawn", "spawn"]

    # live: some aggregation round named the lagging rank
    rows = [json.loads(l) for l in open(hist)]
    assert rows, "no live history rows were appended"
    named = [r["straggler"] for r in rows if r.get("straggler")]
    assert named, f"no round named a straggler: {rows}"
    assert named[-1]["rank"] == 1
    assert named[-1]["worst_skew_ms"] > 200.0

    # job end: the per-rank dumps attribute the same rank
    docs = obs_summary.collect_dumps(dumps)
    assert docs
    section = obs_summary.straggler_section(docs)
    assert section is not None
    assert section.splitlines()[0].startswith("rank 1: last to arrive")
