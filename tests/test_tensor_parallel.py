"""Megatron-style tensor parallelism (parallel/tensor_parallel.py) — the
optional-stretch axis beyond the reference's DP (SURVEY.md §2.9).

Contract: tp_gpt_apply over a tp-axis mesh reproduces the unsharded
GPT.apply exactly (fp32, up to associativity), forward AND gradients,
with each rank holding only whole-head / width shards of the block
weights and exactly two psums per block.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.models.transformer import gpt
from horovod_tpu.parallel.tensor_parallel import (
    stack_tp_params,
    tp_gpt_apply,
)

TP = 4
AXIS = "tp"


def _mesh():
    return Mesh(np.asarray(jax.devices()[:TP]), (AXIS,))


def _model(**overrides):
    common = dict(num_layers=2, num_heads=4, emb_dim=64, max_len=64,
                  vocab_size=512, dtype=jnp.float32,
                  attention_impl="reference")
    common.update(overrides)
    return gpt("nano", **common)


def _tokens(seed=0, s=32):
    return jnp.asarray(
        np.random.RandomState(seed).randint(0, 512, (2, s)), jnp.int32
    )


def _tp_fwd(model, params, tokens):
    sharded, replicated = stack_tp_params(params, model.cfg, TP)

    def local(sharded, replicated, tok):
        return tp_gpt_apply(sharded, replicated, model.cfg, tok, AXIS)

    fwd = jax.jit(
        shard_map(
            local, mesh=_mesh(),
            in_specs=(P(AXIS), P(), P()), out_specs=P(),
            check_vma=False,
        )
    )
    return fwd(sharded, replicated, tokens)


@pytest.mark.parametrize("pos_embedding", ["learned", "rope"])
def test_tp_matches_single_device(pos_embedding):
    model = _model(pos_embedding=pos_embedding)
    tokens = _tokens()
    params = model.init(jax.random.PRNGKey(0), tokens)
    ref = model.apply(params, tokens)
    out = _tp_fwd(model, params, tokens)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-4, rtol=2e-4
    )


def test_tp_gqa_matches_single_device():
    # TRUE GQA: kv_heads (4) < num_heads (8), both divisible by tp
    model = _model(num_heads=8, num_kv_heads=4)
    tokens = _tokens(1)
    params = model.init(jax.random.PRNGKey(1), tokens)
    ref = model.apply(params, tokens)
    out = _tp_fwd(model, params, tokens)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-4, rtol=2e-4
    )


def test_tp_gradients_match():
    """Grads w.r.t. the SHARDED weights equal the matching slices of the
    unsharded model's grads (column/row splits commute with autodiff).
    check_vma=True (replication tracking) is what makes the psum
    transpose correct — see the tp-scaling pin below."""
    model = _model()
    tokens = _tokens(2)
    params = model.init(jax.random.PRNGKey(2), tokens)
    targets = jnp.roll(tokens, -1, axis=1)

    def loss_ref(p):
        logits = model.apply(p, tokens)
        return -jnp.take_along_axis(
            jax.nn.log_softmax(logits), targets[..., None], -1
        ).mean()

    g_ref = jax.grad(loss_ref)(params)["params"]
    sharded, replicated = stack_tp_params(params, model.cfg, TP)

    def local_loss(sharded, replicated, tok, tgt):
        logits = tp_gpt_apply(sharded, replicated, model.cfg, tok, AXIS)
        return -jnp.take_along_axis(
            jax.nn.log_softmax(logits), tgt[..., None], -1
        ).mean()

    grad_fn = jax.jit(
        shard_map(
            jax.grad(local_loss), mesh=_mesh(),
            in_specs=(P(AXIS), P(), P(), P()), out_specs=P(AXIS),
            check_vma=True,
        )
    )
    g_tp = grad_fn(sharded, replicated, tokens, targets)
    # qkv kernel shard 0 of the stacked grads == the reference grad's
    # matching column block (rank 0 holds q head 0 + k/v head 0)
    cfg = model.cfg
    hd = cfg.head_dim
    blk_ref = g_ref["block0"]["qkv"]["kernel"]
    emb = cfg.emb_dim
    want = np.concatenate([
        np.asarray(blk_ref[:, :hd]),                   # q head 0
        np.asarray(blk_ref[:, emb:emb + hd]),          # k head 0
        np.asarray(blk_ref[:, 2 * emb:2 * emb + hd]),  # v head 0
    ], axis=1)
    got = np.asarray(g_tp["block0"]["qkv"]["kernel"][0])
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)
    # fc2 row shard: rank 0 holds the first width/TP rows
    rows = (cfg.mlp_ratio * cfg.emb_dim) // TP
    np.testing.assert_allclose(
        np.asarray(g_tp["block0"]["fc2"]["kernel"][0]),
        np.asarray(g_ref["block0"]["fc2"]["kernel"][:rows]),
        atol=2e-4, rtol=2e-4,
    )


def test_tp_replicated_stacking_scales_grads():
    """Pin the failure mode stack_tp_params' split exists to prevent:
    pass the replicated weights STACKED-AND-SHARDED instead of truly
    replicated and the sharded-weight grads come out scaled by tp."""
    from jax import lax

    mesh = _mesh()
    W = jnp.asarray(np.random.RandomState(0).randn(TP, 2, 3), jnp.float32)
    H = jnp.asarray(np.random.RandomState(2).randn(3, 5), jnp.float32)
    x = jnp.asarray(np.random.RandomState(1).randn(1, 2), jnp.float32)

    def loss_full(W):
        y = sum(x @ W[r] for r in range(TP))
        return ((y @ H) ** 2).sum()

    g_full = jax.grad(loss_full)(W)

    Hs = jnp.broadcast_to(H[None], (TP,) + H.shape)

    def ll_stacked(Wr, Hs, x):
        y = lax.psum(x @ Wr[0], AXIS)
        return ((y @ Hs[0]) ** 2).sum()

    g_bad = jax.jit(shard_map(
        jax.grad(ll_stacked), mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P()), out_specs=P(AXIS),
        check_vma=True,
    ))(W, Hs, x)
    ratio = float(np.median(np.asarray(g_bad) / np.asarray(g_full)))
    assert abs(ratio - TP) < 1e-3, f"expected the {TP}x artifact, {ratio}"

    def ll_rep(Wr, H, x):
        y = lax.psum(x @ Wr[0], AXIS)
        return ((y @ H) ** 2).sum()

    g_good = jax.jit(shard_map(
        jax.grad(ll_rep), mesh=mesh,
        in_specs=(P(AXIS), P(), P()), out_specs=P(AXIS),
        check_vma=True,
    ))(W, H, x)
    np.testing.assert_allclose(np.asarray(g_good), np.asarray(g_full),
                               rtol=1e-5)


def test_tp_divisibility_errors():
    model = _model()
    params = model.init(jax.random.PRNGKey(0), _tokens())
    with pytest.raises(ValueError, match="must divide num_heads"):
        stack_tp_params(params, model.cfg, 3)


def test_unstack_tp_round_trips():
    """stack_tp_params -> unstack_tp_params is the identity (the
    docs/inference.md column/row-split inversion as code); a wrong tp
    raises instead of reassembling a correct-shaped scrambled kernel."""
    import pytest
    from conftest import assert_trees_equal
    from horovod_tpu.parallel.tensor_parallel import unstack_tp_params

    model = _model()
    params = model.init(jax.random.PRNGKey(8), _tokens())["params"]
    sharded, replicated = stack_tp_params({"params": params},
                                          model.cfg, 2)
    assert_trees_equal(
        unstack_tp_params(sharded, replicated, model.cfg, 2), params
    )
    with pytest.raises(ValueError, match="leading dim"):
        unstack_tp_params(sharded, replicated, model.cfg, 4)
