"""Serving plane (horovod_tpu/serve/): the continuous-batching
scheduler as a pure decision table, the slot engine against the
single-stream ``generate`` oracle, sequence-sharded long-context
attention against the replicated math, and the end-to-end elastic
story — staggered requests through a live 2-proc fleet with a
mid-stream kill recovered by respawn + replay, zero requests dropped.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.models.decode import generate
from horovod_tpu.models.transformer import gpt
from horovod_tpu.serve import (
    Request, ServeJob, SlotEngine, SlotScheduler, validate_request,
)
from horovod_tpu.serve.engine import prompt_bucket

AXIS = "seq"


def _req(rid, n=3, mnt=4, eos=None):
    return Request(rid=rid, prompt=tuple(range(1, n + 1)),
                   max_new_tokens=mnt, eos_id=eos)


def _model(**overrides):
    common = dict(num_layers=1, num_heads=2, emb_dim=32, max_len=64,
                  vocab_size=64, dtype=jnp.float32,
                  attention_impl="reference")
    common.update(overrides)
    return gpt("nano", **common)


# ---------------------------------------------------------------------------
# Scheduler core: the pure decision table
# ---------------------------------------------------------------------------


def test_admit_fcfs_into_lowest_free_slots():
    s = SlotScheduler(3)
    for i in range(2):
        s.enqueue(_req(f"r{i}"))
    admits = s.admit(step=1)
    assert [(a.slot, a.req.rid) for a in admits] == [(0, "r0"), (1, "r1")]
    assert s.free_slots() == [2]
    assert s.queue_depth == 0 and s.active_slots == 2


def test_slot_exhaustion_queues_and_recycles():
    s = SlotScheduler(2)
    for i in range(5):
        s.enqueue(_req(f"r{i}", mnt=1))
    assert [a.req.rid for a in s.admit()] == ["r0", "r1"]
    assert s.queue_depth == 3  # pool exhausted -> queued
    assert s.admit() == []     # no free slot, no admission
    s.record(0, 7)
    s.record(1, 7)
    evs = s.evict_finished()
    assert [(e.slot, e.rid, e.reason) for e in evs] == [
        (0, "r0", "budget"), (1, "r1", "budget")]
    # evicted slots recycle immediately, FCFS order preserved
    assert [(a.slot, a.req.rid) for a in s.admit()] == [
        (0, "r2"), (1, "r3")]
    assert s.queue_depth == 1


def test_eviction_reasons_and_stop_conditions():
    s = SlotScheduler(2)
    s.enqueue(_req("budget", mnt=2))
    s.enqueue(_req("eos", mnt=10, eos=9))
    s.admit()
    s.record(0, 5)
    s.record(1, 5)
    assert s.evict_finished() == []
    s.record(0, 6)
    s.record(1, 9)  # the eos token
    evs = {e.rid: e for e in s.evict_finished()}
    assert evs["budget"].reason == "budget"
    assert evs["budget"].tokens == (5, 6)
    assert evs["eos"].reason == "eos"
    assert evs["eos"].tokens == (5, 9)
    # recording past a stop condition is a contract violation
    s.enqueue(_req("x", mnt=1))
    s.admit()
    s.record(0, 1)
    with pytest.raises(ValueError, match="finished"):
        s.record(0, 2)
    with pytest.raises(KeyError):
        s.record(1, 2)  # freed slot has no active request


def test_resume_replay_counts_toward_budget():
    s = SlotScheduler(1)
    s.enqueue(_req("r", mnt=3), resume=(4, 5))
    (adm,) = s.admit()
    assert adm.resume == (4, 5)
    s.record(0, 6)  # one more token exhausts the budget
    (ev,) = s.evict_finished()
    assert ev.tokens == (4, 5, 6) and ev.reason == "budget"


def test_identical_schedule_across_simulated_ranks():
    """The HVD001 invariant: N scheduler instances fed the same inputs
    in the same order make identical decisions, step for step."""
    rng = np.random.RandomState(0)
    ranks = [SlotScheduler(2) for _ in range(3)]
    logs = [[] for _ in ranks]
    rid = 0
    for step in range(1, 40):
        arrivals = [
            _req(f"r{rid + i}", n=int(rng.randint(1, 4)),
                 mnt=int(rng.randint(1, 5)))
            for i in range(rng.randint(0, 3))
        ]
        rid += len(arrivals)
        token = int(rng.randint(0, 50))
        for sched, log in zip(ranks, logs):
            for req in arrivals:
                sched.enqueue(req)
            admits = sched.admit(step)
            for a in admits:
                sched.record(a.slot, token)
            for slot in sorted(sched.active):
                if not sched.active[slot].done:
                    sched.record(slot, token)
            evs = sched.evict_finished()
            log.append((
                step,
                tuple((a.slot, a.req.rid) for a in admits),
                tuple((e.slot, e.rid, e.reason, e.tokens) for e in evs),
                sched.queue_depth, sched.active_slots,
            ))
    assert logs[0] == logs[1] == logs[2]


def test_snapshot_lists_active_then_queued():
    s = SlotScheduler(1)
    s.enqueue(_req("a", mnt=5))
    s.enqueue(_req("b", mnt=5))
    s.admit()
    s.record(0, 3)
    snap = s.snapshot()
    assert [d["rid"] for d in snap] == ["a", "b"]
    assert snap[0]["emitted"] == [3] and snap[1]["emitted"] == []


def test_request_and_scheduler_validation():
    with pytest.raises(ValueError, match="empty prompt"):
        Request(rid="x", prompt=())
    with pytest.raises(ValueError, match="max_new_tokens"):
        Request(rid="x", prompt=(1,), max_new_tokens=0)
    with pytest.raises(ValueError, match="num_slots"):
        SlotScheduler(0)


def test_validate_request_decision_table():
    ok = {"prompt": [1, 2], "max_new_tokens": 4}
    assert validate_request(ok, serve_len=16) is None
    assert validate_request(ok, serve_len=16, vocab_size=64) is None
    # Every verdict is a str (the human message) AND carries the
    # machine-readable code ServeClient.result surfaces (ISSUE 16);
    # tests/test_frontdoor.py has the full code table.
    v = validate_request({"prompt": [], "max_new_tokens": 4}, 16)
    assert "prompt" in v and v.code == "bad_prompt"
    v = validate_request({"prompt": [1, -2], "max_new_tokens": 4}, 16)
    assert "ints" in v and v.code == "bad_token"
    v = validate_request(
        {"prompt": [1, 64], "max_new_tokens": 4}, 16, vocab_size=64)
    assert "vocab" in v and v.code == "oob_token"
    v = validate_request({"prompt": [1], "max_new_tokens": 0}, 16)
    assert "max_new_tokens" in v and v.code == "bad_budget"
    v = validate_request(
        {"prompt": [1] * 10, "max_new_tokens": 8}, 16)
    assert "exceeds" in v and v.code == "ctx_exceeded"


def test_engine_serve_len_caps_oversized_cache():
    """An oversized slot cache must not let a valid-looking request's
    power-of-two prefill bucket exceed the model's max_len (review
    finding: that ValueError would crash-loop the fleet on replay)."""
    model = _model()  # cfg.max_len = 64
    params = model.init(jax.random.PRNGKey(20),
                        jnp.zeros((1, 8), jnp.int32))
    eng = SlotEngine(model.cfg, params, num_slots=1, max_len=128)
    assert eng.cache_len == 128 and eng.serve_len == 64
    # a 40-token prompt would bucket to 64 (<= max_len): admissible
    reason = validate_request(
        {"prompt": [1] * 40, "max_new_tokens": 8}, eng.serve_len)
    assert reason is None
    assert eng.admit(0, [1] * 40) is not None
    # 70 tokens fits the raw cache but not the serving context
    assert "exceeds" in validate_request(
        {"prompt": [1] * 70, "max_new_tokens": 8}, eng.serve_len)


def test_prompt_bucket():
    assert prompt_bucket(3, 64) == 8
    assert prompt_bucket(8, 64) == 8
    assert prompt_bucket(9, 64) == 16
    assert prompt_bucket(40, 48) == 48  # clamped to the cache
    with pytest.raises(ValueError, match="exceeds"):
        prompt_bucket(65, 64)


# ---------------------------------------------------------------------------
# Slot engine vs the single-stream oracle (no launcher)
# ---------------------------------------------------------------------------


def test_engine_continuous_batch_matches_generate():
    """The acceptance core, distilled: requests admitted at different
    steps into a shared pool — including mid-decode admissions — each
    produce exactly the tokens single-stream ``generate`` produces."""
    model = _model(pos_embedding="rope")
    cfg = model.cfg
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    engine = SlotEngine(cfg, params, num_slots=2)
    sched = SlotScheduler(2)
    rng = np.random.RandomState(5)
    reqs = {}
    for i in range(5):
        prompt = tuple(int(t) for t in rng.randint(0, 64,
                                                   rng.randint(3, 9)))
        reqs[f"r{i}"] = Request(rid=f"r{i}", prompt=prompt,
                                max_new_tokens=int(rng.randint(2, 6)))
    oracle = {
        rid: np.asarray(generate(
            cfg, params, jnp.asarray([req.prompt], jnp.int32),
            req.max_new_tokens,
        ))[0].tolist()
        for rid, req in reqs.items()
    }
    # stagger arrivals: two up front, the rest dripped in mid-decode
    pending = list(reqs.values())
    finished = {}
    mid_decode_admission = False
    for step in range(1, 60):
        if pending and (step == 1 or step % 3 == 0):
            sched.enqueue(pending.pop(0))
        admits = sched.admit(step)
        for adm in admits:
            if sched.active_slots > len(admits):
                mid_decode_admission = True
            tok = engine.admit(adm.slot, adm.req.prompt, adm.resume)
            sched.record(adm.slot, tok)
        for ev in sched.evict_finished():
            finished[ev.rid] = list(ev.tokens)
        active = sorted(sched.active)
        if active:
            toks = engine.step(active)
            for slot in active:
                sched.record(slot, toks[slot])
        for ev in sched.evict_finished():
            finished[ev.rid] = list(ev.tokens)
        if len(finished) == len(reqs):
            break
    assert finished == oracle
    assert mid_decode_admission, "no admission ever overlapped a decode"


def test_engine_replay_resumes_mid_stream():
    """Elastic-replay primitive: rebuilding a slot from prompt + the
    tokens already streamed continues the generation bit-exactly."""
    model = _model()
    cfg = model.cfg
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))
    prompt = tuple(int(t) for t in
                   np.random.RandomState(2).randint(0, 64, 6))
    want = np.asarray(generate(
        cfg, params, jnp.asarray([prompt], jnp.int32), 6))[0].tolist()

    fresh = SlotEngine(cfg, params, num_slots=1)
    replay = SlotEngine(cfg, params, num_slots=1)
    # fresh run, interrupted after 3 tokens
    toks = [fresh.admit(0, prompt)]
    for _ in range(2):
        toks.append(fresh.step([0])[0])
    assert toks == want[:3]
    # replayed engine: admit with the emitted prefix, then continue
    assert replay.admit(0, prompt, resume=tuple(toks)) is None
    for _ in range(3):
        toks.append(replay.step([0])[0])
    assert toks == want


# ---------------------------------------------------------------------------
# Long-context: sequence-sharded attention over the 8-device CPU mesh
# ---------------------------------------------------------------------------


def test_sharded_decode_attention_matches_replicated():
    from horovod_tpu.models.decode import _attend_cached
    from horovod_tpu.serve.longctx import sharded_decode_attention

    model = _model(num_kv_heads=2, num_heads=4, emb_dim=64)
    cfg = model.cfg
    rng = np.random.RandomState(3)
    b, s, h, hd = 3, 32, cfg.num_heads, cfg.head_dim
    q = jnp.asarray(rng.randn(b, h, hd), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, cfg.kv_heads, hd), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, cfg.kv_heads, hd), jnp.float32)
    # per-slot positions, including a fresh slot (0) and a full one
    pos = jnp.asarray([5, 0, s - 1], jnp.int32)
    want = _attend_cached(cfg, q, k, v, pos)

    mesh = Mesh(np.asarray(jax.devices()[:4]), (AXIS,))
    fn = jax.jit(
        shard_map(
            lambda q, k, v, pos: sharded_decode_attention(
                cfg, q, k, v, pos, AXIS),
            mesh=mesh,
            in_specs=(P(), P(None, AXIS), P(None, AXIS), P()),
            out_specs=P(),
        )
    )
    got = fn(q, k, v, pos)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)
    # Deeply negative scores: every real max sits far below the 0.0 a
    # fully-masked chunk's clamped max would contribute — the merge
    # must rescale against the true contributing max, not underflow
    # every exp to zero (review finding on the pmax mask).
    q_neg = q - 40.0
    k_neg = k + 40.0
    want_neg = _attend_cached(cfg, q_neg, k_neg, v, pos)
    got_neg = fn(q_neg, k_neg, v, pos)
    assert np.abs(np.asarray(got_neg)).max() > 0.0
    np.testing.assert_allclose(np.asarray(got_neg),
                               np.asarray(want_neg),
                               atol=1e-5, rtol=1e-5)


def test_ulysses_prefill_attention_matches_local():
    from horovod_tpu.parallel.ring_attention import local_attention
    from horovod_tpu.serve.longctx import ulysses_prefill_attention

    rng = np.random.RandomState(4)
    b, s, h, hd = 2, 32, 4, 8
    q = jnp.asarray(rng.randn(b, s, h, hd), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, hd), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, hd), jnp.float32)
    want = local_attention(q, k, v, causal=True)

    mesh = Mesh(np.asarray(jax.devices()[:4]), (AXIS,))
    got = jax.jit(
        shard_map(
            lambda q, k, v: ulysses_prefill_attention(q, k, v, AXIS),
            mesh=mesh,
            in_specs=(P(None, AXIS), P(None, AXIS), P(None, AXIS)),
            out_specs=P(None, AXIS),
        )
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


# ---------------------------------------------------------------------------
# End-to-end: the elastic serving fleet (real processes)
# ---------------------------------------------------------------------------

_OVERRIDES = dict(num_layers=1, num_heads=2, emb_dim=32, max_len=64,
                  vocab_size=64, dtype="float32",
                  attention_impl="reference")


def _spec(slots=2):
    o = dict(_OVERRIDES)
    o["dtype"] = jnp.float32
    return {"size": "nano", "overrides": o, "seed": 3,
            "num_slots": slots, "idle_secs": 0.005}


def _oracle(prompts, steps):
    o = dict(_OVERRIDES)
    o["dtype"] = jnp.float32
    model = gpt("nano", **o)
    params = model.init(jax.random.PRNGKey(3),
                        jnp.zeros((1, 8), jnp.int32))
    return [
        np.asarray(generate(model.cfg, params,
                            jnp.asarray([p], jnp.int32), s))[0].tolist()
        for p, s in zip(prompts, steps)
    ]


@pytest.mark.multiprocess
@pytest.mark.slow
def test_serve_job_staggered_requests_and_rejection():
    """Single-rank fleet: staggered mixed-length requests all complete
    with oracle tokens through slot churn; an oversized request is
    rejected with a reason instead of wedging the loop."""
    rs = np.random.RandomState(0)
    prompts = [rs.randint(0, 64, rs.randint(3, 9)).tolist()
               for _ in range(5)]
    steps = [3, 5, 2, 4, 3]
    oracle = _oracle(prompts, steps)
    job = ServeJob(_spec(), np=1, env={"JAX_PLATFORMS": "cpu"},
                   timeout=240).start()
    try:
        rids = []
        for p, s in zip(prompts, steps):
            rids.append(job.client.submit(p, max_new_tokens=s))
            time.sleep(0.03)
        bad = job.client.submit([1] * 60, max_new_tokens=30)
        docs = [job.client.result(r, timeout=180) for r in rids]
        with pytest.raises(RuntimeError, match="exceeds"):
            job.client.result(bad, timeout=180)
        results, ejob = job.stop()
    finally:
        job.shutdown()
    assert [d["tokens"] for d in docs] == oracle
    # slot exhaustion forced at least one post-start admission
    assert max(d["admitted_step"] for d in docs) > 1
    assert results[0]["completed"] == 5
    assert [e[0] for e in ejob.trace] == ["spawn"]


@pytest.mark.multiprocess
@pytest.mark.slow
def test_serve_chaos_kill_leader_respawn_zero_dropped():
    """ISSUE 10 acceptance: 2-proc fleet, 8 staggered mixed-length
    requests, the LEADER (rank 0 — the only rank that reads the ingest
    log and writes result streams) killed mid-stream at its own step 6,
    which is deterministically mid-stream (8 requests x >=3 tokens
    through 2 slots need far more than 6 busy steps).  The launcher
    respawns it into a fresh epoch, the scheduler replays every
    in-flight request from the durable rank-0 queue, and every request
    completes with tokens identical to single-stream ``generate`` —
    zero dropped."""
    rs = np.random.RandomState(7)
    prompts = [rs.randint(0, 64, rs.randint(3, 9)).tolist()
               for _ in range(8)]
    steps = [3, 4, 5, 6, 3, 4, 5, 6]
    oracle = _oracle(prompts, steps)
    job = ServeJob(
        _spec(), np=2,
        env={"JAX_PLATFORMS": "cpu",
             "HVDTPU_FAULT_SPEC": "worker_exit:step=6:rank=0"},
        max_retries=2, timeout=300,
    ).start()
    try:
        rids = []
        for p, s in zip(prompts, steps):
            rids.append(job.client.submit(p, max_new_tokens=s))
            time.sleep(0.05)
        docs = [job.client.result(r, timeout=240) for r in rids]
        results, ejob = job.stop()
    finally:
        job.shutdown()
    assert [d["tokens"] for d in docs] == oracle
    events = [e[0] for e in ejob.trace]
    assert events.count("failure") == 1 and events.count("respawn") == 1
    # some request finished in the post-recovery epoch (the kill was
    # mid-stream), and the recovery replayed rather than restarted:
    # requests finished before the break keep their epoch-0 stamp
    assert max(d["epoch"] for d in docs) >= 1
    # both ranks drained cleanly and returned summaries
    assert sorted(results) == [0, 1]
    assert all(v["completed"] >= 1 for v in results.values())


@pytest.mark.multiprocess
@pytest.mark.slow
def test_serve_width_fleet_partition_chaos_and_sampling():
    """ISSUE 15 acceptance: a width-sharded fleet (np=2, width=1 -> 2
    independent serving groups over the log partition n % 2) serves 8
    mixed requests — two of them SAMPLED (temperature/top-k) — with a
    mid-stream kill of rank 1 (group 1's leader).  Every stream must
    equal the single-engine oracle bit-for-bit: greedy via
    ``generate``, sampled via the shared (rid, emission index, seed)
    key derivation — the fleet shape, the chaos replay with paged
    block tables, and the sampler must all be invisible in the tokens.
    Both groups must have actually served (the partition is capacity,
    not standby)."""
    from horovod_tpu.serve.engine import SlotEngine as _Eng

    spec = _spec()
    spec.update({"width": 1, "kv_mode": "paged", "page_size": 8,
                 "kv_pages": 16})
    rs = np.random.RandomState(11)
    prompts = [rs.randint(0, 64, rs.randint(3, 9)).tolist()
               for _ in range(8)]
    steps = [3, 4, 5, 6, 3, 4, 5, 6]
    temps = [0.0, 0.0, 0.9, 0.0, 0.0, 0.8, 0.0, 0.0]
    rids = [f"flt{i}" for i in range(8)]

    o = dict(_OVERRIDES)
    o["dtype"] = jnp.float32
    model = gpt("nano", **o)
    params = model.init(jax.random.PRNGKey(3),
                        jnp.zeros((1, 8), jnp.int32))

    def oracle_one(prompt, n, temp, rid):
        eng = _Eng(model.cfg, params, 1, kv_mode="paged", page_size=8,
                   sample_seed=spec["seed"])
        toks = [eng.admit(0, prompt, temperature=temp, top_k=8,
                          rid=rid, total_len=len(prompt) + n)]
        for _ in range(n - 1):
            toks.append(eng.step([0])[0])
        return toks

    oracle = [oracle_one(p, n, t, r)
              for p, n, t, r in zip(prompts, steps, temps, rids)]

    job = ServeJob(
        spec, np=2,
        env={"JAX_PLATFORMS": "cpu",
             "HVDTPU_FAULT_SPEC": "worker_exit:step=6:rank=1"},
        max_retries=2, timeout=300,
    ).start()
    try:
        for p, n, t, r in zip(prompts, steps, temps, rids):
            job.client.submit(p, max_new_tokens=n, temperature=t,
                              top_k=8, rid=r)
            time.sleep(0.05)
        docs = [job.client.result(r, timeout=240) for r in rids]
        results, ejob = job.stop()
    finally:
        job.shutdown()
    assert [d["tokens"] for d in docs] == oracle
    events = [e[0] for e in ejob.trace]
    assert events.count("failure") == 1 and events.count("respawn") == 1
    # the partition is real capacity: each rank completed ITS group
    assert sorted(results) == [0, 1]
    assert all(v["completed"] >= 1 for v in results.values())
    assert {v.get("group") for v in results.values()} == {0, 1}
    # completed is per-incarnation: requests group 1 finished BEFORE
    # the kill died with that incarnation's summary (their done docs
    # survive, which is why the streams above are 8/8) — so the sum is
    # >= 8 minus what pre-kill group 1 finished, never more than 8.
    total_done = sum(v["completed"] for v in results.values())
    assert 4 <= total_done <= 8
