"""SPMD collective correctness over dtype x shape grids, plus autodiff rules.

Models the reference's per-framework op tests (test/test_torch.py,
test/test_tensorflow.py — allreduce/allgather/broadcast over dtype/dim
grids, average vs sum, grad correctness of the autograd Functions)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

import horovod_tpu as hvd

N = 8  # virtual device count (tests/conftest.py)

DTYPES = [jnp.float32, jnp.bfloat16, jnp.int32, jnp.float16]
SHAPES = [(1,), (17,), (2, 3), (4, 5, 2)]


def run_spmd(fn, *per_rank_inputs):
    """Run fn(rank-local args) on all 8 shards; returns per-rank outputs.

    per_rank_inputs: arrays with leading axis N (one slice per shard)."""
    mesh = hvd.mesh("flat")
    specs = tuple(P(hvd.DP_AXIS) for _ in per_rank_inputs)
    out = shard_map(
        fn, mesh=mesh, in_specs=specs, out_specs=P(hvd.DP_AXIS)
    )(*per_rank_inputs)
    return out


def stacked(shape, dtype, seed=0):
    rng = np.random.RandomState(seed)
    if jnp.issubdtype(dtype, jnp.integer):
        x = rng.randint(-10, 10, size=(N,) + shape)
    else:
        x = rng.randn(N, *shape)
    return jnp.asarray(x, dtype)


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("shape", SHAPES)
def test_allreduce_sum(dtype, shape):
    x = stacked(shape, dtype)
    out = run_spmd(
        lambda v: hvd.allreduce(v[0], op=hvd.Sum)[None], x
    )
    expected = jnp.sum(x.astype(jnp.float32), axis=0).astype(dtype)
    for r in range(N):
        tol = 1e-2 if dtype in (jnp.bfloat16, jnp.float16) else 1e-5
        np.testing.assert_allclose(
            np.asarray(out[r], np.float32),
            np.asarray(expected, np.float32),
            rtol=tol,
            atol=tol,
        )


@pytest.mark.parametrize("shape", SHAPES)
def test_allreduce_average(shape):
    x = stacked(shape, jnp.float32)
    out = run_spmd(lambda v: hvd.allreduce(v[0], op=hvd.Average)[None], x)
    expected = jnp.mean(x, axis=0)
    np.testing.assert_allclose(out[0], expected, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(out[N - 1], expected, rtol=1e-5, atol=1e-5)


def test_allreduce_min_max():
    x = stacked((5,), jnp.float32)
    out_min = run_spmd(lambda v: hvd.allreduce(v[0], op=hvd.Min)[None], x)
    out_max = run_spmd(lambda v: hvd.allreduce(v[0], op=hvd.Max)[None], x)
    np.testing.assert_allclose(out_min[0], jnp.min(x, axis=0))
    np.testing.assert_allclose(out_max[3], jnp.max(x, axis=0))


def test_allreduce_prescale_postscale():
    x = stacked((6,), jnp.float32)
    out = run_spmd(
        lambda v: hvd.allreduce(
            v[0], op=hvd.Sum, prescale_factor=0.5, postscale_factor=4.0
        )[None],
        x,
    )
    np.testing.assert_allclose(
        out[0], jnp.sum(x, axis=0) * 2.0, rtol=1e-5
    )


def test_allreduce_pytree():
    a = stacked((3,), jnp.float32, seed=1)
    b = stacked((2, 2), jnp.float32, seed=2)

    def fn(av, bv):
        res = hvd.allreduce({"a": av[0], "b": bv[0]}, op=hvd.Sum)
        return res["a"][None], res["b"][None]

    mesh = hvd.mesh("flat")
    oa, ob = shard_map(
        fn,
        mesh=mesh,
        in_specs=(P(hvd.DP_AXIS), P(hvd.DP_AXIS)),
        out_specs=(P(hvd.DP_AXIS), P(hvd.DP_AXIS)),
    )(a, b)
    np.testing.assert_allclose(oa[0], jnp.sum(a, axis=0), rtol=1e-5)
    np.testing.assert_allclose(ob[0], jnp.sum(b, axis=0), rtol=1e-5)


def test_grouped_allreduce_matches_individual():
    xs = [stacked((4,), jnp.float32, seed=i) for i in range(3)]
    xs.append(stacked((2, 3), jnp.bfloat16, seed=9))

    def fn(*vs):
        outs = hvd.grouped_allreduce([v[0] for v in vs], op=hvd.Sum)
        return tuple(o[None] for o in outs)

    mesh = hvd.mesh("flat")
    outs = shard_map(
        fn,
        mesh=mesh,
        in_specs=tuple(P(hvd.DP_AXIS) for _ in xs),
        out_specs=tuple(P(hvd.DP_AXIS) for _ in xs),
    )(*xs)
    for x, o in zip(xs, outs):
        np.testing.assert_allclose(
            np.asarray(o[0], np.float32),
            np.asarray(jnp.sum(x.astype(jnp.float32), axis=0), np.float32),
            rtol=1e-2,
        )


def test_grouped_allreduce_threshold_chunks():
    """A pytree larger than the fusion threshold is reduced in multiple
    <=threshold bins (reference FuseResponses 64 MB cap,
    controller.cc:640-761) — count psums in the jaxpr — with numerics
    identical to the unchunked result."""
    # 3 leaves x 1000 f32 = 4000 B each; threshold 9000 B -> leaf 1+2
    # fuse (8000 B), leaf 3 opens a new bin -> 2 psums (vs 1 uncapped).
    xs = [stacked((1000,), jnp.float32, seed=i) for i in range(3)]

    def count_psums(threshold):
        def fn(*vs):
            outs = hvd.grouped_allreduce(
                [v[0] for v in vs], op=hvd.Sum,
                fusion_threshold_bytes=threshold,
            )
            return tuple(o[None] for o in outs)

        mesh = hvd.mesh("flat")
        wrapped = shard_map(
            fn,
            mesh=mesh,
            in_specs=tuple(P(hvd.DP_AXIS) for _ in xs),
            out_specs=tuple(P(hvd.DP_AXIS) for _ in xs),
        )
        jaxpr = str(jax.make_jaxpr(wrapped)(*xs))
        return jaxpr.count("psum"), wrapped

    n_unchunked, _ = count_psums(1 << 30)
    n_chunked, wrapped = count_psums(9000)
    assert n_unchunked == 1
    assert n_chunked == 2
    outs = wrapped(*xs)
    for x, o in zip(xs, outs):
        np.testing.assert_allclose(
            o[0], jnp.sum(x, axis=0), rtol=1e-5
        )


def test_grouped_allreduce_oversize_leaf_own_bin():
    """A single leaf above the threshold is not split and still reduces
    correctly alongside small leaves."""
    xs = [stacked((64,), jnp.float32, seed=0),
          stacked((5000,), jnp.float32, seed=1)]

    def fn(*vs):
        outs = hvd.grouped_allreduce(
            [v[0] for v in vs], op=hvd.Sum, fusion_threshold_bytes=1024
        )
        return tuple(o[None] for o in outs)

    mesh = hvd.mesh("flat")
    outs = shard_map(
        fn,
        mesh=mesh,
        in_specs=tuple(P(hvd.DP_AXIS) for _ in xs),
        out_specs=tuple(P(hvd.DP_AXIS) for _ in xs),
    )(*xs)
    for x, o in zip(xs, outs):
        np.testing.assert_allclose(o[0], jnp.sum(x, axis=0), rtol=1e-5)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.int32])
def test_allgather(dtype):
    x = stacked((3, 2), dtype)
    out = run_spmd(lambda v: hvd.allgather(v[0])[None], x)
    expected = x.reshape(N * 3, 2)
    for r in (0, 5):
        np.testing.assert_allclose(
            np.asarray(out[r], np.float32), np.asarray(expected, np.float32)
        )


@pytest.mark.parametrize("root", [0, 3, 7])
def test_broadcast(root):
    x = stacked((4,), jnp.float32)
    out = run_spmd(
        lambda v: hvd.broadcast(v[0], root_rank=root)[None], x
    )
    for r in range(N):
        np.testing.assert_allclose(out[r], x[root])


def test_alltoall():
    x = stacked((N, 2), jnp.float32)  # per-rank (8, 2): one row per peer
    out = run_spmd(lambda v: hvd.alltoall(v[0])[None], x)
    # rank r's output row j == rank j's input row r
    for r in (0, 4):
        for j in range(N):
            np.testing.assert_allclose(out[r][j], x[j][r])


def test_reducescatter():
    x = stacked((N * 2, 3), jnp.float32)
    out = run_spmd(lambda v: hvd.reducescatter(v[0], op=hvd.Sum)[None], x)
    total = jnp.sum(x, axis=0)  # (16, 3)
    for r in (0, 7):
        np.testing.assert_allclose(
            out[r], total[r * 2 : (r + 1) * 2], rtol=1e-5
        )


# ---------------------------------------------------------------------------
# autodiff rules (reference: grad tests in test/test_torch.py for the
# autograd Functions; rules at horovod/torch/mpi_ops.py:158-171,289-307,371-385)
# ---------------------------------------------------------------------------


def grad_spmd(loss_fn, x):
    mesh = hvd.mesh("flat")

    def per_rank(v):
        g = jax.grad(loss_fn)(v[0])
        return g[None]

    return shard_map(
        per_rank, mesh=mesh, in_specs=(P(hvd.DP_AXIS),), out_specs=P(hvd.DP_AXIS)
    )(x)


def test_allreduce_grad_average():
    x = stacked((3,), jnp.float32)
    # loss = sum(allreduce_avg(x)); Horovod rule: grad = allreduce_avg(ones)
    g = grad_spmd(lambda v: jnp.sum(hvd.allreduce(v, op=hvd.Average)), x)
    np.testing.assert_allclose(g[0], jnp.ones(3), rtol=1e-5)


def test_allreduce_grad_sum():
    x = stacked((3,), jnp.float32)
    g = grad_spmd(lambda v: jnp.sum(hvd.allreduce(v, op=hvd.Sum)), x)
    # backward = allreduce_sum(ones) = N * ones
    np.testing.assert_allclose(g[0], np.full(3, float(N)), rtol=1e-5)


def test_allgather_grad():
    x = stacked((2,), jnp.float32)
    # loss weights each gathered row by (global_row_index + 1)
    w = jnp.arange(1.0, N * 2 + 1)

    def loss(v):
        return jnp.sum(hvd.allgather(v) * w)

    g = grad_spmd(loss, x)
    # Rule: reduce (sum over ranks -> w unchanged since each rank same loss
    # weight), then each rank keeps its own slice => grad on rank r is
    # N * w[2r:2r+2]  (cotangent w summed across the N identical copies).
    for r in (0, 3):
        np.testing.assert_allclose(
            g[r], N * np.asarray(w[2 * r : 2 * r + 2]), rtol=1e-5
        )


def test_broadcast_grad():
    x = stacked((3,), jnp.float32)
    root = 2

    def loss(v):
        return jnp.sum(hvd.broadcast(v, root_rank=root) * 3.0)

    g = grad_spmd(loss, x)
    # Rule: cotangent (3.0) summed across ranks lands on root; zero elsewhere.
    np.testing.assert_allclose(g[root], np.full(3, 3.0 * N), rtol=1e-5)
    np.testing.assert_allclose(g[0], np.zeros(3))
    np.testing.assert_allclose(g[7], np.zeros(3))


def test_jit_compiles_single_collective():
    """The whole point of the jit path: collectives trace + compile."""
    mesh = hvd.mesh("flat")
    x = stacked((16,), jnp.float32)

    @functools.partial(
        jax.jit,
    )
    def step(v):
        return shard_map(
            lambda u: hvd.allreduce(u[0], op=hvd.Average)[None],
            mesh=mesh,
            in_specs=(P(hvd.DP_AXIS),),
            out_specs=P(hvd.DP_AXIS),
        )(v)

    out = step(x)
    np.testing.assert_allclose(out[0], jnp.mean(x, axis=0), rtol=1e-5)
