"""horovod.mxnet-compatible interop frontend (reference surface:
test/test_mxnet.py — op signatures, DistributedOptimizer grad allreduce,
DistributedTrainer, broadcast_parameters with deferred-init hook).

Upstream MXNet is EOL and not installed in this image, so the wrapper
logic runs against a duck-typed `mxnet` stand-in injected into
sys.modules: minimal NDArray-on-numpy, optimizer/gluon base classes, and
the DeferredInitializationError protocol.  This is the logic half of the
reference's logic-vs-integration test split; the integration half needs a
real mxnet wheel, which the frontend picks up automatically (lazy import).
"""

from __future__ import annotations

import sys
import types

import numpy as np
import pytest

import horovod_tpu.run as hvdrun


# ---------------------------------------------------------------------------
# duck-typed mxnet stand-in
# ---------------------------------------------------------------------------


class FakeNDArray:
    """Just enough NDArray: asnumpy(), slice-assign, shape/dtype, context."""

    def __init__(self, value, ctx="fake_cpu(0)"):
        self._a = np.array(value)
        self.context = ctx

    def asnumpy(self):
        return self._a.copy()

    def __setitem__(self, key, value):
        if isinstance(value, FakeNDArray):
            value = value._a
        self._a[key] = value

    def __getitem__(self, key):
        return FakeNDArray(self._a[key])

    @property
    def shape(self):
        return self._a.shape

    @property
    def dtype(self):
        return self._a.dtype


def install_fake_mxnet():
    """Builds the `mxnet` module shape the frontend needs and registers it."""
    mx = types.ModuleType("mxnet")

    nd = types.ModuleType("mxnet.nd")
    nd.array = lambda value, dtype=None, ctx="fake_cpu(0)": FakeNDArray(
        np.asarray(value, dtype=dtype), ctx=ctx
    )
    mx.nd = nd

    optimizer = types.ModuleType("mxnet.optimizer")

    class Optimizer:
        pass

    optimizer.Optimizer = Optimizer
    mx.optimizer = optimizer

    gluon = types.ModuleType("mxnet.gluon")

    class Trainer:
        def __init__(self, params, optimizer, optimizer_params=None,
                     kvstore=None):
            assert kvstore is None  # the frontend must bypass kvstore
            self._params = list(params.values()) if hasattr(params, "values") \
                else list(params)
            self._optimizer = optimizer
            self._scale = 1.0

    gluon.Trainer = Trainer

    parameter = types.ModuleType("mxnet.gluon.parameter")

    class DeferredInitializationError(Exception):
        pass

    parameter.DeferredInitializationError = DeferredInitializationError
    gluon.parameter = parameter
    mx.gluon = gluon

    sys.modules["mxnet"] = mx
    sys.modules["mxnet.nd"] = nd
    sys.modules["mxnet.optimizer"] = optimizer
    sys.modules["mxnet.gluon"] = gluon
    sys.modules["mxnet.gluon.parameter"] = parameter
    return mx


@pytest.fixture(autouse=True)
def _fake_mx():
    had = {k: sys.modules.get(k) for k in list(sys.modules)
           if k == "mxnet" or k.startswith("mxnet.")}
    install_fake_mxnet()
    yield
    for k in list(sys.modules):
        if k == "mxnet" or k.startswith("mxnet."):
            del sys.modules[k]
    sys.modules.update({k: v for k, v in had.items() if v is not None})


# ---------------------------------------------------------------------------
# single-process semantics
# ---------------------------------------------------------------------------


def test_allreduce_identity_and_inplace():
    import horovod_tpu.interop.mxnet as hmx

    hmx.init()
    x = FakeNDArray(np.arange(6, dtype=np.float32).reshape(2, 3),
                    ctx="fake_gpu(1)")
    out = hmx.allreduce(x)
    assert isinstance(out, FakeNDArray)
    np.testing.assert_allclose(out.asnumpy(), x.asnumpy())
    # out-of-place results keep the source's context (reference allocates
    # with ctx=tensor.context) instead of falling back to the default ctx
    assert out.context == "fake_gpu(1)"

    y = FakeNDArray(np.ones(4, np.float32))
    ret = hmx.allreduce_(y, average=False)
    assert ret is y
    np.testing.assert_allclose(y.asnumpy(), np.ones(4))


def test_broadcast_and_allgather_single():
    import horovod_tpu.interop.mxnet as hmx

    hmx.init()
    x = FakeNDArray(np.full((2, 2), 3.0, np.float32))
    out = hmx.broadcast(x, root_rank=0)
    np.testing.assert_allclose(out.asnumpy(), x.asnumpy())
    g = hmx.allgather(x)
    np.testing.assert_allclose(g.asnumpy(), x.asnumpy())


def test_distributed_optimizer_rescales_and_updates():
    import horovod_tpu.interop.mxnet as hmx

    hmx.init()

    class SGD(sys.modules["mxnet"].optimizer.Optimizer):
        def __init__(self):
            self.rescale_grad = 1.0
            self.updates = []

        def update(self, index, weight, grad, state):
            self.updates.append((index, weight, grad, state))

        def update_multi_precision(self, index, weight, grad, state):
            self.update(index, weight, grad, state)

        def create_state_multi_precision(self, index, weight):
            return None

        def set_learning_rate(self, lr):
            self.lr = lr

    base = SGD()
    opt = hmx.DistributedOptimizer(base)
    # average folded into rescale_grad (reference mxnet/__init__.py:43-46)
    assert base.rescale_grad == pytest.approx(1.0 / hmx.size())
    g = FakeNDArray(np.ones(3, np.float32))
    w = FakeNDArray(np.zeros(3, np.float32))
    opt.update(0, w, g, None)
    assert len(base.updates) == 1
    opt.update_multi_precision([1, 2], [w, w], [g, g], [None, None])
    assert len(base.updates) == 2
    opt.set_learning_rate(0.5)  # delegation
    assert base.lr == 0.5


def test_distributed_trainer_scale_and_unwrap():
    import horovod_tpu.interop.mxnet as hmx

    hmx.init()

    class SGD(sys.modules["mxnet"].optimizer.Optimizer):
        def __init__(self):
            self.rescale_grad = 1.0

    base = SGD()
    wrapped = hmx.DistributedOptimizer(base)
    with pytest.warns(UserWarning, match="unwrapped"):
        trainer = hmx.DistributedTrainer({}, wrapped)
    assert trainer._optimizer is base
    assert trainer._scale == pytest.approx(1.0 / hmx.size())


def test_deferred_init_hook_broadcasts_after_init():
    """_append_broadcast_init wraps a gluon parameter's _init_impl so the
    post-initialization value is broadcast (reference
    mxnet/__init__.py:111-118)."""
    import types as types_mod

    import horovod_tpu.interop.mxnet as hmx

    hmx.init()
    calls = []

    class Param:
        name = "w1"

        def __init__(self):
            self._value = None

        def data(self):
            return self._value

        def _init_impl(self, *a, **kw):
            calls.append("init")
            self._value = FakeNDArray(np.zeros(2, np.float32))

    p = Param()
    p._init_impl = types_mod.MethodType(
        hmx._append_broadcast_init(p, root_rank=0), p
    )
    p._init_impl()
    assert calls == ["init"]
    np.testing.assert_allclose(p.data().asnumpy(), np.zeros(2))


# ---------------------------------------------------------------------------
# real 2-process semantics under the launcher (SURVEY §4 strategy)
# ---------------------------------------------------------------------------


def _mx_2proc_fn():
    # FakeNDArray / install_fake_mxnet resolve from this module's globals —
    # run() pickles the whole test module by value, so no import is needed
    # (and `tests` is not an importable package in the workers).
    import sys

    import numpy as np

    install_fake_mxnet()
    import horovod_tpu.interop.mxnet as hmx

    hmx.init()
    r = hmx.rank()
    out = {}

    x = FakeNDArray(np.full(3, float(r + 1), np.float32))
    hmx.allreduce_(x, average=False, name="ar")
    out["allreduce_"] = x.asnumpy().tolist()

    b = FakeNDArray(np.full(2, float(r), np.float32))
    hmx.broadcast_(b, root_rank=1, name="bc")
    out["broadcast_"] = b.asnumpy().tolist()

    # DistributedOptimizer end-to-end: grads allreduced before update
    class SGD(sys.modules["mxnet"].optimizer.Optimizer):
        def __init__(self):
            self.rescale_grad = 1.0
            self.seen = None

        def update(self, index, weight, grad, state):
            self.seen = grad.asnumpy() * self.rescale_grad

        def update_multi_precision(self, index, weight, grad, state):
            self.update(index, weight, grad, state)

    base = SGD()
    opt = hmx.DistributedOptimizer(base)
    g = FakeNDArray(np.full(2, float(r + 1), np.float32))
    w = FakeNDArray(np.zeros(2, np.float32))
    opt.update(7, w, g, None)
    out["effective_grad"] = base.seen.tolist()

    # broadcast_parameters across ranks: rank 1 receives rank 0's values
    params = {"w": FakeNDArray(np.full(2, float(10 * (r + 1)), np.float32))}
    hmx.broadcast_parameters(params, root_rank=0)
    out["param_after_bcast"] = params["w"].asnumpy().tolist()

    # gluon ParameterDict branch incl. the deferred-init broadcast hook:
    # the deferred parameter broadcasts as soon as it initializes.
    import types as types_mod  # noqa: F401

    deferred_error = sys.modules[
        "mxnet"
    ].gluon.parameter.DeferredInitializationError

    class Param:
        def __init__(self, name, value=None):
            self.name = name
            self._value = value

        def data(self):
            if self._value is None:
                raise deferred_error()
            return self._value

        def list_grad(self):
            return []

        def _init_impl(self, *a, **kw):
            self._value = FakeNDArray(
                np.full(2, float(100 * (hmx.rank() + 1)), np.float32)
            )

    class ParamDict:
        def __init__(self, p):
            self._p = p

        def items(self):
            return self._p.items()

    ready = Param("p0", FakeNDArray(np.full(2, float(r), np.float32)))
    deferred = Param("p1")
    hmx.broadcast_parameters(ParamDict({"p0": ready, "p1": deferred}))
    out["ready_after_bcast"] = ready.data().asnumpy().tolist()
    deferred._init_impl()  # gluon would call this at first forward
    out["deferred_after_init"] = deferred.data().asnumpy().tolist()

    hmx.shutdown()
    return out


@pytest.mark.multiprocess
def test_mxnet_frontend_two_process(engine_env):
    results = hvdrun.run(_mx_2proc_fn, np=2, use_cpu=True, timeout=240,
                         env=engine_env)
    for res in results:
        assert res["allreduce_"] == [3.0, 3.0, 3.0]  # 1+2
        assert res["broadcast_"] == [1.0, 1.0]  # root 1's value
        # sum(1+2)=3 then rescale 1/2 -> averaged grad 1.5
        assert res["effective_grad"] == [1.5, 1.5]
        assert res["param_after_bcast"] == [10.0, 10.0]
        assert res["ready_after_bcast"] == [0.0, 0.0]  # root 0's value
        # deferred param broadcast fires inside the init hook: both ranks
        # end with rank 0's post-init value
        assert res["deferred_after_init"] == [100.0, 100.0]
