"""Launcher unit tests — in-process, no processes spawned (the strategy of
reference test/test_run.py: arg parsing, config layering, allocation, env
assembly asserted directly)."""

import argparse
import os
import textwrap

import pytest

from horovod_tpu.run.allocate import (
    HostSlots,
    SlotInfo,
    allocate,
    parse_hostfile,
    parse_hosts,
)
from horovod_tpu.run.config_parser import set_env_from_args
from horovod_tpu.run.runner import build_slot_env, check_build, parse_args


def test_parse_hosts():
    assert parse_hosts("h1:2,h2:4") == [HostSlots("h1", 2), HostSlots("h2", 4)]
    assert parse_hosts("solo") == [HostSlots("solo", 1)]
    with pytest.raises(ValueError):
        parse_hosts("")
    with pytest.raises(ValueError):
        parse_hosts("h1:x")


def test_parse_hostfile(tmp_path):
    p = tmp_path / "hosts"
    p.write_text(
        textwrap.dedent(
            """
            # comment
            node1 slots=2
            node2   slots=1
            node3
            """
        )
    )
    assert parse_hostfile(str(p)) == [
        HostSlots("node1", 2),
        HostSlots("node2", 1),
        HostSlots("node3", 1),
    ]


def test_allocate_ranks_and_cross_ranks():
    """reference gloo_run.py:54-112: rank in host order, local_rank within
    host, cross_rank = host index for that local slot."""
    slots = allocate([HostSlots("a", 2), HostSlots("b", 2)], 4)
    assert [s.rank for s in slots] == [0, 1, 2, 3]
    assert [(s.hostname, s.local_rank) for s in slots] == [
        ("a", 0), ("a", 1), ("b", 0), ("b", 1),
    ]
    assert [(s.cross_rank, s.cross_size) for s in slots] == [
        (0, 2), (0, 2), (1, 2), (1, 2),
    ]


def test_allocate_partial_last_host():
    slots = allocate([HostSlots("a", 4), HostSlots("b", 4)], 5)
    assert len(slots) == 5
    assert slots[-1].hostname == "b" and slots[-1].local_size == 1


def test_allocate_heterogeneous_cross_ranks():
    """cross_rank must index within the set of hosts that HAVE that local
    slot, not the global host index (a:1,b:2 -> b's local_rank-1 slot is
    alone in its cross communicator: cross_rank 0 of size 1)."""
    slots = allocate([HostSlots("a", 1), HostSlots("b", 2)], 3)
    by = {(s.hostname, s.local_rank): s for s in slots}
    assert by[("b", 1)].cross_rank == 0
    assert by[("b", 1)].cross_size == 1
    assert by[("a", 0)].cross_rank == 0 and by[("a", 0)].cross_size == 2
    assert by[("b", 0)].cross_rank == 1 and by[("b", 0)].cross_size == 2
    for s in slots:
        assert 0 <= s.cross_rank < s.cross_size


def test_explicit_zero_values_reach_env():
    """0 is a legal explicit knob value and must not be dropped
    (0 == False in python)."""
    args = parse_args(["-np", "1", "--fusion-threshold-mb", "0", "python", "x"])
    env = {}
    set_env_from_args(env, args)
    assert env["HVDTPU_FUSION_THRESHOLD"] == "0"


def test_allocate_overflow_raises():
    with pytest.raises(ValueError, match="only 2 slots"):
        allocate([HostSlots("a", 2)], 3)


def test_parse_args_knobs_to_env():
    args = parse_args(
        [
            "-np", "2",
            "--fusion-threshold-mb", "32",
            "--cycle-time-ms", "3.5",
            "--timeline-filename", "/tmp/t.json",
            "--no-stall-check",
            "--log-level", "debug",
            "python", "train.py",
        ]
    )
    assert args.np == 2
    assert args.command == ["python", "train.py"]
    env = {}
    set_env_from_args(env, args)
    assert env["HVDTPU_FUSION_THRESHOLD"] == str(32 * 1024 * 1024)
    assert env["HVDTPU_CYCLE_TIME"] == "3.5"
    assert env["HVDTPU_TIMELINE"] == "/tmp/t.json"
    assert env["HVDTPU_STALL_CHECK_DISABLE"] == "1"
    assert env["HVDTPU_LOG_LEVEL"] == "debug"


def test_parse_args_obs_knobs():
    args = parse_args(
        [
            "-np", "2",
            "--metrics-dump", "/tmp/metrics/",
            "--stats-summary",
            "--progress-timeout-secs", "120",
            "--progress-grace-secs", "900",
            "python", "train.py",
        ]
    )
    env = {}
    set_env_from_args(env, args)
    assert env["HVDTPU_METRICS_DUMP"] == "/tmp/metrics/"
    assert args.stats_summary is True
    # launcher-local policy knobs (not worker env)
    assert args.progress_timeout_secs == 120.0
    assert args.progress_grace_secs == 900.0


def test_parse_args_autotune_knobs_to_env():
    """The full autotune flag surface maps onto the engine env knobs
    (reference runner.py:318-347 autotune argument group)."""
    args = parse_args(
        [
            "-np", "2",
            "--autotune",
            "--autotune-log-file", "/tmp/a.csv",
            "--autotune-warmup-samples", "1",
            "--autotune-steps-per-sample", "2",
            "--autotune-bayes-opt-max-samples", "5",
            "--autotune-gaussian-process-noise", "0.01",
            "python", "train.py",
        ]
    )
    env = {}
    set_env_from_args(env, args)
    assert env["HVDTPU_AUTOTUNE"] == "1"
    assert env["HVDTPU_AUTOTUNE_LOG"] == "/tmp/a.csv"
    assert env["HVDTPU_AUTOTUNE_WARMUP_SAMPLES"] == "1"
    assert env["HVDTPU_AUTOTUNE_STEPS_PER_SAMPLE"] == "2"
    assert env["HVDTPU_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"] == "5"
    assert env["HVDTPU_AUTOTUNE_GAUSSIAN_PROCESS_NOISE"] == "0.01"


def test_output_filename_captures_per_rank_streams(tmp_path):
    """--output-filename writes each rank's raw stdout/stderr to
    <dir>/rank.<padded>/<stdout|stderr> while still streaming to the
    console (reference gloo_run.py:130-143,204-217)."""
    import sys

    from horovod_tpu.run.runner import launch_job

    out_dir = tmp_path / "logs"
    rcs = launch_job(
        [sys.executable, "-c",
         "import os,sys; r=os.environ['HVDTPU_RANK']; "
         "print('out-rank', r); print('err-rank', r, file=sys.stderr)"],
        2,
        output_filename=str(out_dir),
        job_timeout=60,
    )
    assert rcs == {0: 0, 1: 0}
    for rank in (0, 1):
        rank_dir = out_dir / f"rank.{rank}"
        assert (rank_dir / "stdout").read_text() == f"out-rank {rank}\n"
        assert (rank_dir / "stderr").read_text() == f"err-rank {rank}\n"


def test_config_file_layering(tmp_path):
    """Explicit CLI flags beat the config file; file beats defaults
    (reference runner.py:446-450, test_run.py:168-226)."""
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(
        textwrap.dedent(
            """
            params:
              fusion-threshold-mb: 16
              cycle-time-ms: 2
            timeline:
              filename: /from/file.json
            """
        )
    )
    args = parse_args(
        [
            "-np", "2",
            "--config-file", str(cfg),
            "--cycle-time-ms", "9",  # explicit: must win over file's 2
            "python", "x.py",
        ]
    )
    assert args.fusion_threshold_mb == 16  # from file
    assert args.cycle_time_ms == 9  # CLI wins
    assert args.timeline_filename == "/from/file.json"


def test_build_slot_env():
    slot = SlotInfo("h", 3, 8, 1, 4, 0, 2)
    env = build_slot_env(slot, "10.0.0.1:9999", {"PATH": "/bin"})
    assert env["HVDTPU_RANK"] == "3"
    assert env["HVDTPU_SIZE"] == "8"
    assert env["HVDTPU_LOCAL_RANK"] == "1"
    assert env["HVDTPU_LOCAL_SIZE"] == "4"
    assert env["HVDTPU_CROSS_RANK"] == "0"
    assert env["HVDTPU_CROSS_SIZE"] == "2"
    assert env["HVDTPU_COORDINATOR"] == "10.0.0.1:9999"
    assert env["PATH"] == "/bin"


def test_check_build_reports_capabilities():
    report = check_build()
    assert "XLA collectives" in report
    assert "eager per-op engine" in report


def test_kvstore_roundtrip():
    from horovod_tpu.run.rendezvous import KVStoreClient, KVStoreServer

    server = KVStoreServer()
    port = server.start()
    try:
        client = KVStoreClient(f"127.0.0.1:{port}", secret=server.secret)
        assert client.get("s", "missing") is None
        client.put("s", "k", b"payload")
        assert client.get("s", "k") == b"payload"
        assert client.wait("s", "k", timeout=1) == b"payload"
    finally:
        server.stop()


def test_kvstore_rejects_unsigned_writes():
    """The KV store carries pickles; an unauthenticated write would be
    remote code execution (reference signs messages, run/common/util/
    secret.py)."""
    from horovod_tpu.run.rendezvous import KVStoreClient, KVStoreServer

    server = KVStoreServer()
    port = server.start()
    try:
        attacker = KVStoreClient(f"127.0.0.1:{port}", secret="wrong")
        with pytest.raises(PermissionError, match="rejected"):
            attacker.put("s", "k", b"evil")
        good = KVStoreClient(f"127.0.0.1:{port}", secret=server.secret)
        assert good.get("s", "k") is None  # nothing was stored
    finally:
        server.stop()


def test_kvstore_transport_error_names_address():
    from horovod_tpu.run.rendezvous import KVStoreClient

    client = KVStoreClient("127.0.0.1:1", secret="x")  # nothing listens
    with pytest.raises(ConnectionError, match="127.0.0.1:1"):
        client.get("s", "k")
