"""Differential engine fuzz: a seeded random op schedule must produce
IDENTICAL results on the Python and native engines.

The two engines are one protocol with two implementations (SURVEY §2.1 ≙
the reference's {mpi, gloo} controller/backend cross); the CI smoke matrix
already shows equal training losses, and this test pins the equivalence at
the op level across a randomized mix of collectives, dtypes, shapes, and
roots.  Disagreement = a bug in one engine's data plane or negotiation.
"""

import numpy as np
import pytest

import horovod_tpu.run as hvdrun

pytestmark = [pytest.mark.multiprocess, pytest.mark.full]


def _schedule(seed: int, steps: int):
    """Deterministic op schedule — identical on every rank (names, ops,
    shapes, dtypes must agree; payloads are rank-dependent)."""
    rng = np.random.RandomState(seed)
    ops = []
    for i in range(steps):
        kind = rng.choice(["allreduce", "allgather", "broadcast", "alltoall",
                           "reducescatter"])
        dtype = rng.choice(["float32", "float64", "int32", "bfloat16"])
        dim = int(rng.randint(1, 4))
        shape = tuple(int(rng.randint(1, 4)) for _ in range(dim))
        red = rng.choice(["Sum", "Average", "Min", "Max", "Adasum"])
        if red == "Adasum" and dtype.startswith("int"):
            red = "Sum"
        if dtype == "bfloat16" and red == "Adasum":
            red = "Average"
        root = int(rng.randint(0, 2))
        ops.append((kind, dtype, shape, str(red), root, i))
    return ops


def _fuzz_fn(seed, steps):
    import ml_dtypes
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    r, n = hvd.rank(), hvd.size()
    results = []
    for kind, dtype, shape, red, root, i in _schedule(seed, steps):
        np_dtype = (
            np.dtype(ml_dtypes.bfloat16) if dtype == "bfloat16"
            else np.dtype(dtype)
        )
        data = (
            np.arange(int(np.prod(shape)), dtype=np.float64)
            .reshape(shape) % 5 + r + 1
        ).astype(np_dtype)
        name = f"fuzz.{i}"
        if kind == "allreduce":
            out = hvd.allreduce(data, op=getattr(hvd, red), name=name)
        elif kind == "allgather":
            # ragged: rank contributes r+1 leading rows
            ragged = np.concatenate([data] * (r + 1), axis=0)
            out = hvd.allgather(ragged, name=name)
        elif kind == "broadcast":
            out = hvd.broadcast(data, root_rank=root, name=name)
        elif kind == "reducescatter":
            # Sum/Average only (the op's contract); ints stay exact on Sum
            rs_red = "Average" if (red == "Average"
                                   and not dtype.startswith("int")) else "Sum"
            out = hvd.reducescatter(data, op=getattr(hvd, rs_red), name=name)
        else:  # alltoall: dim0 must divide world
            flat = np.concatenate([data.reshape(-1)] * n)
            out = hvd.alltoall(flat, name=name)
        results.append(np.asarray(out).astype(np.float64).tolist())
    hvd.shutdown()
    return results


def test_engines_agree_on_random_schedule(tmp_path):
    seed, steps = 1234, 30
    per_engine = {}
    for engine in ("python", "native"):
        from horovod_tpu.runtime.native import native_available

        if engine == "native" and not native_available():
            pytest.skip("native library not built (make -C cpp)")
        per_engine[engine] = hvdrun.run(
            _fuzz_fn, (seed, steps), np=2, use_cpu=True, timeout=400,
            env={"HVDTPU_EAGER_ENGINE": engine},
        )
    for rank in (0, 1):
        for i, (a, b) in enumerate(
            zip(per_engine["python"][rank], per_engine["native"][rank])
        ):
            np.testing.assert_allclose(
                a, b, rtol=1e-6, atol=1e-9,
                err_msg=f"rank {rank} op {i}: engines disagree",
            )
