"""True multi-process integration tests: real 2-process CPU worlds through
the launcher, exercising the eager engine's negotiation/data path across
process boundaries.

This is the reference CI's central trick (SURVEY.md §4: pytest under
`mpirun -np 2 -H localhost:2`) inverted: instead of running the test file
under the launcher, the test calls horovod_tpu.run.run(fn, np=2), the
in-process equivalent the reference covers in test_interactiverun.py."""

import numpy as np
import pytest

import horovod_tpu.run as hvdrun

pytestmark = pytest.mark.multiprocess


@pytest.fixture(params=["python", "native"])
def engine_env(request):
    """Run each cross-process test under BOTH eager engines: the pure-Python
    one (runtime/engine.py) and the native C++ one (cpp/hvdtpu via
    runtime/native.py) — same tests, same assertions, mirroring how the
    reference CI crosses its {mpi, gloo} backends (SURVEY.md §4)."""
    if request.param == "native":
        from horovod_tpu.runtime.native import native_available

        if not native_available():
            pytest.skip("native library not built (make -C cpp)")
    return {"HVDTPU_EAGER_ENGINE": request.param}


def _world_fn():
    import jax
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    return {
        "rank": hvd.rank(),
        "size": hvd.size(),
        "procs": jax.process_count(),
        "devices": jax.device_count(),
    }


def test_run_api_two_process_world(engine_env):
    results = hvdrun.run(_world_fn, np=2, use_cpu=True, timeout=180,
                         env=engine_env)
    assert [r["rank"] for r in results] == [0, 1]
    assert all(r["size"] == 2 for r in results)
    assert all(r["procs"] == 2 for r in results)


def _eager_ops_fn():
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    r = hvd.rank()

    out = {}
    # allreduce: sum of per-rank tensors
    x = np.full(4, float(r + 1), np.float32)
    out["allreduce_sum"] = hvd.allreduce(x, op=hvd.Sum).tolist()
    out["allreduce_avg"] = hvd.allreduce(x, op=hvd.Average).tolist()
    # fused pair in one cycle: enqueue two async then synchronize
    h1 = hvd.allreduce_async(np.ones(2, np.float32), op=hvd.Sum, name="f1")
    h2 = hvd.allreduce_async(np.full(3, 2.0, np.float32), op=hvd.Sum, name="f2")
    out["fused"] = [hvd.synchronize(h1).tolist(), hvd.synchronize(h2).tolist()]
    # ragged allgather: rank r contributes r+1 rows
    g = np.full((r + 1, 2), float(r), np.float32)
    out["allgather"] = hvd.allgather(g).tolist()
    # broadcast from rank 1
    b = np.asarray([100.0 * (r + 1)], np.float32)
    out["broadcast"] = hvd.broadcast(b, root_rank=1).tolist()
    # min/max
    out["min"] = hvd.allreduce(np.asarray([float(r)], np.float32), op=hvd.Min).tolist()
    out["max"] = hvd.allreduce(np.asarray([float(r)], np.float32), op=hvd.Max).tolist()
    hvd.shutdown()
    return out


def test_eager_collectives_across_processes(engine_env):
    results = hvdrun.run(_eager_ops_fn, np=2, use_cpu=True, timeout=180,
                         env=engine_env)
    for r in results:
        assert r["allreduce_sum"] == [3.0] * 4  # 1 + 2
        assert r["allreduce_avg"] == [1.5] * 4
        assert r["fused"][0] == [2.0, 2.0]
        assert r["fused"][1] == [4.0, 4.0, 4.0]
        # ragged allgather: rank0's 1 row of 0s then rank1's 2 rows of 1s
        assert r["allgather"] == [[0.0, 0.0], [1.0, 1.0], [1.0, 1.0]]
        assert r["broadcast"] == [200.0]
        assert r["min"] == [0.0]
        assert r["max"] == [1.0]


def _join_fn():
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    r = hvd.rank()
    # Uneven data: rank 0 has 3 batches, rank 1 has 1 (reference
    # test strategy for join, §3.5)
    n_batches = 3 if r == 0 else 1
    sums = []
    for i in range(n_batches):
        out = hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum, name=f"batch{i}")
        sums.append(out.tolist())
    hvd.join()
    hvd.shutdown()
    return sums


def test_join_uneven_batches(engine_env):
    results = hvdrun.run(_join_fn, np=2, use_cpu=True, timeout=180,
                         env=engine_env)
    # batch 0: both ranks -> 2.0; batches 1-2: only rank 0 (rank 1 joined,
    # contributes zeros) -> 1.0
    assert results[0] == [[2.0, 2.0], [1.0, 1.0], [1.0, 1.0]]
    assert results[1] == [[2.0, 2.0]]


def _mismatch_fn():
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    x = np.ones(4 if hvd.rank() == 0 else 5, np.float32)
    try:
        hvd.allreduce(x, op=hvd.Sum, name="bad")
        return "no error"
    except RuntimeError as e:
        return str(e)
    finally:
        hvd.shutdown()


def test_shape_mismatch_raises_on_all_ranks(engine_env):
    results = hvdrun.run(_mismatch_fn, np=2, use_cpu=True, timeout=180,
                         env=engine_env)
    for msg in results:
        assert "Mismatched shapes" in msg


def _raising_fn():
    raise ValueError("bad learning rate 42")


def test_worker_exception_traceback_surfaces():
    with pytest.raises(RuntimeError, match="bad learning rate 42"):
        hvdrun.run(_raising_fn, np=2, use_cpu=True, timeout=120)


def _broadcast_params_fn():
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    r = hvd.rank()
    params = {"w": np.full((3,), float(r), np.float32),
              "b": {"x": np.full((2,), 10.0 * r, np.float32)}}
    out = hvd.broadcast_parameters(params, root_rank=0)
    obj = hvd.broadcast_object({"epoch": 7} if r == 0 else None, root_rank=0)
    hvd.shutdown()
    return {
        "w": np.asarray(out["w"]).tolist(),
        "x": np.asarray(out["b"]["x"]).tolist(),
        "obj": obj,
    }


def test_broadcast_parameters_across_processes(engine_env):
    results = hvdrun.run(_broadcast_params_fn, np=2, use_cpu=True,
                         timeout=180, env=engine_env)
    for r in results:
        assert r["w"] == [0.0, 0.0, 0.0]
        assert r["x"] == [0.0, 0.0]
        assert r["obj"] == {"epoch": 7}


def _ckpt_fn(ckpt_dir):
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.checkpoint import restore_checkpoint, save_checkpoint

    hvd.init()
    r = hvd.rank()
    # per-rank divergent state; save writes rank 0's copy only
    state = {"w": np.full((3,), float(r + 1), np.float32)}
    save_checkpoint(ckpt_dir, state, step=1)
    # restore with broadcast: every rank must come back with rank 0's values
    out = restore_checkpoint(ckpt_dir, {"w": np.zeros((3,), np.float32)})
    hvd.shutdown()
    return np.asarray(out["w"]).tolist()


def test_checkpoint_rank0_write_broadcast_restore(engine_env, tmp_path):
    ckpt_dir = str(tmp_path / "ckpt")
    results = hvdrun.run(_ckpt_fn, (ckpt_dir,), np=2, use_cpu=True,
                         timeout=180, env=engine_env)
    for r in results:
        assert r == [1.0, 1.0, 1.0]  # rank 0's state everywhere


def _ckpt_nonshared_fn(ckpt_dir):
    import os

    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.checkpoint import restore_checkpoint, save_checkpoint

    hvd.init()
    r = hvd.rank()
    # Simulate a NON-shared filesystem: each rank gets a private directory;
    # only rank 0's ever receives the checkpoint.
    my_dir = os.path.join(ckpt_dir, f"private_{r}")
    state = {"w": np.full((2,), 42.0 if r == 0 else -1.0, np.float32)}
    save_checkpoint(my_dir, state, step=3)
    # step=None: rank 0 resolves "latest" and broadcasts it; rank 1's
    # directory has no checkpoints but must still restore successfully.
    restore_dir = my_dir if r == 0 else os.path.join(ckpt_dir, "nowhere")
    out = restore_checkpoint(restore_dir, {"w": np.zeros((2,), np.float32)})
    hvd.shutdown()
    return np.asarray(out["w"]).tolist()


def test_checkpoint_restore_without_shared_filesystem(engine_env, tmp_path):
    results = hvdrun.run(_ckpt_nonshared_fn, (str(tmp_path),), np=2,
                         use_cpu=True, timeout=180, env=engine_env)
    for r in results:
        assert r == [42.0, 42.0]


def _stall_fn():
    import time

    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    r = hvd.rank()
    t0 = time.monotonic()
    if r == 0:
        # Submit immediately; rank 1 never will -> stall -> shutdown.
        try:
            hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum, name="stalled")
            out = ("no error", 0.0)
        except RuntimeError as e:
            out = (str(e), time.monotonic() - t0)
    else:
        time.sleep(25)  # deliberately never submit (reference test_stall.py)
        out = ("slept", time.monotonic() - t0)
    try:
        hvd.shutdown()
    except Exception:
        pass
    return out


def test_stall_shutdown_aborts_instead_of_hanging():
    """Reference test_stall.py: a rank that never submits triggers the
    stall inspector's warning then coordinated shutdown
    (HOROVOD_STALL_SHUTDOWN_TIME_SECONDS; stall_inspector.cc).

    Native engine only: its background loop starts at init() on every rank
    (own TCP mesh), so rank 1's controller cycles without rank 1 ever
    enqueueing — the precondition for observing the stall."""
    from horovod_tpu.runtime.native import native_available

    if not native_available():
        pytest.skip("native library not built (make -C cpp)")
    env = {
        "HVDTPU_EAGER_ENGINE": "native",
        "HVDTPU_STALL_CHECK_TIME_SECONDS": "2",
        "HVDTPU_STALL_SHUTDOWN_TIME_SECONDS": "5",
    }
    results = hvdrun.run(_stall_fn, np=2, use_cpu=True, timeout=120, env=env)
    msg, t_err = results[0]
    # The pending op fails with the coordinated shutdown error (reference:
    # outstanding callbacks get SHUT_DOWN_ERROR, operations.cc:526-532;
    # the "Stalled tensor ..." detail lands in the rank-0 engine log).
    assert "stall" in msg.lower() or "shut down" in msg.lower()
    # Must be the STALL inspector (fires ~5-7 s in), not rank 1's exit at
    # 25 s — wrong env names would make this pass via the slow path.
    assert t_err < 15, f"stall shutdown should fire ~6s in, got {t_err:.0f}s"


def _torch_interop_fn():
    import numpy as np
    import torch

    import horovod_tpu.interop.torch as hvd

    hvd.init()
    r = hvd.rank()
    out = {}
    out["allreduce"] = hvd.allreduce(
        torch.full((3,), float(r + 1)), op=hvd.Sum
    ).tolist()
    out["allgather"] = hvd.allgather(
        torch.full((r + 1, 2), float(r))
    ).tolist()
    out["broadcast"] = hvd.broadcast(
        torch.tensor([float(10 * (r + 1))]), root_rank=1
    ).tolist()

    # autograd across processes: grad of allreduce is allreduced
    x = torch.ones(2, requires_grad=True)
    y = hvd.allreduce(x, op=hvd.Sum)
    y.backward(torch.full((2,), float(r + 1)))
    out["grad"] = x.grad.tolist()  # sum of [1,2] per-rank grads = 3

    # DistributedOptimizer: ranks start identical, divergent grads are
    # averaged, so weights stay identical after step
    torch.manual_seed(0)
    model = torch.nn.Linear(2, 1, bias=False)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters(),
    )
    loss = (model(torch.ones(1, 2)) * float(r + 1)).sum()
    loss.backward()
    opt.step()
    out["weights"] = model.weight.detach().flatten().tolist()
    hvd.shutdown()
    return out


def test_torch_interop_across_processes(engine_env):
    results = hvdrun.run(_torch_interop_fn, np=2, use_cpu=True,
                         timeout=180, env=engine_env)
    for r in results:
        assert r["allreduce"] == [3.0, 3.0, 3.0]
        assert r["allgather"] == [[0.0, 0.0], [1.0, 1.0], [1.0, 1.0]]
        assert r["broadcast"] == [20.0]
        assert r["grad"] == [3.0, 3.0]
    # weight sync: both ranks identical after averaged update
    assert results[0]["weights"] == results[1]["weights"]


def _tf_interop_fn():
    import numpy as np
    import tensorflow as tf

    import horovod_tpu.interop.tf as hvd

    hvd.init()
    r = hvd.rank()
    out = {}
    out["allreduce"] = hvd.allreduce(
        tf.fill((3,), float(r + 1)), op=hvd.Sum
    ).numpy().tolist()
    out["allgather"] = hvd.allgather(
        tf.fill((r + 1, 2), float(r))
    ).numpy().tolist()
    out["broadcast"] = hvd.broadcast(
        tf.constant([float(10 * (r + 1))]), root_rank=1
    ).numpy().tolist()

    # IndexedSlices across processes: rank r contributes row index r
    slices = tf.IndexedSlices(
        values=tf.constant([[float(r + 1), float(r + 1)]]),
        indices=tf.constant([r], dtype=tf.int64),
        dense_shape=tf.constant([4, 2], dtype=tf.int64),
    )
    red = hvd.allreduce(slices, op=hvd.Sum)
    out["sparse_values"] = red.values.numpy().tolist()
    out["sparse_indices"] = red.indices.numpy().tolist()

    # DistributedGradientTape: divergent per-rank grads are averaged
    v = tf.Variable([2.0])
    with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
        loss = tf.reduce_sum(v * float(r + 1))
    grad = tape.gradient(loss, v)
    out["tape_grad"] = grad.numpy().tolist()  # avg of [1, 2] = 1.5

    # Keras DistributedOptimizer: identical start + averaged grads ->
    # identical weights after the step
    opt = hvd.DistributedOptimizer(tf.keras.optimizers.SGD(0.1))
    w = tf.Variable([[1.0, 1.0]])
    hvd.broadcast_variables([w], root_rank=0)
    with tf.GradientTape() as t2:
        loss2 = tf.reduce_sum(w * float(r + 1))
    g2 = t2.gradient(loss2, w)
    opt.apply_gradients([(g2, w)])
    out["weights"] = w.numpy().flatten().tolist()
    hvd.shutdown()
    return out


def test_tf_interop_across_processes(engine_env):
    pytest.importorskip("tensorflow")
    results = hvdrun.run(_tf_interop_fn, np=2, use_cpu=True,
                         timeout=240, env=engine_env)
    for r in results:
        assert r["allreduce"] == [3.0, 3.0, 3.0]
        assert r["allgather"] == [[0.0, 0.0], [1.0, 1.0], [1.0, 1.0]]
        assert r["broadcast"] == [20.0]
        assert r["sparse_values"] == [[1.0, 1.0], [2.0, 2.0]]
        assert r["sparse_indices"] == [0, 1]
        assert r["tape_grad"] == [1.5]
    # weight sync: both ranks identical after averaged update
    assert results[0]["weights"] == results[1]["weights"]


def _sync_bn_fn():
    import numpy as np
    import torch

    import horovod_tpu.interop.torch as hvd

    hvd.init()
    r = hvd.rank()
    torch.manual_seed(0)
    full = torch.randn(8, 3, 4, 4, dtype=torch.float64)
    x = full[r * 4:(r + 1) * 4].clone().requires_grad_(True)

    sbn = hvd.SyncBatchNorm(3).double()
    out = sbn(x)
    g = torch.ones_like(out)
    out.backward(g)

    # reference: plain BN over the FULL batch on one process
    ref_x = full.clone().requires_grad_(True)
    bn = torch.nn.BatchNorm2d(3).double()
    ref = bn(ref_x)
    ref.backward(torch.ones_like(ref))
    ok_fwd = torch.allclose(out, ref[r * 4:(r + 1) * 4], atol=1e-8)
    ok_bwd = torch.allclose(x.grad, ref_x.grad[r * 4:(r + 1) * 4], atol=1e-8)
    ok_stats = torch.allclose(
        sbn.running_mean, bn.running_mean, atol=1e-8
    ) and torch.allclose(sbn.running_var, bn.running_var, atol=1e-8)

    # momentum=None: cumulative moving average (factor 1/num_batches),
    # matching torch._BatchNorm.forward — NOT a fixed 0.1.
    sbn_n = hvd.SyncBatchNorm(3, momentum=None).double()
    bn_n = torch.nn.BatchNorm2d(3, momentum=None).double()
    for step in range(3):
        batch = torch.randn(
            8, 3, 4, 4, dtype=torch.float64,
            generator=torch.Generator().manual_seed(step),
        )
        sbn_n(batch[r * 4:(r + 1) * 4])
        bn_n(batch)
    ok_cma = torch.allclose(
        sbn_n.running_mean, bn_n.running_mean, atol=1e-8
    ) and torch.allclose(sbn_n.running_var, bn_n.running_var, atol=1e-8)
    hvd.shutdown()
    return {"fwd": bool(ok_fwd), "bwd": bool(ok_bwd),
            "stats": bool(ok_stats), "cma": bool(ok_cma)}


def test_sync_batch_norm_matches_full_batch(engine_env):
    """SyncBatchNorm over rank-split batches == plain BN over the full
    batch (reference test_torch.py sync BN cases)."""
    results = hvdrun.run(_sync_bn_fn, np=2, use_cpu=True, timeout=180,
                         env=engine_env)
    for r in results:
        assert r == {"fwd": True, "bwd": True, "stats": True, "cma": True}


def test_estimator_launcher_backend(tmp_path):
    """Estimator fit through the launcher (≙ Spark-task training,
    horovod/spark/runner.py): 2 worker processes, eager gradient averaging."""
    import numpy as np
    import optax

    from horovod_tpu.checkpoint import LocalStore
    from horovod_tpu.estimator import Estimator
    from horovod_tpu.models.simple import MLP

    rng = np.random.RandomState(0)
    n = 128
    x = np.concatenate([
        rng.randn(n // 2, 2).astype(np.float32) + 2.0,
        rng.randn(n // 2, 2).astype(np.float32) - 2.0,
    ])
    y = np.concatenate([
        np.zeros(n // 2, np.int32), np.ones(n // 2, np.int32)
    ])

    est = Estimator(
        MLP(features=(8,), num_classes=2),
        optax.adam(1e-2),
        batch_size=32,
        epochs=3,
        backend="launcher",
        np_workers=2,
        use_cpu=True,
        store=LocalStore(str(tmp_path)),
        run_id="launcher",
    )
    model = est.fit({"features": x, "label": y})
    assert len(model.history) == 3
    assert model.history[-1]["loss"] < model.history[0]["loss"]
    acc = (model.transform({"features": x})["prediction"] == y).mean()
    assert acc > 0.9
