"""True multi-process integration tests: real 2-process CPU worlds through
the launcher, exercising the eager engine's negotiation/data path across
process boundaries.

This is the reference CI's central trick (SURVEY.md §4: pytest under
`mpirun -np 2 -H localhost:2`) inverted: instead of running the test file
under the launcher, the test calls horovod_tpu.run.run(fn, np=2), the
in-process equivalent the reference covers in test_interactiverun.py."""

import numpy as np
import pytest

import horovod_tpu.run as hvdrun

pytestmark = pytest.mark.multiprocess


# engine_env fixture (python/native cross) lives in tests/conftest.py.


def _world_fn():
    import jax
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    return {
        "rank": hvd.rank(),
        "size": hvd.size(),
        "procs": jax.process_count(),
        "devices": jax.device_count(),
    }


def test_run_api_two_process_world(engine_env):
    results = hvdrun.run(_world_fn, np=2, use_cpu=True, timeout=180,
                         env=engine_env)
    assert [r["rank"] for r in results] == [0, 1]
    assert all(r["size"] == 2 for r in results)
    assert all(r["procs"] == 2 for r in results)


def _eager_ops_fn():
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    r = hvd.rank()

    out = {}
    # allreduce: sum of per-rank tensors
    x = np.full(4, float(r + 1), np.float32)
    out["allreduce_sum"] = hvd.allreduce(x, op=hvd.Sum).tolist()
    out["allreduce_avg"] = hvd.allreduce(x, op=hvd.Average).tolist()
    # fused pair in one cycle: enqueue two async then synchronize
    h1 = hvd.allreduce_async(np.ones(2, np.float32), op=hvd.Sum, name="f1")
    h2 = hvd.allreduce_async(np.full(3, 2.0, np.float32), op=hvd.Sum, name="f2")
    out["fused"] = [hvd.synchronize(h1).tolist(), hvd.synchronize(h2).tolist()]
    # ragged allgather: rank r contributes r+1 rows
    g = np.full((r + 1, 2), float(r), np.float32)
    out["allgather"] = hvd.allgather(g).tolist()
    # broadcast from rank 1
    b = np.asarray([100.0 * (r + 1)], np.float32)
    out["broadcast"] = hvd.broadcast(b, root_rank=1).tolist()
    # min/max
    out["min"] = hvd.allreduce(np.asarray([float(r)], np.float32), op=hvd.Min).tolist()
    out["max"] = hvd.allreduce(np.asarray([float(r)], np.float32), op=hvd.Max).tolist()
    hvd.shutdown()
    return out


def test_eager_collectives_across_processes(engine_env):
    results = hvdrun.run(_eager_ops_fn, np=2, use_cpu=True, timeout=180,
                         env=engine_env)
    for r in results:
        assert r["allreduce_sum"] == [3.0] * 4  # 1 + 2
        assert r["allreduce_avg"] == [1.5] * 4
        assert r["fused"][0] == [2.0, 2.0]
        assert r["fused"][1] == [4.0, 4.0, 4.0]
        # ragged allgather: rank0's 1 row of 0s then rank1's 2 rows of 1s
        assert r["allgather"] == [[0.0, 0.0], [1.0, 1.0], [1.0, 1.0]]
        assert r["broadcast"] == [200.0]
        assert r["min"] == [0.0]
        assert r["max"] == [1.0]


def _join_fn():
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    r = hvd.rank()
    # Uneven data: rank 0 has 3 batches, rank 1 has 1 (reference
    # test strategy for join, §3.5)
    n_batches = 3 if r == 0 else 1
    sums = []
    for i in range(n_batches):
        out = hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum, name=f"batch{i}")
        sums.append(out.tolist())
    hvd.join()
    hvd.shutdown()
    return sums


def test_join_uneven_batches(engine_env):
    results = hvdrun.run(_join_fn, np=2, use_cpu=True, timeout=180,
                         env=engine_env)
    # batch 0: both ranks -> 2.0; batches 1-2: only rank 0 (rank 1 joined,
    # contributes zeros) -> 1.0
    assert results[0] == [[2.0, 2.0], [1.0, 1.0], [1.0, 1.0]]
    assert results[1] == [[2.0, 2.0]]


def _mismatch_fn():
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    x = np.ones(4 if hvd.rank() == 0 else 5, np.float32)
    try:
        hvd.allreduce(x, op=hvd.Sum, name="bad")
        return "no error"
    except RuntimeError as e:
        return str(e)
    finally:
        hvd.shutdown()


def test_shape_mismatch_raises_on_all_ranks(engine_env):
    results = hvdrun.run(_mismatch_fn, np=2, use_cpu=True, timeout=180,
                         env=engine_env)
    for msg in results:
        assert "Mismatched shapes" in msg


def _raising_fn():
    raise ValueError("bad learning rate 42")


def test_worker_exception_traceback_surfaces():
    with pytest.raises(RuntimeError, match="bad learning rate 42"):
        hvdrun.run(_raising_fn, np=2, use_cpu=True, timeout=120)


def _broadcast_params_fn():
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    r = hvd.rank()
    params = {"w": np.full((3,), float(r), np.float32),
              "b": {"x": np.full((2,), 10.0 * r, np.float32)}}
    out = hvd.broadcast_parameters(params, root_rank=0)
    obj = hvd.broadcast_object({"epoch": 7} if r == 0 else None, root_rank=0)
    hvd.shutdown()
    return {
        "w": np.asarray(out["w"]).tolist(),
        "x": np.asarray(out["b"]["x"]).tolist(),
        "obj": obj,
    }


def test_broadcast_parameters_across_processes(engine_env):
    results = hvdrun.run(_broadcast_params_fn, np=2, use_cpu=True,
                         timeout=180, env=engine_env)
    for r in results:
        assert r["w"] == [0.0, 0.0, 0.0]
        assert r["x"] == [0.0, 0.0]
        assert r["obj"] == {"epoch": 7}


def _ckpt_fn(ckpt_dir):
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.checkpoint import restore_checkpoint, save_checkpoint

    hvd.init()
    r = hvd.rank()
    # per-rank divergent state; save writes rank 0's copy only
    state = {"w": np.full((3,), float(r + 1), np.float32)}
    save_checkpoint(ckpt_dir, state, step=1)
    # restore with broadcast: every rank must come back with rank 0's values
    out = restore_checkpoint(ckpt_dir, {"w": np.zeros((3,), np.float32)})
    hvd.shutdown()
    return np.asarray(out["w"]).tolist()


def test_checkpoint_rank0_write_broadcast_restore(engine_env, tmp_path):
    ckpt_dir = str(tmp_path / "ckpt")
    results = hvdrun.run(_ckpt_fn, (ckpt_dir,), np=2, use_cpu=True,
                         timeout=180, env=engine_env)
    for r in results:
        assert r == [1.0, 1.0, 1.0]  # rank 0's state everywhere


def _ckpt_async_fn(ckpt_dir):
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.checkpoint import (
        restore_checkpoint, save_checkpoint_async,
    )

    hvd.init()
    r = hvd.rank()
    state = {"w": np.full((3,), float(r + 1), np.float32)}
    handle = save_checkpoint_async(ckpt_dir, state, step=1)
    # training would continue here; wait() is the commit point + barrier
    handle.wait()
    out = restore_checkpoint(ckpt_dir, {"w": np.zeros((3,), np.float32)})
    hvd.shutdown()
    return np.asarray(out["w"]).tolist()


def test_checkpoint_async_rank0_write_broadcast_restore(engine_env,
                                                        tmp_path):
    ckpt_dir = str(tmp_path / "ckpt_async")
    results = hvdrun.run(_ckpt_async_fn, (ckpt_dir,), np=2, use_cpu=True,
                         timeout=180, env=engine_env)
    for r in results:
        assert r == [1.0, 1.0, 1.0]


def _ckpt_nonshared_fn(ckpt_dir):
    import os

    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.checkpoint import restore_checkpoint, save_checkpoint

    hvd.init()
    r = hvd.rank()
    # Simulate a NON-shared filesystem: each rank gets a private directory;
    # only rank 0's ever receives the checkpoint.
    my_dir = os.path.join(ckpt_dir, f"private_{r}")
    state = {"w": np.full((2,), 42.0 if r == 0 else -1.0, np.float32)}
    save_checkpoint(my_dir, state, step=3)
    # step=None: rank 0 resolves "latest" and broadcasts it; rank 1's
    # directory has no checkpoints but must still restore successfully.
    restore_dir = my_dir if r == 0 else os.path.join(ckpt_dir, "nowhere")
    out = restore_checkpoint(restore_dir, {"w": np.zeros((2,), np.float32)})
    hvd.shutdown()
    return np.asarray(out["w"]).tolist()


def test_checkpoint_restore_without_shared_filesystem(engine_env, tmp_path):
    results = hvdrun.run(_ckpt_nonshared_fn, (str(tmp_path),), np=2,
                         use_cpu=True, timeout=180, env=engine_env)
    for r in results:
        assert r == [42.0, 42.0]


def _stall_fn():
    import time

    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    r = hvd.rank()
    t0 = time.monotonic()
    if r == 0:
        # Submit immediately; rank 1 never will -> stall -> shutdown.
        try:
            hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum, name="stalled")
            out = ("no error", 0.0)
        except RuntimeError as e:
            out = (str(e), time.monotonic() - t0)
    else:
        time.sleep(25)  # deliberately never submit (reference test_stall.py)
        out = ("slept", time.monotonic() - t0)
    try:
        hvd.shutdown()
    except Exception:
        pass
    return out


@pytest.mark.slow  # tier-1 budget triage (ISSUE 15): run by node id in ci/test_matrix.sh slow_multiproc gate
def test_stall_shutdown_aborts_instead_of_hanging():
    """Reference test_stall.py: a rank that never submits triggers the
    stall inspector's warning then coordinated shutdown
    (HOROVOD_STALL_SHUTDOWN_TIME_SECONDS; stall_inspector.cc).

    Native engine only: its background loop starts at init() on every rank
    (own TCP mesh), so rank 1's controller cycles without rank 1 ever
    enqueueing — the precondition for observing the stall."""
    from horovod_tpu.runtime.native import native_available

    if not native_available():
        pytest.skip("native library not built (make -C cpp)")
    env = {
        "HVDTPU_EAGER_ENGINE": "native",
        "HVDTPU_STALL_CHECK_TIME_SECONDS": "2",
        "HVDTPU_STALL_SHUTDOWN_TIME_SECONDS": "5",
    }
    results = hvdrun.run(_stall_fn, np=2, use_cpu=True, timeout=120, env=env)
    msg, t_err = results[0]
    # The pending op fails with the coordinated shutdown error (reference:
    # outstanding callbacks get SHUT_DOWN_ERROR, operations.cc:526-532;
    # the "Stalled tensor ..." detail lands in the rank-0 engine log).
    assert "stall" in msg.lower() or "shut down" in msg.lower()
    # Must be the STALL inspector (fires ~5-7 s in), not rank 1's exit at
    # 25 s — wrong env names would make this pass via the slow path.
    assert t_err < 15, f"stall shutdown should fire ~6s in, got {t_err:.0f}s"


def _torch_interop_fn():
    import numpy as np
    import torch

    import horovod_tpu.interop.torch as hvd

    hvd.init()
    r = hvd.rank()
    out = {}
    out["allreduce"] = hvd.allreduce(
        torch.full((3,), float(r + 1)), op=hvd.Sum
    ).tolist()
    out["allgather"] = hvd.allgather(
        torch.full((r + 1, 2), float(r))
    ).tolist()
    out["broadcast"] = hvd.broadcast(
        torch.tensor([float(10 * (r + 1))]), root_rank=1
    ).tolist()

    # autograd across processes: grad of allreduce is allreduced
    x = torch.ones(2, requires_grad=True)
    y = hvd.allreduce(x, op=hvd.Sum)
    y.backward(torch.full((2,), float(r + 1)))
    out["grad"] = x.grad.tolist()  # sum of [1,2] per-rank grads = 3

    # DistributedOptimizer: ranks start identical, divergent grads are
    # averaged, so weights stay identical after step
    torch.manual_seed(0)
    model = torch.nn.Linear(2, 1, bias=False)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters(),
    )
    loss = (model(torch.ones(1, 2)) * float(r + 1)).sum()
    loss.backward()
    opt.step()
    out["weights"] = model.weight.detach().flatten().tolist()
    hvd.shutdown()
    return out


def test_torch_interop_across_processes(engine_env):
    results = hvdrun.run(_torch_interop_fn, np=2, use_cpu=True,
                         timeout=180, env=engine_env)
    for r in results:
        assert r["allreduce"] == [3.0, 3.0, 3.0]
        assert r["allgather"] == [[0.0, 0.0], [1.0, 1.0], [1.0, 1.0]]
        assert r["broadcast"] == [20.0]
        assert r["grad"] == [3.0, 3.0]
    # weight sync: both ranks identical after averaged update
    assert results[0]["weights"] == results[1]["weights"]


def _fastpath_fn():
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu._engine_registry import get_engine

    hvd.init()
    r = hvd.rank()

    # Repeated same-name workload: cycle 1 negotiates + fills the cache,
    # every later submission must ride the bit-vote fast path.
    last = None
    for i in range(6):
        last = hvd.allreduce(
            np.full(4, float(r + 1 + i), np.float32), op=hvd.Sum, name="grad"
        )
    stats = dict(get_engine().stats)

    # dtype-native data plane: int64 beyond 2^53 round-trips exactly
    # (a float64 wire would quantize to multiples of 1024 at 2^60).
    big = hvd.allreduce(
        np.asarray([2**60 + 3 + r], np.int64), op=hvd.Sum, name="big"
    )

    # bf16 stays bf16 on the wire, accumulates in f32
    import ml_dtypes

    half = hvd.allreduce(
        np.ones(4, ml_dtypes.bfloat16), op=hvd.Sum, name="half"
    )
    bf16_ok = half.dtype == ml_dtypes.bfloat16 and np.all(
        half.astype(np.float32) == 2.0
    )

    # overlapping barriers queue instead of DUPLICATE_NAME
    eng = get_engine()
    b1, b2 = eng.barrier(), eng.barrier()
    b1.result()
    b2.result()

    out = {
        "last": last.tolist(),
        "stats": stats,
        "big": [int(v) for v in big.tolist()],
        "bf16_ok": bool(bf16_ok),
    }
    hvd.shutdown()
    return out


def test_python_engine_steady_state_fast_path():
    """VERDICT r1 #3: second-and-later cycles of a repeated workload
    exchange only cache votes (reference response_cache.cc:468 bitvector
    sync), the data plane is dtype-native (exact int64 > 2^53), and
    barriers queue.  Python engine only — the native engine has its own
    C++ response cache covered by its tests."""
    results = hvdrun.run(_fastpath_fn, np=2, use_cpu=True, timeout=180,
                         env={"HVDTPU_EAGER_ENGINE": "python"})
    for res in results:
        # 1 + 2 + i adjustments: ranks sent (i+1) and (i+2) at step i=5
        assert res["last"] == [13.0] * 4  # 6+7 on the final iteration
        # exactly one negotiated allreduce for "grad"; the other five rode
        # the cache (big/half/barriers add their own negotiated ops)
        st = res["stats"]
        assert st["cached_responses"] >= 5, st
        assert st["cache_hits"] >= 5, st
        assert st["fast_cycles"] >= 1, st
        # exact int64: 2*2^60 + 3 + 4 = 2305843009213693959
        assert res["big"] == [2**61 + 7], res["big"]
        assert res["bf16_ok"]


def _join_with_cached_votes_fn():
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    r = hvd.rank()
    # negotiate + cache "g" on both ranks
    first = hvd.allreduce(
        np.full(4, float(r + 1), np.float32), op=hvd.Sum, name="g"
    ).tolist()
    if r == 1:
        # rank 1 runs out of data: join.  While blocked it must still
        # participate (with zeros) in rank 0's CACHED collectives — the
        # fast path must include joined ranks in the vote execution.
        last = hvd.join()
        out = {"first": first, "cached_during_join": None, "join": last}
    else:
        vals = []
        for i in range(3):
            vals.append(
                hvd.allreduce(
                    np.full(4, float(10 + i), np.float32),
                    op=hvd.Sum, name="g",
                ).tolist()
            )
        last = hvd.join()
        out = {"first": first, "cached_during_join": vals, "join": last}
    hvd.shutdown()
    return out


def test_join_participates_in_cached_votes():
    """Regression: a joined rank computed ready=[] from its empty local
    armed set and skipped the cached collective its peers executed,
    desynchronizing the data-plane allgathers."""
    results = hvdrun.run(_join_with_cached_votes_fn, np=2, use_cpu=True,
                         timeout=180,
                         env={"HVDTPU_EAGER_ENGINE": "python"})
    r0 = next(r for r in results if r["cached_during_join"] is not None)
    assert r0["first"] == [3.0] * 4
    # joined rank contributed zeros: sums are rank 0's values alone
    assert r0["cached_during_join"] == [[10.0] * 4, [11.0] * 4, [12.0] * 4]


def _cache_conflict_fn():
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    r = hvd.rank()
    out = {}
    # negotiate + cache "t" as f32 shape (2,)
    out["first"] = hvd.allreduce(
        np.ones(2, np.float32), op=hvd.Sum, name="t"
    ).tolist()
    out["again"] = hvd.allreduce(
        np.full(2, 2.0, np.float32), op=hvd.Sum, name="t"
    ).tolist()
    # re-submit the SAME name with different geometry on every rank: the
    # stale cache entry must be evicted and renegotiated, not collide
    out["reshaped"] = hvd.allreduce(
        np.ones(3, np.float32), op=hvd.Sum, name="t"
    ).tolist()
    # and mismatched ACROSS ranks must produce the negotiated error
    try:
        hvd.allreduce(
            np.ones(2 + r, np.float32), op=hvd.Sum, name="t"
        )
        out["mismatch"] = "no error"
    except RuntimeError as exc:
        out["mismatch"] = (
            "shapes" if "Mismatched shapes" in str(exc) else str(exc)
        )
    hvd.shutdown()
    return out


def test_cache_conflict_renegotiates():
    results = hvdrun.run(_cache_conflict_fn, np=2, use_cpu=True,
                         timeout=180,
                         env={"HVDTPU_EAGER_ENGINE": "python"})
    for res in results:
        assert res["first"] == [2.0, 2.0]
        assert res["again"] == [4.0, 4.0]
        assert res["reshaped"] == [2.0, 2.0, 2.0]
        assert res["mismatch"] == "shapes"


def _reducescatter_fn():
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.ops import eager

    hvd.init()
    r = hvd.rank()
    out = {}
    # even split: dim0=4, world=2 -> 2 rows each; sum of (1s, 2s) = 3s
    x = np.full((4, 3), float(r + 1), np.float32)
    out["even"] = eager.reducescatter(x, op=hvd.Sum).tolist()
    # uneven split: dim0=3 -> rank0 gets 2 rows, rank1 gets 1
    y = np.arange(6, dtype=np.float32).reshape(3, 2) * (r + 1)
    out["uneven"] = eager.reducescatter(y, op=hvd.Sum).tolist()
    out["avg"] = eager.reducescatter(
        np.full(2, float(r + 1), np.float32), op=hvd.Average
    ).tolist()
    # scalar input -> negotiated error
    try:
        eager.reducescatter(np.float32(1.0), op=hvd.Sum)
        out["scalar"] = "no error"
    except RuntimeError as exc:
        out["scalar"] = "scalar" if "1-dimensional" in str(exc) else str(exc)
    hvd.shutdown()
    return out


def test_reducescatter_across_processes(engine_env):
    """VERDICT r1 #10: eager reducescatter on both engines (it was the one
    collective that just raised NotImplementedError)."""
    results = hvdrun.run(_reducescatter_fn, np=2, use_cpu=True, timeout=180,
                         env=engine_env)
    # sum over ranks of arange*([1,2]) = arange*3
    full = (np.arange(6, dtype=np.float32).reshape(3, 2) * 3).tolist()
    for rk, res in enumerate(results):
        assert res["even"] == [[3.0] * 3] * 2
        assert res["uneven"] == (full[:2] if rk == 0 else full[2:])
        assert res["avg"] == [1.5]  # one of the two elements per rank
        assert res["scalar"] == "scalar"


def _native_autotune_fn():
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu._engine_registry import get_engine

    hvd.init()
    eng = get_engine()
    initial_fusion = eng.lib.hvdtpu_get_fusion_bytes()
    # Steady eager traffic for the tuner to score (bytes/sec per sample
    # window, reference parameter_manager.h:178-220).
    import time

    # Generous deadline: the tuner's move cadence is wall-clock (one score
    # sample per ~steps_per_sample cycles); under a loaded CI machine the
    # cycles stretch, which made an 8 s window flaky (ADVICE r2).
    deadline = time.monotonic() + 30.0
    i = 0
    moved_fusion = initial_fusion
    moved_cycle = None
    while time.monotonic() < deadline:
        hvd.allreduce(
            np.ones(4096, np.float32), op=hvd.Sum, name=f"t{i % 4}"
        )
        i += 1
        moved_fusion = eng.lib.hvdtpu_get_fusion_bytes()
        moved_cycle = eng.lib.hvdtpu_get_cycle_ms()
        if moved_fusion != initial_fusion:
            break
    out = {
        "initial": int(initial_fusion),
        "fusion": int(moved_fusion),
        "cycle_ms": float(moved_cycle),
        "perf_bytes": int(eng.lib.hvdtpu_perf_bytes()),
        "iters": i,
    }
    hvd.shutdown()
    return out


@pytest.mark.serial
def test_native_autotune_moves_params():
    """VERDICT r1 #2: under HVDTPU_AUTOTUNE=1 the native engine's
    fusion/cycle move (rank 0 tunes, params ride the ResponseList to every
    rank — reference parameter_manager.cc:528 + controller.cc:33-47).

    serial: the autotuner samples real bytes/sec cycle timings; an
    oversubscribed parallel pass can starve a cycle and flake it."""
    from horovod_tpu.runtime.native import native_available

    if not native_available():
        pytest.skip("native library not built (make -C cpp)")
    env = {
        "HVDTPU_EAGER_ENGINE": "native",
        "HVDTPU_AUTOTUNE": "1",
        # distinctive initial so a tuner move is detectable
        "HVDTPU_FUSION_THRESHOLD": str(3 * 1024 * 1024),
        "HVDTPU_CYCLE_TIME": "2",
        # Deterministic tuner cadence (reference common.h:67-69 knobs):
        # first move after (1 warmup + 1) samples x 2 cycles instead of
        # (3 + 1) x 10 — the wall-clock-window flakiness ADVICE r2 flagged.
        "HVDTPU_AUTOTUNE_WARMUP_SAMPLES": "1",
        "HVDTPU_AUTOTUNE_STEPS_PER_SAMPLE": "2",
    }
    results = hvdrun.run(_native_autotune_fn, np=2, use_cpu=True,
                         timeout=240, env=env)
    for res in results:
        assert res["initial"] == 3 * 1024 * 1024
        assert res["perf_bytes"] > 0, res
        # BOTH ranks applied a tuner move (rank 1 only via the wire)
        assert res["fusion"] != res["initial"], res


def _tf_interop_fn():
    import numpy as np
    import tensorflow as tf

    import horovod_tpu.interop.tf as hvd

    hvd.init()
    r = hvd.rank()
    out = {}
    out["allreduce"] = hvd.allreduce(
        tf.fill((3,), float(r + 1)), op=hvd.Sum
    ).numpy().tolist()
    out["allgather"] = hvd.allgather(
        tf.fill((r + 1, 2), float(r))
    ).numpy().tolist()
    out["broadcast"] = hvd.broadcast(
        tf.constant([float(10 * (r + 1))]), root_rank=1
    ).numpy().tolist()

    # IndexedSlices across processes: rank r contributes row index r
    slices = tf.IndexedSlices(
        values=tf.constant([[float(r + 1), float(r + 1)]]),
        indices=tf.constant([r], dtype=tf.int64),
        dense_shape=tf.constant([4, 2], dtype=tf.int64),
    )
    red = hvd.allreduce(slices, op=hvd.Sum)
    out["sparse_values"] = red.values.numpy().tolist()
    out["sparse_indices"] = red.indices.numpy().tolist()

    # DistributedGradientTape: divergent per-rank grads are averaged
    v = tf.Variable([2.0])
    with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
        loss = tf.reduce_sum(v * float(r + 1))
    grad = tape.gradient(loss, v)
    out["tape_grad"] = grad.numpy().tolist()  # avg of [1, 2] = 1.5

    # Keras DistributedOptimizer: identical start + averaged grads ->
    # identical weights after the step
    opt = hvd.DistributedOptimizer(tf.keras.optimizers.SGD(0.1))
    w = tf.Variable([[1.0, 1.0]])
    hvd.broadcast_variables([w], root_rank=0)
    with tf.GradientTape() as t2:
        loss2 = tf.reduce_sum(w * float(r + 1))
    g2 = t2.gradient(loss2, w)
    opt.apply_gradients([(g2, w)])
    out["weights"] = w.numpy().flatten().tolist()
    hvd.shutdown()
    return out


@pytest.mark.slow  # tier-1 budget triage (ISSUE 15): run by node id in ci/test_matrix.sh slow_multiproc gate
def test_tf_interop_across_processes(engine_env):
    pytest.importorskip("tensorflow")
    results = hvdrun.run(_tf_interop_fn, np=2, use_cpu=True,
                         timeout=240, env=engine_env)
    for r in results:
        assert r["allreduce"] == [3.0, 3.0, 3.0]
        assert r["allgather"] == [[0.0, 0.0], [1.0, 1.0], [1.0, 1.0]]
        assert r["broadcast"] == [20.0]
        assert r["sparse_values"] == [[1.0, 1.0], [2.0, 2.0]]
        assert r["sparse_indices"] == [0, 1]
        assert r["tape_grad"] == [1.5]
    # weight sync: both ranks identical after averaged update
    assert results[0]["weights"] == results[1]["weights"]


def _sync_bn_fn():
    import numpy as np
    import torch

    import horovod_tpu.interop.torch as hvd

    hvd.init()
    r = hvd.rank()
    torch.manual_seed(0)
    full = torch.randn(8, 3, 4, 4, dtype=torch.float64)
    x = full[r * 4:(r + 1) * 4].clone().requires_grad_(True)

    sbn = hvd.SyncBatchNorm(3).double()
    out = sbn(x)
    g = torch.ones_like(out)
    out.backward(g)

    # reference: plain BN over the FULL batch on one process
    ref_x = full.clone().requires_grad_(True)
    bn = torch.nn.BatchNorm2d(3).double()
    ref = bn(ref_x)
    ref.backward(torch.ones_like(ref))
    ok_fwd = torch.allclose(out, ref[r * 4:(r + 1) * 4], atol=1e-8)
    ok_bwd = torch.allclose(x.grad, ref_x.grad[r * 4:(r + 1) * 4], atol=1e-8)
    ok_stats = torch.allclose(
        sbn.running_mean, bn.running_mean, atol=1e-8
    ) and torch.allclose(sbn.running_var, bn.running_var, atol=1e-8)

    # momentum=None: cumulative moving average (factor 1/num_batches),
    # matching torch._BatchNorm.forward — NOT a fixed 0.1.
    sbn_n = hvd.SyncBatchNorm(3, momentum=None).double()
    bn_n = torch.nn.BatchNorm2d(3, momentum=None).double()
    for step in range(3):
        batch = torch.randn(
            8, 3, 4, 4, dtype=torch.float64,
            generator=torch.Generator().manual_seed(step),
        )
        sbn_n(batch[r * 4:(r + 1) * 4])
        bn_n(batch)
    ok_cma = torch.allclose(
        sbn_n.running_mean, bn_n.running_mean, atol=1e-8
    ) and torch.allclose(sbn_n.running_var, bn_n.running_var, atol=1e-8)
    hvd.shutdown()
    return {"fwd": bool(ok_fwd), "bwd": bool(ok_bwd),
            "stats": bool(ok_stats), "cma": bool(ok_cma)}


def test_sync_batch_norm_matches_full_batch(engine_env):
    """SyncBatchNorm over rank-split batches == plain BN over the full
    batch (reference test_torch.py sync BN cases)."""
    results = hvdrun.run(_sync_bn_fn, np=2, use_cpu=True, timeout=180,
                         env=engine_env)
    for r in results:
        assert r == {"fwd": True, "bwd": True, "stats": True, "cma": True}


def test_estimator_launcher_backend(tmp_path):
    """Estimator fit through the launcher (≙ Spark-task training,
    horovod/spark/runner.py): 2 worker processes, eager gradient averaging."""
    import numpy as np
    import optax

    from horovod_tpu.checkpoint import LocalStore
    from horovod_tpu.estimator import Estimator
    from horovod_tpu.models.simple import MLP

    rng = np.random.RandomState(0)
    n = 128
    x = np.concatenate([
        rng.randn(n // 2, 2).astype(np.float32) + 2.0,
        rng.randn(n // 2, 2).astype(np.float32) - 2.0,
    ])
    y = np.concatenate([
        np.zeros(n // 2, np.int32), np.ones(n // 2, np.int32)
    ])

    est = Estimator(
        MLP(features=(8,), num_classes=2),
        optax.adam(1e-2),
        batch_size=32,
        epochs=3,
        backend="launcher",
        np_workers=2,
        use_cpu=True,
        store=LocalStore(str(tmp_path)),
        run_id="launcher",
    )
    model = est.fit({"features": x, "label": y})
    assert len(model.history) == 3
    assert model.history[-1]["loss"] < model.history[0]["loss"]
    acc = (model.transform({"features": x})["prediction"] == y).mean()
    assert acc > 0.9


# ---------------------------------------------------------------------------
# device data plane (VERDICT r2 item 2): jax.Array payloads execute as XLA
# collectives over the process mesh — no host round-trip.
# ---------------------------------------------------------------------------


def _device_plane_fn():
    import jax
    import jax.numpy as jnp

    import horovod_tpu as hvd
    from horovod_tpu._engine_registry import peek_engine

    hvd.init()
    r = hvd.rank()
    out = {}

    x = jnp.full((4,), float(r + 1), jnp.float32)
    s = hvd.allreduce(x, op=hvd.Sum)
    out["sum_is_device"] = isinstance(s, jax.Array)
    out["sum"] = np.asarray(s).tolist()

    b = jnp.asarray([100.0 * (r + 1)], jnp.float32)
    bc = hvd.broadcast(b, root_rank=1)
    out["bcast_is_device"] = isinstance(bc, jax.Array)
    out["bcast"] = np.asarray(bc).tolist()

    g = jnp.full((r + 1, 2), float(r), jnp.float32)
    ag = hvd.allgather(g)
    out["ag_is_device"] = isinstance(ag, jax.Array)
    out["ag"] = np.asarray(ag).tolist()

    # bf16 rides the device wire at 2 B/elt with f32 accumulation
    hb = hvd.allreduce(jnp.full((3,), 0.5, jnp.bfloat16), op=hvd.Average)
    out["bf16"] = np.asarray(hb.astype(jnp.float32)).tolist()

    eng = peek_engine()
    out["device_data_ops"] = eng.stats["device_data_ops"]
    out["host_data_ops"] = eng.stats["host_data_ops"]
    out["device_payload_bytes"] = eng.stats["device_payload_bytes"]
    hvd.shutdown()
    return out


def test_device_plane_no_host_round_trip():
    """Device-array eager collectives return device arrays, computed by the
    XLA data plane: the device-op counter moves, the HOST data plane is
    never touched (the assertion that there is no host round-trip)."""
    results = hvdrun.run(_device_plane_fn, np=2, use_cpu=True, timeout=180,
                         env={"HVDTPU_EAGER_ENGINE": "python"})
    for r in results:
        assert r["sum_is_device"] and r["bcast_is_device"] and r["ag_is_device"]
        assert r["sum"] == [3.0] * 4
        assert r["bcast"] == [200.0]
        assert r["ag"] == [[0.0, 0.0], [1.0, 1.0], [1.0, 1.0]]
        assert r["bf16"] == [0.5, 0.5, 0.5]
        assert r["device_data_ops"] >= 4
        assert r["host_data_ops"] == 0, "payload took a host round-trip"
        assert r["device_payload_bytes"] > 0


def _multi_local_device_fn():
    import jax
    import jax.numpy as jnp

    import horovod_tpu as hvd
    from horovod_tpu._engine_registry import peek_engine

    hvd.init()
    r = hvd.rank()
    out = {"n_local": len(jax.local_devices()),
           "n_global": len(jax.devices())}

    # non-divisible length (11 % 4 != 0) exercises the pad/unpad path
    x = jnp.arange(11, dtype=jnp.float32) + float(r)
    s = hvd.allreduce(x, op=hvd.Sum)
    out["sum_is_device"] = isinstance(s, jax.Array)
    out["sum"] = np.asarray(s).tolist()

    # caller committed to a NON-anchor local chip: result must come back
    # committed to that same chip
    dev = jax.local_devices()[2]
    y = jax.device_put(jnp.full((8,), float(r + 1), jnp.float32), dev)
    sy = hvd.allreduce(y, op=hvd.Average)
    out["y_dev_preserved"] = next(iter(sy.devices())) == dev
    out["y"] = np.asarray(sy).tolist()

    hb = hvd.allreduce(jnp.full((5,), 0.5, jnp.bfloat16), op=hvd.Average)
    out["bf16"] = np.asarray(hb.astype(jnp.float32)).tolist()

    mn = hvd.allreduce(jnp.asarray([float(r)], jnp.float32), op=hvd.Min)
    out["min"] = np.asarray(mn).tolist()

    # row-shaped collectives under the multi-chip topology: every one of
    # allgather/broadcast/reducescatter/alltoall fans its payload across
    # all k local chips (hierarchical: cross-host on 1/k chunks + local
    # reassembly) and never touches the host plane
    g = jax.device_put(
        jnp.full((2,), float(r), jnp.float32), jax.local_devices()[1]
    )
    ag = hvd.allgather(g)
    out["ag"] = np.asarray(ag).tolist()
    bc = hvd.broadcast(
        jnp.asarray([10.0 * (r + 1)], jnp.float32), root_rank=1
    )
    out["bcast"] = np.asarray(bc).tolist()
    # reducescatter: (world*3,) rows of value r+1 -> each rank keeps 3
    # rows of the sum; length 6 is not divisible by k=4 local chips, so
    # the per-block sub-chunk pad/unpad path is exercised too
    rs = hvd.reducescatter(jnp.full((6,), float(r + 1), jnp.float32))
    out["rs"] = np.asarray(rs).tolist()
    # alltoall: rank r sends block d (value 10r+d, 3 elements) to rank d
    a2a_in = jnp.repeat(jnp.arange(2, dtype=jnp.float32), 3) + 10.0 * r
    a2a = hvd.alltoall(a2a_in)
    out["a2a"] = np.asarray(a2a).tolist()

    eng = peek_engine()
    plane = eng._device_plane
    out["plane_n_local"] = plane.n_local
    out["plane_mesh2d_devices"] = (
        0 if plane.mesh2d is None else plane.mesh2d.devices.size
    )
    # cache_info().currsize > 0 proves the SHARDED (all-local-chip) jits
    # actually built — i.e. the row ops took the hierarchical path, not
    # the anchor-row fallback
    out["sharded_fns_built"] = {
        "allgather": plane._allgather_sharded_fn.cache_info().currsize,
        "broadcast": plane._broadcast_sharded_fn.cache_info().currsize,
        "reducescatter":
            plane._reducescatter_sharded_fn.cache_info().currsize,
        "alltoall": plane._alltoall_sharded_fn.cache_info().currsize,
    }
    out["device_data_ops"] = eng.stats["device_data_ops"]
    out["host_data_ops"] = eng.stats["host_data_ops"]
    hvd.shutdown()
    return out


def test_multi_local_device_plane():
    """VERDICT r3 item 3: a process owning k>1 chips meshes ALL of them —
    on an 8-device world (np=2 x 4 local), eager allreduce executes over
    the full (2, 4) mesh (chunks fanned across local chips), results
    commit back to the caller's own chip, and the host data plane is never
    touched."""
    results = hvdrun.run(
        _multi_local_device_fn, np=2, use_cpu=True, timeout=240,
        env={
            "HVDTPU_EAGER_ENGINE": "python",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        },
    )
    for d, r in enumerate(results):
        assert r["n_local"] == 4 and r["n_global"] == 8
        assert r["plane_n_local"] == 4
        assert r["plane_mesh2d_devices"] == 8, "plane did not mesh all chips"
        assert r["sum_is_device"]
        assert r["sum"] == [2.0 * i + 1.0 for i in range(11)]
        assert r["y_dev_preserved"], "result not committed to caller's chip"
        # hierarchical row ops: values correct AND the all-local-chip
        # sharded jits were the ones that ran (VERDICT r4 missing #3)
        assert r["ag"] == [0.0, 0.0, 1.0, 1.0]
        assert r["bcast"] == [20.0]
        assert r["rs"] == [1.5, 1.5, 1.5]
        assert r["a2a"] == [10.0 * src + d for src in (0, 1)
                            for _ in range(3)]
        assert all(v > 0 for v in r["sharded_fns_built"].values()), (
            r["sharded_fns_built"]
        )
        assert r["host_data_ops"] == 0, "payload took a host round-trip"
        assert r["y"] == [1.5] * 8
        assert r["bf16"] == [0.5] * 5
        assert r["min"] == [0.0]
        assert r["ag"] == [0.0, 0.0, 1.0, 1.0]
        assert r["bcast"] == [20.0]
        assert r["device_data_ops"] >= 6
        assert r["host_data_ops"] == 0, "payload took a host round-trip"


def _mixed_plane_fn():
    import jax
    import jax.numpy as jnp

    import horovod_tpu as hvd

    hvd.init()
    r = hvd.rank()
    # rank 0 submits a HOST buffer, rank 1 a device array: negotiation must
    # demote the op to the host plane on BOTH ranks (Request.device AND),
    # and each caller still gets its own kind back.
    if r == 0:
        x = np.full((4,), 1.0, np.float32)
    else:
        x = jnp.full((4,), 2.0, jnp.float32)
    s = hvd.allreduce(x, op=hvd.Sum, name="mixed")
    kind = "device" if isinstance(s, jax.Array) else "host"
    out = {"sum": np.asarray(s).tolist(), "kind": kind}
    hvd.shutdown()
    return out


def test_mixed_plane_demotes_coherently():
    results = hvdrun.run(_mixed_plane_fn, np=2, use_cpu=True, timeout=180,
                         env={"HVDTPU_EAGER_ENGINE": "python"})
    assert results[0]["sum"] == [3.0] * 4
    assert results[1]["sum"] == [3.0] * 4
    assert results[0]["kind"] == "host"
    assert results[1]["kind"] == "device"  # committed back to the caller


def _native_device_roundtrip_fn():
    import jax

    import jax.numpy as jnp

    import horovod_tpu as hvd

    hvd.init()
    r = hvd.rank()
    x = jnp.full((4,), float(r + 1), jnp.float32)
    s = hvd.allreduce(x, op=hvd.Sum)
    out = {
        "is_device": isinstance(s, jax.Array),
        "sum": np.asarray(s).tolist(),
    }
    hvd.shutdown()
    return out


def test_native_engine_returns_device_arrays(engine_env):
    """Both engines honor the device-array contract at the API boundary:
    eager allreduce of a jax.Array returns a committed jax.Array (the
    native engine ingests a zero-copy view and commits the result back)."""
    results = hvdrun.run(_native_device_roundtrip_fn, np=2, use_cpu=True,
                         timeout=180, env=engine_env)
    for r in results:
        assert r["is_device"]
        assert r["sum"] == [3.0] * 4


# ---------------------------------------------------------------------------
# halves on the wire (VERDICT r2 item 4): bf16/f16 frontend tensors must ride
# the engine at 2 B/elt — Compression.fp16 actually halves wire bytes.
# ---------------------------------------------------------------------------


def _halves_wire_fn():
    import numpy as np
    import torch

    import horovod_tpu.interop.torch as hvt
    from horovod_tpu._engine_registry import get_engine

    hvt.init()
    r = hvt.rank()
    eng = get_engine()
    out = {}

    def wire_delta(fn):
        before = eng.stats["host_wire_bytes"]
        result = fn()
        return result, eng.stats["host_wire_bytes"] - before

    n = 1024
    o32, d32 = wire_delta(
        lambda: hvt.allreduce(
            torch.full((n,), float(r + 1), dtype=torch.float32),
            op=hvt.Sum, name="w32",
        )
    )
    o16, d16 = wire_delta(
        lambda: hvt.allreduce(
            torch.full((n,), float(r + 1), dtype=torch.bfloat16),
            op=hvt.Sum, name="w16",
        )
    )
    # Compression.fp16: f32 input compressed to f16 for the wire
    comp, ctx = hvt.Compression.fp16.compress(
        torch.full((n,), float(r + 1), dtype=torch.float32)
    )
    oc, dc = wire_delta(
        lambda: hvt.Compression.fp16.decompress(
            hvt.allreduce(comp, op=hvt.Sum, name="wc"), ctx
        )
    )
    out["bytes_f32"] = d32
    out["bytes_bf16"] = d16
    out["bytes_fp16_compressed"] = dc
    out["sum_f32"] = o32[:2].tolist()
    out["sum_bf16"] = o16.to(torch.float32)[:2].tolist()
    out["sum_fp16c"] = oc[:2].tolist()
    out["dtype_bf16"] = str(o16.dtype)
    out["dtype_fp16c"] = str(oc.dtype)
    hvt.shutdown()
    return out


def test_halves_ride_the_wire_natively():
    results = hvdrun.run(_halves_wire_fn, np=2, use_cpu=True, timeout=180,
                         env={"HVDTPU_EAGER_ENGINE": "python"})
    for r in results:
        # halves cost exactly half the wire bytes of f32
        assert r["bytes_f32"] == 4096
        assert r["bytes_bf16"] == 2048, r
        assert r["bytes_fp16_compressed"] == 2048, r
        assert r["sum_f32"] == [3.0, 3.0]
        assert r["sum_bf16"] == [3.0, 3.0]  # exact at these magnitudes
        assert abs(r["sum_fp16c"][0] - 3.0) < 1e-2  # half precision tol
        assert r["dtype_bf16"] == "torch.bfloat16"
        assert r["dtype_fp16c"] == "torch.float32"  # decompressed back


# ---------------------------------------------------------------------------
# O(bytes) host data plane (VERDICT r2 item 8): host payloads reduce via a
# staged XLA collective, not gather-everything.
# ---------------------------------------------------------------------------


def _staged_host_plane_fn():
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu._engine_registry import get_engine

    hvd.init()
    r = hvd.rank()
    eng = get_engine()
    out = {}

    n = 4096
    before = eng.stats["host_recv_bytes"]
    s = hvd.allreduce(np.full((n,), float(r + 1), np.float32), op=hvd.Sum)
    out["f32_recv"] = eng.stats["host_recv_bytes"] - before
    out["f32_ok"] = bool((np.asarray(s) == 3.0).all())

    # 64-bit payloads must stay on the exact raw-bytes gather
    big = np.full((8,), 2**60, np.int64)
    before = eng.stats["host_recv_bytes"]
    s64 = hvd.allreduce(big, op=hvd.Sum)
    out["i64_recv"] = eng.stats["host_recv_bytes"] - before
    out["i64_ok"] = bool((np.asarray(s64) == 2**61).all())

    before = eng.stats["host_recv_bytes"]
    b = hvd.broadcast(np.full((n,), float(10 * (r + 1)), np.float32),
                      root_rank=1)
    out["bcast_recv"] = eng.stats["host_recv_bytes"] - before
    out["bcast_ok"] = bool((np.asarray(b) == 20.0).all())

    out["staged_ops"] = eng.stats["host_staged_ops"]
    hvd.shutdown()
    return out


def test_host_plane_reduce_is_o_bytes():
    """A large f32 allreduce/broadcast of HOST payloads receives O(bytes),
    not O(world x bytes): the engine stages it through the XLA plane's real
    reduce.  64-bit payloads keep the exact raw-bytes gather."""
    results = hvdrun.run(_staged_host_plane_fn, np=2, use_cpu=True,
                         timeout=180, env={"HVDTPU_EAGER_ENGINE": "python"})
    n_bytes = 4096 * 4
    for r in results:
        assert r["f32_ok"] and r["bcast_ok"] and r["i64_ok"]
        assert r["f32_recv"] == n_bytes, r  # O(bytes), not world x bytes
        assert r["bcast_recv"] == n_bytes, r
        assert r["i64_recv"] == 8 * 8 * 2, r  # raw gather: world x bytes
        assert r["staged_ops"] >= 2


def _python_autotune_fn(log_path):
    import time

    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    rank = hvd.rank()
    deadline = time.monotonic() + 45.0
    i = 0
    while time.monotonic() < deadline:
        hvd.allreduce(np.ones(2048, np.float32), op=hvd.Sum,
                      name=f"t{i % 4}")
        i += 1
        if i % 50 == 0 and rank == 0:
            try:
                with open(log_path) as f:
                    cache_col = {
                        line.split(",")[4] for line in f.readlines()[1:]
                    }
                if {"0", "1"} <= cache_col:
                    break  # both cache states explored — done
            except (OSError, IndexError):
                pass
    # Ranks leave the loop at different times (rank 0 early-breaks on the
    # log condition): join() lets the slower rank's remaining allreduces
    # complete with zero contributions instead of deadlocking — the exact
    # uneven-data semantics Join exists for (§3.5).
    hvd.join()
    hvd.shutdown()
    if rank != 0:
        return None
    with open(log_path) as f:
        rows = f.readlines()
    return {"header": rows[0].strip(), "n": len(rows) - 1,
            "cache_states": sorted({r.split(",")[4] for r in rows[1:]})}


def _alltoall_fn():
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    r, n = hvd.rank(), hvd.size()
    # rank r sends block d of its buffer to rank d: block value = 10*r + d
    x = np.repeat(np.arange(n), 2).astype(np.float32)
    x = 10.0 * r + x
    out = hvd.alltoall(x, name="a2a")
    hvd.shutdown()
    return np.asarray(out).tolist()


def test_alltoall_across_processes(engine_env):
    """alltoall: rank d ends with every rank's d-th block (pairwise
    exchange over the host data plane; the jit-path analog is
    lax.all_to_all over the mesh)."""
    results = hvdrun.run(_alltoall_fn, np=2, use_cpu=True, timeout=240,
                         env=engine_env)
    for d, res in enumerate(results):
        want = []
        for src in (0, 1):
            want += [10.0 * src + d] * 2
        assert res == want, (d, res)


def _timeline_cycles_fn():
    # the timeline path flows through the HVDTPU_TIMELINE env var
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    for i in range(4):
        hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum, name=f"t{i}")
    hvd.shutdown()


def test_timeline_cycle_markers_across_processes(tmp_path):
    """HVDTPU_TIMELINE_MARK_CYCLES puts CYCLE markers in rank 0's Chrome
    trace (reference HOROVOD_TIMELINE_MARK_CYCLES, operations.cc:415;
    asserted like the reference's test_timeline.py:40-57)."""
    import json

    path = str(tmp_path / "timeline.json")
    hvdrun.run(_timeline_cycles_fn, np=2, use_cpu=True,
               timeout=240,
               env={
                   "HVDTPU_EAGER_ENGINE": "python",
                   "HVDTPU_TIMELINE": path,
                   "HVDTPU_TIMELINE_MARK_CYCLES": "1",
               })
    events = json.loads(open(path).read())
    names = {e.get("name") for e in events if isinstance(e, dict)}
    assert any("CYCLE" in (n or "") for n in names), sorted(names)[:20]
    # negotiation + op phases also present (reference asserts
    # NEGOTIATE_ALLREDUCE / ALLREDUCE)
    assert any("ALLREDUCE" in (n or "") for n in names)


def _adasum_per_tensor_fn():
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    r = hvd.rank()
    # two Adasum tensors in flight in the SAME cycle, deliberately
    # non-parallel across ranks so the projection outcome is sensitive to
    # its input span
    a = np.asarray([1.0, 0.0] if r == 0 else [0.0, 1.0], np.float32)
    b = np.asarray([2.0, 2.0] if r == 0 else [2.0, -2.0], np.float32)
    ha = hvd.allreduce_async(a, op=hvd.Adasum, name="ad_a")
    hb = hvd.allreduce_async(b, op=hvd.Adasum, name="ad_b")
    out = {
        "a": np.asarray(hvd.synchronize(ha)).tolist(),
        "b": np.asarray(hvd.synchronize(hb)).tolist(),
    }
    hvd.shutdown()
    return out


def test_adasum_projection_is_per_tensor(engine_env):
    """Two Adasum tensors negotiated in one cycle reduce with PER-TENSOR
    VHDD coefficients (reference adasum.h tensor_counts: one projection
    per layer), not one projection over a fused concatenation."""
    from horovod_tpu.ops.adasum import _numpy_adasum_rows

    results = hvdrun.run(_adasum_per_tensor_fn, np=2, use_cpu=True,
                         timeout=240, env=engine_env)
    want_a = _numpy_adasum_rows([[1.0, 0.0], [0.0, 1.0]])
    want_b = _numpy_adasum_rows([[2.0, 2.0], [2.0, -2.0]])
    for res in results:
        np.testing.assert_allclose(res["a"], want_a, rtol=1e-5)
        np.testing.assert_allclose(res["b"], want_b, rtol=1e-5)


def _torch_adasum_opt_fn():
    import numpy as np
    import torch

    import horovod_tpu.interop.torch as hvd

    hvd.init()
    r = hvd.rank()
    w = torch.nn.Parameter(torch.tensor([1.0, 0.0]))
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD([w], lr=0.1),
        named_parameters=[("w", w)],
        op=hvd.Adasum,
    )
    # rank-dependent, non-parallel gradients so the Adasum projection is
    # non-trivial (parallel deltas would degenerate to an average)
    target = torch.tensor([1.0, 0.0]) if r == 0 else torch.tensor([0.3, 0.9])
    loss = (w * target).sum()
    loss.backward()
    opt.step()
    out = w.detach().numpy().tolist()
    hvd.shutdown()
    return out


def test_torch_adasum_optimizer_matches_numpy_reference(engine_env):
    """The delta-based Adasum optimizer's result equals start +
    numpy-VHDD(deltas) — the projection runs on update directions, not raw
    grads (reference _DistributedAdasumOptimizer, torch/__init__.py:225-393)."""
    from horovod_tpu.ops.adasum import _numpy_adasum_rows

    results = hvdrun.run(_torch_adasum_opt_fn, np=2, use_cpu=True,
                         timeout=240, env=engine_env)
    deltas = [
        -0.1 * np.array([1.0, 0.0]),
        -0.1 * np.array([0.3, 0.9]),
    ]
    want = np.array([1.0, 0.0]) + _numpy_adasum_rows(deltas)
    for res in results:
        np.testing.assert_allclose(res, want, rtol=1e-5)


def _tf_session_hook_fn():
    import numpy as np
    import tensorflow as tf

    tf.compat.v1.disable_eager_execution()  # TF1-style graph/session job

    import horovod_tpu.interop.tf as hvd

    hvd.init()
    r = hvd.rank()
    with tf.Graph().as_default():
        v = tf.compat.v1.get_variable(
            "v", initializer=tf.constant([float(r + 1)] * 3)
        )
        hook = hvd.BroadcastGlobalVariablesHook(root_rank=1)
        with tf.compat.v1.train.MonitoredTrainingSession(
            hooks=[hook]
        ) as sess:
            out = np.asarray(sess.run(v)).tolist()
    hvd.shutdown()
    return out


@pytest.mark.slow  # tier-1 budget triage (ISSUE 15): run by node id in ci/test_matrix.sh slow_multiproc gate
def test_tf_broadcast_hook_in_monitored_session(engine_env):
    """BroadcastGlobalVariablesHook broadcasts on session creation — the
    TF1 estimator migration path (reference tensorflow/__init__.py:194-227)."""
    results = hvdrun.run(_tf_session_hook_fn, np=2, use_cpu=True,
                         timeout=240, env=engine_env)
    for res in results:
        assert res == [2.0, 2.0, 2.0]  # root 1's initial value


def _tf_adasum_opt_fn():
    import numpy as np
    import tensorflow as tf

    import horovod_tpu.interop.tf as hvd

    hvd.init()
    r = hvd.rank()
    v = tf.Variable([1.0, 0.0])
    opt = hvd.DistributedOptimizer(
        tf.keras.optimizers.SGD(learning_rate=0.1), op=hvd.Adasum
    )
    grad = tf.constant([1.0, 0.0]) if r == 0 else tf.constant([0.3, 0.9])
    opt.apply_gradients([(grad, v)])
    out = v.numpy().tolist()

    # Regression: Keras-3 variables carry unscoped duplicate names
    # ('kernel', 'kernel'); the delta exchange must not collide on the
    # engine's duplicate-in-flight-name guard.
    a = tf.Variable([1.0], name="kernel")
    b = tf.Variable([2.0], name="kernel")
    opt2 = hvd.DistributedOptimizer(
        tf.keras.optimizers.SGD(learning_rate=0.1), op=hvd.Adasum
    )
    opt2.apply_gradients(
        [(tf.constant([1.0]), a), (tf.constant([1.0]), b)]
    )
    dup_ok = np.isfinite(float(a.numpy()[0])) and np.isfinite(
        float(b.numpy()[0])
    )

    hvd.shutdown()
    return {"v": out, "dup_ok": bool(dup_ok)}


@pytest.mark.slow  # tier-1 budget triage (ISSUE 15): run by node id in ci/test_matrix.sh slow_multiproc gate
def test_tf_adasum_optimizer_matches_numpy_reference(engine_env):
    """TF frontend delta-Adasum: final var == start + numpy-VHDD(deltas)
    (reference _DistributedAdasumOptimizer, tensorflow/__init__.py:313-407)."""
    from horovod_tpu.ops.adasum import _numpy_adasum_rows

    results = hvdrun.run(_tf_adasum_opt_fn, np=2, use_cpu=True,
                         timeout=240, env=engine_env)
    deltas = [
        -0.1 * np.array([1.0, 0.0]),
        -0.1 * np.array([0.3, 0.9]),
    ]
    want = np.array([1.0, 0.0]) + _numpy_adasum_rows(deltas)
    for res in results:
        np.testing.assert_allclose(res["v"], want, rtol=1e-5)
        assert res["dup_ok"]


def _cache_divergence_fn():
    """Recreate the classification divergence a tuner cache toggle can
    cause: rank 1 holds a tensor cached (arms a slot vote) while rank 0
    negotiates the same tensor through the slow path.  Without the
    divergence repair this deadlocks — the slot vote waits on rank 0, the
    message-table entry waits on rank 1."""
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu._engine_registry import get_engine

    hvd.init()
    eng = get_engine()
    r = hvd.rank()
    # prime the (coherent) cache on both ranks
    hvd.allreduce(np.ones(8, np.float32), op=hvd.Sum, name="div")
    # let the insert settle so the next submission is a clean cache HIT on
    # rank 1 (insertion rides the same cycle's response application)
    hvd.allreduce(np.zeros(1, np.float32), op=hvd.Sum, name="sync")
    if r == 0:  # flip ONLY rank 0's gate — the divergence injection
        if hasattr(eng, "lib"):
            eng.lib.hvdtpu_inject_local_cache_enabled(0)
        else:
            eng.cache_enabled = False
    out = hvd.allreduce(
        np.full(8, float(r + 1), np.float32), op=hvd.Sum, name="div"
    )
    hvd.shutdown()
    return np.asarray(out).tolist()


def test_cache_divergence_repair(engine_env):
    """A cache-hit slot vote on one rank reconciles against a slow-path
    request for the same tensor on another (both engines), instead of
    deadlocking until the stall inspector fires."""
    results = hvdrun.run(_cache_divergence_fn, np=2, use_cpu=True,
                         timeout=120, env=engine_env)
    for res in results:
        assert res == [3.0] * 8  # 1 + 2: the collective completed


def test_python_autotune_explores_cache_axis(tmp_path):
    """VERDICT r2 weak #6: the Python engine's response cache is a real
    code path now, so its tuner explores cache_enabled — both states show
    up in the autotune log (reference LogParameters CSV)."""
    log_path = str(tmp_path / "autotune.csv")
    results = hvdrun.run(
        _python_autotune_fn, (log_path,), np=2, use_cpu=True, timeout=240,
        env={
            "HVDTPU_EAGER_ENGINE": "python",
            "HVDTPU_AUTOTUNE": "1",
            "HVDTPU_AUTOTUNE_LOG": log_path,
            "HVDTPU_CYCLE_TIME": "2",
            # Deterministic tuner cadence (reference common.h:67-69): the
            # cache axis flips after 1 warmup + 3 samples x 2 cycles, not
            # 3 + 12 x 10 — wall-clock windows under CI load were flaky.
            "HVDTPU_AUTOTUNE_WARMUP_SAMPLES": "1",
            "HVDTPU_AUTOTUNE_STEPS_PER_SAMPLE": "2",
            "HVDTPU_AUTOTUNE_BAYES_OPT_MAX_SAMPLES": "3",
        },
    )
    r0 = results[0]
    assert "cache_enabled" in r0["header"]
    assert r0["n"] > 0
    assert r0["cache_states"] == ["0", "1"], r0


# ---------------------------------------------------------------------------
# Keras model.fit across processes (VERDICT r2 item 7): broadcast-on-start
# + averaged epoch metrics through real tf.keras callbacks.
# ---------------------------------------------------------------------------


def _keras_fit_fn():
    import numpy as np
    import tensorflow as tf

    import horovod_tpu.interop.tf_keras as hvk

    hvk.init()
    r = hvk.rank()

    tf.keras.utils.set_random_seed(1234 + r)  # divergent initial weights
    model = tf.keras.Sequential(
        [tf.keras.Input(shape=(2,)),
         tf.keras.layers.Dense(1, use_bias=False)]
    )
    model.compile(
        optimizer=hvk.DistributedOptimizer(
            tf.keras.optimizers.SGD(learning_rate=0.05)
        ),
        loss="mse",
    )
    # rank-dependent CONSTANT targets so per-rank losses differ unless the
    # MetricAverageCallback averages them
    x = np.random.RandomState(7).randn(32, 2).astype(np.float32)
    y = np.full((32, 1), float(r), np.float32)
    hist = model.fit(
        x, y, epochs=2, batch_size=8, verbose=0,
        callbacks=[
            hvk.callbacks.BroadcastGlobalVariablesCallback(0),
            hvk.callbacks.MetricAverageCallback(),
        ],
    )
    out = {
        "weights": model.get_weights()[0].ravel().tolist(),
        "loss": [float(v) for v in hist.history["loss"]],
    }
    hvk.shutdown()
    return out


@pytest.mark.slow  # tier-1 budget triage (ISSUE 15): run by node id in ci/test_matrix.sh slow_multiproc gate
def test_keras_fit_across_processes():
    results = hvdrun.run(_keras_fit_fn, np=2, use_cpu=True, timeout=300,
                         env={"HVDTPU_EAGER_ENGINE": "python"})
    # Broadcast-on-start + identical (averaged) gradients => identical
    # weights on both ranks at the end of fit.
    np.testing.assert_allclose(
        results[0]["weights"], results[1]["weights"], rtol=1e-6
    )
    # MetricAverageCallback: both ranks report the SAME averaged loss even
    # though their local targets (and hence local losses) differ.
    np.testing.assert_allclose(
        results[0]["loss"], results[1]["loss"], rtol=1e-6
    )


# ---------------------------------------------------------------------------
# dtype x dims grid across processes (reference test_torch.py/test_tensorflow
# strategy: allreduce/allgather/broadcast over dtype and dimension grids)
# ---------------------------------------------------------------------------


def _dtype_grid_fn():
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    r = hvd.rank()
    out = {}
    dtypes = ["float32", "float64", "int32", "int64", "uint8", "float16",
              "bfloat16"]
    for dt in dtypes:
        if dt == "bfloat16":
            import ml_dtypes

            npdt = np.dtype(ml_dtypes.bfloat16)
        else:
            npdt = np.dtype(dt)
        for dim in (1, 2, 3):
            shape = (2,) * dim
            x = (np.arange(2 ** dim).reshape(shape) % 3 + r).astype(npdt)
            s = hvd.allreduce(x, op=hvd.Sum, name=f"grid_{dt}_{dim}")
            out[f"{dt}_{dim}"] = np.asarray(s, np.float64).tolist()
    # int64 beyond float64's exact range must survive the wire bit-exactly
    big = np.asarray([2 ** 60 + 1, -(2 ** 61)], np.int64)
    s = hvd.allreduce(big, op=hvd.Sum, name="grid_big_i64")
    out["big_i64"] = [int(v) for v in np.asarray(s)]
    # scalar (0-d) allreduce and broadcast round-trip with shape intact
    sc = hvd.allreduce(np.float32(r + 1.0), op=hvd.Sum, name="grid_scalar")
    out["scalar"] = [float(np.asarray(sc).reshape(-1)[0]),
                     list(np.asarray(sc).shape)]
    hvd.shutdown()
    return out


def test_dtype_dims_grid_across_processes(engine_env):
    results = hvdrun.run(_dtype_grid_fn, np=2, use_cpu=True, timeout=240,
                         env=engine_env)
    for res in results:
        for dt in ["float32", "float64", "int32", "int64", "uint8",
                   "float16", "bfloat16"]:
            for dim in (1, 2, 3):
                base = (np.arange(2 ** dim).reshape((2,) * dim) % 3)
                want = (2 * base + 1).astype(np.float64)  # ranks 0+1
                got = np.asarray(res[f"{dt}_{dim}"])
                np.testing.assert_allclose(got, want.tolist(), rtol=1e-2)
        assert res["big_i64"] == [2 ** 61 + 2, -(2 ** 62)]
        assert res["scalar"][0] == 3.0
        assert res["scalar"][1] == []  # 0-d shape survives the round-trip


def _device_disabled_fn():
    import jax
    import jax.numpy as jnp

    import horovod_tpu as hvd
    from horovod_tpu._engine_registry import get_engine

    hvd.init()
    r = hvd.rank()
    x = jnp.full((4,), float(r + 1), jnp.float32)
    s = hvd.allreduce(x, op=hvd.Sum)
    eng = get_engine()
    out = {
        "sum": np.asarray(s).tolist(),
        "is_device_result": isinstance(s, jax.Array),
        "device_data_ops": eng.stats["device_data_ops"],
    }
    hvd.shutdown()
    return out


def test_eager_device_kill_switch_demotes_globally():
    """HVDTPU_EAGER_DEVICE=0 disables the device plane: jax payloads still
    work (host plane), results still come back as device arrays, and no
    device-plane collective runs — on any rank, coherently."""
    import numpy as np

    results = hvdrun.run(
        _device_disabled_fn, np=2, use_cpu=True, timeout=180,
        env={"HVDTPU_EAGER_ENGINE": "python", "HVDTPU_EAGER_DEVICE": "0"},
    )
    for r in results:
        assert r["sum"] == [3.0] * 4
        assert r["is_device_result"]  # synchronize still restores device
        assert r["device_data_ops"] == 0
