"""horovod.tensorflow-compatible interop frontend (reference surface:
test/test_tensorflow.py — op correctness, gradients, DistributedOptimizer,
DistributedGradientTape, IndexedSlices sparse path; single-process
identities here, real 2-process semantics in test_multiprocess.py)."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import horovod_tpu.interop.tf as hvd  # noqa: E402


@pytest.fixture(autouse=True)
def _init():
    # conftest's session fixture owns the framework lifecycle; don't
    # shutdown here or later test files lose the initialized topology.
    hvd.init()
    yield


def test_allreduce_identity_single_process():
    x = tf.reshape(tf.range(6, dtype=tf.float32), (2, 3))
    out = hvd.allreduce(x)
    assert isinstance(out, tf.Tensor)
    np.testing.assert_allclose(out.numpy(), x.numpy())


def test_allreduce_sum_bf16_roundtrip():
    x = tf.ones((8,), dtype=tf.bfloat16)
    out = hvd.allreduce(x, op=hvd.Sum)
    assert out.dtype == tf.bfloat16
    np.testing.assert_allclose(tf.cast(out, tf.float32).numpy(), np.ones(8))


def test_allreduce_inside_tf_function():
    # py_function keeps the engine call graph-safe (reference runs these
    # as TF graph ops, tensorflow/mpi_ops.cc).
    @tf.function
    def fn(x):
        return hvd.allreduce(x, op=hvd.Sum)

    out = fn(tf.constant([1.0, 2.0]))
    np.testing.assert_allclose(out.numpy(), [1.0, 2.0])


def test_allreduce_indexed_slices_allgathers():
    # reference tensorflow/__init__.py:74-89: IndexedSlices -> allgather
    # of values and indices.
    slices = tf.IndexedSlices(
        values=tf.constant([[1.0, 2.0], [3.0, 4.0]]),
        indices=tf.constant([0, 3], dtype=tf.int64),
        dense_shape=tf.constant([5, 2], dtype=tf.int64),
    )
    out = hvd.allreduce(slices, op=hvd.Average)
    assert isinstance(out, tf.IndexedSlices)
    np.testing.assert_allclose(out.values.numpy(), [[1.0, 2.0], [3.0, 4.0]])
    np.testing.assert_array_equal(out.indices.numpy(), [0, 3])


def test_allreduce_grad_is_allreduced():
    x = tf.Variable([1.0, 2.0, 3.0])
    with tf.GradientTape() as tape:
        y = tf.reduce_sum(hvd._allreduce(x, op=hvd.Sum))
    grad = tape.gradient(y, x)
    np.testing.assert_allclose(grad.numpy(), np.ones(3))


def test_allgather_and_grad():
    x = tf.Variable(np.random.randn(2, 3).astype(np.float32))
    with tf.GradientTape() as tape:
        g = hvd.allgather(x)
        loss = tf.reduce_sum(g)
    assert g.shape == (2, 3)
    grad = tape.gradient(loss, x)
    np.testing.assert_allclose(grad.numpy(), np.ones((2, 3)))


def test_broadcast_grad_root():
    x = tf.Variable(np.random.randn(4).astype(np.float32))
    with tf.GradientTape() as tape:
        y = tf.reduce_sum(hvd.broadcast(x, root_rank=0))
    grad = tape.gradient(y, x)
    # rank 0 IS the root in a single-process world: grads arrive summed
    np.testing.assert_allclose(grad.numpy(), np.ones(4))


def test_broadcast_variables_assigns():
    v = tf.Variable([5.0, 6.0])
    hvd.broadcast_variables([v], root_rank=0)
    np.testing.assert_allclose(v.numpy(), [5.0, 6.0])


def test_distributed_gradient_tape():
    x = tf.Variable(3.0)
    with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
        y = x * x
    grad = tape.gradient(y, x)
    np.testing.assert_allclose(float(grad), 6.0)


def test_distributed_keras_optimizer_applies():
    try:
        opt = tf.keras.optimizers.SGD(learning_rate=0.5)
    except Exception:
        pytest.skip("keras optimizers unavailable")
    dopt = hvd.DistributedOptimizer(opt)
    assert type(dopt).__name__.startswith("Distributed")
    v = tf.Variable(2.0)
    dopt.apply_gradients([(tf.constant(1.0), v)])
    np.testing.assert_allclose(float(v), 1.5)


def test_distributed_legacy_optimizer_wrap():
    try:
        base = tf.compat.v1.train.GradientDescentOptimizer(0.1)
    except AttributeError:
        pytest.skip("tf.compat.v1 unavailable")
    dopt = hvd.DistributedOptimizer(base)
    assert dopt.get_slot_names() == base.get_slot_names()


def test_adasum_optimizer_single_process_delta_step():
    """op=Adasum diverts to the delta-reducing wrapper (reference factory
    tensorflow/__init__.py:453-459); world 1 applies the local update.
    A Keras optimizer yields a real Keras subclass so model.compile
    accepts it."""
    v = tf.Variable([1.0, 2.0])
    opt = hvd.DistributedOptimizer(
        tf.keras.optimizers.SGD(learning_rate=0.1), op=hvd.Adasum
    )
    assert type(opt).__name__ == "AdasumSGD"
    assert isinstance(opt, tf.keras.optimizers.SGD)
    opt.apply_gradients([(tf.constant([1.0, 2.0]), v)])
    np.testing.assert_allclose(v.numpy(), [0.9, 1.8], rtol=1e-6)
    # slot-style start buffer exists per variable
    assert len(opt._hvd_starts) == 1


def test_adasum_keras_optimizer_works_in_model_compile():
    """The Adasum wrapper must survive Keras's optimizer validation in
    model.compile + fit (existing user flow, not just apply_gradients)."""
    model = tf.keras.Sequential(
        [tf.keras.Input(shape=(2,)),
         tf.keras.layers.Dense(1, use_bias=False)]
    )
    model.compile(
        optimizer=hvd.DistributedOptimizer(
            tf.keras.optimizers.SGD(learning_rate=0.1), op=hvd.Adasum
        ),
        loss="mse",
    )
    x = np.ones((8, 2), np.float32)
    y = np.zeros((8, 1), np.float32)
    hist = model.fit(x, y, epochs=1, batch_size=4, verbose=0)
    assert np.isfinite(hist.history["loss"][0])


def test_allreduce_dtype_dims_grid():
    """Reference test_tensorflow.py pattern: allreduce across dtype x
    dimensionality preserves dtype/shape/values (world 1 identities)."""
    dtypes = [tf.float32, tf.float64, tf.float16, tf.bfloat16,
              tf.int32, tf.int64]
    for dt in dtypes:
        for dim in (1, 2, 3):
            shape = (2,) * dim
            x = tf.cast(
                tf.reshape(tf.range(2 ** dim) % 3, shape), dt
            )
            op = hvd.Sum if not dt.is_floating else hvd.Average
            out = hvd.allreduce(x, op=op)
            assert out.dtype == dt, (dt, dim)
            assert tuple(out.shape) == shape, (dt, dim)
            np.testing.assert_allclose(
                tf.cast(out, tf.float64).numpy(),
                tf.cast(x, tf.float64).numpy(),
            )


def test_compression_fp16_roundtrip():
    x = tf.constant([1.0, 2.0, 3.0])
    c, ctx = hvd.Compression.fp16.compress(x)
    assert c.dtype == tf.float16
    out = hvd.Compression.fp16.decompress(c, ctx)
    assert out.dtype == tf.float32
    np.testing.assert_allclose(out.numpy(), x.numpy())


def test_alltoall_single_process_identity():
    x = tf.constant(np.arange(4, dtype=np.float32))
    out = hvd.alltoall(x)
    np.testing.assert_allclose(out.numpy(), x.numpy())


def test_feature_probes_answer():
    assert hvd.size() >= 1
    assert isinstance(hvd.gloo_built(), bool)
    assert isinstance(hvd.mpi_built(), bool)


# ---------------------------------------------------------------------------
# Keras frontend (reference horovod.tensorflow.keras; VERDICT r2 item 7)
# ---------------------------------------------------------------------------


def _tiny_model(lr=0.1):
    import tensorflow as tf

    import horovod_tpu.interop.tf_keras as hvk

    model = tf.keras.Sequential(
        [tf.keras.Input(shape=(2,)),
         tf.keras.layers.Dense(1, use_bias=False)]
    )
    model.compile(
        optimizer=hvk.DistributedOptimizer(
            tf.keras.optimizers.SGD(learning_rate=lr)
        ),
        loss="mse",
    )
    return model


def test_keras_fit_with_callbacks_single_process():
    import numpy as np
    import tensorflow as tf

    import horovod_tpu.interop.tf_keras as hvk

    x = np.random.RandomState(0).randn(32, 2).astype(np.float32)
    y = (x @ np.asarray([[1.0], [2.0]], np.float32)).astype(np.float32)
    model = _tiny_model()
    hist = model.fit(
        x, y, epochs=2, batch_size=8, verbose=0,
        callbacks=[
            hvk.callbacks.BroadcastGlobalVariablesCallback(0),
            hvk.callbacks.MetricAverageCallback(),
            # no steps_per_epoch: must auto-fill from Keras's fit params
            hvk.callbacks.LearningRateWarmupCallback(
                initial_lr=0.1, warmup_epochs=2
            ),
        ],
    )
    assert "loss" in hist.history
    assert hist.history["loss"][-1] < hist.history["loss"][0]
    # warmup ramps toward initial_lr (world==1: multiplier is 1 throughout)
    assert abs(hvk._lr_value(model.optimizer) - 0.1) < 1e-6


def test_keras_lr_schedule_staircase():
    import numpy as np

    import horovod_tpu.interop.tf_keras as hvk

    x = np.zeros((8, 2), np.float32)
    y = np.zeros((8, 1), np.float32)
    model = _tiny_model(lr=1.0)
    cb = hvk.callbacks.LearningRateScheduleCallback(
        initial_lr=1.0, multiplier=lambda epoch: 0.5 ** epoch
    )
    hist = model.fit(x, y, epochs=3, batch_size=8, verbose=0, callbacks=[cb])
    # epoch e runs at lr = 0.5^e; logs record it
    assert hist.history["lr"] == [1.0, 0.5, 0.25]


def test_keras_load_model_rewraps_optimizer(tmp_path):
    import numpy as np

    import horovod_tpu.interop.tf_keras as hvk

    x = np.random.RandomState(0).randn(16, 2).astype(np.float32)
    y = np.zeros((16, 1), np.float32)
    model = _tiny_model()
    model.fit(x, y, epochs=1, batch_size=8, verbose=0)
    path = str(tmp_path / "model.keras")
    model.save(path)
    restored = hvk.load_model(path)
    assert getattr(restored.optimizer, "_hvd_wrapped", False), (
        "load_model must return a model whose optimizer is re-wrapped in "
        "DistributedOptimizer (reference _keras/__init__.py:113-128)"
    )
    restored.fit(x, y, epochs=1, batch_size=8, verbose=0)  # still trains


def test_keras_load_model_restores_adasum_wrap(tmp_path):
    """A model compiled with op=Adasum serializes its optimizer as
    'AdasumSGD'; load_model must deserialize it back into the delta
    wrapper and keep training."""
    import horovod_tpu.interop.tf_keras as hvk

    x = np.random.RandomState(0).randn(16, 2).astype(np.float32)
    y = np.zeros((16, 1), np.float32)
    model = tf.keras.Sequential(
        [tf.keras.Input(shape=(2,)),
         tf.keras.layers.Dense(1, use_bias=False)]
    )
    model.compile(
        optimizer=hvd.DistributedOptimizer(
            tf.keras.optimizers.SGD(learning_rate=0.1), op=hvd.Adasum
        ),
        loss="mse",
    )
    model.fit(x, y, epochs=1, batch_size=8, verbose=0)
    path = str(tmp_path / "adasum.keras")
    model.save(path)
    restored = hvk.load_model(path)
    assert type(restored.optimizer).__name__ == "AdasumSGD"
    assert getattr(restored.optimizer, "_hvd_wrapped", False)
    restored.fit(x, y, epochs=1, batch_size=8, verbose=0)


def test_keras_warmup_momentum_correction_restores():
    import numpy as np
    import tensorflow as tf

    import horovod_tpu.interop.tf_keras as hvk

    x = np.zeros((16, 2), np.float32)
    y = np.zeros((16, 1), np.float32)
    model = tf.keras.Sequential(
        [tf.keras.Input(shape=(2,)),
         tf.keras.layers.Dense(1, use_bias=False)]
    )
    model.compile(
        optimizer=hvk.DistributedOptimizer(
            tf.keras.optimizers.SGD(learning_rate=0.1, momentum=0.9)
        ),
        loss="mse",
    )
    model.fit(
        x, y, epochs=2, batch_size=8, verbose=0,
        callbacks=[hvk.callbacks.LearningRateWarmupCallback(
            initial_lr=0.1, warmup_epochs=2
        )],
    )
    # per-batch LR changes temporarily rescale momentum (Goyal et al.
    # correction) and must restore it after every batch
    assert abs(float(model.optimizer.momentum) - 0.9) < 1e-9
