"""Elastic fault tolerance (horovod_tpu/elastic/ + run/blacklist.py +
testing/faults.py): commit/rollback/sync semantics, the recover-and-resume
loop, host blacklisting with backoff, and the end-to-end chaos acceptance
from ISSUE 1 — a 4-process job losing a rank mid-training recovers via
rollback + respawn to the same final state as a no-fault run, with a
deterministic recovery trace."""

import importlib
import pickle
import threading
import time

import numpy as np
import pytest

import horovod_tpu.elastic as elastic
from horovod_tpu.elastic.context import ElasticContext
from horovod_tpu.elastic.exceptions import (
    HorovodShutdownError,
    RankDroppedError,
    WorkersAvailableException,
)
from horovod_tpu.run.blacklist import HostBlacklist
from horovod_tpu.run.rendezvous import KVStoreClient, KVStoreServer
from horovod_tpu.testing import faults


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    """Every test starts with an empty fault registry, no leaked
    ambient context, and a zeroed progress beat."""
    from horovod_tpu.obs import progress as obs_progress

    monkeypatch.delenv(faults.SPEC_ENV, raising=False)
    faults.reset()
    elastic.reset_context()
    obs_progress.reset()
    yield
    faults.reset()
    elastic.reset_context()
    obs_progress.reset()


# ---------------------------------------------------------------------------
# State: commit / restore / sync
# ---------------------------------------------------------------------------


def test_state_commit_restore_roundtrip():
    state = elastic.State(w=np.arange(4.0), step=0, meta={"lr": 0.1})
    state.w = state.w + 1
    state.step = 3
    state.commit()
    state.w = state.w * 100
    state.step = 9
    state.meta["lr"] = 0.5
    state.restore()
    np.testing.assert_array_equal(state.w, np.arange(4.0) + 1)
    assert state.step == 3
    assert state.meta == {"lr": 0.1}
    assert state.commits == 1


def test_state_restore_without_commit_rewinds_to_init():
    state = elastic.State(step=7)
    state.step = 99
    state.restore()
    assert state.step == 7
    assert state.commits == 0


def test_state_snapshot_is_isolated_from_live_values():
    """commit() must deep-copy: later in-place mutation of the live
    arrays may not corrupt the rollback point."""
    w = np.zeros(3)
    state = elastic.State(w=w)
    state.commit()
    w += 5  # in-place on the live buffer
    state.restore()
    np.testing.assert_array_equal(state.w, np.zeros(3))


def test_state_jax_arrays_snapshot_to_host():
    import jax.numpy as jnp

    state = elastic.State(w=jnp.ones(2))
    state.commit()
    state.w = jnp.zeros(2)
    state.restore()
    np.testing.assert_array_equal(np.asarray(state.w), np.ones(2))


def test_state_register_and_unknown_attr():
    state = elastic.State(a=1)
    state.register(b=2)
    assert state.b == 2
    assert sorted(state.values()) == ["a", "b"]
    with pytest.raises(AttributeError, match="no value 'missing'"):
        state.missing


def test_state_sync_is_identity_on_local_context():
    state = elastic.State(w=np.ones(2), step=4)
    state.commit()
    state.sync()
    np.testing.assert_array_equal(state.w, np.ones(2))
    assert state.step == 4


class _FakeCtx:
    """Scripted context: fails the first ``fail_first`` rendezvous-cycle
    executions of the wrapped fn with the given exception class."""

    def __init__(self, fail_first=0, exc=HorovodShutdownError):
        self.rank, self.size, self.epoch, self.world = 0, 1, 0, (0,)
        self.rendezvous_calls = 0
        self.sync_calls = 0
        self._fail_first = fail_first
        self._exc = exc

    def rendezvous(self, timeout=None):
        self.rendezvous_calls += 1
        return self.epoch

    def world_changed(self):
        return False

    def sync_state(self, blob, commit_count):
        self.sync_calls += 1
        return blob

    def maybe_fail(self):
        if self._fail_first > 0:
            self._fail_first -= 1
            raise self._exc("scripted failure")


def test_run_rolls_back_and_resumes(monkeypatch):
    run_mod = importlib.import_module("horovod_tpu.elastic.run")

    ctx = _FakeCtx(fail_first=2)
    monkeypatch.setattr(run_mod, "_ambient_context", lambda: ctx)

    state = elastic.State(step=0, log=[])

    @elastic.run
    def loop(state):
        while state.step < 4:
            ctx.maybe_fail()  # dies twice, at step 0 of attempts 1 and 2
            state.log = state.log + [state.step]
            state.step += 1
            state.commit()
        return state.step

    assert loop(state) == 4
    # three attempts -> three rendezvous + sync cycles
    assert ctx.rendezvous_calls == 3
    assert ctx.sync_calls == 3
    # rollback semantics: no step was double-applied after recovery
    assert state.log == [0, 1, 2, 3]


def test_run_exhausts_retry_budget(monkeypatch):
    run_mod = importlib.import_module("horovod_tpu.elastic.run")

    ctx = _FakeCtx(fail_first=99)
    monkeypatch.setattr(run_mod, "_ambient_context", lambda: ctx)
    monkeypatch.setenv(run_mod.MAX_RETRIES_ENV, "2")

    @elastic.run
    def loop(state):
        ctx.maybe_fail()
        return "unreachable"

    with pytest.raises(HorovodShutdownError, match="retry budget"):
        loop(elastic.State(step=0))


def test_run_absorbs_workers_available(monkeypatch):
    run_mod = importlib.import_module("horovod_tpu.elastic.run")

    ctx = _FakeCtx(fail_first=1, exc=WorkersAvailableException)
    monkeypatch.setattr(run_mod, "_ambient_context", lambda: ctx)

    @elastic.run
    def loop(state):
        ctx.maybe_fail()
        return state.step

    assert loop(elastic.State(step=1)) == 1
    assert ctx.rendezvous_calls == 2


def test_run_propagates_user_errors(monkeypatch):
    """Only world breakage is recoverable; user bugs surface unchanged."""
    run_mod = importlib.import_module("horovod_tpu.elastic.run")

    ctx = _FakeCtx()
    monkeypatch.setattr(run_mod, "_ambient_context", lambda: ctx)

    @elastic.run
    def loop(state):
        raise ValueError("user bug")

    with pytest.raises(ValueError, match="user bug"):
        loop(elastic.State())
    assert ctx.rendezvous_calls == 1


def test_run_recovers_from_sync_failure(monkeypatch):
    """A peer dying while THIS rank is mid-sync (a cascading second
    failure) retries like a failure inside fn, not a job abort."""
    run_mod = importlib.import_module("horovod_tpu.elastic.run")

    ctx = _FakeCtx()
    fails = iter([HorovodShutdownError("peer died mid-sync")])
    real_sync = ctx.sync_state

    def flaky_sync(blob, commit_count):
        exc = next(fails, None)
        if exc is not None:
            raise exc
        return real_sync(blob, commit_count)

    ctx.sync_state = flaky_sync
    monkeypatch.setattr(run_mod, "_ambient_context", lambda: ctx)

    @elastic.run
    def loop(state):
        return state.step

    assert loop(elastic.State(step=7)) == 7
    assert ctx.rendezvous_calls == 2


def test_run_reraises_rank_dropped(monkeypatch):
    """A rank the launcher shrank past cannot rejoin; elastic.run must
    not burn the retry budget on a rendezvous that can never succeed."""
    run_mod = importlib.import_module("horovod_tpu.elastic.run")

    ctx = _FakeCtx()

    def dropped(timeout=None):
        ctx.rendezvous_calls += 1
        raise RankDroppedError("rank 0 is not a member")

    ctx.rendezvous = dropped
    monkeypatch.setattr(run_mod, "_ambient_context", lambda: ctx)

    @elastic.run
    def loop(state):
        return "unreachable"

    with pytest.raises(RankDroppedError):
        loop(elastic.State())
    assert ctx.rendezvous_calls == 1


def test_commit_raises_on_world_change_after_snapshot(monkeypatch):
    ctx = _FakeCtx()
    flags = iter([True])
    ctx.world_changed = lambda: next(flags, False)
    state = elastic.State(step=0)
    state._ctx = ctx
    state.step = 5
    with pytest.raises(WorkersAvailableException):
        state.commit()
    # the commit itself is durable: restore rewinds to it, not past it
    state.step = 99
    state.restore()
    assert state.step == 5
    assert state.commits == 1


# ---------------------------------------------------------------------------
# Fault-injection registry
# ---------------------------------------------------------------------------


def test_fault_spec_grammar():
    specs = faults.parse_spec(
        "ckpt_write:step=3:rank=0,worker_exit:step=5:rank=2,"
        "enqueue:name=g7:action=raise:count=2:epoch=any"
    )
    assert [s.point for s in specs] == ["ckpt_write", "worker_exit",
                                       "enqueue"]
    assert specs[0].action == "raise" and specs[0].step == 3
    # worker_exit defaults to a hard exit (looks like a crash)
    assert specs[1].action == "exit" and specs[1].code == 43
    assert specs[2].name == "g7" and specs[2].count == 2
    assert specs[2].epoch is None  # 'any' disables the filter


@pytest.mark.parametrize("bad", [
    "ckpt_write:step",          # not key=value
    ":step=1",                  # no point name
    "ckpt_write:wat=1",         # unknown key
    "ckpt_write:action=explode",  # unknown action
])
def test_fault_spec_malformed_raises(bad):
    with pytest.raises(ValueError):
        faults.parse_spec(bad)


def test_maybe_fail_step_and_count(monkeypatch):
    monkeypatch.setenv(faults.SPEC_ENV, "pt:step=2:action=raise:count=1")
    faults.reset()
    faults.maybe_fail("pt")  # visit 1: no fire
    with pytest.raises(faults.InjectedFault):
        faults.maybe_fail("pt")  # visit 2: fires
    faults.maybe_fail("pt")  # count exhausted


def test_maybe_fail_explicit_step_beats_counter(monkeypatch):
    monkeypatch.setenv(faults.SPEC_ENV, "pt:step=7:action=raise")
    faults.reset()
    faults.maybe_fail("pt", step=3)
    with pytest.raises(faults.InjectedFault):
        faults.maybe_fail("pt", step=7)


def test_maybe_fail_rank_filter(monkeypatch):
    monkeypatch.setenv(faults.SPEC_ENV, "pt:rank=1:action=raise")
    faults.reset()
    faults.maybe_fail("pt", rank=0)
    with pytest.raises(faults.InjectedFault):
        faults.maybe_fail("pt", rank=1)


def test_maybe_fail_epoch_filter_suppresses_respawned_rank(monkeypatch):
    """The default epoch=0 filter is what makes chaos runs convergent: a
    respawned worker re-executes the same step at epoch 1 and must NOT
    re-trigger the fault that killed its predecessor."""
    monkeypatch.setenv(faults.SPEC_ENV, "pt:step=1:action=raise")
    monkeypatch.setenv("HVDTPU_ELASTIC_EPOCH", "1")
    faults.reset()
    faults.maybe_fail("pt")  # respawn world: suppressed
    monkeypatch.setenv("HVDTPU_ELASTIC_EPOCH", "0")
    faults.reset()
    with pytest.raises(faults.InjectedFault):
        faults.maybe_fail("pt")


def test_maybe_fail_inactive_is_cheap_noop():
    assert not faults.active()
    faults.maybe_fail("anything")  # no spec, no error


# ---------------------------------------------------------------------------
# Host blacklist
# ---------------------------------------------------------------------------


class _Clock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now


def test_blacklist_exponential_backoff():
    clock = _Clock()
    bl = HostBlacklist(cooldown_base=10.0, cooldown_cap=35.0, clock=clock)
    assert bl.is_admissible("h")
    bl.record_failure("h")
    assert not bl.is_admissible("h")
    assert bl.readmission_in("h") == 10.0
    clock.now = 10.0
    assert bl.is_admissible("h")  # implicit re-admission
    bl.record_failure("h")
    assert bl.readmission_in("h") == 20.0  # doubled
    clock.now = 30.0
    bl.record_failure("h")
    assert bl.readmission_in("h") == 35.0  # capped, not 40
    assert bl.failures("h") == 3
    assert bl.blacklisted() == ["h"]


def test_blacklist_select_prefers_original_then_clean_host():
    clock = _Clock()
    bl = HostBlacklist(cooldown_base=10.0, clock=clock)
    hosts = ["a", "b", "c"]
    assert bl.select(hosts, prefer="b") == "b"
    bl.record_failure("b")
    assert bl.select(hosts, prefer="b") == "a"  # first admissible
    bl.record_failure("a")
    assert bl.select(hosts, prefer="b") == "c"


def test_blacklist_single_host_degenerate_mode():
    """All-blacklisted must pick the soonest-readmitted host, never
    deadlock — on localhost jobs the only host is the only option."""
    clock = _Clock()
    bl = HostBlacklist(cooldown_base=10.0, clock=clock)
    bl.record_failure("only")
    assert bl.select(["only"], prefer="only") == "only"
    bl.record_failure("x")
    bl.record_failure("x")  # x readmits at 30, y at 10
    bl.record_failure("y")
    assert bl.select(["x", "y"]) == "y"


def test_cli_explicit_zero_knobs_not_coerced(monkeypatch):
    """`--max-elastic-retries 0` / `--blacklist-cooldown-secs 0` must
    reach the launcher as 0 (immediate-shrink mode), not be `or`-coerced
    back to the defaults."""
    from horovod_tpu.run import runner

    seen = {}

    def fake_launch(command, np, **kwargs):
        seen.update(kwargs)
        return runner.ElasticJobResult()

    monkeypatch.setattr(runner, "launch_elastic_job", fake_launch)
    rc = runner.main([
        "-np", "2", "--elastic", "--max-elastic-retries", "0",
        "--blacklist-cooldown-secs", "0", "--min-workers", "1",
        "python", "-c", "pass",
    ])
    assert rc == 0
    assert seen["max_retries"] == 0
    assert seen["blacklist_cooldown"] == 0.0


# ---------------------------------------------------------------------------
# ElasticContext against a real KV store (threads as ranks)
# ---------------------------------------------------------------------------


@pytest.fixture()
def kv_world():
    server = KVStoreServer()
    server.start()
    kv = KVStoreClient(f"127.0.0.1:{server.port}", server.secret)

    def mint(epoch, world):
        kv.put("elastic", f"world_{epoch}", pickle.dumps(sorted(world)))
        kv.put("elastic", "epoch", str(epoch).encode())

    def ctx(rank, epoch=0, timeout=20.0):
        return ElasticContext(
            rank, KVStoreClient(f"127.0.0.1:{server.port}", server.secret),
            epoch=epoch, timeout=timeout,
        )

    try:
        yield kv, mint, ctx
    finally:
        server.stop()


def _in_threads(*fns):
    out = [None] * len(fns)
    errs = [None] * len(fns)

    def call(i, fn):
        try:
            out[i] = fn()
        except BaseException as e:  # noqa: BLE001
            errs[i] = e

    threads = [threading.Thread(target=call, args=(i, f), daemon=True)
               for i, f in enumerate(fns)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    return out, errs


def test_context_rendezvous_and_allreduce(kv_world):
    kv, mint, make_ctx = kv_world
    mint(0, [0, 1])
    c0, c1 = make_ctx(0), make_ctx(1)

    def member(ctx):
        ctx.rendezvous()
        return ctx.allreduce(np.full(3, float(ctx.rank + 1)),
                             name="g0", average=False).tolist()

    out, errs = _in_threads(lambda: member(c0), lambda: member(c1))
    assert errs == [None, None]
    assert out == [[3.0] * 3, [3.0] * 3]
    assert c0.size == 2 and c0.world == [0, 1]


def test_context_sync_elects_highest_commit_count(kv_world):
    kv, mint, make_ctx = kv_world
    mint(0, [0, 1])
    c0, c1 = make_ctx(0), make_ctx(1)

    def member(ctx, blob, commits):
        ctx.rendezvous()
        return ctx.sync_state(blob, commits)

    out, errs = _in_threads(
        lambda: member(c0, b"stale", 0),      # a respawned rank
        lambda: member(c1, b"fresh", 5),      # the survivor
    )
    assert errs == [None, None]
    assert out == [b"fresh", b"fresh"]


def test_context_epoch_bump_interrupts_wait(kv_world):
    """A survivor blocked on a dead peer notices the launcher's re-minted
    epoch and raises the recoverable shutdown error."""
    kv, mint, make_ctx = kv_world
    mint(0, [0, 1])
    c0 = make_ctx(0, timeout=30.0)

    def blocked():
        c0.rendezvous(timeout=5.0)
        return c0.allreduce(np.ones(1), name="g0")

    def bump():
        # wait until rank 0 checked in, then re-form the world without
        # rank 1 (it "died" before ever contributing)
        while kv.get("elastic", "ready_0_0") is None:
            time.sleep(0.01)
        mint(1, [0])
        return True

    # rank 1 checks in for rendezvous but never calls allreduce
    c1 = make_ctx(1)
    kv.put("elastic", "ready_0_1", b"1")

    out, errs = _in_threads(blocked, bump)
    assert isinstance(errs[0], HorovodShutdownError)
    assert "re-formed" in str(errs[0])


def test_context_dropped_rank_refuses_to_rejoin(kv_world):
    kv, mint, make_ctx = kv_world
    mint(0, [0, 2])
    c1 = make_ctx(1)
    with pytest.raises(RankDroppedError, match="not a member"):
        c1.rendezvous(timeout=2.0)


def test_context_recovery_requires_fresh_epoch(kv_world):
    """After a world failure, re-rendezvousing into the SAME epoch is
    refused — its keys still hold pre-failure values (stale collective
    contributions, the epoch-start sync blob), so replaying rolled-back
    steps against it would silently diverge from peers."""
    kv, mint, make_ctx = kv_world
    mint(0, [0])
    c0 = make_ctx(0)
    c0.rendezvous()
    c0.notify_world_broken()
    with pytest.raises(HorovodShutdownError, match="fresh epoch"):
        c0.rendezvous(timeout=0.3)
    mint(1, [0])
    assert c0.rendezvous(timeout=5.0) == 1


def test_context_auto_names_agree_after_respawn(kv_world):
    """Collective numbering is per-epoch: a survivor deep into its own
    _seq and a freshly respawned rank (seq 0) must mint the same default
    names after re-rendezvousing, or every unnamed collective deadlocks
    on recovery."""
    kv, mint, make_ctx = kv_world
    mint(0, [0])
    c0 = make_ctx(0)
    c0.rendezvous()
    for _ in range(5):  # survivor's counter runs ahead pre-failure
        c0.allreduce(np.ones(1))
    mint(1, [0, 1])
    c1 = make_ctx(1, epoch=1)  # the replacement, fresh process

    def member(ctx):
        ctx.rendezvous()
        return ctx.allreduce(np.full(2, float(ctx.rank + 1)),
                             average=False).tolist()

    out, errs = _in_threads(lambda: member(c0), lambda: member(c1))
    assert errs == [None, None]
    assert out == [[3.0, 3.0], [3.0, 3.0]]
    assert c0._seq == c1._seq == 1


def test_context_rendezvous_timeout_names_missing_rank(kv_world):
    kv, mint, make_ctx = kv_world
    mint(0, [0, 1])
    c0 = make_ctx(0)
    with pytest.raises(HorovodShutdownError, match="rank 1"):
        c0.rendezvous(timeout=0.5)


# ---------------------------------------------------------------------------
# End-to-end chaos (real processes through the elastic launcher)
# ---------------------------------------------------------------------------


def _chaos_train(total_steps=8):
    import numpy as np  # noqa: PLC0415

    import horovod_tpu.elastic as elastic  # noqa: PLC0415

    ctx = elastic.context()
    state = elastic.State(w=np.zeros(4, dtype=np.float64), step=0)

    @elastic.run
    def loop(state):
        while state.step < total_steps:
            grad = np.full(4, float(state.step + 1) * (ctx.rank + 1))
            state.w = state.w - 0.1 * ctx.allreduce(
                grad, name=f"g{state.step}")
            state.step += 1
            state.commit()
        return state.w.tolist(), state.step

    return loop(state)


def _raising_fn():
    raise ValueError("deliberate user bug")


@pytest.mark.multiprocess
def test_elastic_e2e_recovery_matches_no_fault_run():
    """ISSUE 1 acceptance: 4-process elastic.run job; the fault spec
    kills rank 2 mid-training; the job recovers via rollback + respawn
    and finishes with state equal to a no-fault run; a second faulted
    run produces the identical recovery trace."""
    fault_env = {"HVDTPU_FAULT_SPEC": "worker_exit:step=5:rank=2",
                 "JAX_PLATFORMS": "cpu"}
    clean_env = {"JAX_PLATFORMS": "cpu"}

    clean, clean_job = elastic.launch(
        _chaos_train, np=4, env=clean_env, timeout=120)
    faulted, job = elastic.launch(
        _chaos_train, np=4, env=fault_env, max_retries=3, timeout=120)
    faulted2, job2 = elastic.launch(
        _chaos_train, np=4, env=fault_env, max_retries=3, timeout=120)

    # recovered state == no-fault state, on every rank
    assert faulted == clean
    assert sorted(faulted) == [0, 1, 2, 3]
    # the failure was actually injected and recovered from
    events = [e[0] for e in job.trace]
    assert events.count("failure") == 1
    assert events.count("respawn") == 1
    assert ("blacklist", "localhost", 1) in job.trace
    assert job.epoch == 1 and job.world == [0, 1, 2, 3]
    # determinism: identical spec -> identical recovery trace
    assert job2.trace == job.trace
    # and the no-fault run never recovered anything
    assert [e[0] for e in clean_job.trace] == ["spawn"] * 4


@pytest.mark.multiprocess
def test_elastic_shrink_when_budget_spent():
    """With the respawn budget at 0 and min_workers below np, losing a
    rank shrinks the world instead of failing the job."""
    env = {"HVDTPU_FAULT_SPEC": "worker_exit:step=3:rank=1",
           "JAX_PLATFORMS": "cpu"}
    results, job = elastic.launch(
        _chaos_train, np=3, env=env, min_workers=2, max_retries=0,
        timeout=120)
    assert job.world == [0, 2]
    assert sorted(results) == [0, 2]
    events = [e[0] for e in job.trace]
    assert "shrink" in events and "respawn" not in events
    # the survivors completed all steps in the reduced world
    assert all(results[r][1] == 8 for r in results)


def _staggered_finish_crash_run():
    import os  # noqa: PLC0415
    import time  # noqa: PLC0415

    import horovod_tpu.elastic as elastic  # noqa: PLC0415

    ctx = elastic.context()
    if ctx.rank == 1:
        time.sleep(3.0)
        os._exit(9)
    if ctx.rank == 2:
        time.sleep(6.0)
    return ctx.rank


@pytest.mark.multiprocess
def test_elastic_min_workers_counts_finished_ranks():
    """min_workers counts CONTRIBUTING ranks (alive + already finished):
    an early finisher must not make a later crash abort a job that will
    still deliver min_workers results."""
    results, job = elastic.launch(
        _staggered_finish_crash_run, np=3, min_workers=2, max_retries=0,
        env={"JAX_PLATFORMS": "cpu"}, timeout=60)
    assert job.world == [0, 2]
    assert sorted(results) == [0, 2]
    events = [e[0] for e in job.trace]
    assert "shrink" in events and "respawn" not in events


def _peers_finish_then_rank0_dies():
    import os  # noqa: PLC0415
    import time  # noqa: PLC0415

    import horovod_tpu.elastic as elastic  # noqa: PLC0415

    ctx = elastic.context()
    if ctx.rank == 0:
        time.sleep(3.0)  # peers return (and exit 0) well before this
        os._exit(9)
    return ctx.rank


@pytest.mark.multiprocess
def test_elastic_no_solo_respawn_after_peers_finished():
    """A rank dying after every peer already exited 0 must NOT be
    respawned into a world of one — the replacement would have no
    survivor to sync from and would retrain alone from initial state.
    The job finishes with the finished ranks' results instead."""
    results, job = elastic.launch(
        _peers_finish_then_rank0_dies, np=3, min_workers=1,
        max_retries=3, env={"JAX_PLATFORMS": "cpu"}, timeout=60)
    assert job.world == [1, 2]
    assert sorted(results) == [1, 2]
    events = [e[0] for e in job.trace]
    assert "respawn" not in events
    assert "shrink" in events


@pytest.mark.multiprocess
def test_elastic_user_exception_aborts_not_respawns():
    """A user exception is a correctness error: the launcher surfaces the
    traceback instead of burning the respawn budget on it."""
    with pytest.raises(RuntimeError, match="deliberate user bug"):
        elastic.launch(_raising_fn, np=2,
                       env={"JAX_PLATFORMS": "cpu"}, timeout=60)


# ---------------------------------------------------------------------------
# Progress beat: deadlocked training threads vs. long compile phases
# (ISSUE 2 acceptance; closes the ROADMAP heartbeat-scope open item)
# ---------------------------------------------------------------------------


def _compile_then_train():
    import time  # noqa: PLC0415

    import horovod_tpu.elastic as elastic  # noqa: PLC0415
    import horovod_tpu.obs as obs  # noqa: PLC0415

    ctx = elastic.context()
    if ctx.rank == 1:
        # A legitimately long non-collective phase, well past the steady
        # budget the deadlock test kills with — the declared phase is
        # what must keep this rank alive.
        obs.set_phase("compile")
        time.sleep(6.0)
    return _chaos_train(total_steps=4)


@pytest.mark.multiprocess
def test_elastic_deadlock_detected_by_progress_beat():
    """ISSUE 2 acceptance, part 1: a fault-injected training-thread
    deadlock (action=hang — the KV heartbeat thread keeps beating, so
    the process-liveness rule can never fire) is detected via
    progress-beat staleness; the rank is killed and respawned and the
    job converges to the no-fault result.  The peers' collective timeout
    is set far above the job runtime, so recovery happening at all
    proves the launcher acted on the beat — no peer burned its retry
    budget discovering the hang."""
    clean, _ = elastic.launch(
        _chaos_train, np=4, env={"JAX_PLATFORMS": "cpu"}, timeout=120)
    env = {
        "HVDTPU_FAULT_SPEC": "worker_exit:step=5:rank=2:action=hang",
        "JAX_PLATFORMS": "cpu",
        # Peer collective waits massively outlive the test: timeouts
        # CANNOT be what rescues the job.
        "HVDTPU_ELASTIC_TIMEOUT": "600",
    }
    faulted, job = elastic.launch(
        _chaos_train, np=4, env=env, max_retries=3,
        progress_timeout=2.0, timeout=120)

    assert faulted == clean
    assert sorted(faulted) == [0, 1, 2, 3]
    events = [e[0] for e in job.trace]
    assert ("progress_lost", 2, 0) in job.trace
    assert events.count("respawn") == 1
    assert job.world == [0, 1, 2, 3]
    # the beat thread never went stale — only the training thread did
    assert "heartbeat_lost" not in events


@pytest.mark.multiprocess
def test_elastic_long_compile_phase_not_killed():
    """ISSUE 2 acceptance, part 2 (the workload-aware half): a rank
    sitting in a declared compile phase for 3x the steady budget is NOT
    killed while under the grace window — long XLA compiles are
    legitimate, and shooting them is how flapping starts."""
    results, job = elastic.launch(
        _compile_then_train, np=3, env={"JAX_PLATFORMS": "cpu"},
        progress_timeout=2.0, progress_grace=60.0, timeout=120)
    assert sorted(results) == [0, 1, 2]
    events = [e[0] for e in job.trace]
    assert "progress_lost" not in events
    assert "respawn" not in events
    assert "heartbeat_lost" not in events
    assert all(results[r][1] == 4 for r in results)
