"""horovod.torch-compatible interop frontend (reference surface:
test/test_torch.py — op correctness, autograd Functions, optimizer wrap,
state broadcast; here single-process identities in-process and real
2-process semantics under the launcher in test_multiprocess.py)."""

import numpy as np
import pytest
import torch

import horovod_tpu.interop.torch as hvd


@pytest.fixture(autouse=True)
def _init():
    # conftest's session fixture owns the framework lifecycle; don't
    # shutdown here or later test files lose the initialized topology.
    hvd.init()
    yield


def test_allreduce_identity_single_process():
    x = torch.arange(6, dtype=torch.float32).reshape(2, 3)
    out = hvd.allreduce(x)
    assert torch.allclose(out, x)
    assert isinstance(out, torch.Tensor)


def test_allreduce_inplace_writes_back():
    x = torch.ones(4)
    out = hvd.allreduce_(x, op=hvd.Sum)
    assert out is x
    assert torch.allclose(x, torch.ones(4))


def test_allreduce_bf16_roundtrip():
    x = torch.ones(8, dtype=torch.bfloat16)
    out = hvd.allreduce(x, op=hvd.Sum)
    assert out.dtype == torch.bfloat16
    assert torch.allclose(out.float(), torch.ones(8))


def test_gpu_tensor_rejected():
    x = torch.ones(2)
    fake = x.to("meta")
    with pytest.raises(ValueError, match="host \\(CPU\\) tensors"):
        hvd.allreduce(fake)


def test_allreduce_grad_is_allreduced():
    x = torch.randn(3, requires_grad=True)
    y = hvd.allreduce(x, op=hvd.Sum)
    y.sum().backward()
    # single process: backward allreduce is identity -> grad of sum is ones
    assert torch.allclose(x.grad, torch.ones(3))


def test_allgather_and_grad():
    x = torch.randn(2, 3, requires_grad=True)
    g = hvd.allgather(x)
    assert g.shape == (2, 3)
    g.sum().backward()
    assert torch.allclose(x.grad, torch.ones(2, 3))


def test_broadcast_grad_root():
    x = torch.randn(4, requires_grad=True)
    y = hvd.broadcast(x, root_rank=0)
    y.sum().backward()
    # rank 0 IS the root in a single-process world: grads arrive summed
    assert torch.allclose(x.grad, torch.ones(4))


def test_poll_synchronize():
    h = hvd.allreduce_async(torch.ones(2))
    out = hvd.synchronize(h)
    assert hvd.poll(h)
    assert torch.allclose(out, torch.ones(2))


def test_distributed_optimizer_step():
    model = torch.nn.Linear(3, 2)
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters(),
    )
    before = model.weight.detach().clone()
    loss = model(torch.ones(1, 3)).sum()
    loss.backward()
    opt.step()
    assert not torch.allclose(model.weight, before)
    opt.zero_grad()


def test_zero_grad_with_inflight_raises():
    model = torch.nn.Linear(2, 1)
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters(),
    )
    model(torch.ones(1, 2)).sum().backward()
    # handles now outstanding (hooks fired, no step/synchronize yet)
    with pytest.raises(AssertionError, match="in flight"):
        opt.zero_grad()
    opt.synchronize()
    opt.zero_grad()


def test_duplicate_parameter_names_rejected():
    model = torch.nn.Linear(2, 1)
    params = list(model.parameters())
    with pytest.raises(ValueError, match="unique"):
        hvd.DistributedOptimizer(
            torch.optim.SGD(params, lr=0.1),
            named_parameters=[("p", params[0]), ("p", params[1])],
        )


def test_broadcast_parameters_state_dict():
    model = torch.nn.Linear(3, 2)
    sd_before = {k: v.clone() for k, v in model.state_dict().items()}
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    for k, v in model.state_dict().items():
        assert torch.allclose(v, sd_before[k])


def test_broadcast_optimizer_state():
    model = torch.nn.Linear(3, 2)
    opt = torch.optim.Adam(model.parameters(), lr=1e-3)
    model(torch.ones(1, 3)).sum().backward()
    opt.step()
    hvd.broadcast_optimizer_state(opt, root_rank=0)  # no-op world of 1
    assert opt.state_dict()["state"]


def test_sync_batch_norm_single_process_matches_local_bn():
    torch.manual_seed(0)
    x = torch.randn(8, 4, 5, 5, requires_grad=True)
    x2 = x.detach().clone().requires_grad_(True)
    sbn = hvd.SyncBatchNorm(4)
    bn = torch.nn.BatchNorm2d(4)
    bn.load_state_dict(sbn.state_dict())
    # world of 1: must match plain BN exactly (fallback path)
    out_s, out_b = sbn(x), bn(x2)
    assert torch.allclose(out_s, out_b, atol=1e-6)
    out_s.sum().backward()
    out_b.sum().backward()
    assert torch.allclose(x.grad, x2.grad, atol=1e-6)


def test_sync_batch_norm_fn_gradcheck_single():
    """The custom Function (stats via engine allreduce) must match plain
    batch norm numerics in a world of one, forward and backward."""
    torch.manual_seed(1)
    x = torch.randn(6, 3, requires_grad=True, dtype=torch.float64)
    w = torch.ones(3, requires_grad=True, dtype=torch.float64)
    b = torch.zeros(3, requires_grad=True, dtype=torch.float64)
    from horovod_tpu.interop.torch import _SyncBatchNormFn

    out, mean, var = _SyncBatchNormFn.apply(x, w, b, 1e-5)
    ref = torch.nn.functional.batch_norm(
        x, None, None, w, b, training=True, eps=1e-5
    )
    assert torch.allclose(out, ref, atol=1e-8)
    g = torch.randn_like(out)
    out.backward(g)
    x2 = x.detach().clone().requires_grad_(True)
    w2 = w.detach().clone().requires_grad_(True)
    b2 = b.detach().clone().requires_grad_(True)
    ref2 = torch.nn.functional.batch_norm(
        x2, None, None, w2, b2, training=True, eps=1e-5
    )
    ref2.backward(g)
    assert torch.allclose(x.grad, x2.grad, atol=1e-7)
    assert torch.allclose(w.grad, w2.grad, atol=1e-7)
    assert torch.allclose(b.grad, b2.grad, atol=1e-7)


def test_adasum_optimizer_single_process_delta_step():
    """op=Adasum selects the delta-reducing optimizer (reference factory
    torch/__init__.py:443-449).  World 1: Adasum of one delta is the delta
    itself, so the wrapped optimizer's step applies exactly."""
    w = torch.nn.Parameter(torch.tensor([1.0, 2.0]))
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD([w], lr=0.1),
        named_parameters=[("w", w)],
        op=hvd.Adasum,
    )
    assert type(opt).__name__ == "_DistributedAdasumOptimizer"
    loss = (w * torch.tensor([1.0, 2.0])).sum()
    loss.backward()
    opt.step()
    # delta = -lr * grad = [-0.1, -0.2]
    assert torch.allclose(w.detach(), torch.tensor([0.9, 1.8]), atol=1e-6)
    opt.zero_grad()


def test_adasum_optimizer_zero_grad_race_guard():
    w = torch.nn.Parameter(torch.ones(2))
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD([w], lr=0.1), named_parameters=[("w", w)],
        op=hvd.Adasum,
    )
    w.sum().backward()
    with pytest.raises(AssertionError, match="in flight"):
        opt.zero_grad()
    opt.step()


def test_allreduce_dtype_dims_grid():
    """Reference test_torch.py pattern: allreduce across a dtype x
    dimensionality grid preserves dtype, shape, and values (world 1:
    identity for Average, identity for Sum)."""
    dtypes = [torch.float32, torch.float64, torch.float16, torch.bfloat16,
              torch.int32, torch.int64, torch.uint8]
    for dt in dtypes:
        for dim in (1, 2, 3):
            shape = (2,) * dim
            x = (torch.arange(2 ** dim) % 3).reshape(shape).to(dt)
            op = hvd.Sum if not dt.is_floating_point else hvd.Average
            out = hvd.allreduce(x, op=op, name=f"grid.{dt}.{dim}")
            assert out.dtype == dt, (dt, dim)
            assert out.shape == shape, (dt, dim)
            assert torch.equal(out.to(torch.float64),
                               x.to(torch.float64)), (dt, dim)


def test_allgather_ragged_dim0_grid():
    """Allgather across element ranks; world 1 returns the input
    (reference test_torch.py test_horovod_allgather*)."""
    for dim in (1, 2, 3):
        x = torch.ones((3,) + (2,) * (dim - 1))
        out = hvd.allgather(x, name=f"ag.{dim}")
        assert torch.equal(out, x)


def test_skip_synchronize_clip_pattern():
    """synchronize -> clip -> step-without-resync (reference
    torch/__init__.py:184-202), plus the step-after-synchronize warning."""
    import warnings

    w = torch.nn.Parameter(torch.tensor([3.0, 4.0]))
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD([w], lr=1.0), named_parameters=[("w", w)]
    )
    (w * torch.tensor([30.0, 40.0])).sum().backward()
    opt.synchronize()
    torch.nn.utils.clip_grad_norm_([w], max_norm=5.0)
    with opt.skip_synchronize():
        opt.step()
    # clipped grad = [30,40]/50*5 = [3,4]; w = [3,4] - 1.0*[3,4] = 0
    assert torch.allclose(w.detach(), torch.zeros(2), atol=1e-6)

    # step() after synchronize() WITHOUT the context warns
    opt.zero_grad()
    (w.sum()).backward()
    opt.synchronize()
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        opt.step()
    assert any("skip_synchronize" in str(x.message) for x in rec)

    # Adasum optimizer refuses the context (reference :359-361)
    w2 = torch.nn.Parameter(torch.ones(2))
    aopt = hvd.DistributedOptimizer(
        torch.optim.SGD([w2], lr=0.1), named_parameters=[("w2", w2)],
        op=hvd.Adasum,
    )
    with pytest.raises(AssertionError, match="not supported"):
        with aopt.skip_synchronize():
            pass


def test_allreduce_average_spelling_compat():
    """The 0.19-era positional/keyword ``average`` bool is accepted on all
    four allreduce spellings, and conflicts with op= are rejected
    (reference torch/mpi_ops.py:94-129 + get_average_backwards_
    compatibility_fun)."""
    x = torch.ones(4)
    out = hvd.allreduce(x, True)  # positional average
    assert torch.allclose(out, x)
    out = hvd.allreduce(x, average=False)  # sum at world 1
    assert torch.allclose(out, x)
    y = torch.ones(3)
    hvd.synchronize(hvd.allreduce_async(y, average=False))
    hvd.allreduce_(y, average=True)
    hvd.synchronize(hvd.allreduce_async_(y, average=False))
    with pytest.raises(ValueError, match="op parameter supersedes"):
        hvd.allreduce(x, average=True, op=hvd.Sum)


def test_allreduce_compression_kwarg():
    """Sync allreduce accepts compression= like the reference
    (torch/mpi_ops.py:173) and round-trips the dtype."""
    x = torch.full((8,), 3.0)
    out = hvd.allreduce(x, op=hvd.Sum, compression=hvd.Compression.fp16)
    assert out.dtype == torch.float32
    assert torch.allclose(out, x)


def test_compression_fp16_roundtrip():
    t = torch.randn(8)
    wire, ctx = hvd.Compression.fp16.compress(t)
    assert wire.dtype == torch.float16
    out = hvd.Compression.fp16.decompress(wire, ctx)
    assert out.dtype == torch.float32
    assert torch.allclose(out, t, atol=1e-3)
