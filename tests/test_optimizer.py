"""DistributedOptimizer / grad-transform tests.

Models the reference's DistributedOptimizer coverage in test/test_torch.py
(optimizer produces identical updates across ranks from rank-local grads)
and the Adasum numerics tests (test/test_adasum_pytorch.py — compares the
in-framework VHDD result against a NumPy reference of the projection
formula)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.optim import DistributedGradientTransform, DistributedOptimizer
from horovod_tpu.ops.adasum import adasum_allreduce, adasum_combine

N = 8


def per_rank(fn, *stacked_args):
    mesh = hvd.mesh("flat")
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=tuple(P(hvd.DP_AXIS) for _ in stacked_args),
        out_specs=P(hvd.DP_AXIS),
        check_vma=False,
    )(*stacked_args)


def test_grad_transform_averages():
    grads = jnp.asarray(np.random.RandomState(0).randn(N, 4), jnp.float32)
    tx = DistributedGradientTransform(hvd.Average)

    def fn(g):
        out, _ = tx.update({"w": g[0]}, tx.init(None))
        return out["w"][None]

    out = per_rank(fn, grads)
    np.testing.assert_allclose(out[0], jnp.mean(grads, axis=0), rtol=1e-5)
    np.testing.assert_allclose(out[5], jnp.mean(grads, axis=0), rtol=1e-5)


def test_grad_transform_predivide():
    grads = jnp.asarray(np.random.RandomState(1).randn(N, 4), jnp.float32)
    tx = DistributedGradientTransform(hvd.Average, gradient_predivide_factor=2.0)

    def fn(g):
        out, _ = tx.update((g[0],), tx.init(None))
        return out[0][None]

    out = per_rank(fn, grads)
    np.testing.assert_allclose(out[0], jnp.mean(grads, axis=0), rtol=1e-5)


def test_grad_transform_bf16_compression():
    grads = jnp.asarray(np.random.RandomState(2).randn(N, 16), jnp.float32)
    tx = DistributedGradientTransform(
        hvd.Average, compression=hvd.Compression.bf16
    )

    def fn(g):
        out, _ = tx.update((g[0],), tx.init(None))
        return out[0][None]

    out = per_rank(fn, grads)
    assert out.dtype == jnp.float32  # decompressed back
    np.testing.assert_allclose(
        out[0], jnp.mean(grads, axis=0), rtol=3e-2, atol=3e-2
    )


def test_distributed_optimizer_identical_updates():
    """Every rank must apply the same update from different local grads —
    the core DistributedOptimizer contract."""
    rng = np.random.RandomState(3)
    params = {"w": jnp.asarray(rng.randn(4), jnp.float32)}
    grads = jnp.asarray(rng.randn(N, 4), jnp.float32)
    tx = DistributedOptimizer(optax.sgd(0.1))

    def fn(g):
        state = tx.init(params)
        updates, _ = tx.update({"w": g[0]}, state, params)
        new = optax.apply_updates(params, updates)
        return new["w"][None]

    out = per_rank(fn, grads)
    expected = params["w"] - 0.1 * jnp.mean(grads, axis=0)
    for r in range(N):
        np.testing.assert_allclose(out[r], expected, rtol=1e-5)


def test_backward_passes_per_step_accumulates():
    """Reference semantics (torch/__init__.py:101-126): k backward passes
    per optimizer step; the wire carries the accumulated grads once."""
    params = {"w": jnp.zeros(2, jnp.float32)}
    tx = DistributedOptimizer(optax.sgd(1.0), backward_passes_per_step=2)
    g1 = jnp.asarray(np.full((N, 2), 1.0), jnp.float32)
    g2 = jnp.asarray(np.full((N, 2), 3.0), jnp.float32)

    def fn(a, b):
        state = tx.init(params)
        u1, state = tx.update({"w": a[0]}, state, params)
        p1 = optax.apply_updates(params, u1)
        u2, state = tx.update({"w": b[0]}, state, p1)
        p2 = optax.apply_updates(p1, u2)
        return p2["w"][None]

    out = per_rank(fn, g1, g2)
    # MultiSteps averages the k microbatch grads: (1+3)/2 = 2 -> sgd(1.0)
    np.testing.assert_allclose(out[0], np.full(2, -2.0), rtol=1e-5)


# ---------------------------------------------------------------------------
# Adasum numerics (reference: test/test_adasum_pytorch.py strategy — NumPy
# reference model of the recursive projection formula)
# ---------------------------------------------------------------------------


def numpy_adasum(vectors):
    """Recursive binary-tree reference of adasum.h:167-299."""
    vecs = [np.asarray(v, np.float64) for v in vectors]
    n = len(vecs)
    if n == 1:
        return vecs[0]
    half = n // 2
    a = numpy_adasum(vecs[:half])
    b = numpy_adasum(vecs[half:])
    dot = float(np.dot(a, b))
    na2 = float(np.dot(a, a))
    nb2 = float(np.dot(b, b))
    ac = 1.0 - dot / (2.0 * max(na2, 1e-30))
    bc = 1.0 - dot / (2.0 * max(nb2, 1e-30))
    return ac * a + bc * b


def test_adasum_combine_limits():
    """Orthogonal -> sum; identical -> average (the defining property)."""
    a = jnp.asarray([1.0, 0.0])
    b = jnp.asarray([0.0, 1.0])
    out = adasum_combine(a, b, jnp.dot(a, b), jnp.dot(a, a), jnp.dot(b, b))
    np.testing.assert_allclose(out, [1.0, 1.0], rtol=1e-6)
    c = jnp.asarray([2.0, 2.0])
    out2 = adasum_combine(c, c, jnp.dot(c, c), jnp.dot(c, c), jnp.dot(c, c))
    np.testing.assert_allclose(out2, c, rtol=1e-6)


def test_adasum_allreduce_matches_numpy_reference():
    rng = np.random.RandomState(7)
    vecs = rng.randn(N, 6).astype(np.float32)
    out = per_rank(
        lambda v: adasum_allreduce(v[0])[None], jnp.asarray(vecs)
    )
    expected = numpy_adasum(list(vecs))
    for r in (0, 3, 7):
        np.testing.assert_allclose(out[r], expected, rtol=1e-4, atol=1e-4)


def test_adasum_via_allreduce_op():
    rng = np.random.RandomState(8)
    vecs = rng.randn(N, 2, 3).astype(np.float32)
    out = per_rank(
        lambda v: hvd.allreduce(v[0], op=hvd.Adasum)[None], jnp.asarray(vecs)
    )
    expected = numpy_adasum([v.ravel() for v in vecs]).reshape(2, 3)
    np.testing.assert_allclose(out[0], expected, rtol=1e-4, atol=1e-4)


def test_broadcast_parameters_single_process_identity():
    params = {"a": jnp.ones(3), "b": {"c": jnp.zeros(2)}}
    out = hvd.broadcast_parameters(params, root_rank=0)
    assert out is params  # single process: no-op


def test_compression_roundtrip():
    x = jnp.asarray(np.random.RandomState(9).randn(32), jnp.float32)
    comp, ctx = hvd.Compression.bf16.compress(x)
    assert comp.dtype == jnp.bfloat16 and ctx == jnp.float32
    back = hvd.Compression.bf16.decompress(comp, ctx)
    assert back.dtype == jnp.float32
    np.testing.assert_allclose(back, x, rtol=1e-2, atol=1e-2)
    # ints pass through untouched
    xi = jnp.arange(4)
    ci, ctxi = hvd.Compression.bf16.compress(xi)
    assert ci.dtype == xi.dtype and ctxi is None
