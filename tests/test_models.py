"""Model zoo tests: shapes, dtypes, trainability, SyncBatchNorm variant."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from horovod_tpu import models


def test_convnet_and_mlp_shapes():
    x = jnp.ones((4, 28, 28, 1))
    for model in (models.ConvNet(), models.MLP()):
        params = model.init(jax.random.PRNGKey(0), x)
        out = model.apply(params, x)
        assert out.shape == (4, 10)


def test_resnet18_forward_backward():
    model = models.ResNet18(num_classes=10)
    x = jnp.ones((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=True)

    def loss_fn(p):
        logits, _ = model.apply(
            {"params": p, "batch_stats": variables["batch_stats"]},
            x, train=True, mutable=["batch_stats"],
        )
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, jnp.zeros(2, jnp.int32)
        ).mean()

    loss, grads = jax.value_and_grad(loss_fn)(variables["params"])
    assert np.isfinite(float(loss))
    norms = jax.tree_util.tree_map(lambda g: float(jnp.abs(g).max()), grads)
    assert any(v > 0 for v in jax.tree_util.tree_leaves(norms))


def test_resnet50_structure():
    model = models.ResNet50(num_classes=1000)
    x = jnp.ones((1, 64, 64, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 1000)
    assert out.dtype == jnp.float32  # head in fp32 even under bf16 compute
    n_params = sum(
        int(np.prod(p.shape))
        for p in jax.tree_util.tree_leaves(variables["params"])
    )
    # canonical resnet50 parameter count ~25.5M
    assert 25_000_000 < n_params < 26_000_000, n_params


def test_resnet_bf16_compute_fp32_params():
    model = models.ResNet18(num_classes=10, compute_dtype=jnp.bfloat16)
    x = jnp.ones((1, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    for leaf in jax.tree_util.tree_leaves(variables["params"]):
        assert leaf.dtype == jnp.float32


def test_resnet_s2d_stem_matches_shapes():
    """The space-to-depth stem (MLPerf TPU recipe) is architecturally
    equivalent: same output shape, same downstream stage geometry."""
    x = jnp.ones((2, 64, 64, 3))
    base = models.ResNet18(num_classes=10)
    s2d = models.ResNet18(num_classes=10, s2d_stem=True)
    vb = base.init(jax.random.PRNGKey(0), x, train=False)
    vs = s2d.init(jax.random.PRNGKey(0), x, train=False)
    assert base.apply(vb, x, train=False).shape == (2, 10)
    assert s2d.apply(vs, x, train=False).shape == (2, 10)
    # stem conv consumes the folded 12-channel input at stride 1
    assert vs["params"]["conv_init"]["kernel"].shape == (4, 4, 12, 64)
    # every non-stem layer is unchanged
    for k in vb["params"]:
        if k != "conv_init":
            assert (
                jax.tree_util.tree_map(
                    lambda p: p.shape, vb["params"][k]
                )
                == jax.tree_util.tree_map(
                    lambda p: p.shape, vs["params"][k]
                )
            ), k


def test_resnet_fp8_activation_storage_trains():
    """act_store_dtype=float8_e4m3fn: forward/backward stay finite and
    produce nonzero grads — the lossy storage is numerically viable."""
    model = models.ResNet18(
        num_classes=10,
        compute_dtype=jnp.bfloat16,
        act_store_dtype=jnp.float8_e4m3fn,
    )
    x = jnp.ones((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=True)

    def loss_fn(p):
        logits, _ = model.apply(
            {"params": p, "batch_stats": variables["batch_stats"]},
            x, train=True, mutable=["batch_stats"],
        )
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, jnp.zeros(2, jnp.int32)
        ).mean()

    loss, grads = jax.value_and_grad(loss_fn)(variables["params"])
    assert np.isfinite(float(loss))
    assert any(
        float(jnp.abs(g).max()) > 0
        for g in jax.tree_util.tree_leaves(grads)
    )


def test_graft_entry_single_device():
    import __graft_entry__ as g

    fn, example = g.entry()
    out = jax.jit(fn)(*example)
    assert out.shape == (8, 1000)


@pytest.mark.multiprocess
def test_graft_entry_dryrun_multichip():
    import __graft_entry__ as g

    g.dryrun_multichip(8)


def test_gpt_remat_matches_no_remat():
    """cfg.remat=True (dots-saveable block remat) is a pure memory/compute
    trade: outputs AND gradients must match the non-remat model exactly on
    the same params."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from horovod_tpu.models.transformer import gpt

    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 1024, size=(2, 32)), jnp.int32
    )
    base = gpt("nano", attention_impl="reference")
    rematted = gpt("nano", attention_impl="reference", remat=True)
    params = base.init(jax.random.PRNGKey(0), tokens)

    def loss_fn(model):
        def f(p):
            logits = model.apply(p, tokens)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, tokens
            ).mean()
        return f

    l0, g0 = jax.value_and_grad(loss_fn(base))(params)
    l1, g1 = jax.value_and_grad(loss_fn(rematted))(params)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
        g0, g1,
    )


def test_vgg16_forward_backward():
    """VGG-16 (reference headline family, benchmarks.rst:13-14): forward
    shape, fp32 logits from bf16 compute, finite grads; no BN state."""
    import jax
    import jax.numpy as jnp
    import optax

    from horovod_tpu.models import VGG16

    model = VGG16(num_classes=10)
    x = jnp.ones((2, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    assert "batch_stats" not in variables
    logits = model.apply(variables, x, train=True)
    assert logits.shape == (2, 10) and logits.dtype == jnp.float32

    def loss_fn(p):
        out = model.apply({"params": p}, x, train=True)
        return optax.softmax_cross_entropy_with_integer_labels(
            out, jnp.asarray([1, 2])
        ).mean()

    loss, grads = jax.value_and_grad(loss_fn)(variables["params"])
    assert np.isfinite(float(loss))
    leaves = jax.tree.leaves(grads)
    assert leaves and all(np.all(np.isfinite(g)) for g in leaves)


def test_inception_v3_forward_backward():
    """Inception V3 (the reference's top headline model): canonical branch
    concatenation geometry trains on a small input; BN stats mutate."""
    import jax
    import jax.numpy as jnp
    import optax

    from horovod_tpu.models import InceptionV3

    model = InceptionV3(num_classes=10)
    x = jnp.ones((2, 96, 96, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    assert "batch_stats" in variables

    def loss_fn(p):
        out, mutated = model.apply(
            {"params": p, "batch_stats": variables["batch_stats"]},
            x, train=True, mutable=["batch_stats"],
        )
        return optax.softmax_cross_entropy_with_integer_labels(
            out, jnp.asarray([1, 2])
        ).mean(), mutated["batch_stats"]

    (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
        variables["params"]
    )
    assert np.isfinite(float(loss))
    assert jax.tree.leaves(new_stats)
    assert all(np.all(np.isfinite(g)) for g in jax.tree.leaves(grads))
    # eval mode runs with frozen stats
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)


def test_gpt_gqa_trains():
    """num_kv_heads < num_heads (GQA): model builds, the qkv projection
    shrinks accordingly, flash and reference impls agree."""
    import jax
    import jax.numpy as jnp
    import optax

    from horovod_tpu.models.transformer import gpt

    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 1024, size=(2, 32)), jnp.int32
    )
    import pytest
    with pytest.raises(ValueError, match="multiple of num_kv_heads"):
        gpt("nano", num_kv_heads=3)  # 4 % 3 != 0 -> fail at config time
    with pytest.raises(ValueError, match="multiple of num_kv_heads"):
        gpt("nano", num_kv_heads=0)
    flash = gpt("nano", num_kv_heads=2, dtype=jnp.float32)  # 4 q, 2 kv heads
    ref = gpt("nano", num_kv_heads=2, dtype=jnp.float32,
              attention_impl="reference")
    params = flash.init(jax.random.PRNGKey(0), tokens)
    # qkv projection: emb + 2 * kv_dim = 128 + 2*64 = 256 (not 3*128)
    assert params["params"]["block0"]["qkv"]["kernel"].shape == (128, 256)

    def loss(model, p):
        logits = model.apply(p, tokens)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, tokens
        ).mean()

    lf, gf = jax.value_and_grad(lambda p: loss(flash, p))(params)
    lr, gr = jax.value_and_grad(lambda p: loss(ref, p))(params)
    np.testing.assert_allclose(float(lf), float(lr), rtol=5e-5, atol=5e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4),
        gf, gr,
    )


def test_transformer_position_guards():
    """Layout misuse fails loudly: zigzag without explicit positions
    raises at trace time; an out-of-range learned position poisons the
    output with NaN instead of silently reusing the clamped last row."""
    from horovod_tpu.models.transformer import gpt

    tokens = jnp.zeros((1, 8), jnp.int32)
    zz = gpt("nano", attention_impl="zigzag", sp_axis="sp")
    with pytest.raises(ValueError, match="requires explicit positions"):
        zz.init(jax.random.PRNGKey(0), tokens)

    m = gpt("nano", attention_impl="reference", dtype=jnp.float32)
    params = m.init(jax.random.PRNGKey(0), tokens)
    bad_positions = jnp.arange(8) + 255  # nano max_len=256 -> 255..262
    out = m.apply(params, tokens, positions=bad_positions)
    assert not np.isfinite(np.asarray(out)).all(), \
        "out-of-range position did not poison the output"
