"""Model zoo tests: shapes, dtypes, trainability, SyncBatchNorm variant."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from horovod_tpu import models


def test_convnet_and_mlp_shapes():
    x = jnp.ones((4, 28, 28, 1))
    for model in (models.ConvNet(), models.MLP()):
        params = model.init(jax.random.PRNGKey(0), x)
        out = model.apply(params, x)
        assert out.shape == (4, 10)


def test_resnet18_forward_backward():
    model = models.ResNet18(num_classes=10)
    x = jnp.ones((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=True)

    def loss_fn(p):
        logits, _ = model.apply(
            {"params": p, "batch_stats": variables["batch_stats"]},
            x, train=True, mutable=["batch_stats"],
        )
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, jnp.zeros(2, jnp.int32)
        ).mean()

    loss, grads = jax.value_and_grad(loss_fn)(variables["params"])
    assert np.isfinite(float(loss))
    norms = jax.tree_util.tree_map(lambda g: float(jnp.abs(g).max()), grads)
    assert any(v > 0 for v in jax.tree_util.tree_leaves(norms))


def test_resnet50_structure():
    model = models.ResNet50(num_classes=1000)
    x = jnp.ones((1, 64, 64, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 1000)
    assert out.dtype == jnp.float32  # head in fp32 even under bf16 compute
    n_params = sum(
        int(np.prod(p.shape))
        for p in jax.tree_util.tree_leaves(variables["params"])
    )
    # canonical resnet50 parameter count ~25.5M
    assert 25_000_000 < n_params < 26_000_000, n_params


def test_resnet_bf16_compute_fp32_params():
    model = models.ResNet18(num_classes=10, compute_dtype=jnp.bfloat16)
    x = jnp.ones((1, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    for leaf in jax.tree_util.tree_leaves(variables["params"]):
        assert leaf.dtype == jnp.float32


def test_graft_entry_single_device():
    import __graft_entry__ as g

    fn, example = g.entry()
    out = jax.jit(fn)(*example)
    assert out.shape == (8, 1000)


@pytest.mark.multiprocess
def test_graft_entry_dryrun_multichip():
    import __graft_entry__ as g

    g.dryrun_multichip(8)
