"""Faithful `pyspark` stand-in at the RDD-API level (local mode).

pyspark does not install on this image's python, so the Spark adapter
(`horovod_tpu.cluster.spark_executor`) is exercised against this stand-in
instead — the `tests/test_mxnet_interop.py` pattern, but process-faithful:
like Spark local mode, every partition's function runs in its OWN python
worker process (cloudpickled over a file, concurrent across partitions),
and a task failure aborts the stage with the worker's traceback.  That is
exactly the execution contract `spark_executor` depends on:
``sc.parallelize(range(n), n).mapPartitionsWithIndex(f).collect()`` with
``f`` blocking until the whole horovod_tpu job finishes
(reference topology: spark/runner.py _make_spark_thread +
mapPartitionsWithIndex).

Install with ``install_fake_pyspark()`` BEFORE importing code that does
``import pyspark``.
"""

from __future__ import annotations

import os
import pickle
import subprocess
import sys
import tempfile
import types
from typing import Callable, List, Sequence

import cloudpickle

_WORKER_CODE = """
import pickle, sys, traceback
import cloudpickle
with open(sys.argv[1], "rb") as fh:
    fn, index, items = cloudpickle.load(fh)
try:
    out = list(fn(index, iter(items)))
    payload = (True, out)
except BaseException:
    payload = (False, traceback.format_exc())
with open(sys.argv[2], "wb") as fh:
    pickle.dump(payload, fh)
sys.exit(0 if payload[0] else 1)
"""


def _partition(data: Sequence, num_slices: int) -> List[list]:
    """Spark's parallelize split: partition i gets
    items [i*len//n, (i+1)*len//n)."""
    n = len(data)
    return [
        list(data[(i * n) // num_slices : ((i + 1) * n) // num_slices])
        for i in range(num_slices)
    ]


class _MappedRDD:
    def __init__(self, partitions: List[list], fn: Callable):
        self._partitions = partitions
        self._fn = fn

    def collect(self):
        """Run every partition task in its own worker process,
        concurrently (Spark local[n] task slots); gather yielded values in
        partition order; abort the stage on the first task failure."""
        workdir = tempfile.mkdtemp(prefix="fake_spark_")
        procs = []
        for index, items in enumerate(self._partitions):
            in_path = os.path.join(workdir, f"task_{index}.in")
            out_path = os.path.join(workdir, f"task_{index}.out")
            with open(in_path, "wb") as fh:
                cloudpickle.dump((self._fn, index, items), fh)
            procs.append((
                index, out_path,
                subprocess.Popen(
                    [sys.executable, "-c", _WORKER_CODE, in_path, out_path]
                ),
            ))
        results = []
        failure = None
        for index, out_path, proc in procs:
            proc.wait()
            try:
                with open(out_path, "rb") as fh:
                    ok, value = pickle.load(fh)
            except FileNotFoundError:
                ok, value = False, f"worker {index} died without output"
            if ok:
                results.extend(value)
            elif failure is None:
                failure = (index, value)
        if failure is not None:
            raise Exception(
                f"Job aborted due to stage failure: Task {failure[0]} "
                f"in stage 0.0 failed:\n{failure[1]}"
            )
        return results


class _RDD:
    def __init__(self, data: list, num_slices: int):
        self._partitions = _partition(data, num_slices)

    def mapPartitionsWithIndex(self, fn: Callable) -> _MappedRDD:
        return _MappedRDD(self._partitions, fn)

    def getNumPartitions(self) -> int:
        return len(self._partitions)


class SparkContext:
    _active_spark_context = None

    def __init__(self, master: str = "local[*]", appName: str = "test"):
        self.master = master
        self.appName = appName
        SparkContext._active_spark_context = self

    def parallelize(self, data, numSlices: int) -> _RDD:
        return _RDD(list(data), numSlices)

    def stop(self) -> None:
        SparkContext._active_spark_context = None


def install_fake_pyspark() -> types.ModuleType:
    mod = types.ModuleType("pyspark")
    mod.SparkContext = SparkContext
    mod.__version__ = "0.0-standin"
    sys.modules["pyspark"] = mod
    return mod
