"""Checkpoint/resume + Store (SURVEY.md §5.4: rank-0 checkpoint +
broadcast-on-start; Store mirrors horovod/spark/common/store.py)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.checkpoint import (
    LocalStore,
    latest_checkpoint_step,
    restore_checkpoint,
    save_checkpoint,
)


@pytest.fixture(autouse=True)
def _init():
    hvd.init()
    yield


def _state(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "params": {
            "w": jnp.asarray(rng.randn(4, 3).astype(np.float32)),
            "b": jnp.asarray(rng.randn(3).astype(np.float32)),
        },
        "step": jnp.asarray(7, jnp.int32),
    }


def test_save_restore_roundtrip(tmp_path):
    d = str(tmp_path / "ckpt")
    state = _state()
    save_checkpoint(d, state, step=7)
    assert latest_checkpoint_step(d) == 7
    out = restore_checkpoint(d, _state(seed=1))
    np.testing.assert_array_equal(
        np.asarray(out["params"]["w"]), np.asarray(state["params"]["w"])
    )
    np.testing.assert_array_equal(np.asarray(out["step"]), 7)


def test_restore_latest_and_explicit(tmp_path):
    d = str(tmp_path / "ckpt")
    for s in (1, 5, 3):
        state = _state(seed=s)
        save_checkpoint(d, state, step=s)
    assert latest_checkpoint_step(d) == 5
    latest = restore_checkpoint(d, _state())
    np.testing.assert_array_equal(
        np.asarray(latest["params"]["w"]),
        np.asarray(_state(seed=5)["params"]["w"]),
    )
    old = restore_checkpoint(d, _state(), step=1)
    np.testing.assert_array_equal(
        np.asarray(old["params"]["w"]),
        np.asarray(_state(seed=1)["params"]["w"]),
    )


def test_keep_prunes_old_steps(tmp_path):
    d = str(tmp_path / "ckpt")
    for s in range(5):
        save_checkpoint(d, _state(seed=s), step=s, keep=2)
    names = sorted(os.listdir(d))
    assert names == ["step_0000000003", "step_0000000004"]


def test_resave_same_step_overwrites(tmp_path):
    d = str(tmp_path / "ckpt")
    save_checkpoint(d, _state(seed=0), step=1)
    save_checkpoint(d, _state(seed=9), step=1)
    out = restore_checkpoint(d, _state())
    np.testing.assert_array_equal(
        np.asarray(out["params"]["w"]),
        np.asarray(_state(seed=9)["params"]["w"]),
    )


def test_keep_zero_rejected(tmp_path):
    with pytest.raises(ValueError, match="keep must be >= 1"):
        save_checkpoint(str(tmp_path / "c"), _state(), step=0, keep=0)


def test_restore_missing_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        restore_checkpoint(str(tmp_path / "none"), _state())


def test_local_store_metadata_and_paths(tmp_path):
    store = LocalStore(str(tmp_path))
    assert store.read_metadata("run1") is None
    store.write_metadata({"epochs": 3}, "run1")
    assert store.read_metadata("run1") == {"epochs": 3}
    assert store.checkpoint_dir("run1").startswith(str(tmp_path))
    # atomic write: no .tmp residue
    assert not any(p.endswith(".tmp") for p in os.listdir(
        os.path.dirname(store.metadata_path("run1"))
    ))


def test_async_save_restore_roundtrip(tmp_path):
    """save_checkpoint_async returns before commit; wait() is the
    commit point, after which restore sees the same pytree as a sync
    save would."""
    from horovod_tpu.checkpoint import save_checkpoint_async

    state = _state(3)
    handle = save_checkpoint_async(str(tmp_path), state, step=1)
    path = handle.wait()
    assert path.endswith("step_0000000001")
    got = restore_checkpoint(str(tmp_path), state, broadcast=False)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        got, state,
    )
    # wait() is idempotent
    assert handle.wait() == path


def test_async_save_retention(tmp_path):
    from horovod_tpu.checkpoint import save_checkpoint_async

    for step in (1, 2, 3):
        save_checkpoint_async(
            str(tmp_path), _state(step), step=step, keep=2
        ).wait()
    assert latest_checkpoint_step(str(tmp_path)) == 3
    names = sorted(os.listdir(tmp_path))
    assert names == ["step_0000000002", "step_0000000003"]


def test_async_save_keep_validated(tmp_path):
    from horovod_tpu.checkpoint import save_checkpoint_async

    with pytest.raises(ValueError, match="keep"):
        save_checkpoint_async(str(tmp_path), _state(), step=1, keep=0)


def test_async_save_failure_raises_at_wait(tmp_path):
    """A failed save must surface at wait() — and keep surfacing on
    repeat wait() — never silently bless the step."""
    from horovod_tpu.checkpoint import save_checkpoint_async

    blocker = tmp_path / "file"
    blocker.write_text("x")
    # directory path nested under a regular FILE: makedirs fails
    handle = save_checkpoint_async(
        str(blocker / "ckpt"), _state(), step=1
    )
    with pytest.raises(Exception):
        handle.wait()
    with pytest.raises(Exception):
        handle.wait()


def _async_save_with_injected_fault(directory):
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.checkpoint import save_checkpoint_async

    hvd.init()
    state = {"w": np.zeros(3, np.float32)}
    handle = save_checkpoint_async(directory, state, step=5)
    try:
        path = handle.wait()
        out = {"raised": False, "msg": path}
    except Exception as exc:  # noqa: BLE001 — the contract under test
        out = {"raised": True, "msg": str(exc)}
    out["rank"] = hvd.rank()
    hvd.shutdown()
    return out


@pytest.mark.multiprocess
def test_injected_ckpt_failure_raises_on_all_ranks(tmp_path):
    """ISSUE 1 satellite (ADVICE r5 #2): a failed rank-0 save must raise
    at wait() on EVERY rank — survivors may not silently return the step
    path and train on believing the commit point exists.  The failure is
    injected deterministically via HVDTPU_FAULT_SPEC."""
    import horovod_tpu.run as hvdrun

    results = hvdrun.run(
        _async_save_with_injected_fault,
        args=(str(tmp_path / "ckpt"),),
        np=2, use_cpu=True, timeout=180,
        env={"HVDTPU_FAULT_SPEC": "ckpt_write:step=5:rank=0"},
    )
    by_rank = {r["rank"]: r for r in results}
    assert by_rank[0]["raised"], by_rank
    assert "injected fault at 'ckpt_write'" in by_rank[0]["msg"]
    assert by_rank[1]["raised"], (
        "rank 1 silently blessed a save that failed on rank 0: "
        f"{by_rank[1]['msg']}"
    )
    assert "failed on rank 0" in by_rank[1]["msg"]
