"""fp8 activation-storage numerics contract (VERDICT r3 weak #2).

``bench.py --dtype fp8`` (bf16 compute, e4m3 activation storage between
ResNet blocks) changes the loss contract, so the opt-in path needs a
convergence-sanity assertion, reference-style: on a fixed seed, a short
training run under fp8 must track the bf16 run's loss within a stated
tolerance — and must actually train (loss decreases).

Tolerance contract (documented in docs/performance.md):
- step-1 loss (identical params, pure forward numerics): within 2% of bf16
- every later step (trajectories compound the rounding): within 15% + 0.05
- both runs strictly decrease loss over the 6 steps
The run is deterministic (fixed data/init seeds, single CPU-mesh process),
so these are regression bounds, not statistical ones.
"""

from __future__ import annotations

import numpy as np
import pytest

pytestmark = pytest.mark.full


def _short_train(dtype: str, steps: int = 6) -> list:
    import bench

    step, state, static = bench.build_step(
        "resnet18", dtype, batch_size=2, image_size=32
    )
    carry, const = state[:3], state[3:]
    losses = []
    for _ in range(steps):
        *carry, loss = step(*carry, *const)
        losses.append(float(loss))
    return losses


def test_fp8_tracks_bf16_loss():
    losses_bf16 = _short_train("bf16")
    losses_fp8 = _short_train("fp8")
    # both runs actually train
    assert losses_bf16[-1] < losses_bf16[0]
    assert losses_fp8[-1] < losses_fp8[0]
    # step 1: same params on both runs, so the gap is pure e4m3
    # activation-storage rounding in the forward pass — tight bound
    assert abs(losses_fp8[0] - losses_bf16[0]) <= 0.02 * abs(losses_bf16[0]), (
        f"fp8 forward numerics off: {losses_fp8[0]} vs {losses_bf16[0]}"
    )
    # later steps: trajectories compound the rounding — loose bound
    for b, f in zip(losses_bf16[1:], losses_fp8[1:]):
        assert np.isfinite(f)
        assert abs(f - b) <= 0.15 * abs(b) + 0.05, (
            f"fp8 loss {f} diverged from bf16 loss {b} "
            f"(series fp8={losses_fp8}, bf16={losses_bf16})"
        )


def _short_gpt_train(dtype: str, steps: int = 6) -> list:
    import bench

    step, state, static = bench.build_gpt_step(
        "nano", dtype, batch_size=2, seq_len=64, attention="reference"
    )
    *carry, const = state
    losses = []
    for _ in range(steps):
        *carry, loss = step(*carry, const)
        losses.append(float(loss))
    return losses


def test_gpt_fp8_tracks_bf16_loss():
    """The transformer act-storage path (attention context, branch
    deltas, gelu intermediate at e4m3 — models/transformer.py act_store)
    under the same contract as the ResNet path: step-1 within 2%, later
    steps within 15% + 0.05, both runs strictly decrease."""
    losses_bf16 = _short_gpt_train("bf16")
    losses_fp8 = _short_gpt_train("fp8")
    assert losses_bf16[-1] < losses_bf16[0]
    assert losses_fp8[-1] < losses_fp8[0]
    assert abs(losses_fp8[0] - losses_bf16[0]) <= 0.02 * abs(losses_bf16[0]), (
        f"gpt fp8 forward numerics off: {losses_fp8[0]} vs {losses_bf16[0]}"
    )
    for b, f in zip(losses_bf16[1:], losses_fp8[1:]):
        assert np.isfinite(f)
        assert abs(f - b) <= 0.15 * abs(b) + 0.05, (
            f"gpt fp8 loss {f} diverged from bf16 loss {b} "
            f"(series fp8={losses_fp8}, bf16={losses_bf16})"
        )


def test_moe_expert_ffn_act_store():
    """The MoE leg of fp8 act storage: the expert gelu intermediate
    quantizes through the same e4m3 round-trip (the combination
    --moe-experts + --dtype fp8 must not silently run bf16 experts)."""
    import jax
    import jax.numpy as jnp

    from horovod_tpu.parallel.moe import init_moe_params, moe_mlp

    x = jnp.asarray(
        np.random.RandomState(0).randn(2, 8, 16), jnp.float32
    )
    params = init_moe_params(jax.random.PRNGKey(0), 16, 64, 4)
    y_bf16, _ = moe_mlp(x, params, top_k=2, dtype=jnp.float32)
    y_fp8, _ = moe_mlp(x, params, top_k=2, dtype=jnp.float32,
                       act_store_dtype=jnp.float8_e4m3fn)
    assert np.isfinite(np.asarray(y_fp8)).all()
    # quantization must actually change the values (the knob is live)...
    assert not np.allclose(np.asarray(y_fp8), np.asarray(y_bf16))
    # ...but only by e4m3 rounding of the gelu intermediate
    np.testing.assert_allclose(
        np.asarray(y_fp8), np.asarray(y_bf16), atol=0.15, rtol=0.15
    )
