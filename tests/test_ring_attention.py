"""Sequence-parallel attention tests.

Strategy (SURVEY.md §4 lesson): run the real SPMD schedule on the 8-device
virtual CPU mesh and compare bit-level behavior against the single-device
reference (`local_attention`) — no mocks.  Gradients are compared too,
since both schedules are advertised as training-ready.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.parallel import (
    local_attention,
    ring_attention,
    ulysses_attention,
)

B, S, H, D = 2, 32, 8, 16  # global seq 32 over 8 devices = 4 per shard
AXIS = "sp"


def _qkv(seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, S, H, D), dtype) * 0.3
    return mk(), mk(), mk()


def _mesh():
    return Mesh(np.asarray(jax.devices()[:8]), (AXIS,))


def _sharded(fn, **kw):
    spec = P(None, AXIS)  # shard dim 1 (sequence)
    return jax.jit(
        shard_map(
            lambda q, k, v: fn(q, k, v, AXIS, **kw),
            mesh=_mesh(),
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )
    )


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_local_attention(self, causal):
        q, k, v = _qkv()
        ref = local_attention(q, k, v, causal=causal)
        out = _sharded(ring_attention, causal=causal)(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    def test_grads_match(self):
        q, k, v = _qkv(seed=1)
        sharded = _sharded(ring_attention, causal=True)

        def loss_ref(q, k, v):
            return (local_attention(q, k, v, causal=True) ** 2).sum()

        def loss_ring(q, k, v):
            return (sharded(q, k, v) ** 2).sum()

        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ring, g_ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4
            )

    def test_bfloat16_io(self):
        q, k, v = _qkv(seed=2, dtype=jnp.bfloat16)
        out = _sharded(ring_attention, causal=True)(q, k, v)
        assert out.dtype == jnp.bfloat16
        ref = local_attention(
            q.astype(jnp.float32), k.astype(jnp.float32),
            v.astype(jnp.float32), causal=True,
        )
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref), atol=0.05, rtol=0.05
        )

    def test_long_context_memory_shape(self):
        """Each shard only ever materializes S/P-sized score blocks — the
        schedule compiles with per-device attention matrices of
        (s_local, s_local), not (S, S)."""
        q, k, v = _qkv(seed=3)
        fn = _sharded(ring_attention, causal=False)
        compiled = fn.lower(q, k, v).compile()
        # sanity: it runs; the (S,S) matrix never exists on one device by
        # construction of the scan (block is (B,H,4,4) here)
        out = compiled(q, k, v)
        assert out.shape == (B, S, H, D)


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_local_attention(self, causal):
        q, k, v = _qkv(seed=4)
        ref = local_attention(q, k, v, causal=causal)
        out = _sharded(ulysses_attention, causal=causal)(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    def test_grads_match(self):
        q, k, v = _qkv(seed=5)
        sharded = _sharded(ulysses_attention, causal=True)
        g_ref = jax.grad(
            lambda *a: (local_attention(*a, causal=True) ** 2).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        g_uly = jax.grad(
            lambda *a: (sharded(*a) ** 2).sum(), argnums=(0, 1, 2)
        )(q, k, v)
        for a, b in zip(g_uly, g_ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4
            )

    def test_head_divisibility_error(self):
        q = jnp.zeros((1, 8, 6, 4))  # 6 heads over 8 devices
        with pytest.raises(ValueError, match="divisible"):
            _sharded(ulysses_attention)(q, q, q)


class TestLocalAttentionOffsets:
    def test_global_causal_offsets(self):
        """q_offset/kv_offset place the causal triangle in global coords."""
        q, k, v = _qkv(seed=6)
        full = local_attention(q, k, v, causal=True)
        # second half of queries attending the full key set
        half = local_attention(
            q[:, S // 2:], k, v, causal=True, q_offset=S // 2, kv_offset=0
        )
        np.testing.assert_allclose(
            np.asarray(half), np.asarray(full[:, S // 2:]), atol=2e-5,
            rtol=2e-5,
        )


class TestZigzagRing:
    """Load-balanced causal ring: zigzag layout round-trips and the
    distributed result matches single-device causal attention."""

    def test_shard_roundtrip(self):
        from horovod_tpu.parallel import zigzag_shard, zigzag_unshard

        x = jnp.arange(B * S * 3, dtype=jnp.float32).reshape(B, S, 3)
        z = zigzag_shard(x, 8, axis=1)
        back = zigzag_unshard(z, 8, axis=1)
        np.testing.assert_array_equal(np.asarray(back), np.asarray(x))
        # rank 0's shard is chunks (0, 15): rows 0,1 and 30,31
        s_local = S // 8
        np.testing.assert_array_equal(
            np.asarray(z[:, :s_local]),
            np.asarray(jnp.concatenate([x[:, 0:2], x[:, 30:32]], axis=1)),
        )

    def test_matches_local_attention_causal(self):
        from horovod_tpu.parallel import (
            ring_attention_zigzag, zigzag_shard, zigzag_unshard,
        )

        q, k, v = _qkv(3)
        ref = local_attention(q, k, v, causal=True)
        zz = lambda t: zigzag_shard(t, 8, axis=1)
        out_z = _sharded(ring_attention_zigzag)(zz(q), zz(k), zz(v))
        out = zigzag_unshard(out_z, 8, axis=1)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    def test_grads_match(self):
        from horovod_tpu.parallel import (
            ring_attention_zigzag, zigzag_shard, zigzag_unshard,
        )

        q, k, v = _qkv(4)
        zz = lambda t: zigzag_shard(t, 8, axis=1)
        uz = lambda t: zigzag_unshard(t, 8, axis=1)
        w = jnp.asarray(
            np.random.RandomState(5).randn(B, S, H, D), jnp.float32
        )

        def loss_ref(q, k, v):
            return (local_attention(q, k, v, causal=True) * w).sum()

        def loss_zig(q, k, v):
            out = _sharded(ring_attention_zigzag)(zz(q), zz(k), zz(v))
            return (uz(out) * w).sum()

        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        g_zig = jax.grad(loss_zig, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_zig, g_ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5
            )

    def test_odd_local_length_rejected(self):
        from horovod_tpu.parallel import ring_attention_zigzag

        q = jnp.zeros((1, 8, 2, 4))  # 8 over 8 devices -> s_local 1 (odd)
        with pytest.raises(Exception, match="even local sequence"):
            _sharded(ring_attention_zigzag)(q, q, q)


def test_zigzag_positions_match_layout():
    """zigzag_positions(i) must be exactly the global positions of rank
    i's rows after zigzag_shard + contiguous split."""
    from horovod_tpu.parallel import zigzag_positions, zigzag_shard

    size, s = 4, 24
    x = jnp.arange(s)  # value == global position
    z = zigzag_shard(x, size)
    s_local = s // size
    for i in range(size):
        shard = np.asarray(z[i * s_local:(i + 1) * s_local])
        np.testing.assert_array_equal(
            shard, np.asarray(zigzag_positions(i, size, s_local))
        )
