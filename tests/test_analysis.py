"""hvdtpu-lint test suite (ISSUE 5).

Coverage contract (acceptance criteria):

* every rule ID has at least one FIRING fixture and one NON-FIRING
  fixture (parametrized below from ``FIXTURES`` — a new rule without
  fixtures fails ``test_every_rule_has_fixtures``);
* CLI behavior: exit codes, ``--format json`` schema, baseline
  matching (reasoned entries only), inline suppression comments,
  ``--rules`` filtering;
* a regression case reproducing the PR-4 reentrant-flush deadlock
  shape (SIGTERM-inside-SIGUSR1: a non-reentrant lock on the
  signal-flush path), which HVDC103 must catch.

Fixture sources live as string literals so the analyzer never sees
them when linting tests/ itself.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

from horovod_tpu.analysis import all_rules, analyze_paths
from horovod_tpu.analysis.baseline import (
    BASELINE_SCHEMA,
    BaselineError,
    load_baseline,
)
from horovod_tpu.analysis.config import load_config

# ---------------------------------------------------------------------------
# fixtures: rule id -> (firing source, clean source)
# ---------------------------------------------------------------------------

FIXTURES = {
    "HVD001": (
        """
        import horovod_tpu as hvd

        def step(x):
            if hvd.rank() == 0:
                return hvd.allreduce(x)
            return x
        """,
        """
        import horovod_tpu as hvd

        def step(x):
            total = hvd.allreduce(x)
            if hvd.rank() == 0:
                print(total)
            return total
        """,
    ),
    "HVD002": (
        """
        import horovod_tpu as hvd

        def reduce_all(grads):
            for k in {"w", "b"}:
                grads[k] = hvd.allreduce(grads[k])
        """,
        """
        import horovod_tpu as hvd

        def reduce_all(grads):
            for k in sorted({"w", "b"}):
                grads[k] = hvd.allreduce(grads[k])
        """,
    ),
    "HVD003": (
        """
        import horovod_tpu as hvd

        def step(x, spiked):
            if spiked:
                x = hvd.allreduce(x)
            return x
        """,
        """
        import horovod_tpu as hvd

        def step(x, spiked):
            if spiked:
                x = hvd.allreduce(x, name="spike_fix")
            return x
        """,
    ),
    "HVD004": (
        """
        import optax
        import horovod_tpu as hvd

        def main(params):
            hvd.init()
            tx = hvd.DistributedOptimizer(optax.adam(1e-3))
            return tx.init(params)

        if __name__ == "__main__":
            main({})
        """,
        """
        import optax
        import horovod_tpu as hvd

        def main(params):
            hvd.init()
            params = hvd.broadcast_parameters(params, root_rank=0)
            tx = hvd.DistributedOptimizer(optax.adam(1e-3))
            return tx.init(params)

        if __name__ == "__main__":
            main({})
        """,
    ),
    "HVD005": (
        """
        import horovod_tpu as hvd

        IS_CHIEF = hvd.rank() == 0
        """,
        """
        import horovod_tpu as hvd

        hvd.init()
        IS_CHIEF = hvd.rank() == 0
        """,
    ),
    "HVD006": (
        """
        import horovod_tpu as hvd

        def step(x):
            try:
                return hvd.allreduce(x, name="g")
            except Exception:
                return hvd.allreduce(x, name="retry")
        """,
        """
        import horovod_tpu as hvd

        def step(x):
            try:
                return hvd.allreduce(x, name="g")
            finally:
                hvd.barrier()
        """,
    ),
    "HVD007": (
        """
        import horovod_tpu as hvd

        def step(x):
            return hvd.allreduce(x, name=f"grad_{hvd.rank()}")
        """,
        """
        import horovod_tpu as hvd

        def step(x):
            return hvd.allreduce(x, name="grad_w0")
        """,
    ),
    "HVD008": (
        """
        from jax.experimental import multihost_utils

        def checkpoint_barrier():
            multihost_utils.sync_global_devices("ckpt")
        """,
        """
        import horovod_tpu as hvd

        def checkpoint_barrier():
            hvd.barrier()
        """,
    ),
    "HVD009": (
        """
        import jax

        def local_step(params, opt_state, batch):
            return params, opt_state

        step = jax.jit(local_step)
        """,
        """
        import jax

        def local_step(params, opt_state, batch):
            return params, opt_state

        step = jax.jit(local_step, donate_argnums=(0, 1))
        """,
    ),
    "HVD010": (
        """
        import horovod_tpu as hvd
        from jax import lax

        def reduce_part(flag, x):
            if flag == 0:
                return lax.psum(x, "hvd_local")
            return x

        def step(x):
            return reduce_part(hvd.local_rank(), x)
        """,
        """
        import horovod_tpu as hvd
        from jax import lax

        def reduce_part(flag, x):
            y = lax.psum(x, "hvd_local")
            if flag == 0:
                return y
            return y * 0

        def step(x):
            return reduce_part(hvd.local_rank(), x)
        """,
    ),
    "HVD011": (
        """
        from jax import lax

        def step(x, fast_path):
            axis = "hvd_local" if fast_path else "hvd_cross"
            return lax.psum(x, axis)
        """,
        """
        from jax import lax

        def step(x):
            return lax.psum(x, ("hvd_local", "hvd_cross"))
        """,
    ),
    "HVD012": (
        """
        import random

        # hvdtpu: deterministic
        def pick_slot(queue, slots):
            return random.choice(slots)
        """,
        """
        # hvdtpu: deterministic
        def pick_slot(queue, slots):
            return min(slots)
        """,
    ),
    "HVD013": (
        """
        import horovod_tpu as hvd

        def record(trace, tid, t0, t1):
            if hvd.rank() == 0:
                trace.add_span(tid, "decode", t0, t1)
        """,
        """
        def record(trace, tid, t0, t1, enabled):
            if enabled:
                trace.add_span(tid, "decode", t0, t1)
        """,
    ),
    "HVDC101": (
        """
        import threading

        _table_lock = threading.Lock()
        _stats_lock = threading.Lock()

        def update_table():
            with _table_lock:
                with _stats_lock:
                    pass

        def update_stats():
            with _stats_lock:
                with _table_lock:
                    pass
        """,
        """
        import threading

        _table_lock = threading.Lock()
        _stats_lock = threading.Lock()

        def update_table():
            with _table_lock:
                with _stats_lock:
                    pass

        def update_stats():
            with _table_lock:
                with _stats_lock:
                    pass
        """,
    ),
    "HVDC102": (
        """
        import threading
        import time

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()

            def cycle(self):
                with self._lock:
                    time.sleep(1.0)
        """,
        """
        import threading
        import time

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()

            def cycle(self):
                with self._lock:
                    pending = 1
                time.sleep(1.0)
                return pending
        """,
    ),
    "HVDC103": (
        """
        import signal
        import threading

        _lock = threading.Lock()

        def _flush():
            with _lock:
                pass

        def _handler(signum, frame):
            _flush()

        def install():
            signal.signal(signal.SIGTERM, _handler)
        """,
        """
        import signal
        import threading

        _lock = threading.RLock()

        def _flush():
            with _lock:
                pass

        def _handler(signum, frame):
            _flush()

        def install():
            signal.signal(signal.SIGTERM, _handler)
        """,
    ),
    "HVDC104": (
        """
        import logging
        import signal

        LOG = logging.getLogger("x")

        def _handler(signum, frame):
            LOG.warning("dying")

        def install():
            signal.signal(signal.SIGTERM, _handler)
        """,
        """
        import logging
        import signal

        LOG = logging.getLogger("x")

        def _handler(signum, frame):
            pass

        def install():
            signal.signal(signal.SIGTERM, _handler)
            LOG.info("hooks installed")  # outside signal context
        """,
    ),
    "HVDC105": (
        """
        import horovod_tpu as hvd

        def step(g):
            try:
                return hvd.allreduce(g, name="g")
            except Exception:
                return g
        """,
        """
        import horovod_tpu as hvd
        from horovod_tpu.exceptions import HorovodShutdownError

        def step(g):
            try:
                return hvd.allreduce(g, name="g")
            except HorovodShutdownError:
                raise
            except Exception:
                return g
        """,
    ),
    "HVDC106": (
        """
        import time

        from horovod_tpu.obs.flightrec import on_death

        def _flush():
            time.sleep(1.0)

        def arm():
            on_death(_flush)
        """,
        """
        from horovod_tpu.obs.flightrec import on_death

        def _flush():
            pass

        def arm():
            on_death(_flush)
        """,
    ),
    "HVDC107": (
        """
        import signal

        def _handler(signum, frame):
            events = []
            while True:
                events.append(frame)

        def install():
            signal.signal(signal.SIGTERM, _handler)
        """,
        """
        import signal

        def _handler(signum, frame):
            events = []
            while True:
                events.append(frame)
                if len(events) > 8:
                    break

        def install():
            signal.signal(signal.SIGTERM, _handler)
        """,
    ),
    "HVDC108": (
        """
        import threading

        class Pump:
            def __init__(self):
                self._lock = threading.Lock()
                self._depth = 0

            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                with self._lock:
                    self._depth += 1
                with self._lock:
                    self._depth -= 1

            def depth(self):
                with self._lock:
                    return self._depth

            def spill(self):
                self._depth = 0  # write outside the inferred guard
        """,
        """
        import threading

        class Pump:
            def __init__(self):
                self._lock = threading.Lock()
                self._depth = 0

            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                with self._lock:
                    self._depth += 1
                with self._lock:
                    self._depth -= 1

            def depth(self):
                with self._lock:
                    return self._depth

            def spill(self):
                with self._lock:
                    self._depth = 0
        """,
    ),
    "HVDC109": (
        """
        import threading

        class Gauge:
            def __init__(self):
                self._lock = threading.Lock()
                self._value = 0

            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                with self._lock:
                    self._value += 1
                with self._lock:
                    self._value = 0

            def peek(self):
                return self._value  # read outside the write guard
        """,
        """
        import threading

        class Gauge:
            def __init__(self):
                self._lock = threading.Lock()
                self._value = 0

            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                with self._lock:
                    self._value += 1
                with self._lock:
                    self._value = 0

            def peek(self):
                with self._lock:
                    return self._value
        """,
    ),
    "HVDC110": (
        """
        import threading

        class Once:
            def __init__(self):
                self._lock = threading.Lock()
                self._started = False

            def launch(self):
                threading.Thread(target=self._work).start()

            def _work(self):
                with self._lock:
                    self._started = False

            def start(self):
                if not self._started:  # test outside the lock
                    with self._lock:
                        self._started = True  # act under it
        """,
        """
        import threading

        class Once:
            def __init__(self):
                self._lock = threading.Lock()
                self._started = False

            def launch(self):
                threading.Thread(target=self._work).start()

            def _work(self):
                with self._lock:
                    self._started = False

            def start(self):
                with self._lock:
                    if not self._started:
                        self._started = True
        """,
    ),
}


def _lint_source(tmp_path, source, name="snippet.py", rules=None):
    path = tmp_path / name
    path.write_text(textwrap.dedent(source))
    return analyze_paths([str(path)], root=str(tmp_path), rules=rules)


def _new(findings, rule=None):
    return [
        f for f in findings
        if f.status == "new" and (rule is None or f.rule == rule)
    ]


# ---------------------------------------------------------------------------
# per-rule firing / non-firing
# ---------------------------------------------------------------------------


def test_every_rule_has_fixtures():
    missing = set(all_rules()) - set(FIXTURES)
    assert not missing, f"rules without fixtures: {sorted(missing)}"
    assert len(all_rules()) >= 12  # acceptance criterion


@pytest.mark.parametrize("rule_id", sorted(FIXTURES))
def test_rule_fires_on_bad_fixture(tmp_path, rule_id):
    bad, _ = FIXTURES[rule_id]
    findings = _lint_source(tmp_path, bad)
    assert _new(findings, rule_id), (
        f"{rule_id} did not fire; findings: "
        f"{[(f.rule, f.message) for f in findings]}"
    )


@pytest.mark.parametrize("rule_id", sorted(FIXTURES))
def test_rule_quiet_on_clean_fixture(tmp_path, rule_id):
    _, good = FIXTURES[rule_id]
    findings = _lint_source(tmp_path, good)
    hits = _new(findings, rule_id)
    assert not hits, (
        f"{rule_id} fired on the clean fixture: "
        f"{[f.message for f in hits]}"
    )


# ---------------------------------------------------------------------------
# rule-specific edge cases
# ---------------------------------------------------------------------------


def test_hvd001_early_exit_guard(tmp_path):
    findings = _lint_source(tmp_path, """
        import horovod_tpu as hvd

        def save(x):
            if hvd.rank() != 0:
                return None
            return hvd.allreduce(x)
    """)
    assert _new(findings, "HVD001")


def test_hvd001_uniform_size_guard_ok(tmp_path):
    findings = _lint_source(tmp_path, """
        import horovod_tpu as hvd

        def maybe(x):
            if hvd.size() > 1:
                return hvd.allreduce(x)
            return x
    """)
    assert not _new(findings, "HVD001")


def test_hvd002_dict_items_fires_and_sorted_ok(tmp_path):
    findings = _lint_source(tmp_path, """
        import horovod_tpu as hvd

        def reduce_all(grads):
            for k, v in grads.items():
                grads[k] = hvd.allreduce(v)
    """)
    assert _new(findings, "HVD002")
    findings = _lint_source(tmp_path, """
        import horovod_tpu as hvd

        def reduce_all(grads):
            for k in sorted(grads.keys()):
                grads[k] = hvd.allreduce(grads[k])
    """)
    assert not _new(findings, "HVD002")


def test_hvd003_main_guard_exempt(tmp_path):
    findings = _lint_source(tmp_path, """
        import horovod_tpu as hvd

        if __name__ == "__main__":
            hvd.init()
            hvd.allreduce([1.0])
    """)
    assert not _new(findings, "HVD003")


def test_hvd005_function_scope_ok(tmp_path):
    findings = _lint_source(tmp_path, """
        import horovod_tpu as hvd

        def who_am_i():
            return hvd.rank()
    """)
    assert not _new(findings, "HVD005")


def test_hvd009_resolves_through_shard_map_wrapper(tmp_path):
    findings = _lint_source(tmp_path, """
        import jax
        from jax.experimental.shard_map import shard_map

        def local_step(params, opt_state, xb):
            return params, opt_state

        step = jax.jit(shard_map(local_step, mesh=None,
                                 in_specs=(), out_specs=()))
    """)
    assert _new(findings, "HVD009")


def test_hvd009_quiet_on_stateless_apply_and_donate_argnames(tmp_path):
    findings = _lint_source(tmp_path, """
        import jax

        apply = jax.jit(lambda p, xb: p @ xb)

        def local_step(params, opt_state, xb):
            return params, opt_state

        step = jax.jit(local_step, donate_argnames=("params",))
    """)
    assert not _new(findings, "HVD009")


def test_hvd009_resolution_is_scope_first(tmp_path):
    # Two builders bind the same name to different callables: each jit
    # call must be judged against ITS OWN function's binding — the
    # stateless apply stays quiet, the train step fires.
    findings = _lint_source(tmp_path, """
        import jax
        from jax.experimental.shard_map import shard_map

        def build_eval():
            step = shard_map(lambda p_, xb: p_, mesh=None,
                             in_specs=(), out_specs=())
            return jax.jit(step)

        def build_train():
            def local(params, opt_state, xb):
                return params, opt_state
            step = shard_map(local, mesh=None, in_specs=(), out_specs=())
            return jax.jit(step)
    """)
    hits = _new(findings, "HVD009")
    assert len(hits) == 1, [f.message for f in hits]
    assert "params" in hits[0].message


def test_hvd009_name_does_not_resolve_to_same_named_method(tmp_path):
    # Regression: `init = shard_map(lambda bufs: ..., ...)` then
    # jax.jit(init) must not resolve `init` to an unrelated class's
    # `init(self, params)` method and convict the lambda.
    findings = _lint_source(tmp_path, """
        import jax
        from jax.experimental.shard_map import shard_map

        class Plan:
            def init(self, params):
                return params

        def build():
            init = shard_map(lambda bufs: bufs, mesh=None,
                             in_specs=(), out_specs=())
            return jax.jit(init)
    """)
    assert not _new(findings, "HVD009")


def test_hvdc105_stored_exception_ok(tmp_path):
    # checkpoint.py's deferred-error pattern: the handler KEEPS the
    # exception (re-raised later) — not a swallow.
    findings = _lint_source(tmp_path, """
        import horovod_tpu as hvd

        class Save:
            def wait(self, g):
                try:
                    return hvd.allreduce(g, name="g")
                except Exception as exc:
                    self._error = exc
                    return None
    """)
    assert not _new(findings, "HVDC105")


def test_hvdc102_via_callee(tmp_path):
    # The blocking call hides one call level down, same module.
    findings = _lint_source(tmp_path, """
        import threading

        class Pub:
            def __init__(self):
                self._lock = threading.Lock()
                self._thread = threading.Thread(target=lambda: None)

            def _stop_worker(self):
                self._thread.join(timeout=2)

            def stop(self):
                with self._lock:
                    self._stop_worker()
    """)
    hits = _new(findings, "HVDC102")
    assert hits and "join" in hits[0].message


def test_thread_target_closure_not_signal_reachable(tmp_path):
    # exec.py's mitigation pattern: the handler only SPAWNS a thread;
    # the closure doing lock work runs outside signal context.
    findings = _lint_source(tmp_path, """
        import signal
        import threading

        _lock = threading.Lock()

        def _handler(signum, frame):
            def _work():
                with _lock:
                    pass
            threading.Thread(target=_work, daemon=True).start()

        def install():
            signal.signal(signal.SIGTERM, _handler)
    """)
    assert not _new(findings, "HVDC103")


# ---------------------------------------------------------------------------
# interprocedural taint edge cases (ISSUE 12)
# ---------------------------------------------------------------------------


def test_hvd010_taint_through_kwarg(tmp_path):
    findings = _lint_source(tmp_path, """
        import horovod_tpu as hvd
        from jax import lax

        def reduce_part(x, flag=0):
            if flag == 0:
                return lax.psum(x, "hvd_local")
            return x

        def step(x):
            return reduce_part(x, flag=hvd.local_rank())
    """)
    hits = _new(findings, "HVD010")
    assert hits and "flag" in hits[0].message, \
        [f.message for f in findings]


def test_hvd010_taint_through_returned_tuple(tmp_path):
    # A rank carried inside a returned tuple must not launder through
    # unpacking.
    findings = _lint_source(tmp_path, """
        import horovod_tpu as hvd
        from jax import lax

        def who_and_size():
            return hvd.rank(), hvd.size()

        def step(x):
            r, n = who_and_size()
            if r == 0:
                return lax.psum(x, "hvd_local")
            return x
    """)
    hits = _new(findings, "HVD010")
    assert hits and "who_and_size" in hits[0].message, \
        [f.message for f in findings]


def test_hvd010_sanitized_by_uniform_broadcast(tmp_path):
    # An allreduce result is identical on every rank: branching on it
    # is safe even though a rank value flowed in.
    findings = _lint_source(tmp_path, """
        import horovod_tpu as hvd
        from jax import lax

        def step(x):
            chief = hvd.allreduce(hvd.rank(), name="who")
            if chief == 0:
                return lax.psum(x, "hvd_local")
            return x
    """)
    assert not _new(findings, "HVD010"), \
        [f.message for f in _new(findings, "HVD010")]


def test_hvd010_three_frame_chain_attribution(tmp_path):
    findings = _lint_source(tmp_path, """
        import horovod_tpu as hvd
        from jax import lax

        def helper(x, r):
            if r > 0:
                return x
            return lax.psum(x, "hvd_cross")

        def mid(x, rr):
            return helper(x, rr)

        def top(x):
            return mid(x, hvd.cross_rank())
    """)
    hits = _new(findings, "HVD010")
    assert hits, [f.message for f in findings]
    # call-chain attribution: every frame named, caller-first
    msg = hits[0].message
    assert "top" in msg and "mid" in msg and "helper" in msg


def test_hvd010_scoped_taint_is_uniform_off_axis(tmp_path):
    # local_rank() differs WITHIN a local group but is uniform within a
    # cross group (the group fixes every other mesh coordinate): a
    # local-scoped guard around a CROSS collective must stay quiet.
    findings = _lint_source(tmp_path, """
        import horovod_tpu as hvd
        from jax import lax

        def reduce_cross(x, lr):
            if lr == 0:
                return lax.psum(x, "hvd_cross")
            return x

        def step(x):
            return reduce_cross(x, hvd.local_rank())
    """)
    assert not _new(findings, "HVD010"), \
        [f.message for f in _new(findings, "HVD010")]


def test_hvd010_param_laundered_in_callee_stays_quiet(tmp_path):
    # The callee itself launders the tainted parameter along the
    # collective's axis before branching on it: uniform by the time it
    # reaches the guard, whatever the caller passed in.  Two distinct
    # regressions hid here — ValueTaint.merge wiped the sanitized set
    # when merging into a fresh value, and the parameter-hazard path
    # never consulted it.
    findings = _lint_source(tmp_path, """
        import horovod_tpu as hvd
        from jax import lax

        def reduce_part(flag, x):
            flag = lax.psum(flag, "hvd_local")
            if flag == 0:
                return lax.psum(x, "hvd_local")
            return x

        def step(x):
            return reduce_part(hvd.local_rank(), x)
    """)
    assert not _new(findings, "HVD010"), \
        [f.message for f in _new(findings, "HVD010")]


def test_hvd011_same_name_in_unrelated_functions_stays_quiet(tmp_path):
    # Two helpers each binding their own constant `axis` are two
    # single-axis call sites — the assignment map is scoped per
    # enclosing function, not per file.
    findings = _lint_source(tmp_path, """
        from jax import lax

        def local_reduce(x):
            axis = "hvd_local"
            return lax.psum(x, axis)

        def cross_reduce(x):
            axis = "hvd_cross"
            return lax.psum(x, axis)
    """)
    assert not _new(findings, "HVD011"), \
        [f.message for f in _new(findings, "HVD011")]


def test_hvd011_reassigned_selector_in_one_function_fires(tmp_path):
    findings = _lint_source(tmp_path, """
        from jax import lax

        def pick(x, fast):
            axis = "hvd_local"
            if fast:
                axis = "hvd_cross"
            return lax.psum(x, axis)
    """)
    assert _new(findings, "HVD011")


def test_hvd010_world_taint_diverges_in_every_subgroup(tmp_path):
    findings = _lint_source(tmp_path, """
        import horovod_tpu as hvd
        from jax import lax

        def step(x):
            if hvd.rank() == 0:
                return lax.psum(x, "hvd_local")
            return x
    """)
    assert _new(findings, "HVD010")


def test_hvd012_impure_helper_via_call_tree(tmp_path):
    findings = _lint_source(tmp_path, """
        import time

        def now_ms():
            return time.time() * 1000

        # hvdtpu: deterministic
        def pick_slot(queue, slots):
            t = now_ms()
            return slots[int(t) % len(slots)]
    """)
    hits = _new(findings, "HVD012")
    assert hits and "now_ms" in " ".join(f.message for f in hits), \
        [f.message for f in findings]


def test_hvd012_impure_arg_into_contract_function(tmp_path):
    findings = _lint_source(tmp_path, """
        import random

        # hvdtpu: deterministic
        def pick_slot(queue, seed):
            return queue[seed % len(queue)]

        def caller(queue):
            return pick_slot(queue, random.randint(0, 7))
    """)
    hits = _new(findings, "HVD012")
    assert hits and any("flows into" in f.message for f in hits), \
        [f.message for f in findings]


def test_hvd013_rank_in_sampled_args(tmp_path):
    findings = _lint_source(tmp_path, """
        import horovod_tpu as hvd
        from horovod_tpu.obs.trace import sampled

        def should_trace(tid):
            return sampled(f"{tid}-{hvd.rank()}")
    """)
    assert _new(findings, "HVD013")


# ---------------------------------------------------------------------------
# PR-4 regression: the reentrant-flush deadlock shape
# ---------------------------------------------------------------------------


def test_pr4_reentrant_flush_deadlock_shape(tmp_path):
    """The bug PR 4 fixed by hand: SIGUSR1's flush holds a module lock
    when SIGTERM lands on the same thread; the SIGTERM handler re-enters
    flush() and deadlocks on a non-reentrant Lock.  The signal pass must
    flag the Lock (HVDC103) — and must go quiet once it is an RLock,
    which is exactly the shipped fix in obs/flightrec.py."""
    bad = """
        import signal
        import threading

        _death_lock = threading.Lock()
        _callbacks = []

        def flush(trigger):
            with _death_lock:
                cbs = list(_callbacks)
            for fn in cbs:
                fn()

        def _signal_handler(signum, frame):
            flush(f"signal:{signum}")

        def install_death_hooks():
            for sig in (signal.SIGTERM, signal.SIGUSR1):
                signal.signal(sig, _signal_handler)
    """
    findings = _lint_source(tmp_path, bad, name="flightrec_shape.py")
    hits = _new(findings, "HVDC103")
    assert hits, "the PR-4 deadlock shape must be rejected"
    assert "_death_lock" in hits[0].message
    fixed = bad.replace("threading.Lock()", "threading.RLock()")
    findings = _lint_source(tmp_path, fixed, name="flightrec_shape.py")
    assert not _new(findings, "HVDC103")


# ---------------------------------------------------------------------------
# race rules (HVDC108-110): guarded-by inference edge cases
# ---------------------------------------------------------------------------


def test_racer_init_writes_exempt(tmp_path):
    """Construction-time writes (in __init__ and init-only callees,
    before the first escape) are exempt from guard coverage: they
    happen before any other thread can hold a reference."""
    src = """
        import threading

        class Table:
            def __init__(self):
                self._lock = threading.Lock()
                self._rows = []       # unguarded, but pre-escape
                self._fill()

            def _fill(self):
                self._rows.append(0)  # init-only callee: same exemption

            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                with self._lock:
                    self._rows.append(1)
                with self._lock:
                    self._rows.append(2)
                with self._lock:
                    self._rows.pop()
                with self._lock:
                    self._rows.clear()

            def snap(self):
                with self._lock:
                    return list(self._rows)
    """
    findings = _lint_source(tmp_path, src)
    assert not _new(findings, "HVDC108"), \
        [f.message for f in _new(findings, "HVDC108")]
    assert not _new(findings, "HVDC109")


def test_racer_unescaped_class_never_reported(tmp_path):
    """The RacerD ownership rule: a lock-owning class whose instances
    never escape to another thread (no spawn, no registry handoff, no
    module global) is single-threaded as far as the analysis can see —
    even a field with a broken guard protocol stays quiet."""
    src = """
        import threading

        class Pump:
            def __init__(self):
                self._lock = threading.Lock()
                self._depth = 0

            def _run(self):
                with self._lock:
                    self._depth += 1
                with self._lock:
                    self._depth -= 1

            def depth(self):
                with self._lock:
                    return self._depth

            def spill(self):
                self._depth = 0  # would be HVDC108 if Pump escaped
    """
    findings = _lint_source(tmp_path, src)
    for rid in ("HVDC108", "HVDC109", "HVDC110"):
        assert not _new(findings, rid), rid


def test_racer_callee_held_lock_counts_as_guarded(tmp_path):
    """Interprocedural held-lock closure: a write in a helper with no
    visible ``with`` is guarded when EVERY call path into the helper
    holds the lock (the HVDC101-style fixpoint) — and becomes a finding
    the moment one lockless call site appears."""
    quiet = """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                with self._lock:
                    self._bump()
                with self._lock:
                    self._n = 0

            def get(self):
                with self._lock:
                    return self._n

            def peek(self):
                with self._lock:
                    return self._n

            def _bump(self):
                self._n += 1  # every caller holds self._lock
    """
    findings = _lint_source(tmp_path, quiet)
    assert not _new(findings, "HVDC108"), \
        [f.message for f in _new(findings, "HVDC108")]
    racy = quiet + """
            def poke(self):
                self._bump()  # lockless path into the helper
    """
    findings = _lint_source(tmp_path, racy)
    hits = _new(findings, "HVDC108")
    assert hits, "lockless call path into _bump must fire"
    assert "Counter" in hits[0].message
    assert "_n" in hits[0].message
    assert "_lock" in hits[0].message


def test_racer_no_dominant_guard_stays_quiet(tmp_path):
    """Threshold edge: with one guarded write, one unguarded write and
    an unguarded read, no lock reaches the guard fraction on either the
    all-access or the write-side criterion — no discernible discipline
    means nothing to enforce (reporting here would be noise)."""
    src = """
        import threading

        class Mixed:
            def __init__(self):
                self._lock = threading.Lock()
                self._x = 0

            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                with self._lock:
                    self._x = 1

            def a(self):
                self._x = 2

            def b(self):
                return self._x
    """
    findings = _lint_source(tmp_path, src)
    assert not _new(findings, "HVDC108")
    assert not _new(findings, "HVDC109")


# ---------------------------------------------------------------------------
# PR-20 self-application regressions: the races the rules found & fixed
# ---------------------------------------------------------------------------


def test_race_fix_engine_pending_params_shape(tmp_path):
    """Reduced shape of the EagerEngine._pending_params race: the
    negotiation loop drains the field under the engine lock and the
    replay path writes it under the lock, but the post-negotiation
    store skipped it.  HVDC108 must fire on the lockless store and go
    quiet once it is inside the lock — the shipped fix in
    runtime/engine.py."""
    bad = """
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self._pending = None

            def start(self):
                threading.Thread(target=self._loop).start()

            def _loop(self):
                while True:
                    with self._lock:
                        req = self._pending
                        self._pending = None
                    self._negotiate(req)

            def _negotiate(self, req):
                self._pending = req  # the bug: lockless store

            def replay(self, req):
                with self._lock:
                    self._pending = req
    """
    findings = _lint_source(tmp_path, bad, name="engine_shape.py")
    hits = _new(findings, "HVDC108")
    assert hits, "the pending-params shape must be rejected"
    assert "_pending" in hits[0].message
    fixed = bad.replace(
        "self._pending = req  # the bug: lockless store",
        "with self._lock:\n"
        "                    self._pending = req",
    )
    findings = _lint_source(tmp_path, fixed, name="engine_shape.py")
    assert not _new(findings, "HVDC108"), \
        [f.message for f in _new(findings, "HVDC108")]


def test_race_fix_frontend_stats_snapshot_shape(tmp_path):
    """Reduced shape of the FrontDoor.stats() race: the supervisor
    thread mutates owners/epoch under the lock while stats() reads them
    bare (the one-guarded-writer-many-lockless-readers shape that the
    write-side guard criterion exists for).  HVDC109 must fire on both
    fields; the snapshot-under-lock fix must be quiet."""
    bad = """
        import threading

        class Door:
            def __init__(self):
                self._lock = threading.Lock()
                self.owners = {}
                self.epoch = 0

            def start(self):
                threading.Thread(target=self._watch).start()

            def _watch(self):
                while True:
                    with self._lock:
                        self.owners = {"s0": "fe1"}
                        self.epoch += 1

            def stats(self):
                return {"owners": dict(self.owners),
                        "epoch": self.epoch}
    """
    findings = _lint_source(tmp_path, bad, name="door_shape.py")
    hits = _new(findings, "HVDC109")
    assert {m for f in hits for m in ("owners", "epoch")
            if m in f.message} == {"owners", "epoch"}, \
        [f.message for f in hits]
    fixed = bad.replace(
        'return {"owners": dict(self.owners),\n'
        '                        "epoch": self.epoch}',
        'with self._lock:\n'
        '                    return {"owners": dict(self.owners),\n'
        '                            "epoch": self.epoch}',
    )
    assert fixed != bad
    findings = _lint_source(tmp_path, fixed, name="door_shape.py")
    assert not _new(findings, "HVDC109"), \
        [f.message for f in _new(findings, "HVDC109")]


def test_race_fix_frontend_publish_doc_shape(tmp_path):
    """Reduced shape of the FrontDoor._publish_doc race: building the
    discovery document read owners/epoch with no lock before handing it
    to the KV store.  The fix snapshots under the lock and publishes
    outside it (publishing INSIDE would trade the race for an HVDC102
    blocking-call-under-lock finding)."""
    bad = """
        import threading

        class Door:
            def __init__(self, kv):
                self._lock = threading.Lock()
                self._kv = kv
                self.owners = {}
                self.epoch = 0

            def start(self):
                threading.Thread(target=self._watch).start()

            def _watch(self):
                while True:
                    with self._lock:
                        self.owners = {"s0": "fe1"}
                        self.epoch += 1
                    self.publish()

            def publish(self):
                doc = {"owners": dict(self.owners),
                       "epoch": self.epoch}
                self._kv.put("frontends", doc)
    """
    findings = _lint_source(tmp_path, bad, name="publish_shape.py")
    assert _new(findings, "HVDC109"), "lockless doc build must fire"
    fixed = bad.replace(
        'doc = {"owners": dict(self.owners),\n'
        '                       "epoch": self.epoch}\n',
        'with self._lock:\n'
        '                    doc = {"owners": dict(self.owners),\n'
        '                           "epoch": self.epoch}\n',
    )
    assert fixed != bad
    findings = _lint_source(tmp_path, fixed, name="publish_shape.py")
    assert not _new(findings, "HVDC109"), \
        [f.message for f in _new(findings, "HVDC109")]


def test_race_fix_frontend_takeover_log_read_shape(tmp_path):
    """Reduced shape of the FrontDoor._takeover race: the epoch bump
    happens under the lock but the log line after the block re-reads
    the field bare — a second takeover can bump it in between, logging
    the wrong epoch.  The fix captures a local inside the block."""
    bad = """
        import threading

        class Door:
            def __init__(self):
                self._lock = threading.Lock()
                self.epoch = 0

            def start(self):
                threading.Thread(target=self._watch).start()

            def _watch(self):
                with self._lock:
                    self.epoch += 1
                print("took over at epoch", self.epoch)
    """
    findings = _lint_source(tmp_path, bad, name="takeover_shape.py")
    hits = _new(findings, "HVDC109")
    assert hits and "epoch" in hits[0].message
    fixed = bad.replace(
        "self.epoch += 1\n"
        '                print("took over at epoch", self.epoch)',
        "self.epoch += 1\n"
        "                    epoch = self.epoch\n"
        '                print("took over at epoch", epoch)',
    )
    assert fixed != bad
    findings = _lint_source(tmp_path, fixed, name="takeover_shape.py")
    assert not _new(findings, "HVDC109"), \
        [f.message for f in _new(findings, "HVDC109")]


def test_self_application_is_clean_against_baseline():
    """The shipped tree lints clean: no new findings over horovod_tpu/
    + examples/ + scripts/ once the committed baseline (reasoned false
    positives only) is applied.  This is the acceptance criterion run
    in-process."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cfg = load_config(root)
    findings = analyze_paths(cfg.paths, root=root, exclude=cfg.exclude)
    baseline = load_baseline(os.path.join(root, cfg.baseline))
    for f in findings:
        if f.status == "new" and f.key() in baseline:
            f.status = "baselined"
    new = [f for f in findings if f.status == "new"]
    assert not new, [
        f"{f.path}:{f.line} {f.rule} {f.message}" for f in new
    ]
    # and the baseline itself carries a real reason per entry
    for entry in baseline.values():
        assert len(entry["reason"]) > 20


# ---------------------------------------------------------------------------
# suppression comments
# ---------------------------------------------------------------------------


def test_inline_suppression_same_line_and_line_above(tmp_path):
    findings = _lint_source(tmp_path, """
        import horovod_tpu as hvd

        def a(x, cond):
            if cond:
                return hvd.allreduce(x)  # hvdtpu: disable=HVD003
            return x

        def b(x, cond):
            if cond:
                # hvdtpu: disable=HVD003
                return hvd.allreduce(x)
            return x
    """)
    assert not _new(findings, "HVD003")
    assert sum(1 for f in findings if f.status == "suppressed") == 2


def test_suppression_is_per_rule(tmp_path):
    findings = _lint_source(tmp_path, """
        import horovod_tpu as hvd

        def a(x, cond):
            if cond:
                # hvdtpu: disable=HVD007
                return hvd.allreduce(x)
            return x
    """)
    assert _new(findings, "HVD003")  # wrong id: still fires


def test_suppression_inside_string_literal_ignored(tmp_path):
    findings = _lint_source(tmp_path, '''
        import horovod_tpu as hvd

        DOC = """example: # hvdtpu: disable=HVD003"""

        def a(x, cond):
            if cond:
                return hvd.allreduce(x)
            return x
    ''')
    assert _new(findings, "HVD003")


# ---------------------------------------------------------------------------
# CLI: exit codes, formats, baseline
# ---------------------------------------------------------------------------

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_cli(args, cwd):
    return subprocess.run(
        [sys.executable, "-m", "horovod_tpu.analysis", *args],
        cwd=cwd, capture_output=True, text=True, timeout=120,
        env={**os.environ,
             "PYTHONPATH": _REPO + os.pathsep
             + os.environ.get("PYTHONPATH", "")},
    )


@pytest.fixture(scope="module")
def cli_tmp(tmp_path_factory):
    d = tmp_path_factory.mktemp("lint_cli")
    (d / "bad.py").write_text(textwrap.dedent(FIXTURES["HVD001"][0]))
    (d / "good.py").write_text(textwrap.dedent(FIXTURES["HVD001"][1]))
    return d


@pytest.mark.serial
def test_cli_exit_codes(cli_tmp):
    r = _run_cli(["good.py"], cwd=cli_tmp)
    assert r.returncode == 0, r.stdout + r.stderr
    r = _run_cli(["bad.py"], cwd=cli_tmp)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "HVD001" in r.stdout


@pytest.mark.serial
def test_cli_json_schema(cli_tmp):
    r = _run_cli(["bad.py", "--format", "json"], cwd=cli_tmp)
    assert r.returncode == 1
    doc = json.loads(r.stdout)
    assert doc["schema"] == "hvdtpu-lint-v1"
    assert set(doc) >= {"schema", "rules", "findings", "summary"}
    assert doc["summary"]["new"] >= 1
    f = doc["findings"][0]
    assert set(f) >= {"rule", "severity", "path", "line", "col",
                      "message", "context", "status"}
    assert doc["rules"]["HVD001"]["severity"] == "error"


@pytest.mark.serial
def test_cli_baseline_roundtrip(cli_tmp):
    # findings baselined with a reason -> exit 0; reasonless -> exit 2
    r = _run_cli(["bad.py", "--format", "json"], cwd=cli_tmp)
    doc = json.loads(r.stdout)
    entries = [
        {"rule": f["rule"], "path": f["path"], "context": f["context"],
         "reason": "test fixture: acknowledged on purpose"}
        for f in doc["findings"]
    ]
    bl = cli_tmp / "bl.json"
    bl.write_text(json.dumps(
        {"schema": BASELINE_SCHEMA, "entries": entries}
    ))
    r = _run_cli(["bad.py", "--baseline", "bl.json"], cwd=cli_tmp)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "baselined" in r.stdout
    # empty reason must be rejected (the "no unreasoned baseline" rule)
    for e in entries:
        e["reason"] = ""
    bl.write_text(json.dumps(
        {"schema": BASELINE_SCHEMA, "entries": entries}
    ))
    r = _run_cli(["bad.py", "--baseline", "bl.json"], cwd=cli_tmp)
    assert r.returncode == 2
    assert "reason" in r.stderr


@pytest.mark.serial
def test_cli_rules_filter_and_list(cli_tmp):
    r = _run_cli(["bad.py", "--rules", "HVD005"], cwd=cli_tmp)
    assert r.returncode == 0  # HVD001 finding filtered out
    r = _run_cli(["--list-rules"], cwd=cli_tmp)
    assert r.returncode == 0
    for rid in FIXTURES:
        assert rid in r.stdout
    r = _run_cli(["bad.py", "--rules", "NOPE001"], cwd=cli_tmp)
    assert r.returncode == 2


def test_baseline_loader_rejects_missing_reason(tmp_path):
    p = tmp_path / "b.json"
    p.write_text(json.dumps({
        "schema": BASELINE_SCHEMA,
        "entries": [{"rule": "HVD001", "path": "x.py",
                     "context": "f"}],
    }))
    with pytest.raises(BaselineError):
        load_baseline(str(p))


def test_baseline_loader_rejects_wrong_schema(tmp_path):
    p = tmp_path / "b.json"
    p.write_text(json.dumps({"schema": "nope", "entries": []}))
    with pytest.raises(BaselineError):
        load_baseline(str(p))


def test_parse_error_is_a_finding(tmp_path):
    findings = _lint_source(tmp_path, "def broken(:\n")
    assert any(f.rule == "PARSE" for f in findings)


def test_pyproject_config_is_read():
    cfg = load_config(_REPO)
    assert cfg.paths == ["horovod_tpu", "examples", "scripts"]
    assert cfg.baseline == "horovod_tpu/analysis/baseline.json"


def test_config_fallback_parser(tmp_path):
    # the 3.10 path: no tomllib — the subset parser must read our block
    from horovod_tpu.analysis.config import _read_table_fallback

    p = tmp_path / "pyproject.toml"
    p.write_text(textwrap.dedent("""
        [project]
        name = "x"

        [tool.hvdtpu-lint]
        paths = ["a", "b"]  # trailing comments are legal TOML
        baseline = "bl.json"
        exclude = [
            "a/skip",  # and on list continuation lines too
        ]
    """))
    table = _read_table_fallback(str(p), "tool.hvdtpu-lint")
    assert table == {
        "paths": ["a", "b"], "baseline": "bl.json",
        "exclude": ["a/skip"],
    }


@pytest.mark.serial
def test_cli_config_error_is_exit_2(tmp_path):
    # A broken [tool.hvdtpu-lint] block must exit 2 (usage error), not
    # crash with a traceback that exits 1 and reads as "findings".
    (tmp_path / "pyproject.toml").write_text(textwrap.dedent("""
        [tool.hvdtpu-lint]
        paths = [unquoted]
    """))
    (tmp_path / "ok.py").write_text("x = 1\n")
    r = _run_cli(["--root", str(tmp_path)], cwd=tmp_path)
    assert r.returncode == 2, r.stdout + r.stderr
    assert "config" in r.stderr.lower()


def test_suppression_scanner_survives_tokenize_divergence(tmp_path):
    # ast.parse accepts some inputs the pure-Python tokenizer rejects
    # with TokenError (e.g. an unterminated trailing line continuation);
    # parse_suppressions must degrade to "no suppressions", not raise.
    from horovod_tpu.analysis.core import parse_suppressions

    assert parse_suppressions("x = 1\\") == {}


@pytest.mark.serial
def test_cli_rules_filter_does_not_report_stale_baseline(cli_tmp):
    # A --rules run sees a rule subset; baseline entries for other
    # rules must not be reported as stale ("fixed? remove it").
    r = _run_cli(["bad.py", "--format", "json"], cwd=cli_tmp)
    doc = json.loads(r.stdout)
    entries = [
        {"rule": f["rule"], "path": f["path"], "context": f["context"],
         "reason": "test fixture: acknowledged on purpose"}
        for f in doc["findings"]
    ]
    bl = cli_tmp / "bl_rules.json"
    bl.write_text(json.dumps(
        {"schema": BASELINE_SCHEMA, "entries": entries}
    ))
    r = _run_cli(
        ["--rules", "HVD005", "--baseline", "bl_rules.json", "bad.py"],
        cwd=cli_tmp,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "no longer matches" not in r.stderr


@pytest.mark.serial
def test_cli_changed_without_git_is_exit_2(tmp_path):
    (tmp_path / "x.py").write_text("x = 1\n")
    r = _run_cli(["--changed", "--root", str(tmp_path)], cwd=tmp_path)
    assert r.returncode == 2, r.stdout + r.stderr
    assert "git" in r.stderr


# ---------------------------------------------------------------------------
# per-file cache + baseline pruning + --changed robustness (ISSUE 12)
# ---------------------------------------------------------------------------


def test_cache_roundtrip_same_findings(tmp_path):
    from horovod_tpu.analysis import cache as cache_mod

    (tmp_path / "bad.py").write_text(
        textwrap.dedent(FIXTURES["HVD001"][0]))
    cp = str(tmp_path / "cache.json")
    cold = analyze_paths([str(tmp_path / "bad.py")],
                         root=str(tmp_path), cache_path=cp)
    assert os.path.isfile(cp)
    assert cache_mod.load_cache(cp)  # entries landed
    warm = analyze_paths([str(tmp_path / "bad.py")],
                         root=str(tmp_path), cache_path=cp)
    assert [(f.rule, f.path, f.line, f.message) for f in cold] == \
        [(f.rule, f.path, f.line, f.message) for f in warm]


def test_cache_hit_skips_module_rules(tmp_path, monkeypatch):
    from horovod_tpu.analysis import registry

    (tmp_path / "bad.py").write_text(
        textwrap.dedent(FIXTURES["HVD001"][0]))
    cp = str(tmp_path / "cache.json")
    analyze_paths([str(tmp_path / "bad.py")], root=str(tmp_path),
                  cache_path=cp)
    calls = []
    orig = registry.run_module_rules
    monkeypatch.setattr(
        registry, "run_module_rules",
        lambda model: calls.append(model.relpath) or orig(model))
    warm = analyze_paths([str(tmp_path / "bad.py")],
                         root=str(tmp_path), cache_path=cp)
    assert not calls, f"cache hit still ran module rules on {calls}"
    assert _new(warm, "HVD001")


def test_cache_invalidated_by_edit(tmp_path):
    p = tmp_path / "f.py"
    p.write_text(textwrap.dedent(FIXTURES["HVD001"][0]))
    cp = str(tmp_path / "cache.json")
    first = analyze_paths([str(p)], root=str(tmp_path), cache_path=cp)
    assert _new(first, "HVD001")
    p.write_text(textwrap.dedent(FIXTURES["HVD001"][1]))
    second = analyze_paths([str(p)], root=str(tmp_path), cache_path=cp)
    assert not _new(second, "HVD001")


def test_cache_subset_run_merges_instead_of_clobbering(tmp_path):
    # a --changed-style run over ONE file must not evict the other
    # files' entries from the cache
    from horovod_tpu.analysis import cache as cache_mod

    a = tmp_path / "a.py"
    b = tmp_path / "b.py"
    a.write_text("x = 1\n")
    b.write_text("y = 2\n")
    cp = str(tmp_path / "cache.json")
    analyze_paths([str(a), str(b)], root=str(tmp_path), cache_path=cp)
    assert set(cache_mod.load_cache(cp)) == {"a.py", "b.py"}
    a.write_text("x = 3\n")  # dirty, so the subset run rewrites
    analyze_paths([str(a)], root=str(tmp_path), cache_path=cp)
    assert set(cache_mod.load_cache(cp)) == {"a.py", "b.py"}


def test_cache_corruption_is_recomputed(tmp_path):
    p = tmp_path / "f.py"
    p.write_text(textwrap.dedent(FIXTURES["HVD001"][0]))
    cp = tmp_path / "cache.json"
    analyze_paths([str(p)], root=str(tmp_path), cache_path=str(cp))
    cp.write_text("{ not json")
    findings = analyze_paths([str(p)], root=str(tmp_path),
                             cache_path=str(cp))
    assert _new(findings, "HVD001")


def test_cache_rejected_on_rule_set_change(tmp_path):
    from horovod_tpu.analysis import cache as cache_mod

    p = tmp_path / "f.py"
    p.write_text("x = 1\n")
    cp = tmp_path / "cache.json"
    analyze_paths([str(p)], root=str(tmp_path), cache_path=str(cp))
    doc = json.loads(cp.read_text())
    doc["rules"] = "HVD999"  # a different analyzer wrote this
    cp.write_text(json.dumps(doc))
    assert cache_mod.load_cache(str(cp)) == {}


@pytest.mark.serial
def test_prune_baseline_removes_stale_entries(tmp_path):
    # a baseline with one live and one stale entry; --prune-baseline
    # must drop exactly the stale one and keep the live entry's reason.
    (tmp_path / "pyproject.toml").write_text(textwrap.dedent("""
        [tool.hvdtpu-lint]
        paths = ["bad.py"]
        baseline = "bl.json"
    """))
    (tmp_path / "bad.py").write_text(
        textwrap.dedent(FIXTURES["HVD001"][0]))
    r = _run_cli(["--no-baseline", "--format", "json"], cwd=tmp_path)
    doc = json.loads(r.stdout)
    entries = [
        {"rule": f["rule"], "path": f["path"], "context": f["context"],
         "reason": "live entry, still fires"}
        for f in doc["findings"]
    ]
    entries.append({
        "rule": "HVD007", "path": "gone.py", "context": "nope",
        "reason": "stale: the finding this acknowledged was fixed",
    })
    (tmp_path / "bl.json").write_text(json.dumps(
        {"schema": BASELINE_SCHEMA, "entries": entries}))
    r = _run_cli(["--prune-baseline"], cwd=tmp_path)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "pruned stale baseline entry" in r.stderr
    kept = json.loads((tmp_path / "bl.json").read_text())["entries"]
    assert all(e["path"] != "gone.py" for e in kept)
    assert any(e["reason"] == "live entry, still fires" for e in kept)


@pytest.mark.serial
def test_strict_baseline_exits_1_on_stale(tmp_path):
    (tmp_path / "pyproject.toml").write_text(textwrap.dedent("""
        [tool.hvdtpu-lint]
        paths = ["ok.py"]
        baseline = "bl.json"
    """))
    (tmp_path / "ok.py").write_text("x = 1\n")
    (tmp_path / "bl.json").write_text(json.dumps({
        "schema": BASELINE_SCHEMA,
        "entries": [{"rule": "HVD001", "path": "gone.py",
                     "context": "f", "reason": "stale on purpose"}],
    }))
    r = _run_cli(["--strict-baseline"], cwd=tmp_path)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "stale" in r.stderr
    # without the flag the same run is exit 0 (note only)
    r = _run_cli([], cwd=tmp_path)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "no longer matches" in r.stderr


@pytest.mark.serial
def test_prune_and_strict_rejected_on_partial_view(tmp_path):
    (tmp_path / "ok.py").write_text("x = 1\n")
    for extra in (["--rules", "HVD001"], ["ok.py"], ["--changed"]):
        for flag in ("--prune-baseline", "--strict-baseline"):
            r = _run_cli([flag, *extra], cwd=tmp_path)
            assert r.returncode == 2, (flag, extra, r.stderr)
            assert "full-surface" in r.stderr


@pytest.mark.serial
def test_changed_survives_deleted_and_renamed_files(tmp_path):
    # a deleted tracked file and a rename must not crash --changed (the
    # old names no longer exist on disk).
    def git(*a):
        subprocess.run(
            ["git", *a], cwd=tmp_path, check=True, capture_output=True,
            env={**os.environ,
                 "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
                 "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t"},
        )

    (tmp_path / "pyproject.toml").write_text(textwrap.dedent("""
        [tool.hvdtpu-lint]
        paths = ["src"]
        baseline = ""
    """))
    src = tmp_path / "src"
    src.mkdir()
    (src / "doomed.py").write_text("x = 1\n")
    (src / "old_name.py").write_text("y = 2\n")
    git("init", "-q")
    git("add", "-A")
    git("commit", "-qm", "seed")
    (src / "doomed.py").unlink()
    (src / "old_name.py").rename(src / "new_name.py")
    (src / "fresh.py").write_text(
        textwrap.dedent(FIXTURES["HVD001"][0]))
    r = _run_cli(["--changed"], cwd=tmp_path)
    # no traceback/exit-2 from the missing paths; the surviving files
    # are linted and the bad one still fails the run
    assert r.returncode == 1, r.stdout + r.stderr
    assert "HVD001" in r.stdout
    assert "Traceback" not in r.stderr


def test_write_baseline_preserves_curated_reasons(tmp_path):
    from horovod_tpu.analysis.baseline import (
        load_baseline, write_baseline,
    )
    from horovod_tpu.analysis.core import Finding

    f1 = Finding(rule="HVD001", severity="error", path="a.py", line=3,
                 col=0, message="m1", context="f")
    f2 = Finding(rule="HVD002", severity="warning", path="b.py", line=7,
                 col=0, message="m2", context="g")
    existing = {
        f1.key(): {"rule": "HVD001", "path": "a.py", "context": "f",
                   "reason": "curated justification, hand-written"},
    }
    out = tmp_path / "bl.json"
    write_baseline(str(out), [f1, f2], reason="", existing=existing)
    doc = json.loads(out.read_text())
    by_rule = {e["rule"]: e for e in doc["entries"]}
    # the pre-existing entry keeps its human reason...
    assert by_rule["HVD001"]["reason"] == \
        "curated justification, hand-written"
    # ...and the new entry's empty reason still fails the loader
    assert by_rule["HVD002"]["reason"] == ""
    with pytest.raises(Exception):
        load_baseline(str(out))


def test_lint_script_flag_values_not_paths():
    # "--format json" must NOT read 'json' as an explicit path (which
    # would silently disable the default --changed fast mode).
    sys.path.insert(0, os.path.join(_REPO, "scripts"))
    try:
        import lint as lint_script
    finally:
        sys.path.pop(0)
    assert not lint_script._has_explicit_paths(["--format", "json"])
    assert not lint_script._has_explicit_paths(
        ["--rules", "HVD001", "--format=json"])
    assert not lint_script._has_explicit_paths(["--jobs", "4"])
    assert not lint_script._has_explicit_paths(["-j", "4"])
    assert lint_script._has_explicit_paths(["horovod_tpu"])
    assert lint_script._has_explicit_paths(["--format", "json", "a.py"])


# ---------------------------------------------------------------------------
# --jobs: parallel per-file analysis
# ---------------------------------------------------------------------------


def test_jobs_parallel_matches_serial(tmp_path):
    """A --jobs run must be bit-identical to a serial run: same
    findings (rule/path/line/status) over a mixed dirty tree, including
    project-scope race findings whose closure runs in-process."""
    (tmp_path / "a.py").write_text(textwrap.dedent(FIXTURES["HVD001"][0]))
    (tmp_path / "b.py").write_text(textwrap.dedent(FIXTURES["HVDC108"][0]))
    (tmp_path / "c.py").write_text(textwrap.dedent(FIXTURES["HVD002"][1]))
    (tmp_path / "d.py").write_text(textwrap.dedent(FIXTURES["HVDC109"][0]))
    key = lambda fs: [(f.rule, f.path, f.line, f.status) for f in fs]  # noqa: E731
    serial = analyze_paths([str(tmp_path)], root=str(tmp_path))
    par = analyze_paths([str(tmp_path)], root=str(tmp_path), jobs=3)
    assert key(par) == key(serial)
    assert any(f.rule == "HVDC108" for f in par)


def test_jobs_cache_written_by_workers_is_coherent(tmp_path, monkeypatch):
    """The cache a parallel run persists must satisfy a later serial
    run as a plain content-hash hit — worker results travel in cache-
    entry shape, so an incoherent merge would show up here as a module-
    rule recompute (or wrong findings)."""
    from horovod_tpu.analysis import registry

    (tmp_path / "a.py").write_text(textwrap.dedent(FIXTURES["HVD001"][0]))
    (tmp_path / "b.py").write_text(textwrap.dedent(FIXTURES["HVDC108"][0]))
    cache = tmp_path / "cache.json"
    first = analyze_paths([str(tmp_path)], root=str(tmp_path),
                          cache_path=str(cache), jobs=2)
    assert cache.is_file()

    def boom(model):
        raise AssertionError(f"module rules re-ran for {model.relpath}")

    monkeypatch.setattr(registry, "run_module_rules", boom)
    warm = analyze_paths([str(tmp_path)], root=str(tmp_path),
                         cache_path=str(cache))
    key = lambda fs: [(f.rule, f.path, f.line) for f in fs]  # noqa: E731
    assert key(warm) == key(first)


@pytest.mark.serial
def test_cli_jobs_flag(cli_tmp):
    r = _run_cli(["bad.py", "--jobs", "2", "--no-cache"], cwd=cli_tmp)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "HVD001" in r.stdout
    r = _run_cli(["bad.py", "--jobs", "-3"], cwd=cli_tmp)
    assert r.returncode == 2
    assert "--jobs" in r.stderr


# ---------------------------------------------------------------------------
# --changed hardening + wrapper-level coverage
# ---------------------------------------------------------------------------


@pytest.mark.serial
def test_changed_handles_non_ascii_paths(tmp_path):
    """Text-mode ``git diff`` C-quotes non-ASCII paths (core.quotePath
    default), which the isfile() filter then silently drops — the file
    escapes the lint. ``-z`` keeps the bytes verbatim."""
    def git(*a):
        subprocess.run(
            ["git", *a], cwd=tmp_path, check=True, capture_output=True,
            env={**os.environ,
                 "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
                 "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t"},
        )

    from horovod_tpu.analysis.cli import _changed_files

    git("init", "-q")
    (tmp_path / "sürface.py").write_text("x = 1\n")
    git("add", "-A")
    git("commit", "-qm", "seed")
    (tmp_path / "sürface.py").write_text("x = 2\n")
    assert _changed_files(str(tmp_path)) == ["sürface.py"]


@pytest.mark.serial
def test_lint_script_survives_deleted_and_renamed_files(tmp_path):
    """Wrapper-level regression for the reported dev-loop crash: the
    `python scripts/lint.py` entry (which defaults to --changed) must
    ride out a working tree with deletions and renames."""
    def git(*a):
        subprocess.run(
            ["git", *a], cwd=tmp_path, check=True, capture_output=True,
            env={**os.environ,
                 "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
                 "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t"},
        )

    (tmp_path / "pyproject.toml").write_text(textwrap.dedent("""
        [tool.hvdtpu-lint]
        paths = ["src"]
        baseline = ""
    """))
    src = tmp_path / "src"
    src.mkdir()
    (src / "doomed.py").write_text("x = 1\n")
    (src / "old_name.py").write_text("y = 2\n")
    git("init", "-q")
    git("add", "-A")
    git("commit", "-qm", "seed")
    (src / "doomed.py").unlink()
    (src / "old_name.py").rename(src / "new_name.py")
    (src / "fresh.py").write_text(
        textwrap.dedent(FIXTURES["HVD001"][0]))
    r = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "lint.py"),
         "--root", str(tmp_path)],
        cwd=tmp_path, capture_output=True, text=True, timeout=120,
        env={**os.environ,
             "PYTHONPATH": _REPO + os.pathsep
             + os.environ.get("PYTHONPATH", "")},
    )
    assert r.returncode == 1, r.stdout + r.stderr
    assert "HVD001" in r.stdout
    assert "Traceback" not in r.stderr


# ---------------------------------------------------------------------------
# configured surface audit
# ---------------------------------------------------------------------------


def test_configured_surface_covers_package():
    """[tool.hvdtpu-lint] paths must cover EVERY python file under
    horovod_tpu/ except explicit excludes: a subpackage added without
    updating the config would otherwise silently escape the CI gate."""
    from horovod_tpu.analysis.cli import _iter_py_files

    cfg = load_config(_REPO)
    surface = set(_iter_py_files(cfg.paths, cfg.exclude, _REPO))
    excl = [os.path.normpath(os.path.join(_REPO, e))
            for e in cfg.exclude]

    def excluded(p):
        np_ = os.path.normpath(p)
        return any(np_ == e or np_.startswith(e + os.sep) for e in excl)

    missing = []
    pkg = os.path.join(_REPO, "horovod_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            fp = os.path.join(dirpath, fn)
            if fn.endswith(".py") and not excluded(fp) \
                    and fp not in surface:
                missing.append(os.path.relpath(fp, _REPO))
    assert not missing, \
        f"python files outside the configured lint surface: {missing}"
