"""Hierarchical (2-level, cross x local) collectives over a 2x4 virtual
mesh (reference: NCCLHierarchicalAllreduce, nccl_operations.cc:162-300;
AdasumGpuAllreduceOp, adasum_gpu_operations.cc)."""

import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.ops.adasum import _numpy_adasum_rows
from horovod_tpu.parallel.hierarchical import (
    hierarchical_adasum,
    hierarchical_allreduce,
)

N = 8  # 2 cross x 4 local


def _mesh2d():
    """A true 2 (cross) x 4 (local) mesh: the in-process topology reports
    one host, so hvd.mesh('hierarchical') would be 1x8; the 2-slice
    structure under test needs explicit construction."""
    import jax
    from jax.sharding import Mesh

    devices = np.asarray(jax.devices()[:N], dtype=object).reshape(2, 4)
    return Mesh(devices, (hvd.CROSS_AXIS, hvd.LOCAL_AXIS))


def _run(fn, x):
    mesh = _mesh2d()
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=(P((hvd.CROSS_AXIS, hvd.LOCAL_AXIS)),),
        out_specs=P((hvd.CROSS_AXIS, hvd.LOCAL_AXIS)),
    )(x)


@pytest.mark.parametrize("op", [hvd.Average, hvd.Sum])
@pytest.mark.parametrize("shape", [(5,), (3, 7)])
def test_hierarchical_allreduce_matches_flat(op, shape):
    rng = np.random.RandomState(0)
    x = rng.randn(N, *shape).astype(np.float32)

    def step(v):
        return hierarchical_allreduce(v[0], op)[None]

    out = _run(step, x)
    expect = x.sum(axis=0)
    if op == hvd.Average:
        expect = expect / N
    for r in range(N):
        np.testing.assert_allclose(np.asarray(out[r]), expect, rtol=1e-5)


def test_hierarchical_allreduce_uneven_size_pads():
    # length 5 not divisible by local_n=4: pad/unpad path
    x = np.arange(N * 5, dtype=np.float32).reshape(N, 5)

    def step(v):
        return hierarchical_allreduce(v[0], hvd.Sum)[None]

    out = _run(step, x)
    np.testing.assert_allclose(np.asarray(out[0]), x.sum(axis=0), rtol=1e-5)


def test_hierarchical_adasum_matches_reference_recursion():
    """local mean within each slice, then the VHDD projection across the
    2 slices, applied PER SHARD — each local rank runs the cross-slice
    Adasum on its own shard with its own coefficients, exactly the
    reference hierarchy (adasum_gpu_operations.cc: each local rank feeds
    its ReduceScatter shard to Adasum-MPI independently)."""
    rng = np.random.RandomState(1)
    x = rng.randn(N, 8).astype(np.float32)

    def step(v):
        return hierarchical_adasum(v[0])[None]

    out = _run(step, x)
    slice_means = x.reshape(2, 4, 8).mean(axis=1)  # per-slice local average
    # local_n=4 shards of the length-8 vector -> shard size 2; VHDD per shard
    expect = np.zeros(8, np.float32)
    for s in range(4):
        seg = slice_means[:, s * 2:(s + 1) * 2]
        expect[s * 2:(s + 1) * 2] = _numpy_adasum_rows(seg)
    for r in range(N):
        np.testing.assert_allclose(
            np.asarray(out[r]), expect, rtol=1e-5, atol=1e-6
        )


def test_hierarchical_adasum_identical_grads_behave_like_average():
    """Adasum of identical vectors returns that vector (the projection
    degenerates), so identical per-rank grads pass through unchanged."""
    x = np.tile(np.arange(6, dtype=np.float32), (N, 1))

    def step(v):
        return hierarchical_adasum(v[0])[None]

    out = _run(step, x)
    np.testing.assert_allclose(np.asarray(out[0]), x[0], rtol=1e-5)
