"""Sharded front door + tenant-aware QoS (ISSUE 16).

Fast half: the pure pieces — rid-hash routing, machine-readable
rejection codes, the tenant-weighted admission decision table (with the
single-tenant degenerate case byte-identical to FCFS and the HVD001
cross-rank replay property), multi-shard recovery interleave, client
poll backoff — plus the FrontDoor supervisor on a real KV store with
no serving fleet: kill a frontend, the survivor adopts its shards with
no drop and no double-ingest; kill the only frontend, a replacement is
spawned in place.

Slow half (CI frontdoor gate): a live fleet with F=2 frontends and
mixed tenants, one frontend killed mid-stream — every request completes
with tokens bitwise-identical to the single-stream oracle; and a
noisy-tenant flood where the flooder is throttled while its victims
still complete promptly.
"""

from __future__ import annotations

import pickle
import time
import zlib

import numpy as np
import pytest

from horovod_tpu.serve.frontend import (
    SCOPE, FrontDoor, IngestPump, Rejection, RequestRejected,
    ServeClient, shard_of, validate_request,
)
from horovod_tpu.serve.scheduler import (
    Request, SlotScheduler, TenantQoS,
)


def _req(rid, n=3, mnt=4, tenant="default", slo="standard"):
    return Request(rid=rid, prompt=tuple(range(1, n + 1)),
                   max_new_tokens=mnt, tenant=tenant, slo=slo)


# ---------------------------------------------------------------------------
# Routing: the pure rid hash
# ---------------------------------------------------------------------------


def test_shard_of_is_pure_crc32_mod_f():
    # The exact function, not "some hash": clients, pumps and workers
    # must all derive THIS route (PYTHONHASHSEED-proof by construction).
    for rid in ("a", "req-123", "f" * 16):
        assert shard_of(rid, 4) == zlib.crc32(rid.encode()) % 4
        assert shard_of(rid, 1) == 0
        assert shard_of(rid, 0) == 0
    # Sanity: a modest rid population touches every shard of F=4.
    shards = {shard_of(f"rid{i}", 4) for i in range(64)}
    assert shards == {0, 1, 2, 3}


# ---------------------------------------------------------------------------
# Rejection codes: machine-readable, str-compatible, picklable
# ---------------------------------------------------------------------------


def test_rejection_code_decision_table():
    cases = [
        ({"prompt": [], "max_new_tokens": 4}, "bad_prompt"),
        ({"prompt": [1, -2], "max_new_tokens": 4}, "bad_token"),
        ({"prompt": [1, 99], "max_new_tokens": 4}, "oob_token"),
        ({"prompt": [1], "max_new_tokens": 0}, "bad_budget"),
        ({"prompt": [1] * 14, "max_new_tokens": 8}, "ctx_exceeded"),
        ({"prompt": [1], "max_new_tokens": 2, "temperature": -1.0},
         "bad_temperature"),
        ({"prompt": [1], "max_new_tokens": 2, "top_k": -1}, "bad_top_k"),
        ({"prompt": [1], "max_new_tokens": 2, "tenant": ""},
         "bad_tenant"),
        ({"prompt": [1], "max_new_tokens": 2, "tenant": "a/b"},
         "bad_tenant"),
        ({"prompt": [1], "max_new_tokens": 2, "slo": "gold"}, "bad_slo"),
    ]
    for doc, code in cases:
        verdict = validate_request(doc, serve_len=16, vocab_size=64)
        assert isinstance(verdict, Rejection), doc
        assert verdict.code == code, (doc, verdict.code)
    ok = {"prompt": [1, 2], "max_new_tokens": 4, "tenant": "acme",
          "slo": "interactive"}
    assert validate_request(ok, serve_len=16, vocab_size=64) is None


def test_validate_request_rejects_cost_over_tenant_budget():
    """A request whose cost exceeds the WHOLE per-window budget could
    never be admitted: left in the queue it would brick its tenant's
    FIFO head in every window and stall the shard's compaction
    watermark forever — so validation rejects it up front."""
    doc = {"prompt": [1, 2, 3], "max_new_tokens": 8}  # cost 11
    v = validate_request(doc, serve_len=16, vocab_size=64,
                         budget_tokens=10)
    assert isinstance(v, Rejection) and v.code == "budget_exceeded"
    # Exactly at the budget is admissible; no policy means no check.
    assert validate_request(doc, serve_len=16, vocab_size=64,
                            budget_tokens=11) is None
    assert validate_request(doc, serve_len=16, vocab_size=64) is None


def test_rejection_is_a_str_and_pickles():
    r = Rejection("ctx_exceeded", "prompt too long")
    assert isinstance(r, str) and "too long" in r
    assert r.code == "ctx_exceeded" and r.message == "prompt too long"
    # The verdict crosses the KV wire inside pickled result docs.
    r2 = pickle.loads(pickle.dumps(r))
    assert r2 == r and r2.code == "ctx_exceeded"


# ---------------------------------------------------------------------------
# Tenant-weighted admission: the decision table
# ---------------------------------------------------------------------------


def _decision_log(sched, workload, steps=40):
    """Drive a scheduler through a canned workload; return the full
    decision log (admissions, evictions, queue state per step)."""
    log = []
    by_step = {}
    for step, req in workload:
        by_step.setdefault(step, []).append(req)
    for step in range(1, steps):
        for req in by_step.get(step, ()):
            sched.enqueue(req)
        admits = sched.admit(step)
        for a in admits:
            sched.record(a.slot, 7)
        for slot in sorted(sched.active):
            if not sched.active[slot].done:
                sched.record(slot, 7)
        evs = sched.evict_finished()
        log.append((
            step,
            tuple((a.slot, a.req.rid) for a in admits),
            tuple((e.slot, e.rid, e.reason) for e in evs),
            sched.queue_depth, sched.active_slots,
        ))
    return log


def test_single_tenant_degenerate_is_byte_identical_to_fcfs():
    """One tenant, one slo class, uniform weights: the QoS path must
    reproduce the FCFS schedule exactly — the policy is invisible
    until there is actual contention to arbitrate."""
    rng = np.random.RandomState(0)
    workload = []
    for i in range(12):
        workload.append((1 + i // 2,
                         _req(f"r{i}", n=int(rng.randint(1, 4)),
                              mnt=int(rng.randint(1, 5)))))
    fcfs = _decision_log(SlotScheduler(2), workload)
    qos = _decision_log(SlotScheduler(2, qos=TenantQoS()), workload)
    assert fcfs == qos


def test_slo_preemption_interactive_beats_earlier_batch():
    s = SlotScheduler(1, qos=TenantQoS())
    s.enqueue(_req("slow", tenant="t1", slo="batch"))
    s.enqueue(_req("fast", tenant="t2", slo="interactive"))
    (adm,) = s.admit(step=1)
    assert adm.req.rid == "fast"  # weight 8 beats weight 1, arrival be damned


def test_budget_exhaustion_throttles_and_window_refills():
    # cost = len(prompt) + mnt = 3 + 4 = 7; budget 10 admits one
    # request per tenant per window, never two.
    qos = TenantQoS(budget_tokens=10, window_steps=8)
    s = SlotScheduler(2, qos=qos)
    s.enqueue(_req("f0", tenant="flood", slo="batch"))
    s.enqueue(_req("f1", tenant="flood", slo="batch"))
    s.enqueue(_req("v0", tenant="victim", slo="standard"))
    admits = s.admit(step=1)
    # Both tenants' heads fit their window budget; victim's higher slo
    # weight (standard 4 > batch 1) admits it first despite arriving
    # last.
    assert [a.req.rid for a in admits] == ["v0", "f0"]
    assert s.throttled == {}
    while s.active:
        for slot in sorted(s.active):
            if not s.active[slot].done:
                s.record(slot, 7)
        s.evict_finished()
    # Same window: flood's next head would blow the budget (7+7 > 10)
    # — throttled, counted, nothing admitted.
    assert s.admit(step=2) == []
    assert s.throttled == {"flood": 1}
    # Next step-indexed window: spend resets, f1 admits.
    (adm,) = s.admit(step=8)
    assert adm.req.rid == "f1"
    assert s.admitted_tokens == {"flood": 14, "victim": 7}


def test_weighted_fairness_converges_to_weight_ratio():
    """Two tenants in one slo class with 2:1 custom weights: admitted
    tokens converge to ~2:1 because each admission advances the
    winner's virtual clock by cost/weight."""
    qos = TenantQoS(weights={"standard": 2, "batch": 1})
    s = SlotScheduler(1, qos=qos)
    for i in range(24):
        s.enqueue(_req(f"a{i}", tenant="a", slo="standard"))
        s.enqueue(_req(f"b{i}", tenant="b", slo="batch"))
    admitted = []
    for step in range(1, 40):
        for a in s.admit(step):
            admitted.append(a.req.tenant)
            s.record(a.slot, 7)
        for slot in sorted(s.active):
            if not s.active[slot].done:
                s.record(slot, 7)
        while s.active:
            for slot in sorted(s.active):
                if not s.active[slot].done:
                    s.record(slot, 7)
            s.evict_finished()
    a_n, b_n = admitted.count("a"), admitted.count("b")
    assert a_n + b_n >= 20
    assert 1.5 <= a_n / max(b_n, 1) <= 3.0


def test_qos_schedule_identical_across_simulated_ranks():
    """The HVD001 invariant extends through tenant-aware admission:
    N schedulers fed the same mixed-tenant log in the same order make
    identical decisions — including identical throttle accounting."""
    rng = np.random.RandomState(1)
    tenants = ["acme", "bigco", "solo"]
    slos = ["interactive", "standard", "batch"]
    ranks = [
        SlotScheduler(2, qos=TenantQoS(budget_tokens=32,
                                       window_steps=8))
        for _ in range(3)
    ]
    logs = [[] for _ in ranks]
    rid = 0
    for step in range(1, 50):
        arrivals = [
            _req(f"r{rid + i}", n=int(rng.randint(1, 4)),
                 mnt=int(rng.randint(1, 5)),
                 tenant=tenants[rng.randint(0, 3)],
                 slo=slos[rng.randint(0, 3)])
            for i in range(rng.randint(0, 3))
        ]
        rid += len(arrivals)
        for sched, log in zip(ranks, logs):
            for req in arrivals:
                sched.enqueue(req)
            admits = sched.admit(step)
            for a in admits:
                sched.record(a.slot, 7)
            for slot in sorted(sched.active):
                if not sched.active[slot].done:
                    sched.record(slot, 7)
            evs = sched.evict_finished()
            log.append((
                step,
                tuple((a.slot, a.req.rid, a.req.tenant) for a in admits),
                tuple((e.slot, e.rid) for e in evs),
                tuple(sorted(sched.throttled.items())),
                tuple(sorted(sched.admitted_tokens.items())),
                tuple(sorted(sched.tenant_depths().items())),
            ))
    assert logs[0] == logs[1] == logs[2]


def test_tenant_qos_from_spec():
    assert TenantQoS.from_spec(None) is None
    assert TenantQoS.from_spec({}) is None
    q = TenantQoS.from_spec({"budget_tokens": 64, "window_steps": 16,
                             "weights": {"batch": 2}})
    assert q.budget_tokens == 64 and q.window_steps == 16
    assert q.weight_of("batch") == 2 and q.weight_of("interactive") == 8
    with pytest.raises(ValueError, match="weights"):
        TenantQoS(weights={"batch": 0})
    with pytest.raises(ValueError, match="budget_tokens"):
        TenantQoS(budget_tokens=0)


# ---------------------------------------------------------------------------
# FrontDoor on a bare KV store: takeover without a fleet
# ---------------------------------------------------------------------------


@pytest.fixture
def kv_server():
    from horovod_tpu.run.rendezvous import KVStoreServer

    server = KVStoreServer()
    server.start()
    try:
        yield server
    finally:
        server.stop()


def _rids_for_shard(shard, frontends, count, salt=""):
    out = []
    i = 0
    while len(out) < count:
        rid = f"{salt}rid{i}"
        if shard_of(rid, frontends) == shard:
            out.append(rid)
        i += 1
    return out


def _wait(cond, timeout=5.0, tick=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(tick)
    return False


def test_frontdoor_takeover_adopts_shards_no_drop(kv_server):
    door = FrontDoor(kv_server, frontends=2, interval=0.01,
                     heartbeat_timeout=0.3)
    client = ServeClient(f"127.0.0.1:{kv_server.port}",
                         kv_server.secret)
    assert client.frontends() == 2
    door.start()
    try:
        s0 = _rids_for_shard(0, 2, 3)
        s1 = _rids_for_shard(1, 2, 2)
        for rid in s0 + s1:
            client.submit([1, 2], max_new_tokens=2, rid=rid)
        assert _wait(lambda: door.ingested == 5)
        door.kill(0)
        assert _wait(lambda: door.takeovers == 1)
        events = door.poll_takeover()
        assert len(events) == 1
        assert events[0]["fid"] == 0 and events[0]["owner"] == 1
        assert 0 in events[0]["shards"]
        assert door.owners[0] == 1 and door.fd_epoch == 1
        # Exactly one event — the supervisor must not re-fire it.
        time.sleep(0.5)
        assert door.poll_takeover() == []
        # Post-takeover traffic to the dead frontend's shard is
        # ingested by the survivor, continuing the shard's sequence
        # with no gap and no double-append.
        late = _rids_for_shard(0, 2, 2, salt="late")
        for rid in late:
            client.submit([3], max_new_tokens=1, rid=rid)
        assert _wait(lambda: door.ingested == 7)
        log0 = kv_server.scan(SCOPE + "/log/0/")
        ns = sorted(int(k.rsplit("/", 1)[1]) for k in log0)
        assert ns == list(range(len(s0) + len(late)))
        rids = {pickle.loads(b)["rid"] for b in log0.values()}
        assert rids == set(s0) | set(late)
        # gkeys carry the interleave constant F=2.
        gkeys = sorted(pickle.loads(b)["gkey"] for b in log0.values())
        assert gkeys == [n * 2 for n in ns]
        stats = door.stats()
        assert stats["takeovers"] == 1
        assert sum(stats["ingested_by_shard"].values()) == 7
        prom = door.prometheus()
        assert "hvdtpu_serve_frontend_count 2" in prom
        assert "hvdtpu_serve_frontend_takeovers 1" in prom
        assert 'hvdtpu_serve_frontend_up{fid="0"} 0' in prom
    finally:
        door.stop()


def test_frontdoor_respawns_replacement_when_no_survivor(kv_server):
    door = FrontDoor(kv_server, frontends=1, interval=0.01,
                     heartbeat_timeout=0.3)
    client = ServeClient(f"127.0.0.1:{kv_server.port}",
                         kv_server.secret)
    door.start()
    try:
        client.submit([1], max_new_tokens=1, rid="one")
        assert _wait(lambda: door.ingested == 1)
        door.kill(0)
        assert _wait(lambda: door.takeovers == 1)
        (ev,) = door.poll_takeover()
        assert ev == {"fid": 0, "owner": 0, "shards": [0]}
        client.submit([2], max_new_tokens=1, rid="two")
        assert _wait(lambda: door.ingested == 2)
        # The replacement pump continued the shard cursor.
        assert kv_server.scan(SCOPE + "/log/0/").keys() == {
            SCOPE + "/log/0/0", SCOPE + "/log/0/1"}
    finally:
        door.stop()


def test_frontend_exit_chaos_point_kills_pump_abruptly(
        kv_server, monkeypatch):
    """The frontend analog of worker_exit: the advisory fault spec
    kills the pump thread at its Nth beat without draining, and the
    supervisor detects it through the stale heartbeat path."""
    from horovod_tpu.testing import faults

    monkeypatch.setenv("HVDTPU_FAULT_SPEC",
                       "frontend_beat:action=frontend_exit:step=3:rank=0")
    faults.reset()
    try:
        door = FrontDoor(kv_server, frontends=2, interval=0.01,
                         heartbeat_timeout=0.3)
        door.start()
        try:
            assert _wait(lambda: door.takeovers == 1, timeout=8.0)
            (ev,) = door.poll_takeover()
            assert ev["fid"] == 0 and ev["owner"] == 1
            assert not door._pumps[0].alive()
            assert door._pumps[1].alive()
        finally:
            door.stop()
    finally:
        monkeypatch.delenv("HVDTPU_FAULT_SPEC")
        faults.reset()


def test_shard_fence_blocks_pump_that_lost_ownership(kv_server):
    """The false-positive-death race: a live-but-SLOW pump whose stale
    heartbeat triggered a takeover must not append concurrently with
    its adopter.  Driven synchronously (no threads): after the fence
    flips shard 0 to the survivor, the old owner's round is a no-op
    and the adopter continues the cursor with no gap, no drop, and no
    double-ingest."""
    from horovod_tpu.run.rendezvous import KVStoreClient
    from horovod_tpu.serve.frontend import _ShardFence

    kv = KVStoreClient(f"127.0.0.1:{kv_server.port}", kv_server.secret)
    fence = _ShardFence({0: 0, 1: 1})
    p0 = IngestPump(kv_server, fid=0, frontends=2, gc=False,
                    fence=fence)
    p1 = IngestPump(kv_server, fid=1, frontends=2, gc=False,
                    fence=fence)
    kv.put(SCOPE, "req/0/x", pickle.dumps(
        {"rid": "x", "prompt": [1], "max_new_tokens": 1}))
    assert p0.round() == 1
    # Takeover while p0 is "slow": ownership fences over, p1 adopts.
    fence.transfer(0, 1)
    p1.adopt([0])
    kv.put(SCOPE, "req/0/y", pickle.dumps(
        {"rid": "y", "prompt": [2], "max_new_tokens": 1}))
    # The zombie lost the shard: its round must append NOTHING and
    # leave the pending submission for the adopter.
    assert p0.round() == 0
    assert kv.get(SCOPE, "req/0/y") is not None
    assert p1.round() == 1
    log0 = kv_server.scan(SCOPE + "/log/0/")
    assert {k: pickle.loads(b)["rid"] for k, b in log0.items()} == {
        SCOPE + "/log/0/0": "x", SCOPE + "/log/0/1": "y"}


def test_shard_fence_lock_defers_round_until_released(kv_server):
    """A shard whose lock is held (a sibling mid-round) is skipped —
    never raced, never wedged behind — and picked up the next round."""
    from horovod_tpu.run.rendezvous import KVStoreClient
    from horovod_tpu.serve.frontend import _ShardFence

    kv = KVStoreClient(f"127.0.0.1:{kv_server.port}", kv_server.secret)
    fence = _ShardFence({0: 0})
    pump = IngestPump(kv_server, fid=0, frontends=1, gc=False,
                      fence=fence)
    kv.put(SCOPE, "req/0/z", pickle.dumps(
        {"rid": "z", "prompt": [3], "max_new_tokens": 1}))
    lock = fence.lock_of(0)
    lock.acquire()
    try:
        assert pump.round() == 0
        assert kv.get(SCOPE, "req/0/z") is not None
    finally:
        lock.release()
    assert pump.round() == 1
    assert kv.get(SCOPE, "req/0/z") is None


def test_unfiltered_frontend_exit_spares_gc_pump(kv_server,
                                                 monkeypatch):
    """A frontend_exit fault spec WITHOUT a rank filter must only ever
    kill frontend pumps: the GC pump (fid=-1) publishes no heartbeat,
    so killing it would silently stop stale-epoch and finished-output
    GC for the rest of the job."""
    from horovod_tpu.testing import faults

    monkeypatch.setenv("HVDTPU_FAULT_SPEC",
                       "frontend_beat:action=frontend_exit:step=3")
    faults.reset()
    try:
        door = FrontDoor(kv_server, frontends=1, interval=0.01,
                         heartbeat_timeout=0.3)
        door.start()
        try:
            assert _wait(lambda: door.takeovers == 1, timeout=8.0)
            assert door._gc_pump.alive()
        finally:
            door.stop()
    finally:
        monkeypatch.delenv("HVDTPU_FAULT_SPEC")
        faults.reset()


def test_gc_pump_respawned_by_supervisor(kv_server):
    """The GC duty must survive its own pump's death too: the
    supervisor watches the GC pump by thread liveness (it has no
    heartbeat) and respawns it in place — without counting a takeover
    or re-minting the fd epoch (no shards moved)."""
    door = FrontDoor(kv_server, frontends=1, interval=0.01,
                     heartbeat_timeout=0.3)
    door.start()
    try:
        original = door._gc_pump
        original.kill()
        assert _wait(lambda: door._gc_pump is not original
                     and door._gc_pump.alive())
        assert door.takeovers == 0 and door.fd_epoch == 0
    finally:
        door.stop()


def test_client_frontends_fallback_is_not_cached(kv_server):
    from horovod_tpu.run.rendezvous import KVStoreClient

    client = ServeClient(f"127.0.0.1:{kv_server.port}",
                         kv_server.secret)
    # No frontdoor doc yet: fall back to F=1 WITHOUT pinning it.
    assert client.frontends() == 1
    kv = KVStoreClient(f"127.0.0.1:{kv_server.port}", kv_server.secret)
    kv.put(SCOPE, "frontdoor", pickle.dumps(
        {"frontends": 4, "owners": {s: s for s in range(4)},
         "fd_epoch": 0}))
    # Doc published after the first read: the client picks up F=4 —
    # a client constructed before the FrontDoor must not route every
    # submission to shard 0 for its lifetime.
    assert client.frontends() == 4


def test_build_recovery_merges_shards_in_gkey_order(kv_server):
    from horovod_tpu.run.rendezvous import KVStoreClient
    from horovod_tpu.serve.service import (
        _build_recovery, _frontdoor_shape,
    )

    kv = KVStoreClient(f"127.0.0.1:{kv_server.port}", kv_server.secret)
    assert _frontdoor_shape(kv) == 1  # no doc yet: the pre-16 shape

    def entry(rid, shard, n):
        return pickle.dumps({"rid": rid, "prompt": [1, 2],
                             "max_new_tokens": 2, "shard": shard,
                             "n": n, "gkey": n * 2 + shard})

    # shard 0: n=1,2 (n=0 compacted below the watermark);
    # shard 1: n=0,1.  gkeys: s0/1->2, s0/2->4, s1/0->1, s1/1->3.
    kv.put(SCOPE, "log_watermark/0", b"1")
    kv.put(SCOPE, "log/0/1", entry("a", 0, 1))
    kv.put(SCOPE, "log/0/2", entry("b", 0, 2))
    kv.put(SCOPE, "log/1/0", entry("c", 1, 0))
    kv.put(SCOPE, "log/1/1", entry("d", 1, 1))
    # "c" already finished: recovery keeps only its compaction slot.
    kv.put(SCOPE, "out/c", pickle.dumps(
        {"rid": "c", "done": True, "tokens": [9], "shard": 1, "n": 0}))
    # "a" was mid-stream: its emitted prefix rides the replay.
    kv.put(SCOPE, "out/a", pickle.dumps(
        {"rid": "a", "done": False, "tokens": [5], "shard": 0, "n": 1}))

    rec = _build_recovery(kv, frontends=2)
    assert rec["frontends"] == 2
    assert rec["log_next"] == {0: 3, 1: 2}
    assert rec["watermark"] == {0: 1, 1: 0}
    assert rec["done_slots"] == [(1, 0)]
    # The interleave, not per-shard concatenation: gkey order 2, 3, 4.
    assert [(e["rid"], e["gkey"]) for e in rec["inflight"]] == [
        ("a", 2), ("d", 3), ("b", 4)]
    assert list(rec["inflight"][0]["emitted"]) == [5]

    # A width-sharded fleet splits the SAME order by gkey % groups.
    g0 = _build_recovery(kv, group=0, groups=2, frontends=2)
    g1 = _build_recovery(kv, group=1, groups=2, frontends=2)
    assert [e["rid"] for e in g0["inflight"]] == ["a", "b"]
    assert [e["rid"] for e in g1["inflight"]] == ["d"]
    assert g0["others"] == {(1, 1): "d"}


# ---------------------------------------------------------------------------
# Client: rejection surfacing + poll backoff
# ---------------------------------------------------------------------------


def test_client_surfaces_machine_readable_rejection(kv_server):
    from horovod_tpu.run.rendezvous import KVStoreClient

    kv = KVStoreClient(f"127.0.0.1:{kv_server.port}", kv_server.secret)
    kv.put(SCOPE, "out/bad", pickle.dumps({
        "rid": "bad", "done": True, "tokens": [],
        "error": "prompt (10) + max_new_tokens (8) exceeds the "
                 "16-token serving context",
        "error_code": "ctx_exceeded",
    }))
    client = ServeClient(f"127.0.0.1:{kv_server.port}",
                         kv_server.secret)
    with pytest.raises(RequestRejected) as ei:
        client.result("bad", timeout=5)
    assert ei.value.code == "ctx_exceeded"
    assert ei.value.rid == "bad" and "exceeds" in ei.value.message
    # str(exc) keeps matching the legacy pytest.raises(match=...) sites.
    assert "exceeds" in str(ei.value)


def test_client_result_backoff_caps_poll_rate(kv_server):
    """A request that never progresses is polled at an exponentially
    decaying rate (floor -> cap), not at the floor forever: over a 1s
    wait the client must land FAR fewer polls than fixed-floor
    polling's ~50."""
    calls = []
    client = ServeClient(f"127.0.0.1:{kv_server.port}",
                         kv_server.secret)
    orig = client.poll

    def counting_poll(rid):
        calls.append(time.monotonic())
        return orig(rid)

    client.poll = counting_poll
    with pytest.raises(TimeoutError):
        client.result("ghost", timeout=1.0,
                      poll_floor=0.02, poll_cap=0.5)
    assert 2 <= len(calls) <= 12
    # The last gap is at (or near) the cap, evidencing the decay.
    assert calls[-1] - calls[-2] >= 0.25


def test_client_result_backoff_resets_on_progress(kv_server):
    """Progress (more tokens) resets the delay to the floor: an
    actively streaming request is tracked closely even after a long
    quiet spell pushed the poll delay to the cap."""
    from horovod_tpu.run.rendezvous import KVStoreClient

    kv = KVStoreClient(f"127.0.0.1:{kv_server.port}", kv_server.secret)
    client = ServeClient(f"127.0.0.1:{kv_server.port}",
                         kv_server.secret)
    gaps = []
    last = [None]
    orig = client.poll

    def counting_poll(rid):
        now = time.monotonic()
        if last[0] is not None:
            gaps.append(now - last[0])
        last[0] = now
        return orig(rid)

    client.poll = counting_poll

    def feeder():
        # Quiet long enough for the delay to climb to the cap, then a
        # slow stream: the reset-to-floor shows up as tight polls
        # between the streamed updates.
        time.sleep(0.7)
        kv.put(SCOPE, "out/slow", pickle.dumps(
            {"rid": "slow", "done": False, "tokens": [1]}))
        time.sleep(0.3)
        kv.put(SCOPE, "out/slow", pickle.dumps(
            {"rid": "slow", "done": False, "tokens": [1, 2]}))
        time.sleep(0.3)
        kv.put(SCOPE, "out/slow", pickle.dumps(
            {"rid": "slow", "done": True, "tokens": [1, 2, 3]}))

    import threading

    t = threading.Thread(target=feeder, daemon=True)
    t.start()
    doc = client.result("slow", timeout=10.0,
                        poll_floor=0.02, poll_cap=0.4)
    t.join()
    assert doc["tokens"] == [1, 2, 3]
    assert max(gaps) >= 0.3  # the quiet spell hit the cap...
    # ...and progress reset the delay: after the longest (capped) gap
    # there are floor-scale polls again.
    after_cap = gaps[gaps.index(max(gaps)) + 1:]
    assert any(g <= 0.1 for g in after_cap)


# ---------------------------------------------------------------------------
# End-to-end acceptances (CI frontdoor gate)
# ---------------------------------------------------------------------------


@pytest.mark.multiprocess
@pytest.mark.slow
def test_frontdoor_kill_frontend_mid_stream_zero_drops_bitwise():
    """ISSUE 16 acceptance: np=1 fleet behind an F=2 sharded front
    door, 8 mixed-tenant requests, frontend 0 killed abruptly after
    half the submissions.  The survivor adopts shard 0, the elastic
    monitor re-mints the epoch (PR-13 machinery), the worker replays
    from the per-shard logs — and every request completes with tokens
    bitwise-identical to single-stream ``generate``.  Zero drops."""
    import jax
    import jax.numpy as jnp

    from horovod_tpu.models.decode import generate
    from horovod_tpu.models.transformer import gpt
    from horovod_tpu.serve import ServeJob

    o = dict(num_layers=1, num_heads=2, emb_dim=32, max_len=64,
             vocab_size=64, dtype=jnp.float32,
             attention_impl="reference")
    spec = {"size": "nano", "overrides": o, "seed": 3,
            "num_slots": 2, "idle_secs": 0.005, "frontends": 2}
    rs = np.random.RandomState(16)
    prompts = [rs.randint(0, 64, rs.randint(3, 9)).tolist()
               for _ in range(8)]
    steps = [3, 4, 5, 6, 3, 4, 5, 6]
    tenants = ["acme", "bigco"] * 4
    slos = ["interactive", "batch"] * 4
    rids = [f"fd{i}" for i in range(8)]

    model = gpt("nano", **o)
    params = model.init(jax.random.PRNGKey(3),
                        jnp.zeros((1, 8), jnp.int32))
    oracle = [
        np.asarray(generate(model.cfg, params,
                            jnp.asarray([p], jnp.int32), s))[0].tolist()
        for p, s in zip(prompts, steps)
    ]

    job = ServeJob(spec, np=1, env={"JAX_PLATFORMS": "cpu"},
                   timeout=300).start()
    try:
        for i, (p, s, r) in enumerate(zip(prompts, steps, rids)):
            job.client.submit(p, max_new_tokens=s, rid=r,
                              tenant=tenants[i], slo=slos[i])
            time.sleep(0.05)
            if i == 3:
                job.front_door.kill(0)
        docs = [job.client.result(r, timeout=240) for r in rids]
        stats = job.front_door.stats()
        results, ejob = job.stop()
    finally:
        job.shutdown()
    assert [d["tokens"] for d in docs] == oracle
    assert stats["frontends"] == 2 and stats["takeovers"] == 1
    assert stats["owners"][0] == 1  # shard 0 adopted by frontend 1
    # Both shards carried real traffic (the split is capacity).
    assert set(stats["ingested_by_shard"]) == {0, 1}
    events = [e[0] for e in ejob.trace]
    assert events.count("frontend_takeover") == 1
    assert results[0]["completed"] == 8
    assert results[0].get("frontends") == 2


@pytest.mark.multiprocess
@pytest.mark.slow
def test_frontdoor_noisy_tenant_throttled_victims_complete():
    """ISSUE 16 acceptance, QoS leg: a flooding batch tenant saturates
    the fleet while two interactive victims arrive late.  The budget
    throttles the flooder (throttle counter > 0 in the drain summary)
    and every victim still completes with oracle tokens — the flood
    cannot starve them."""
    import jax
    import jax.numpy as jnp

    from horovod_tpu.models.decode import generate
    from horovod_tpu.models.transformer import gpt
    from horovod_tpu.serve import ServeJob

    o = dict(num_layers=1, num_heads=2, emb_dim=32, max_len=64,
             vocab_size=64, dtype=jnp.float32,
             attention_impl="reference")
    spec = {"size": "nano", "overrides": o, "seed": 3,
            "num_slots": 2, "idle_secs": 0.005,
            "tenants": {"budget_tokens": 24, "window_steps": 16}}
    rs = np.random.RandomState(17)
    flood_prompts = [rs.randint(0, 64, 8).tolist() for _ in range(6)]
    victim_prompts = [rs.randint(0, 64, 4).tolist() for _ in range(2)]

    model = gpt("nano", **o)
    params = model.init(jax.random.PRNGKey(3),
                        jnp.zeros((1, 8), jnp.int32))

    def oracle(p, s):
        return np.asarray(generate(
            model.cfg, params, jnp.asarray([p], jnp.int32),
            s))[0].tolist()

    job = ServeJob(spec, np=1, env={"JAX_PLATFORMS": "cpu"},
                   timeout=300).start()
    try:
        flood = [job.client.submit(p, max_new_tokens=6, tenant="flood",
                                   slo="batch")
                 for p in flood_prompts]
        time.sleep(0.3)
        t0 = time.monotonic()
        victims = [job.client.submit(p, max_new_tokens=4,
                                     tenant="victim",
                                     slo="interactive")
                   for p in victim_prompts]
        vdocs = [job.client.result(r, timeout=120) for r in victims]
        victim_secs = time.monotonic() - t0
        fdocs = [job.client.result(r, timeout=240) for r in flood]
        results, _ = job.stop()
    finally:
        job.shutdown()
    assert [d["tokens"] for d in vdocs] == [
        oracle(p, 4) for p in victim_prompts]
    assert [d["tokens"] for d in fdocs] == [
        oracle(p, 6) for p in flood_prompts]
    tstats = results[0].get("tenants") or {}
    assert tstats.get("flood", {}).get("throttled", 0) > 0
    assert tstats.get("victim", {}).get("admitted_tokens", 0) > 0
    # Victims finished while flood work remained — generously bounded
    # (CPU CI box), but far tighter than draining the whole flood.
    assert victim_secs < 60.0
