"""Negotiation-protocol unit tests, in-process with synthetic request lists
(the strategy the reference uses for launcher/controller logic in
test/test_run.py — no multi-process needed to pin the protocol down)."""

import numpy as np
import pytest

from horovod_tpu.runtime.controller import ControllerState, compute_responses
from horovod_tpu.runtime.messages import (
    Request,
    RequestList,
    RequestType,
    Response,
    ResponseType,
)

FUSION = 64 * 1024 * 1024


def req(rank, name, rtype=RequestType.ALLREDUCE, shape=(4,), dtype="float32", **kw):
    return Request(
        request_rank=rank,
        request_type=rtype,
        tensor_name=name,
        dtype=dtype,
        shape=shape,
        **kw,
    )


def cycle(state, lists):
    return compute_responses(state, lists, fusion_threshold_bytes=FUSION)


def test_tensor_ready_only_when_all_ranks_submitted():
    state = ControllerState(world_size=2)
    out, _ = cycle(state, [RequestList([req(0, "t")]), RequestList([])])
    assert out == []
    out, _ = cycle(state, [RequestList([]), RequestList([req(1, "t")])])
    assert len(out) == 1
    assert out[0].response_type == ResponseType.ALLREDUCE
    assert out[0].tensor_names == ["t"]


def test_request_list_roundtrip():
    rl = RequestList(
        [req(1, "x", RequestType.BROADCAST, (2, 3), "int32", root_rank=1)],
        shutdown=True,
        joined=False,
    )
    back = RequestList.deserialize(rl.serialize())
    assert back.shutdown and not back.joined
    assert back.requests[0].tensor_name == "x"
    assert back.requests[0].request_type == RequestType.BROADCAST
    assert back.requests[0].shape == (2, 3)
    assert back.requests[0].root_rank == 1


def test_dtype_mismatch_produces_error_response():
    state = ControllerState(world_size=2)
    out, _ = cycle(
        state,
        [
            RequestList([req(0, "t", dtype="float32")]),
            RequestList([req(1, "t", dtype="int32")]),
        ],
    )
    assert out[0].response_type == ResponseType.ERROR
    assert "Mismatched data types" in out[0].error_message


def test_shape_mismatch_produces_error_response():
    state = ControllerState(world_size=2)
    out, _ = cycle(
        state,
        [
            RequestList([req(0, "t", shape=(4,))]),
            RequestList([req(1, "t", shape=(5,))]),
        ],
    )
    assert out[0].response_type == ResponseType.ERROR
    assert "Mismatched shapes" in out[0].error_message


def test_allgather_ragged_sizes_negotiated():
    state = ControllerState(world_size=3)
    out, _ = cycle(
        state,
        [
            RequestList([req(0, "g", RequestType.ALLGATHER, (2, 7))]),
            RequestList([req(1, "g", RequestType.ALLGATHER, (5, 7))]),
            RequestList([req(2, "g", RequestType.ALLGATHER, (1, 7))]),
        ],
    )
    assert out[0].response_type == ResponseType.ALLGATHER
    assert out[0].tensor_sizes == [2, 5, 1]


def test_allgather_scalar_is_error_not_crash():
    """A 0-d allgather must become an ERROR response, not an IndexError
    that kills the engine loop."""
    state = ControllerState(world_size=2)
    out, _ = cycle(
        state,
        [
            RequestList([req(0, "s", RequestType.ALLGATHER, ())]),
            RequestList([req(1, "s", RequestType.ALLGATHER, ())]),
        ],
    )
    assert out[0].response_type == ResponseType.ERROR
    assert "1-dimensional" in out[0].error_message


def test_allgather_trailing_shape_mismatch_is_error():
    state = ControllerState(world_size=2)
    out, _ = cycle(
        state,
        [
            RequestList([req(0, "g", RequestType.ALLGATHER, (2, 7))]),
            RequestList([req(1, "g", RequestType.ALLGATHER, (5, 8))]),
        ],
    )
    assert out[0].response_type == ResponseType.ERROR


def test_broadcast_root_mismatch_is_error():
    state = ControllerState(world_size=2)
    out, _ = cycle(
        state,
        [
            RequestList([req(0, "b", RequestType.BROADCAST, root_rank=0)]),
            RequestList([req(1, "b", RequestType.BROADCAST, root_rank=1)]),
        ],
    )
    assert out[0].response_type == ResponseType.ERROR
    assert "root rank" in out[0].error_message.lower()


def test_fusion_groups_same_dtype_adjacent_allreduces():
    state = ControllerState(world_size=1)
    lists = [
        RequestList(
            [
                req(0, "a", dtype="float32"),
                req(0, "b", dtype="float32"),
                req(0, "c", dtype="int32"),
                req(0, "d", dtype="float32"),
            ]
        )
    ]
    out, _ = cycle(state, lists)
    # a+b fuse; c breaks the run (dtype); d starts a new group
    names = [r.tensor_names for r in out]
    assert names == [["a", "b"], ["c"], ["d"]]


def test_fusion_respects_threshold():
    state = ControllerState(world_size=1)
    big = (1024 * 1024,)  # 4 MB each at fp32
    lists = [RequestList([req(0, f"t{i}", shape=big) for i in range(4)])]
    out, _ = compute_responses(
        state, lists, fusion_threshold_bytes=8 * 1024 * 1024
    )
    names = [r.tensor_names for r in out]
    assert names == [["t0", "t1"], ["t2", "t3"]]


def test_mixed_reduce_ops_do_not_fuse():
    state = ControllerState(world_size=1)
    lists = [
        RequestList(
            [req(0, "a", reduce_op=1), req(0, "b", reduce_op=2)]
        )
    ]
    out, _ = cycle(state, lists)
    assert [r.tensor_names for r in out] == [["a"], ["b"]]


def test_join_lowers_required_count_and_completes():
    """reference controller.cc:219-221,263-307: joined ranks are excluded
    from readiness counting; all-joined emits a JOIN response."""
    state = ControllerState(world_size=2)
    # rank 1 joins; rank 0 still reducing
    out, _ = cycle(
        state,
        [RequestList([req(0, "t")]), RequestList([], joined=True)],
    )
    # t is ready with only rank 0's request (needed = 2 - 1 joined)
    assert any(
        r.response_type == ResponseType.ALLREDUCE and r.tensor_names == ["t"]
        for r in out
    )
    assert not any(r.response_type == ResponseType.JOIN for r in out)
    # now rank 0 joins too -> JOIN response, state reset
    out2, _ = cycle(
        state,
        [RequestList([], joined=True), RequestList([], joined=True)],
    )
    assert any(r.response_type == ResponseType.JOIN for r in out2)
    assert state.joined_ranks == set()


def test_shutdown_propagates():
    state = ControllerState(world_size=2)
    _, stop = cycle(
        state, [RequestList([], shutdown=True), RequestList([])]
    )
    assert stop


def test_deterministic_order_across_cycles():
    """Responses come out in first-arrival order — identical on every rank
    because inputs are identical (the invariant replacing rank-0 bcast)."""
    state = ControllerState(world_size=2)
    cycle(state, [RequestList([req(0, "z"), req(0, "a")]), RequestList([])])
    out, _ = cycle(
        state,
        [RequestList([]), RequestList([req(1, "a"), req(1, "z")])],
    )
    flat = [n for r in out for n in r.tensor_names]
    assert flat == ["z", "a"]  # rank 0's arrival order, not alphabetical


def test_stall_warning_logged(caplog):
    import horovod_tpu.runtime.controller as ctl

    state = ControllerState(world_size=2)
    cycle(state, [RequestList([req(0, "stuck")]), RequestList([])])
    # age the entry artificially and force the check window open
    key = ("stuck", RequestType.ALLREDUCE)
    state.message_table[key].first_seen -= 100.0
    state.last_stall_check -= 100.0
    import logging

    root = logging.getLogger("horovod_tpu")
    root.propagate = True  # let caplog's root handler see it
    try:
        with caplog.at_level("WARNING", logger="horovod_tpu.controller"):
            compute_responses(
                state,
                [RequestList([]), RequestList([])],
                fusion_threshold_bytes=FUSION,
                stall_warning_secs=60.0,
            )
    finally:
        root.propagate = False
    assert any("waiting on ranks [1]" in r.getMessage() for r in caplog.records)
