"""User-facing error paths: the guard rails a migrating script hits first.

The reference's equivalents are its check_extension/initialization guards
and per-op validation errors (common.h:161, controller.cc:378-611); here
each misuse must fail loudly with an actionable message, not hang or
produce garbage.
"""

import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd


def test_rank_before_init_raises_cleanly():
    # conftest initializes the in-process world, so before-init behavior
    # needs a fresh interpreter.
    code = (
        "import os;"
        "os.environ['JAX_PLATFORMS']='cpu';"
        "import horovod_tpu as hvd;"
        "from horovod_tpu.basics import NotInitializedError\n"
        "try:\n"
        "    hvd.rank()\n"
        "    print('NO-ERROR')\n"
        "except NotInitializedError as e:\n"
        "    print('OK:', e)\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=240,
    )
    assert "OK:" in out.stdout, (out.stdout, out.stderr)
    assert "init()" in out.stdout  # message tells the user what to call


def test_double_init_is_noop():
    topo_before = hvd.basics.global_topology()
    hvd.init()  # second init must not rebuild or error (reference
    #             InitializeHorovodOnce latches, operations.cc:604-650)
    assert hvd.basics.global_topology() is topo_before


def test_unknown_mesh_shape_raises():
    with pytest.raises(ValueError, match="mesh"):
        hvd.mesh("cube")


def test_alltoall_nondivisible_dim0_raises_at_trace():
    mesh = hvd.mesh("flat")
    n = len(mesh.devices.flat)
    x = jnp.ones((n * n + 1,), jnp.float32)  # dim0 % n != 0 per shard

    with pytest.raises(ValueError, match="divide"):
        shard_map(
            lambda v: hvd.alltoall(v),
            mesh=mesh,
            in_specs=(P(),),
            out_specs=P(),
        )(x)


def test_broadcast_bad_root_raises():
    with pytest.raises(ValueError):
        hvd.broadcast(np.ones(2, np.float32), root_rank=99)


def test_allreduce_unknown_op_rejected():
    with pytest.raises((ValueError, TypeError, KeyError)):
        hvd.allreduce(np.ones(2, np.float32), op="definitely-not-an-op")
