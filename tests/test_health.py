"""Training-health plane (obs/health.py + obs/divergence.py): digest
algebra (digest equality ⟺ bitwise equality on adversarial float pairs
— ±0.0, NaN payloads, denormals — and host/in-graph parity), the
anomaly judge as a pure decision table (spike/ramp/plateau/nonfinite,
rising-edge counting, min-sample guard), the divergence sentinel's
localization with an injected exchange, the HLO-unchanged-when-off
artifact check on ``OverlapPlan.local_step``, the ``grad_ready`` fault
actions, and the postmortem folding of health events."""

from __future__ import annotations

import json
import re
import time

import numpy as np
import pytest

import horovod_tpu.obs as obs
from horovod_tpu.obs import divergence, flightrec, health, postmortem
from horovod_tpu.testing import faults


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    monkeypatch.delenv("HVDTPU_FAULT_SPEC", raising=False)
    faults.reset()
    obs.reset_registry()
    flightrec.reset_recorder()
    yield
    faults.reset()
    obs.reset_registry()
    flightrec.reset_recorder()


# ---------------------------------------------------------------------------
# digest algebra
# ---------------------------------------------------------------------------


def test_digest_bitwise_equality_on_adversarial_float_pairs():
    """Value-equal but bit-different pairs MUST digest differently;
    bit-identical arrays MUST digest identically."""
    pos_zero = np.array([0.0], np.float32)
    neg_zero = np.array([-0.0], np.float32)
    assert pos_zero[0] == neg_zero[0]  # value comparison waves it through
    assert not np.array_equal(divergence.digest_array(pos_zero),
                              divergence.digest_array(neg_zero))

    nan_a = np.uint32(0x7FC00000).reshape(1).view(np.float32)
    nan_b = np.uint32(0x7FC00001).reshape(1).view(np.float32)
    assert not np.array_equal(divergence.digest_array(nan_a),
                              divergence.digest_array(nan_b))

    denorm = np.array([1e-42], np.float32)
    zero = np.array([0.0], np.float32)
    assert not np.array_equal(divergence.digest_array(denorm),
                              divergence.digest_array(zero))

    x = np.linspace(-3, 3, 97).astype(np.float32)
    assert np.array_equal(divergence.digest_array(x),
                          divergence.digest_array(x.copy()))


def test_digest_single_bit_flip_always_detected():
    """M odd ⟹ the per-word mix is bijective: any single-element bit
    flip, at any position, changes the digest."""
    base = np.arange(64, dtype=np.float32)
    ref = divergence.digest_array(base)
    for pos in (0, 1, 31, 63):
        for bit in (0, 7, 22, 31):
            mutated = base.copy()
            raw = mutated.view(np.uint32)
            raw[pos] ^= np.uint32(1) << np.uint32(bit)
            assert not np.array_equal(divergence.digest_array(mutated),
                                      ref), (pos, bit)


def test_digest_dtype_coverage_and_length_mixing():
    for dt in (np.float16, np.float32, np.float64, np.int8, np.uint8,
               np.int32, np.int64):
        arr = np.arange(7).astype(dt)
        d = divergence.digest_array(arr)
        assert d.shape == (divergence.DIGEST_WIDTH,)
        assert d.dtype == np.uint32
    # zero padding is not invisible: [x] vs [x, 0] differ
    a = np.array([1.5], np.float32)
    b = np.array([1.5, 0.0], np.float32)
    assert not np.array_equal(divergence.digest_array(a),
                              divergence.digest_array(b))
    # empty arrays digest deterministically
    assert np.array_equal(
        divergence.digest_array(np.empty(0, np.float32)),
        divergence.digest_array(np.empty(0, np.float32)))


def test_digest_concat_order_sensitivity():
    a = np.array([1.0, 2.0], np.float32)
    b = np.array([3.0], np.float32)
    assert not np.array_equal(divergence.digest_leaves([a, b]),
                              divergence.digest_leaves([b, a]))


def test_jit_digest_matches_host_digest():
    """The in-graph digest is byte-for-byte the host digest — the
    device and host halves of the sentinel can be mixed freely."""
    import jax
    import jax.numpy as jnp

    from horovod_tpu.optim.overlap import build_layout

    params = {"w1": np.linspace(-2, 2, 32).astype(np.float32)
              .reshape(8, 4),
              "b": np.array([0.0, -0.0, 1e-42, np.inf], np.float32)}
    leaves, _ = jax.tree_util.tree_flatten(params)
    layout = build_layout(params, 64)
    vec, names = divergence.tree_digest_vector(leaves, layout)
    host = vec.reshape(len(layout.buckets), divergence.DIGEST_WIDTH)
    dev = np.asarray(
        divergence.jit_digest(layout)(*[jnp.asarray(l) for l in leaves])
    )
    assert np.array_equal(dev, host)


def test_blob_and_page_state_digest():
    assert np.array_equal(divergence.blob_digest(b"abc"),
                          divergence.blob_digest(b"abc"))
    assert not np.array_equal(divergence.blob_digest(b"abc"),
                              divergence.blob_digest(b"abd"))
    assert divergence.page_state_digest(None).shape == (
        divergence.DIGEST_WIDTH,)


# ---------------------------------------------------------------------------
# anomaly judge: pure decision table
# ---------------------------------------------------------------------------


def _warm(judge, n=10, loss=1.0, grad=1.0):
    for _ in range(n):
        assert judge.observe(loss=loss, grad_norm=grad) == []


def test_judge_loss_spike_fires_and_is_rising_edge():
    j = health.AnomalyJudge(min_samples=4)
    _warm(j)
    alerts = j.observe(loss=500.0, grad_norm=1.0)
    assert [a.cls for a in alerts] == ["loss-spike"]
    assert alerts[0].rising
    # persists: still firing, but NOT another rising edge
    alerts = j.observe(loss=500.0, grad_norm=1.0)
    assert alerts and not alerts[0].rising
    assert j.alerts_total["loss-spike"] == 1
    # recovers, then spikes again: a second episode counts again
    for _ in range(12):
        j.observe(loss=1.0, grad_norm=1.0)
    assert j.observe(loss=500.0, grad_norm=1.0)[0].rising
    assert j.alerts_total["loss-spike"] == 2


def test_judge_downward_loss_move_is_not_a_spike():
    j = health.AnomalyJudge(min_samples=4)
    _warm(j, loss=100.0)
    assert j.observe(loss=0.01, grad_norm=1.0) == []


def test_judge_gradual_ramp_does_not_fire():
    """The EWMA tracks a steady ramp; only a step change is a spike."""
    j = health.AnomalyJudge(min_samples=4)
    loss = 1.0
    for _ in range(200):
        loss *= 1.01
        assert j.observe(loss=loss, grad_norm=1.0) == []


def test_judge_plateau_stays_silent():
    j = health.AnomalyJudge(min_samples=4)
    for _ in range(100):
        assert j.observe(loss=3.14, grad_norm=0.5) == []


def test_judge_grad_explode_and_vanish():
    j = health.AnomalyJudge(min_samples=4)
    _warm(j)
    assert [a.cls for a in j.observe(loss=1.0, grad_norm=1e6)] == \
        ["grad-explode"]
    j2 = health.AnomalyJudge(min_samples=4, vanish_frac=1e-3)
    _warm(j2)
    assert [a.cls for a in j2.observe(loss=1.0, grad_norm=1e-7)] == \
        ["grad-vanish"]


def test_judge_min_sample_guard_blocks_cold_relative_rules():
    """A spike on observation 2 is warmup noise, not an anomaly."""
    j = health.AnomalyJudge(min_samples=8)
    j.observe(loss=1.0, grad_norm=1.0)
    assert j.observe(loss=1e9, grad_norm=1e9) == []


def test_judge_nonfinite_is_absolute_and_skips_baseline():
    """Nonfinite fires even before min_samples, and a NaN loss must
    not poison the EWMA baseline."""
    j = health.AnomalyJudge(min_samples=8)
    alerts = j.observe(loss=float("nan"), grad_norm=1.0)
    assert [a.cls for a in alerts] == ["nonfinite"]
    assert alerts[0].rising
    assert j.loss.n == 0  # baseline untouched
    _warm(j)
    assert [a.cls for a in j.observe(loss=1.0, grad_norm=1.0,
                                     nonfinite=3)] == ["nonfinite"]


def test_judge_dead_gradient_needs_a_streak():
    j = health.AnomalyJudge(min_samples=4, dead_steps=5)
    _warm(j)
    for i in range(4):
        assert j.observe(loss=1.0, grad_norm=1.0,
                         bucket_norms=[1.0, 0.0]) == [], i
    alerts = j.observe(loss=1.0, grad_norm=1.0, bucket_norms=[1.0, 0.0])
    assert [a.cls for a in alerts] == ["dead-gradient"]
    assert "bucket=1" in alerts[0].detail
    # one live step resets the streak
    j.observe(loss=1.0, grad_norm=1.0, bucket_norms=[1.0, 0.5])
    assert j.observe(loss=1.0, grad_norm=1.0,
                     bucket_norms=[1.0, 0.0]) == []


# ---------------------------------------------------------------------------
# monitor publishing
# ---------------------------------------------------------------------------


def _metric(name, **tags):
    for m in obs.get_registry().snapshot():
        if m["name"] == name and (not tags or m.get("tags") == tags):
            return m
    return None


def test_monitor_publishes_bundle_and_rising_edges():
    mon = health.HealthMonitor(n_buckets=2)
    bundle = np.array([2.5, 3.0, 0.01, 0.0, 1.0, 2.0])
    for step in range(10):
        mon.observe_bundle(step, bundle)
    assert _metric("health.loss")["value"] == 2.5
    assert _metric("health.grad_norm")["value"] == 3.0
    assert _metric("health.bucket_grad_norm", bucket="1")["value"] == 2.0
    spike = bundle.copy()
    spike[0] = 900.0
    mon.observe_bundle(10, spike)
    mon.observe_bundle(11, spike)
    assert _metric("health.alert", **{"class": "loss-spike"})["value"] \
        == 1
    assert _metric("health.alerts", **{"class": "loss-spike"})["value"] \
        == 1  # rising edge counted once
    kinds = [(e["kind"], e["name"]) for e in
             flightrec.get_recorder().snapshot()]
    assert ("health.alert", "loss-spike") in kinds


def test_monitor_first_nonfinite_provenance_names_the_leaf():
    import jax

    from horovod_tpu.optim.overlap import build_layout

    params = {"a": np.ones(4, np.float32), "b": np.ones(4, np.float32)}
    layout = build_layout(params, 8)  # one bucket per leaf
    leaves, _ = jax.tree_util.tree_flatten(params)
    grads = [l.copy() for l in leaves]
    grads[1][2] = np.nan
    names = [f"leaf{i}" for i in range(len(leaves))]
    mon = health.HealthMonitor(n_buckets=len(layout.buckets), rank=3,
                               leaf_names=names)
    mon.observe(7, loss=1.0, grad_norm=1.0, nonfinite=1,
                grads_flat=grads, layout=layout)
    assert mon.first_nonfinite["step"] == 7
    assert mon.first_nonfinite["rank"] == 3
    assert mon.first_nonfinite["leaf"] == "leaf1"
    # second nonfinite does not overwrite the FIRST story
    mon.observe(9, loss=1.0, grad_norm=1.0, nonfinite=5,
                grads_flat=grads, layout=layout)
    assert mon.first_nonfinite["step"] == 7
    evs = [e for e in flightrec.get_recorder().snapshot()
           if e["kind"] == "health.nonfinite"]
    assert len(evs) == 1 and "leaf=leaf1" in evs[0]["detail"]


# ---------------------------------------------------------------------------
# divergence sentinel with an injected exchange
# ---------------------------------------------------------------------------


class _FakeExchange:
    """World-of-N allgather: rank r's vector is ``mutate(r, vec)``."""

    def __init__(self, world, mutate):
        self.world = world
        self.mutate = mutate
        self.calls = []

    def __call__(self, vec, name):
        self.calls.append(name)
        rows = [np.asarray(self.mutate(r, vec.copy()), dtype=np.uint32)
                for r in range(self.world)]
        return np.concatenate(rows)


def _layout_and_leaves():
    import jax

    from horovod_tpu.optim.overlap import build_layout

    params = {"w1": np.ones((4, 4), np.float32),
              "w2": np.full((4, 4), 2.0, np.float32),
              "w3": np.full((4, 4), 3.0, np.float32)}
    layout = build_layout(params, 64)  # 64B buckets: one leaf each
    leaves, _ = jax.tree_util.tree_flatten(params)
    return layout, [np.asarray(l) for l in leaves]


def test_sentinel_clean_run_alerts_nothing():
    layout, leaves = _layout_and_leaves()
    ex = _FakeExchange(4, lambda r, v: v)
    s = divergence.DivergenceSentinel(layout, rank=0, check_steps=10,
                                      exchange=ex)
    assert s.maybe_check(5, leaves) is None   # off-cadence: no exchange
    assert ex.calls == []
    assert s.maybe_check(10, leaves) is None  # on-cadence: clean
    assert ex.calls and s.checks == 1 and s.detections == 0
    assert _metric("health.divergence.checks")["value"] == 1
    assert _metric("health.divergence.alert")["value"] == 0


def test_sentinel_localizes_minority_rank_bucket_and_leaf():
    layout, leaves = _layout_and_leaves()
    names = ["w1", "w2", "w3"]
    # rank 1's copy of bucket 2's leaf took a bit flip
    bad_leaf = layout.buckets[2].leaf_indices[0]

    def mutate(r, vec):
        if r != 1:
            return vec
        mutated = [l.copy() for l in leaves]
        raw = mutated[bad_leaf].view(np.uint32)
        raw.reshape(-1)[5] ^= np.uint32(1) << np.uint32(30)
        if vec.size == len(layout.buckets) * divergence.DIGEST_WIDTH:
            # phase 1: full per-bucket vector
            v, _ = divergence.tree_digest_vector(mutated, layout)
        else:
            # phase 2: per-leaf descent inside the named bucket
            v = divergence.leaf_digest_matrix(
                mutated, layout.buckets[2]).ravel()
        return v

    ex = _FakeExchange(4, mutate)
    s = divergence.DivergenceSentinel(layout, rank=0, check_steps=10,
                                      exchange=ex, leaf_names=names,
                                      action="warn")
    report = s.maybe_check(20, leaves)
    assert report is not None
    assert report.minority_ranks == (1,)
    assert report.bucket == 2
    assert report.leaf_name == names[bad_leaf]
    assert len(ex.calls) == 2  # bucket phase + leaf descent
    assert "minority=1" in report.detail and "bucket=2" in report.detail
    ev = [e for e in flightrec.get_recorder().snapshot()
          if e["kind"] == "health.divergence"]
    assert len(ev) == 1 and ev[0]["cycle"] == 20
    det = _metric("health.divergence.detected",
                  component="bucket2", leaf=names[bad_leaf])
    assert det is not None and det["value"] == 1


def test_sentinel_extras_localize_opt_state_and_prng():
    layout, leaves = _layout_and_leaves()
    opt = [np.zeros(4, np.float32)]
    key = np.array([7, 9], np.uint32)

    def mutate(r, vec):
        if r != 2:
            return vec
        v, _ = divergence.tree_digest_vector(
            leaves, layout,
            extras=[("opt_state", opt),
                    ("prng", [np.array([7, 10], np.uint32)])])
        return v

    s = divergence.DivergenceSentinel(layout, rank=0, check_steps=1,
                                      exchange=_FakeExchange(3, mutate))
    report = s.check(1, leaves, opt_leaves=opt, prng_key=key)
    assert report.component == "prng"
    assert report.minority_ranks == (2,)
    assert report.bucket is None


def test_sentinel_halt_raises_on_every_rank():
    layout, leaves = _layout_and_leaves()

    def mutate(r, vec):
        if r == 1:
            v = vec.copy()
            v[0] ^= np.uint32(1)
            return v
        return vec

    for rank in (0, 1):  # culprit and bystander reach the same verdict
        obs.reset_registry()
        s = divergence.DivergenceSentinel(
            layout, rank=rank, check_steps=1, action="halt",
            exchange=_FakeExchange(2, mutate))
        with pytest.raises(divergence.DivergenceHalt, match="halt"):
            s.check(1, leaves)


def test_sentinel_rejects_bad_knobs():
    layout, _ = _layout_and_leaves()
    with pytest.raises(ValueError, match="action"):
        divergence.DivergenceSentinel(layout, rank=0, action="explode")
    with pytest.raises(ValueError, match="check_steps"):
        divergence.DivergenceSentinel(layout, rank=0, check_steps=0)


def test_partition_majority_tie_breaks_deterministically():
    # 2-rank tie: lowest rank's pattern is the "majority" everywhere
    mat = np.array([[1, 2], [3, 4]], dtype=np.uint32)
    minority, majority = divergence._partition(mat)
    assert majority == [0] and minority == [1]


# ---------------------------------------------------------------------------
# HLO-unchanged-when-off (the artifact check CI re-runs)
# ---------------------------------------------------------------------------


def _compiled_text(step):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    mesh = Mesh(np.array(jax.devices()[:1]), ("hvd",))
    params = {"w": jnp.ones((4, 4), jnp.float32)}
    import optax

    tx = optax.sgd(0.1)
    state = (params, tx.init(params))
    x = jnp.ones((2, 4))
    fn = jax.jit(shard_map(step, mesh=mesh, in_specs=(P(), P()),
                           out_specs=P(), check_rep=False))
    text = fn.lower(state, x).compile().as_text()
    return re.sub(r"HloModule [^,]*", "HloModule M", text)


def test_health_off_leaves_compiled_hlo_byte_identical():
    import jax.numpy as jnp
    import optax

    from horovod_tpu.optim.overlap import OverlapPlan

    params = {"w": jnp.ones((4, 4), jnp.float32)}
    plan = OverlapPlan(params, optax.sgd(0.1), mode="off")

    def loss_fn(p, x):
        return jnp.mean((x @ p["w"]) ** 2)

    baseline = _compiled_text(plan.local_step(loss_fn))
    off = _compiled_text(plan.local_step(loss_fn, health=False))
    on = _compiled_text(plan.local_step(loss_fn, health=True))
    assert off == baseline          # --health off: byte-identical
    assert on != baseline           # and the flag is not a no-op


def test_health_bundle_values_in_graph():
    import jax
    import jax.numpy as jnp
    import optax

    from horovod_tpu.optim.overlap import OverlapPlan
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    params = {"w": jnp.ones((4, 4), jnp.float32)}
    plan = OverlapPlan(params, optax.sgd(0.1), mode="off")

    def loss_fn(p, x):
        return jnp.sum(x @ p["w"])

    mesh = Mesh(np.array(jax.devices()[:1]), ("hvd",))
    tx_state = plan.tx.init(params)
    step = jax.jit(shard_map(plan.local_step(loss_fn, health=True),
                             mesh=mesh, in_specs=(P(), P()),
                             out_specs=P(), check_rep=False))
    x = jnp.ones((2, 4))
    (_, loss, bundle) = step((params, tx_state), x)
    bundle = np.asarray(bundle)
    assert bundle[0] == float(loss)
    grads = jax.grad(loss_fn)(params, x)
    expect = float(np.sqrt(np.sum(np.asarray(grads["w"]) ** 2)))
    assert abs(bundle[1] - expect) < 1e-4
    assert bundle[3] == 0.0  # no nonfinites
    assert len(bundle) == 4 + len(plan.layout.buckets)


def test_zero1_bundle_matches_replicated_bundle():
    """The ZeRO-1 path computes the bundle from gradient shards +
    psum; loss, global grad norm, and nonfinite count must agree with
    the replicated path on the same batch."""
    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.optim.overlap import OverlapPlan
    from horovod_tpu.ops.collectives import shard_map_compat
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.asarray(jax.devices()[:8], dtype=object).reshape(8),
                (hvd.DP_AXIS,))
    params = {"w": jnp.ones((8, 8), jnp.float32) * 0.1,
              "b": jnp.zeros(8, jnp.float32)}

    def loss_fn(p, x):
        return jnp.mean((x @ p["w"] + p["b"]) ** 2)

    x = jax.random.normal(jax.random.PRNGKey(0), (16, 8))
    bundles = {}
    for mode in ("off", "bucket+zero1"):
        plan = OverlapPlan(params, optax.sgd(0.1), mode=mode, mesh=mesh,
                           bucket_mb=1e-4)
        spec = plan.state_spec()
        step = jax.jit(shard_map_compat(
            plan.local_step(loss_fn, health=True), mesh=mesh,
            in_specs=(spec, P(hvd.DP_AXIS)),
            out_specs=(spec, P(), P())))
        _, _, bundle = step(plan.init(params), x)
        bundles[mode] = np.asarray(bundle)
    off, z1 = bundles["off"], bundles["bucket+zero1"]
    assert abs(off[0] - z1[0]) < 1e-6       # loss
    assert abs(off[1] - z1[1]) < 1e-4       # global grad norm
    assert off[3] == z1[3] == 0.0           # nonfinite count


# ---------------------------------------------------------------------------
# grad_ready fault actions
# ---------------------------------------------------------------------------


def test_fault_spec_flip_bits_only_valid_at_grad_ready():
    specs = faults.parse_spec("grad_ready:rank=1:step=6:action=flip_bits")
    assert specs[0].action == "flip_bits"
    with pytest.raises(ValueError, match="grad_ready"):
        faults.parse_spec("ckpt_write:action=flip_bits")
    with pytest.raises(ValueError, match="grad_ready"):
        faults.parse_spec("enqueue:action=nan_inject")


def test_corrupt_grad_flip_bits_is_deterministic_single_element():
    a = np.linspace(0.1, 1.0, 16).astype(np.float32)
    out1 = faults.corrupt_grad(a, "flip_bits", rank=1, step=6, name="g")
    out2 = faults.corrupt_grad(a, "flip_bits", rank=1, step=6, name="g")
    assert np.array_equal(out1, out2)                    # deterministic
    assert not np.array_equal(out1, a)
    assert int((out1 != a).sum()) == 1                   # one element
    assert np.isfinite(out1).all()                       # finite SDC
    assert out1.dtype == a.dtype
    assert np.array_equal(a, np.linspace(0.1, 1.0, 16)
                          .astype(np.float32))           # input intact
    # the hit position is keyed by (rank, step, name): across a handful
    # of ranks at least one must land elsewhere (mod-16 collisions are
    # fine for any single pair)
    others = [faults.corrupt_grad(a, "flip_bits", rank=r, step=6, name="g")
              for r in range(8)]
    assert any(not np.array_equal(out1, o) for o in others)


def test_corrupt_grad_nan_inject():
    a = np.ones(8, np.float32)
    out = faults.corrupt_grad(a, "nan_inject", rank=0, step=3, name="x")
    assert int(np.isnan(out).sum()) == 1
    # integer arrays fall back to the bit flip (NaN has no int encoding)
    ints = np.arange(8, dtype=np.int32)
    iout = faults.corrupt_grad(ints, "nan_inject", rank=0, step=3,
                               name="x")
    assert int((iout != ints).sum()) == 1


def test_maybe_fail_grad_ready_returns_advisory_action(monkeypatch):
    monkeypatch.setenv("HVDTPU_FAULT_SPEC",
                       "grad_ready:rank=1:step=2:action=flip_bits")
    faults.reset()
    assert faults.maybe_fail("grad_ready", step=1, rank=1) is None
    assert faults.maybe_fail("grad_ready", step=2, rank=0) is None
    assert faults.maybe_fail("grad_ready", step=2, rank=1) == "flip_bits"
    # count=1 default: fires once
    assert faults.maybe_fail("grad_ready", step=2, rank=1) is None


# ---------------------------------------------------------------------------
# postmortem folding
# ---------------------------------------------------------------------------


def _flightrec_dump(tmp_path, rank, events, trigger="atexit",
                    last_exception=None):
    doc = {
        "schema": flightrec.SCHEMA, "rank": rank, "pid": 1000 + rank,
        "wall_time": time.time() + rank, "trigger": trigger, "epoch": 0,
        "capacity": 64, "recorded": len(events), "overwritten": 0,
        "last_exception": last_exception,
        "events": [
            {"seq": i, "t": time.time(), "kind": k, "name": n,
             "cycle": c, "detail": d}
            for i, (k, n, c, d) in enumerate(events)
        ],
    }
    path = tmp_path / f"flightrec.rank{rank}.json"
    path.write_text(json.dumps(doc))
    return doc


def test_postmortem_carries_divergence_and_nonfinite(tmp_path):
    _flightrec_dump(
        tmp_path, 0,
        [("complete", "g0", 1, ""),
         ("health.divergence", "bucket2", 8,
          "step=8 minority=1 component=bucket2 bucket=2 leaf=w1")],
        trigger="exception",
        last_exception={"type": "DivergenceHalt", "message": "", "where": "",
                        "traceback": ""},
    )
    _flightrec_dump(
        tmp_path, 1,
        [("complete", "g0", 1, ""),
         ("health.nonfinite", "first", 6,
          "step=6 rank=1 count=2 bucket=2 leaf_index=1 leaf=w1"),
         ("health.alert", "nonfinite", 6, "step=6 count=2"),
         ("health.divergence", "bucket2", 8,
          "step=8 minority=1 component=bucket2 bucket=2 leaf=w1")],
        trigger="exception",
        last_exception={"type": "DivergenceHalt", "message": "", "where": "",
                        "traceback": ""},
    )
    report = postmortem.analyze(postmortem.load_dumps(str(tmp_path)),
                                expected_ranks=2)
    h = report["health"]
    assert h["0"]["divergence"]["leaf"] == "w1"
    assert h["0"]["divergence"]["minority"] == "1"
    assert h["1"]["first_nonfinite"]["step"] == 6
    assert "nonfinite" in h["1"]["alerts"]
    v = postmortem.verdict(report)
    assert "TRAINING-STATE DIVERGENCE" in v
    assert "bucket2 (leaf w1)" in v
    assert "step 8" in v
    assert "NONFINITE GRADIENTS" in v
    assert "step 6" in v and "'w1'" in v


def test_postmortem_clean_run_has_no_health_section(tmp_path):
    _flightrec_dump(tmp_path, 0, [("complete", "g0", 1, "")])
    report = postmortem.analyze(postmortem.load_dumps(str(tmp_path)))
    assert report["health"] == {}
    assert "DIVERGENCE" not in postmortem.verdict(report)


# ---------------------------------------------------------------------------
# summary + live surfaces
# ---------------------------------------------------------------------------


def test_health_section_aggregates_dumps():
    from horovod_tpu.obs import summary

    dumps = {
        "0": {"metrics": [
            {"name": "health.alerts", "tags": {"class": "loss-spike"},
             "value": 2},
            # histograms have quantiles, not "value" — must be skipped
            {"name": "health.grad_norm_hist", "tags": {},
             "count": 12, "p50": 1.0, "p99": 1.0},
            {"name": "health.grad_norm_z", "tags": {}, "value": 1.5},
            {"name": "health.divergence.checks", "tags": {}, "value": 4},
            {"name": "health.divergence.last_check_step", "tags": {},
             "value": 400},
        ]},
        "1": {"metrics": [
            {"name": "health.grad_norm_z", "tags": {}, "value": 7.2},
            {"name": "health.divergence.detected",
             "tags": {"component": "bucket2", "leaf": "w1"}, "value": 1},
        ]},
    }
    text = summary.health_section(dumps)
    assert "loss-spike x2" in text
    assert "worst grad-norm z-score: 7.20" in text
    assert "divergence checks: 4 (last at step 400)" in text
    assert "DIVERGENCE DETECTED x1 in bucket2/w1" in text
    assert summary.health_section({"0": {"metrics": []}}) is None


def test_live_digest_health_token():
    from horovod_tpu.obs.live import LiveAggregator

    class _View:
        def __init__(self, metrics):
            self.metrics = {i: m for i, m in enumerate(metrics)}

    ok = {0: _View([{"name": "health.alert",
                     "tags": {"class": "loss-spike"}, "value": 0}])}
    firing = {0: _View([
        {"name": "health.alert", "tags": {"class": "loss-spike"},
         "value": 1},
        {"name": "health.divergence.alert", "tags": {}, "value": 1},
    ])}
    assert LiveAggregator._health_part(ok) == "health OK"
    assert LiveAggregator._health_part(firing) == \
        "health ALERT(divergence, loss-spike)"
    assert LiveAggregator._health_part({}) is None


def test_health_config_from_env(monkeypatch):
    monkeypatch.delenv("HVDTPU_HEALTH", raising=False)
    assert not health.HealthConfig.from_env().enabled
    monkeypatch.setenv("HVDTPU_HEALTH", "on")
    monkeypatch.setenv("HVDTPU_HEALTH_CHECK_STEPS", "25")
    monkeypatch.setenv("HVDTPU_DIVERGENCE_ACTION", "halt")
    cfg = health.HealthConfig.from_env()
    assert cfg.enabled and cfg.check_steps == 25
    assert cfg.divergence_action == "halt"
