"""Tenant SLO burn-rate plane (obs/slo.py): target parsing from the
serve spec, the two-window alerting decision table on a fake clock
(acceptance: a forced ttft breach on an ``interactive`` tenant fires
within two fast windows; untagged traffic trips nothing), rising-edge
alert counting, the minimum-sample guard, registry publishing, and the
drain summary document."""

from __future__ import annotations

import pytest

import horovod_tpu.obs as obs
from horovod_tpu.obs import slo


@pytest.fixture(autouse=True)
def _fresh():
    obs.reset_registry()
    yield
    obs.reset_registry()


def _plane(**kw):
    targets = {"interactive": slo.SLOTarget(ttft_ms=500.0, tpot_ms=80.0,
                                            objective=0.99)}
    return slo.SLOPlane(targets, **kw)


# ---------------------------------------------------------------------------
# targets
# ---------------------------------------------------------------------------


def test_targets_from_spec_parses_classes():
    spec = {"slo": {
        "interactive": {"ttft_ms": 500, "tpot_ms": 80,
                        "objective": 0.99},
        "standard": {"ttft_ms": 2000},
        "batch": {},                 # no ceilings: dropped
        "junk": "not a dict",        # tolerated
    }}
    targets = slo.targets_from_spec(spec)
    assert set(targets) == {"interactive", "standard"}
    assert targets["interactive"].threshold_ms("ttft") == 500.0
    assert targets["interactive"].threshold_ms("tpot") == 80.0
    assert targets["standard"].threshold_ms("tpot") is None
    assert targets["standard"].objective == slo.DEFAULT_OBJECTIVE
    assert targets["interactive"].budget == pytest.approx(0.01)


def test_targets_from_spec_absent_is_empty():
    assert slo.targets_from_spec({}) == {}
    assert slo.targets_from_spec({"slo": None}) == {}
    assert not slo.SLOPlane({}).armed


def test_objective_must_be_a_fraction():
    with pytest.raises(ValueError):
        slo.SLOTarget(ttft_ms=500.0, objective=1.0)
    with pytest.raises(ValueError):
        slo.SLOTarget(ttft_ms=500.0, objective=0.0)


# ---------------------------------------------------------------------------
# alerting decision table
# ---------------------------------------------------------------------------


def test_forced_ttft_breach_fires_within_two_fast_windows():
    """Acceptance: every interactive first token lands at 900ms against
    a 500ms ceiling — the fast window must page before two fast windows
    (120s) elapse.  Here it fires as soon as the minimum sample count
    is in, well inside the first window."""
    plane = _plane()
    t = 0.0
    fired_at = None
    while t < 2 * plane.fast_window:
        plane.observe_ttft("acme", "interactive", 900.0, t)
        alerts = plane.evaluate(t)
        if any(a["window"] == "fast" for a in alerts):
            fired_at = t
            break
        t += 5.0
    assert fired_at is not None and fired_at < 2 * plane.fast_window
    fast = [a for a in plane.evaluate(fired_at)
            if a["window"] == "fast"][0]
    assert fast["tenant"] == "acme"
    assert fast["slo"] == "interactive"
    assert fast["metric"] == "ttft"
    # all-breach traffic burns at 1/budget = 100x: far past threshold
    assert fast["burn"] >= plane.thresholds["fast"]


def test_untagged_traffic_trips_nothing():
    """Traffic whose SLO class carries no target is digested but can
    never alert — even at 100% breach-looking latencies."""
    plane = _plane()
    for i in range(50):
        plane.observe_ttft("anon", "batch", 99999.0, float(i))
        plane.observe_tpot("anon", "batch", 99999.0, float(i))
    assert plane.evaluate(50.0) == []
    assert plane.burn_rates(50.0) == {}
    # but the digest still exists (percentiles are worth seeing)
    doc = plane.summary(50.0)
    assert doc["anon/batch"]["ttft"]["n"] == 50
    assert "burn_fast" not in doc["anon/batch"]["ttft"]
    assert doc["anon/batch"]["ttft"]["breaches"] == 0


def test_healthy_traffic_never_fires():
    plane = _plane()
    for i in range(100):
        plane.observe_ttft("acme", "interactive", 120.0, float(i))
    assert plane.evaluate(100.0) == []
    burns = plane.burn_rates(100.0)
    assert burns[("acme", "interactive", "ttft")]["fast"] == 0.0


def test_min_sample_guard_one_unlucky_request_pages_nobody():
    plane = _plane()
    plane.observe_ttft("acme", "interactive", 5000.0, 0.0)
    plane.observe_ttft("acme", "interactive", 5000.0, 1.0)
    assert plane.evaluate(1.0) == []  # 2 < MIN_WINDOW_SAMPLES
    plane.observe_ttft("acme", "interactive", 5000.0, 2.0)
    assert plane.evaluate(2.0) != []


def test_slow_window_catches_a_slow_burn_the_fast_window_dismisses():
    """4% breach rate = burn 4x on a 1% budget: past the slow threshold
    (2) but under the fast one (8) — the slow window alone must warn."""
    plane = _plane()
    t = 0.0
    for i in range(500):
        ms = 900.0 if i % 25 == 0 else 100.0  # 4% over the ceiling
        plane.observe_ttft("acme", "interactive", ms, t)
        t += 1.0
    wins = {a["window"] for a in plane.evaluate(t)}
    assert wins == {"slow"}


def test_rising_edge_alert_counting():
    plane = _plane()
    for i in range(5):
        plane.observe_ttft("acme", "interactive", 900.0, float(i))
    plane.evaluate(4.0)
    plane.evaluate(5.0)   # still firing: not a second page
    series = plane._series[("acme", "interactive", "ttft")]
    assert series.alerts_total >= 1
    first_total = series.alerts_total
    # recover: the bad samples age out of both windows
    quiet = 4.0 + plane.slow_window + 1.0
    for i in range(5):
        plane.observe_ttft("acme", "interactive", 100.0, quiet + i)
    assert plane.evaluate(quiet + 5.0) == []
    assert series.alerts_total == first_total
    # breach again: a NEW rising edge
    for i in range(5):
        plane.observe_ttft("acme", "interactive", 900.0, quiet + 10 + i)
    assert plane.evaluate(quiet + 15.0) != []
    assert series.alerts_total > first_total


def test_tpot_breaches_judged_against_their_own_ceiling():
    plane = _plane()
    for i in range(5):
        plane.observe_tpot("acme", "interactive", 200.0, float(i))  # >80
    alerts = plane.evaluate(5.0)
    assert {a["metric"] for a in alerts} == {"tpot"}


# ---------------------------------------------------------------------------
# publishing + summary
# ---------------------------------------------------------------------------


def test_publish_lands_serve_slo_metrics():
    plane = _plane()
    for i in range(10):
        plane.observe_ttft("acme", "interactive", 900.0, float(i))
    reg = obs.get_registry()
    plane.publish(reg, 10.0)
    snap = {(m["name"], tuple(sorted((m.get("tags") or {}).items()))): m
            for m in reg.snapshot()}
    tags = (("metric", "ttft"), ("slo", "interactive"),
            ("tenant", "acme"))
    assert snap[("serve.slo.p99_ms", tags)]["value"] \
        == pytest.approx(900.0)
    fast_tags = tuple(sorted(tags + (("window", "fast"),)))
    assert snap[("serve.slo.alert", fast_tags)]["value"] == 1.0
    assert snap[("serve.slo.burn", fast_tags)]["value"] \
        >= slo.DEFAULT_FAST_BURN
    assert snap[("serve.slo.breaches", tags)]["value"] == 10
    assert snap[("serve.slo.alerts", tags)]["value"] >= 1
    # republish: counters must not double-count (delta vs counter value)
    plane.publish(reg, 11.0)
    snap = {(m["name"], tuple(sorted((m.get("tags") or {}).items()))): m
            for m in reg.snapshot()}
    assert snap[("serve.slo.breaches", tags)]["value"] == 10


def test_summary_document_shape():
    plane = _plane()
    for i in range(4):
        plane.observe_ttft("acme", "interactive", 900.0, float(i))
    plane.evaluate(4.0)
    doc = plane.summary(4.0)
    entry = doc["acme/interactive"]["ttft"]
    assert entry["n"] == 4
    assert entry["breaches"] == 4
    assert entry["burn_fast"] == pytest.approx(100.0)
    assert entry["firing"] is True
    assert entry["alerts"] >= 1
