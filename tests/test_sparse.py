"""Sparse (IndexedSlices) gradient collectives.

Models the reference's sparse tests (test/test_tensorflow.py
horovod_allreduce IndexedSlices cases): allreduce of an IndexedSlices is an
allgather of values+indices (horovod/tensorflow/__init__.py:74-89), and
sparse_as_dense densifies before the wire."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

import horovod_tpu as hvd
from horovod_tpu.ops.sparse import IndexedSlices, allreduce_sparse, to_dense

N = 8


def test_to_dense_scatter_adds_duplicates():
    s = IndexedSlices(
        values=jnp.array([[1.0, 2.0], [3.0, 4.0], [10.0, 10.0]]),
        indices=jnp.array([1, 1, 3]),
        dense_shape=(5, 2),
    )
    dense = to_dense(s)
    np.testing.assert_allclose(
        np.asarray(dense),
        [[0, 0], [4, 6], [0, 0], [10, 10], [0, 0]],
    )


@pytest.mark.parametrize("op", [hvd.Average, hvd.Sum])
def test_allreduce_sparse_spmd(op):
    rows, dim, per_rank = 16, 4, 3
    rng = np.random.RandomState(0)
    values = rng.randn(N, per_rank, dim).astype(np.float32)
    indices = rng.randint(0, rows, size=(N, per_rank)).astype(np.int32)

    mesh = hvd.mesh("flat")

    def step(v, i):
        s = IndexedSlices(v[0], i[0], (rows, dim))
        out = hvd.allreduce(s, op)
        return to_dense(out)[None]

    out = shard_map(
        step,
        mesh=mesh,
        in_specs=(P(hvd.DP_AXIS), P(hvd.DP_AXIS)),
        out_specs=P(hvd.DP_AXIS),
    )(values, indices)

    expect = np.zeros((rows, dim), np.float32)
    for r in range(N):
        for k in range(per_rank):
            expect[indices[r, k]] += values[r, k]
    if op == hvd.Average:
        expect /= N
    for r in range(N):
        np.testing.assert_allclose(np.asarray(out[r]), expect, rtol=1e-5)


def test_allreduce_mixed_pytree_with_sparse_leaf():
    """A nested IndexedSlices must take the sparse path, not be flattened
    into its fields (which would psum integer indices into garbage)."""
    rows, dim, per_rank = 8, 2, 2
    rng = np.random.RandomState(3)
    values = rng.randn(N, per_rank, dim).astype(np.float32)
    indices = rng.randint(0, rows, size=(N, per_rank)).astype(np.int32)

    mesh = hvd.mesh("flat")

    def step(v, i):
        tree = {
            "emb": IndexedSlices(v[0], i[0], (rows, dim)),
            "w": jnp.ones((dim,)),
        }
        out = hvd.allreduce(tree, hvd.Sum)
        s = out["emb"]
        assert isinstance(s, IndexedSlices)
        assert s.dense_shape == (rows, dim)  # not psum'd
        return to_dense(s)[None], out["w"][None]

    dense, w = shard_map(
        step,
        mesh=mesh,
        in_specs=(P(hvd.DP_AXIS), P(hvd.DP_AXIS)),
        out_specs=(P(hvd.DP_AXIS), P(hvd.DP_AXIS)),
    )(values, indices)

    expect = np.zeros((rows, dim), np.float32)
    for r in range(N):
        for k in range(per_rank):
            expect[indices[r, k]] += values[r, k]
    np.testing.assert_allclose(np.asarray(dense[0]), expect, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(w[0]), np.full(dim, float(N)))


def test_adasum_sparse_raises_without_densify():
    tx = hvd.DistributedGradientTransform(
        op=hvd.Adasum, sparse_as_dense=False
    )
    mesh = hvd.mesh("flat")

    def step(v):
        grads = {"emb": IndexedSlices(v[0], jnp.array([0]), (4, 1))}
        with pytest.raises(ValueError, match="Adasum does not support"):
            tx.update(grads, tx.init(None))
        return v

    shard_map(
        step, mesh=mesh, in_specs=(P(hvd.DP_AXIS),),
        out_specs=P(hvd.DP_AXIS),
    )(np.ones((N, 1, 1), np.float32))


def test_sparse_as_dense_in_gradient_transform():
    rows, dim, per_rank = 8, 2, 2
    rng = np.random.RandomState(1)
    values = rng.randn(N, per_rank, dim).astype(np.float32)
    indices = rng.randint(0, rows, size=(N, per_rank)).astype(np.int32)

    tx = hvd.DistributedGradientTransform()
    mesh = hvd.mesh("flat")

    def step(v, i):
        grads = {"emb": IndexedSlices(v[0], i[0], (rows, dim)),
                 "w": jnp.ones((dim,)) * (i[0, 0].astype(jnp.float32))}
        state = tx.init(None)
        out, _ = tx.update(grads, state)
        return out["emb"][None], out["w"][None]

    emb, w = shard_map(
        step,
        mesh=mesh,
        in_specs=(P(hvd.DP_AXIS), P(hvd.DP_AXIS)),
        out_specs=(P(hvd.DP_AXIS), P(hvd.DP_AXIS)),
    )(values, indices)

    expect = np.zeros((rows, dim), np.float32)
    for r in range(N):
        for k in range(per_rank):
            expect[indices[r, k]] += values[r, k]
    expect /= N
    np.testing.assert_allclose(np.asarray(emb[0]), expect, rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(w[0]), np.full(dim, indices[:, 0].astype(np.float32).mean()),
        rtol=1e-5,
    )


def test_sparse_kept_sparse_when_disabled():
    rows, dim, per_rank = 6, 2, 2
    values = np.arange(N * per_rank * dim, dtype=np.float32).reshape(
        N, per_rank, dim
    )
    indices = np.tile(np.arange(per_rank, dtype=np.int32), (N, 1))

    tx = hvd.DistributedGradientTransform(sparse_as_dense=False)
    mesh = hvd.mesh("flat")

    def step(v, i):
        grads = {"emb": IndexedSlices(v[0], i[0], (rows, dim))}
        out, _ = tx.update(grads, tx.init(None))
        s = out["emb"]
        assert isinstance(s, IndexedSlices)
        # concatenated across ranks: N * per_rank rows
        assert s.values.shape == (N * per_rank, dim)
        return to_dense(s)[None]

    dense = shard_map(
        step,
        mesh=mesh,
        in_specs=(P(hvd.DP_AXIS), P(hvd.DP_AXIS)),
        out_specs=P(hvd.DP_AXIS),
    )(values, indices)

    expect = np.zeros((rows, dim), np.float32)
    for r in range(N):
        for k in range(per_rank):
            expect[indices[r, k]] += values[r, k] / N
    np.testing.assert_allclose(np.asarray(dense[0]), expect, rtol=1e-5)
