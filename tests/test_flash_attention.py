"""Flash-attention kernel + transformer model tests.

The Pallas kernel runs through the interpreter on the CPU test mesh
(identical program, no TPU needed); correctness is against the plain
softmax reference, gradients included — the kernel is advertised as
training-ready.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.models import TransformerConfig, gpt
from horovod_tpu.ops.flash_attention import flash_attention
from horovod_tpu.parallel import local_attention


def _qkv(b=2, s=64, h=4, d=16, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, s, h, d), dtype) * 0.3
    return mk(), mk(), mk()


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        q, k, v = _qkv()
        out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
        ref = local_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
        )

    def test_uneven_blocks(self):
        # S=48 forces _pick_block to drop to a divisor
        q, k, v = _qkv(s=48, seed=1)
        out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
        ref = local_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
        )

    def test_grads_match_reference(self):
        q, k, v = _qkv(seed=2)
        f = lambda *a: (
            flash_attention(*a, causal=True, block_q=16, block_k=16) ** 2
        ).sum()
        r = lambda *a: (local_attention(*a, causal=True) ** 2).sum()
        gf = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(r, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5
            )

    def test_bf16_inputs(self):
        q, k, v = _qkv(seed=3, dtype=jnp.bfloat16)
        out = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
        assert out.dtype == jnp.bfloat16
        ref = local_attention(
            *(x.astype(jnp.float32) for x in (q, k, v)), causal=True
        )
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref), atol=0.05,
            rtol=0.05,
        )

    def test_shape_mismatch_rejected(self):
        q, k, v = _qkv()
        with pytest.raises(ValueError, match="matching"):
            flash_attention(q, k[:, :32], v)


class TestGPT:
    def _cfg(self, **kw):
        return dict(size="nano", flash_block_q=16, flash_block_k=16, **kw)

    def test_forward_shapes_and_finite(self):
        model = gpt(**self._cfg())
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, 1024, (2, 32))
        )
        params = model.init(jax.random.PRNGKey(0), tokens)
        logits = model.apply(params, tokens)
        assert logits.shape == (2, 32, 1024)
        assert logits.dtype == jnp.float32
        assert np.isfinite(np.asarray(logits)).all()

    def test_flash_equals_reference_impl(self):
        tokens = jnp.asarray(
            np.random.RandomState(1).randint(0, 1024, (2, 32))
        )
        m_flash = gpt(**self._cfg(attention_impl="flash",
                                  dtype=jnp.float32))
        m_ref = gpt(**self._cfg(attention_impl="reference",
                                dtype=jnp.float32))
        params = m_flash.init(jax.random.PRNGKey(0), tokens)
        np.testing.assert_allclose(
            np.asarray(m_flash.apply(params, tokens)),
            np.asarray(m_ref.apply(params, tokens)),
            atol=2e-4, rtol=2e-4,
        )

    def test_causality(self):
        """Changing a future token must not change past logits."""
        model = gpt(**self._cfg(dtype=jnp.float32))
        rng = np.random.RandomState(2)
        t1 = rng.randint(0, 1024, (1, 16))
        t2 = t1.copy()
        t2[0, -1] = (t2[0, -1] + 1) % 1024
        params = model.init(jax.random.PRNGKey(0), jnp.asarray(t1))
        l1 = model.apply(params, jnp.asarray(t1))
        l2 = model.apply(params, jnp.asarray(t2))
        np.testing.assert_allclose(
            np.asarray(l1[:, :-1]), np.asarray(l2[:, :-1]), atol=1e-5
        )
        assert np.abs(np.asarray(l1[:, -1]) - np.asarray(l2[:, -1])).max() > 1e-3

    def test_sequence_parallel_training_step(self):
        """One GPT training step with ring attention over an 8-way
        sequence-parallel mesh matches the single-device step."""
        S = 64
        cfg_sp = self._cfg(attention_impl="ring", sp_axis="sp",
                           dtype=jnp.float32)
        cfg_1d = self._cfg(attention_impl="reference", dtype=jnp.float32)
        model_sp, model_1d = gpt(**cfg_sp), gpt(**cfg_1d)
        tokens = jnp.asarray(np.random.RandomState(3).randint(0, 1024, (2, S)))
        targets = jnp.roll(tokens, -1, axis=1)
        params = model_1d.init(jax.random.PRNGKey(0), tokens[:, :8])

        def loss_1d(p):
            logits = model_1d.apply(p, tokens)
            return -jnp.take_along_axis(
                jax.nn.log_softmax(logits), targets[..., None], -1
            ).mean()

        mesh = Mesh(np.asarray(jax.devices()[:8]), ("sp",))
        s_local = S // 8

        def local_loss(p, tok, tgt):
            off = jax.lax.axis_index("sp") * s_local
            logits = model_sp.apply(p, tok, pos_offset=off)
            nll = -jnp.take_along_axis(
                jax.nn.log_softmax(logits), tgt[..., None], -1
            ).mean()
            return jax.lax.pmean(nll, "sp")

        loss_sp = jax.jit(
            shard_map(
                local_loss,
                mesh=mesh,
                in_specs=(P(), P(None, "sp"), P(None, "sp")),
                out_specs=P(),
                check_vma=False,
            )
        )
        l1, g1 = jax.value_and_grad(loss_1d)(params)
        l2 = loss_sp(params, tokens, targets)
        np.testing.assert_allclose(float(l1), float(l2), atol=1e-5, rtol=1e-5)
        g2 = jax.grad(
            lambda p: loss_sp(p, tokens, targets)
        )(params)
        flat1 = jax.tree_util.tree_leaves(g1)
        flat2 = jax.tree_util.tree_leaves(g2)
        for a, b in zip(flat2, flat1):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4
            )

    def test_ring_requires_axis(self):
        with pytest.raises(ValueError, match="sp_axis"):
            cfg = TransformerConfig(attention_impl="ring")
            _attend_probe(cfg)


def _attend_probe(cfg):
    from horovod_tpu.models.transformer import _attend

    x = jnp.zeros((1, 8, cfg.num_heads, cfg.head_dim))
    _attend(cfg, x, x, x, 0)


class TestPallasBackward:
    """The fused Pallas backward must match the scan-fallback backward
    (its differential reference) bit-for-bit at fp32 tolerance, causal
    and bidirectional, including the block-skipping causal path."""

    @pytest.mark.parametrize("causal,window", [
        (False, None), (True, None), (True, 24),
    ])
    def test_pallas_bwd_matches_scan_bwd(self, causal, window):
        from horovod_tpu.ops.flash_attention import (
            _flash_bwd_blockwise, _flash_bwd_pallas, _flash_fwd_kernel,
        )

        rng = np.random.RandomState(0)
        z, s, d, bq, bk = 3, 64, 16, 16, 16
        q, k, v, do = (
            jnp.asarray(rng.randn(z, s, d), jnp.float32) for _ in range(4)
        )
        scale = d ** -0.5
        o, lse = _flash_fwd_kernel(q, k, v, causal, scale, bq, bk, 1, 1,
                                   window, True)
        ref = _flash_bwd_blockwise(q, k, v, o, lse, do, causal, scale, bk,
                                   window=window)
        got = _flash_bwd_pallas(q, k, v, o, lse, do, causal, scale, bq, bk,
                                1, 1, window, True)
        for name, a, b in zip(("dq", "dk", "dv"), got, ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5,
                err_msg=f"{name} mismatch (causal={causal}, "
                        f"window={window})",
            )

    def test_pallas_bwd_uneven_blocks(self):
        from horovod_tpu.ops.flash_attention import (
            _flash_bwd_blockwise, _flash_bwd_pallas, _flash_fwd_kernel,
        )

        rng = np.random.RandomState(1)
        z, s, d, bq, bk = 2, 48, 8, 16, 8  # nq != nk
        q, k, v, do = (
            jnp.asarray(rng.randn(z, s, d), jnp.float32) for _ in range(4)
        )
        scale = d ** -0.5
        o, lse = _flash_fwd_kernel(q, k, v, True, scale, bq, bk, 1, 1,
                                   None, True)
        ref = _flash_bwd_blockwise(q, k, v, o, lse, do, True, scale, bk)
        got = _flash_bwd_pallas(q, k, v, o, lse, do, True, scale, bq, bk,
                                1, 1, None, True)
        for a, b in zip(got, ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5, rtol=1e-5)


class TestGQA:
    """Native grouped-query attention: k/v with fewer heads route through
    the kernels' index maps (no broadcast materialization); outputs and
    ALL gradients must match the broadcast-k/v reference."""

    @pytest.mark.parametrize("hkv", [1, 2])  # MQA and GQA
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_broadcast_reference(self, hkv, causal):
        from horovod_tpu.ops.flash_attention import flash_attention
        from horovod_tpu.parallel import local_attention

        rng = np.random.RandomState(7)
        b, s, h, d = 2, 32, 4, 16
        q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32) * 0.3
        k = jnp.asarray(rng.randn(b, s, hkv, d), jnp.float32) * 0.3
        v = jnp.asarray(rng.randn(b, s, hkv, d), jnp.float32) * 0.3
        w = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
        rep = lambda t: jnp.repeat(t, h // hkv, axis=2)

        def loss_flash(q, k, v):
            out = flash_attention(q, k, v, causal=causal,
                                  block_q=16, block_k=16)
            return (out * w).sum()

        def loss_ref(q, k, v):
            out = local_attention(q, rep(k), rep(v), causal=causal)
            return (out * w).sum()

        (lf, gf) = jax.value_and_grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        (lr, gr) = jax.value_and_grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(float(lf), float(lr), rtol=2e-5)
        for name, a, b_ in zip(("dq", "dk", "dv"), gf, gr):
            assert a.shape == b_.shape  # dk/dv stay at hkv heads
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), atol=3e-5, rtol=3e-5,
                err_msg=f"{name} (hkv={hkv}, causal={causal})",
            )

    def test_bad_kv_heads_rejected(self):
        from horovod_tpu.ops.flash_attention import flash_attention

        q = jnp.zeros((1, 16, 4, 8))
        kv = jnp.zeros((1, 16, 3, 8))  # 4 % 3 != 0
        with pytest.raises(ValueError, match="multiple of num_kv_heads"):
            flash_attention(q, kv, kv)


class TestZigzagModel:
    """End-to-end model-level zigzag SP: a RoPE GPT with
    attention_impl='zigzag' on an 8-way mesh (zigzag-sharded tokens,
    positions from zigzag_positions) must reproduce the single-device
    model's logits."""

    @pytest.mark.parametrize("kv_heads", [None, 2])
    def test_zigzag_model_matches_single_device(self, kv_heads):
        from horovod_tpu.parallel import zigzag_positions, zigzag_shard, \
            zigzag_unshard

        S, P_SIZE = 64, 8
        s_local = S // P_SIZE
        common = dict(num_layers=2, num_heads=4, emb_dim=64, max_len=S,
                      vocab_size=512, dtype=jnp.float32,
                      pos_embedding="rope", num_kv_heads=kv_heads)
        model_1d = gpt("nano", attention_impl="reference", **common)
        model_zz = gpt("nano", attention_impl="zigzag", sp_axis="sp",
                       **common)
        tokens = jnp.asarray(
            np.random.RandomState(11).randint(0, 512, (2, S)), jnp.int32
        )
        params = model_1d.init(jax.random.PRNGKey(0), tokens[:, :8])
        ref = model_1d.apply(params, tokens)

        mesh = Mesh(np.asarray(jax.devices()[:P_SIZE]), ("sp",))

        def local_fwd(p, tok):
            pos = zigzag_positions(
                jax.lax.axis_index("sp"), P_SIZE, s_local
            )
            return model_zz.apply(p, tok, positions=pos)

        fwd = jax.jit(
            shard_map(
                local_fwd, mesh=mesh,
                in_specs=(P(), P(None, "sp")),
                out_specs=P(None, "sp"),
                check_vma=False,
            )
        )
        out = zigzag_unshard(
            fwd(params, zigzag_shard(tokens, P_SIZE, axis=1)),
            P_SIZE, axis=1,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-4, rtol=2e-4
        )

    def test_rope_flash_matches_reference(self):
        """RoPE + flash vs RoPE + reference on one device (fp32)."""
        common = dict(num_layers=2, num_heads=4, emb_dim=64, max_len=64,
                      vocab_size=512, dtype=jnp.float32,
                      pos_embedding="rope")
        m_flash = gpt("nano", **common)
        m_ref = gpt("nano", attention_impl="reference", **common)
        tokens = jnp.asarray(
            np.random.RandomState(12).randint(0, 512, (2, 64)), jnp.int32
        )
        params = m_flash.init(jax.random.PRNGKey(0), tokens)
        assert "wpe" not in params["params"], "rope model must have no wpe"
        np.testing.assert_allclose(
            np.asarray(m_flash.apply(params, tokens)),
            np.asarray(m_ref.apply(params, tokens)),
            atol=2e-4, rtol=2e-4,
        )


class TestSlidingWindow:
    """window=W masks each row to its last W keys; tiles outside the
    band are skipped in fwd and bwd — values and grads must match a
    dense masked-softmax oracle exactly (up to fp32 tolerance)."""

    @staticmethod
    def _oracle(q, k, v, scale, window):
        b, s, h, d = q.shape
        rep = h // k.shape[2]
        kf = jnp.repeat(k, rep, axis=2).astype(jnp.float32)
        vf = jnp.repeat(v, rep, axis=2).astype(jnp.float32)
        st = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        kf) * scale
        q_pos = jnp.arange(s)[:, None]
        k_pos = jnp.arange(s)[None, :]
        mask = (k_pos > q_pos) | (k_pos < q_pos - (window - 1))
        st = jnp.where(mask, -1e30, st)
        p = jax.nn.softmax(st, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, vf)

    def _qkv(self, s=64, h=4, hkv=4, d=16, seed=0):
        rng = np.random.RandomState(seed)
        mk = lambda hh: jnp.asarray(
            rng.randn(2, s, hh, d) * 0.5, jnp.float32
        )
        return mk(h), mk(hkv), mk(hkv)

    @pytest.mark.parametrize("window,bq,bk", [
        (8, 16, 16),    # band narrower than a tile
        (24, 16, 8),    # band spans several tiles, bq != bk
        (1, 8, 8),      # degenerate: attend to self only
        (64, 16, 16),   # window == S: plain causal
        (200, 16, 16),  # window > S: clamps to plain causal
    ])
    def test_forward_matches_oracle(self, window, bq, bk):
        q, k, v = self._qkv()
        scale = q.shape[-1] ** -0.5
        got = flash_attention(q, k, v, causal=True, block_q=bq,
                              block_k=bk, window=window)
        want = self._oracle(q, k, v, scale, min(window, q.shape[1]))
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
        )

    def test_gradients_match_oracle(self):
        q, k, v = self._qkv(seed=1)
        scale = q.shape[-1] ** -0.5
        window = 24

        def loss_flash(q, k, v):
            return (flash_attention(
                q, k, v, causal=True, block_q=16, block_k=8,
                window=window,
            ) ** 2).sum()

        def loss_oracle(q, k, v):
            return (self._oracle(q, k, v, scale, window) ** 2).sum()

        got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(loss_oracle, argnums=(0, 1, 2))(q, k, v)
        for g, w, name in zip(got, want, "qkv"):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), atol=5e-4, rtol=5e-4,
                err_msg=f"d{name} mismatch",
            )

    def test_gqa_window(self):
        q, k, v = self._qkv(h=8, hkv=2, seed=2)
        got = flash_attention(q, k, v, causal=True, block_q=16,
                              block_k=16, window=16)
        want = self._oracle(q, k, v, q.shape[-1] ** -0.5, 16)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
        )

    def test_window_validation(self):
        q, k, v = self._qkv()
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, k, v, causal=False, window=8)
        with pytest.raises(ValueError, match=">= 1"):
            flash_attention(q, k, v, causal=True, window=0)

    def test_model_plumbing(self):
        """attention_window reaches the kernel through the GPT config,
        and non-flash impls reject it."""
        from horovod_tpu.models.transformer import gpt

        toks = jnp.asarray(
            np.random.RandomState(3).randint(0, 512, (2, 32)), jnp.int32
        )
        win = gpt("nano", num_layers=2, num_heads=4, emb_dim=64,
                  vocab_size=512, max_len=32, dtype=jnp.float32,
                  attention_window=8)
        full = gpt("nano", num_layers=2, num_heads=4, emb_dim=64,
                   vocab_size=512, max_len=32, dtype=jnp.float32)
        params = full.init(jax.random.PRNGKey(0), toks)
        out_w = win.apply(params, toks)
        out_f = full.apply(params, toks)
        assert out_w.shape == out_f.shape
        # the band must actually bite (different logits)...
        assert not np.allclose(np.asarray(out_w), np.asarray(out_f))
        # ...and rows 0..7 (inside the window from position 0) agree
        np.testing.assert_allclose(
            np.asarray(out_w[:, :8]), np.asarray(out_f[:, :8]),
            atol=2e-4, rtol=2e-4,
        )
        ref = gpt("nano", num_layers=2, num_heads=4, emb_dim=64,
                  vocab_size=512, max_len=32, dtype=jnp.float32,
                  attention_impl="reference", attention_window=8)
        with pytest.raises(ValueError, match="flash-only"):
            ref.apply(params, toks)
