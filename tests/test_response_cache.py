"""ResponseCache unit tests (reference response_cache.cc: LRU keyed on
name+params, deterministic slot allocation, conflict eviction)."""

import numpy as np
import pytest

from horovod_tpu.runtime.messages import (
    Request,
    RequestType,
    Response,
    ResponseType,
)
from horovod_tpu.runtime import response_cache as rc


def _req(name, shape=(2,), dtype="float32", rtype=RequestType.ALLREDUCE,
         reduce_op=1):
    return Request(
        request_rank=0, request_type=rtype, tensor_name=name,
        dtype=dtype, shape=shape, reduce_op=reduce_op,
    )


def _resp(name, rtype=ResponseType.ALLREDUCE):
    r = Response(rtype, [name])
    r._shapes = [(2,)]
    r._dtype = "float32"
    r._fuse_meta = ("float32", 1, 1.0, 1.0)
    r._nbytes = 8
    return r


def test_miss_then_hit():
    c = rc.ResponseCache(8)
    req = _req("a")
    assert c.lookup(req) == (rc.MISS, -1)
    c.insert(req, _resp("a"))
    status, slot = c.lookup(req)
    assert status == rc.HIT
    out = c.response_for(slot)
    assert out.tensor_names == ["a"]
    assert out.response_type == ResponseType.ALLREDUCE
    assert out._fuse_meta == ("float32", 1, 1.0, 1.0)


def test_changed_params_conflict():
    c = rc.ResponseCache(8)
    c.insert(_req("a"), _resp("a"))
    status, _ = c.lookup(_req("a", shape=(3,)))
    assert status == rc.CONFLICT
    status, _ = c.lookup(_req("a", dtype="int64"))
    assert status == rc.CONFLICT
    c.evict_name("a")
    assert c.lookup(_req("a")) == (rc.MISS, -1)


def test_slot_allocation_is_lowest_free():
    c = rc.ResponseCache(8)
    for name in ("a", "b", "c"):
        c.insert(_req(name), _resp(name))
    assert [c.lookup(_req(n))[1] for n in ("a", "b", "c")] == [0, 1, 2]
    c.evict_name("b")
    c.insert(_req("d"), _resp("d"))
    assert c.lookup(_req("d"))[1] == 1  # reuses the freed slot


def test_lru_eviction_at_capacity():
    c = rc.ResponseCache(2)
    c.insert(_req("a"), _resp("a"))
    c.insert(_req("b"), _resp("b"))
    c.touch(c.lookup(_req("a"))[1])  # a is now most-recent
    c.insert(_req("c"), _resp("c"))  # evicts b (least recent)
    assert c.lookup(_req("a"))[0] == rc.HIT
    assert c.lookup(_req("b"))[0] == rc.MISS
    assert c.lookup(_req("c"))[0] == rc.HIT


def test_allgather_and_barrier_not_cacheable():
    c = rc.ResponseCache(8)
    ag = _req("g", rtype=RequestType.ALLGATHER)
    c.insert(ag, _resp("g", ResponseType.ALLGATHER))
    assert c.lookup(ag) == (rc.MISS, -1)
    assert not rc.cacheable(RequestType.BARRIER)
    assert not rc.cacheable(RequestType.JOIN)
    assert rc.cacheable(RequestType.ADASUM)


def test_capacity_zero_disables():
    c = rc.ResponseCache(0)
    c.insert(_req("a"), _resp("a"))
    assert c.lookup(_req("a")) == (rc.MISS, -1)
    assert c.num_bits == 0
