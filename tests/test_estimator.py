"""Estimator/Model API (reference: horovod.spark estimators —
test/test_spark_keras.py, test_spark_torch.py: fit on a small dataset,
check the transformer's predictions and store round-trip)."""

import numpy as np
import optax
import pytest

import horovod_tpu as hvd
from horovod_tpu.checkpoint import LocalStore
from horovod_tpu.estimator import Estimator, Model
from horovod_tpu.models.simple import MLP


@pytest.fixture(autouse=True)
def _init():
    hvd.init()
    yield


def _blobs(n=256, seed=0):
    """Two linearly separable 2-D blobs."""
    rng = np.random.RandomState(seed)
    half = n // 2
    x = np.concatenate([
        rng.randn(half, 2).astype(np.float32) + 2.0,
        rng.randn(n - half, 2).astype(np.float32) - 2.0,
    ])
    y = np.concatenate([
        np.zeros(half, np.int32), np.ones(n - half, np.int32)
    ])
    return {"features": x, "label": y}


def test_fit_local_learns_and_transforms(tmp_path):
    data = _blobs()
    est = Estimator(
        MLP(features=(16,), num_classes=2),
        optax.adam(1e-2),
        batch_size=32,
        epochs=5,
        store=LocalStore(str(tmp_path)),
        run_id="blobs",
    )
    model = est.fit(data)
    assert len(model.history) == 5
    assert model.history[-1]["loss"] < model.history[0]["loss"]
    out = model.transform(data)
    acc = (out["prediction"] == data["label"]).mean()
    assert acc > 0.95
    # metadata landed in the store
    meta = LocalStore(str(tmp_path)).read_metadata("blobs")
    assert meta["model"] == "MLP"
    assert len(meta["history"]) == 5


def test_model_save_load_roundtrip(tmp_path):
    data = _blobs(n=128)
    store = LocalStore(str(tmp_path))
    est = Estimator(
        MLP(features=(8,), num_classes=2),
        optax.adam(1e-2),
        batch_size=32,
        epochs=2,
        store=store,
        run_id="r1",
    )
    model = est.fit(data)
    preds = model.transform(data)["prediction"]

    import jax

    template = MLP(features=(8,), num_classes=2).init(
        jax.random.PRNGKey(0), data["features"][:1]
    )
    loaded = Model.load(
        MLP(features=(8,), num_classes=2), store, "r1",
        template_params=template,
    )
    preds2 = loaded.transform(data)["prediction"]
    np.testing.assert_array_equal(preds, preds2)


def test_final_epoch_always_checkpointed(tmp_path):
    """epochs not a multiple of the cadence: the last epoch must still be
    saved so Model.load matches the fitted Model."""
    from horovod_tpu.checkpoint import latest_checkpoint_step

    store = LocalStore(str(tmp_path))
    est = Estimator(
        MLP(features=(4,), num_classes=2),
        optax.sgd(0.1),
        batch_size=32,
        epochs=3,
        checkpoint_every_epochs=5,
        store=store,
        run_id="cad",
    )
    est.fit(_blobs(n=64))
    assert latest_checkpoint_step(store.checkpoint_dir("cad")) == 3


def test_bad_batch_size_raises():
    est = Estimator(
        MLP(features=(8,), num_classes=2), optax.sgd(0.1),
        batch_size=31, epochs=1,  # 31 % 8 devices != 0
    )
    with pytest.raises(ValueError, match="not divisible"):
        est.fit(_blobs(n=64))


def test_mismatched_lengths_raise():
    est = Estimator(MLP(), optax.sgd(0.1))
    with pytest.raises(ValueError, match="length mismatch"):
        est.fit({"features": np.zeros((4, 2), np.float32),
                 "label": np.zeros(3, np.int32)})


def test_unknown_backend_rejected():
    with pytest.raises(ValueError, match="unknown backend"):
        Estimator(MLP(), optax.sgd(0.1), backend="spark")
