"""bench.py tunnel-resilience machinery (unit level).

The driver's official benchmark capture depends on this logic working
the first time a real outage hits (BENCH_r03 was lost to one), so the
string matching and the re-exec argv rebuild are pinned here; the
end-to-end timing path is exercised by the CPU smoke in CI.
"""

from __future__ import annotations

import pytest

import bench


def test_is_unavailable_matches_tunnel_signatures():
    assert bench._is_unavailable(
        RuntimeError("Unable to initialize backend 'axon': UNAVAILABLE: "
                     "TPU backend setup/compile error (Unavailable).")
    )
    assert bench._is_unavailable(Exception("UNAVAILABLE: socket closed"))
    assert not bench._is_unavailable(ValueError("shape mismatch"))
    assert not bench._is_unavailable(KeyboardInterrupt())


class _Args:
    """Minimal stand-in for the parsed-argparse namespace."""

    cpu = False
    watchdog_secs = 780
    retry_attempt = 1
    attempts = 4
    deadline_epoch = 0.0


def test_reexec_rebuilds_argv_with_incremented_attempt(monkeypatch):
    calls = {}

    def fake_execv(exe, argv):
        calls["exe"], calls["argv"] = exe, argv
        raise SystemExit(0)  # execv never returns; simulate the cut

    monkeypatch.setattr(bench.os, "execv", fake_execv)
    monkeypatch.setattr(
        bench.sys, "argv",
        ["bench.py", "--model", "resnet50", "--batch-size", "128",
         "--retry-attempt=1", "--deadline-epoch=123.0"],
    )
    args = _Args()
    args.deadline_epoch = 456.0
    with pytest.raises(SystemExit):
        bench._reexec_next_attempt(args)
    argv = calls["argv"]
    # old attempt flag stripped, new one appended exactly once
    assert argv.count("--retry-attempt=2") == 1
    assert "--retry-attempt=1" not in argv
    # the deadline is carried forward (re-minted ones would reset the
    # total budget every re-exec — the exact bug that cost BENCH_r04)
    assert argv.count("--deadline-epoch=456.0") == 1
    assert "--deadline-epoch=123.0" not in argv
    # the measurement flags survive verbatim
    assert ["--model", "resnet50", "--batch-size", "128"] == [
        a for a in argv if a in ("--model", "resnet50",
                                 "--batch-size", "128")
    ]


def test_give_up_when_budget_exhausted(monkeypatch):
    """With retries left but <180s of total budget, the machinery must
    exit 86 promptly instead of re-execing into a doomed cold compile
    (the driver then records a clean rc, not an outer-timeout rc=124)."""
    import time as _time

    rc = {}
    monkeypatch.setattr(bench.os, "_exit", lambda c: rc.setdefault("rc", c))
    monkeypatch.setattr(
        bench.os, "execv",
        lambda *a: pytest.fail("must not re-exec with no budget"),
    )
    args = _Args()
    args.deadline_epoch = _time.time() + 60  # < 180s left
    bench._give_up_or_retry(args, "watchdog: test")
    assert rc["rc"] == 86


def test_retry_when_budget_remains(monkeypatch):
    calls = {}

    def fake_execv(exe, argv):
        calls["argv"] = argv
        raise SystemExit(0)

    import time as _time

    monkeypatch.setattr(bench.os, "execv", fake_execv)
    monkeypatch.setattr(bench.sys, "argv", ["bench.py"])
    args = _Args()
    args.deadline_epoch = _time.time() + 1000
    with pytest.raises(SystemExit):
        bench._give_up_or_retry(args, "axon UNAVAILABLE")
    assert any(a == "--retry-attempt=2" for a in calls["argv"])


def test_compile_cache_configured():
    """The persistent compilation cache must point inside the repo so
    driver re-runs and future rounds reuse warmed executables."""
    import jax

    if not hasattr(jax.config, "jax_compilation_cache_dir"):
        pytest.skip("this JAX has no persistent compilation cache "
                    "(bench degrades gracefully by design)")
    assert jax.config.jax_compilation_cache_dir == bench._CACHE_DIR
    assert bench._CACHE_DIR.startswith(
        bench.os.path.dirname(bench.os.path.abspath(bench.__file__))
    )


def test_watchdog_disarmed_on_cpu(monkeypatch):
    """--cpu runs must never arm the watchdog (dev machines may
    legitimately take arbitrarily long)."""
    import threading

    started = []
    monkeypatch.setattr(
        threading, "Thread",
        lambda *a, **k: started.append(1) or _FakeThread(),
    )

    args = _Args()
    args.cpu = True
    bench._arm_watchdog(args)
    assert not started


class _FakeThread:
    daemon = True

    def start(self):
        pass


def test_backend_provenance_no_probe_never_imports_jax(monkeypatch):
    """Degraded give-up paths may fire while ``import jax`` is the very
    thing that hangs: probe=False must only read sys.modules, never
    import."""
    import builtins
    import sys as _sys

    monkeypatch.setitem(_sys.modules, "jax", None)
    monkeypatch.delitem(_sys.modules, "jax")
    real_import = builtins.__import__

    def guard(name, *a, **k):
        if name == "jax" or name.startswith("jax."):
            raise AssertionError("probe=False imported jax")
        return real_import(name, *a, **k)

    monkeypatch.setattr(builtins, "__import__", guard)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    prov = bench.backend_provenance(probe=False)
    assert prov == {"platform": None, "device_kind": None,
                    "jax_platforms": "cpu"}


def test_backend_provenance_probe_reports_device(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    prov = bench.backend_provenance(probe=True)
    assert prov["platform"] == "cpu"
    assert prov["device_kind"]
    assert prov["jax_platforms"] == "cpu"


def test_degraded_record_carries_provenance_stamp(tmp_path, monkeypatch):
    """Satellite acceptance: every degraded BENCH record embeds the
    backend-provenance stamp, so the perf gate can separate 'ran on
    CPU' from 'tunnel flaked' without guessing."""
    import json

    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    path = bench.write_degraded_record(
        "watchdog fired", rc=86, phase="measure",
        record_dir=str(tmp_path),
    )
    doc = json.load(open(path))
    assert doc["degraded"] is True
    prov = doc["provenance"]
    assert set(prov) == {"platform", "device_kind", "jax_platforms"}
    assert prov["jax_platforms"] == "cpu"
