"""bench.py tunnel-resilience machinery (unit level).

The driver's official benchmark capture depends on this logic working
the first time a real outage hits (BENCH_r03 was lost to one), so the
string matching and the re-exec argv rebuild are pinned here; the
end-to-end timing path is exercised by the CPU smoke in CI.
"""

from __future__ import annotations

import pytest

import bench


def test_is_unavailable_matches_tunnel_signatures():
    assert bench._is_unavailable(
        RuntimeError("Unable to initialize backend 'axon': UNAVAILABLE: "
                     "TPU backend setup/compile error (Unavailable).")
    )
    assert bench._is_unavailable(Exception("UNAVAILABLE: socket closed"))
    assert not bench._is_unavailable(ValueError("shape mismatch"))
    assert not bench._is_unavailable(KeyboardInterrupt())


def test_reexec_rebuilds_argv_with_incremented_attempt(monkeypatch):
    calls = {}

    def fake_execv(exe, argv):
        calls["exe"], calls["argv"] = exe, argv
        raise SystemExit(0)  # execv never returns; simulate the cut

    monkeypatch.setattr(bench.os, "execv", fake_execv)
    monkeypatch.setattr(
        bench.sys, "argv",
        ["bench.py", "--model", "resnet50", "--batch-size", "128",
         "--retry-attempt=1"],
    )
    with pytest.raises(SystemExit):
        bench._reexec_next_attempt(1)
    argv = calls["argv"]
    # old attempt flag stripped, new one appended exactly once
    assert argv.count("--retry-attempt=2") == 1
    assert "--retry-attempt=1" not in argv
    # the measurement flags survive verbatim
    assert ["--model", "resnet50", "--batch-size", "128"] == [
        a for a in argv if a in ("--model", "resnet50",
                                 "--batch-size", "128")
    ]


def test_watchdog_disarmed_on_cpu(monkeypatch):
    """--cpu runs must never arm the watchdog (dev machines may
    legitimately take arbitrarily long)."""
    import threading

    started = []
    monkeypatch.setattr(
        threading, "Thread",
        lambda *a, **k: started.append(1) or _FakeThread(),
    )

    class _Args:
        cpu = True
        watchdog_secs = 900
        retry_attempt = 0
        attempts = 4

    bench._arm_watchdog(_Args())
    assert not started


class _FakeThread:
    daemon = True

    def start(self):
        pass
