"""Soak: sustained mixed eager traffic across processes — steady-state
cache cycling, periodic renegotiation, mixed host/device payloads, fusion,
and a Join finale, on both engines.  Guards the interactions the focused
tests can't see (cache eviction under live votes, plane selection flapping
between ops, fused responses straddling cache hits and misses)."""

import numpy as np
import pytest

import horovod_tpu.run as hvdrun

pytestmark = [pytest.mark.multiprocess, pytest.mark.full]


# engine_env fixture (python/native cross) lives in tests/conftest.py.


def _soak_fn(steps):
    import jax.numpy as jnp
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    r = hvd.rank()
    world = hvd.size()
    errors = []
    for step in range(steps):
        # steady-state names (cache HITs after round one)
        hs = [
            hvd.allreduce_async(
                np.full(64, float(r + 1 + k), np.float32),
                op=hvd.Sum, name=f"grad_{k}",
            )
            for k in range(4)
        ]
        for k, h in enumerate(hs):
            got = hvd.synchronize(h)
            want = sum(float(i + 1 + k) for i in range(world))
            if not np.allclose(np.asarray(got), want):
                errors.append(f"step{step} grad_{k}: {got[0]} != {want}")
        # device payload every step (python engine: XLA plane)
        dv = hvd.allreduce(jnp.full((8,), float(r + 1), jnp.bfloat16),
                           op=hvd.Average, name="dev_grad")
        if not np.allclose(np.asarray(dv, np.float32), (1 + world) / 2):
            errors.append(f"step{step} dev_grad wrong")
        # fresh name every 10 steps: forces slow-path negotiation and,
        # eventually, cache insertions alongside live votes
        if step % 10 == 0:
            fresh = hvd.allreduce(
                np.ones(16, np.float32), op=hvd.Sum, name=f"fresh_{step}"
            )
            if not np.allclose(np.asarray(fresh), world):
                errors.append(f"step{step} fresh wrong")
        # a broadcast and a ragged allgather in the same cycles
        b = hvd.broadcast(
            np.full(5, float(100 * (r + 1)), np.float32), root_rank=0,
            name="bcast",
        )
        if not np.allclose(np.asarray(b), 100.0):
            errors.append(f"step{step} bcast wrong")
    hvd.join()
    from horovod_tpu._engine_registry import peek_engine

    eng = peek_engine()
    stats = dict(getattr(eng, "stats", {}))
    hvd.shutdown()
    return {"errors": errors[:5], "n_errors": len(errors), "stats": stats}


def test_soak_mixed_traffic(engine_env):
    results = hvdrun.run(_soak_fn, (60,), np=2, use_cpu=True, timeout=300,
                         env=engine_env)
    for res in results:
        assert res["n_errors"] == 0, res["errors"]
    if "fast_cycles" in (results[0]["stats"] or {}):
        # python engine: the steady-state fast path must have engaged
        assert results[0]["stats"]["cache_hits"] > 100
