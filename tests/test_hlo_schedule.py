"""HLO collective-schedule checker (ISSUE 12): the compiled-artifact
gate's parser and differ, on synthetic scheduled-module text.

The jax-compiling half (engine fused-allreduce, overlap bucket, serve
decode attention, per-rank subprocess compiles) lives in
``scripts/hlo_gate.py`` and runs in the CI matrix — these tests pin the
stdlib checker itself: extraction (opcodes, shapes, bytes, replica
groups, nested computations), the diff verdicts, and the CLI contract.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

from horovod_tpu.analysis import hlo

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MODULE_A = """
HloModule train_step, is_scheduled=true

%decode_body (p: f32[8]) -> f32[8] {
  %x = f32[8]{0} parameter(0)
  %pm = f32[8]{0} all-reduce(f32[8]{0} %x), channel_id=1, replica_groups={{0,1},{2,3}}, to_apply=%max
  ROOT %ar = f32[8]{0} all-reduce(f32[8]{0} %pm), channel_id=2, replica_groups={{0,1},{2,3}}, to_apply=%add
}

ENTRY %main (p0: f32[64]) -> f32[64] {
  %p0 = f32[64]{0} parameter(0)
  %f = f32[64]{0} fusion(f32[64]{0} %p0), kind=kLoop, calls=%fused
  %rs = f32[16]{0} reduce-scatter(f32[64]{0} %f), channel_id=3, replica_groups={{0,1,2,3}}, to_apply=%add
  %ags = (f32[16]{0}, f32[64]{0}) all-gather-start(f32[16]{0} %rs), channel_id=4, replica_groups=[1,4]<=[4], dimensions={0}
  ROOT %agd = f32[64]{0} all-gather-done((f32[16]{0}, f32[64]{0}) %ags)
}
"""


def test_extract_schedule_ops_and_order():
    s = hlo.extract_schedule(MODULE_A, "rank0")
    assert [i.opcode for i in s.instrs] == [
        "all-reduce", "all-reduce", "reduce-scatter", "all-gather-start",
    ]
    # nested computations are tracked by name
    assert s.instrs[0].computation == "decode_body"
    assert s.instrs[2].computation == "main"


def test_extract_schedule_bytes_and_groups():
    s = hlo.extract_schedule(MODULE_A)
    ar = s.instrs[0]
    assert ar.elements == 8 and ar.nbytes == 32  # f32[8]
    assert ar.replica_groups == "{{0,1},{2,3}}"
    assert ar.channel_id == 1
    # the -start tuple shape sums every array member
    ags = s.instrs[3]
    assert ags.elements == 16 + 64
    assert ags.replica_groups == "[1,4]<=[4]"  # iota form preserved
    assert s.total_bytes == 32 + 32 + 64 + (16 + 64) * 4


def test_layout_is_not_a_schedule_property():
    # {1,0} vs {0,1} layouts are backend choices; same payload
    b = MODULE_A.replace("f32[64]{0}", "f32[64]{0:T(256)}")
    assert hlo.diff_schedules([
        hlo.extract_schedule(MODULE_A, "a"),
        hlo.extract_schedule(b, "b"),
    ]) == []


def test_diff_identical_and_group_divergence():
    a = hlo.extract_schedule(MODULE_A, "rank0")
    same = hlo.extract_schedule(MODULE_A, "rank1")
    assert hlo.diff_schedules([a, same]) == []
    b = hlo.extract_schedule(
        MODULE_A.replace("replica_groups={{0,1},{2,3}}",
                         "replica_groups={{0,2},{1,3}}"),
        "rank1",
    )
    problems = hlo.diff_schedules([a, b])
    assert problems and "collective #0 diverges" in problems[0]
    assert "rank1" in problems[0] and "rank0" in problems[0]


def test_diff_count_divergence_names_the_extra():
    # one rank compiles an extra collective (the HVD010 bug as an
    # artifact): the differ must call out the count mismatch
    lines = [l for l in MODULE_A.splitlines()
             if "reduce-scatter" not in l]
    b = hlo.extract_schedule("\n".join(lines), "rank1")
    a = hlo.extract_schedule(MODULE_A, "rank0")
    problems = hlo.diff_schedules([a, b])
    assert any("HOW MANY" in p for p in problems)


def test_single_schedule_trivially_clean():
    assert hlo.diff_schedules([hlo.extract_schedule(MODULE_A)]) == []


def test_schedule_of_accepts_text():
    s = hlo.schedule_of(MODULE_A, label="x")
    assert s.label == "x" and len(s.instrs) == 4


def test_as_dict_schema():
    d = hlo.extract_schedule(MODULE_A, "r").as_dict()
    assert d["schema"] == hlo.HLO_SCHEMA
    assert len(d["collectives"]) == 4
    assert {"opcode", "shape", "elements", "bytes", "replica_groups",
            "channel_id", "computation"} <= set(d["collectives"][0])


def _run_hlo_cli(args, cwd):
    return subprocess.run(
        [sys.executable, "-m", "horovod_tpu.analysis.hlo", *args],
        cwd=cwd, capture_output=True, text=True, timeout=120,
        env={**os.environ,
             "PYTHONPATH": _REPO + os.pathsep
             + os.environ.get("PYTHONPATH", "")},
    )


def test_cli_identical_exit_0_and_divergent_exit_1(tmp_path):
    (tmp_path / "a.txt").write_text(MODULE_A)
    (tmp_path / "b.txt").write_text(MODULE_A)
    r = _run_hlo_cli(["rank0=a.txt", "rank1=b.txt"], cwd=tmp_path)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "identical" in r.stdout
    (tmp_path / "b.txt").write_text(MODULE_A.replace(
        "replica_groups={{0,1},{2,3}}", "replica_groups={{0,3},{1,2}}"))
    r = _run_hlo_cli(["rank0=a.txt", "rank1=b.txt"], cwd=tmp_path)
    assert r.returncode == 1
    assert "DIVERGENCE" in r.stdout


def test_cli_expect_collectives_guards_empty_dumps(tmp_path):
    (tmp_path / "a.txt").write_text("HloModule empty\n")
    (tmp_path / "b.txt").write_text("HloModule empty\n")
    r = _run_hlo_cli(
        ["a.txt", "b.txt", "--expect-collectives", "1"], cwd=tmp_path)
    assert r.returncode == 1
    assert "expected >= 1" in r.stdout


def test_cli_json_format_and_missing_file(tmp_path):
    (tmp_path / "a.txt").write_text(MODULE_A)
    r = _run_hlo_cli(["a.txt", "--format", "json"], cwd=tmp_path)
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(r.stdout)
    assert doc["schema"] == hlo.HLO_SCHEMA
    assert doc["divergences"] == []
    r = _run_hlo_cli(["nope.txt"], cwd=tmp_path)
    assert r.returncode == 2
